"""Driver benchmark: VQC client-rounds/sec/chip (BASELINE.md north star).

Prints ONE JSON line whose primary fields are:
    {"metric": "vqc_client_rounds_per_sec_per_chip", "value": N,
     "unit": "client-rounds/s/chip", "vs_baseline": R, ...}

``value``: flagship 8-qubit VQC federated round — one jitted SPMD program
(shard_map + psum over a client mesh axis) — measured as
(clients x rounds) / wall-clock / chips.

``vs_baseline``: speedup vs the reference's architecture on the SAME
hardware, model, and config: a sequential per-client Python loop with host
aggregation (reference src/CFed/Classical_FL.py:128-147), with each client's
local update individually jitted (which is *generous* to the baseline — the
reference ran eager torch). The reference publishes no numbers of its own
(BASELINE.md), so the architectural baseline is measured here, in the same
process, on the same chip.

Extra fields (round-2 VERDICT items 1 and 5):

- ``compute_bound``: the 16-qubit dense regime where simulation, not
  dispatch, dominates (reference ROADMAP.md:86's dense frontier): batched
  forward+grad through a 3-layer VQC, reported as amplitude·gates/s plus
  estimated FLOP and HBM-bandwidth utilization. Statevector gate
  application is a 2×2(×2²) contraction streamed over the whole state —
  arithmetic intensity ~1 FLOP/byte, so the op is HBM-bound by
  construction and the bandwidth figure is the meaningful one; the MXU
  FLOP number is reported to show WHY (it is single-digit % at best).
- ``time_to_target``: wall-clock to a fixed accuracy on the learnable
  synthetic set — the second half of the north-star metric.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def _bench_util():
    """Import benchmarks._util, making sure the repo root is importable
    even if bench.py is invoked from elsewhere (the driver's contract is
    `python bench.py` at the repo root, but don't depend on it)."""
    import os
    import sys as _sys

    root = os.path.dirname(os.path.abspath(__file__))
    if root not in _sys.path:
        _sys.path.insert(0, root)
    from benchmarks import _util

    return _util


def _enable_compile_cache(jax):
    """Persistent compilation cache next to the repo: the big XLA/Mosaic
    programs take minutes to compile; the cache makes every bench run
    after the first start hot (shared definition: benchmarks/_util.py)."""
    _bench_util().enable_cache(jax)


def _build():
    import jax

    _enable_compile_cache(jax)

    from qfedx_tpu.fed.client import make_local_update
    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.fed.round import client_mesh, make_fed_round, shard_client_data
    from qfedx_tpu.models.vqc import make_vqc_classifier

    # Flagship config: 8-qubit, 3-layer VQC; reference training hyperparams
    # (5 local epochs, batch 32 — src/CFed/Classical_FL.py:40-53).
    n_qubits, n_layers = 8, 3
    num_clients, samples = 8, 128
    cfg = FedConfig(
        local_epochs=5, batch_size=32, learning_rate=0.01, momentum=0.9
    )
    model = make_vqc_classifier(n_qubits=n_qubits, n_layers=n_layers, num_classes=2)

    rng = np.random.default_rng(0)
    cx = rng.uniform(0, 1, (num_clients, samples, n_qubits)).astype(np.float32)
    cy = rng.integers(0, 2, (num_clients, samples)).astype(np.int32)
    cmask = np.ones((num_clients, samples), dtype=np.float32)

    n_dev = min(len(jax.devices()), num_clients)
    while num_clients % n_dev != 0:
        n_dev -= 1
    mesh = client_mesh(num_devices=n_dev)
    return (
        jax,
        model,
        cfg,
        mesh,
        n_dev,
        num_clients,
        (cx, cy, cmask),
        (make_fed_round, shard_client_data, make_local_update),
    )


def _time_spmd(jax, model, cfg, mesh, num_clients, data, make_fed_round,
               shard_client_data, rounds=7):
    cx, cy, cmask = data
    round_fn = make_fed_round(model, cfg, mesh, num_clients=num_clients)
    scx, scy, scm = shard_client_data(mesh, cx, cy, np.asarray(cmask))
    params = model.init(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(1)
    # Two warmup rounds: the first compiles for plain init params, the
    # second for the NamedSharding-carrying params the round itself emits —
    # the steady-state layout the timed loop runs with.
    params, _ = round_fn(params, scx, scy, scm, key)
    params, _ = round_fn(params, scx, scy, scm, key)
    jax.block_until_ready(params)
    # Chain params/keys through REAL training rounds and time the whole
    # block, anchored by a host fetch: repeated dispatches with identical
    # inputs are elided by the tunnel (~0.1-0.4 ms "rounds" — BENCH_r04's
    # first run recorded a bogus 73679 rounds/s from exactly that), and
    # block_until_ready alone can ack queued-but-unexecuted work
    # (benchmarks/_util.device_sync). Wall-clock over a chained, fetched
    # sequence divided by its length is the honest sequential-throughput
    # number.
    state = {"params": params, "key": key}

    def measure():
        t0 = time.perf_counter()
        for r in range(rounds):
            state["key"] = jax.random.fold_in(state["key"], r)
            state["params"], _ = round_fn(
                state["params"], scx, scy, scm, state["key"]
            )
        _bench_util().device_sync(state["params"])
        return (time.perf_counter() - t0) / rounds

    return _bench_util().retry_timing(
        measure, floor=3e-4, label="per-dispatch round"
    )


def _time_spmd_scanned(jax, model, cfg, mesh, num_clients, data,
                       shard_client_data, rounds_per_call=10, reps=5):
    """The trainer's optimized path (--rounds-per-call): K rounds scanned
    inside one dispatch (fed.round.make_fed_rounds, bit-identical to
    sequential rounds). Returns seconds PER ROUND (median across chained
    measurement blocks - benchmarks/_util.retry_timing)."""
    from qfedx_tpu.fed.round import make_fed_rounds

    cx, cy, cmask = data
    rounds_fn = make_fed_rounds(
        model, cfg, mesh, num_clients=num_clients,
        rounds_per_call=rounds_per_call,
    )
    scx, scy, scm = shard_client_data(mesh, cx, cy, np.asarray(cmask))
    params = model.init(jax.random.PRNGKey(0))
    base = jax.random.PRNGKey(1)
    params, _ = rounds_fn(params, scx, scy, scm, base, 0)  # compile
    params, _ = rounds_fn(params, scx, scy, scm, base, 1)  # steady layout
    jax.block_until_ready(params)
    # Chained across reps + host-fetch anchored, for the same reasons as
    # _time_spmd (dispatch elision; lying block_until_ready).
    state = {"params": params}

    def measure():
        t0 = time.perf_counter()
        for r in range(reps):
            state["params"], _ = rounds_fn(
                state["params"], scx, scy, scm, base, r
            )
        _bench_util().device_sync(state["params"])
        return (time.perf_counter() - t0) / (reps * rounds_per_call)

    return _bench_util().retry_timing(
        measure, floor=1e-3 / rounds_per_call, label="scanned rounds"
    )


def _time_sequential(jax, model, cfg, num_clients, data, make_local_update,
                     rounds=2):
    """Reference architecture: per-client jitted update in a Python loop,
    host-side weighted averaging (src/CFed/Classical_FL.py:128-147)."""
    import jax.numpy as jnp

    cx, cy, cmask = data
    local_update = jax.jit(make_local_update(model, cfg))
    params = model.init(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(1)

    def one_round(params, key):
        deltas, weights = [], []
        for c in range(num_clients):
            d, n, _ = local_update(
                params, cx[c], cy[c], cmask[c], jax.random.fold_in(key, c)
            )
            deltas.append(d)
            weights.append(n)
        total = sum(float(w) for w in weights)
        avg = jax.tree.map(
            lambda *ls: sum(float(w) * l for w, l in zip(weights, ls)) / total,
            *deltas,
        )
        return jax.tree.map(lambda p, u: p + u, params, avg)

    params = one_round(params, key)  # warmup/compile
    params = one_round(params, key)  # steady-state layout
    jax.block_until_ready(params)
    times = []
    for r in range(rounds):
        t0 = time.perf_counter()
        params = one_round(params, jax.random.fold_in(key, r))
        jax.block_until_ready(params)
        times.append(time.perf_counter() - t0)
    return sorted(times)[len(times) // 2]


# --- compute-bound regime (VERDICT r1 item 1) -------------------------------

# Per-chip peaks used for the utilization ESTIMATES below (TPU v5e; the
# bench chip). If the driver runs on different hardware the absolute
# utilization shifts but the FLOP-vs-bandwidth conclusion does not: gate
# application is ~1 FLOP/byte and will be HBM-bound on every TPU.
_PEAK_F32_FLOPS = 49.2e12  # v5e MXU fp32 (bf16 peak 197 TF / 4)
_PEAK_HBM_BPS = 819e9  # v5e HBM bandwidth


def _dense_cost_model(n_qubits: int, n_layers: int, state_bytes: int = 4):
    """(gates, est FLOPs, est HBM bytes) per sample-forward — an analytic
    PER-GATE STREAMING model, kept as a reference point, NOT a bound.

    Rotation (complex 2×2 in flip/select form): ~18·2^n FLOPs; CNOT
    (select/permutation): ~16·2^n FLOP-equivalents; each gate charged one
    full re+im state round trip ≈ 4·state_bytes·2^n bytes (state_bytes =
    4 f32, 2 bf16). The r04 slab engine BEATS this model's byte count —
    XLA fuses consecutive row-qubit gates into shared passes (measured
    device time below the per-gate streaming roofline; docs/PERF.md §2)
    — so est_hbm_util can legitimately exceed what per-gate streaming
    would allow and est_flop_util is meaningful only as a trend.
    """
    amps = 1 << n_qubits
    rot_gates = n_layers * n_qubits
    cnot_gates = n_layers * n_qubits  # ring
    gates = rot_gates + cnot_gates
    flops = rot_gates * 18 * amps + cnot_gates * 16 * amps
    bytes_ = gates * 4 * state_bytes * amps
    return gates, flops, bytes_


def _with_env(env: dict, fn, *a, **k):
    """Run fn with env vars set, restoring previous values after."""
    import os

    prev = {var: os.environ.get(var) for var in env}
    os.environ.update(env)
    try:
        return fn(*a, **k)
    finally:
        for var, old in prev.items():
            if old is None:
                os.environ.pop(var, None)
            else:
                os.environ[var] = old


def _bench_compute_bound(jax, n_qubits=16, n_layers=3, batch=64, reps=5,
                         steps=8, remat=False):
    """Batched forward+grad of the dense n-qubit VQC — simulation-dominated
    (2^16 amplitudes/sample × 96 gates ≫ dispatch). ``steps`` gradient
    steps run inside ONE jitted lax.scan so device time dominates the
    measurement — a single dispatch through the tunneled TPU carries
    ~100ms latency, comparable to one whole fwd+grad, which un-amortized
    flattened every timing to the latency floor. Utilization estimates
    take backward ≈ 2× forward cost (adjoint state pass + gate-parameter
    reductions). Honors QFEDX_DTYPE for the HBM-byte estimate."""
    import os

    import jax.numpy as jnp
    import optax

    from qfedx_tpu.models.vqc import make_vqc_classifier

    state_bytes = (
        2 if os.environ.get("QFEDX_DTYPE", "") in ("bf16", "bfloat16") else 4
    )
    model = make_vqc_classifier(n_qubits=n_qubits, n_layers=n_layers,
                                num_classes=2, remat=remat)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.uniform(0, 1, (batch, n_qubits)), dtype=jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, (batch,)), dtype=jnp.int32)

    def loss(p):
        logits = model.apply(p, x)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    @jax.jit
    def many_steps(params):
        def body(p, _):
            l, g = jax.value_and_grad(loss)(p)
            # Tiny SGD step: keeps every iteration's work live (no CSE/DCE
            # of identical steps) without changing the op mix.
            p2 = jax.tree.map(lambda a, b: a - 1e-6 * b, p, g)
            return p2, l
        return jax.lax.scan(body, params, None, length=steps)

    p_out, ls = many_steps(params)  # compile
    jax.block_until_ready(ls)

    # Chained across reps + host-fetch anchored (dispatch elision and
    # lying block_until_ready — see _time_spmd / _util.device_sync).
    state = {"params": params}

    def measure():
        t0 = time.perf_counter()
        for _ in range(reps):
            state["params"], ls = many_steps(state["params"])
        _bench_util().device_sync(ls)
        return (time.perf_counter() - t0) / (reps * steps)

    # ~0s tunnel artifact guard (shared policy: benchmarks/_util.py).
    t = _bench_util().retry_timing(
        measure, floor=1e-3 / steps, label=f"dense n={n_qubits}"
    )

    gates, fwd_flops, fwd_bytes = _dense_cost_model(
        n_qubits, n_layers, state_bytes
    )
    total_flops = 3 * batch * fwd_flops  # fwd + ~2x bwd
    total_bytes = 3 * batch * fwd_bytes
    amps = 1 << n_qubits
    return {
        "n_qubits": n_qubits,
        "n_layers": n_layers,
        "batch": batch,
        "fwd_grad_s": round(t, 5),
        "amp_gates_per_s": round(3 * batch * gates * amps / t, 1),
        "est_tflops": round(total_flops / t / 1e12, 3),
        "est_flop_util": round(total_flops / t / _PEAK_F32_FLOPS, 4),
        "est_hbm_gbps": round(total_bytes / t / 1e9, 1),
        "est_hbm_util": round(total_bytes / t / _PEAK_HBM_BPS, 3),
    }


def _bench_fused(jax, n_qubits=16, n_layers=3, batch=64):
    """The same compute-bound program through the fused whole-circuit
    kernel + adjoint backward (QFEDX_FUSED=1, ops/fused_hea.py). First
    run pays a multi-minute Mosaic compile; the persistent compilation
    cache (enabled in _build) makes subsequent bench runs hot."""
    if jax.devices()[0].platform == "cpu":
        return {"skipped": "fused kernel needs TPU (interpret mode is test-only)"}
    try:
        on = _with_env(
            {"QFEDX_FUSED": "1"},
            _bench_compute_bound, jax, n_qubits, n_layers, batch,
        )
    except Exception as e:  # noqa: BLE001
        return {"error": f"{type(e).__name__}: {e}"}
    return {"fwd_grad_s": on["fwd_grad_s"], "est_hbm_gbps": on["est_hbm_gbps"]}


def _bench_time_to_target(jax, target=0.90, max_rounds=40):
    """Wall-clock to ``target`` accuracy on the learnable synthetic set —
    the second north-star metric (BASELINE.json "FedAvg wall-clock to
    target accuracy"): flagship 8-qubit config, 8 clients."""
    from qfedx_tpu.data.datasets import load_dataset
    from qfedx_tpu.data.partition import iid_partition, pack_clients
    from qfedx_tpu.data.pipeline import preprocess
    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.models.vqc import make_vqc_classifier
    from qfedx_tpu.run.trainer import train_federated

    _, tr, te = load_dataset("mnist", synthetic_train=1024, synthetic_test=256, seed=1)
    pre = preprocess(tr, te, classes=(0, 1), features="pca", n_features=8)
    parts = iid_partition(len(pre.train[0]), 8, seed=0)
    cx, cy, cmask = pack_clients(*pre.train, parts, pad_multiple=32)
    model = make_vqc_classifier(n_qubits=8, n_layers=3, num_classes=2)
    cfg = FedConfig(local_epochs=2, batch_size=32, learning_rate=0.1, optimizer="adam")

    t0 = time.perf_counter()
    # Scanned dispatch with ON-DEVICE per-round eval (rounds_per_call):
    # accuracy at every round comes out of the same device program, so
    # the timed window is training + in-scan eval, not 40 host eval
    # round-trips. The hit round is exact (per-round accuracies from the
    # scan); the hit TIME is the sum of recorded per-round wall times up
    # to it (chunk compiles amortize into their chunk's rounds — the
    # persistent cache makes them ~free after the first bench run).
    res = train_federated(
        model, cfg, cx, cy, cmask, *pre.test, num_rounds=max_rounds,
        eval_every=1, seed=0, rounds_per_call=10,
    )
    total = time.perf_counter() - t0
    # accuracies[0] is the round-0 (pre-training) eval.
    hit_round = next(
        (i for i, a in enumerate(res.accuracies) if i > 0 and a >= target),
        None,
    )
    hit_s = (
        round(sum(res.round_times_s[:hit_round]), 3)
        if hit_round is not None
        else None
    )
    return {
        "target_accuracy": target,
        "seconds": hit_s,
        "rounds": hit_round,
        "reached": hit_round is not None,
        "total_s_40_rounds": round(total, 3),
    }


def main():
    (jax, model, cfg, mesh, n_dev, num_clients, data, fns) = _build()
    make_fed_round, shard_client_data, make_local_update = fns

    spmd_s = _time_spmd(
        jax, model, cfg, mesh, num_clients, data, make_fed_round, shard_client_data
    )
    seq_s = _time_sequential(jax, model, cfg, num_clients, data, make_local_update)
    # Scan depth measured on v5e: 10 → 331/s, 20 → 395/s, 40 → 435/s per
    # chip (diminishing past that); training is bit-identical at any K.
    scan_k = 40
    try:
        scan_s = _time_spmd_scanned(
            jax, model, cfg, mesh, num_clients, data, shard_client_data,
            rounds_per_call=scan_k,
        )
    except Exception:  # noqa: BLE001 — fall back to the per-dispatch number
        scan_s, scan_k = spmd_s, 1

    def safe(fn, *a, **k):
        try:
            return fn(jax, *a, **k)
        except Exception as e:  # noqa: BLE001
            return {"error": f"{type(e).__name__}: {e}"}

    # Baseline XLA path measured with the fused auto-route pinned off, so
    # the rows are the engines, not "whatever auto picked".
    compute = safe(
        lambda j: _with_env({"QFEDX_FUSED": "0"}, _bench_compute_bound, j)
    )
    fused = safe(_bench_fused)
    if "fwd_grad_s" in compute and "fwd_grad_s" in fused:
        fused["speedup_vs_xla"] = round(
            compute["fwd_grad_s"] / fused["fwd_grad_s"], 3
        )
    # bf16 state path (QFEDX_DTYPE=bf16): halves state bytes. Measured
    # effect is width-dependent (docs/PERF.md §3): ~parity at n=16 (the
    # slab engine is fusion/bubble-bound there), ~1.4× at n=18-20 where
    # gate passes genuinely stream multi-MB states. Convergence parity is
    # pinned by tests/test_bf16.py.
    compute_bf16 = safe(
        lambda j: _with_env(
            {"QFEDX_FUSED": "0", "QFEDX_DTYPE": "bf16"},
            _bench_compute_bound, j,
        )
    )
    def _fused_bf16(j):
        if j.devices()[0].platform == "cpu":
            return {"skipped": "needs TPU"}
        on = _with_env(
            {"QFEDX_FUSED": "1", "QFEDX_DTYPE": "bf16"},
            _bench_compute_bound, j,
        )
        # Strip the streaming-cost-model fields (like _bench_fused does):
        # the fused kernel makes O(1) HBM passes, so per-gate byte
        # estimates would report nonsense bandwidth for it.
        return {"fwd_grad_s": on["fwd_grad_s"]}

    fused_bf16 = safe(_fused_bf16)
    for row in (compute_bf16, fused_bf16):
        if "fwd_grad_s" in row and "fwd_grad_s" in compute:
            row["speedup_vs_xla_f32"] = round(
                compute["fwd_grad_s"] / row["fwd_grad_s"], 3
            )
    # The 18–20-qubit dense frontier (reference ROADMAP.md:86), measured on
    # the real chip: 18q batch 16, 20q batch 8 — both WITHOUT remat. The
    # r04 per-layer remat at 20q was the whole performance cliff (XLA fused
    # the recomputed forward into every angle-cotangent reduction: 311 ms →
    # 64 ms f32 without it; docs/PERF.md §7). The real tape is ~60
    # rotation-gate residuals ≈ 4 GB f32 at batch 8 — it fits.
    dense18 = safe(
        lambda j: _with_env(
            {"QFEDX_FUSED": "0"}, _bench_compute_bound, j,
            18, 3, 16, 3, 4, False,
        )
    )
    dense18_bf16 = safe(
        lambda j: _with_env(
            {"QFEDX_FUSED": "0", "QFEDX_DTYPE": "bf16"},
            _bench_compute_bound, j, 18, 3, 16, 3, 4, False,
        )
    )
    dense20 = safe(
        lambda j: _with_env(
            {"QFEDX_FUSED": "0"}, _bench_compute_bound, j,
            20, 3, 8, 3, 4, False,
        )
    )
    dense20_bf16 = safe(
        lambda j: _with_env(
            {"QFEDX_FUSED": "0", "QFEDX_DTYPE": "bf16"},
            _bench_compute_bound, j, 20, 3, 8, 3, 4, False,
        )
    )
    for now, base in ((dense18_bf16, dense18), (dense20_bf16, dense20)):
        if "fwd_grad_s" in now and "fwd_grad_s" in base:
            now["speedup_vs_f32"] = round(
                base["fwd_grad_s"] / now["fwd_grad_s"], 3
            )
            now["verdict"] = (
                "better" if now["speedup_vs_f32"] >= 1.1 else
                "worse" if now["speedup_vs_f32"] <= 0.9 else "parity"
            )
    ttt = safe(_bench_time_to_target)

    # Headline: the trainer's optimized path (K rounds scanned per
    # dispatch — CLI --rounds-per-call, bit-identical training). The
    # per-dispatch number is kept alongside for the latency-bound view.
    value = num_clients / scan_s / n_dev
    per_dispatch = num_clients / spmd_s / n_dev
    baseline_value = num_clients / seq_s / n_dev

    # Round-over-round regression tracking (VERDICT r03 item 5): compare
    # against the newest committed BENCH_r*.json so a drift in the
    # headline / per-dispatch / engine rows is visible AT BENCH TIME (the
    # r02→r03 −10% per-dispatch drift shipped unnoticed for a round).
    vs_prev = {}
    try:
        import glob
        import os as _os

        prevs = sorted(glob.glob(
            _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                          "BENCH_r*.json")
        ))
        if prevs:
            with open(prevs[-1]) as f:
                prev = json.load(f)
            # The driver wraps the bench line under "parsed" (alongside
            # n/cmd/rc/tail); accept both the wrapped and bare layouts.
            prev = prev.get("parsed", prev)
            vs_prev["prev_file"] = _os.path.basename(prevs[-1])

            def delta(name, now_v, prev_v, higher_is_better):
                if now_v is None or prev_v in (None, 0):
                    return
                r = now_v / prev_v
                vs_prev[name] = {
                    "prev": round(prev_v, 5), "now": round(now_v, 5),
                    "ratio": round(r, 3),
                    "regressed": bool(
                        r < 0.95 if higher_is_better else r > 1.05
                    ),
                }

            delta("headline_rounds_per_s", value, prev.get("value"), True)
            delta("per_dispatch_rounds_per_s", per_dispatch,
                  prev.get("per_dispatch_value"), True)
            delta("compute_bound_fwd_grad_s", compute.get("fwd_grad_s"),
                  (prev.get("compute_bound") or {}).get("fwd_grad_s"), False)
            delta("fused_fwd_grad_s", fused.get("fwd_grad_s"),
                  (prev.get("fused") or {}).get("fwd_grad_s"), False)
            delta("dense20q_fwd_grad_s", dense20.get("fwd_grad_s"),
                  (prev.get("dense20q") or {}).get("fwd_grad_s"), False)
    except Exception as e:  # noqa: BLE001 — tracking must never kill bench
        vs_prev["error"] = f"{type(e).__name__}: {e}"
    print(
        json.dumps(
            {
                "metric": "vqc_client_rounds_per_sec_per_chip",
                "value": round(value, 3),
                "unit": "client-rounds/s/chip",
                # r04 onward: timing loops chain dispatches and anchor on
                # a real host fetch (benchmarks/_util.device_sync) — the
                # tunnel elides identical-input dispatches AND can ack
                # readiness for unexecuted work. Cross-round comparisons
                # against pre-r04 BENCH files mix methodologies (the old
                # per-rep block method over-counted per-dispatch
                # overhead; e.g. n=16 dense reads 16 ms now vs 26-28 ms
                # measured the old way on the SAME engine).
                "timing_methodology": "chained+fetch-anchored (r04)",
                # Headline ratio compares the K-round scanned dispatch
                # against the reference's sequential per-round architecture
                # (dispatch amortization included, by design — both run the
                # same training); the per-dispatch ratio alongside is the
                # apples-to-apples single-round comparison.
                "vs_baseline": round(value / baseline_value, 3),
                "vs_baseline_note": "scanned(K) vs sequential per-round loop",
                "per_dispatch_vs_baseline": round(
                    per_dispatch / baseline_value, 3
                ),
                "rounds_per_call": scan_k,
                "per_dispatch_value": round(per_dispatch, 3),
                # The un-scanned number is tunnel-RTT-bound, not
                # engine-bound: one 8q round's device time is ~3-8 ms
                # while the measured per-dispatch round tracks the
                # tunnel's round-trip latency, which varies 16-150 ms
                # day to day (r03 vs r04 measurements). Compare engines
                # on the scanned headline and the compute_bound rows.
                "per_dispatch_note": "tunnel-RTT-bound; varies with "
                "tunnel weather, not engine speed",
                "compute_bound": compute,
                "fused": fused,
                "compute_bound_bf16": compute_bf16,
                "fused_bf16": fused_bf16,
                "dense18q": dense18,
                "dense18q_bf16": dense18_bf16,
                "dense20q": dense20,
                "dense20q_bf16": dense20_bf16,
                "time_to_target": ttt,
                "vs_prev": vs_prev,
            }
        )
    )


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # never leave the driver without a JSON line
        print(
            json.dumps(
                {
                    "metric": "vqc_client_rounds_per_sec_per_chip",
                    "value": 0.0,
                    "unit": "client-rounds/s/chip",
                    "vs_baseline": 0.0,
                    "error": f"{type(e).__name__}: {e}",
                }
            )
        )
        sys.exit(1)
