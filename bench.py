"""Driver benchmark: VQC client-rounds/sec/chip (BASELINE.md north star).

Prints ONE JSON line:
    {"metric": "vqc_client_rounds_per_sec_per_chip", "value": N,
     "unit": "client-rounds/s/chip", "vs_baseline": R}

``value``: flagship 8-qubit VQC federated round — one jitted SPMD program
(shard_map + psum over a client mesh axis) — measured as
(clients x rounds) / wall-clock / chips.

``vs_baseline``: speedup vs the reference's architecture on the SAME
hardware, model, and config: a sequential per-client Python loop with host
aggregation (reference src/CFed/Classical_FL.py:128-147), with each client's
local update individually jitted (which is *generous* to the baseline — the
reference ran eager torch). The reference publishes no numbers of its own
(BASELINE.md), so the architectural baseline is measured here, in the same
process, on the same chip.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def _build():
    import jax

    from qfedx_tpu.fed.client import make_local_update
    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.fed.round import client_mesh, make_fed_round, shard_client_data
    from qfedx_tpu.models.vqc import make_vqc_classifier

    # Flagship config: 8-qubit, 3-layer VQC; reference training hyperparams
    # (5 local epochs, batch 32 — src/CFed/Classical_FL.py:40-53).
    n_qubits, n_layers = 8, 3
    num_clients, samples = 8, 128
    cfg = FedConfig(
        local_epochs=5, batch_size=32, learning_rate=0.01, momentum=0.9
    )
    model = make_vqc_classifier(n_qubits=n_qubits, n_layers=n_layers, num_classes=2)

    rng = np.random.default_rng(0)
    cx = rng.uniform(0, 1, (num_clients, samples, n_qubits)).astype(np.float32)
    cy = rng.integers(0, 2, (num_clients, samples)).astype(np.int32)
    cmask = np.ones((num_clients, samples), dtype=np.float32)

    n_dev = min(len(jax.devices()), num_clients)
    while num_clients % n_dev != 0:
        n_dev -= 1
    mesh = client_mesh(num_devices=n_dev)
    return (
        jax,
        model,
        cfg,
        mesh,
        n_dev,
        num_clients,
        (cx, cy, cmask),
        (make_fed_round, shard_client_data, make_local_update),
    )


def _time_spmd(jax, model, cfg, mesh, num_clients, data, make_fed_round,
               shard_client_data, rounds=7):
    cx, cy, cmask = data
    round_fn = make_fed_round(model, cfg, mesh, num_clients=num_clients)
    scx, scy, scm = shard_client_data(mesh, cx, cy, np.asarray(cmask))
    params = model.init(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(1)
    # Two warmup rounds: the first compiles for plain init params, the
    # second for the NamedSharding-carrying params the round itself emits —
    # the steady-state layout the timed loop runs with.
    params, _ = round_fn(params, scx, scy, scm, key)
    params, _ = round_fn(params, scx, scy, scm, key)
    jax.block_until_ready(params)
    times = []
    for r in range(rounds):
        key = jax.random.fold_in(key, r)
        t0 = time.perf_counter()
        params, _ = round_fn(params, scx, scy, scm, key)
        jax.block_until_ready(params)
        times.append(time.perf_counter() - t0)
    # Median: robust to transient dispatch-latency spikes (tunneled TPU).
    return sorted(times)[len(times) // 2]


def _time_sequential(jax, model, cfg, num_clients, data, make_local_update,
                     rounds=2):
    """Reference architecture: per-client jitted update in a Python loop,
    host-side weighted averaging (src/CFed/Classical_FL.py:128-147)."""
    import jax.numpy as jnp

    cx, cy, cmask = data
    local_update = jax.jit(make_local_update(model, cfg))
    params = model.init(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(1)

    def one_round(params, key):
        deltas, weights = [], []
        for c in range(num_clients):
            d, n, _ = local_update(
                params, cx[c], cy[c], cmask[c], jax.random.fold_in(key, c)
            )
            deltas.append(d)
            weights.append(n)
        total = sum(float(w) for w in weights)
        avg = jax.tree.map(
            lambda *ls: sum(float(w) * l for w, l in zip(weights, ls)) / total,
            *deltas,
        )
        return jax.tree.map(lambda p, u: p + u, params, avg)

    params = one_round(params, key)  # warmup/compile
    params = one_round(params, key)  # steady-state layout
    jax.block_until_ready(params)
    times = []
    for r in range(rounds):
        t0 = time.perf_counter()
        params = one_round(params, jax.random.fold_in(key, r))
        jax.block_until_ready(params)
        times.append(time.perf_counter() - t0)
    return sorted(times)[len(times) // 2]


def main():
    (jax, model, cfg, mesh, n_dev, num_clients, data, fns) = _build()
    make_fed_round, shard_client_data, make_local_update = fns

    spmd_s = _time_spmd(
        jax, model, cfg, mesh, num_clients, data, make_fed_round, shard_client_data
    )
    seq_s = _time_sequential(jax, model, cfg, num_clients, data, make_local_update)

    value = num_clients / spmd_s / n_dev
    baseline_value = num_clients / seq_s / n_dev
    print(
        json.dumps(
            {
                "metric": "vqc_client_rounds_per_sec_per_chip",
                "value": round(value, 3),
                "unit": "client-rounds/s/chip",
                "vs_baseline": round(value / baseline_value, 3),
            }
        )
    )


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # never leave the driver without a JSON line
        print(
            json.dumps(
                {
                    "metric": "vqc_client_rounds_per_sec_per_chip",
                    "value": 0.0,
                    "unit": "client-rounds/s/chip",
                    "vs_baseline": 0.0,
                    "error": f"{type(e).__name__}: {e}",
                }
            )
        )
        sys.exit(1)
