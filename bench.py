"""Driver benchmark: VQC client-rounds/sec/chip (BASELINE.md north star).

Prints ONE compact JSON line whose primary fields are:
    {"metric": "vqc_client_rounds_per_sec_per_chip", "value": N,
     "unit": "client-rounds/s/chip", "vs_baseline": R, ...}
and writes the full per-section results to ``bench_details.json`` next to
this file (r04's single line outgrew the driver's tail capture and parsed
as null — VERDICT r04 weak 5; the printed line now stays small and
parseable, details go to the sidecar).

``value``: flagship 8-qubit VQC federated round — one jitted SPMD program
(shard_map + psum over a client mesh axis), K rounds scanned per dispatch —
measured as (clients × rounds) / wall-clock / chips, the MEDIAN across ≥3
chained measurement blocks with the per-block values shipped alongside
(``value_blocks``) so the artifact carries its own run-to-run spread.

``vs_baseline``: speedup vs the reference's architecture on the SAME
hardware, model, and config: a sequential per-client Python loop with host
aggregation (reference src/CFed/Classical_FL.py:128-147), with each client's
local update individually jitted (which is *generous* to the baseline — the
reference ran eager torch). The reference publishes no numbers of its own
(BASELINE.md), so the architectural baseline is measured here, in the same
process, on the same chip.

Sections in ``bench_details.json`` (beyond the headline):

- ``compute_bound`` / ``dense18q`` / ``dense20q`` (+ ``_bf16``): the dense
  16–20-qubit frontier (reference ROADMAP.md:86), bare fwd+grad. Bandwidth
  figures are reported RELATIVE TO the per-gate streaming cost model
  (``vs_pergate_bound``) — the slab engine legitimately beats that model
  (XLA fuses consecutive row-qubit gates into shared passes), so the ratio
  can exceed 1.0 and is labeled as a model ratio, not a hardware
  utilization (VERDICT r04 weak 2).
- ``fed16q`` (+``_bf16``): the COMPOSED path — K scanned federated rounds
  through shard_map at n=16 — client-rounds/s where simulation dominates,
  proving the engine's speed survives inside the federated program
  (VERDICT r04 missing 3; the r05 batched slab engine exists because it
  once didn't — docs/PERF.md §8).
- ``fed16q_bf16_pipeline`` / ``_pipeline_off``: the r09 round-loop
  pipeline lever measured through the REAL trainer (in-scan eval +
  per-round JSONL host work) with QFEDX_PIPELINE on vs 0 — the raw
  fed16q rows cannot see the host work the pipeline overlaps.
- ``fed16q_bf16_guards_off``: the r11 fault-tolerance lever — the same
  composed row with QFEDX_GUARDS=off (pre-r11 program: no non-finite
  quarantine, no survivor machinery), so the guards' overhead stays
  measured head-to-head like the fold/fuse/pipeline levers.
- ``fed16q_bf16_trace_on``: the r15 observability lever — the trainer-
  path row under QFEDX_TRACE=1 (spans + compile attribution + per-row
  phases + span histograms), head-to-head vs the identical trace-off
  pipeline row; ``trace_overhead_vs_off`` is the measured end-to-end
  cost of enabled tracing (PERF.md §13 pins only the disabled-span
  microcost), ``vs_prev``-tracked.
- ``fed16q_bf16_watch_on``: the r20 detection lever — the trainer-path
  row under QFEDX_WATCH=1 (one rule sweep per tick + bounded
  instruments recording, trace off), head-to-head vs the identical
  watch-off pipeline row; ``watch_overhead_vs_off`` is the measured
  end-to-end cost of always-on detection. ``alerts_fired`` on this row
  and the serve row is the quiet-run canary (expected 0; any firing —
  or any increase vs prev — is ``vs_prev``-flagged as a regression).
- ``fault_tolerance``: accuracy under injected client churn — the
  dropout_rate → accuracy degradation curve at 0/5/20% casualties per
  round (half drops, half NaN updates; utils/faults), streamed trainer;
  ``vs_prev`` tracks the 20% point.
- ``byzantine``: accuracy under ADVERSARIAL clients (r12) — scale:100
  attackers at 0/10/20% per round, defense off (mean) vs clip_mean /
  trimmed_mean / median; the headline is mean collapsing at 20% while
  a robust rule stays within 2 points of clean; ``vs_prev`` tracks the
  best defended 20% point.
- ``straggler``: accuracy + utilized client-rounds/s under injected
  STRAGGLERS (r13) — 0/10/30% of waves one round late (wave.delay),
  drop (r12 casualties) vs buffer (QFEDX_STALE staleness-discounted
  salvage); the headline is buffered 30% staying within noise of clean
  accuracy while recovering the fleet work drop measurably throws away
  (utilized client-rounds/s, ~2.7× at 30% on CPU); ``vs_prev`` tracks
  the buffered 30% point.
- ``serve``: the serving rows (r14) — an offered-load sweep through the
  real ServeEngine + MicroBatcher (docs/SERVING.md) at 0.2/0.5/0.8× of
  the measured max-bucket capacity: p50/p95 submit→answer latency,
  completed throughput, shed counts, and ``throughput_at_slo`` (best
  completed rate whose p95 meets the stated 50 ms SLO). The
  zero-compiles-inside-the-serving-loop contract is measured by the obs
  compile listener (``zero_compiles_in_loop``); ``vs_prev`` tracks
  serve_p50_ms / serve_p95_ms / throughput_at_slo.
- ``dense18q_bf16_scan16``: the r14 floor lever — the dense18q_bf16 step
  at scan depth 16 vs 4, reading the dispatch-gap share of the §11
  dtype-invariant floor directly (docs/PERF.md §15).
- ``fed16q_bf16_scan_off``: the r17 scan-over-fused-layers lever — the
  same composed row with QFEDX_SCAN_LAYERS=off (the r07 per-layer fused
  program bit-for-bit); the default row's ``scan_speedup_vs_off`` is the
  measured end-to-end value of the op-count collapse.
- ``floor_attribution`` (r16/r17, compact copy on the printed line): the
  MEASURED floor — a profiler capture of the step program parsed by
  ``obs/profile.py`` into executed ops vs the static ``fusion_hlo``
  census, the measured inter-op gap quantiles (the §15 3–5 µs/op
  inference, now measured), and device-busy fraction; ``vs_prev``
  tracks ``gap_us_per_op`` / ``ops_per_step`` — the evidence harness
  every op-count-collapse PR is judged against (docs/PERF.md §16).
  Since r17 the headline row profiles the scanned program head-to-head
  with the r07-fused one (``ops_per_step_vs_fused``), plus a ``depth6``
  L=6 pair: the scanned body is depth-invariant, so the collapse
  factor rises with L and the L=3 headline is its floor (§17). r19
  adds the third arm — ``pallas`` (QFEDX_PALLAS=1, the scan body as
  ONE kernel) — and ``route_resolved`` fuse/scan/pallas booleans so
  the snapshot is self-describing; off-chip the pallas arm runs
  interpreted (flagged) and the kernel judgement is the static
  TPU-lowered census (§18).
- ``time_to_target`` / ``time_to_target_20q``: wall-clock to target
  accuracy, flagship 8q config and the TRUE 20-qubit config-5 width
  (VERDICT r04 missing 1: 20q had been timed but never trained).
- ``phase_breakdown`` (inside ``time_to_target``, compact copy on the
  printed line): per-phase span rollup of the traced hot run
  (qfedx_tpu/obs, QFEDX_TRACE) — dispatch / eval / trace-build /
  compile walls, so ``vs_prev`` localizes a headline regression to a
  phase automatically (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _bench_util():
    """Import benchmarks._util, making sure the repo root is importable
    even if bench.py is invoked from elsewhere (the driver's contract is
    `python bench.py` at the repo root, but don't depend on it)."""
    import sys as _sys

    root = os.path.dirname(os.path.abspath(__file__))
    if root not in _sys.path:
        _sys.path.insert(0, root)
    from benchmarks import _util

    return _util


def _enable_compile_cache(jax):
    """Persistent compilation cache next to the repo: the big XLA programs
    take minutes to compile; the cache makes every bench run after the
    first start hot (shared definition: benchmarks/_util.py)."""
    _bench_util().enable_cache(jax)


def _build():
    import jax

    _enable_compile_cache(jax)

    from qfedx_tpu.fed.client import make_local_update
    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.fed.round import client_mesh, make_fed_round, shard_client_data
    from qfedx_tpu.models.vqc import make_vqc_classifier

    # Flagship config: 8-qubit, 3-layer VQC; reference training hyperparams
    # (5 local epochs, batch 32 — src/CFed/Classical_FL.py:40-53).
    n_qubits, n_layers = 8, 3
    num_clients, samples = 8, 128
    cfg = FedConfig(
        local_epochs=5, batch_size=32, learning_rate=0.01, momentum=0.9
    )
    model = make_vqc_classifier(n_qubits=n_qubits, n_layers=n_layers, num_classes=2)

    rng = np.random.default_rng(0)
    cx = rng.uniform(0, 1, (num_clients, samples, n_qubits)).astype(np.float32)
    cy = rng.integers(0, 2, (num_clients, samples)).astype(np.int32)
    cmask = np.ones((num_clients, samples), dtype=np.float32)

    n_dev = min(len(jax.devices()), num_clients)
    while num_clients % n_dev != 0:
        n_dev -= 1
    mesh = client_mesh(num_devices=n_dev)
    return (
        jax,
        model,
        cfg,
        mesh,
        n_dev,
        num_clients,
        (cx, cy, cmask),
        (make_fed_round, shard_client_data, make_local_update),
    )


def _time_spmd(jax, model, cfg, mesh, num_clients, data, make_fed_round,
               shard_client_data, rounds=7):
    cx, cy, cmask = data
    round_fn = make_fed_round(model, cfg, mesh, num_clients=num_clients)
    scx, scy, scm = shard_client_data(mesh, cx, cy, np.asarray(cmask))
    params = model.init(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(1)
    # Two warmup rounds: the first compiles for plain init params, the
    # second for the NamedSharding-carrying params the round itself emits —
    # the steady-state layout the timed loop runs with.
    params, _ = round_fn(params, scx, scy, scm, key)
    params, _ = round_fn(params, scx, scy, scm, key)
    jax.block_until_ready(params)
    # Chain params/keys through REAL training rounds and time the whole
    # block, anchored by a host fetch: repeated dispatches with identical
    # inputs are elided by the tunnel, and block_until_ready alone can ack
    # queued-but-unexecuted work (benchmarks/_util.device_sync).
    state = {"params": params, "key": key}

    def measure():
        t0 = time.perf_counter()
        for r in range(rounds):
            state["key"] = jax.random.fold_in(state["key"], r)
            state["params"], _ = round_fn(
                state["params"], scx, scy, scm, state["key"]
            )
        _bench_util().device_sync(state["params"])
        return (time.perf_counter() - t0) / rounds

    return _bench_util().retry_timing(
        measure, floor=3e-4, label="per-dispatch round"
    )


def _time_spmd_scanned(jax, model, cfg, mesh, num_clients, data,
                       shard_client_data, rounds_per_call=10, reps=5):
    """The trainer's optimized path (--rounds-per-call): K rounds scanned
    inside one dispatch (fed.round.make_fed_rounds, bit-identical to
    sequential rounds). Returns (median, per-block values) of seconds PER
    ROUND across chained measurement blocks (benchmarks/_util)."""
    from qfedx_tpu.fed.round import make_fed_rounds

    cx, cy, cmask = data
    rounds_fn = make_fed_rounds(
        model, cfg, mesh, num_clients=num_clients,
        rounds_per_call=rounds_per_call,
    )
    scx, scy, scm = shard_client_data(mesh, cx, cy, np.asarray(cmask))
    params = model.init(jax.random.PRNGKey(0))
    base = jax.random.PRNGKey(1)
    params, _ = rounds_fn(params, scx, scy, scm, base, 0)  # compile
    params, _ = rounds_fn(params, scx, scy, scm, base, 1)  # steady layout
    jax.block_until_ready(params)
    state = {"params": params}

    def measure():
        t0 = time.perf_counter()
        for r in range(reps):
            state["params"], _ = rounds_fn(
                state["params"], scx, scy, scm, base, r
            )
        _bench_util().device_sync(state["params"])
        return (time.perf_counter() - t0) / (reps * rounds_per_call)

    return _bench_util().retry_timing_vals(
        measure, floor=1e-3 / rounds_per_call, label="scanned rounds"
    )


def _time_sequential(jax, model, cfg, num_clients, data, make_local_update,
                     rounds=2):
    """Reference architecture: per-client jitted update in a Python loop,
    host-side weighted averaging (src/CFed/Classical_FL.py:128-147)."""
    cx, cy, cmask = data
    local_update = jax.jit(make_local_update(model, cfg))
    params = model.init(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(1)

    def one_round(params, key):
        deltas, weights = [], []
        for c in range(num_clients):
            d, n, _ = local_update(
                params, cx[c], cy[c], cmask[c], jax.random.fold_in(key, c)
            )
            deltas.append(d)
            weights.append(n)
        total = sum(float(w) for w in weights)
        avg = jax.tree.map(
            lambda *ls: sum(float(w) * l for w, l in zip(weights, ls)) / total,
            *deltas,
        )
        return jax.tree.map(lambda p, u: p + u, params, avg)

    params = one_round(params, key)  # warmup/compile
    params = one_round(params, key)  # steady-state layout
    jax.block_until_ready(params)
    times = []
    for r in range(rounds):
        t0 = time.perf_counter()
        params = one_round(params, jax.random.fold_in(key, r))
        jax.block_until_ready(params)
        times.append(time.perf_counter() - t0)
    return sorted(times)[len(times) // 2]


# --- compute-bound regime ----------------------------------------------------

# Per-chip peaks used for the cost-model ratios below (TPU v5e; the bench
# chip). If the driver runs on different hardware the absolute ratios shift
# but the FLOP-vs-bandwidth conclusion does not: gate application is
# ~1 FLOP/byte and will be HBM-bound on every TPU.
_PEAK_F32_FLOPS = 49.2e12  # v5e MXU fp32 (bf16 peak 197 TF / 4)
_PEAK_HBM_BPS = 819e9  # v5e HBM bandwidth


def _dense_cost_model(n_qubits: int, n_layers: int, state_bytes: int = 4):
    """(gates, est FLOPs, est HBM bytes) per sample-forward — an analytic
    PER-GATE STREAMING model, kept as a reference point, NOT a bound.

    Rotation (complex 2×2 in flip/select form): ~18·2^n FLOPs; CNOT
    (select/permutation): ~16·2^n FLOP-equivalents; each gate charged one
    full re+im state round trip ≈ 4·state_bytes·2^n bytes (state_bytes =
    4 f32, 2 bf16). The slab engine BEATS this model's byte count — XLA
    fuses consecutive row-qubit gates into shared passes (docs/PERF.md §2)
    — so ``vs_pergate_bound`` (achieved / model-predicted throughput) can
    legitimately exceed 1.0; it is a model ratio, not a utilization.
    """
    amps = 1 << n_qubits
    rot_gates = n_layers * n_qubits
    cnot_gates = n_layers * n_qubits  # ring
    gates = rot_gates + cnot_gates
    flops = rot_gates * 18 * amps + cnot_gates * 16 * amps
    bytes_ = gates * 4 * state_bytes * amps
    return gates, flops, bytes_


def _with_env(env: dict, fn, *a, **k):
    """Run fn with env vars set, restoring previous values after
    (single definition: benchmarks/_util.with_env)."""
    return _bench_util().with_env(env, fn, *a, **k)


def _bench_compute_bound(jax, n_qubits=16, n_layers=3, batch=64, reps=5,
                         steps=8, remat=False):
    """Batched forward+grad of the dense n-qubit VQC — simulation-dominated
    (2^16 amplitudes/sample × 96 gates ≫ dispatch). ``steps`` gradient
    steps run inside ONE jitted lax.scan so device time dominates the
    measurement — a single dispatch through the tunneled TPU carries
    ~100ms latency, comparable to one whole fwd+grad, which un-amortized
    flattened every timing to the latency floor. Cost-model ratios take
    backward ≈ 2× forward cost (adjoint state pass + gate-parameter
    reductions). Honors QFEDX_DTYPE for the byte model."""
    import jax.numpy as jnp
    import optax

    from qfedx_tpu.models.vqc import make_vqc_classifier

    state_bytes = (
        2 if os.environ.get("QFEDX_DTYPE", "") in ("bf16", "bfloat16") else 4
    )
    model = make_vqc_classifier(n_qubits=n_qubits, n_layers=n_layers,
                                num_classes=2, remat=remat)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.uniform(0, 1, (batch, n_qubits)), dtype=jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, (batch,)), dtype=jnp.int32)

    def loss(p):
        logits = model.apply(p, x)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    @jax.jit
    def many_steps(params):
        def body(p, _):
            l, g = jax.value_and_grad(loss)(p)
            # Tiny SGD step: keeps every iteration's work live (no CSE/DCE
            # of identical steps) without changing the op mix.
            p2 = jax.tree.map(lambda a, b: a - 1e-6 * b, p, g)
            return p2, l
        return jax.lax.scan(body, params, None, length=steps)

    p_out, ls = many_steps(params)  # compile
    jax.block_until_ready(ls)

    # Chained across reps + host-fetch anchored (dispatch elision and
    # lying block_until_ready — see _time_spmd / _util.device_sync).
    state = {"params": params}

    def measure():
        t0 = time.perf_counter()
        for _ in range(reps):
            state["params"], ls = many_steps(state["params"])
        _bench_util().device_sync(ls)
        return (time.perf_counter() - t0) / (reps * steps)

    t = _bench_util().retry_timing(
        measure, floor=1e-3 / steps, label=f"dense n={n_qubits}"
    )

    gates, fwd_flops, fwd_bytes = _dense_cost_model(
        n_qubits, n_layers, state_bytes
    )
    total_flops = 3 * batch * fwd_flops  # fwd + ~2x bwd
    total_bytes = 3 * batch * fwd_bytes
    amps = 1 << n_qubits
    from qfedx_tpu.ops.fuse import fuse_active

    return {
        "n_qubits": n_qubits,
        "n_layers": n_layers,
        "batch": batch,
        "fuse": fuse_active(n_qubits),
        "fwd_grad_s": round(t, 5),
        "amp_gates_per_s": round(3 * batch * gates * amps / t, 1),
        "est_tflops": round(total_flops / t / 1e12, 3),
        "est_flop_util": round(total_flops / t / _PEAK_F32_FLOPS, 4),
        "pergate_model_gbps": round(total_bytes / t / 1e9, 1),
        # Achieved throughput relative to what perfect per-gate streaming
        # at HBM peak would allow; > 1.0 ⇒ XLA fused gates into shared
        # passes and beat the per-gate model (docs/PERF.md §2) — this is
        # NOT a hardware utilization (VERDICT r04 weak 2).
        "vs_pergate_bound": round(total_bytes / t / _PEAK_HBM_BPS, 3),
    }


def _bench_fed16q(jax, rounds_per_call=10, reps=3):
    """The COMPOSED path at a simulation-dominated width: K scanned
    federated rounds (shard_map + epoch/batch scans) with the 16-qubit
    3-layer VQC, 2 clients on one chip. The quantity the north star
    actually scores — client-rounds/s — where the engine, not dispatch,
    is the cost (VERDICT r04 missing 3). From r06 the round folds the
    client axis into the batched slab (per-client gate coefficients,
    fed.round fold_clients_enabled; docs/PERF.md §10) instead of vmapping
    the engine over clients — QFEDX_FOLD_CLIENTS pins either form and the
    unfolded row below keeps the lever's cost measured."""
    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.fed.round import (
        client_mesh, fold_clients_enabled, shard_client_data,
    )
    from qfedx_tpu.models.vqc import make_vqc_classifier

    n_qubits, n_layers = 16, 3
    num_clients, samples, batch = 2, 64, 16
    steps_per_round = (samples // batch) * 1  # epochs=1
    model = make_vqc_classifier(n_qubits=n_qubits, n_layers=n_layers,
                                num_classes=2)
    cfg = FedConfig(local_epochs=1, batch_size=batch, learning_rate=0.1,
                    optimizer="adam")
    rng = np.random.default_rng(0)
    cx = rng.uniform(0, 1, (num_clients, samples, n_qubits)).astype(np.float32)
    cy = rng.integers(0, 2, (num_clients, samples)).astype(np.int32)
    cm = np.ones((num_clients, samples), dtype=np.float32)
    mesh = client_mesh(num_devices=1)
    # Same warmup + chained + fetch-anchored measurement protocol as the
    # headline (single definition — the tunnel-elision policy must not
    # fork between the two federated rows).
    per_round, _ = _time_spmd_scanned(
        jax, model, cfg, mesh, num_clients, (cx, cy, cm),
        shard_client_data, rounds_per_call=rounds_per_call, reps=reps,
    )
    from qfedx_tpu.ops.fuse import fuse_active, scan_active

    return {
        "n_qubits": n_qubits,
        "n_layers": n_layers,
        "clients": num_clients,
        "batch": batch,
        "local_steps_per_round": steps_per_round,
        "rounds_per_call": rounds_per_call,
        "fold_clients": fold_clients_enabled(model, cfg),
        "fuse": fuse_active(n_qubits),
        "scan_layers": scan_active(n_qubits, n_layers),
        "round_s": round(per_round, 5),
        "client_rounds_per_s": round(num_clients / per_round, 2),
        # per local step per client — directly comparable to the bare
        # compute_bound fwd_grad_s rows (same engine, composed program).
        "per_step_ms": round(per_round / steps_per_round * 1e3, 2),
    }


def _bench_fed16q_pipeline(jax, num_rounds=20, rounds_per_call=10):
    """The r09 pipeline lever measured END-TO-END through the trainer.

    The raw fed16q rows time bare scanned dispatches and by construction
    cannot see the host work the pipeline overlaps; this row runs the
    REAL round loop — train_federated with in-scan per-round eval, ε-free
    config, and a JSONL metrics row fsynced per round into a throwaway
    dir (the host tax every production round pays). Same 16-qubit/
    3-layer/2-client shapes as fed16q. QFEDX_PIPELINE=0 on the lever row
    pins the sequential dispatch→drain loop head-to-head (training is
    bit-identical either way, so any delta is pure overlap). Hot 2nd
    run; headline round_s = end-to-end wall / rounds (per-drain times
    are not comparable across depths — see the comment at the
    measurement site; the drain median is kept as a secondary field)."""
    import tempfile

    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.fed.round import client_mesh, donate_enabled
    from qfedx_tpu.models.vqc import make_vqc_classifier
    from qfedx_tpu.run.metrics import MetricsLogger
    from qfedx_tpu.run.trainer import resolve_pipeline_depth, train_federated

    n_qubits, n_layers = 16, 3
    num_clients, samples, batch = 2, 64, 16
    model = make_vqc_classifier(n_qubits=n_qubits, n_layers=n_layers,
                                num_classes=2)
    cfg = FedConfig(local_epochs=1, batch_size=batch, learning_rate=0.1,
                    optimizer="adam")
    rng = np.random.default_rng(0)
    cx = rng.uniform(0, 1, (num_clients, samples, n_qubits)).astype(np.float32)
    cy = rng.integers(0, 2, (num_clients, samples)).astype(np.int32)
    cm = np.ones((num_clients, samples), dtype=np.float32)
    tx = rng.uniform(0, 1, (64, n_qubits)).astype(np.float32)
    ty = rng.integers(0, 2, 64).astype(np.int32)
    mesh = client_mesh(num_devices=1)

    def one_run():
        with tempfile.TemporaryDirectory() as d:
            with MetricsLogger(os.path.join(d, "metrics.jsonl")) as log:
                t0 = time.perf_counter()
                res = train_federated(
                    model, cfg, cx, cy, cm, tx, ty, num_rounds=num_rounds,
                    eval_every=1, seed=0, mesh=mesh,
                    rounds_per_call=rounds_per_call,
                    on_round_end=lambda r, m: log.log(m),
                )
                total = time.perf_counter() - t0
        return res, total

    one_run()  # cold: compiles inside the first chunks
    res, total = one_run()  # hot measurement
    # Headline = END-TO-END wall / rounds. The trainer's per-drain
    # round_times_s are NOT comparable across depths (depth 0 excludes
    # the inter-chunk host block by construction — trainer dt_per_round
    # — while depth ≥ 1 drains fetch-to-fetch and includes any
    # non-hidden host work), so a median-of-drains ratio would cancel
    # exactly the overlap this lever exists to measure. Total wall
    # counts every host block at both depths; the drain median stays as
    # a secondary field.
    per_round = total / num_rounds
    drain_median = float(np.median(np.asarray(res.round_times_s[1:])))
    return {
        "n_qubits": n_qubits,
        "clients": num_clients,
        "rounds_per_call": rounds_per_call,
        "pipeline_depth": resolve_pipeline_depth(),
        "donate": donate_enabled(),
        "round_s": round(per_round, 5),
        "drain_round_s_median": round(drain_median, 5),
        "client_rounds_per_s": round(num_clients / per_round, 2),
        f"total_s_{num_rounds}_rounds": round(total, 3),
        "timing": "hot (2nd run; trainer path incl. in-scan eval + "
                  "per-round JSONL fsync; round_s = total wall / rounds)",
    }


def _bench_fed256(jax, target=0.90, max_rounds=30):
    """BASELINE config 5's actual cohort: 256 clients on ONE chip as a
    single 256-client block (fed/round.py supports block = C/D), trained
    to target accuracy on the learnable synthetic task through the
    scanned dispatch — the last "named but never executed" BASELINE
    number, measured (VERDICT r05 missing #1). 4096 synthetic samples →
    ~3 binary-filtered per client (padded to 8); ring secure-agg + 50%
    client sampling, the config-5 composition. Settings were tuned on
    the CPU mesh until the target is genuinely SUSTAINED (≥2 evals):
    reaches 0.9 around round 16 and holds ≥0.97 at round 40 (the 1024-
    train/4-per-client variant plateaued at 0.79 — cohort width without
    local data does not converge at this depth). The 8×32-block variant
    runs as a suite test on the virtual mesh (tests/test_fed_cohort.py)."""
    from qfedx_tpu.data.datasets import load_dataset
    from qfedx_tpu.data.partition import iid_partition, pack_clients
    from qfedx_tpu.data.pipeline import preprocess
    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.fed.round import client_mesh
    from qfedx_tpu.models.vqc import make_vqc_classifier
    from qfedx_tpu.run.trainer import train_federated

    num_clients = 256
    _, tr, te = load_dataset(
        "mnist", synthetic_train=4096, synthetic_test=1024, seed=1
    )
    pre = preprocess(tr, te, classes=(0, 1), features="pca", n_features=8)
    parts = iid_partition(len(pre.train[0]), num_clients, seed=0)
    cx, cy, cmask = pack_clients(*pre.train, parts, pad_multiple=8)
    model = make_vqc_classifier(n_qubits=8, n_layers=3, num_classes=2)
    cfg = FedConfig(
        local_epochs=2,
        batch_size=8,
        learning_rate=0.1,
        optimizer="adam",
        client_fraction=0.5,
        secure_agg=True,
        secure_agg_mode="ring",
    )
    mesh = client_mesh(num_devices=1)
    t0 = time.time()
    # pipeline_depth=0: keep this row's per-round timings on the
    # pre-r09 dispatch→ready methodology so vs_prev compares like with
    # like (at depth ≥ 1 round_times_s become fetch-to-fetch windows
    # that include host-block time — the r05/r06 methodology-compare
    # trap); the fed16q_bf16_pipeline rows own the r09 measurement.
    res = train_federated(
        model, cfg, cx, cy, cmask, *pre.test, num_rounds=max_rounds,
        eval_every=1, seed=0, mesh=mesh, rounds_per_call=10,
        pipeline_depth=0,
    )
    total = time.time() - t0
    out = {
        "clients": num_clients,
        "client_block_per_device": num_clients,
        "target_accuracy": target,
    }
    out.update(_target_hits(res.accuracies, res.round_times_s, target))
    steady = (
        float(np.median(np.asarray(res.round_times_s[1:])))
        if len(res.round_times_s) > 1
        else None
    )
    out["round_s"] = None if steady is None else round(steady, 4)
    out["client_rounds_per_s"] = (
        None if not steady else round(num_clients / steady, 1)
    )
    out["final_accuracy"] = round(float(res.accuracies[-1]), 4)
    out[f"total_s_{max_rounds}_rounds"] = round(total, 3)
    return out


def _bench_fed_streamed(jax, cohort=4096, wave=256, num_rounds=3):
    """Aggregate client-rounds/s with the cohort UNBOUNDED by HBM (r10):
    ``cohort`` clients/round streamed through ``wave``-client waves on
    ONE chip via the hierarchical partial/apply round — peak device
    residency is one wave's data (+ the prefetch depth's staged
    uploads), never the cohort's, so 4096 clients/round runs where the
    resident path tops out at fed256's slab. Clients come from a
    simulated 2^20-client registry (data.stream.SyntheticRegistry —
    counter-hash data, materialized per wave); config-5 composition
    (ring secure-agg + 50% sampling) like the fed256 row it extends.
    Headline = cohort / median steady round wall (round 0 holds the
    partial/accum/apply compiles); the QFEDX_STREAM=0 lever re-times the
    loop with synchronous uploads, so the delta is pure H2D overlap."""
    from qfedx_tpu.data.stream import SyntheticRegistry
    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.fed.round import client_mesh
    from qfedx_tpu.models.vqc import make_vqc_classifier
    from qfedx_tpu.run.trainer import train_federated_streamed

    registry = SyntheticRegistry(1 << 20, samples=8, n_features=8, seed=1)
    model = make_vqc_classifier(n_qubits=8, n_layers=3, num_classes=2)
    cfg = FedConfig(
        local_epochs=1,
        batch_size=8,
        learning_rate=0.1,
        optimizer="adam",
        client_fraction=0.5,
        secure_agg=True,
        secure_agg_mode="ring",
    )
    mesh = client_mesh(num_devices=1)
    # Eval set drawn from the registry's own distribution (held-out ids
    # at the top of the registry — the cohort sampler can reach them,
    # but at 2^20 clients a 4096-cohort collision is immaterial for a
    # throughput row).
    ex, ey, _ = registry.batch(np.arange((1 << 20) - 32, 1 << 20))
    tx = ex.reshape(-1, 8)
    ty = ey.reshape(-1)

    def run(depth, rounds):
        res = train_federated_streamed(
            model, cfg, registry, tx, ty, cohort_size=cohort,
            wave_size=wave, num_rounds=rounds, seed=0, mesh=mesh,
            eval_every=rounds + 1, stream_depth=depth,
        )
        return res

    res = run(1, num_rounds)
    steady = float(np.median(np.asarray(res.round_times_s[1:])))
    out = {
        "registry_clients": 1 << 20,
        "cohort": cohort,
        "wave_size": wave,
        "waves_per_round": cohort // wave,
        "hbm_resident_clients": wave,
        "round_s": round(steady, 4),
        "client_rounds_per_s": round(cohort / steady, 1),
        "comm_mb_per_round": round(res.comm_mb_per_round, 4),
        "final_accuracy": round(float(res.accuracies[-1]), 4),
        "timing": "median steady round (round 0 = compile, excluded)",
    }
    # H2D-overlap lever: same loop, synchronous uploads (QFEDX_STREAM=0).
    res_off = run(0, 2)
    off_s = float(res_off.round_times_s[-1])
    out["stream_off_round_s"] = round(off_s, 4)
    out["stream_speedup_vs_sync"] = round(off_s / steady, 3)
    return out


def _bench_fault_tolerance(jax, cohort=128, wave=64, num_rounds=6):
    """Dropout-rate → accuracy degradation curve (r11): the streamed
    trainer under injected client casualties at 0 / 5 / 20% per round
    (half drops, half NaN-poisoned updates — both recovery paths), same
    registry/config family as the fed_streamed row. The 0% run doubles
    as the guards-on baseline; the curve says how much accuracy the
    dropout-resilient aggregation actually preserves as churn grows —
    the number the million-client north star lives on. vs_prev tracks
    the 20% point."""
    from qfedx_tpu.data.stream import SyntheticRegistry
    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.fed.round import client_mesh
    from qfedx_tpu.models.vqc import make_vqc_classifier
    from qfedx_tpu.run.trainer import train_federated_streamed
    from qfedx_tpu.utils.faults import FaultPlan

    registry = SyntheticRegistry(1 << 18, samples=8, n_features=8, seed=2)
    model = make_vqc_classifier(n_qubits=8, n_layers=3, num_classes=2)
    cfg = FedConfig(
        local_epochs=1, batch_size=8, learning_rate=0.1,
        optimizer="adam", secure_agg=True, secure_agg_mode="ring",
    )
    mesh = client_mesh(num_devices=1)
    ex, ey, _ = registry.batch(np.arange((1 << 18) - 32, 1 << 18))
    tx, ty = ex.reshape(-1, 8), ey.reshape(-1)

    out = {
        "cohort": cohort, "wave_size": wave, "rounds": num_rounds,
        "mix": "rate/2 drops + rate/2 nan per round",
    }
    for rate in (0.0, 0.05, 0.20):
        plan = None
        if rate > 0:
            plan = FaultPlan(seed=11, rules=[
                {"site": "client.compute", "kind": "drop", "rate": rate / 2},
                {"site": "client.compute", "kind": "nan", "rate": rate / 2},
            ])
        res = train_federated_streamed(
            model, cfg, registry, tx, ty, cohort_size=cohort,
            wave_size=wave, num_rounds=num_rounds, seed=6, mesh=mesh,
            eval_every=num_rounds, fault_plan=plan,
        )
        key = f"acc_rate_{int(rate * 100)}pct"
        out[key] = round(float(res.accuracies[-1]), 4)
        if rate > 0:
            out[f"degradation_{int(rate * 100)}pct"] = round(
                out["acc_rate_0pct"] - out[key], 4
            )
    return out


def _bench_byzantine(jax, cohort=64, wave=16, num_rounds=12):
    """Attack-fraction → accuracy curves with the defense off vs each
    robust rule (r12): scale:100 model-poisoning attackers at 0/10/20%
    of the registry per round, streamed trainer (4 waves — the robust
    rules' hierarchical level is live), secure-agg OFF so trimmed/
    median defend per client (their masked composition is pinned in
    tests/test_byzantine.py; this section measures ACCURACY under
    attack). The headline the ISSUE asks for: at 20% attackers plain
    mean collapses while at least one robust rule stays within 2
    accuracy points of the clean run — ``vs_prev`` tracks the defended
    20% point so the defense can never silently rot."""
    from qfedx_tpu.data.stream import SyntheticRegistry
    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.fed.round import client_mesh
    from qfedx_tpu.models.vqc import make_vqc_classifier
    from qfedx_tpu.run.trainer import train_federated_streamed
    from qfedx_tpu.utils.faults import FaultPlan

    # samples=16 × 2 epochs at batch 8 = 4 local steps/round — enough
    # for the clean run to actually converge inside the bench budget
    # (a clean baseline at chance level can demonstrate no collapse).
    registry = SyntheticRegistry(1 << 16, samples=16, n_features=8, seed=5)
    model = make_vqc_classifier(n_qubits=8, n_layers=3, num_classes=2)
    mesh = client_mesh(num_devices=1)
    ex, ey, _ = registry.batch(np.arange((1 << 16) - 32, 1 << 16))
    tx, ty = ex.reshape(-1, 8), ey.reshape(-1)

    def cfg_for(agg):
        return FedConfig(
            local_epochs=2, batch_size=8, learning_rate=0.1,
            optimizer="adam", aggregator=agg,
            # clip_bound ≈ a generous honest adam-update norm (measured
            # ~2-3 at this shape; a tighter bound throttles honest
            # learning); trim 0.25 eats up to 25% attackers per
            # coordinate end.
            clip_bound=(3.0 if agg == "clip_mean" else float("inf")),
            trim_fraction=0.25,
        )

    def run(agg, rate):
        plan = None
        if rate > 0:
            plan = FaultPlan(seed=17, rules=[
                {"site": "client.byzantine", "kind": "scale:100",
                 "rate": rate},
            ])
        res = train_federated_streamed(
            model, cfg_for(agg), registry, tx, ty, cohort_size=cohort,
            wave_size=wave, num_rounds=num_rounds, seed=9, mesh=mesh,
            eval_every=num_rounds, fault_plan=plan,
        )
        return round(float(res.accuracies[-1]), 4)

    out = {
        "cohort": cohort, "wave_size": wave, "rounds": num_rounds,
        "attack": "scale:100 at rate per round (client.byzantine)",
        "acc_clean": run("mean", 0.0),
    }
    rules = ("mean", "clip_mean", "trimmed_mean", "median")
    for rate in (0.10, 0.20):
        pct = int(rate * 100)
        for agg in rules:
            out[f"acc_{agg}_{pct}pct"] = run(agg, rate)
    best20 = max(out[f"acc_{agg}_20pct"] for agg in rules[1:])
    out["best_defended_acc_20pct"] = best20
    out["mean_collapse_20pct"] = round(
        out["acc_clean"] - out["acc_mean_20pct"], 4
    )
    out["defended_within_2pts_of_clean_at_20pct"] = bool(
        best20 >= out["acc_clean"] - 0.02
    )
    return out


def _bench_straggler(jax, cohort=64, wave=16, num_rounds=12):
    """Straggler-rate → accuracy + utilized-throughput curves (r13):
    0/10/30% of waves go ONE ROUND LATE (``wave.delay``, declared
    deterministically) under the two policies — ``drop`` (r12: the
    late work dies as casualties; the in-order uploader additionally
    suffers head-of-line amplification, which IS the r12 behavior
    under stragglers) vs ``buffer`` (QFEDX_STALE: the work lands a
    round late at the staleness discount). The headline: at 30%
    injected stragglers the buffered run stays within noise of the
    clean run's accuracy while recovering the straggler work — and
    drop MEASURABLY loses that work: ``utilized_client_rounds_per_s``
    counts client updates that actually reached θ per steady-state
    wall second (stale ones included — that is the recovered signal),
    the north-star throughput metric. Measured honestly: on the IID
    SyntheticRegistry final ACCURACY is insensitive to random wave
    subsampling (losing 30% of an IID cohort ≈ a smaller cohort, well
    inside seed noise at this scale), so drop's measurable loss is
    utilization — wasted client compute plus head-of-line stalls —
    not the last accuracy digit; the within-noise flag guards the
    buffered run's accuracy, ``utilization_recovered_30pct`` the
    recovered work. ``vs_prev`` tracks the buffered 30% point."""
    from qfedx_tpu.data.stream import SyntheticRegistry
    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.fed.round import client_mesh
    from qfedx_tpu.models.vqc import make_vqc_classifier
    from qfedx_tpu.run.trainer import train_federated_streamed
    from qfedx_tpu.utils.faults import FaultPlan

    registry = SyntheticRegistry(1 << 16, samples=16, n_features=8, seed=8)
    model = make_vqc_classifier(n_qubits=8, n_layers=3, num_classes=2)
    cfg = FedConfig(local_epochs=2, batch_size=8, learning_rate=0.1,
                    optimizer="adam", secure_agg=True,
                    secure_agg_mode="ring")
    mesh = client_mesh(num_devices=1)
    ex, ey, _ = registry.batch(np.arange((1 << 16) - 32, 1 << 16))
    tx, ty = ex.reshape(-1, 8), ey.reshape(-1)

    def run(rate, policy):
        plan = None
        if rate > 0:
            plan = FaultPlan(seed=23, rules=[
                {"site": "wave.delay", "kind": "delay:0.4", "rate": rate},
            ])
        rows = []

        def go():
            return train_federated_streamed(
                model, cfg, registry, tx, ty, cohort_size=cohort,
                wave_size=wave, num_rounds=num_rounds, seed=13,
                mesh=mesh, eval_every=num_rounds, fault_plan=plan,
                wave_deadline_s=0.05, stale_poll_s=20.0,
                on_round_end=lambda r, m: rows.append(m),
            )

        res = _with_env(
            {"QFEDX_STALE": "1" if policy == "buffer" else "0"}, go
        )
        # Steady-state utilized throughput: clients whose update
        # actually reached θ per second, rounds 1+ (round 0 holds the
        # partial/apply compiles and would penalize whichever policy
        # runs first).
        utilized = sum(r.get("participants", 0) for r in rows[1:])
        steady_wall = max(sum(res.round_times_s[1:]), 1e-9)
        return {
            "acc": round(float(res.accuracies[-1]), 4),
            "utilized_client_rounds_per_s": round(
                utilized / steady_wall, 1
            ),
            "stale_partials_applied": sum(
                r.get("stale_partials_applied", 0) for r in rows
            ),
            "dropped_clients": sum(
                r.get("dropped_clients", 0) for r in rows
            ),
        }

    out = {
        "cohort": cohort, "wave_size": wave, "rounds": num_rounds,
        "injection": "wave.delay delay:0.4 at rate, one-round lateness "
                     "(deadline 0.05s)",
    }
    clean = run(0.0, "drop")
    out["acc_clean"] = clean["acc"]
    out["utilized_cr_s_clean"] = clean["utilized_client_rounds_per_s"]
    for rate in (0.10, 0.30):
        pct = int(rate * 100)
        for policy in ("drop", "buffer"):
            r = run(rate, policy)
            out[f"acc_{policy}_{pct}pct"] = r["acc"]
            out[f"utilized_cr_s_{policy}_{pct}pct"] = r[
                "utilized_client_rounds_per_s"
            ]
            if policy == "buffer":
                out[f"stale_partials_{pct}pct"] = r[
                    "stale_partials_applied"
                ]
            else:
                out[f"dropped_clients_{policy}_{pct}pct"] = r[
                    "dropped_clients"
                ]
    out["drop_loss_30pct"] = round(
        out["acc_clean"] - out["acc_drop_30pct"], 4
    )
    out["buffer_loss_30pct"] = round(
        out["acc_clean"] - out["acc_buffer_30pct"], 4
    )
    out["buffered_within_noise_of_clean_30pct"] = bool(
        out["acc_buffer_30pct"] >= out["acc_clean"] - 0.02
    )
    # The measurable drop-mode loss: the fraction of fleet work drop
    # throws away that buffering recovers (≥ 1; ~2.7× measured on CPU).
    if out["utilized_cr_s_drop_30pct"]:
        out["utilization_recovered_30pct"] = round(
            out["utilized_cr_s_buffer_30pct"]
            / out["utilized_cr_s_drop_30pct"],
            3,
        )
    return out


def _bench_serve(jax, n_qubits=16, n_layers=3, requests_per_rate=384):
    """Serving rows (r14): offered-load sweep through the REAL serving
    stack — ServeEngine (persistent compiled forward, bucketed padding)
    + MicroBatcher (deadline/bucket-full flushes, bounded-queue
    shedding) — at the dense n=16 serving shape.

    Method: measure the warm max-bucket batch latency once to size the
    engine's capacity, then offer load at 0.2/0.5/0.8× capacity with
    deterministic uniform inter-arrival gaps (seeded; stated — Poisson
    burstiness is a follow-up knob). Per rate: p50/p95 of the full
    submit→answer latency (queue + pad + compute + fetch), completed
    throughput, shed count. ``throughput_at_slo`` is the best completed
    throughput among rates whose p95 meets the stated SLO
    (ServeConfig.slo_ms, 50 ms); headline p50/p95 come from that rate.
    ``vs_prev`` tracks serve_p50_ms / serve_p95_ms / throughput_at_slo.

    The zero-compile contract is MEASURED here, not assumed: the sweep
    runs under QFEDX_TRACE with the jax.monitoring compile listener on,
    and ``compile_s_in_loop`` must be 0.0 after warmup — every bucket
    was compiled before the first request (tests/test_serve.py pins the
    same invariant in tier-1)."""
    from qfedx_tpu import obs
    from qfedx_tpu.models.vqc import make_vqc_classifier
    from qfedx_tpu.serve import MicroBatcher, Overloaded, ServeConfig, ServeEngine

    def run():
        obs.reset()
        model = make_vqc_classifier(
            n_qubits=n_qubits, n_layers=n_layers, num_classes=2
        )
        params = model.init(jax.random.PRNGKey(0))
        cfg = ServeConfig(
            buckets=(8, 32, 128), deadline_ms=5.0, max_queue=512, slo_ms=50.0
        )
        engine = ServeEngine(model, params, (n_qubits,), config=cfg)
        warm = engine.warmup()

        def compile_s():
            return sum(
                v for k, v in obs.registry().counters.items()
                if k.startswith("compile.")
            )

        rng = np.random.default_rng(0)
        x_cap = rng.uniform(0, 1, (cfg.buckets[-1], n_qubits)).astype(
            np.float32
        )
        engine.infer(x_cap)  # warm timing path

        def measure():
            t0 = time.perf_counter()
            engine.infer(x_cap)
            return time.perf_counter() - t0

        batch_s = _bench_util().retry_timing(
            measure, floor=1e-5, label="serve capacity"
        )
        capacity = cfg.buckets[-1] / batch_s
        compile_before = compile_s()

        reqs = rng.uniform(0, 1, (requests_per_rate, n_qubits)).astype(
            np.float32
        )
        rates = {}
        for frac in (0.2, 0.5, 0.8):
            rate = frac * capacity
            gap = 1.0 / rate
            futs, shed = [], 0
            with MicroBatcher(engine) as b:
                t_next = time.monotonic()
                for i in range(requests_per_rate):
                    now = time.monotonic()
                    if now < t_next:
                        time.sleep(t_next - now)
                    t_next += gap
                    try:
                        futs.append(b.submit(reqs[i]))
                    except Overloaded:
                        shed += 1
                for f in futs:
                    f.result(timeout=60.0)
            if not futs:  # fully shed — record the refusal, no percentiles
                rates[f"load_{frac}"] = {
                    "offered_rps": round(rate, 1), "shed": shed,
                }
                continue
            # Bounded histogram (r15): the log-bucketed quantiles the
            # live /metrics endpoint serves — within one bucket-width
            # (~10%) of the exact sorted-list percentile (pinned in
            # tests/test_obs.py), fixed memory at any request count.
            hist = obs.Histogram()
            for f in futs:
                hist.record((f.done_t - f.submit_t) * 1e3)
            wall = max(f.done_t for f in futs) - futs[0].submit_t
            rates[f"load_{frac}"] = {
                "offered_rps": round(rate, 1),
                "completed_rps": round(len(futs) / wall, 1),
                "p50_ms": round(hist.percentile(0.50), 3),
                "p95_ms": round(hist.percentile(0.95), 3),
                "shed": shed,
                "batches": b.stats["batches"],
            }
        compile_in_loop = compile_s() - compile_before

        # r20 detection canary: the sweep ran under the live watchdog
        # (QFEDX_WATCH in the section wrapper — warmup starts the
        # ticker). A closing evaluation flushes the last window; any
        # firing lands in alerts_fired. Expected 0 on-chip: a breach
        # during bench IS a regression signal, tracked by vs_prev.
        from qfedx_tpu.obs import watch as _watch

        _watch.evaluate_once()
        alert_totals = _watch.fired_totals()

        # r21 tuned lever: run the offline tuner's deadline lattice over
        # the SAME model (tune/offline.py — the `qfedx tune` engine) and
        # report the winning cell next to the default one, so vs_prev
        # tracks throughput_at_slo tuned-vs-default as a lever row. The
        # per-route persistent-forward cache hands the equal-route cells
        # their already-compiled programs.
        from qfedx_tpu.tune import offline as _tune_offline

        try:
            tuned_sweep = _tune_offline.sweep_serve(
                model, params, (n_qubits,),
                slo_ms=cfg.slo_ms,
                bucket_sets=(cfg.buckets,),
                deadlines_ms=(2.5, 5.0, 10.0),
                requests=min(requests_per_rate, 96),
                rate_fracs=(0.5, 0.8),
                max_queue=cfg.max_queue,
            )
            tuned_best = tuned_sweep["best"]
            tuned = {
                "deadline_ms": tuned_best["deadline_ms"],
                "buckets": tuned_best["buckets"],
                "throughput_at_slo": tuned_best["throughput_at_slo"],
                "p50_ms": tuned_best["p50_ms"],
                "p95_ms": tuned_best["p95_ms"],
                "cells": len(tuned_sweep["cells"]),
            }
        except Exception as exc:  # noqa: BLE001 — a broken tuner must not
            tuned = {"error": str(exc)}  # sink the serve rows themselves

        ok = [
            r for r in rates.values()
            if r.get("p95_ms") is not None
            and r["p95_ms"] <= cfg.slo_ms and r["shed"] == 0
        ]
        best = max(ok, key=lambda r: r["completed_rps"]) if ok else None
        return {
            "n_qubits": n_qubits,
            "buckets": list(cfg.buckets),
            "deadline_ms": cfg.deadline_ms,
            "slo_ms": cfg.slo_ms,
            # Stated so the first post-r15 vs_prev is readable: p50/p95
            # switched from exact sorted-list percentiles to histogram
            # LOWER-EDGE quantiles (<= one ~10% bucket below exact), so
            # that round's serve_p50/p95 delta includes a one-time
            # definitional shift, not a real latency change.
            "quantile_definition": "histogram lower-edge (r15)",
            "warmup": warm["buckets"],
            # r17: the engine routing the warmed programs resolved to
            # (ServeEngine.warmup) — the serve floor row states which
            # program (scanned or per-layer) it re-reports even when
            # every raw pin is unset, plus the raw env snapshot for
            # exact repro.
            "route": warm.get("route_resolved"),
            "route_pins": warm.get("route"),
            "batch_s_max_bucket": round(batch_s, 5),
            "capacity_rps": round(capacity, 1),
            "rates": rates,
            "compile_s_in_loop": round(compile_in_loop, 4),
            "zero_compiles_in_loop": compile_in_loop == 0.0,
            "throughput_at_slo": best["completed_rps"] if best else 0.0,
            "serve_p50_ms": best["p50_ms"] if best else None,
            "serve_p95_ms": best["p95_ms"] if best else None,
            "alerts_fired": int(sum(alert_totals.values())),
            "alerts_by_rule": alert_totals or None,
            "tuned": tuned,
        }

    # QFEDX_TRACE on for the whole section: the compile listener is the
    # zero-compile measurement; span overhead is µs against ms batches
    # (docs/PERF.md §13). QFEDX_WATCH on (r20): the watchdog ticks over
    # the live sweep — the alerts_fired canary above.
    from qfedx_tpu.obs import watch as _watch

    _watch.reset()
    try:
        return _with_env({"QFEDX_TRACE": "1", "QFEDX_WATCH": "1"}, run)
    finally:
        _watch.reset()


def _bench_fusion_hlo(jax):
    """Per-step STATE-SIZED emitted-op counts with the fusion pass on vs
    off — the floor-reduction claim measured in ops, not asserted (ISSUE
    r07; docs/PERF.md §12). A state-sized op (result ≥ 2^n elements) is
    one HBM pass / scheduling slot — the thing §11's floor model prices;
    raw op totals are NOT the metric (fusion adds tiny trace-time
    matrix-composition ops while removing state passes). Counts come
    from the LOWERED (StableHLO) module of a ONE-step fwd+grad program
    (lowering only — no backend compile, so this section is cheap);
    compiled-module pass counts are the chip-side follow-up via
    benchmarks/profile_step.py."""
    from benchmarks._util import build_step
    from qfedx_tpu.obs.hlo import lowered_state_ops

    out = {}
    for n, batch in ((16, 64), (18, 16), (20, 8)):
        row = {}
        for pin, label in (("1", "fused"), ("off", "unfused")):

            def count(_j):
                fn, params, _steps = build_step(n, 3, batch, 1)
                # The ONE static-census helper (obs/hlo.py) this
                # section shares with floor_attribution below and
                # profile_step --device-profile — the static side of
                # every measured-vs-static comparison counts ops
                # identically (ISSUE r16 hygiene).
                return lowered_state_ops(fn, params, n)

            row[label] = _with_env({"QFEDX_FUSE": pin}, count, jax)
        row["state_op_ratio"] = round(
            row["unfused"] / max(row["fused"], 1), 3
        )
        out[f"n{n}"] = row
    return out


def _bench_floor_attribution(jax):
    """The MEASURED floor evidence (r16; docs/PERF.md §16–17): a
    profiler capture of the real step program, parsed into the runtime
    op census (obs/profile.py) — executed ops vs the static
    ``fusion_hlo`` census (same ``obs.hlo.lowered_state_ops`` helper),
    the measured inter-op gap quantiles the §15 3–5 µs/op inference
    guessed at, and the device-busy fraction. Since r17 the headline
    row profiles the SCANNED program (QFEDX_SCAN_LAYERS — the op-count
    collapse this harness was built to judge) with the r07-fused
    program captured head-to-head: ``ops_per_step_vs_fused`` is the
    measured collapse factor, ``vs_prev`` keeps tracking
    gap_us_per_op/ops_per_step on the headline row, and the ``depth6``
    pair measures the same collapse at L=6 (always n=12), where the
    depth-invariant scanned body pulls further ahead of the
    linearly-growing r07 program (docs/PERF.md §17).

    Width is backend-sized: the chip profiles the dense18q production
    step; this container's CPU profiles n=12 with the TPU slab routing
    pinned (a dense18q CPU step is ~30 s of thunks — same math,
    recorded once in PERF.md §16)."""
    import tempfile

    from benchmarks._util import build_step, device_sync
    from qfedx_tpu.obs import profile as obs_profile
    from qfedx_tpu.obs.hlo import lowered_state_ops

    on_chip = jax.default_backend() == "tpu"
    n, batch, steps = (18, 16, 4) if on_chip else (12, 16, 2)
    route = {"QFEDX_FUSE": "1", "QFEDX_SCAN_LAYERS": "1"}
    if not on_chip:
        # Off-chip the production slab route must be pinned explicitly
        # (on the chip these ARE the defaults, so the pins are no-ops).
        route.update({
            "QFEDX_GATE_FORM": "flip",
            "QFEDX_SLAB_LANES": "matmul",
            "QFEDX_BATCHED": "1",
        })

    def profile_one(n_layers=3, n_q=None):
        nq = n if n_q is None else n_q
        fn, params, _ = build_step(nq, n_layers, batch, steps)
        static = lowered_state_ops(fn, params, nq)
        params, ls = fn(params)  # warm: compile outside the capture
        device_sync(ls)
        with tempfile.TemporaryDirectory(prefix="qfedx-floor-") as tdir:
            with obs_profile.capture(tdir):
                params, ls = fn(params)
                device_sync(params)
            parsed = obs_profile.parse_capture(tdir)
        summary = obs_profile.summarize(
            parsed, static_state_ops=static, steps=steps
        )
        return obs_profile.floor_attribution(static, summary)

    row = _with_env(route, profile_one)
    fused = _with_env(
        {**route, "QFEDX_SCAN_LAYERS": "off"}, profile_one
    )
    row["route"] = "scanned"
    # The resolved fuse/scan/pallas booleans of the HEADLINE row's env —
    # snapshots are self-describing (r19): a future reader must not have
    # to reconstruct what an unset pin defaulted to on this backend.
    from qfedx_tpu.ops.pallas_body import resolved_route

    row["route_resolved"] = _with_env(route, resolved_route)
    row["r07_fused"] = {
        k: fused.get(k)
        for k in ("static_state_ops", "ops_per_step", "gap_us_per_op",
                  "gap_p95_us", "device_busy_fraction")
    }
    # r19 third arm: the SAME program with the scan body as one Pallas
    # kernel (QFEDX_PALLAS=1). On the chip this is the kernel the route
    # defaults to; off-chip pallas_call runs INTERPRETED — the executed
    # census then measures the interpreter, not the kernel, so the row
    # carries the ``interpreted`` flag and the honest judgement lives in
    # the static TPU-lowered census (tests/test_obs_hlo.py pins pallas
    # 279 < scanned 336 state ops at n=12; docs/PERF.md §18).
    pallas = _with_env({**route, "QFEDX_PALLAS": "1"}, profile_one)
    row["pallas"] = {
        k: pallas.get(k)
        for k in ("static_state_ops", "ops_per_step", "gap_us_per_op",
                  "gap_p95_us", "device_busy_fraction")
    }
    row["pallas"]["interpreted"] = not on_chip
    if pallas.get("ops_per_step") and row.get("ops_per_step"):
        row["pallas"]["ops_per_step_vs_scanned"] = round(
            row["ops_per_step"] / pallas["ops_per_step"], 2
        )
    if row.get("ops_per_step") and fused.get("ops_per_step"):
        row["ops_per_step_vs_fused"] = round(
            fused["ops_per_step"] / row["ops_per_step"], 2
        )
    if row.get("static_state_ops") and fused.get("static_state_ops"):
        row["static_vs_fused"] = round(
            fused["static_state_ops"] / row["static_state_ops"], 2
        )
    # Depth scaling (r17): the scanned body appears ONCE in the lowered
    # program whatever the depth, while the r07 program repeats every
    # super-gate per layer — so the collapse factor RISES with L and the
    # L=3 headline (the repo's flagship depth, kept for vs_prev
    # continuity) is its floor. The L=6 head-to-head pair measures the
    # depth dimension on the same harness; always n=12 so the number is
    # backend-stable (an unrolled deep fused program at chip widths is
    # minutes of XLA compile for a census no different from n=12's).
    deep = _with_env(route, lambda: profile_one(6, n_q=12))
    deep_fused = _with_env(
        {**route, "QFEDX_SCAN_LAYERS": "off"},
        lambda: profile_one(6, n_q=12),
    )
    row["depth6"] = {
        "n": 12,
        "ops_per_step": deep.get("ops_per_step"),
        "static_state_ops": deep.get("static_state_ops"),
        "r07_ops_per_step": deep_fused.get("ops_per_step"),
        "r07_static_state_ops": deep_fused.get("static_state_ops"),
    }
    if deep.get("ops_per_step") and deep_fused.get("ops_per_step"):
        row["depth6"]["ops_per_step_vs_fused"] = round(
            deep_fused["ops_per_step"] / deep["ops_per_step"], 2
        )
    row["n"] = n
    row["batch"] = batch
    row["steps"] = steps
    return row


def _target_hits(accuracies, round_times_s, target):
    """first_touch and SUSTAINED hit from a per-round accuracy series.

    ``accuracies[0]`` is the round-0 (pre-training) eval. first_touch: the
    first round whose eval meets the target (one eval can be a spike —
    the 20q run counted exactly such a spike as success in r05).
    sustained: the first round of a streak of ≥ 2 consecutive evals at or
    above the target — the round whose params genuinely reached the
    target; a final-round hit with no successor eval cannot be confirmed
    and does not count. Hit time = Σ per-round walls through the hit
    round."""
    def hit_s(rnd):
        return (
            round(sum(round_times_s[:rnd]), 3) if rnd is not None else None
        )

    first = next(
        (i for i, a in enumerate(accuracies) if i > 0 and a >= target), None
    )
    sustained = next(
        (
            i
            for i in range(1, len(accuracies) - 1)
            if accuracies[i] >= target and accuracies[i + 1] >= target
        ),
        None,
    )
    return {
        "seconds": hit_s(sustained),
        "rounds": sustained,
        "reached": sustained is not None,
        "reached_definition": "accuracy >= target for >=2 consecutive evals",
        "first_touch_seconds": hit_s(first),
        "first_touch_rounds": first,
    }


def _bench_time_to_target(jax, target=0.90, max_rounds=40):
    """Wall-clock to ``target`` accuracy on the learnable synthetic set —
    the second north-star metric (BASELINE.json "FedAvg wall-clock to
    target accuracy"): flagship 8-qubit config, 8 clients.

    Measured HOT (r06, the r05 regression finding — docs/PERF.md §11):
    the run executes twice and the second run is the reported one. The
    r05 "regression" of this row was the first scanned chunk's cold-cache
    XLA compile landing inside the timed window — total 40-round wall was
    unchanged (18.9 → 19.5 s) while the 15-round hit time doubled, i.e.
    the metric was measuring compile-cache state, not the engine. The
    cold (first-run) wall is kept alongside so compile cost stays
    visible instead of silently mixed in."""
    from qfedx_tpu.data.datasets import load_dataset
    from qfedx_tpu.data.partition import iid_partition, pack_clients
    from qfedx_tpu.data.pipeline import preprocess
    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.models.vqc import make_vqc_classifier
    from qfedx_tpu.run.trainer import train_federated

    _, tr, te = load_dataset("mnist", synthetic_train=1024, synthetic_test=256, seed=1)
    pre = preprocess(tr, te, classes=(0, 1), features="pca", n_features=8)
    parts = iid_partition(len(pre.train[0]), 8, seed=0)
    cx, cy, cmask = pack_clients(*pre.train, parts, pad_multiple=32)
    model = make_vqc_classifier(n_qubits=8, n_layers=3, num_classes=2)
    cfg = FedConfig(local_epochs=2, batch_size=32, learning_rate=0.1, optimizer="adam")

    # Scanned dispatch with ON-DEVICE per-round eval (rounds_per_call):
    # accuracy at every round comes out of the same device program, so
    # the timed window is training + in-scan eval, not 40 host eval
    # round-trips. Two identical runs: the first compiles (persistent
    # cache + in-process jit caches), the second is the hot measurement —
    # training is seed-deterministic, so both runs hit the same rounds.
    def one_run():
        t0 = time.perf_counter()
        # pipeline_depth=0: pre-r09 per-round timing methodology, so
        # vs_prev diffs against BENCH_r08 compare like with like (see
        # _bench_fed256); the pipeline lever rows own the r09 delta.
        res = train_federated(
            model, cfg, cx, cy, cmask, *pre.test, num_rounds=max_rounds,
            eval_every=1, seed=0, rounds_per_call=10, pipeline_depth=0,
        )
        return res, time.perf_counter() - t0

    _, cold_total = one_run()

    # The hot run is TRACED (QFEDX_TRACE is a per-call host guard, not
    # trace-time routing, so with_env covers the whole run): the
    # phase_breakdown below localizes a future regression of this row to
    # dispatch vs eval vs trace-build vs compile instead of requiring a
    # §11-style forensic pass. Span overhead is a few host µs per round —
    # inside this row's run-to-run noise.
    def hot_traced():
        from qfedx_tpu import obs

        obs.reset()
        res, total = one_run()
        return res, total, obs.phase_rollup()

    res, total, rollup = _with_env({"QFEDX_TRACE": "1"}, hot_traced)
    out = {"target_accuracy": target, "phase_breakdown": rollup}
    out.update(_target_hits(res.accuracies, res.round_times_s, target))
    out["timing"] = "hot (2nd run; cold wall kept alongside)"
    out[f"total_s_{max_rounds}_rounds"] = round(total, 3)
    out[f"cold_total_s_{max_rounds}_rounds"] = round(cold_total, 3)
    return out


def _bench_time_to_target_20q(jax, target=0.90, max_rounds=15):
    """A REAL 20-qubit federated training run to target accuracy on the
    bench chip (VERDICT r04 missing 1 / next 2: BASELINE config 5's named
    width had been timed, never trained). Dense slab engine, bf16 state
    (set by the caller via QFEDX_DTYPE), batched routing, 2 clients,
    PCA-20 features of the synthetic binary task. Per-round host eval on
    the full binary-filtered test split (~205 of the 1024 synthetic test
    samples survive the (0,1) class filter); hit time = sum of per-round
    walls to the hit, and the hit can oscillate afterwards at this
    constant lr — final_accuracy reports where round ``max_rounds``
    actually landed."""
    from qfedx_tpu.data.datasets import load_dataset
    from qfedx_tpu.data.partition import iid_partition, pack_clients
    from qfedx_tpu.data.pipeline import preprocess
    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.models.vqc import make_vqc_classifier
    from qfedx_tpu.run.trainer import train_federated

    _, tr, te = load_dataset("mnist", synthetic_train=1024, synthetic_test=1024, seed=1)
    pre = preprocess(tr, te, classes=(0, 1), features="pca", n_features=20)
    parts = iid_partition(len(pre.train[0]), 2, seed=0)
    cx, cy, cmask = pack_clients(*pre.train, parts, pad_multiple=4)
    model = make_vqc_classifier(n_qubits=20, n_layers=3, num_classes=2)
    cfg = FedConfig(local_epochs=1, batch_size=4, learning_rate=0.1,
                    optimizer="adam")
    t0 = time.perf_counter()
    res = train_federated(
        model, cfg, cx, cy, cmask, *pre.test, num_rounds=max_rounds,
        eval_every=1, seed=0, pipeline_depth=0,  # pre-r09 timing methodology
    )
    total = time.perf_counter() - t0
    out = {"n_qubits": 20, "target_accuracy": target}
    # Sustained (≥2 consecutive evals) semantics: the r05 row counted a
    # single round-9 eval spike as "reached" while final_accuracy sat at
    # 0.82 — first_touch still records that spike, but it no longer
    # counts as success. Single (cold) run: a hot repeat would double the
    # longest bench section and this row carries no regression flag.
    out.update(_target_hits(res.accuracies, res.round_times_s, target))
    out["timing"] = "cold (single run; compile in first chunks)"
    out["final_accuracy"] = round(float(res.accuracies[-1]), 4)
    out["round_s"] = round(
        float(np.median(np.asarray(res.round_times_s[1:]))), 3
    ) if len(res.round_times_s) > 1 else None
    out[f"total_s_{max_rounds}_rounds"] = round(total, 3)
    return out


# Rounds before this one timed per-rep blocks without chained dispatches or
# fetch anchoring (docs/PERF.md §6) — their numbers over-count dispatch
# overhead and are NOT comparable to r04+ rows. _load_prev_bench skips
# them rather than silently producing apples-to-oranges ratios (the r05
# run compared against BENCH_r03 exactly this way — ADVICE r05).
_FIRST_COMPARABLE_ROUND = 4


def _write_json_atomic(path: str, text: str) -> None:
    """Sidecar write discipline (r21): tmp + rename with a trailing
    newline — a reader (or this process, killed mid-write) can never
    observe a torn JSON document. The printed compact line gets the
    same whole-line guarantee via one flushed stdout write; the
    tail-recovery path in _load_prev_bench stays for the pre-r21
    snapshots that were truncated before this discipline existed."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text if text.endswith("\n") else text + "\n")
    os.replace(tmp, path)


def _bench_round_num(path: str) -> int | None:
    """Numeric round of a BENCH_r*.json path (lexicographic sort breaks at
    r100+: 'r100' < 'r99')."""
    import re

    m = re.search(r"BENCH_r(\d+)\.json$", os.path.basename(path))
    return int(m.group(1)) if m else None


def _load_prev_bench():
    """Newest committed BENCH_r*.json (by NUMERIC round) with a usable
    parsed payload (r04's parsed field is null — its tail was truncated
    mid-object — so walk backwards until a round parses). Pre-r04 rounds
    are skipped outright (different timing methodology); the skip list is
    returned so vs_prev can record what was excluded."""
    import glob

    paths = glob.glob(
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_r*.json")
    )
    numbered = sorted(
        ((n, p) for p in paths if (n := _bench_round_num(p)) is not None),
        reverse=True,
    )
    skipped = [
        os.path.basename(p)
        for n, p in numbered
        if n < _FIRST_COMPARABLE_ROUND
    ]
    for n, path in numbered:
        if n < _FIRST_COMPARABLE_ROUND:
            continue
        try:
            with open(path) as f:
                obj = json.load(f)
        except Exception:  # noqa: BLE001
            continue
        parsed = obj.get("parsed", obj)
        if isinstance(parsed, dict) and "value" in parsed:
            return os.path.basename(path), parsed, skipped
        # Unparsed tail: recover the JSON line if the full object is there.
        tail = obj.get("tail", "")
        start = tail.find('{"metric"')
        if start >= 0:
            try:
                parsed = json.loads(tail[start:].strip())
                if "value" in parsed:
                    return os.path.basename(path), parsed, skipped
            except Exception:  # noqa: BLE001
                pass
    return None, None, skipped


def main():
    (jax, model, cfg, mesh, n_dev, num_clients, data, fns) = _build()
    make_fed_round, shard_client_data, make_local_update = fns

    spmd_s = _time_spmd(
        jax, model, cfg, mesh, num_clients, data, make_fed_round, shard_client_data
    )
    seq_s = _time_sequential(jax, model, cfg, num_clients, data, make_local_update)
    # Scan depth measured on v5e: 10 → 331/s, 20 → 395/s, 40 → 435/s per
    # chip (diminishing past that); training is bit-identical at any K.
    scan_k = 40
    try:
        scan_s, scan_blocks = _time_spmd_scanned(
            jax, model, cfg, mesh, num_clients, data, shard_client_data,
            rounds_per_call=scan_k,
        )
    except Exception:  # noqa: BLE001 — fall back to the per-dispatch number
        scan_s, scan_blocks, scan_k = spmd_s, [spmd_s], 1

    def safe(fn, *a, **k):
        try:
            return fn(jax, *a, **k)
        except Exception as e:  # noqa: BLE001
            return {"error": f"{type(e).__name__}: {e}"}

    compute = safe(_bench_compute_bound)
    # bf16 state path (QFEDX_DTYPE=bf16): halves state bytes. Measured
    # effect is width-dependent (docs/PERF.md §3): ~parity at n=16 (the
    # slab engine is fusion/bubble-bound there), 1.3–2× at n=18-20 where
    # gate passes genuinely stream multi-MB states. Convergence parity is
    # pinned by tests/test_bf16.py.
    compute_bf16 = safe(
        lambda j: _with_env({"QFEDX_DTYPE": "bf16"}, _bench_compute_bound, j)
    )
    # The 18–20-qubit dense frontier (reference ROADMAP.md:86), measured on
    # the real chip: 18q batch 16, 20q batch 8 — both WITHOUT remat. The
    # r04 per-layer remat at 20q was the whole performance cliff (XLA fused
    # the recomputed forward into every angle-cotangent reduction: 311 ms →
    # 64 ms f32 without it; docs/PERF.md §7).
    dense18 = safe(lambda j: _bench_compute_bound(j, 18, 3, 16, 3, 4, False))
    dense18_bf16 = safe(
        lambda j: _with_env(
            {"QFEDX_DTYPE": "bf16"},
            _bench_compute_bound, j, 18, 3, 16, 3, 4, False,
        )
    )
    # r14 floor lever (docs/PERF.md §15): the SAME dense18 bf16 step at
    # scan depth 16 instead of 4 — four more steps amortize each
    # dispatch's share of the §11 dtype-invariant floor; the per-step
    # delta reads the dispatch-gap share directly off the chip.
    dense18_bf16_scan16 = safe(
        lambda j: _with_env(
            {"QFEDX_DTYPE": "bf16"},
            _bench_compute_bound, j, 18, 3, 16, 3, 16, False,
        )
    )
    dense20 = safe(lambda j: _bench_compute_bound(j, 20, 3, 8, 3, 4, False))
    dense20_bf16 = safe(
        lambda j: _with_env(
            {"QFEDX_DTYPE": "bf16"},
            _bench_compute_bound, j, 20, 3, 8, 3, 4, False,
        )
    )
    for now, base in (
        (compute_bf16, compute),
        (dense18_bf16, dense18),
        (dense20_bf16, dense20),
    ):
        if "fwd_grad_s" in now and "fwd_grad_s" in base:
            now["speedup_vs_f32"] = round(
                base["fwd_grad_s"] / now["fwd_grad_s"], 3
            )
            now["verdict"] = (
                "better" if now["speedup_vs_f32"] >= 1.1 else
                "worse" if now["speedup_vs_f32"] <= 0.9 else "parity"
            )
    fed16 = safe(_bench_fed16q)
    fed16_bf16 = safe(
        lambda j: _with_env({"QFEDX_DTYPE": "bf16"}, _bench_fed16q, j)
    )
    # The client-VMAP form of the same program (QFEDX_FOLD_CLIENTS=0)
    # keeps the folded lever's effect measured head-to-head; bf16 because
    # that is the production fed dtype and where PERF.md §8 located the
    # residual ~1.5× composition tax.
    fed16_bf16_unfolded = safe(
        lambda j: _with_env(
            {"QFEDX_DTYPE": "bf16", "QFEDX_FOLD_CLIENTS": "0"},
            _bench_fed16q, j,
        )
    )
    if (
        fed16_bf16.get("fold_clients") is True
        and "client_rounds_per_s" in fed16_bf16_unfolded
    ):
        fed16_bf16["fold_speedup_vs_vmap"] = round(
            fed16_bf16["client_rounds_per_s"]
            / fed16_bf16_unfolded["client_rounds_per_s"],
            3,
        )
    # The fusion lever on the same composed row (QFEDX_FUSE=off pins the
    # per-gate engine): keeps the r07 fusion pass's value measured
    # head-to-head, like the fold lever above.
    fed16_bf16_fuse_off = safe(
        lambda j: _with_env(
            {"QFEDX_DTYPE": "bf16", "QFEDX_FUSE": "off"}, _bench_fed16q, j
        )
    )
    if (
        fed16_bf16.get("fuse") is True
        and "client_rounds_per_s" in fed16_bf16_fuse_off
    ):
        fed16_bf16["fuse_speedup_vs_unfused"] = round(
            fed16_bf16["client_rounds_per_s"]
            / fed16_bf16_fuse_off["client_rounds_per_s"],
            3,
        )
    # The r17 scan lever on the same composed row (QFEDX_SCAN_LAYERS=off
    # pins the r07 per-layer fused program bit-for-bit): keeps the
    # scan-over-fused-layers op-count collapse measured head-to-head in
    # client-rounds/s, like the fuse/fold levers above.
    fed16_bf16_scan_off = safe(
        lambda j: _with_env(
            {"QFEDX_DTYPE": "bf16", "QFEDX_SCAN_LAYERS": "off"},
            _bench_fed16q, j,
        )
    )
    if (
        fed16_bf16.get("scan_layers") is True
        and "client_rounds_per_s" in fed16_bf16_scan_off
    ):
        fed16_bf16["scan_speedup_vs_off"] = round(
            fed16_bf16["client_rounds_per_s"]
            / fed16_bf16_scan_off["client_rounds_per_s"],
            3,
        )
    # The r09 pipeline lever, END-TO-END through the trainer (the rows
    # above time bare dispatches and cannot see the host work the
    # pipeline overlaps): default loop vs QFEDX_PIPELINE=0 head-to-head,
    # bf16 like the other fed levers. Training is bit-identical, so the
    # delta is pure dispatch/host overlap.
    fed16_bf16_pipeline = safe(
        lambda j: _with_env(
            {"QFEDX_DTYPE": "bf16", "QFEDX_PIPELINE": "1"},
            _bench_fed16q_pipeline, j,
        )
    )
    fed16_bf16_pipeline_off = safe(
        lambda j: _with_env(
            {"QFEDX_DTYPE": "bf16", "QFEDX_PIPELINE": "0"},
            _bench_fed16q_pipeline, j,
        )
    )
    if (
        "client_rounds_per_s" in fed16_bf16_pipeline
        and "client_rounds_per_s" in fed16_bf16_pipeline_off
    ):
        fed16_bf16_pipeline["pipeline_speedup_vs_off"] = round(
            fed16_bf16_pipeline["client_rounds_per_s"]
            / fed16_bf16_pipeline_off["client_rounds_per_s"],
            3,
        )
    # The r11 guards lever: same composed row with the fault-tolerance
    # machinery compiled OUT (QFEDX_GUARDS=off builds the pre-r11
    # program) — the overhead of quarantine isfinite/where ops plus the
    # casualty counters, measured head-to-head like the fold/fuse/
    # pipeline levers above.
    fed16_bf16_guards_off = safe(
        lambda j: _with_env(
            {"QFEDX_DTYPE": "bf16", "QFEDX_GUARDS": "off"},
            _bench_fed16q, j,
        )
    )
    if (
        "client_rounds_per_s" in fed16_bf16
        and "client_rounds_per_s" in fed16_bf16_guards_off
    ):
        fed16_bf16["guards_overhead_vs_off"] = round(
            fed16_bf16_guards_off["client_rounds_per_s"]
            / fed16_bf16["client_rounds_per_s"],
            3,
        )
    # The r15 tracing lever: the SAME trainer-path row with QFEDX_TRACE
    # on — per-round spans, compile attribution, per-row phases merged
    # into the JSONL, per-span histograms. PERF.md §13 pins only the
    # ~3.5 µs disabled-span microcost; this measures what enabling the
    # whole exporter pipeline costs END-TO-END, head-to-head against
    # fed16q_bf16_pipeline (identical loop, trace off), vs_prev-tracked.
    def _fed16q_traced(j):
        from qfedx_tpu import obs as _obs

        _obs.reset()  # isolate this row's spans from earlier sections
        try:
            out = _with_env(
                {"QFEDX_DTYPE": "bf16", "QFEDX_PIPELINE": "1",
                 "QFEDX_TRACE": "1"},
                _bench_fed16q_pipeline, j,
            )
            # Compact per-phase walls of the traced row (cold + hot run
            # combined — the cold run's compile lands in dispatch's
            # compile_s, which is the attribution story being priced).
            out["phase_totals"] = _obs.phase_totals()
        finally:
            _obs.reset()  # later sections must not inherit these spans
        return out

    fed16_bf16_trace_on = safe(_fed16q_traced)
    if (
        "client_rounds_per_s" in fed16_bf16_trace_on
        and "client_rounds_per_s" in fed16_bf16_pipeline
    ):
        fed16_bf16_trace_on["trace_overhead_vs_off"] = round(
            fed16_bf16_pipeline["client_rounds_per_s"]
            / fed16_bf16_trace_on["client_rounds_per_s"],
            3,
        )

    # The r20 watchdog lever: the SAME trainer-path row with
    # QFEDX_WATCH=1 and trace OFF — what always-on detection costs
    # END-TO-END (bounded instruments recording + one rule sweep per
    # tick), head-to-head against fed16q_bf16_pipeline. The closing
    # evaluation flushes the last window; alerts_fired is the quiet-run
    # canary (expected 0 — a healthy trainer fires nothing).
    def _fed16q_watched(j):
        from qfedx_tpu.obs import watch as _watch

        _watch.reset()
        try:

            def run_watched():
                _watch.evaluate_once()  # baseline tick for delta rules
                out = _bench_fed16q_pipeline(j)
                _watch.evaluate_once()
                return out

            out = _with_env(
                {"QFEDX_DTYPE": "bf16", "QFEDX_PIPELINE": "1",
                 "QFEDX_WATCH": "1"},
                run_watched,
            )
            totals = _watch.fired_totals()
            out["alerts_fired"] = int(sum(totals.values()))
            out["alerts_by_rule"] = totals or None
        finally:
            _watch.reset()
        return out

    fed16_bf16_watch_on = safe(_fed16q_watched)
    if (
        "client_rounds_per_s" in fed16_bf16_watch_on
        and "client_rounds_per_s" in fed16_bf16_pipeline
    ):
        fed16_bf16_watch_on["watch_overhead_vs_off"] = round(
            fed16_bf16_pipeline["client_rounds_per_s"]
            / fed16_bf16_watch_on["client_rounds_per_s"],
            3,
        )
    fed256 = safe(_bench_fed256)
    # r10: cohort size unbound from HBM — 4096 clients/round through
    # 256-client streamed waves on one chip (hierarchical partial/apply
    # + background H2D staging; the resident fed256 row stays as the
    # one-wave anchor).
    fed_streamed = safe(_bench_fed_streamed)
    # r11: accuracy under injected client churn (0/5/20% casualties).
    fault_tolerance = safe(_bench_fault_tolerance)
    # r12: accuracy under ADVERSARIAL clients — attack-fraction curves
    # with defense off (mean) vs clip_mean/trimmed_mean/median.
    byzantine = safe(_bench_byzantine)
    # r13: accuracy + utilized throughput under injected STRAGGLERS —
    # 0/10/30% one-round-late waves, drop vs buffered (QFEDX_STALE).
    straggler = safe(_bench_straggler)
    # r14: the serving rows — offered-load sweep through the real
    # engine+batcher, p50/p95 + throughput at the stated SLO, with the
    # zero-compiles-in-loop contract measured by the compile listener.
    serve = safe(_bench_serve)
    fusion_hlo = safe(_bench_fusion_hlo)
    # r16: the MEASURED floor — profiler capture of the step program
    # parsed into executed ops, inter-op gap quantiles, busy fraction
    # (the runtime complement of the static fusion_hlo census above;
    # docs/PERF.md §16). The dense18q_bf16 bandwidth-model ratio rides
    # along so the floor evidence reads as one unit: ops x gap next to
    # achieved-vs-streaming-bound.
    floor_attr = safe(_bench_floor_attribution)
    if (
        "error" not in floor_attr
        and isinstance(dense18_bf16, dict)
        and dense18_bf16.get("vs_pergate_bound") is not None
    ):
        floor_attr["dense18q_bf16_vs_pergate_bound"] = dense18_bf16[
            "vs_pergate_bound"
        ]
    ttt = safe(_bench_time_to_target)
    ttt20 = safe(
        lambda j: _with_env(
            {"QFEDX_DTYPE": "bf16"}, _bench_time_to_target_20q, j
        )
    )

    # Headline: the trainer's optimized path (K rounds scanned per
    # dispatch — CLI --rounds-per-call, bit-identical training). The
    # per-dispatch number is kept alongside for the latency-bound view;
    # it is tunnel-RTT-bound (16–150 ms day to day) and therefore NOT
    # regression-flagged (ADVICE r04 item 4).
    value = num_clients / scan_s / n_dev
    per_dispatch = num_clients / spmd_s / n_dev
    baseline_value = num_clients / seq_s / n_dev
    value_blocks = [round(num_clients / s / n_dev, 1) for s in scan_blocks]

    # Round-over-round regression tracking: compare against the newest
    # PARSEABLE committed BENCH_r*.json so drift is visible at bench time.
    vs_prev = {}
    try:
        prev_name, prev, skipped = _load_prev_bench()
        if skipped:
            vs_prev["skipped_files"] = skipped
            vs_prev["skipped_reason"] = (
                "pre-r04 timing methodology (per-rep blocks, no "
                "chain/fetch anchoring) — not comparable"
            )
        if prev is not None:
            vs_prev["prev_file"] = prev_name

            def delta(name, now_v, prev_v, higher_is_better):
                if now_v is None or prev_v in (None, 0):
                    return
                r = now_v / prev_v
                vs_prev[name] = {
                    "prev": round(prev_v, 5), "now": round(now_v, 5),
                    "ratio": round(r, 3),
                    "regressed": bool(
                        r < 0.95 if higher_is_better else r > 1.05
                    ),
                }

            def prev_engine_s(section, compact_key):
                """Engine fwd+grad seconds from either prior format:
                the pre-r05 full sections ({"compute_bound": {...}}) or
                the r05+ compact printed line ({"engine_fwd_grad_ms":
                {"n16": ...}}) — the driver captures the compact line,
                so r06's prev will only have the latter."""
                full = (prev.get(section) or {}).get("fwd_grad_s")
                if full is not None:
                    return full
                ms = (prev.get("engine_fwd_grad_ms") or {}).get(compact_key)
                return None if ms is None else ms / 1e3

            delta("headline_rounds_per_s", value, prev.get("value"), True)
            delta(
                "fed_streamed_client_rounds_per_s",
                fed_streamed.get("client_rounds_per_s"),
                (prev.get("fed_streamed") or {}).get("client_rounds_per_s"),
                True,
            )
            delta(
                "fault_tolerance_acc_20pct",
                fault_tolerance.get("acc_rate_20pct"),
                (prev.get("fault_tolerance") or {}).get("acc_rate_20pct"),
                True,
            )
            delta(
                "byzantine_defended_acc_20pct",
                byzantine.get("best_defended_acc_20pct"),
                (prev.get("byzantine") or {}).get(
                    "best_defended_acc_20pct"
                ),
                True,
            )
            delta(
                "straggler_buffered_acc_30pct",
                straggler.get("acc_buffer_30pct"),
                (prev.get("straggler") or {}).get("acc_buffer_30pct"),
                True,
            )
            # The r15 enabled-tracing overhead, end-to-end: prev rows
            # predate the lever, so the delta appears once both exist.
            delta(
                "fed16q_trace_on_client_rounds_per_s",
                fed16_bf16_trace_on.get("client_rounds_per_s"),
                (prev.get("fed16q_bf16_trace_on") or {}).get(
                    "client_rounds_per_s"
                ),
                True,
            )
            # The r20 watchdog lever, same first-appearance rule.
            delta(
                "fed16q_watch_on_client_rounds_per_s",
                fed16_bf16_watch_on.get("client_rounds_per_s"),
                (prev.get("fed16q_bf16_watch_on") or {}).get(
                    "client_rounds_per_s"
                ),
                True,
            )
            # alerts_fired canaries: expected 0 on BOTH sides, so the
            # ratio-based delta() (which skips prev == 0) cannot track
            # them — any increase regresses outright.
            for cname, now_a, prev_a in (
                ("serve_alerts_fired", serve.get("alerts_fired"),
                 (prev.get("serve") or {}).get("alerts_fired")),
                ("fed16q_watch_on_alerts_fired",
                 fed16_bf16_watch_on.get("alerts_fired"),
                 (prev.get("fed16q_bf16_watch_on") or {}).get(
                     "alerts_fired")),
            ):
                if now_a is not None and prev_a is not None:
                    vs_prev[cname] = {
                        "prev": prev_a, "now": now_a,
                        "regressed": bool(now_a > prev_a),
                    }
            # NOTE: r15 changed the serve quantile definition to
            # histogram lower-edge (see _bench_serve) — the first
            # vs_prev across that boundary carries a <= one-bucket
            # (~10%) definitional shift in p50/p95.
            delta(
                "serve_p50_ms",
                serve.get("serve_p50_ms"),
                (prev.get("serve") or {}).get("serve_p50_ms"),
                False,
            )
            delta(
                "serve_p95_ms",
                serve.get("serve_p95_ms"),
                (prev.get("serve") or {}).get("serve_p95_ms"),
                False,
            )
            delta(
                "serve_throughput_at_slo",
                serve.get("throughput_at_slo"),
                (prev.get("serve") or {}).get("throughput_at_slo"),
                True,
            )
            # r21 tuned lever: the offline tuner's winning cell vs its
            # own previous round — a tuned number that stops beating the
            # default is the auto-tuner regressing, not serving.
            delta(
                "serve_tuned_throughput_at_slo",
                (serve.get("tuned") or {}).get("throughput_at_slo"),
                ((prev.get("serve") or {}).get("tuned") or {}).get(
                    "throughput_at_slo"
                ),
                True,
            )
            # r16 floor attribution: a growing measured gap or op count
            # is exactly the regression the §15 model prices. Only
            # compared when the profiled width matches (the row is
            # backend-sized; a CPU-vs-chip prev is not a regression).
            prev_floor = prev.get("floor_attribution") or {}
            if prev_floor.get("n") == floor_attr.get("n"):
                delta(
                    "floor_gap_us_per_op",
                    floor_attr.get("gap_us_per_op"),
                    prev_floor.get("gap_us_per_op"),
                    False,
                )
                delta(
                    "floor_ops_per_step",
                    floor_attr.get("ops_per_step"),
                    prev_floor.get("ops_per_step"),
                    False,
                )
                # r19 pallas arm: only comparable kernel-vs-kernel —
                # an interpreted (off-chip) census against a chip one
                # would flag the interpreter, not a regression.
                now_p = floor_attr.get("pallas") or {}
                prev_p = prev_floor.get("pallas") or {}
                if now_p.get("interpreted") == prev_p.get("interpreted"):
                    delta(
                        "floor_pallas_ops_per_step",
                        now_p.get("ops_per_step"),
                        prev_p.get("ops_per_step"),
                        False,
                    )
            delta("compute_bound_fwd_grad_s", compute.get("fwd_grad_s"),
                  prev_engine_s("compute_bound", "n16"), False)
            delta("dense18q_fwd_grad_s", dense18.get("fwd_grad_s"),
                  prev_engine_s("dense18q", "n18"), False)
            delta("dense20q_fwd_grad_s", dense20.get("fwd_grad_s"),
                  prev_engine_s("dense20q", "n20"), False)
            # Per-phase drift of the traced time_to_target run: the prev
            # printed line carries {phase: total_s}, so a regression in
            # the headline localizes to a phase right here in vs_prev
            # instead of needing a post-hoc forensic pass.
            prev_pb = prev.get("phase_breakdown")
            now_pb = {
                k: v.get("total_s")
                for k, v in ((ttt or {}).get("phase_breakdown") or {}).items()
                if isinstance(v, dict)
            }
            if isinstance(prev_pb, dict) and now_pb:
                vs_prev["phase_breakdown"] = {
                    ph: {
                        "prev": prev_pb[ph],
                        "now": now_pb[ph],
                        "ratio": round(now_pb[ph] / prev_pb[ph], 3),
                    }
                    for ph in sorted(set(prev_pb) & set(now_pb))
                    if isinstance(prev_pb[ph], (int, float)) and prev_pb[ph]
                }
            prev_ttt = prev.get("time_to_target") or {}
            if prev_ttt.get("timing", "").startswith("hot"):
                delta("time_to_target_s", (ttt or {}).get("seconds"),
                      prev_ttt.get("seconds"), False)
            else:
                # Pre-r06 rows timed a cold first run (compile-cache
                # state inside the window — the r05 "regression",
                # docs/PERF.md §11); a hot-vs-cold ratio is methodology
                # noise, not drift. Record, don't flag.
                vs_prev["time_to_target_s"] = {
                    "prev": prev_ttt.get("seconds"),
                    "now": (ttt or {}).get("seconds"),
                    "note": "prev is cold/first-touch (pre-r06 "
                            "methodology) — not compared",
                    "regressed": False,
                }
    except Exception as e:  # noqa: BLE001 — tracking must never kill bench
        vs_prev["error"] = f"{type(e).__name__}: {e}"

    # Static-analysis artifact (r18, docs/ANALYSIS.md): the full
    # `qfedx lint --json` report lands bench-adjacent (bench_lint.json)
    # and the counts ride the details sidecar, so every bench run
    # records the contract state it measured under; the vs-baseline
    # delta prints as ONE line below.
    try:
        from qfedx_tpu.analysis import render_json, run_lint

        _lint = run_lint()
        lint_row = {
            "ok": _lint.ok,
            "counts_by_rule": _lint.counts_by_rule(),
            "new": len(_lint.findings),
            "baselined": len(_lint.baselined),
            "suppressed": _lint.suppressed,
            "stale_baseline": len(_lint.stale_baseline),
            "delta": _lint.delta_line(),
        }
        _write_json_atomic(os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "bench_lint.json"
        ), render_json(_lint))
        print(lint_row["delta"])
    except Exception as e:  # noqa: BLE001 — lint must never kill bench
        lint_row = {"error": f"{type(e).__name__}: {e}"}

    details = {
        "metric": "vqc_client_rounds_per_sec_per_chip",
        "value": round(value, 3),
        "unit": "client-rounds/s/chip",
        # Provenance (r20): `qfedx bench history` must never trend a
        # CPU-container number against an on-chip one — the explicit
        # field beats the round-watermark inference.
        "backend": jax.default_backend(),
        "value_blocks": value_blocks,
        "timing_methodology": "chained+fetch-anchored; median over >=3 blocks (r04+)",
        "vs_baseline": round(value / baseline_value, 3),
        "vs_baseline_note": "scanned(K) vs sequential per-round loop",
        "per_dispatch_value": round(per_dispatch, 3),
        "per_dispatch_vs_baseline": round(per_dispatch / baseline_value, 3),
        "per_dispatch_note": "tunnel-RTT-bound; varies with tunnel weather, "
        "not engine speed; excluded from regression flags",
        "rounds_per_call": scan_k,
        "compute_bound": compute,
        "compute_bound_bf16": compute_bf16,
        "dense18q": dense18,
        "dense18q_bf16": dense18_bf16,
        "dense18q_bf16_scan16": dense18_bf16_scan16,
        "dense20q": dense20,
        "dense20q_bf16": dense20_bf16,
        "fed16q": fed16,
        "fed16q_bf16": fed16_bf16,
        "fed16q_bf16_unfolded": fed16_bf16_unfolded,
        "fed16q_bf16_fuse_off": fed16_bf16_fuse_off,
        "fed16q_bf16_scan_off": fed16_bf16_scan_off,
        "fed16q_bf16_pipeline": fed16_bf16_pipeline,
        "fed16q_bf16_pipeline_off": fed16_bf16_pipeline_off,
        "fed16q_bf16_guards_off": fed16_bf16_guards_off,
        "fed16q_bf16_trace_on": fed16_bf16_trace_on,
        "fed16q_bf16_watch_on": fed16_bf16_watch_on,
        "fed256": fed256,
        "fed_streamed": fed_streamed,
        "fault_tolerance": fault_tolerance,
        "byzantine": byzantine,
        "straggler": straggler,
        "serve": serve,
        "fusion_hlo": fusion_hlo,
        "floor_attribution": floor_attr,
        "time_to_target": ttt,
        "time_to_target_20q": ttt20,
        "lint": lint_row,
        "vs_prev": vs_prev,
    }
    sidecar = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "bench_details.json"
    )
    try:
        _write_json_atomic(sidecar, json.dumps(details, indent=1))
    except Exception:  # noqa: BLE001 — the printed line is the contract
        sidecar = None

    def ms(row):
        t = row.get("fwd_grad_s")
        return None if t is None else round(t * 1e3, 1)

    def ttt_brief(row):
        return {
            k: row.get(k) for k in ("seconds", "rounds", "reached")
        } if "error" not in row else {"error": row["error"][:80]}

    regressed = [
        k for k, v in vs_prev.items()
        if isinstance(v, dict) and v.get("regressed")
    ]
    line = json.dumps(
            {
                "metric": "vqc_client_rounds_per_sec_per_chip",
                "value": round(value, 3),
                "unit": "client-rounds/s/chip",
                "backend": jax.default_backend(),
                "vs_baseline": round(value / baseline_value, 3),
                "value_blocks": value_blocks,
                "rounds_per_call": scan_k,
                "per_dispatch_value": round(per_dispatch, 3),
                "engine_fwd_grad_ms": {
                    "n16": ms(compute), "n16_bf16": ms(compute_bf16),
                    "n18": ms(dense18), "n18_bf16": ms(dense18_bf16),
                    # r14 floor lever: scan depth 16 vs the n18_bf16
                    # row's 4 — the per-step delta is the dispatch-gap
                    # share of the §11 floor (docs/PERF.md §15).
                    "n18_bf16_scan16": ms(dense18_bf16_scan16),
                    "n20": ms(dense20), "n20_bf16": ms(dense20_bf16),
                },
                "fed16q_client_rounds_per_s": {
                    "f32": fed16.get("client_rounds_per_s"),
                    "bf16": fed16_bf16.get("client_rounds_per_s"),
                    "bf16_unfolded": fed16_bf16_unfolded.get(
                        "client_rounds_per_s"
                    ),
                    "bf16_fuse_off": fed16_bf16_fuse_off.get(
                        "client_rounds_per_s"
                    ),
                    # r17 lever: the same composed row with the scan
                    # route pinned off (the r07 per-layer program).
                    "bf16_scan_off": fed16_bf16_scan_off.get(
                        "client_rounds_per_s"
                    ),
                    # Trainer-path pair (r09): NOT comparable to the raw
                    # dispatch rows above — includes in-scan eval + the
                    # per-round host work the pipeline overlaps.
                    "bf16_trainer_pipeline": fed16_bf16_pipeline.get(
                        "client_rounds_per_s"
                    ),
                    "bf16_trainer_pipeline_off": fed16_bf16_pipeline_off.get(
                        "client_rounds_per_s"
                    ),
                    "bf16_guards_off": fed16_bf16_guards_off.get(
                        "client_rounds_per_s"
                    ),
                    # r15: the same trainer path with QFEDX_TRACE=1 —
                    # the measured end-to-end cost of enabled tracing
                    # (compare bf16_trainer_pipeline; ratio in
                    # bench_details.json trace_overhead_vs_off).
                    "bf16_trainer_trace_on": fed16_bf16_trace_on.get(
                        "client_rounds_per_s"
                    ),
                    # r20: the same trainer path with QFEDX_WATCH=1 —
                    # the measured end-to-end cost of always-on
                    # detection (compare bf16_trainer_pipeline; ratio
                    # in bench_details.json watch_overhead_vs_off).
                    "bf16_trainer_watch_on": fed16_bf16_watch_on.get(
                        "client_rounds_per_s"
                    ),
                },
                # r20 canaries: watchdog firings during the watched
                # rows — expected 0; any breach is a regression signal
                # (vs_prev tracks both).
                "alerts_fired": {
                    "serve": serve.get("alerts_fired"),
                    "fed16q_watch_on": fed16_bf16_watch_on.get(
                        "alerts_fired"
                    ),
                },
                "fed256": {
                    "client_rounds_per_s": fed256.get("client_rounds_per_s"),
                    "reached": fed256.get("reached"),
                }
                if "error" not in fed256
                else {"error": fed256["error"][:80]},
                "fed_streamed": {
                    k: fed_streamed.get(k)
                    for k in (
                        "cohort", "wave_size", "client_rounds_per_s",
                        "stream_speedup_vs_sync",
                    )
                }
                if "error" not in fed_streamed
                else {"error": fed_streamed["error"][:80]},
                # r11: the dropout_rate → accuracy degradation curve
                # (0/5/20% casualties; vs_prev tracks the 20% point).
                "fault_tolerance": {
                    k: fault_tolerance.get(k)
                    for k in (
                        "acc_rate_0pct", "acc_rate_5pct", "acc_rate_20pct",
                    )
                }
                if "error" not in fault_tolerance
                else {"error": fault_tolerance["error"][:80]},
                # r12: the Byzantine headline — clean vs undefended vs
                # best-defended accuracy at 20% scale:100 attackers.
                "byzantine": {
                    k: byzantine.get(k)
                    for k in (
                        "acc_clean", "acc_mean_20pct",
                        "best_defended_acc_20pct",
                        "defended_within_2pts_of_clean_at_20pct",
                    )
                }
                if "error" not in byzantine
                else {"error": byzantine["error"][:80]},
                # r13: the straggler headline — at 30% one-round-late
                # waves, buffered aggregation recovers what drop loses.
                "straggler": {
                    k: straggler.get(k)
                    for k in (
                        "acc_clean", "acc_drop_30pct", "acc_buffer_30pct",
                        "buffered_within_noise_of_clean_30pct",
                        "utilized_cr_s_drop_30pct",
                        "utilized_cr_s_buffer_30pct",
                        "utilization_recovered_30pct",
                    )
                }
                if "error" not in straggler
                else {"error": straggler["error"][:80]},
                # r14: the serving headline — p50/p95 at the best rate
                # meeting the stated SLO, completed throughput there,
                # and the measured zero-compiles-in-loop contract.
                "serve": {
                    k: serve.get(k)
                    for k in (
                        "serve_p50_ms", "serve_p95_ms",
                        "throughput_at_slo", "slo_ms", "capacity_rps",
                        "zero_compiles_in_loop", "tuned",
                    )
                }
                if "error" not in serve
                else {"error": serve["error"][:80]},
                "fusion_hlo_n18": fusion_hlo.get("n18")
                if isinstance(fusion_hlo, dict)
                else None,
                # r16: the measured floor — executed ops vs the static
                # census, measured inter-op gap, device-busy fraction
                # (docs/PERF.md §16; full row in bench_details.json).
                "floor_attribution": {
                    k: floor_attr.get(k)
                    for k in (
                        "n", "route", "route_resolved", "ops_per_step",
                        "static_state_ops", "measured_vs_static",
                        "gap_us_per_op", "device_busy_fraction",
                        "ops_per_step_vs_fused", "static_vs_fused",
                        "depth6", "pallas",
                    )
                }
                if "error" not in floor_attr
                else {"error": floor_attr["error"][:80]},
                "time_to_target": ttt_brief(ttt),
                "time_to_target_20q": ttt_brief(ttt20),
                # Compact {phase: total_s} of the traced hot
                # time_to_target run — the artifact next round's vs_prev
                # phase diff reads (full rollup in bench_details.json).
                "phase_breakdown": {
                    k: v.get("total_s")
                    for k, v in (
                        (ttt or {}).get("phase_breakdown") or {}
                    ).items()
                    if isinstance(v, dict)
                }
                or None,
                "regressed": regressed,
                "details": "bench_details.json" if sidecar else None,
            }
    )
    # Whole-line stdout contract (r21): ONE flushed write — the driver's
    # tail capture can never interleave with or truncate the compact row
    # (the committed r04 snapshot is exactly that failure mode).
    sys.stdout.write(line + "\n")
    sys.stdout.flush()


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # never leave the driver without a JSON line
        sys.stdout.write(
            json.dumps(
                {
                    "metric": "vqc_client_rounds_per_sec_per_chip",
                    "value": 0.0,
                    "unit": "client-rounds/s/chip",
                    "vs_baseline": 0.0,
                    "error": f"{type(e).__name__}: {e}",
                }
            ) + "\n"
        )
        sys.stdout.flush()
        sys.exit(1)
