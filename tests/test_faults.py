"""Fault-injection harness (r11): plan determinism, retry policy, the
QFEDX_FAULTS pin, DP-accountant dropout invariance, and the tier-1
chaos smoke test — a short streamed run with a mixed fault plan (one
NaN client, one dropped client, one transient registry failure) must
complete, converge, and report EXACT casualty counts in metrics.jsonl.

Shapes are tiny (3 qubits, 1 layer, 8–16 clients): this file sits
mid-alphabet in the tier-1 wall-clock budget.
"""

import json

import numpy as np
import pytest

from qfedx_tpu.fed.config import DPConfig, FedConfig
from qfedx_tpu.fed.round import client_mesh
from qfedx_tpu.models.vqc import make_vqc_classifier
from qfedx_tpu.utils.faults import (
    FaultInjected,
    FaultPlan,
    active_plan,
    resolve_plan,
)
from qfedx_tpu.utils.retry import RetryExhausted, retry_with_deadline

N_Q = 3


# --- FaultPlan --------------------------------------------------------------


def test_plan_is_deterministic_and_kind_independent():
    plan = FaultPlan(seed=3, rules=[
        {"site": "client.compute", "kind": "drop", "rate": 0.3},
        {"site": "client.compute", "kind": "nan", "rate": 0.3},
    ])
    ids = np.arange(64)
    s1, s2 = plan.survivors(5, ids), plan.survivors(5, ids)
    np.testing.assert_array_equal(s1, s2)  # pure in (seed, round, ids)
    assert 0 < (s1 == 0).sum() < 64
    assert not np.array_equal(s1, plan.survivors(6, ids))  # varies by round
    assert not np.array_equal(
        s1, FaultPlan(seed=4, rules=plan_rules(plan)).survivors(5, ids)
    )
    # drop and nan draws are independent coins, not the same hash
    pois = plan.poison(5, ids)
    nan_hit = ~np.isfinite(pois)
    assert 0 < nan_hit.sum() < 64
    assert not np.array_equal(nan_hit, s1 == 0)
    counts = plan.casualty_counts(5, ids)
    assert counts["drop"] == int((s1 == 0).sum())
    assert counts["nan"] == int(nan_hit.sum())
    assert counts["inf"] == 0


def plan_rules(plan):
    return [
        {"site": "client.compute", "kind": "drop", "rate": 0.3},
        {"site": "client.compute", "kind": "nan", "rate": 0.3},
    ]


def test_plan_exact_clients_rounds_and_error_sites():
    plan = FaultPlan.from_spec({"seed": 1, "rules": [
        {"site": "client.compute", "kind": "drop", "clients": [3, 7],
         "rounds": [2]},
        {"site": "registry.fetch", "rounds": [1], "waves": [0], "times": 1},
        {"site": "checkpoint.write", "rounds": [4]},
    ]})
    ids = np.arange(8)
    np.testing.assert_array_equal(
        plan.survivors(2, ids),
        np.array([1, 1, 1, 0, 1, 1, 1, 0], np.float32),
    )
    np.testing.assert_array_equal(plan.survivors(3, ids), np.ones(8))
    # id-keyed, not position-keyed: a different cohort still drops 3, 7
    np.testing.assert_array_equal(
        plan.survivors(2, np.array([2, 3, 7])),
        np.array([1, 0, 0], np.float32),
    )
    # transient: attempt 0 fails, attempt 1 passes; other coords clean
    with pytest.raises(FaultInjected) as ei:
        plan.check("registry.fetch", 1, wave=0, attempt=0)
    assert ei.value.site == "registry.fetch" and ei.value.round_idx == 1
    plan.check("registry.fetch", 1, wave=0, attempt=1)
    plan.check("registry.fetch", 0, wave=0, attempt=0)
    plan.check("registry.fetch", 1, wave=1, attempt=0)
    # persistent: no times bound — every attempt fails
    for k in range(4):
        with pytest.raises(FaultInjected):
            plan.check("checkpoint.write", 4, attempt=k)


def test_same_site_rate_rules_fall_independent_coins():
    """Two rate rules on one error site must not fire on perfectly
    correlated coordinates (each rule's hash is salted by its position
    in the plan)."""
    plan = FaultPlan(seed=0, rules=[
        {"site": "registry.fetch", "rate": 0.5},
        {"site": "registry.fetch", "rate": 0.5},
    ])
    single = FaultPlan(seed=0, rules=[
        {"site": "registry.fetch", "rate": 0.5},
    ])
    both_fire = one_fires = 0
    for r in range(200):
        a = fires(single, r)
        b = fires(plan, r)
        one_fires += a
        both_fire += b
    # Rule 1 alone fires ~50%; with an INDEPENDENT second coin the
    # union fires ~75% — correlated rules would leave it at ~50%.
    assert 70 <= one_fires <= 130
    assert both_fire > one_fires + 20


def fires(plan, round_idx) -> bool:
    try:
        plan.check("registry.fetch", round_idx)
        return False
    except FaultInjected:
        return True


def test_byzantine_plan_kinds_params_and_determinism():
    """client.byzantine (r12): parameterized kinds parse, draws are
    pure in (seed, round, ids), multipliers compose, and the attack
    array is None exactly when every client is honest."""
    plan = FaultPlan(seed=4, rules=[
        {"site": "client.byzantine", "kind": "scale:100", "clients": [2]},
        {"site": "client.byzantine", "kind": "sign_flip", "clients": [2, 5]},
        {"site": "client.byzantine", "kind": "noise:3", "clients": [7]},
        {"site": "client.byzantine", "kind": "label_flip", "rate": 0.25},
    ])
    ids = np.arange(8)
    mult = plan.byzantine_multipliers(0, ids)
    np.testing.assert_array_equal(
        mult, [1, 1, -100, 1, 1, -1, 1, 1]  # scale × sign_flip compose
    )
    sigma = plan.byzantine_noise(0, ids)
    assert sigma[7] == 3.0 and sigma.sum() == 3.0
    flips = plan.label_flips(0, ids)
    np.testing.assert_array_equal(flips, plan.label_flips(0, ids))
    counts = plan.byzantine_counts(0, ids)
    assert counts["scale"] == 1 and counts["sign_flip"] == 2
    assert counts["noise"] == 1 and counts["label_flip"] == int(flips.sum())
    atk = plan.byzantine_attack(0, ids)
    assert atk.shape == (8, 2)
    np.testing.assert_array_equal(atk[:, 0], mult)
    assert FaultPlan(seed=4).byzantine_attack(0, ids) is None  # honest
    # kind grammar is loud
    with pytest.raises(ValueError, match="scale"):
        FaultPlan(rules=[{"site": "client.byzantine", "kind": "scale",
                          "clients": [1]}])
    with pytest.raises(ValueError, match="no parameter"):
        FaultPlan(rules=[{"site": "client.byzantine",
                          "kind": "sign_flip:2", "clients": [1]}])
    with pytest.raises(ValueError, match="base must be"):
        FaultPlan(rules=[{"site": "client.byzantine", "kind": "krum",
                          "clients": [1]}])
    with pytest.raises(ValueError, match="exactly one"):
        FaultPlan(rules=[{"site": "client.byzantine", "kind": "noise"}])


def test_plan_spec_validation():
    with pytest.raises(ValueError, match="site"):
        FaultPlan(rules=[{"site": "nonsense"}])
    with pytest.raises(ValueError, match="kind"):
        FaultPlan(rules=[{"site": "client.compute", "kind": "error",
                          "rate": 0.1}])
    with pytest.raises(ValueError, match="exactly one"):
        FaultPlan(rules=[{"site": "client.compute", "kind": "drop"}])
    with pytest.raises(ValueError, match="rate"):
        FaultPlan(rules=[{"site": "client.compute", "kind": "drop",
                          "rate": 1.5}])
    with pytest.raises(ValueError, match="unknown fault-rule keys"):
        FaultPlan(rules=[{"site": "registry.fetch", "typo": 1}])
    with pytest.raises(ValueError, match="unknown fault-plan keys"):
        FaultPlan.from_spec({"seeds": 1})
    with pytest.raises(ValueError, match="unknown error site"):
        FaultPlan().check("client.compute", 0)


def test_faults_pin_grammar(monkeypatch, tmp_path):
    monkeypatch.delenv("QFEDX_FAULTS", raising=False)
    assert active_plan() is None
    monkeypatch.setenv("QFEDX_FAULTS", "off")
    assert active_plan() is None
    inline = json.dumps({"seed": 2, "rules": [
        {"site": "client.compute", "kind": "drop", "clients": [1]},
    ]})
    monkeypatch.setenv("QFEDX_FAULTS", inline)
    plan = active_plan()
    assert plan is not None and plan.seed == 2
    path = tmp_path / "plan.json"
    path.write_text(inline)
    monkeypatch.setenv("QFEDX_FAULTS", str(path))
    assert active_plan().seed == 2
    # an explicit argument beats the pin
    override = FaultPlan(seed=9)
    assert resolve_plan(override) is override
    assert resolve_plan(None).seed == 2


# --- retry helper -----------------------------------------------------------


def test_retry_jitter_is_seeded_and_decorrelates():
    """r12 satellite: backoff jitter is a pure hash of (site, attempt)
    — no ``random`` — so schedules reproduce exactly across reruns
    while two SITES (concurrent uploaders/processes) land on different
    delays instead of retrying in lockstep."""
    from qfedx_tpu.utils.retry import jitter_factor

    def schedule(site):
        sleeps = []

        def always(k):
            raise OSError("down")

        with pytest.raises(RetryExhausted):
            retry_with_deadline(
                always, attempts=4, base_delay_s=0.1, max_delay_s=10.0,
                sleep=sleeps.append, jitter_site=site,
            )
        return sleeps

    a1, a2 = schedule("ingest/0/1"), schedule("ingest/0/1")
    b = schedule("ingest/0/2")
    assert a1 == a2  # pure function of coordinates: reruns identical
    assert a1 != b  # different sites de-correlate
    for k, d in enumerate(a1):
        base = 0.1 * 2.0 ** k
        assert 0.5 * base <= d < base  # factor in [0.5, 1.0)
        assert d == base * jitter_factor("ingest/0/1", k)
    # jitter off (the default) keeps the bare exponential schedule
    plain = []
    with pytest.raises(RetryExhausted):
        retry_with_deadline(
            lambda k: (_ for _ in ()).throw(OSError("x")),
            attempts=3, base_delay_s=0.1, sleep=plain.append,
        )
    assert plain == [0.1, 0.2]


def test_retry_recovers_and_exhausts():
    sleeps = []
    calls = []

    def flaky(k):
        calls.append(k)
        if k < 2:
            raise OSError(f"boom {k}")
        return "ok"

    out = retry_with_deadline(
        flaky, attempts=3, base_delay_s=0.05, sleep=sleeps.append,
        describe="flaky op",
    )
    assert out == "ok" and calls == [0, 1, 2]
    assert sleeps == [0.05, 0.1]  # exponential, deterministic

    def always(k):
        raise OSError("disk gone")

    with pytest.raises(RetryExhausted) as ei:
        retry_with_deadline(
            always, attempts=3, sleep=lambda s: None, describe="doomed"
        )
    assert ei.value.attempts == 3
    assert isinstance(ei.value.last, OSError)
    assert isinstance(ei.value.__cause__, OSError)
    assert "doomed" in str(ei.value) and "disk gone" in str(ei.value)


def test_retry_respects_deadline_and_error_filter():
    import time

    t = {"now": 0.0}
    real_monotonic = time.monotonic
    try:
        time.monotonic = lambda: t["now"]

        def slow_fail(k):
            t["now"] += 6.0
            raise OSError("slow")

        with pytest.raises(RetryExhausted) as ei:
            retry_with_deadline(
                slow_fail, attempts=10, deadline_s=10.0,
                sleep=lambda s: None,
            )
        assert ei.value.attempts == 2  # deadline cut it, not attempts
    finally:
        time.monotonic = real_monotonic
    # non-retry_on errors propagate immediately
    with pytest.raises(KeyboardInterrupt):
        retry_with_deadline(
            lambda k: (_ for _ in ()).throw(KeyboardInterrupt()),
            attempts=5, sleep=lambda s: None,
        )


# --- DP accountant dropout invariance (satellite) ---------------------------


def test_epsilon_unchanged_by_injected_dropouts():
    """The accountant charges the SAMPLED cohort: a run with 25% of
    clients dropping every round reports the exact same per-round ε as
    the casualty-free run — dropout never shrinks the accounted q."""
    from qfedx_tpu.data.stream import ArrayRegistry
    from qfedx_tpu.run.trainer import train_federated_streamed

    rng = np.random.default_rng(0)
    cx = rng.uniform(0, 1, (16, 4, N_Q)).astype(np.float32)
    cy = (cx.mean(axis=2) > 0.5).astype(np.int32)
    cm = np.ones((16, 4), dtype=np.float32)
    tx, ty = cx[:, 0, :], cy[:, 0]
    model = make_vqc_classifier(n_qubits=N_Q, n_layers=1, num_classes=2)
    cfg = FedConfig(
        local_epochs=1, batch_size=4, learning_rate=0.1,
        client_fraction=0.5,
        dp=DPConfig(clip_norm=1.0, noise_multiplier=1.0),
    )
    reg = ArrayRegistry(cx, cy, cm)
    mesh = client_mesh(num_devices=4)
    kw = dict(cohort_size=8, wave_size=8, num_rounds=2, seed=1,
              eval_every=3, mesh=mesh)
    clean = train_federated_streamed(model, cfg, reg, tx, ty, **kw)
    plan = FaultPlan(seed=5, rules=[
        {"site": "client.compute", "kind": "drop", "rate": 0.25},
    ])
    faulty = train_federated_streamed(
        model, cfg, reg, tx, ty, fault_plan=plan, **kw
    )
    assert clean.epsilons == faulty.epsilons
    assert len(clean.epsilons) == 2


# --- the tier-1 chaos smoke test (satellite) --------------------------------


def test_chaos_smoke_streamed_run(tmp_path):
    """A streamed run under a mixed CRASH + BYZANTINE plan — per round:
    client 3 drops, client 5's data goes NaN, client 6 scales its
    upload ×1000, client 2 trains on flipped labels, and round 1 wave
    0's registry fetch fails once transiently — must complete without
    error under the clip_mean defense, keep θ finite, converge on the
    learnable synthetic task, and report the EXACT casualty AND
    byzantine counts in metrics.jsonl (r11 + r12 satellites)."""
    import jax

    from qfedx_tpu.data.stream import ArrayRegistry
    from qfedx_tpu.run.metrics import MetricsLogger
    from qfedx_tpu.run.trainer import train_federated_streamed

    rng = np.random.default_rng(7)
    C, S = 8, 16
    cx = rng.uniform(0, 1, (C, S, N_Q)).astype(np.float32)
    cy = (cx.mean(axis=2) > 0.5).astype(np.int32)
    cm = np.ones((C, S), dtype=np.float32)
    tx = rng.uniform(0, 1, (64, N_Q)).astype(np.float32)
    ty = (tx.mean(axis=1) > 0.5).astype(np.int32)
    model = make_vqc_classifier(n_qubits=N_Q, n_layers=2, num_classes=2)
    # clip_bound 5.0 ≈ several honest adam-update norms: honest clients
    # never hit it (reconciled below), the ×1000 attacker always does.
    cfg = FedConfig(local_epochs=2, batch_size=8, learning_rate=0.1,
                    optimizer="adam", secure_agg=True,
                    secure_agg_mode="ring", aggregator="clip_mean",
                    clip_bound=5.0)
    plan = FaultPlan(seed=0, rules=[
        {"site": "client.compute", "kind": "drop", "clients": [3]},
        {"site": "client.compute", "kind": "nan", "clients": [5]},
        {"site": "client.byzantine", "kind": "scale:1000", "clients": [6]},
        {"site": "client.byzantine", "kind": "label_flip", "clients": [2]},
        {"site": "registry.fetch", "rounds": [1], "waves": [0], "times": 1},
    ])
    mesh = client_mesh(num_devices=4)
    logger = MetricsLogger(tmp_path / "metrics.jsonl")
    num_rounds = 8
    res = train_federated_streamed(
        model, cfg, ArrayRegistry(cx, cy, cm), tx, ty,
        cohort_size=C, wave_size=4, num_rounds=num_rounds, seed=2,
        eval_every=2, mesh=mesh, fault_plan=plan,
        on_round_end=lambda r, m: logger.log(m),
    )
    logger.close()
    for leaf in jax.tree.leaves(res.params):
        assert np.all(np.isfinite(np.asarray(leaf)))
    assert all(np.isfinite(res.losses))
    # converged despite 25% crash casualties + 25% adversaries
    assert res.final_accuracy > 0.7
    rows = [
        json.loads(line)
        for line in (tmp_path / "metrics.jsonl").read_text().splitlines()
    ]
    assert len(rows) == num_rounds
    for r, row in enumerate(rows):
        want = plan.casualty_counts(r, np.arange(C))
        byz = plan.byzantine_counts(r, np.arange(C))
        assert row["dropped_clients"] == want["drop"] == 1
        assert row["rejected_updates"] == want["nan"] + want["inf"] == 1
        # EXACTLY the scale attacker hits the norm bound — honest
        # clients (the label-flipper included) stay under it, and the
        # quarantined NaN client never reaches the clip.
        assert row["clipped_clients"] == byz["scale"] == 1
        assert row["aggregator"] == "clip_mean"
        assert row["participants"] == C - 2
        assert "skipped" not in row


@pytest.mark.slow
def test_twenty_rounds_ten_percent_casualties_within_noise():
    """The r11 acceptance run: 20 streamed rounds with ~10% injected
    casualties per round (drops + NaN updates mixed) completes, θ stays
    finite every round, and final accuracy lands within noise of the
    casualty-free run."""
    import jax

    from qfedx_tpu.data.stream import SyntheticRegistry
    from qfedx_tpu.run.trainer import train_federated_streamed

    registry = SyntheticRegistry(
        1 << 16, samples=16, n_features=N_Q, seed=3
    )
    ex, ey, _ = registry.batch(np.arange((1 << 16) - 16, 1 << 16))
    tx, ty = ex.reshape(-1, N_Q), ey.reshape(-1)
    model = make_vqc_classifier(n_qubits=N_Q, n_layers=2, num_classes=2)
    cfg = FedConfig(local_epochs=2, batch_size=8, learning_rate=0.1,
                    optimizer="adam", secure_agg=True,
                    secure_agg_mode="ring")
    mesh = client_mesh(num_devices=4)
    kw = dict(cohort_size=16, wave_size=8, num_rounds=20, seed=4,
              eval_every=5, mesh=mesh)
    clean = train_federated_streamed(model, cfg, registry, tx, ty, **kw)
    plan = FaultPlan(seed=1, rules=[
        {"site": "client.compute", "kind": "drop", "rate": 0.05},
        {"site": "client.compute", "kind": "nan", "rate": 0.05},
    ])
    chaos = train_federated_streamed(
        model, cfg, registry, tx, ty, fault_plan=plan, **kw
    )
    for leaf in jax.tree.leaves(chaos.params):
        assert np.all(np.isfinite(np.asarray(leaf)))
    assert all(np.isfinite(chaos.losses))
    from qfedx_tpu.fed.sampling import CohortSampler

    sampler = CohortSampler(
        registry_size=1 << 16, cohort_size=16, seed=4
    )
    total = sum(
        sum(plan.casualty_counts(r, sampler.round_ids(r)).values())
        for r in range(20)
    )
    assert total > 10  # the plan actually fired ~10%/round
    assert chaos.final_accuracy > clean.final_accuracy - 0.1


# --- straggler sites (client.slow / wave.delay, r13) ------------------------


def test_straggler_plan_kinds_params_and_determinism():
    """client.slow / wave.delay: parameterized kinds parse, draws are
    pure in (seed, round, ids/wave), and wave_delays composes the two
    sites into the per-wave sleep the stream actually performs."""
    plan = FaultPlan(seed=6, rules=[
        {"site": "client.slow", "kind": "slow:0.5", "clients": [6]},
        {"site": "client.slow", "kind": "slow", "clients": [6]},  # 1 s wins
        {"site": "wave.delay", "kind": "delay:0.25", "rounds": [1],
         "waves": [0]},
    ])
    ids = np.arange(8)
    slow = plan.slow_seconds(0, ids)
    assert slow[6] == 1.0 and slow.sum() == 1.0  # overlapping rules: max
    np.testing.assert_array_equal(slow, plan.slow_seconds(0, ids))
    assert plan.wave_delay_s(1, 0) == 0.25
    assert plan.wave_delay_s(0, 0) == 0.0  # round-restricted
    # wave_delays = max(wave rule, slowest client in the wave)
    np.testing.assert_allclose(
        plan.wave_delays(1, ids, 4), [0.25, 1.0]
    )
    np.testing.assert_allclose(plan.wave_delays(0, ids, 4), [0.0, 1.0])
    # grammar is loud
    with pytest.raises(ValueError, match="slow"):
        FaultPlan(rules=[{"site": "client.slow", "kind": "fast",
                          "clients": [1]}])
    with pytest.raises(ValueError, match="delay:seconds"):
        FaultPlan(rules=[{"site": "wave.delay", "kind": "delay"}])
    with pytest.raises(ValueError, match="> 0"):
        FaultPlan(rules=[{"site": "wave.delay", "kind": "delay:0"}])
    with pytest.raises(ValueError, match="exactly one"):
        FaultPlan(rules=[{"site": "client.slow", "kind": "slow:1"}])
    # wave.delay has no client axis — a clients key must fail loudly,
    # never be silently ignored (rate would default to 1.0)
    with pytest.raises(ValueError, match="client.slow"):
        FaultPlan(rules=[{"site": "wave.delay", "kind": "delay:1",
                          "clients": [3]}])
    # ...and client.slow has no wave axis (per-client draws pin
    # wave=0, so a waves restriction would silently never fire)
    with pytest.raises(ValueError, match="wave.delay"):
        FaultPlan(rules=[{"site": "client.slow", "kind": "slow:1",
                          "clients": [3], "waves": [1]}])
    # duration sites have no retry attempts for 'times' to bound
    with pytest.raises(ValueError, match="times"):
        FaultPlan(rules=[{"site": "wave.delay", "kind": "delay:1",
                          "times": 1}])
    with pytest.raises(ValueError, match="times"):
        FaultPlan(rules=[{"site": "client.slow", "kind": "slow:1",
                          "clients": [3], "times": 1}])
    # wave.delay is a duration site, not an error site
    with pytest.raises(ValueError, match="unknown error site"):
        plan.check("wave.delay", 0)


def test_chaos_smoke_straggler_run(tmp_path, monkeypatch):
    """The r13 tier-1 chaos smoke: a streamed run under QFEDX_STALE
    with a mixed plan — client 3 drops every round, client 6 is SLOW
    (its wave goes late every round, salvaged the next) — must
    complete, converge, keep theta finite, and reconcile the EXACT
    staleness ledger (late_waves / stale_partials_applied /
    dropped_clients) against the plan's wave_delays oracle per round."""
    import jax

    from qfedx_tpu.data.stream import ArrayRegistry
    from qfedx_tpu.run.metrics import MetricsLogger
    from qfedx_tpu.run.trainer import train_federated_streamed

    monkeypatch.setenv("QFEDX_STALE", "1")
    rng = np.random.default_rng(7)
    C, S = 8, 16
    cx = rng.uniform(0, 1, (C, S, N_Q)).astype(np.float32)
    cy = (cx.mean(axis=2) > 0.5).astype(np.int32)
    cm = np.ones((C, S), dtype=np.float32)
    tx = rng.uniform(0, 1, (64, N_Q)).astype(np.float32)
    ty = (tx.mean(axis=1) > 0.5).astype(np.int32)
    model = make_vqc_classifier(n_qubits=N_Q, n_layers=2, num_classes=2)
    cfg = FedConfig(local_epochs=2, batch_size=8, learning_rate=0.1,
                    optimizer="adam", secure_agg=True,
                    secure_agg_mode="ring")
    plan = FaultPlan(seed=0, rules=[
        {"site": "client.compute", "kind": "drop", "clients": [3]},
        {"site": "client.slow", "kind": "slow:0.4", "clients": [6]},
    ])
    mesh = client_mesh(num_devices=4)
    logger = MetricsLogger(tmp_path / "metrics.jsonl")
    num_rounds = 6
    res = train_federated_streamed(
        model, cfg, ArrayRegistry(cx, cy, cm), tx, ty,
        cohort_size=C, wave_size=4, num_rounds=num_rounds, seed=2,
        eval_every=2, mesh=mesh, fault_plan=plan,
        wave_deadline_s=0.1, stale_poll_s=15.0,
        on_round_end=lambda r, m: logger.log(m),
    )
    logger.close()
    for leaf in jax.tree.leaves(res.params):
        assert np.all(np.isfinite(np.asarray(leaf)))
    assert all(np.isfinite(res.losses))
    # converged: the straggler's work keeps LANDING (discounted), so
    # chaos costs accuracy little
    assert res.final_accuracy > 0.7
    rows = [
        json.loads(line)
        for line in (tmp_path / "metrics.jsonl").read_text().splitlines()
    ]
    assert len(rows) == num_rounds
    for r, row in enumerate(rows):
        want_late = int((plan.wave_delays(r, np.arange(C), 4) > 0).sum())
        assert row["late_waves"] == want_late == 1
        # client 6's wave (ids 4..7) is salvaged one round late, every
        # round after the first; the final round's straggler is still
        # in flight when training ends
        assert row["stale_partials_applied"] == (1 if r > 0 else 0)
        assert row["dropped_clients"] == 1  # client 3, nothing else
        want_fresh = 3  # wave 0's sampled survivors (client 3 dead)
        want = want_fresh + (4 if r > 0 else 0)
        assert row["participants"] == want
        assert "skipped" not in row
