"""Tier-1 gate: the repo is lint-clean modulo its committed baseline.

The engine (qfedx_tpu/analysis, docs/ANALYSIS.md) proves the
invariants tests can only sample — trace-purity, pin discipline,
span/lock/donation hygiene, every doc-taxonomy contract. This test
wires `qfedx lint` into the suite so a violation fails CI, not a code
review, exactly as tests/test_check_pins.py did for the pin table
alone. The companion unit fixtures live in tests/test_analysis.py.
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from qfedx_tpu.analysis import all_rules, render_text, run_lint  # noqa: E402


def test_repo_is_clean_modulo_baseline():
    result = run_lint()
    assert result.findings == [], (
        "qfedx lint found non-baselined findings:\n"
        + render_text(result)
    )
    assert result.stale_baseline == [], (
        "stale baseline entries (their findings were fixed — remove "
        f"them): {result.stale_baseline}"
    )


def test_every_rule_is_registered_and_ran():
    # The full ID surface: the engine's own hygiene rule, five analyses,
    # and the doc/contract guards (five rehosted check_* scripts, the
    # rule taxonomy itself, the r20 alert taxonomy, and the r21 tune
    # decision taxonomy).
    expected = {
        "QFX000", "QFX001", "QFX002", "QFX003", "QFX004", "QFX005",
        "QFX100", "QFX101", "QFX102", "QFX103", "QFX104", "QFX105",
        "QFX106", "QFX107",
    }
    assert set(all_rules()) == expected
    assert set(run_lint().rules_run) == expected


def test_real_sites_are_accounted_for():
    # The r18 acceptance ledger: every new rule caught real pre-existing
    # sites, now either fixed (absent), suppressed (reasoned, counted)
    # or baselined. The suppression count pins the reasoned exemptions:
    # 5 in run/config.py's env ledger (QFX002), obs/trace.py's
    # annotation bridge (QFX003), run/trainer.py's params_ref alias
    # (QFX005), obs/flight.py's write-only telemetry timestamp
    # (QFX001, r20). Growing this number should be a conscious diff
    # here.
    result = run_lint()
    assert result.suppressed == 8, (
        f"reasoned suppressions changed: {result.suppressed} != 8 — "
        "update this pin consciously (docs/ANALYSIS.md policy)"
    )
    # The one baselined finding: __main__.py's pre-import JAX_PLATFORMS
    # read (see benchmarks/lint_baseline.json for the reason).
    assert [
        (f.rule, f.path) for f in result.baselined
    ] == [("QFX002", "qfedx_tpu/__main__.py")]
