"""Config system, CLI arg mapping, end-to-end `train` command, SPSA, viz."""

import json

import numpy as np
import pytest

from qfedx_tpu.data.partition import iid_partition, partition_stats
from qfedx_tpu.data.viz import save_class_distribution, save_client_samples
from qfedx_tpu.fed.config import FedConfig
from qfedx_tpu.run.cli import build_parser, config_from_args, run_train
from qfedx_tpu.run.config import (
    DataConfig,
    ExperimentConfig,
    ModelConfig,
    build_data,
    build_model,
)


def parse(argv):
    return config_from_args(build_parser().parse_args(argv))


def test_cli_maps_args():
    cfg = parse(
        [
            "train", "--model", "vqc", "--qubits", "4", "--layers", "1",
            "--dataset", "fashion_mnist", "--classes", "0,1", "--clients", "8",
            "--partition", "dirichlet", "--alpha", "0.1", "--optimizer", "spsa",
            "--algorithm", "fedprox", "--prox-mu", "0.05",
            "--dp-clip", "0.5", "--dp-sigma", "2.0", "--secure-agg",
        ]
    )
    assert cfg.model.n_qubits == 4 and cfg.data.dataset == "fashion_mnist"
    assert cfg.data.classes == (0, 1) and cfg.data.partition == "dirichlet"
    assert cfg.fed.optimizer == "spsa" and cfg.fed.algorithm == "fedprox"
    assert cfg.fed.dp.clip_norm == 0.5 and cfg.fed.dp.noise_multiplier == 2.0
    assert cfg.fed.secure_agg and cfg.fed.prox_mu == 0.05
    assert "vqc4q" in cfg.run_name() and "fashion_mnist" in cfg.run_name()


def test_build_data_quantum_and_classical_shapes():
    base = dict(dataset="mnist", classes=(0, 1), num_clients=4, seed=1)
    qcfg = ExperimentConfig(
        data=DataConfig(features="pca", **base),
        model=ModelConfig(model="vqc", n_qubits=4),
        fed=FedConfig(batch_size=8),
    )
    qd = build_data(qcfg)
    assert qd["cx"].shape[0] == 4 and qd["cx"].shape[2] == 4  # 4 PCA features
    assert qd["cx"].shape[1] % 8 == 0  # padded to batch multiple
    assert qd["num_classes"] == 2
    assert (qd["cx"] >= 0).all() and (qd["cx"] <= 1).all()  # angle-ready

    ccfg = ExperimentConfig(
        data=DataConfig(**base),
        model=ModelConfig(model="cnn"),
        fed=FedConfig(batch_size=8),
    )
    cd = build_data(ccfg)
    assert cd["cx"].shape[2:] == (28, 28)  # images kept for the CNN

    model = build_model(ccfg, cd["num_classes"])
    assert "cnn" in model.name
    model = build_model(qcfg, qd["num_classes"])
    assert "vqc" in model.name


def test_build_model_kernel_and_noise():
    cfg = ExperimentConfig(
        model=ModelConfig(model="qkernel", n_qubits=3, n_landmarks=4)
    )
    assert "qkernel" in build_model(cfg, 2).name
    noisy = ExperimentConfig(
        model=ModelConfig(model="vqc", n_qubits=3, depolarizing_p=0.1)
    )
    assert "vqc" in build_model(noisy, 2).name


def test_run_train_end_to_end(tmp_path, monkeypatch):
    """The full CLI path: synthetic data → SPMD training → run artifacts."""
    monkeypatch.delenv("QFEDX_PROFILE", raising=False)
    cfg = parse(
        [
            "train", "--model", "vqc", "--qubits", "3", "--layers", "1",
            "--classes", "0,1", "--clients", "4", "--rounds", "2",
            "--local-epochs", "1", "--batch-size", "8", "--lr", "0.1",
            "--optimizer", "adam", "--run-root", str(tmp_path), "--name", "t",
        ]
    )
    summary = run_train(cfg)
    assert 0.0 <= summary["final_accuracy"] <= 1.0
    run_dir = tmp_path / "t"
    assert (run_dir / "config.json").exists()
    assert (run_dir / "summary.json").exists()
    metrics = [
        json.loads(l) for l in (run_dir / "metrics.jsonl").read_text().splitlines()
    ]
    assert len(metrics) == 2 and metrics[-1]["round"] == 2
    # Default-off invariance (r16): no --profile flag and QFEDX_PROFILE
    # unset → no profiler session ran, no capture dir, no summary file.
    assert not (run_dir / "profile").exists()
    assert not (run_dir / "profile_summary.json").exists()


@pytest.mark.slow
def test_run_train_profiled_writes_summary_and_device_trace(tmp_path, monkeypatch):
    """--profile end-to-end (r16): the capture is parsed into
    profile_summary.json (measured census + gaps + busy fraction), the
    traced run's trace.json gains the device lane, and summary.json's
    phase_breakdown carries device_busy_s/utilization columns. Slow:
    real captures live in the slow tier (the r16 test pattern — the
    parser math is fixture-pinned fast in tests/test_obs.py)."""
    monkeypatch.delenv("QFEDX_PROFILE", raising=False)
    monkeypatch.setenv("QFEDX_TRACE", "1")
    monkeypatch.delenv("QFEDX_TRACE_XLA", raising=False)
    # Identical model/fed config to test_run_train_end_to_end above —
    # the round program is already jitted in this process, so this test
    # pays capture+parse cost, not a second compile.
    cfg = parse(
        [
            "train", "--model", "vqc", "--qubits", "3", "--layers", "1",
            "--classes", "0,1", "--clients", "4", "--rounds", "2",
            "--local-epochs", "1", "--batch-size", "8", "--lr", "0.1",
            "--optimizer", "adam",
            "--run-root", str(tmp_path), "--name", "prof",
        ]
    )
    run_train(cfg, profile=True, trace=True)
    run_dir = tmp_path / "prof"
    psum = json.loads((run_dir / "profile_summary.json").read_text())
    from qfedx_tpu.obs.profile import SUMMARY_FIELDS

    assert set(psum) == set(SUMMARY_FIELDS)
    assert psum["ops_executed"] > 0 and psum["gap_count"] > 0
    assert psum["device_busy_fraction"] is not None
    # span correlation reached the rollup: a phase carries device time
    # within its wall (--profile with --trace auto-bridges the spans)
    assert psum["spans"], "no annotation ranges correlated"
    summary = json.loads((run_dir / "summary.json").read_text())
    rolled = [
        row for row in summary["phase_breakdown"].values()
        if "device_busy_s" in row
    ]
    assert rolled
    for row in rolled:
        assert 0 < row["device_busy_s"] <= row["total_s"] + 1e-9
        assert 0 < row["utilization"] <= 1.0
    # the merged trace: host spans (pid 1) + the device lane (pid 1000)
    trace = json.loads((run_dir / "trace.json").read_text())
    pids = {e.get("pid") for e in trace["traceEvents"] if e.get("ph") == "X"}
    assert 1 in pids and 1000 in pids
    # capture artifacts live under <run-dir>/profile
    from qfedx_tpu.obs.profile import find_capture

    assert find_capture(run_dir / "profile") is not None


@pytest.mark.slow
def test_run_train_profiled_killed_midway_keeps_parseable_capture(
    tmp_path, monkeypatch
):
    """The r16 crash-safety satellite: a --profile run killed mid-train
    (the KeyboardInterrupt SIGTERM translates into) still stops the
    profiler session, leaves a PARSEABLE capture, and writes
    profile_summary.json from it — the bare jax.profiler.trace at this
    seam could leave a torn capture. Slow: real capture (the fast
    crash-safety unit is tests/test_obs.py::
    test_profile_capture_crash_safe_and_parseable)."""
    import qfedx_tpu.run.trainer as trainer_mod

    real = trainer_mod.train_federated

    def die_after_training(*args, **kwargs):
        real(*args, **kwargs)
        raise KeyboardInterrupt("SIGTERM")

    monkeypatch.setattr(trainer_mod, "train_federated", die_after_training)
    monkeypatch.delenv("QFEDX_PROFILE", raising=False)
    # Same cached program again (see the profiled test above).
    cfg = parse(
        [
            "train", "--model", "vqc", "--qubits", "3", "--layers", "1",
            "--classes", "0,1", "--clients", "4", "--rounds", "2",
            "--local-epochs", "1", "--batch-size", "8", "--lr", "0.1",
            "--optimizer", "adam",
            "--run-root", str(tmp_path), "--name", "killed",
        ]
    )
    with pytest.raises(KeyboardInterrupt):
        run_train(cfg, profile=True)
    run_dir = tmp_path / "killed"
    from qfedx_tpu.obs.profile import parse_capture

    parsed = parse_capture(run_dir / "profile")
    assert parsed["ops_executed"] > 0  # the capture survived, parseable
    psum = json.loads((run_dir / "profile_summary.json").read_text())
    assert psum["ops_executed"] == parsed["ops_executed"]


def test_inspect_run_dir(tmp_path, capsys):
    """qfedx inspect: the read side of the run directory — trajectory,
    ledger totals, schema validation, profile summary."""
    from qfedx_tpu.run.cli import main, run_inspect

    run_dir = tmp_path / "r"
    run_dir.mkdir()
    rows = [
        {"schema": 1, "round": 1, "ts": 1.0, "loss": 0.9, "accuracy": 0.5,
         "rejected_updates": 1, "late_waves": 2},
        {"schema": 1, "round": 2, "ts": 2.0, "loss": 0.5, "accuracy": 0.8,
         "rejected_updates": 0, "late_waves": 1, "epsilon": 2.5},
        {"round": 3, "ts": 3.0, "loss": 0.4, "accuracy": 0.9},  # no schema
        "not json at all",
    ]
    (run_dir / "metrics.jsonl").write_text(
        "\n".join(
            r if isinstance(r, str) else json.dumps(r) for r in rows
        ) + "\n"
    )
    (run_dir / "summary.json").write_text(
        json.dumps({"final_accuracy": 0.9, "wall_time_s": 12.5})
    )
    (run_dir / "profile_summary.json").write_text(
        json.dumps({"ops_executed": 1200, "gap_p50_us": 3.4,
                    "device_busy_fraction": 0.97, "device_busy_s": 1.0})
    )
    (run_dir / "config.json").write_text(
        json.dumps({"model": {"model": "vqc", "n_qubits": 8, "n_layers": 2}})
    )
    out = run_inspect(run_dir)
    assert out["rounds_completed"] == 3  # schema-less row still counted
    assert out["metrics_rows"] == 3
    assert out["invalid_rows"] == 2  # bad JSON + missing schema field
    assert out["first_accuracy"] == 0.5 and out["best_accuracy"] == 0.9
    assert out["last_epsilon"] == 2.5
    assert out["ledger"] == {"rejected_updates": 1, "late_waves": 3}
    assert out["summary"]["final_accuracy"] == 0.9
    assert out["profile"]["gap_p50_us"] == 3.4
    assert out["model"].startswith("vqc n=8")
    # the CLI path prints the same dict as its final JSON line
    capsys.readouterr()
    main(["inspect", str(run_dir)])
    last = capsys.readouterr().out.strip().splitlines()[-1]
    assert json.loads(last.split("] ", 1)[1])["rounds_completed"] == 3
    # a truncated artifact is reported in the JSON line, apart from the
    # metrics-row validation count
    (run_dir / "summary.json").write_text('{"final_accuracy": 0.')
    out = run_inspect(run_dir)
    assert out["unreadable_artifacts"] == ["summary.json"]
    assert out["invalid_rows"] == 2  # metrics rows only, unchanged


def test_inspect_missing_run_dir_is_loud(tmp_path):
    from qfedx_tpu.run.cli import run_inspect

    with pytest.raises(FileNotFoundError, match="metrics.jsonl"):
        run_inspect(tmp_path)


def test_spsa_trains():
    """SPSA gradient estimation drives loss down on a separable task."""
    from qfedx_tpu.models.vqc import make_vqc_classifier
    from qfedx_tpu.run.trainer import train_federated

    rng = np.random.default_rng(0)
    clients, samples, nq = 4, 32, 2
    cx = rng.uniform(0, 1, (clients, samples, nq)).astype(np.float32)
    cy = (cx[..., 0] > 0.5).astype(np.int32)
    cm = np.ones((clients, samples), dtype=np.float32)
    tx = rng.uniform(0, 1, (64, nq)).astype(np.float32)
    ty = (tx[:, 0] > 0.5).astype(np.int32)
    model = make_vqc_classifier(nq, n_layers=1, num_classes=2)
    cfg = FedConfig(
        local_epochs=2, batch_size=8, learning_rate=0.3, optimizer="spsa",
        momentum=0.0, spsa_c=0.15,
    )
    res = train_federated(model, cfg, cx, cy, cm, tx, ty, num_rounds=12, seed=3)
    assert res.losses[-1] < res.losses[0]
    assert res.final_accuracy > 0.6, res.accuracies


def test_viz_outputs(tmp_path):
    rng = np.random.default_rng(0)
    x = rng.uniform(0, 1, (40, 8, 8)).astype(np.float32)
    y = rng.integers(0, 3, 40)
    parts = iid_partition(40, 4, seed=0)
    p1 = save_client_samples(x, parts, tmp_path / "samples.png")
    stats = partition_stats(y, parts, 3)
    p2 = save_class_distribution(stats, tmp_path / "dist.png")
    assert p1.exists() and p1.stat().st_size > 0
    assert p2.exists() and p2.stat().st_size > 0
    flat = rng.uniform(0, 1, (40, 6)).astype(np.float32)  # non-square features
    p3 = save_client_samples(flat, parts, tmp_path / "flat.png")
    assert p3.exists()


def test_run_train_mps_model(tmp_path):
    """--model mps: the tensor-network simulator through the full CLI path
    at a qubit count the dense engine also handles (fast), plus flag
    mapping for --bond-dim."""
    cfg = parse(
        [
            "train", "--model", "mps", "--qubits", "6", "--bond-dim", "4",
            "--layers", "1", "--classes", "0,1", "--clients", "4",
            "--rounds", "2", "--local-epochs", "1", "--batch-size", "8",
            "--lr", "0.1", "--optimizer", "adam",
            "--run-root", str(tmp_path), "--name", "m",
        ]
    )
    assert cfg.model.model == "mps" and cfg.model.bond_dim == 4
    summary = run_train(cfg)
    assert 0.0 <= summary["final_accuracy"] <= 1.0
    assert (tmp_path / "m" / "summary.json").exists()
