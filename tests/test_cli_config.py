"""Config system, CLI arg mapping, end-to-end `train` command, SPSA, viz."""

import json

import numpy as np
import pytest

from qfedx_tpu.data.partition import iid_partition, partition_stats
from qfedx_tpu.data.viz import save_class_distribution, save_client_samples
from qfedx_tpu.fed.config import FedConfig
from qfedx_tpu.run.cli import build_parser, config_from_args, run_train
from qfedx_tpu.run.config import (
    DataConfig,
    ExperimentConfig,
    ModelConfig,
    build_data,
    build_model,
)


def parse(argv):
    return config_from_args(build_parser().parse_args(argv))


def test_cli_maps_args():
    cfg = parse(
        [
            "train", "--model", "vqc", "--qubits", "4", "--layers", "1",
            "--dataset", "fashion_mnist", "--classes", "0,1", "--clients", "8",
            "--partition", "dirichlet", "--alpha", "0.1", "--optimizer", "spsa",
            "--algorithm", "fedprox", "--prox-mu", "0.05",
            "--dp-clip", "0.5", "--dp-sigma", "2.0", "--secure-agg",
        ]
    )
    assert cfg.model.n_qubits == 4 and cfg.data.dataset == "fashion_mnist"
    assert cfg.data.classes == (0, 1) and cfg.data.partition == "dirichlet"
    assert cfg.fed.optimizer == "spsa" and cfg.fed.algorithm == "fedprox"
    assert cfg.fed.dp.clip_norm == 0.5 and cfg.fed.dp.noise_multiplier == 2.0
    assert cfg.fed.secure_agg and cfg.fed.prox_mu == 0.05
    assert "vqc4q" in cfg.run_name() and "fashion_mnist" in cfg.run_name()


def test_build_data_quantum_and_classical_shapes():
    base = dict(dataset="mnist", classes=(0, 1), num_clients=4, seed=1)
    qcfg = ExperimentConfig(
        data=DataConfig(features="pca", **base),
        model=ModelConfig(model="vqc", n_qubits=4),
        fed=FedConfig(batch_size=8),
    )
    qd = build_data(qcfg)
    assert qd["cx"].shape[0] == 4 and qd["cx"].shape[2] == 4  # 4 PCA features
    assert qd["cx"].shape[1] % 8 == 0  # padded to batch multiple
    assert qd["num_classes"] == 2
    assert (qd["cx"] >= 0).all() and (qd["cx"] <= 1).all()  # angle-ready

    ccfg = ExperimentConfig(
        data=DataConfig(**base),
        model=ModelConfig(model="cnn"),
        fed=FedConfig(batch_size=8),
    )
    cd = build_data(ccfg)
    assert cd["cx"].shape[2:] == (28, 28)  # images kept for the CNN

    model = build_model(ccfg, cd["num_classes"])
    assert "cnn" in model.name
    model = build_model(qcfg, qd["num_classes"])
    assert "vqc" in model.name


def test_build_model_kernel_and_noise():
    cfg = ExperimentConfig(
        model=ModelConfig(model="qkernel", n_qubits=3, n_landmarks=4)
    )
    assert "qkernel" in build_model(cfg, 2).name
    noisy = ExperimentConfig(
        model=ModelConfig(model="vqc", n_qubits=3, depolarizing_p=0.1)
    )
    assert "vqc" in build_model(noisy, 2).name


def test_run_train_end_to_end(tmp_path):
    """The full CLI path: synthetic data → SPMD training → run artifacts."""
    cfg = parse(
        [
            "train", "--model", "vqc", "--qubits", "3", "--layers", "1",
            "--classes", "0,1", "--clients", "4", "--rounds", "2",
            "--local-epochs", "1", "--batch-size", "8", "--lr", "0.1",
            "--optimizer", "adam", "--run-root", str(tmp_path), "--name", "t",
        ]
    )
    summary = run_train(cfg)
    assert 0.0 <= summary["final_accuracy"] <= 1.0
    run_dir = tmp_path / "t"
    assert (run_dir / "config.json").exists()
    assert (run_dir / "summary.json").exists()
    metrics = [
        json.loads(l) for l in (run_dir / "metrics.jsonl").read_text().splitlines()
    ]
    assert len(metrics) == 2 and metrics[-1]["round"] == 2


def test_spsa_trains():
    """SPSA gradient estimation drives loss down on a separable task."""
    from qfedx_tpu.models.vqc import make_vqc_classifier
    from qfedx_tpu.run.trainer import train_federated

    rng = np.random.default_rng(0)
    clients, samples, nq = 4, 32, 2
    cx = rng.uniform(0, 1, (clients, samples, nq)).astype(np.float32)
    cy = (cx[..., 0] > 0.5).astype(np.int32)
    cm = np.ones((clients, samples), dtype=np.float32)
    tx = rng.uniform(0, 1, (64, nq)).astype(np.float32)
    ty = (tx[:, 0] > 0.5).astype(np.int32)
    model = make_vqc_classifier(nq, n_layers=1, num_classes=2)
    cfg = FedConfig(
        local_epochs=2, batch_size=8, learning_rate=0.3, optimizer="spsa",
        momentum=0.0, spsa_c=0.15,
    )
    res = train_federated(model, cfg, cx, cy, cm, tx, ty, num_rounds=12, seed=3)
    assert res.losses[-1] < res.losses[0]
    assert res.final_accuracy > 0.6, res.accuracies


def test_viz_outputs(tmp_path):
    rng = np.random.default_rng(0)
    x = rng.uniform(0, 1, (40, 8, 8)).astype(np.float32)
    y = rng.integers(0, 3, 40)
    parts = iid_partition(40, 4, seed=0)
    p1 = save_client_samples(x, parts, tmp_path / "samples.png")
    stats = partition_stats(y, parts, 3)
    p2 = save_class_distribution(stats, tmp_path / "dist.png")
    assert p1.exists() and p1.stat().st_size > 0
    assert p2.exists() and p2.stat().st_size > 0
    flat = rng.uniform(0, 1, (40, 6)).astype(np.float32)  # non-square features
    p3 = save_client_samples(flat, parts, tmp_path / "flat.png")
    assert p3.exists()


def test_run_train_mps_model(tmp_path):
    """--model mps: the tensor-network simulator through the full CLI path
    at a qubit count the dense engine also handles (fast), plus flag
    mapping for --bond-dim."""
    cfg = parse(
        [
            "train", "--model", "mps", "--qubits", "6", "--bond-dim", "4",
            "--layers", "1", "--classes", "0,1", "--clients", "4",
            "--rounds", "2", "--local-epochs", "1", "--batch-size", "8",
            "--lr", "0.1", "--optimizer", "adam",
            "--run-root", str(tmp_path), "--name", "m",
        ]
    )
    assert cfg.model.model == "mps" and cfg.model.bond_dim == 4
    summary = run_train(cfg)
    assert 0.0 <= summary["final_accuracy"] <= 1.0
    assert (tmp_path / "m" / "summary.json").exists()
