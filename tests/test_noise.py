"""Noise models: analytic ⟨Z⟩ maps, Kraus trajectory sampling, shots.

Covers the reference's noise-phase spec (reference ROADMAP.md:64-73),
including its own acceptance check that noise degrades accuracy monotonically
in strength (ROADMAP.md:73) — here as expectation shrinkage — and
cross-checks the cheap analytic readout channels against the general
trajectory engine.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from qfedx_tpu.circuits.encoders import angle_encode
from qfedx_tpu.models.vqc import make_vqc_classifier
from qfedx_tpu.noise import (
    NoiseModel,
    amplitude_damping_kraus,
    apply_channel,
    apply_channel_all,
    bit_flip_kraus,
    depolarizing_kraus,
    trajectory_average,
)
from qfedx_tpu.ops import statevector as sv
from qfedx_tpu.ops.cpx import from_complex


def random_state(n, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(2,) * n) + 1j * rng.normal(size=(2,) * n)
    return from_complex(x / np.linalg.norm(x))


# --- analytic channel maps -------------------------------------------------


def test_depolarizing_shrinks_z():
    z = jnp.asarray([0.8, -0.4])
    nm = NoiseModel(depolarizing_p=0.25)
    np.testing.assert_allclose(nm.apply_to_z(z, None), 0.75 * z, atol=1e-6)


def test_amplitude_damping_pulls_toward_zero_state():
    z = jnp.asarray([-1.0, 0.0, 1.0])
    nm = NoiseModel(amp_damping_gamma=0.5)
    # ⟨Z⟩ → ⟨Z⟩ + γ(1−⟨Z⟩); |0⟩ (z=1) is the fixed point.
    np.testing.assert_allclose(nm.apply_to_z(z, None), [0.0, 0.5, 1.0], atol=1e-6)


def test_readout_confusion_symmetric():
    z = jnp.asarray([0.6])
    nm = NoiseModel(readout_e01=0.1, readout_e10=0.1)
    np.testing.assert_allclose(nm.apply_to_z(z, None), 0.8 * z, atol=1e-6)


def test_noise_strength_monotone():
    """Reference ROADMAP.md:73: stronger noise ⇒ more degradation."""
    z = jnp.asarray([0.9])
    vals = [
        float(NoiseModel(depolarizing_p=p).apply_to_z(z, None)[0])
        for p in (0.0, 0.1, 0.3, 0.6)
    ]
    assert vals == sorted(vals, reverse=True)


def test_composed_matches_sequential_application():
    """composed(n) ≡ applying the channel's ⟨Z⟩ map n times — including
    both channels on at once (the maps don't commute; the composition must
    track the interleaved order, not compose each channel separately)."""
    z = jnp.asarray([0.7, -0.3])
    for nm in (
        NoiseModel(depolarizing_p=0.15),
        NoiseModel(amp_damping_gamma=0.2),
        NoiseModel(depolarizing_p=0.3, amp_damping_gamma=0.3),
        NoiseModel(depolarizing_p=0.1, amp_damping_gamma=1.0),
    ):
        seq = z
        for _ in range(3):
            seq = nm.apply_to_z(seq, None)
        np.testing.assert_allclose(
            nm.composed(3).apply_to_z(z, None), seq, atol=1e-6
        )
    assert NoiseModel(depolarizing_p=0.1).composed(1) == NoiseModel(depolarizing_p=0.1)


def test_finite_shots_unbiased_and_noisy():
    z = jnp.asarray([0.4] * 64)
    nm = NoiseModel(shots=256)
    out = nm.apply_to_z(z, jax.random.PRNGKey(0))
    assert float(jnp.std(out)) > 0.0  # actually sampled
    np.testing.assert_allclose(float(jnp.mean(out)), 0.4, atol=0.05)
    assert NoiseModel(shots=None).apply_to_z(z, None) is z  # exact path


def test_shots_require_key():
    with pytest.raises(ValueError, match="key"):
        NoiseModel(shots=16).apply_to_z(jnp.asarray([0.0]), None)


# --- trajectory engine -----------------------------------------------------


def test_trajectory_preserves_norm():
    state = random_state(4, seed=1)
    out = apply_channel(state, depolarizing_kraus(0.3), 2, jax.random.PRNGKey(0))
    np.testing.assert_allclose(float(jnp.sum(sv.cabs2(out))), 1.0, atol=1e-5)


def test_depolarizing_kraus_exact_channel_matches_analytic():
    """Σ_k ⟨ψ|K_k†ZK_k|ψ⟩ = (1−p)⟨Z⟩ — deterministic convention check.

    expect_z is the plain quadratic form (no renormalization), so summing
    it over unnormalized Kraus branches IS the exact channel average. This
    pins the Kraus convention {√(1−3p/4)I, √(p/4)X/Y/Z} to the analytic
    readout map ⟨Z⟩→(1−p)⟨Z⟩ with no Monte-Carlo slack.
    """
    from qfedx_tpu.noise.trajectory import _kraus_op

    n, p, qubit = 3, 0.4, 1
    state = random_state(n, seed=2)
    z_clean = float(sv.expect_z(state, qubit))
    kraus = depolarizing_kraus(p)
    z_exact = sum(
        float(sv.expect_z(sv.apply_gate(state, _kraus_op(kraus, i), qubit), qubit))
        for i in range(kraus.re.shape[0])
    )
    np.testing.assert_allclose(z_exact, (1.0 - p) * z_clean, atol=1e-6)


def test_trajectory_depolarizing_matches_analytic():
    """E_traj[⟨Z⟩] = (1−p)·⟨Z⟩ for the depolarizing channel."""
    n, p, qubit = 3, 0.4, 1
    state = random_state(n, seed=2)
    z_clean = float(sv.expect_z(state, qubit))

    est = trajectory_average(
        lambda key: sv.expect_z(
            apply_channel(state, depolarizing_kraus(p), qubit, key), qubit
        ),
        n_trajectories=8000,
    )
    z_noisy = float(est(jax.random.PRNGKey(3)))
    np.testing.assert_allclose(z_noisy, (1.0 - p) * z_clean, atol=0.025)


def test_trajectory_damping_matches_analytic():
    n, gamma, qubit = 2, 0.35, 0
    state = random_state(n, seed=4)
    z_clean = float(sv.expect_z(state, qubit))
    est = trajectory_average(
        lambda key: sv.expect_z(
            apply_channel(state, amplitude_damping_kraus(gamma), qubit, key), qubit
        ),
        n_trajectories=3000,
    )
    z_noisy = float(est(jax.random.PRNGKey(5)))
    np.testing.assert_allclose(z_noisy, z_clean + gamma * (1.0 - z_clean), atol=0.05)


def test_bit_flip_full_strength_flips_z():
    state = angle_encode(jnp.asarray([0.0, 0.0]))  # |00⟩, ⟨Z⟩=+1 each
    out = apply_channel_all(state, bit_flip_kraus(1.0), jax.random.PRNGKey(0))
    np.testing.assert_allclose(np.asarray(sv.expect_z_all(out)), [-1, -1], atol=1e-5)


# --- model integration -----------------------------------------------------


def test_vqc_with_finite_shots_trains_and_evals():
    """shots-enabled VQC: eval is exact (deterministic), training samples
    shot noise through apply_train (regression: apply() used to crash)."""
    model = make_vqc_classifier(
        3, n_layers=1, num_classes=2, noise_model=NoiseModel(shots=64)
    )
    params = model.init(jax.random.PRNGKey(0))
    x = jnp.linspace(0.1, 0.9, 6).reshape(2, 3)
    l1, l2 = model.apply(params, x), model.apply(params, x)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2))  # exact eval
    assert model.apply_train is not None
    lt1 = model.apply_train(params, x, jax.random.PRNGKey(1))
    lt2 = model.apply_train(params, x, jax.random.PRNGKey(2))
    assert not np.allclose(np.asarray(lt1), np.asarray(lt2))  # sampled


def test_vqc_with_noise_model_runs_and_degrades():
    x = jnp.linspace(0.1, 0.9, 8).reshape(2, 4)
    clean = make_vqc_classifier(4, n_layers=1, num_classes=2)
    noisy = make_vqc_classifier(
        4, n_layers=1, num_classes=2, noise_model=NoiseModel(depolarizing_p=0.3)
    )
    params = clean.init(jax.random.PRNGKey(0))
    lc = clean.apply(params, x)
    ln = noisy.apply(params, x)
    assert lc.shape == ln.shape == (2, 2)
    # depolarizing shrinks ⟨Z⟩ ⇒ logits move toward the bias (0 here)
    assert float(jnp.sum(jnp.abs(ln))) < float(jnp.sum(jnp.abs(lc)))
