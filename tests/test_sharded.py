"""Sharded statevector engine vs the dense engine — exact agreement.

The distributed engine (parallel.sharded) must be bit-for-bit the same
simulation as the dense one (ops.statevector), shard choreography aside.
Every test builds the same circuit both ways on the 8-device CPU mesh
(3 global qubits) and compares.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from qfedx_tpu.circuits.ansatz import hardware_efficient, init_ansatz_params
from qfedx_tpu.circuits.encoders import angle_encode
from qfedx_tpu.ops import gates, statevector as sv
from qfedx_tpu.ops.cpx import CArray, from_complex, to_complex
from qfedx_tpu.parallel import (
    ShardCtx,
    apply_gate_2q_sharded,
    apply_gate_sharded,
    expect_z_all_sharded,
    expect_z_sharded,
    from_dense,
    make_sharded_forward,
    norm_sq_sharded,
    swap_global_local,
    zero_state_local,
)
from qfedx_tpu.utils.compat import shard_map

N_GLOBAL = 3  # 8 devices


def mesh8():
    return Mesh(np.array(jax.devices()), ("sv",))


def run_gathered(n_qubits, fn, *args):
    """Run fn(ctx, *args) -> CArray under shard_map; gather to dense complex."""
    ctx = ShardCtx("sv", n_qubits, N_GLOBAL)

    def per_device(*a):
        out = fn(ctx, *a)
        return out.re.reshape(1, -1), out.imag_or_zeros().reshape(1, -1)

    f = shard_map(
        per_device, mesh=mesh8(), in_specs=P(), out_specs=P("sv"), check_vma=False
    )
    re, im = f(*args)
    shape = (2,) * n_qubits
    return np.asarray(re).reshape(shape) + 1j * np.asarray(im).reshape(shape)


def run_scalar(n_qubits, fn, *args):
    """Run fn(ctx, *args) -> replicated array under shard_map."""
    ctx = ShardCtx("sv", n_qubits, N_GLOBAL)
    f = shard_map(
        lambda *a: fn(ctx, *a),
        mesh=mesh8(),
        in_specs=P(),
        out_specs=P(),
        check_vma=False,
    )
    return np.asarray(f(*args))


def random_state(n_qubits, seed=0, real=False):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(2,) * n_qubits)
    if not real:
        x = x + 1j * rng.normal(size=(2,) * n_qubits)
    x = x / np.linalg.norm(x)
    return from_complex(x)


def test_zero_state():
    got = run_gathered(5, lambda ctx: zero_state_local(ctx))
    want = to_complex(sv.zero_state(5))
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_from_dense_roundtrip_and_norm():
    dense = random_state(6, seed=1)
    got = run_gathered(6, from_dense, dense)
    np.testing.assert_allclose(got, to_complex(dense), atol=1e-6)
    norm = run_scalar(6, lambda ctx, d: norm_sq_sharded(ctx, from_dense(ctx, d)), dense)
    np.testing.assert_allclose(norm, 1.0, atol=1e-5)


@pytest.mark.parametrize("qubit", [0, 2, 3, 5])  # global (0,2) and local (3,5)
@pytest.mark.parametrize("real", [True, False])
def test_single_qubit_gate(qubit, real):
    n = 6
    dense = random_state(n, seed=qubit, real=real)
    gate = gates.rx(0.7) if not real else gates.ry(1.1)
    got = run_gathered(
        n, lambda ctx, d: apply_gate_sharded(ctx, from_dense(ctx, d), gate, qubit), dense
    )
    want = to_complex(sv.apply_gate(dense, gate, qubit))
    np.testing.assert_allclose(got, want, atol=1e-5)


@pytest.mark.parametrize("qubit", [1, 4])
def test_complex_gate_on_real_state(qubit):
    n = 5
    dense = random_state(n, seed=9, real=True)
    got = run_gathered(
        n,
        lambda ctx, d: apply_gate_sharded(ctx, from_dense(ctx, d), gates.rz(0.4), qubit),
        dense,
    )
    want = to_complex(sv.apply_gate(dense, gates.rz(0.4), qubit))
    np.testing.assert_allclose(got, want, atol=1e-5)


@pytest.mark.parametrize("g,l", [(0, 3), (2, 5), (1, 4)])
def test_swap_global_local(g, l):
    n = 6
    dense = random_state(n, seed=g * 10 + l)
    got = run_gathered(
        n, lambda ctx, d: swap_global_local(ctx, from_dense(ctx, d), g, l), dense
    )
    want = to_complex(sv.apply_gate_2q(dense, gates.SWAP, g, l))
    np.testing.assert_allclose(got, want, atol=1e-5)


@pytest.mark.parametrize(
    "q1,q2",
    [
        (3, 4),  # local-local
        (0, 3),  # global control, local target
        (3, 0),  # local control, global target
        (0, 2),  # global-global
        # global-global reversed: same ppermute choreography as (0, 2)
        # with the operand order flipped — ~17 s of XLA:CPU compile for a
        # duplicate topology, kept out of the tier-1 gate budget.
        pytest.param(2, 1, marks=pytest.mark.slow),
    ],
)
def test_cnot_everywhere(q1, q2):
    n = 6
    dense = random_state(n, seed=q1 * 7 + q2)
    got = run_gathered(
        n,
        lambda ctx, d: apply_gate_2q_sharded(ctx, from_dense(ctx, d), gates.CNOT, q1, q2),
        dense,
    )
    want = to_complex(sv.apply_gate_2q(dense, gates.CNOT, q1, q2))
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_crz_global_pair():
    n = 5
    dense = random_state(n, seed=3)
    gate = gates.crz(0.9)
    got = run_gathered(
        n,
        lambda ctx, d: apply_gate_2q_sharded(ctx, from_dense(ctx, d), gate, 1, 0),
        dense,
    )
    want = to_complex(sv.apply_gate_2q(dense, gate, 1, 0))
    np.testing.assert_allclose(got, want, atol=1e-5)


@pytest.mark.parametrize("qubit", [0, 1, 3, 4])
def test_expect_z(qubit):
    n = 5
    dense = random_state(n, seed=qubit + 20)
    got = run_scalar(
        n, lambda ctx, d: expect_z_sharded(ctx, from_dense(ctx, d), qubit), dense
    )
    want = np.asarray(sv.expect_z(dense, qubit))
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_expect_z_all():
    n = 6
    dense = random_state(n, seed=42)
    got = run_scalar(
        n, lambda ctx, d: expect_z_all_sharded(ctx, from_dense(ctx, d)), dense
    )
    want = np.asarray(sv.expect_z_all(dense))
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_sharded_hea_forward_matches_dense():
    """Full pipeline: angle encode → L-layer HEA → ⟨Z⟩ all qubits."""
    n, layers = 6, 2
    params = init_ansatz_params(jax.random.PRNGKey(0), n, layers, scale=0.3)
    x = jnp.linspace(0.1, 0.9, n)

    forward, ctx = make_sharded_forward(n, mesh8())
    assert ctx.n_global == N_GLOBAL
    got = np.asarray(forward(params, x))

    dense_state = hardware_efficient(angle_encode(x), params)
    want = np.asarray(sv.expect_z_all(dense_state))
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_sharded_forward_grad():
    """jax.grad flows through the collective choreography."""
    n = 5
    params = init_ansatz_params(jax.random.PRNGKey(1), n, 1, scale=0.2)
    x = jnp.linspace(0.2, 0.8, n)
    forward, _ = make_sharded_forward(n, mesh8())

    def loss(p):
        return jnp.sum(forward(p, x) ** 2)

    g = jax.grad(loss)(params)
    dense_loss = lambda p: jnp.sum(
        sv.expect_z_all(hardware_efficient(angle_encode(x), p)) ** 2
    )
    g_dense = jax.grad(dense_loss)(params)
    for k in g:
        np.testing.assert_allclose(np.asarray(g[k]), np.asarray(g_dense[k]), atol=1e-4)


@pytest.mark.slow
def test_sharded_beyond_dense_22q():
    """The past-the-dense-wall claim (module docstring: "extends the
    ceiling"; reference ROADMAP.md:86 — beyond ~20 qubits, distribute):
    a 22-qubit, 1-layer HEA forward on the 8-way-sharded engine, checked
    against the dense engine — which the CPU host can still hold as an
    oracle (2^22 amps ≈ 33 MB; a real chip could not hold the training
    tape at this width, the host forward can). Exercises the full
    global-qubit choreography at a width no other test reaches."""
    n, layers = 22, 1
    params = init_ansatz_params(jax.random.PRNGKey(5), n, layers, scale=0.2)
    x = jnp.linspace(0.05, 0.95, n)

    forward, _ = make_sharded_forward(n, mesh8())
    got = np.asarray(forward(params, x))

    dense_state = hardware_efficient(angle_encode(x), params)
    want = np.asarray(sv.expect_z_all(dense_state))
    # atol scales with width: summing 2^22 f32 products accumulates
    # ~sqrt(N)·eps ≈ 2e-4 of rounding in EACH engine's readout (the
    # n=6 tests use 1e-4; this is the same agreement, wider state).
    np.testing.assert_allclose(got, want, atol=2e-3)


@pytest.mark.slow
def test_sharded_22q_federated_round():
    """One real federated training round at 22 qubits on the (1 client
    device × 8 sv) mesh: the >20-qubit regime composed with the
    federated runtime — loss is finite and the round updates params."""
    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.fed.round import make_fed_round, shard_client_data
    from qfedx_tpu.models.vqc_sharded import make_sharded_vqc_classifier
    from qfedx_tpu.parallel.mesh import fed_mesh

    n, clients, samples = 22, 2, 2
    model = make_sharded_vqc_classifier(n, sv_size=8, n_layers=1, num_classes=2)
    mesh = fed_mesh(sv_size=8, num_client_devices=1)
    cfg = FedConfig(local_epochs=1, batch_size=2, learning_rate=0.1,
                    optimizer="adam")
    rng = np.random.default_rng(3)
    cx = rng.uniform(0, 1, (clients, samples, n)).astype(np.float32)
    cy = (cx[..., 0] > 0.5).astype(np.int32)
    cm = np.ones((clients, samples), dtype=np.float32)
    round_fn = make_fed_round(model, cfg, mesh, num_clients=clients)
    sx, sy, sm = shard_client_data(mesh, cx, cy, jnp.asarray(cm))
    params = model.init(jax.random.PRNGKey(0))
    new_params, stats = round_fn(params, sx, sy, sm, jax.random.PRNGKey(1))
    assert np.isfinite(float(stats.mean_loss))
    moved = any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(new_params))
    )
    assert moved, "round did not update parameters"
