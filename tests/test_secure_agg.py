"""Secure-aggregation mask constructions (reference ROADMAP.md:52-55,137-138).

The load-bearing property for both pair graphs is EXACT cancellation under
the cohort-wide sum (the roadmap's own acceptance test, ROADMAP.md:55,61) —
for the ring graph additionally at the 256-client BASELINE config-5 scale,
where the complete graph's O(C²) PRG samples per round are prohibitive and
the ring's O(k·C) must hold the property at the same tolerance.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from qfedx_tpu.fed.secure_agg import client_mask, ring_mask


def small_template():
    return {"w": jnp.zeros((63, 2), jnp.float32), "b": jnp.zeros((5,), jnp.float32)}


def _total_and_rows(mask_fn, num_clients, part):
    masks = jax.vmap(mask_fn)(jnp.arange(num_clients))
    total = jax.tree.map(lambda m: jnp.sum(m, axis=0), masks)
    return total, masks


def _participation(num_clients, kind, seed=0):
    if kind == "all":
        return jnp.ones((num_clients,), jnp.float32)
    if kind == "none":
        return jnp.zeros((num_clients,), jnp.float32)
    if kind == "one":
        return jnp.zeros((num_clients,), jnp.float32).at[num_clients // 2].set(1.0)
    if kind == "two":
        return (
            jnp.zeros((num_clients,), jnp.float32).at[0].set(1.0).at[num_clients - 1].set(1.0)
        )
    rng = np.random.default_rng(seed)
    return jnp.asarray((rng.random(num_clients) < 0.6).astype(np.float32))


@pytest.mark.parametrize("kind", ["all", "none", "one", "two", "random"])
@pytest.mark.parametrize("neighbors", [1, 2, 5])
def test_ring_masks_cancel(kind, neighbors):
    num_clients = 16
    part = _participation(num_clients, kind)
    key = jax.random.PRNGKey(3)
    tmpl = small_template()
    total, masks = _total_and_rows(
        lambda i: ring_mask(key, i, num_clients, tmpl, part, 4.0, neighbors),
        num_clients,
        part,
    )
    for leaf in jax.tree.leaves(total):
        np.testing.assert_allclose(np.asarray(leaf), 0.0, atol=1e-4)
    # Non-participants contribute nothing; participants (cohort ≥ 2) are
    # actually masked — the update never travels in the clear.
    row_norms = np.asarray(
        jax.vmap(lambda m: sum(jnp.sum(jnp.abs(x)) for x in jax.tree.leaves(m)))(masks)
    )
    np.testing.assert_allclose(row_norms[np.asarray(part) == 0.0], 0.0, atol=1e-6)
    if float(jnp.sum(part)) >= 2:
        assert np.all(row_norms[np.asarray(part) == 1.0] > 1.0)


def test_ring_mask_cohort_of_one_degenerates_to_no_mask():
    """A lone participant has no peer to hide behind — mask must be zero,
    not a self-cancelling pair (which would add noise that never cancels)."""
    part = _participation(8, "one")
    tmpl = small_template()
    m = ring_mask(jax.random.PRNGKey(0), 4, 8, tmpl, part, 2.0, 1)
    for leaf in jax.tree.leaves(m):
        np.testing.assert_allclose(np.asarray(leaf), 0.0, atol=1e-7)


def test_ring_masks_cancel_at_256_clients_fast():
    """BASELINE config-5 scale: cancellation at C=256 in seconds (the
    VERDICT round-1 criterion; the complete graph needs 65,536 PRG tree
    samples here, the ring needs 512)."""
    num_clients = 256
    part = _participation(num_clients, "random", seed=7)
    key = jax.random.PRNGKey(11)
    tmpl = small_template()
    t0 = time.perf_counter()
    total, _ = _total_and_rows(
        lambda i: ring_mask(key, i, num_clients, tmpl, part, 3.0, 1),
        num_clients,
        part,
    )
    jax.block_until_ready(total)
    elapsed = time.perf_counter() - t0
    for leaf in jax.tree.leaves(total):
        np.testing.assert_allclose(np.asarray(leaf), 0.0, atol=5e-4)
    assert elapsed < 30.0, f"ring masks took {elapsed:.1f}s at 256 clients"


def test_pairwise_and_ring_agree_on_the_aggregate():
    """Both graphs perturb individual contributions but leave the sum
    untouched, so summed masks from either construction vanish identically."""
    num_clients = 8
    part = _participation(num_clients, "all")
    key = jax.random.PRNGKey(5)
    tmpl = small_template()
    total_ring, _ = _total_and_rows(
        lambda i: ring_mask(key, i, num_clients, tmpl, part, 2.0, 2),
        num_clients,
        part,
    )
    total_pair, _ = _total_and_rows(
        lambda i: client_mask(key, i, num_clients, tmpl, part, 2.0),
        num_clients,
        part,
    )
    for lr, lp in zip(jax.tree.leaves(total_ring), jax.tree.leaves(total_pair)):
        np.testing.assert_allclose(np.asarray(lr), 0.0, atol=1e-4)
        np.testing.assert_allclose(np.asarray(lp), 0.0, atol=1e-4)


def test_ring_neighbors_exceeding_cohort_still_cancel():
    """neighbors ≥ cohort size wraps hops onto self-edges (coefficient 0)
    and repeated rotations (independent keys per hop) — still cancels."""
    num_clients = 6
    part = _participation(num_clients, "two")  # cohort of 2, neighbors 4
    key = jax.random.PRNGKey(9)
    tmpl = small_template()
    total, _ = _total_and_rows(
        lambda i: ring_mask(key, i, num_clients, tmpl, part, 2.0, 4),
        num_clients,
        part,
    )
    for leaf in jax.tree.leaves(total):
        np.testing.assert_allclose(np.asarray(leaf), 0.0, atol=1e-4)
