"""MPS engine: safe_svd gradients, dense-engine equivalence, >20q scale.

Oracle for circuit equivalence: the per-gate dense engine
(ops.statevector) running the SAME real-amplitudes circuit (RY + CNOT
line). At full bond dimension (χ ≥ 2^{n/2}) the MPS is exact, so forward
AND gradients must agree with the dense simulation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from qfedx_tpu.models.vqc_mps import make_mps_classifier
from qfedx_tpu.ops import gates, mps, statevector as sv
from qfedx_tpu.ops.linalg import safe_svd
from qfedx_tpu.circuits.encoders import angle_encode


# --- safe_svd ---------------------------------------------------------------


def test_safe_svd_matches_stock_vjp_on_separated_spectrum():
    """Where the stock SVD gradient is well-defined, safe_svd must agree."""
    rng = np.random.default_rng(0)
    # Random matrix + strong distinct diagonal → well-separated spectrum.
    m = jnp.asarray(
        0.2 * rng.normal(size=(6, 4))
        + np.pad(np.diag([5.0, 3.0, 2.0, 1.0]), ((0, 2), (0, 0))),
        dtype=jnp.float32,
    )
    w_u = jnp.asarray(rng.normal(size=(6, 4)), dtype=jnp.float32)
    w_s = jnp.asarray(rng.normal(size=(4,)), dtype=jnp.float32)
    w_v = jnp.asarray(rng.normal(size=(4, 4)), dtype=jnp.float32)

    def loss_safe(m_):
        u, s, vh = safe_svd(m_)
        # Gauge-invariant-enough weighting: squares kill the sign gauge.
        return (
            jnp.sum(w_u * u * u) + jnp.sum(w_s * s) + jnp.sum(w_v * vh * vh)
        )

    def loss_stock(m_):
        u, s, vh = jnp.linalg.svd(m_, full_matrices=False)
        return (
            jnp.sum(w_u * u * u) + jnp.sum(w_s * s) + jnp.sum(w_v * vh * vh)
        )

    g1 = jax.grad(loss_safe)(m)
    g2 = jax.grad(loss_stock)(m)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-3)


def test_safe_svd_finite_at_rank_deficiency():
    """Rank-1 input (a product state through a CNOT) → finite gradients
    where the stock VJP divides by zero."""
    a = jnp.array([[1.0], [0.5]])
    m = (a @ a.T)  # rank 1, 2x2

    def loss(m_):
        u, s, vh = safe_svd(m_)
        rec = (u * s[None, :]) @ vh
        return jnp.sum(rec * jnp.array([[1.0, 2.0], [3.0, 4.0]]))

    g = jax.grad(loss)(m)
    assert np.all(np.isfinite(np.asarray(g)))
    # Reconstruction ≡ identity ⇒ gradient ≈ the weight matrix.
    np.testing.assert_allclose(
        np.asarray(g), np.array([[1.0, 2.0], [3.0, 4.0]]), atol=1e-3
    )


def test_safe_svd_reconstruction_gradient_rectangular():
    rng = np.random.default_rng(1)
    m = jnp.asarray(rng.normal(size=(8, 4)), dtype=jnp.float32)
    w = jnp.asarray(rng.normal(size=(8, 4)), dtype=jnp.float32)

    def loss(m_):
        u, s, vh = safe_svd(m_)
        return jnp.sum(w * ((u * s[None, :]) @ vh))

    np.testing.assert_allclose(
        np.asarray(jax.grad(loss)(m)), np.asarray(w), atol=1e-3
    )


# --- MPS vs dense oracle ----------------------------------------------------


def _dense_real_amplitudes_z(ry_params: jnp.ndarray, xi: jnp.ndarray):
    """Dense-engine oracle of the EXACT circuit models.vqc_mps runs."""
    state = angle_encode(xi)  # RY(π·f) product state, real
    n_layers, n = ry_params.shape
    for layer in range(n_layers):
        for q in range(n):
            state = sv.apply_gate(state, gates.ry(ry_params[layer, q]), q)
        for q in range(n - 1):
            state = sv.apply_gate_2q(state, gates.CNOT, q, q + 1)
    return sv.expect_z_all(state)


def _mps_z(ry_params: jnp.ndarray, xi: jnp.ndarray, chi: int):
    from qfedx_tpu.models.vqc_mps import _ry_mats

    amps = _ry_mats(xi * jnp.pi)[:, :, 0]
    state = mps.product_mps(amps, chi)
    for layer in range(ry_params.shape[0]):
        state = mps.apply_1q_all(state, _ry_mats(ry_params[layer]))
        state = mps.apply_cnot_chain(state)
    return mps.expect_z_all(state)


@pytest.mark.parametrize("n,layers", [(4, 1), (6, 2)])
def test_mps_exact_at_full_bond_dim(n, layers):
    rng = np.random.default_rng(2)
    ry = jnp.asarray(rng.normal(scale=0.8, size=(layers, n)), dtype=jnp.float32)
    xi = jnp.asarray(rng.uniform(0, 1, (n,)), dtype=jnp.float32)
    chi = 2 ** (n // 2)  # exact
    got = _mps_z(ry, xi, chi)
    want = _dense_real_amplitudes_z(ry, xi)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4)


def test_mps_gradients_match_dense_at_full_bond_dim():
    n, layers, chi = 4, 2, 4
    rng = np.random.default_rng(3)
    ry = jnp.asarray(rng.normal(scale=0.8, size=(layers, n)), dtype=jnp.float32)
    xi = jnp.asarray(rng.uniform(0, 1, (n,)), dtype=jnp.float32)
    w = jnp.asarray(rng.normal(size=(n,)), dtype=jnp.float32)

    g_mps = jax.grad(lambda p: jnp.sum(w * _mps_z(p, xi, chi)))(ry)
    g_dense = jax.grad(lambda p: jnp.sum(w * _dense_real_amplitudes_z(p, xi)))(ry)
    np.testing.assert_allclose(np.asarray(g_mps), np.asarray(g_dense), atol=2e-3)


def test_truncation_is_sane():
    """χ=2 at n=8: runs, finite, ⟨Z⟩ within [−1, 1]."""
    rng = np.random.default_rng(4)
    ry = jnp.asarray(rng.normal(scale=0.8, size=(2, 8)), dtype=jnp.float32)
    xi = jnp.asarray(rng.uniform(0, 1, (8,)), dtype=jnp.float32)
    z = np.asarray(_mps_z(ry, xi, chi=2))
    assert np.all(np.isfinite(z))
    assert np.all(np.abs(z) <= 1.0 + 1e-5)
    # Gradients at heavy truncation stay finite (safe_svd's whole point).
    g = jax.grad(lambda p: jnp.sum(_mps_z(p, xi, 2)))(ry)
    assert np.all(np.isfinite(np.asarray(g)))


def test_beyond_dense_scale_28_qubits():
    """28 qubits — a 4 GB statevector if dense; tiny as an MPS."""
    n, chi = 28, 8
    rng = np.random.default_rng(5)
    ry = jnp.asarray(rng.normal(scale=0.3, size=(1, n)), dtype=jnp.float32)
    xi = jnp.asarray(rng.uniform(0, 1, (n,)), dtype=jnp.float32)
    z = np.asarray(_mps_z(ry, xi, chi))
    assert z.shape == (n,)
    assert np.all(np.isfinite(z))
    assert np.all(np.abs(z) <= 1.0 + 1e-5)


# --- the Model rides the federated harness ----------------------------------


def test_mps_model_federated_round():
    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.fed.round import client_mesh, make_fed_round, shard_client_data

    n_qubits, clients, samples = 4, 4, 8
    model = make_mps_classifier(n_qubits, n_layers=1, num_classes=2, bond_dim=4)
    cfg = FedConfig(local_epochs=1, batch_size=4, learning_rate=0.1,
                    optimizer="adam")
    mesh = client_mesh(num_devices=4)
    round_fn = make_fed_round(model, cfg, mesh, num_clients=clients)

    rng = np.random.default_rng(6)
    cx = rng.uniform(0, 1, (clients, samples, n_qubits)).astype(np.float32)
    cy = rng.integers(0, 2, (clients, samples)).astype(np.int32)
    cm = np.ones((clients, samples), dtype=np.float32)
    scx, scy, scm = shard_client_data(mesh, cx, cy, jnp.asarray(cm))

    params = model.init(jax.random.PRNGKey(0))
    new_params, stats = round_fn(params, scx, scy, scm, jax.random.PRNGKey(1))
    leaves = jax.tree.leaves(new_params)
    assert all(bool(jnp.all(jnp.isfinite(l))) for l in leaves)
    # Parameters actually moved.
    moved = jax.tree.map(
        lambda a, b: float(jnp.max(jnp.abs(a - b))), params, new_params
    )
    assert max(jax.tree.leaves(moved)) > 0.0
