"""Fused whole-circuit kernel vs the per-gate dense engine (interpret mode).

The fused kernel (ops.fused_hea) must be a pure performance routing: the
same circuit — angle encoding → L × [rot_zx + CNOT ring] → ⟨Z_k⟩ — so
forward values AND gradients must match the tensordot engine that the
rest of the framework (and these tests' oracle) uses.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import qfedx_tpu.ops.fused_hea as fh
from qfedx_tpu.circuits.ansatz import hardware_efficient, init_ansatz_params
from qfedx_tpu.circuits.encoders import angle_encode
from qfedx_tpu.ops.statevector import expect_z_all


@pytest.fixture(autouse=True)
def interpret_mode():
    old = fh._INTERPRET
    fh._INTERPRET = True  # no TPU in the test environment
    yield
    fh._INTERPRET = old


def _dense_zexp(rx, rz, x):
    """Oracle: per-gate engine, identical circuit."""

    def one(xi):
        state = hardware_efficient(angle_encode(xi), {"rx": rx, "rz": rz})
        return expect_z_all(state)

    return jax.vmap(one)(x)


def _fused_zexp(rx, rz, x, n, layers):
    enc = jax.vmap(lambda xi: angle_encode(xi).re.reshape(-1))(x)
    return fh.hea_zexp(rx, rz, enc, n, layers)


def _setup(n, layers, batch, seed=0):
    params = init_ansatz_params(jax.random.PRNGKey(seed), n, layers, scale=0.7)
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.uniform(0, 1, (batch, n)), dtype=jnp.float32)
    return params["rx"], params["rz"], x


# n=8 puts every qubit except qubit 0 in the lane dim (R=2 rows); n=10
# exercises a real row/lane mix (and ragged batch → padding path).
@pytest.mark.parametrize("n,layers,batch", [(8, 2, 4), (10, 3, 5)])
def test_forward_matches_dense(n, layers, batch):
    rx, rz, x = _setup(n, layers, batch)
    got = _fused_zexp(rx, rz, x, n, layers)
    want = _dense_zexp(rx, rz, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


@pytest.mark.parametrize("n,layers,batch", [(8, 2, 3), (10, 2, 4)])
def test_gradients_match_dense(n, layers, batch):
    """Fused adjoint backward ≡ jax.grad through the per-gate engine."""
    rx, rz, x = _setup(n, layers, batch, seed=1)
    w = jnp.asarray(
        np.random.default_rng(2).normal(size=(batch, n)), dtype=jnp.float32
    )

    def loss_fused(rx_, rz_):
        return jnp.sum(w * _fused_zexp(rx_, rz_, x, n, layers))

    def loss_dense(rx_, rz_):
        return jnp.sum(w * _dense_zexp(rx_, rz_, x))

    np.testing.assert_allclose(
        float(loss_fused(rx, rz)), float(loss_dense(rx, rz)), atol=1e-5
    )
    gf = jax.grad(loss_fused, argnums=(0, 1))(rx, rz)
    gd = jax.grad(loss_dense, argnums=(0, 1))(rx, rz)
    np.testing.assert_allclose(np.asarray(gf[0]), np.asarray(gd[0]), atol=2e-4)
    np.testing.assert_allclose(np.asarray(gf[1]), np.asarray(gd[1]), atol=2e-4)


@pytest.mark.parametrize("n,layers,batch", [(8, 2, 3), (10, 2, 4)])
def test_input_gradients_match_dense(n, layers, batch):
    """The enc cotangent from the adjoint sweep gives true grad-wrt-x:
    the fused path must agree with the XLA path for input gradients too
    (round-2 advisor item: it used to silently return zeros)."""
    rx, rz, x = _setup(n, layers, batch, seed=4)
    w = jnp.asarray(
        np.random.default_rng(5).normal(size=(batch, n)), dtype=jnp.float32
    )

    def loss_fused(x_):
        return jnp.sum(w * _fused_zexp(rx, rz, x_, n, layers))

    def loss_dense(x_):
        return jnp.sum(w * _dense_zexp(rx, rz, x_))

    gf = jax.grad(loss_fused)(x)
    gd = jax.grad(loss_dense)(x)
    assert float(jnp.max(jnp.abs(gd))) > 1e-3  # oracle gradient is nonzero
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gd), atol=2e-4)


def test_model_fused_path_matches_default(monkeypatch):
    """make_vqc_classifier with QFEDX_FUSED=1 ≡ the default path, end to
    end through the Model.apply contract (logits, not just ⟨Z⟩)."""
    from qfedx_tpu.models.vqc import make_vqc_classifier

    n, layers, batch = 8, 2, 6
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.uniform(0, 1, (batch, n)), dtype=jnp.float32)

    monkeypatch.delenv("QFEDX_FUSED", raising=False)
    base = make_vqc_classifier(n_qubits=n, n_layers=layers, num_classes=2)
    params = base.init(jax.random.PRNGKey(0))
    want = base.apply(params, x)

    monkeypatch.setenv("QFEDX_FUSED", "1")
    fused = make_vqc_classifier(n_qubits=n, n_layers=layers, num_classes=2)
    got = fused.apply(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


# --- data-reuploading variant (BASELINE config 4) --------------------------


def _dense_reup_zexp(params, x):
    from qfedx_tpu.circuits.ansatz import data_reuploading

    def one(xi):
        return expect_z_all(data_reuploading(xi, params))

    return jax.vmap(one)(x)


def _fused_reup_zexp(params, x, n, layers):
    ang = (
        params["enc_w"][None] * (x[:, None, :] * jnp.pi) + params["enc_b"][None]
    ).reshape(x.shape[0], layers * n)
    return fh.hea_reupload_zexp(params["rx"], params["rz"], ang, n, layers)


def _setup_reup(n, layers, batch, seed=0):
    from qfedx_tpu.circuits.ansatz import init_reuploading_params

    params = init_reuploading_params(
        jax.random.PRNGKey(seed), n, layers, scale=0.4
    )
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.uniform(0, 1, (batch, n)), dtype=jnp.float32)
    return params, x


@pytest.mark.parametrize("n,layers,batch", [(8, 2, 3), (10, 2, 4)])
def test_reupload_forward_matches_dense(n, layers, batch):
    params, x = _setup_reup(n, layers, batch)
    got = _fused_reup_zexp(params, x, n, layers)
    want = _dense_reup_zexp(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


@pytest.mark.parametrize("n,layers,batch", [(8, 2, 3)])
def test_reupload_gradients_match_dense(n, layers, batch):
    """Fused adjoint backward ≡ jax.grad through the dense engine for ALL
    parameter leaves — including enc_w/enc_b/x, which chain through the
    kernel's per-sample angle cotangent."""
    params, x = _setup_reup(n, layers, batch, seed=2)
    w = jnp.asarray(
        np.random.default_rng(3).normal(size=(batch, n)), dtype=jnp.float32
    )

    def loss_fused(params_, x_):
        return jnp.sum(w * _fused_reup_zexp(params_, x_, n, layers))

    def loss_dense(params_, x_):
        return jnp.sum(w * _dense_reup_zexp(params_, x_))

    np.testing.assert_allclose(
        float(loss_fused(params, x)), float(loss_dense(params, x)), atol=1e-5
    )
    gf, gfx = jax.grad(loss_fused, argnums=(0, 1))(params, x)
    gd, gdx = jax.grad(loss_dense, argnums=(0, 1))(params, x)
    for k in ("rx", "rz", "enc_w", "enc_b"):
        np.testing.assert_allclose(
            np.asarray(gf[k]), np.asarray(gd[k]), atol=3e-4, err_msg=k
        )
    np.testing.assert_allclose(np.asarray(gfx), np.asarray(gdx), atol=3e-4)


def test_reupload_model_fused_matches_default(monkeypatch):
    """make_vqc_classifier(encoding='reupload') with QFEDX_FUSED=1 ≡ the
    default dense path end to end (the config-4 flagship route)."""
    from qfedx_tpu.models.vqc import make_vqc_classifier

    n, layers, batch = 8, 2, 5
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.uniform(0, 1, (batch, n)), dtype=jnp.float32)

    monkeypatch.delenv("QFEDX_FUSED", raising=False)
    base = make_vqc_classifier(n_qubits=n, n_layers=layers, num_classes=2,
                               encoding="reupload")
    params = base.init(jax.random.PRNGKey(0))
    want = base.apply(params, x)

    monkeypatch.setenv("QFEDX_FUSED", "1")
    fused = make_vqc_classifier(n_qubits=n, n_layers=layers, num_classes=2,
                                encoding="reupload")
    got = fused.apply(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_routing(monkeypatch):
    monkeypatch.delenv("QFEDX_FUSED", raising=False)
    assert not fh.fused_eligible(7)  # needs a full 128-lane dim
    assert fh.fused_eligible(8)
    assert fh.fused_eligible(16)
    assert not fh.fused_eligible(17)  # compile-time cap (see MAX_QUBITS)

    # r04: auto routing retired — the kernel is opt-in only (the XLA slab
    # engine measured faster at every eligible width; docs/PERF.md §4).
    assert not fh.fused_enabled(16)
    assert not fh.fused_enabled(fh.AUTO_MIN_QUBITS - 1)

    monkeypatch.setenv("QFEDX_FUSED", "1")
    assert fh.fused_enabled(8)
    assert fh.fused_enabled(16)
    assert not fh.fused_enabled(17)  # force cannot override eligibility
    monkeypatch.setenv("QFEDX_FUSED", "0")
    assert not fh.fused_enabled(16)
