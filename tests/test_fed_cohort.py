"""Config 5's actual cohort: a full 256-client federated round (r07).

VERDICT r05 missing #1: BASELINE config 5 names 256 clients (reference
ROADMAP.md:88-89's scale-out phase); the ring secure-agg *mask
cancellation* was tested at 256, but nothing ever drove a 256-client
round through the round program itself. This does — 256 clients as 8×32
client blocks on the 8-device virtual mesh, through the scanned
``make_fed_rounds`` dispatch (the trainer's optimized path), with the
config-5 composition on: ring secure aggregation + client sampling.
The single-chip (block = 256) timing row lives in bench.py
(``_bench_fed256``) and lands in BENCH_r07 on the real chip.
"""

import jax
import jax.numpy as jnp
import numpy as np

from qfedx_tpu.fed.config import FedConfig
from qfedx_tpu.fed.round import (
    client_mesh,
    make_fed_round,
    make_fed_rounds,
    shard_client_data,
)
from qfedx_tpu.models.vqc import make_vqc_classifier

NUM_CLIENTS = 256


def _cohort_data(n_q=3, samples=4, seed=0):
    rng = np.random.default_rng(seed)
    cx = rng.uniform(0, 1, (NUM_CLIENTS, samples, n_q)).astype(np.float32)
    # Learnable signal so the round has a real gradient to aggregate.
    cy = (cx.mean(axis=2) > 0.5).astype(np.int32)
    cm = np.ones((NUM_CLIENTS, samples), dtype=np.float32)
    return cx, cy, cm


def test_256_client_round_on_virtual_mesh():
    """One scanned dispatch of 2 rounds × 256 clients (32-client blocks on
    each of 8 devices) with ring secure-agg + 50% client sampling: the
    program runs, aggregates a plausible participant subset, and moves
    the global parameters; a follow-up chunk continues from the result
    (the trainer's chunked-dispatch contract)."""
    n_q = 3
    cfg = FedConfig(
        local_epochs=1,
        batch_size=4,
        learning_rate=0.1,
        optimizer="adam",
        client_fraction=0.5,
        secure_agg=True,
        secure_agg_mode="ring",
    )
    model = make_vqc_classifier(n_qubits=n_q, n_layers=1, num_classes=2)
    mesh = client_mesh()
    assert NUM_CLIENTS % mesh.shape["clients"] == 0  # 8 × 32 blocks
    cx, cy, cm = _cohort_data(n_q)
    scx, scy, scm = shard_client_data(mesh, cx, cy, jnp.asarray(cm))
    params0 = model.init(jax.random.PRNGKey(0))
    rounds_fn = make_fed_rounds(
        model, cfg, mesh, num_clients=NUM_CLIENTS, rounds_per_call=2
    )
    base = jax.random.PRNGKey(1)
    params1, stats = rounds_fn(params0, scx, scy, scm, base, 0)

    # Stats per scanned round: a real subset participated, weights summed.
    n_part = np.asarray(stats.num_participants)
    assert n_part.shape == (2,)
    assert np.all(n_part > 0) and np.all(n_part <= NUM_CLIENTS)
    # ~50% sampling of 256: far from both edges (participation_mask is
    # deterministic in the round key; this pins plausibility, not luck).
    assert np.all(n_part > 64) and np.all(n_part < 192)
    assert np.all(np.isfinite(np.asarray(stats.mean_loss)))
    assert float(stats.total_weight[0]) > 0

    # Parameters moved, stayed finite, and the next chunk continues.
    moved = False
    for a, b in zip(jax.tree.leaves(params0), jax.tree.leaves(params1)):
        assert np.all(np.isfinite(np.asarray(b)))
        moved = moved or not np.allclose(np.asarray(a), np.asarray(b))
    assert moved
    params2, stats2 = rounds_fn(params1, scx, scy, scm, base, 2)
    assert np.all(np.isfinite(np.asarray(stats2.mean_loss)))


def test_256_client_scanned_equals_sequential_rounds():
    """Key-derivation parity at the cohort scale: the 2-round scan equals
    two sequential make_fed_round calls with fold_in(base, r) keys — the
    256-block program is bit-stable under the dispatch restructure."""
    n_q = 3
    cfg = FedConfig(local_epochs=1, batch_size=4, learning_rate=0.1)
    model = make_vqc_classifier(n_qubits=n_q, n_layers=1, num_classes=2)
    mesh = client_mesh()
    cx, cy, cm = _cohort_data(n_q, seed=3)
    scx, scy, scm = shard_client_data(mesh, cx, cy, jnp.asarray(cm))
    params0 = model.init(jax.random.PRNGKey(0))
    base = jax.random.PRNGKey(5)

    rounds_fn = make_fed_rounds(
        model, cfg, mesh, num_clients=NUM_CLIENTS, rounds_per_call=2
    )
    p_scan, _ = rounds_fn(params0, scx, scy, scm, base, 0)

    one = make_fed_round(model, cfg, mesh, num_clients=NUM_CLIENTS)
    p_seq = params0
    for rnd in range(2):
        p_seq, _ = one(p_seq, scx, scy, scm, jax.random.fold_in(base, rnd))
    for a, b in zip(jax.tree.leaves(p_scan), jax.tree.leaves(p_seq)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6, rtol=0
        )
