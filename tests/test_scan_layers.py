"""Scan-over-fused-layers (ops/fuse.py r17) ≡ fused ≡ per-gate execution.

Parity is pinned at the same four altitudes as tests/test_fuse.py:

- pass: ``fuse_ops_stacked`` collapses a layer-stacked HEA trace into
  the expected super-gate body (row matrix + ctrl'd lane matrix + wrap
  CNOT at narrow rows; row pairs + a row permutation past the row-matrix
  cap) and the cross-layer boundary merge hoists layer 0's head;
- primitives: the r17 engine ops (row matrix, row permutation, row-/
  lane-controlled matrix pairs) ≡ their gate-sequence definitions on
  dense and batched states, shared and grouped;
- ops: one ``apply_scan`` over the stacked program ≡ the gate-by-gate
  reference layer by layer — dense, batched with per-client (G,…) and
  per-sample (B,…) coefficient stacks;
- model: QFEDX_SCAN_LAYERS=1 ≡ =0 logits AND gradients for HEA and
  reupload on the batched engine and the client-folded path, f32
  (≤ 2e-5) and bf16 (rounding-bounded), with circuit-level Kraus noise
  interleaved (channels are scan barriers: the per-layer loop is kept
  and trajectories coincide sample-for-sample), and on the sharded
  engine's local runs.

All tests pin the TPU production formulation (flip gate form + matmul
lanes) so the scanned slab programs are covered on the CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from qfedx_tpu.circuits import ansatz
from qfedx_tpu.ops import batched as bt
from qfedx_tpu.ops import fuse, gates
from qfedx_tpu.ops import statevector as sv
from qfedx_tpu.ops.cpx import CArray, from_complex, to_complex

N = 10  # smallest slab width


@pytest.fixture
def tpu_form(monkeypatch):
    monkeypatch.setenv("QFEDX_GATE_FORM", "flip")
    monkeypatch.setenv("QFEDX_SLAB_LANES", "matmul")
    monkeypatch.setenv("QFEDX_FUSE", "1")
    monkeypatch.setenv("QFEDX_SCAN_LAYERS", "1")


def _rand_state(n: int, seed: int = 0) -> CArray:
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(2,) * n) + 1j * rng.normal(size=(2,) * n)
    return from_complex(x / np.linalg.norm(x))


def _stacks(n, n_layers, seed=0):
    rng = np.random.default_rng(seed)
    rx = jnp.asarray(rng.uniform(-2, 2, (n_layers, n)), dtype=jnp.float32)
    rz = jnp.asarray(rng.uniform(-2, 2, (n_layers, n)), dtype=jnp.float32)
    return rx, rz


def _ref_layers(state, n, rx, rz):
    for l in range(rx.shape[0]):
        state = fuse.apply_ops_unfused(
            state, ansatz.hea_layer_ops(n, rx[l], rz[l])
        )
    return state


# --- the pin and the gates ---------------------------------------------------


def test_scan_pin_rejects_invalid(monkeypatch):
    monkeypatch.setenv("QFEDX_SCAN_LAYERS", "banana")
    with pytest.raises(ValueError, match="QFEDX_SCAN_LAYERS"):
        fuse.scan_enabled()


@pytest.mark.parametrize(
    "pin,expect", [("1", True), ("on", True), ("0", False), ("off", False)]
)
def test_scan_pin_values(monkeypatch, pin, expect):
    monkeypatch.setenv("QFEDX_SCAN_LAYERS", pin)
    assert fuse.scan_enabled() is expect


def test_scan_gates_on_fuse_width_and_depth(monkeypatch):
    """The scan route needs an active fusion route AND ≥ 2 layers —
    QFEDX_SCAN_LAYERS=1 alone must not engage below the slab or with
    fusion pinned off (scan is built ON the fused forms)."""
    monkeypatch.setenv("QFEDX_SCAN_LAYERS", "1")
    monkeypatch.setenv("QFEDX_FUSE", "1")
    assert fuse.scan_active(N, 2) is True
    assert fuse.scan_active(N, 1) is False
    assert fuse.scan_active(8, 2) is False
    monkeypatch.setenv("QFEDX_FUSE", "0")
    assert fuse.scan_active(N, 2) is False


def test_scan_off_never_builds_stacked_program(monkeypatch, tpu_form):
    """QFEDX_SCAN_LAYERS=0 reproduces the r07 route bit-for-bit: the
    stacked pass is never entered (the r07 code path is untouched)."""
    monkeypatch.setenv("QFEDX_SCAN_LAYERS", "0")

    def boom(*a, **k):  # pragma: no cover - failure mode
        raise AssertionError("fuse_ops_stacked called with scan off")

    monkeypatch.setattr(fuse, "fuse_ops_stacked", boom)
    rx, rz = _stacks(N, 3)
    state = _rand_state(N)
    ansatz.hardware_efficient(state, {"rx": rx, "rz": rz})


def test_build_model_scan_env_seam(monkeypatch):
    """build_model's explicit scan_layers override is undone by a later
    scan_layers=None build (the operator's pre-override pin comes back),
    but an env change BETWEEN builds — a bench _with_env lever, an
    operator export — wins over the stale baseline: restoring over it
    would silently re-route the next trace."""
    import os

    from qfedx_tpu.run import config as rc

    def cfg(scan):
        return rc.ExperimentConfig(
            model=rc.ModelConfig(scan_layers=scan)
        )

    monkeypatch.setattr(rc, "_SCAN_ENV_SAVED", [])
    monkeypatch.setenv("QFEDX_SCAN_LAYERS", "on")
    rc.build_model(cfg(True), 2)
    assert os.environ["QFEDX_SCAN_LAYERS"] == "1"
    rc.build_model(cfg(None), 2)  # follows the pin: operator state back
    assert os.environ["QFEDX_SCAN_LAYERS"] == "on"

    rc.build_model(cfg(True), 2)
    monkeypatch.setenv("QFEDX_SCAN_LAYERS", "off")  # external change
    rc.build_model(cfg(None), 2)
    assert os.environ["QFEDX_SCAN_LAYERS"] == "off", (
        "a pin set after the override must not be clobbered by the "
        "stale pre-override baseline"
    )


# --- pass-level structure ----------------------------------------------------


def test_hea_stacked_structure_narrow_rows(tpu_form):
    """At row widths within the row-matrix cap the whole L-layer HEA
    collapses to a 3-op body: row matrix, row-controlled lane-matrix
    pair (the boundary CNOT absorbed), wrap CNOT."""
    rx, rz = _stacks(N, 3)
    prog = fuse.fuse_ops_stacked(ansatz.hea_scan_ops(N, rx, rz), N, 3)
    kinds = [o.kind for o in prog.body]
    assert kinds == ["rowmat", "glane", "cnot"]
    assert prog.body[0].stacked and prog.body[1].stacked
    assert not prog.body[2].stacked
    assert prog.body[1].qubits[0] == 2  # ctrl row qubit of CNOT(2,3)
    assert prog.length == 3


def test_hea_stacked_structure_growmat(monkeypatch, tpu_form):
    """On the dispatch-bound backend the wrap CNOT merges into the next
    layer's row matrix: body [glane, growmat], layer-0 rowmat hoisted."""
    monkeypatch.setattr(fuse, "_growmat_merge_ok", lambda: True)
    rx, rz = _stacks(N, 3)
    prog = fuse.fuse_ops_stacked(ansatz.hea_scan_ops(N, rx, rz), N, 3)
    assert [o.kind for o in prog.pre] == ["rowmat"]
    assert [o.kind for o in prog.body] == ["glane", "growmat"]
    assert prog.body[1].qubits[0] == N - 1  # ctrl lane qubit


def test_hea_stacked_structure_wide_rows(monkeypatch, tpu_form):
    """Past the row-matrix cap rows fall back to pairs; the CNOT chain
    becomes ONE gather-applied row permutation on backends whose
    gather/scatter are single kernels, and stays per-gate elsewhere."""
    monkeypatch.setattr(fuse, "_ROWMAT_MAX_BITS", 1)
    monkeypatch.setattr(fuse, "_gather_ok", lambda: True)
    rx, rz = _stacks(N, 2)
    prog = fuse.fuse_ops_stacked(ansatz.hea_scan_ops(N, rx, rz), N, 2)
    kinds = [o.kind for o in prog.body]
    assert kinds.count("rowpair") == 1  # qubits (0,1)
    assert kinds.count("g1") == 1  # unpaired row qubit 2
    assert kinds.count("rowperm") == 1  # the row CNOT chain
    assert kinds.count("glane") == 1
    monkeypatch.setattr(fuse, "_gather_ok", lambda: False)
    prog2 = fuse.fuse_ops_stacked(ansatz.hea_scan_ops(N, rx, rz), N, 2)
    kinds2 = [o.kind for o in prog2.body]
    assert kinds2.count("rowperm") == 0
    assert kinds2.count("cnot") > kinds.count("cnot")


def test_stacked_trace_rejects_wrong_layer_axis(tpu_form):
    rx, rz = _stacks(N, 3)
    ops = ansatz.hea_scan_ops(N, rx, rz)
    with pytest.raises(ValueError, match="layer count"):
        fuse.fuse_ops_stacked(ops, N, 4)


# --- primitive parity --------------------------------------------------------


def test_row_matrix_primitive(tpu_form):
    """apply_row_matrix(M_B@M_A) ≡ the two row gates in sequence."""
    state = _rand_state(N, 1)
    rbits = N - 7
    A, B_ = gates.rot_zx(0.3, -0.9), gates.ry(1.2)
    ma = fuse._kron_matrix({fuse._row_pos(rbits, 0): A}, rbits)
    mb = fuse._kron_matrix({fuse._row_pos(rbits, 2): B_}, rbits)
    out = sv.apply_row_matrix(state, fuse._cmatmul(mb, ma))
    ref = sv.apply_gate(sv.apply_gate(state, A, 0), B_, 2)
    np.testing.assert_allclose(to_complex(out), to_complex(ref), atol=1e-6)


def test_row_perm_primitive(tpu_form):
    """apply_row_perm(σ-chain) ≡ the row-row CNOTs in sequence."""
    state = _rand_state(N, 2)
    rbits = N - 7
    chain = [(0, 1), (1, 2)]
    sigma = None
    for c, t in chain:
        s = fuse._row_cnot_sigma(
            fuse._row_pos(rbits, c), fuse._row_pos(rbits, t), rbits
        )
        sigma = s if sigma is None else sigma[s]
    out = sv.apply_row_perm(state, sigma)
    ref = state
    for c, t in chain:
        ref = sv.apply_cnot(ref, c, t)
    np.testing.assert_allclose(to_complex(out), to_complex(ref), atol=1e-6)
    # batched twin
    b = CArray(
        jnp.stack([state.re.reshape(-1)] * 2),
        jnp.stack([state.im.reshape(-1)] * 2),
    )
    outb = bt.apply_row_perm_b(b, N, sigma)
    np.testing.assert_allclose(
        np.asarray(outb.re[0]), np.asarray(out.re).reshape(-1), atol=1e-6
    )


def test_lane_matrix_ctrl_primitive(tpu_form):
    """apply_lane_matrix_ctrl ≡ (boundary CNOT then lane gate): branch 0
    = plain matrix, branch 1 = perm-then-matrix."""
    state = _rand_state(N, 3)
    ctrl, tgt = 2, N - 1  # CNOT(2,9): row control, lane target
    g = gates.rot_zx(0.8, 0.4)
    mt_g = fuse._lane_g1(g, sv._slab_pos(N, N - 2))
    perm = CArray(jnp.asarray(fuse._np_lane_flip(sv._slab_pos(N, tgt))), None)
    pair = CArray(
        jnp.stack([mt_g.re, fuse._cmatmul(perm, mt_g).re]),
        jnp.stack([mt_g.im, fuse._cmatmul(perm, mt_g).im]),
    )
    out = sv.apply_lane_matrix_ctrl(state, pair, ctrl)
    ref = sv.apply_cnot(state, ctrl, tgt)
    ref = sv.apply_gate(ref, g, N - 2)
    np.testing.assert_allclose(to_complex(out), to_complex(ref), atol=1e-6)


def test_row_matrix_ctrl_primitive(tpu_form):
    """apply_row_matrix_ctrl ≡ (wrap CNOT then row gate): lanes with the
    control bit set take the flipped-then-rotated branch."""
    state = _rand_state(N, 4)
    rbits = N - 7
    ctrl, tgt = N - 1, 0  # CNOT(9, 0): lane control, row target
    g = gates.rot_zx(-0.6, 1.1)
    mr = fuse._kron_matrix({fuse._row_pos(rbits, 1): g}, rbits)
    flip = fuse._sigma_matrix(
        np.arange(1 << rbits) ^ (1 << fuse._row_pos(rbits, tgt))
    )
    m_flip = fuse._cmatmul(mr, flip)
    pair = CArray(
        jnp.stack([mr.re, m_flip.re]), jnp.stack([mr.im, m_flip.im])
    )
    out = sv.apply_row_matrix_ctrl(state, pair, ctrl)
    ref = sv.apply_gate(sv.apply_cnot(state, ctrl, tgt), g, 1)
    np.testing.assert_allclose(to_complex(out), to_complex(ref), atol=1e-6)


def test_batched_primitives_grouped(tpu_form):
    """Grouped (G,…) stacks through the batched r17 primitives ≡ the
    per-row dense primitives."""
    G, S = 2, 2
    B = G * S
    rng = np.random.default_rng(5)
    re = jnp.asarray(rng.standard_normal((B, 1 << N)), dtype=jnp.float32)
    im = jnp.asarray(rng.standard_normal((B, 1 << N)), dtype=jnp.float32)
    state = CArray(re, im)
    rbits = N - 7
    th = jnp.asarray(rng.uniform(-2, 2, (G,)), dtype=jnp.float32)
    g = gates.rot_zx_batched(th, -th)  # (G,2,2)
    mr = fuse._kron_matrix({fuse._row_pos(rbits, 1): g}, rbits)  # (G,R,R)
    out = bt.apply_row_matrix_b(state, N, mr)
    for r in range(B):
        one = CArray(re[r].reshape((2,) * N), im[r].reshape((2,) * N))
        gi = r // S
        ref = sv.apply_gate(
            one, CArray(g.re[gi], g.im[gi]), 1
        )
        np.testing.assert_allclose(
            np.asarray(out.re[r]),
            np.asarray(ref.re).reshape(-1),
            atol=1e-5,
        )
    bad = CArray(jnp.zeros((3, 1 << rbits, 1 << rbits)), None)  # 3 ∤ 4
    with pytest.raises(ValueError, match="G must divide B"):
        bt.apply_row_matrix_b(state, N, bad)


def test_ctrl_primitives_validate_region(tpu_form):
    state = _rand_state(N, 6)
    pair = CArray(jnp.zeros((2, 128, 128)), None)
    with pytest.raises(ValueError, match="row qubit"):
        sv.apply_lane_matrix_ctrl(state, pair, N - 1)
    rpair = CArray(jnp.zeros((2, 8, 8)), None)
    with pytest.raises(ValueError, match="lane qubit"):
        sv.apply_row_matrix_ctrl(state, rpair, 0)


# --- ops-level parity --------------------------------------------------------


def test_scanned_hea_dense_parity(tpu_form):
    rx, rz = _stacks(N, 3, seed=1)
    state = _rand_state(N, 7)
    prog = fuse.fuse_ops_stacked(ansatz.hea_scan_ops(N, rx, rz), N, 3)
    out = fuse.apply_scan(state, N, prog)
    ref = _ref_layers(state, N, rx, rz)
    np.testing.assert_allclose(to_complex(out), to_complex(ref), atol=2e-5)


def test_scanned_growmat_dense_parity(monkeypatch, tpu_form):
    monkeypatch.setattr(fuse, "_growmat_merge_ok", lambda: True)
    rx, rz = _stacks(N, 3, seed=2)
    state = _rand_state(N, 8)
    prog = fuse.fuse_ops_stacked(ansatz.hea_scan_ops(N, rx, rz), N, 3)
    assert any(o.kind == "growmat" for o in prog.body)
    out = fuse.apply_scan(state, N, prog)
    ref = _ref_layers(state, N, rx, rz)
    np.testing.assert_allclose(to_complex(out), to_complex(ref), atol=2e-5)


def test_scanned_wide_row_dense_parity(monkeypatch, tpu_form):
    """The past-the-cap mechanisms (row pairs + rowperm gather) execute
    correctly — the cap is lowered so the wide path runs at a cheap
    width instead of a pathological-compile n ≥ 15 CPU program."""
    monkeypatch.setattr(fuse, "_ROWMAT_MAX_BITS", 1)
    monkeypatch.setattr(fuse, "_gather_ok", lambda: True)
    rx, rz = _stacks(N, 2, seed=3)
    state = _rand_state(N, 9)
    prog = fuse.fuse_ops_stacked(ansatz.hea_scan_ops(N, rx, rz), N, 2)
    assert any(o.kind == "rowperm" for o in prog.body)
    out = fuse.apply_scan(state, N, prog)
    ref = _ref_layers(state, N, rx, rz)
    np.testing.assert_allclose(to_complex(out), to_complex(ref), atol=2e-5)


def test_scanned_batched_grouped_parity(tpu_form):
    """Per-client (G,…) + per-sample (B,…) stacks ride the scan with the
    r06/r07 grouping contract intact."""
    G, S = 2, 3
    B = G * S
    L = 3
    rng = np.random.default_rng(6)
    re = jnp.asarray(rng.standard_normal((B, 1 << N)), dtype=jnp.float32)
    im = jnp.asarray(rng.standard_normal((B, 1 << N)), dtype=jnp.float32)
    state = CArray(re, im)
    rxc = jnp.asarray(rng.uniform(-2, 2, (L, G, N)), dtype=jnp.float32)
    rzc = jnp.asarray(rng.uniform(-2, 2, (L, G, N)), dtype=jnp.float32)
    enc = jnp.asarray(rng.uniform(-2, 2, (L, B, N)), dtype=jnp.float32)
    ops = [
        fuse.Op("g1", (q,), gates.ry_batched(enc[:, :, q])) for q in range(N)
    ] + ansatz.hea_scan_ops(N, rxc, rzc)
    out = fuse.apply_scan(
        state, N, fuse.fuse_ops_stacked(ops, N, L), batched=True
    )

    def one_row(r):
        st = CArray(re[r].reshape((2,) * N), im[r].reshape((2,) * N))
        g = r // S
        for l in range(L):
            for q in range(N):
                st = sv.apply_gate(
                    st, CArray(gates.ry_batched(enc[l, :, q]).re[r], None), q
                )
            for q in range(N):
                c = gates.rot_zx_batched(rxc[l, :, q], rzc[l, :, q])
                st = sv.apply_gate(st, CArray(c.re[g], c.im[g]), q)
            for q in range(N - 1):
                st = sv.apply_cnot(st, q, q + 1)
            st = sv.apply_cnot(st, N - 1, 0)
        return st

    for r in range(B):
        ref = one_row(r)
        np.testing.assert_allclose(
            np.asarray(out.re[r]), np.asarray(ref.re).reshape(-1), atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(out.im[r]), np.asarray(ref.im).reshape(-1), atol=1e-5
        )


def test_boundary_merge_masks(tpu_form):
    """Cross-layer diagonal chaining: a body bounded by masks hoists the
    layer-0 head and folds tail[l]·head[l+1] — one boundary op per
    layer — with exact parity."""
    L = 3
    rng = np.random.default_rng(7)
    th = jnp.asarray(rng.uniform(-2, 2, (L,)), dtype=jnp.float32)
    # diag(0) | g1(0) | diag(0): the row single flushes the head chain,
    # the tail diag starts a fresh one -> [mask, g1, mask] body.
    ops = [
        fuse.Op("diag1", (0,), gates.rz_diag(th)),
        fuse.Op("g1", (0,), gates.ry_batched(th)),
        fuse.Op("diag1", (0,), gates.rz_diag(2 * th)),
    ]
    # Disable the row-matrix fold so the structure is mask/g1/mask.
    import unittest.mock as mock

    with mock.patch.object(fuse, "_ROWMAT_MAX_BITS", 0):
        prog = fuse.fuse_ops_stacked(ops, N, L)
    assert [o.kind for o in prog.pre] == ["mask"]
    assert [o.kind for o in prog.body] == ["g1", "mask"]
    state = _rand_state(N, 10)
    out = fuse.apply_scan(state, N, prog)
    ref = state
    for l in range(L):
        ref = fuse.apply_ops_unfused(
            ref,
            [
                fuse.Op("diag1", (0,), gates.rz_diag(th[l])),
                fuse.Op("g1", (0,), gates.ry(th[l])),
                fuse.Op("diag1", (0,), gates.rz_diag(2 * th[l])),
            ],
        )
    np.testing.assert_allclose(to_complex(out), to_complex(ref), atol=2e-5)


def test_stacked_g2_requires_layer_axis(tpu_form):
    """A g2 rides the scan xs untouched, so a layer-constant (2,2,2,2)
    coefficient must be rejected loudly — at L=2 its first GATE axis
    equals the layer count and the scan would silently slice it."""
    rng = np.random.default_rng(21)
    flat = jnp.asarray(
        rng.normal(size=(2, 2, 2, 2)), dtype=jnp.float32
    )
    with pytest.raises(ValueError, match="leading"):
        fuse.fuse_ops_stacked(
            [fuse.Op("g2", (0, 1), CArray(flat, None))], N, 2
        )


def test_grouped_diag_row_fold_capped(tpu_form):
    """A per-sample diagonal stack past _ROWMAT_GROUP_MAX must not fold
    into the row matrix (more (L,B,R,R) matrix than state) — it chains
    on the mask path instead."""
    import unittest.mock as mock

    L, B = 2, 4
    rng = np.random.default_rng(22)
    th = jnp.asarray(rng.uniform(-2, 2, (L, B)), dtype=jnp.float32)
    th1 = jnp.asarray(rng.uniform(-2, 2, (L,)), dtype=jnp.float32)
    ops = [
        fuse.Op("g1", (0,), gates.ry_batched(th1)),  # opens a rowmat
        fuse.Op("diag1", (1,), gates.rz_diag(th)),  # grouped (L,B,2)
    ]
    with mock.patch.object(fuse, "_ROWMAT_GROUP_MAX", 1):
        prog = fuse.fuse_ops_stacked(ops, N, L)
    kinds = [o.kind for o in prog.pre + prog.body]
    assert "mask" in kinds, kinds
    assert all(o.kind != "rowmat" or o.coeffs.re.ndim <= 3
               for o in prog.pre + prog.body if o.coeffs is not None)


def test_ctrl_cnot_after_collapse(tpu_form):
    """A second same-control boundary CNOT arriving after a lane gate
    collapsed the first pair into the matrix form must restart the
    static pair, not crash (general-IR path; HEA never orders ops this
    way). Parity vs the per-gate reference pins the composition."""
    L = 2
    rng = np.random.default_rng(13)
    th = jnp.asarray(rng.uniform(-2, 2, (L,)), dtype=jnp.float32)
    ops = [
        fuse.Op("cnot", (2, N - 1)),  # row ctrl → lane target
        fuse.Op("g1", (N - 2,), gates.ry_batched(th)),  # collapses pair
        fuse.Op("cnot", (2, N - 3)),  # same ctrl, new lane target
    ]
    prog = fuse.fuse_ops_stacked(ops, N, L)
    assert [o.kind for o in prog.body] == ["glane"]
    state = _rand_state(N, 14)
    out = fuse.apply_scan(state, N, prog)
    ref = state
    for l in range(L):
        ref = fuse.apply_ops_unfused(
            ref,
            [
                fuse.Op("cnot", (2, N - 1)),
                fuse.Op("g1", (N - 2,), gates.ry(th[l])),
                fuse.Op("cnot", (2, N - 3)),
            ],
        )
    np.testing.assert_allclose(to_complex(out), to_complex(ref), atol=2e-5)


def test_boundary_merge_mixed_groups(tpu_form):
    """Boundary merge with a grouped head and an ungrouped tail of the
    same kind: the final tail layer broadcasts across the groups
    instead of a rank-mismatched concat (general-IR path)."""
    L, G, S = 2, 2, 2
    B = G * S
    rng = np.random.default_rng(15)
    thg = jnp.asarray(rng.uniform(-2, 2, (L, G)), dtype=jnp.float32)
    th = jnp.asarray(rng.uniform(-2, 2, (L,)), dtype=jnp.float32)
    # grouped row rot | lane-ctrl-row cnot (flushes the rowmat) |
    # shared row rot — head rowmat grouped, tail rowmat ungrouped.
    ops = [
        fuse.Op("g1", (0,), gates.ry_batched(thg)),
        fuse.Op("cnot", (N - 1, 1)),
        fuse.Op("g1", (0,), gates.ry_batched(th)),
    ]
    prog = fuse.fuse_ops_stacked(ops, N, L)
    kinds = [o.kind for o in prog.pre + prog.body]
    assert kinds.count("rowmat") >= 1
    re = jnp.asarray(rng.standard_normal((B, 1 << N)), dtype=jnp.float32)
    im = jnp.asarray(rng.standard_normal((B, 1 << N)), dtype=jnp.float32)
    out = fuse.apply_scan(CArray(re, im), N, prog, batched=True)

    def one_row(r):
        st = CArray(re[r].reshape((2,) * N), im[r].reshape((2,) * N))
        g = r // S
        for l in range(L):
            st = sv.apply_gate(st, gates.ry(thg[l, g]), 0)
            st = sv.apply_cnot(st, N - 1, 1)
            st = sv.apply_gate(st, gates.ry(th[l]), 0)
        return st

    for r in range(B):
        ref = one_row(r)
        np.testing.assert_allclose(
            np.asarray(out.re[r]), np.asarray(ref.re).reshape(-1), atol=1e-5
        )


def test_scanned_diag_runs_stack(tpu_form):
    """Layer-varying diagonal runs chain into ONE stacked (L,2^n) mask."""
    L = 2
    rng = np.random.default_rng(8)
    th = jnp.asarray(rng.uniform(-2, 2, (L,)), dtype=jnp.float32)
    ops = [
        fuse.Op("diag1", (2,), gates.rz_diag(th)),
        fuse.Op("diag2", (3, 8), gates.cphase_diag(2 * th)),
    ]
    prog = fuse.fuse_ops_stacked(ops, N, L)
    assert [o.kind for o in prog.body] == ["mask"]
    assert prog.body[0].coeffs.re.shape == (L, 1 << N)
    state = _rand_state(N, 11)
    out = fuse.apply_scan(state, N, prog)
    ref = state
    for l in range(L):
        ref = fuse.apply_ops_unfused(
            ref,
            [
                fuse.Op("diag1", (2,), gates.rz_diag(th[l])),
                fuse.Op("diag2", (3, 8), gates.cphase_diag(2 * th[l])),
            ],
        )
    np.testing.assert_allclose(to_complex(out), to_complex(ref), atol=2e-5)


def test_tree_product_state_matches_sequential():
    from qfedx_tpu.ops.batched import bstate_product, bstate_product_tree

    rng = np.random.default_rng(12)
    for n in (3, 8, 12):
        ang = rng.uniform(0, np.pi, (3, n))
        amps = CArray(
            jnp.asarray(
                np.stack([np.cos(ang), np.sin(ang)], -1), dtype=jnp.float32
            ),
            None,
        )
        a, b = bstate_product(amps), bstate_product_tree(amps)
        np.testing.assert_allclose(
            np.asarray(a.re), np.asarray(b.re), atol=2e-6
        )
        assert b.im is None


# --- model-level parity ------------------------------------------------------


def _model(monkeypatch, encoding, n_layers=2, noise_model=None):
    from qfedx_tpu.models.vqc import make_vqc_classifier

    monkeypatch.setenv("QFEDX_BATCHED", "1")
    return make_vqc_classifier(
        n_qubits=N,
        n_layers=n_layers,
        num_classes=2,
        encoding=encoding,
        noise_model=noise_model,
    )


@pytest.mark.parametrize("encoding", ["angle", "reupload"])
def test_model_scanned_parity(encoding, monkeypatch, tpu_form):
    """Scanned ≡ fused logits AND gradients (batched engine + the
    client-folded path). The pins are read at trace time, so each route
    applies under its own pin window. Reupload scans layers 1..L−1, so
    its model is one layer deeper for the route to engage."""
    import optax

    # reupload needs L−1 ≥ 2 for its scanned block stack
    m = _model(monkeypatch, encoding, n_layers=3 if encoding == "reupload" else 2)
    rng = np.random.default_rng(9)
    x = jnp.asarray(rng.uniform(0, 1, (2, N)), dtype=jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, (2,)), dtype=jnp.int32)
    params = m.init(jax.random.PRNGKey(0))

    monkeypatch.setenv("QFEDX_SCAN_LAYERS", "1")
    a = m.apply(params, x)
    monkeypatch.setenv("QFEDX_SCAN_LAYERS", "0")
    b = m.apply(params, x)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5, rtol=0)

    def loss(p):
        return optax.softmax_cross_entropy_with_integer_labels(
            m.apply(p, x), y
        ).mean()

    monkeypatch.setenv("QFEDX_SCAN_LAYERS", "1")
    g1 = jax.grad(loss)(params)
    monkeypatch.setenv("QFEDX_SCAN_LAYERS", "0")
    g0 = jax.grad(loss)(params)
    for u, v in zip(jax.tree.leaves(g1), jax.tree.leaves(g0)):
        np.testing.assert_allclose(
            np.asarray(u), np.asarray(v), atol=2e-5, rtol=0
        )

    # client-folded path: per-client stacks ride the scan too
    cparams = jax.tree.map(
        lambda p: p[None]
        * (1.0 + 0.1 * jnp.arange(2).reshape((2,) + (1,) * p.ndim)),
        params,
    )
    cx = jnp.stack([x, x * 0.9])
    monkeypatch.setenv("QFEDX_SCAN_LAYERS", "1")
    fa = m.apply_clients(cparams, cx)
    monkeypatch.setenv("QFEDX_SCAN_LAYERS", "0")
    fb = m.apply_clients(cparams, cx)
    np.testing.assert_allclose(
        np.asarray(fa), np.asarray(fb), atol=2e-5, rtol=0
    )


def test_model_scanned_parity_bf16(monkeypatch, tpu_form):
    monkeypatch.setenv("QFEDX_DTYPE", "bf16")
    m = _model(monkeypatch, "angle")
    rng = np.random.default_rng(10)
    x = jnp.asarray(rng.uniform(0, 1, (2, N)), dtype=jnp.float32)
    params = m.init(jax.random.PRNGKey(1))
    monkeypatch.setenv("QFEDX_SCAN_LAYERS", "1")
    a = np.asarray(m.apply(params, x))
    monkeypatch.setenv("QFEDX_SCAN_LAYERS", "0")
    b = np.asarray(m.apply(params, x))
    assert np.all(np.isfinite(a))
    np.testing.assert_allclose(a, b, atol=3e-2, rtol=0)


def test_noise_channels_are_scan_barriers(monkeypatch, tpu_form):
    """Circuit-level Kraus noise keeps the per-layer loop (a channel
    between layers is a scan barrier) and consumes the SAME PRNG
    stream: scanned-pin and off trajectories coincide sample-for-
    sample."""
    from qfedx_tpu.noise import NoiseModel

    nm = NoiseModel(depolarizing_p=0.1, circuit_level=True)
    m = _model(monkeypatch, "angle", n_layers=2, noise_model=nm)
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.uniform(0, 1, (2, N)), dtype=jnp.float32)
    params = m.init(jax.random.PRNGKey(2))
    key = jax.random.PRNGKey(3)
    monkeypatch.setenv("QFEDX_SCAN_LAYERS", "1")
    a = np.asarray(m.apply_train(params, x, key))
    monkeypatch.setenv("QFEDX_SCAN_LAYERS", "0")
    b = np.asarray(m.apply_train(params, x, key))
    np.testing.assert_allclose(a, b, atol=1e-5, rtol=0)


def test_persistent_forward_routes_on_scan_pin(monkeypatch, tpu_form):
    """The serving cache keys on QFEDX_SCAN_LAYERS: flipping the pin
    around one facade compiles a SECOND route instead of serving the
    stale program (serve/forward.py)."""
    from qfedx_tpu.serve.forward import cached_routes, persistent_forward

    m = _model(monkeypatch, "angle")
    params = m.init(jax.random.PRNGKey(4))
    x = jnp.zeros((2, N), dtype=jnp.float32)
    fwd = persistent_forward(m.apply)
    monkeypatch.setenv("QFEDX_SCAN_LAYERS", "1")
    fwd(params, x)
    assert cached_routes(m.apply) == 1
    monkeypatch.setenv("QFEDX_SCAN_LAYERS", "0")
    fwd(params, x)
    assert cached_routes(m.apply) == 2


# --- sharded engine ----------------------------------------------------------


def test_sharded_scanned_parity(monkeypatch, tpu_form):
    """The sharded layer loop scans with the body running the segment-
    and-fuse pass once — parity vs the dense per-gate oracle on a
    2-device sv mesh, with the scan route asserted engaged."""
    from jax.sharding import Mesh

    from qfedx_tpu.circuits.ansatz import (
        hardware_efficient,
        init_ansatz_params,
    )
    from qfedx_tpu.circuits.encoders import angle_encode
    from qfedx_tpu.ops.statevector import expect_z_all
    from qfedx_tpu.parallel.circuit import make_sharded_forward

    n = 10
    mesh = Mesh(np.array(jax.devices()[:2]), ("sv",))
    params = init_ansatz_params(jax.random.PRNGKey(4), n, 2)
    x = jnp.asarray(
        np.random.default_rng(12).uniform(0, 1, (n,)), dtype=jnp.float32
    )

    scans = []
    real = jax.lax.scan

    def spy(*a, **k):
        scans.append(1)
        return real(*a, **k)

    monkeypatch.setattr(jax.lax, "scan", spy)
    fwd, ctx = make_sharded_forward(n, mesh)
    sharded = np.asarray(fwd(params, x))
    assert scans  # the layer loop really scanned

    monkeypatch.setenv("QFEDX_SCAN_LAYERS", "0")
    monkeypatch.setenv("QFEDX_FUSE", "0")
    dense = np.asarray(
        expect_z_all(hardware_efficient(angle_encode(x, "ry"), params))
    )
    np.testing.assert_allclose(sharded, dense, atol=2e-5, rtol=0)
