"""Fusion pass (ops/fuse.py) ≡ per-gate execution (r07 tentpole).

Parity is pinned at four altitudes, mirroring tests/test_fold_clients.py:

- pass: ``fuse_ops`` emits the expected super-gate structure (lane
  matrices, row pairs, one mask per diagonal run) and only reorders
  commuting ops;
- ops: fused execution ≡ the gate-by-gate reference on random complex
  states — dense, batched shared/grouped/per-sample, diagonal chains;
- model: QFEDX_FUSE=1 ≡ QFEDX_FUSE=0 logits and gradients for HEA and
  reupload ansätze on the batched engine and the client-folded path,
  f32 and bf16, and with circuit-level Kraus noise interleaved (channel
  boundaries are fusion barriers — trajectory PRNG streams unchanged);
- sharded: the segment-and-fuse route of parallel/circuit.py ≡ the
  per-gate ppermute loop on a 4-device sv mesh.

All tests pin the TPU production formulation (flip gate form + matmul
lanes) so the fused slab programs are covered on the CPU mesh, exactly
like the slab parity tests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from qfedx_tpu.ops import fuse, gates
from qfedx_tpu.ops import statevector as sv
from qfedx_tpu.ops.cpx import CArray, from_complex, to_complex

N = 10  # smallest slab width (statevector._SLAB_MIN)


@pytest.fixture
def tpu_form(monkeypatch):
    """Pin the TPU production routing on the CPU test backend."""
    monkeypatch.setenv("QFEDX_GATE_FORM", "flip")
    monkeypatch.setenv("QFEDX_SLAB_LANES", "matmul")


def _rand_state(n: int, seed: int = 0) -> CArray:
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(2,) * n) + 1j * rng.normal(size=(2,) * n)
    return from_complex(x / np.linalg.norm(x))


def _hea_ops(n: int, seed: int = 0) -> list:
    rng = np.random.default_rng(seed)
    rx = jnp.asarray(rng.uniform(-2, 2, n), dtype=jnp.float32)
    rz = jnp.asarray(rng.uniform(-2, 2, n), dtype=jnp.float32)
    from qfedx_tpu.circuits.ansatz import hea_layer_ops

    return hea_layer_ops(n, rx, rz)


# --- the pass: structure and the env pin -----------------------------------


def test_fuse_pin_rejects_invalid(monkeypatch):
    """A typo'd pin must fail loudly, not silently run the other route
    (the wrong-path-measured error class — same contract as
    QFEDX_GATE_FORM / QFEDX_SLAB_LANES)."""
    monkeypatch.setenv("QFEDX_FUSE", "banana")
    with pytest.raises(ValueError, match="QFEDX_FUSE"):
        fuse.fuse_enabled()


@pytest.mark.parametrize(
    "pin,expect", [("1", True), ("on", True), ("0", False), ("off", False)]
)
def test_fuse_pin_values(monkeypatch, pin, expect):
    monkeypatch.setenv("QFEDX_FUSE", pin)
    assert fuse.fuse_enabled() is expect


def test_fuse_cannot_engage_below_slab(monkeypatch):
    """Like the batched route (test_fold_clients), fusion gates on
    _SLAB_MIN before reading any pin — the flagship 8-qubit shape can
    never route fused."""
    monkeypatch.setenv("QFEDX_FUSE", "1")
    assert fuse.fuse_active(8) is False
    assert fuse.fuse_active(N) is True


def test_both_routes_reachable_under_pin(monkeypatch, tpu_form):
    """QFEDX_FUSE independently selects the fused / per-gate executor on
    CPU: the ansatz layer calls fuse.apply_fused exactly when pinned on."""
    from qfedx_tpu.circuits import ansatz

    calls = []
    real = fuse.apply_fused
    monkeypatch.setattr(
        fuse, "apply_fused", lambda s, ops: calls.append(1) or real(s, ops)
    )
    state = _rand_state(N)
    rx = jnp.zeros(N)
    rz = jnp.zeros(N)
    monkeypatch.setenv("QFEDX_FUSE", "0")
    ansatz.ansatz_layer(state, rx, rz)
    assert not calls
    monkeypatch.setenv("QFEDX_FUSE", "1")
    ansatz.ansatz_layer(state, rx, rz)
    assert calls


def test_hea_layer_fused_structure():
    """One n=10 HEA layer (20 gate passes) collapses to ≤ 9 fused ops:
    lane rotations → ONE lane matrix, lane-lane ring CNOTs → one more,
    row rotations → pairs, row/mixed CNOTs unfused."""
    ops = _hea_ops(N)
    fused = fuse.fuse_ops(ops, N)
    kinds = [f.kind for f in fused]
    assert len(fused) <= 9 < len(ops)
    assert kinds.count("lane") == 2  # rotations; ring permutations
    assert kinds.count("rowpair") == 1  # rots on row qubits 0,1
    assert kinds.count("g1") == 1  # row qubit 2's unpaired rotation
    # the ring's row-row + row↔lane boundary CNOTs stay per-gate
    assert kinds.count("cnot") == len(fused) - 4


def test_diag_run_collapses_to_one_mask():
    ops = [
        fuse.Op("diag1", (2,), gates.rz_diag(0.7)),
        fuse.Op("diag2", (3, 8), gates.CZ_DIAG),
        fuse.Op("diag1", (9,), gates.rz_diag(-1.1)),
        fuse.Op("diag2", (1, 4), gates.cphase_diag(0.5)),
    ]
    fused = fuse.fuse_ops(ops, N)
    assert [f.kind for f in fused] == ["mask"]


def test_fuse_never_reorders_overlapping_ops(tpu_form):
    """A trace built to trip every flush path (same-qubit composition,
    diag interleaved with rotations and CNOTs on overlapping qubits)
    stays correct: fused ≡ gate-by-gate."""
    rng = np.random.default_rng(7)
    a = lambda: jnp.asarray(rng.uniform(-2, 2), dtype=jnp.float32)
    ops = [
        fuse.Op("g1", (0,), gates.rot_zx(a(), a())),
        fuse.Op("diag1", (0,), gates.rz_diag(a())),  # flushes row single
        fuse.Op("g1", (0,), gates.ry(a())),  # flushes the diag
        fuse.Op("g1", (0,), gates.ry(a())),  # same-qubit 2×2 compose
        fuse.Op("g1", (N - 1,), gates.rot_zx(a(), a())),  # lane acc
        fuse.Op("diag1", (N - 1,), gates.rz_diag(a())),  # folds into acc
        fuse.Op("cnot", (N - 2, N - 1)),  # folds into acc
        fuse.Op("cnot", (2, N - 1)),  # mixed: flushes lane acc
        fuse.Op("diag2", (0, 2), gates.cphase_diag(a())),
        fuse.Op("cnot", (0, 1)),  # overlaps diag: flushes mask
        fuse.Op("g2", (1, 2), gates.CZ),  # general 2q passes through
    ]
    state = _rand_state(N, 8)
    out = fuse.apply_fused(state, fuse.fuse_ops(ops, N))
    ref = fuse.apply_ops_unfused(state, ops)
    np.testing.assert_allclose(
        to_complex(out), to_complex(ref), atol=2e-6
    )


# --- ops-level parity -------------------------------------------------------


def test_dense_layer_fused_parity(tpu_form):
    """Fused HEA layer + diagonal tail ≡ per-gate on a dense state."""
    ops = _hea_ops(N, seed=1) + [
        fuse.Op("diag1", (4,), gates.rz_diag(0.3)),
        fuse.Op("diag2", (0, 5), gates.CZ_DIAG),
    ]
    state = _rand_state(N, 2)
    out = fuse.apply_fused(state, fuse.fuse_ops(ops, N))
    ref = fuse.apply_ops_unfused(state, ops)
    np.testing.assert_allclose(to_complex(out), to_complex(ref), atol=2e-6)


def test_rowpair_primitive_matches_sequential(tpu_form):
    """apply_rowpair(kron(A,B)) ≡ apply A then B on distinct row qubits."""
    state = _rand_state(N, 3)
    A = gates.rot_zx(0.7, -1.3)
    B = gates.ry(2.1)
    super_ = fuse._ckron2(A, B)
    out = sv.apply_rowpair(state, super_, 0, 2)
    ref = sv.apply_gate(sv.apply_gate(state, A, 0), B, 2)
    np.testing.assert_allclose(to_complex(out), to_complex(ref), atol=1e-6)


def test_lane_matrix_primitive_matches_sequential(tpu_form):
    """apply_lane_matrix(M1@M2) ≡ the two lane gates in sequence."""
    state = _rand_state(N, 4)
    g1_, g2_ = gates.rot_zx(0.4, 0.9), gates.rx(-1.7)
    q1, q2 = N - 1, N - 3
    mt = fuse._cmatmul(
        fuse._lane_g1(g1_, sv._slab_pos(N, q1)),
        fuse._lane_g1(g2_, sv._slab_pos(N, q2)),
    )
    out = sv.apply_lane_matrix(state, mt)
    ref = sv.apply_gate(sv.apply_gate(state, g1_, q1), g2_, q2)
    np.testing.assert_allclose(to_complex(out), to_complex(ref), atol=1e-6)


def test_phase_mask_primitive_matches_gates(tpu_form):
    state = _rand_state(N, 5)
    ops = [
        fuse.Op("diag1", (1,), gates.rz_diag(0.8)),
        fuse.Op("diag2", (3, 9), gates.cphase_diag(-0.6)),
    ]
    (mask_op,) = fuse.fuse_ops(ops, N)
    out = sv.apply_phase_mask(state, mask_op.coeffs)
    ref = fuse.apply_ops_unfused(state, ops)
    np.testing.assert_allclose(to_complex(out), to_complex(ref), atol=1e-6)


def test_batched_grouped_fused_parity(tpu_form):
    """Grouped (G,2,2) + per-sample (B,2,2) stacks through the fused
    batched executor ≡ per-row dense reference (the folded federated
    path's coefficient forms — docs/PERF.md §10)."""
    G, S = 3, 2
    B = G * S
    rng = np.random.default_rng(6)
    re = jnp.asarray(rng.standard_normal((B, 1 << N)), dtype=jnp.float32)
    im = jnp.asarray(rng.standard_normal((B, 1 << N)), dtype=jnp.float32)
    state = CArray(re, im)
    th = jnp.asarray(rng.uniform(-2, 2, (G, N)), dtype=jnp.float32)
    ph = jnp.asarray(rng.uniform(-2, 2, (G, N)), dtype=jnp.float32)
    enc = jnp.asarray(rng.uniform(-2, 2, (B, N)), dtype=jnp.float32)
    ops = [
        fuse.Op("g1", (q,), gates.ry_batched(enc[:, q])) for q in range(N)
    ] + [
        fuse.Op("g1", (q,), gates.rot_zx_batched(th[:, q], ph[:, q]))
        for q in range(N)
    ]
    ops += [fuse.Op("cnot", (q, q + 1)) for q in range(N - 1)]
    ops += [fuse.Op("cnot", (N - 1, 0))]

    out = fuse.apply_fused_b(state, N, fuse.fuse_ops(ops, N))

    def one_row(r):
        st = CArray(
            re[r].reshape((2,) * N), im[r].reshape((2,) * N)
        )
        g = r // S
        for op in ops:
            if op.kind == "cnot":
                st = sv.apply_cnot(st, *op.qubits)
                continue
            c = op.coeffs
            idx = g if c.re.shape[0] == G else r
            st = sv.apply_gate(
                st,
                CArray(c.re[idx], None if c.im is None else c.im[idx]),
                op.qubits[0],
            )
        return st

    for r in range(B):
        ref = one_row(r)
        np.testing.assert_allclose(
            np.asarray(out.re[r]),
            np.asarray(ref.re).reshape(-1),
            atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(out.im[r]),
            np.asarray(ref.im).reshape(-1),
            atol=1e-5,
        )


def test_grouped_coeffs_reject_nondivisor(tpu_form):
    from qfedx_tpu.ops.batched import apply_lane_matrix_b

    state = CArray(jnp.zeros((6, 1 << N)), None)
    bad = CArray(jnp.zeros((4, 128, 128)), None)  # 4 ∤ 6
    with pytest.raises(ValueError, match="G must divide B"):
        apply_lane_matrix_b(state, N, bad)


# --- model-level parity -----------------------------------------------------


def _model_pair(monkeypatch, encoding, n_layers=2, noise_model=None):
    """Build (fused, unfused) models with the batched engine pinned."""
    from qfedx_tpu.models.vqc import make_vqc_classifier

    monkeypatch.setenv("QFEDX_BATCHED", "1")
    out = {}
    for pin in ("1", "0"):
        monkeypatch.setenv("QFEDX_FUSE", pin)
        out[pin] = make_vqc_classifier(
            n_qubits=N,
            n_layers=n_layers,
            num_classes=2,
            encoding=encoding,
            noise_model=noise_model,
        )
    return out["1"], out["0"]


@pytest.mark.parametrize("encoding", ["angle", "reupload"])
def test_model_fused_parity(encoding, monkeypatch, tpu_form):
    """Fused ≡ unfused logits AND gradients on the batched engine and the
    client-folded path (HEA + reupload). The env pin is read at trace
    time, so each route is applied under its own pin."""
    import optax

    m1, m0 = _model_pair(monkeypatch, encoding)
    rng = np.random.default_rng(9)
    x = jnp.asarray(rng.uniform(0, 1, (3, N)), dtype=jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, (3,)), dtype=jnp.int32)
    params = m1.init(jax.random.PRNGKey(0))

    monkeypatch.setenv("QFEDX_FUSE", "1")
    a = m1.apply(params, x)
    monkeypatch.setenv("QFEDX_FUSE", "0")
    b = m0.apply(params, x)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5, rtol=0)

    def loss(m):
        def g(p):
            return optax.softmax_cross_entropy_with_integer_labels(
                m.apply(p, x), y
            ).mean()

        return g

    monkeypatch.setenv("QFEDX_FUSE", "1")
    g1_ = jax.grad(loss(m1))(params)
    monkeypatch.setenv("QFEDX_FUSE", "0")
    g0_ = jax.grad(loss(m0))(params)
    for u, v in zip(jax.tree.leaves(g1_), jax.tree.leaves(g0_)):
        np.testing.assert_allclose(
            np.asarray(u), np.asarray(v), atol=1e-5, rtol=0
        )

    # client-folded path (per-client grouped stacks fuse too)
    cparams = jax.tree.map(
        lambda p: p[None] * (1.0 + 0.1 * jnp.arange(2).reshape((2,) + (1,) * p.ndim)),
        params,
    )
    cx = jnp.stack([x, x * 0.9])
    monkeypatch.setenv("QFEDX_FUSE", "1")
    fa = m1.apply_clients(cparams, cx)
    monkeypatch.setenv("QFEDX_FUSE", "0")
    fb = m0.apply_clients(cparams, cx)
    np.testing.assert_allclose(
        np.asarray(fa), np.asarray(fb), atol=1e-5, rtol=0
    )


def test_model_fused_parity_bf16(monkeypatch, tpu_form):
    """Fused ≡ unfused under QFEDX_DTYPE=bf16 to bf16 rounding (both
    routes run the bf16-state/f32-accumulate recipe)."""
    monkeypatch.setenv("QFEDX_DTYPE", "bf16")
    m1, m0 = _model_pair(monkeypatch, "angle")
    rng = np.random.default_rng(10)
    x = jnp.asarray(rng.uniform(0, 1, (2, N)), dtype=jnp.float32)
    params = m1.init(jax.random.PRNGKey(1))
    monkeypatch.setenv("QFEDX_FUSE", "1")
    a = np.asarray(m1.apply(params, x))
    monkeypatch.setenv("QFEDX_FUSE", "0")
    b = np.asarray(m0.apply(params, x))
    assert np.all(np.isfinite(a))
    np.testing.assert_allclose(a, b, atol=3e-2, rtol=0)


def test_noise_channels_are_fusion_barriers(monkeypatch, tpu_form):
    """Circuit-level Kraus trajectories: the fused route consumes the
    SAME per-(layer, channel, qubit) PRNG stream — channels sit between
    per-layer traces and are never fused across — so fused and unfused
    trajectories coincide sample-for-sample."""
    from qfedx_tpu.noise import NoiseModel

    nm = NoiseModel(depolarizing_p=0.1, circuit_level=True)
    m1, m0 = _model_pair(monkeypatch, "angle", n_layers=1, noise_model=nm)
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.uniform(0, 1, (2, N)), dtype=jnp.float32)
    params = m1.init(jax.random.PRNGKey(2))
    key = jax.random.PRNGKey(3)
    monkeypatch.setenv("QFEDX_FUSE", "1")
    a = np.asarray(m1.apply_train(params, x, key))
    monkeypatch.setenv("QFEDX_FUSE", "0")
    b = np.asarray(m0.apply_train(params, x, key))
    np.testing.assert_allclose(a, b, atol=1e-5, rtol=0)


# --- sharded engine ---------------------------------------------------------


def test_sharded_fused_parity(monkeypatch, tpu_form):
    """Segment-and-fuse on a 2-device sv mesh (n=10 → n_local=9: lane
    fusion + one row pair on the local shard) ≡ the DENSE per-gate
    oracle — one sharded compile, not two (the per-gate sharded program
    is the expensive compile on XLA:CPU). Lane fusion is sharding-
    oblivious: the 7 lane qubits are the last 7 and always local; the
    fused route is asserted engaged via the pass hook."""
    from jax.sharding import Mesh

    from qfedx_tpu.circuits.ansatz import (
        hardware_efficient,
        init_ansatz_params,
    )
    from qfedx_tpu.circuits.encoders import angle_encode
    from qfedx_tpu.ops.statevector import expect_z_all
    from qfedx_tpu.parallel.circuit import make_sharded_forward

    n = 10
    mesh = Mesh(np.array(jax.devices()[:2]), ("sv",))
    params = init_ansatz_params(jax.random.PRNGKey(4), n, 1)
    x = jnp.asarray(
        np.random.default_rng(12).uniform(0, 1, (n,)), dtype=jnp.float32
    )

    fused_calls = []
    real = fuse.apply_fused
    monkeypatch.setattr(
        fuse,
        "apply_fused",
        lambda s, ops: fused_calls.append(1) or real(s, ops),
    )
    monkeypatch.setenv("QFEDX_FUSE", "1")
    fwd, ctx = make_sharded_forward(n, mesh)
    sharded = np.asarray(fwd(params, x))
    assert ctx.n_local == 9
    assert fused_calls  # the local runs really took the fused route

    monkeypatch.setenv("QFEDX_FUSE", "0")
    dense = np.asarray(
        expect_z_all(hardware_efficient(angle_encode(x, "ry"), params))
    )
    np.testing.assert_allclose(sharded, dense, atol=1e-5, rtol=0)
