"""Unit tests for the r19 Pallas scan-body kernel's building blocks.

``tests/test_pallas.py`` pins the kernel end-to-end (interpret-mode
logits/grad parity against the lax.scan route); these are the fast
unit-level pins for the pieces that parity would only implicate
indirectly — the no-operand bit-flip spelling of row permutations, the
numpy twins of the lane CNOT matrices, the static-operand selection per
CNOT register placement, the adjoint spec/coefficient transforms the
custom_vjp bwd launch is built from, and the coefficient-group
contract ``route_ok`` enforces. All eager, no pallas_call, no jit — a
wrong sign or a missed transpose fails HERE with a readable name
instead of as an opaque parity diff.
"""

import os
import sys
from types import SimpleNamespace

import numpy as np
import jax.numpy as jnp

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from qfedx_tpu.ops import pallas_body as pb  # noqa: E402
from qfedx_tpu.ops.cpx import CArray  # noqa: E402

_LANES = 128


def _bit_flip_ref(x, rbits, qubit):
    # Independent reference: row index r maps to r with the (row-local,
    # MSB-first) ``qubit`` bit flipped.
    idx = np.arange(1 << rbits) ^ (1 << (rbits - qubit - 1))
    return np.asarray(x)[idx]


def test_row_flip_matches_index_xor_reference():
    rng = np.random.default_rng(0)
    for rbits, qubit in ((3, 0), (3, 2), (5, 1), (1, 0)):
        x = rng.normal(size=(1 << rbits, _LANES)).astype(np.float32)
        out = np.asarray(pb._row_flip(jnp.asarray(x), rbits, qubit))
        np.testing.assert_array_equal(out, _bit_flip_ref(x, rbits, qubit))


def test_row_flip_is_an_involution():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(8, _LANES)).astype(np.float32))
    twice = pb._row_flip(pb._row_flip(x, 3, 1), 3, 1)
    np.testing.assert_array_equal(np.asarray(twice), np.asarray(x))


def test_lane_cnot_matrix_is_the_statevector_permutation():
    # s @ M must equal the index-map CNOT: lane l reads from l with the
    # target bit flipped when its control bit is set.
    n, ctrl, tgt = 12, 7, 9
    m = pb._np_lane_cnot(n, ctrl, tgt)
    pc, pt = n - 1 - ctrl, n - 1 - tgt
    l = np.arange(_LANES)
    src = np.where(((l >> pc) & 1) == 1, l ^ (1 << pt), l)
    rng = np.random.default_rng(2)
    s = rng.normal(size=(_LANES,)).astype(np.float32)
    np.testing.assert_array_equal(s @ m, s[src])
    # Symmetric involution: its own transpose AND its own inverse, so
    # the adjoint launch reuses the forward operand unchanged.
    np.testing.assert_array_equal(m, m.T)
    np.testing.assert_array_equal(m @ m, np.eye(_LANES, dtype=m.dtype))


def test_lane_flip_matrix_is_a_symmetric_involution():
    m = pb._np_lane_flip(12, 8)
    p = 12 - 1 - 8
    l = np.arange(_LANES)
    rng = np.random.default_rng(3)
    s = rng.normal(size=(_LANES,)).astype(np.float32)
    np.testing.assert_array_equal(s @ m, s[l ^ (1 << p)])
    np.testing.assert_array_equal(m, m.T)
    np.testing.assert_array_equal(m @ m, np.eye(_LANES, dtype=m.dtype))


def _spec(n=12, ops=()):
    return pb._KernelSpec(
        n=n, length=2, tb=1, batched=False, ops=tuple(ops),
        interpret=True,
    )


def _cnot(ctrl, tgt):
    return pb._OpSpec("cnot", (ctrl, tgt), False, 1, False, None)


def test_static_arrays_per_cnot_register_placement():
    # n=12 → rbits=5: qubits 0–4 live on the row axis, 5–11 on lanes.
    spec = _spec()
    # row-row and lane-ctrl/row-tgt emit as bit-flip reshapes — no
    # operand; lane-lane and row-ctrl/lane-tgt need their (128,128)
    # permutation matrix DMA'd in.
    assert pb._static_arrays(spec, _cnot(0, 1), np.float32) == []
    assert pb._static_arrays(spec, _cnot(9, 2), np.float32) == []
    (lane_lane,) = pb._static_arrays(spec, _cnot(5, 8), np.float32)
    np.testing.assert_array_equal(lane_lane, pb._np_lane_cnot(12, 5, 8))
    (lane_flip,) = pb._static_arrays(spec, _cnot(2, 9), np.float32)
    np.testing.assert_array_equal(lane_flip, pb._np_lane_flip(12, 9))


def test_static_arrays_rowperm_is_an_int32_gather_operand():
    op = pb._OpSpec("rowperm", (), False, 1, False, (2, 0, 3, 1))
    (idx,) = pb._static_arrays(_spec(), op, np.float32)
    assert idx.dtype == np.int32
    np.testing.assert_array_equal(idx, [2, 0, 3, 1])


def test_adjoint_spec_reverses_ops_and_inverts_rowperm():
    perm_op = pb._OpSpec("rowperm", (), False, 1, False, (2, 0, 1))
    lane_op = pb._OpSpec("lane", (8,), True, 1, True, None)
    spec = _spec(ops=(perm_op, _cnot(0, 1), lane_op))
    adj = pb._adjoint_spec(spec)
    assert [o.kind for o in adj.ops] == ["lane", "cnot", "rowperm"]
    # (2,0,1) sends 0→2, 1→0, 2→1; its inverse is (1,2,0). CNOTs are
    # involutions and pass through untouched.
    assert adj.ops[-1].perm == (1, 2, 0)
    assert adj.ops[1] == _cnot(0, 1)
    # Adjoint of the adjoint restores the forward spec exactly.
    assert pb._adjoint_spec(adj) == spec


def test_adjoint_xs_conjugates_transposes_and_flips_layers():
    mask_op = pb._OpSpec("mask", (), True, 1, True, None)
    lane_op = pb._OpSpec("lane", (8,), True, 1, True, None)
    spec = _spec(ops=(mask_op, lane_op))
    rng = np.random.default_rng(4)
    mask = CArray(
        jnp.asarray(rng.normal(size=(2, 4)), jnp.float32),
        jnp.asarray(rng.normal(size=(2, 4)), jnp.float32),
    )
    lane = CArray(
        jnp.asarray(rng.normal(size=(2, 2, 2)), jnp.float32),
        jnp.asarray(rng.normal(size=(2, 2, 2)), jnp.float32),
    )
    adj_lane, adj_mask = pb._adjoint_xs(spec, (mask, lane))
    # Masks are diagonal: adjoint = conjugate, layers reversed.
    np.testing.assert_array_equal(
        np.asarray(adj_mask.re), np.asarray(mask.re)[::-1]
    )
    np.testing.assert_array_equal(
        np.asarray(adj_mask.im), -np.asarray(mask.im)[::-1]
    )
    # Branch matrices: M† per layer (conjugate transpose), reversed.
    np.testing.assert_array_equal(
        np.asarray(adj_lane.re),
        np.asarray(lane.re)[::-1].transpose(0, 2, 1),
    )
    np.testing.assert_array_equal(
        np.asarray(adj_lane.im),
        -np.asarray(lane.im)[::-1].transpose(0, 2, 1),
    )


def test_adjoint_xs_rowpair_swaps_the_paired_axes():
    op = pb._OpSpec("rowpair", (0, 2), True, 1, True, None)
    rng = np.random.default_rng(5)
    c = CArray(
        jnp.asarray(rng.normal(size=(2, 2, 2, 2, 2)), jnp.float32),
        jnp.asarray(rng.normal(size=(2, 2, 2, 2, 2)), jnp.float32),
    )
    (adj,) = pb._adjoint_xs(_spec(ops=(op,)), (c,))
    # G'[..., o1, o2, i1, i2] = conj(G[..., i1, i2, o1, o2]) with the
    # layer axis flipped.
    ref = np.asarray(c.re)[::-1].transpose(0, 3, 4, 1, 2)
    np.testing.assert_array_equal(np.asarray(adj.re), ref)
    ref_im = -np.asarray(c.im)[::-1].transpose(0, 3, 4, 1, 2)
    np.testing.assert_array_equal(np.asarray(adj.im), ref_im)


def _coeff_op(kind, shape):
    return SimpleNamespace(
        kind=kind, coeffs=SimpleNamespace(re=np.zeros(shape)),
    )


def test_op_groups_speaks_the_batched_group_contract():
    # Shared coefficients (no group axis) → one group at any tb.
    assert pb._op_groups(_coeff_op("lane", (3, 2, 2)), 8) == 1
    # One leading group axis: G must divide the state-block count.
    assert pb._op_groups(_coeff_op("lane", (3, 4, 2, 2)), 8) == 4
    assert pb._op_groups(_coeff_op("lane", (3, 3, 2, 2)), 8) is None
    # Two extra leading axes are not a shape the kernel packs.
    assert pb._op_groups(_coeff_op("lane", (3, 2, 4, 2, 2)), 8) is None
    # Kind-specific gate ndim: rowpair carries 4 paired gate axes.
    assert pb._op_groups(
        _coeff_op("rowpair", (3, 2, 2, 2, 2, 2)), 8
    ) == 2


def test_route_ok_rejects_foreign_kinds_not_pallas_shapes(monkeypatch):
    # A stacked rowperm (dynamic permutation coefficients) and a static
    # kind outside {cnot, rowperm} both degrade to the lax.scan route —
    # route_ok answers False instead of letting the builder throw.
    monkeypatch.setenv("QFEDX_PALLAS", "1")
    state = CArray(jnp.zeros((32, _LANES)), None)

    def prog(body):
        return SimpleNamespace(length=2, body=body)

    stacked_rowperm = SimpleNamespace(
        kind="rowperm", stacked=True, coeffs=None, qubits=(),
    )
    assert not pb.route_ok(state, 12, prog([stacked_rowperm]), False)
    foreign = SimpleNamespace(
        kind="kraus", stacked=False, coeffs=None, qubits=(0,),
    )
    assert not pb.route_ok(state, 12, prog([foreign]), False)
    three_q = SimpleNamespace(
        kind="cnot", stacked=False, coeffs=None, qubits=(0, 1, 2),
    )
    assert not pb.route_ok(state, 12, prog([three_q]), False)
    # And the empty body never launches a kernel.
    assert not pb.route_ok(state, 12, prog([]), False)
