"""Unit fixtures for the `qfedx lint` engine (qfedx_tpu/analysis).

Each new rule (QFX001–QFX005) gets one minimal POSITIVE snippet (the
rule must fire) and one NEGATIVE (it must stay quiet) — the
"demonstrably fires on a fixture" half of the ISSUE 15 acceptance.
Engine semantics (suppressions, baseline multiset + staleness, JSON
schema round-trip) and call-graph reachability (direct, aliased
import, method) are pinned here too. Everything runs on tmp_path
fixture packages through the same run_lint entry the CLI and tier-1
use — no internal shortcuts that could drift from the real path.
"""

import json
import os
import sys
import textwrap

import pytest

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from qfedx_tpu.analysis import (  # noqa: E402
    LintConfig,
    render_json,
    run_lint,
)
from qfedx_tpu.analysis.callgraph import build_callgraph  # noqa: E402
from qfedx_tpu.analysis.loader import load_tree  # noqa: E402


def make_repo(tmp_path, files: dict[str, str]) -> LintConfig:
    """A throwaway repo with a ``pkg/`` package; returns its config."""
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    (pkg / "__init__.py").write_text("")
    for rel, text in files.items():
        p = pkg / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    return LintConfig(
        root=tmp_path, packages=("pkg",),
        baseline=str(tmp_path / "baseline.json"),
    )


def findings_for(tmp_path, rule: str, files: dict[str, str]):
    cfg = make_repo(tmp_path, files)
    result = run_lint(config=cfg, rules=(rule,))
    return result.findings


# --- QFX001 trace-purity ------------------------------------------------------


def test_qfx001_fires_on_impure_reachable_from_jit(tmp_path):
    found = findings_for(tmp_path, "QFX001", {"mod.py": """
        import time
        import jax

        def helper():
            return time.time()

        def step(x):
            return helper() + x

        fast = jax.jit(step)
    """})
    assert len(found) == 1
    assert "time.time()" in found[0].message
    assert "helper" in found[0].message  # witness path names the chain


def test_qfx001_quiet_when_impurity_unreachable(tmp_path):
    found = findings_for(tmp_path, "QFX001", {"mod.py": """
        import time
        import jax

        def host_only():
            return time.time()

        def step(x):
            return x * 2

        fast = jax.jit(step)
    """})
    assert found == []


def test_qfx001_scan_body_and_np_random(tmp_path):
    found = findings_for(tmp_path, "QFX001", {"mod.py": """
        import numpy as np
        from jax import lax

        def body(carry, x):
            return carry + np.random.normal(), x

        def run(xs):
            return lax.scan(body, 0.0, xs)
    """})
    assert len(found) == 1
    assert "np.random.normal" in found[0].message


# --- QFX002 raw-pin-read ------------------------------------------------------


def test_qfx002_fires_on_raw_environ_and_getenv(tmp_path):
    found = findings_for(tmp_path, "QFX002", {"mod.py": """
        import os
        a = os.environ.get("QFEDX_X")
        b = os.getenv("QFEDX_Y")
    """})
    assert len(found) == 2


def test_qfx002_quiet_in_pins_module_and_helper_callers(tmp_path):
    found = findings_for(tmp_path, "QFX002", {
        "utils/pins.py": """
            import os
            def bool_pin(name, default):
                return os.environ.get(name, default)
        """,
        "mod.py": """
            from pkg.utils import pins
            val = pins.bool_pin("QFEDX_X", False)
        """,
    })
    assert found == []


# --- QFX003 span-leak ---------------------------------------------------------


def test_qfx003_fires_on_unclosed_span(tmp_path):
    found = findings_for(tmp_path, "QFX003", {"mod.py": """
        from pkg import obs

        def f():
            sp = obs.span("leaky.phase")
            sp.__enter__()
            do_work()
    """})
    # both the non-with factory call and the unprotected manual enter
    assert len(found) == 2


def test_qfx003_quiet_on_with_and_assigned_with(tmp_path):
    found = findings_for(tmp_path, "QFX003", {"mod.py": """
        from pkg import obs

        def f():
            with obs.span("clean.phase"):
                pass
            ctx = obs.span("later.phase")
            with ctx:
                pass
    """})
    assert found == []


# --- QFX004 lock-discipline ---------------------------------------------------


_LOCK_CLASS = """
    import threading

    class Registry:
        def __init__(self):
            self.counters = {}
            self._lock = threading.Lock()

        def good(self, name):
            with self._lock:
                self.counters[name] = self.counters.get(name, 0) + 1

        def _bump_locked(self, name):
            self.counters[name] = 1  # caller holds the lock (convention)
"""


def test_qfx004_fires_on_unlocked_mutation(tmp_path):
    found = findings_for(tmp_path, "QFX004", {"mod.py": """
        import threading

        class Registry:
            def __init__(self):
                self.counters = {}
                self._lock = threading.Lock()

            def bad(self, name):
                self.counters[name] = 0
    """})
    assert len(found) == 1
    assert "self.counters" in found[0].message


def test_qfx004_quiet_under_lock_and_locked_suffix(tmp_path):
    found = findings_for(tmp_path, "QFX004", {"mod.py": _LOCK_CLASS})
    assert found == []


# --- QFX005 donation-after-use ------------------------------------------------


def test_qfx005_fires_on_read_after_donating_dispatch(tmp_path):
    found = findings_for(tmp_path, "QFX005", {"mod.py": """
        import jax

        def train(step, theta, xs):
            fast = jax.jit(step, donate_argnums=(0,))
            out = fast(theta, xs)
            return theta  # donated buffer read back
    """})
    assert len(found) == 1
    assert "'theta'" in found[0].message


def test_qfx005_quiet_on_chaining_rebind(tmp_path):
    found = findings_for(tmp_path, "QFX005", {"mod.py": """
        import jax

        def train(step, theta, xs):
            fast = jax.jit(step, donate_argnums=(0,))
            for x in xs:
                theta, stats = fast(theta, x)
            return theta
    """})
    assert found == []


def test_qfx005_fires_on_loop_alias(tmp_path):
    found = findings_for(tmp_path, "QFX005", {"mod.py": """
        import jax

        def train(step, theta, xs):
            fast = jax.jit(step, donate_argnums=(0,))
            refs = []
            for x in xs:
                theta, stats = fast(theta, x)
                ref = theta
                refs.append(ref)
            return refs
    """})
    assert len(found) == 1
    assert "alias 'ref'" in found[0].message


# --- suppression semantics ----------------------------------------------------


def test_suppression_with_reason_silences_and_counts(tmp_path):
    cfg = make_repo(tmp_path, {"mod.py": """
        import os
        a = os.environ.get("QFEDX_X")  # qfedx: ignore[QFX002] fixture exemption
    """})
    result = run_lint(config=cfg, rules=("QFX000", "QFX002"))
    assert result.findings == []
    assert result.suppressed == 1


def test_reasonless_suppression_is_a_finding_and_cannot_self_suppress(
    tmp_path,
):
    cfg = make_repo(tmp_path, {"mod.py": """
        import os
        a = os.environ.get("QFEDX_X")  # qfedx: ignore[QFX002,QFX000]
    """})
    result = run_lint(config=cfg, rules=("QFX000", "QFX002"))
    assert [f.rule for f in result.findings] == ["QFX000"]
    assert result.suppressed == 1  # the QFX002 half still suppressed


def test_suppression_grammar_in_strings_is_inert(tmp_path):
    # The grammar inside a docstring or string literal is documentation,
    # not an exemption: it must neither suppress a finding on its line
    # nor trip QFX000 (reasonless) — only real COMMENT tokens count.
    cfg = make_repo(tmp_path, {"mod.py": '''
        """Example: x()  # qfedx: ignore[QFX002]"""
        import os
        s = 'os.environ  # qfedx: ignore[QFX002]'; a = os.environ.get("QFEDX_X")
    '''})
    result = run_lint(config=cfg, rules=("QFX000", "QFX002"))
    assert [f.rule for f in result.findings] == ["QFX002"]
    assert result.suppressed == 0


def test_suppression_of_other_rule_does_not_silence(tmp_path):
    cfg = make_repo(tmp_path, {"mod.py": """
        import os
        a = os.environ.get("QFEDX_X")  # qfedx: ignore[QFX005] wrong rule
    """})
    result = run_lint(config=cfg, rules=("QFX002",))
    assert [f.rule for f in result.findings] == ["QFX002"]


# --- baseline semantics -------------------------------------------------------


def _baseline(tmp_path, entries):
    (tmp_path / "baseline.json").write_text(
        json.dumps({"version": 1, "entries": entries})
    )


def test_baseline_hides_matching_finding_by_line_text(tmp_path):
    cfg = make_repo(tmp_path, {"mod.py": """
        import os
        a = os.environ.get("QFEDX_X")
    """})
    _baseline(tmp_path, [{
        "rule": "QFX002", "path": "pkg/mod.py",
        "text": 'a = os.environ.get("QFEDX_X")', "reason": "fixture",
    }])
    result = run_lint(config=cfg, rules=("QFX002",))
    assert result.findings == []
    assert len(result.baselined) == 1
    assert result.ok


def test_baseline_is_multiset_and_stale_entries_fail(tmp_path):
    cfg = make_repo(tmp_path, {"mod.py": """
        import os
        a = os.environ.get("QFEDX_X")
    """})
    _baseline(tmp_path, [
        {"rule": "QFX002", "path": "pkg/mod.py",
         "text": 'a = os.environ.get("QFEDX_X")'},
        {"rule": "QFX002", "path": "pkg/gone.py",
         "text": "vanished = os.environ"},
    ])
    result = run_lint(config=cfg, rules=("QFX002",))
    assert result.findings == []
    assert len(result.baselined) == 1
    assert len(result.stale_baseline) == 1  # the gone.py entry
    assert not result.ok  # stale entries fail the run


def test_baseline_entries_for_unselected_rules_are_ignored(tmp_path):
    cfg = make_repo(tmp_path, {"mod.py": "x = 1\n"})
    _baseline(tmp_path, [{
        "rule": "QFX002", "path": "pkg/mod.py", "text": "whatever",
    }])
    result = run_lint(config=cfg, rules=("QFX005",))
    assert result.ok  # a subset run can't judge other rules' entries


def test_update_baseline_subset_run_preserves_other_rules(tmp_path):
    # A `--rules` subset rewrite must not drop entries it never judged:
    # run_lint ignores other rules' entries for matching AND staleness,
    # so write_baseline(rules_run=...) preserves them verbatim.
    from qfedx_tpu.analysis.engine import (
        LintContext,
        load_baseline,
        write_baseline,
    )

    cfg = make_repo(tmp_path, {"mod.py": """
        import os
        a = os.environ.get("QFEDX_X")
    """})
    _baseline(tmp_path, [{
        "rule": "QFX005", "path": "pkg/other.py",
        "text": "return theta", "reason": "kept: not judged by QFX002",
    }])
    result = run_lint(config=cfg, rules=("QFX002",))
    n = write_baseline(
        cfg.baseline_path, LintContext(cfg),
        result.findings + result.baselined,
        rules_run=result.rules_run,
    )
    entries = load_baseline(cfg.baseline_path)
    assert n == len(entries) == 2
    assert {e["rule"] for e in entries} == {"QFX002", "QFX005"}
    # and the rewritten file round-trips clean for the subset
    assert run_lint(config=cfg, rules=("QFX002",)).ok


def test_loader_parse_cache_shared_across_rel_keys(tmp_path):
    # One parse per file regardless of how callers key it: the engine
    # (repo-relative rels) and the historical check_* surfaces
    # (package-relative rels) must share tree objects, and a second
    # engine run must not re-parse (the sub-second CLI contract).
    cfg = make_repo(tmp_path, {"mod.py": "x = 1\n"})
    pkg_rel = load_tree(tmp_path / "pkg")["mod.py"]
    repo_rel = load_tree(tmp_path / "pkg", rel_prefix="pkg")["pkg/mod.py"]
    assert pkg_rel.tree is repo_rel.tree
    assert pkg_rel.rel == "mod.py" and repo_rel.rel == "pkg/mod.py"
    assert repo_rel.name == "pkg.mod"
    again = load_tree(tmp_path / "pkg", rel_prefix="pkg")["pkg/mod.py"]
    assert again.tree is repo_rel.tree


# --- JSON schema round-trip ---------------------------------------------------


def test_json_report_round_trip(tmp_path):
    cfg = make_repo(tmp_path, {"mod.py": """
        import os
        a = os.environ.get("QFEDX_X")
    """})
    result = run_lint(config=cfg, rules=("QFX002",))
    data = json.loads(render_json(result))
    assert data["version"] == 1
    assert data["ok"] is False
    assert data["counts_by_rule"] == {"QFX002": 1}
    assert data["summary"] == {
        "new": 1, "baselined": 0, "suppressed": 0, "stale_baseline": 0,
    }
    (finding,) = data["findings"]
    assert set(finding) == {"rule", "path", "line", "message", "baselined"}
    assert finding["path"] == "pkg/mod.py"
    assert isinstance(finding["line"], int)
    assert "lint:" in data["delta"]


# --- call-graph reachability --------------------------------------------------


def _graph(tmp_path, files):
    pkg = tmp_path / "cgpkg"
    pkg.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    for rel, text in files.items():
        p = pkg / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    return build_callgraph(load_tree(pkg, rel_prefix="cgpkg"))


def test_callgraph_direct_call_reachability(tmp_path):
    g = _graph(tmp_path, {"a.py": """
        import jax

        def leaf():
            return 1

        def root(x):
            return leaf() + x

        fast = jax.jit(root)
    """})
    reach = g.reachable_from_traced()
    assert "cgpkg/a.py::root" in reach
    assert "cgpkg/a.py::leaf" in reach
    assert reach["cgpkg/a.py::leaf"] == [
        "cgpkg/a.py::root", "cgpkg/a.py::leaf",
    ]


def test_callgraph_aliased_import_reachability(tmp_path):
    g = _graph(tmp_path, {
        "helpers.py": """
            def impure():
                return 1
        """,
        "b.py": """
            import jax
            from cgpkg.helpers import impure as imp

            def root(x):
                return imp() + x

            fast = jax.jit(root)
        """,
    })
    reach = g.reachable_from_traced()
    assert "cgpkg/helpers.py::impure" in reach


def test_callgraph_method_call_reachability(tmp_path):
    g = _graph(tmp_path, {"c.py": """
        import jax

        class Engine:
            def helper(self):
                return 2

            @jax.jit
            def apply(self, x):
                return self.helper() * x
    """})
    reach = g.reachable_from_traced()
    assert "cgpkg/c.py::Engine.apply" in reach
    assert "cgpkg/c.py::Engine.helper" in reach


def test_callgraph_lambda_and_nested_roots(tmp_path):
    g = _graph(tmp_path, {"d.py": """
        import jax

        def outer():
            def inner(x):
                return x + 1
            return jax.vmap(inner)
    """})
    assert "cgpkg/d.py::outer.inner" in g.reachable_from_traced()


def test_unknown_rule_id_raises(tmp_path):
    cfg = make_repo(tmp_path, {"mod.py": "x = 1\n"})
    with pytest.raises(ValueError, match="QFX999"):
        run_lint(config=cfg, rules=("QFX999",))


# ---------------------------------------------------------------------------
# Pallas kernel idioms must not false-positive (r19 scan-body kernel)
# ---------------------------------------------------------------------------
#
# ops/pallas_body.py reintroduced Pallas in r19. Kernel bodies are full of
# idioms that superficially resemble lint violations: ``pl.program_id`` looks
# like a runtime-environment read, ``@pl.when`` wraps a nested def whose only
# job is a side effect, and the kernel communicates exclusively by mutating
# Ref arguments (``o_ref[...] = value``) from inside a jitted pallas_call.
# These fixtures pin that QFX001 (trace purity) and QFX003 (span discipline)
# stay quiet on that shape of code.

_PALLAS_KERNEL_MODULE = """
    import functools

    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl


    def _kernel(in_re, in_im, out_re, out_im, bnd_re):
        layer = pl.program_id(1)

        @pl.when(layer == 0)
        def _seed():
            out_re[...] = in_re[...]
            out_im[...] = in_im[...]

        bnd_re[0] = out_re[...]
        sre = out_re[...]
        sim = out_im[...]
        out_re[...] = sre - sim
        out_im[...] = sre + sim


    @jax.jit
    def run(packed):
        return pl.pallas_call(
            _kernel,
            out_shape=[
                jax.ShapeDtypeStruct(packed.shape[1:], packed.dtype),
                jax.ShapeDtypeStruct(packed.shape[1:], packed.dtype),
                jax.ShapeDtypeStruct((1,) + packed.shape[1:], packed.dtype),
            ],
            grid=(1, 1),
        )(packed[0], packed[1])
"""


def test_qfx001_quiet_on_pallas_kernel_idioms(tmp_path):
    # program_id reads, pl.when-wrapped nested defs, and Ref mutation are
    # all trace-pure: nothing here escapes to the host environment.
    assert findings_for(tmp_path, "QFX001", {"kern.py": _PALLAS_KERNEL_MODULE}) == []


def test_qfx003_quiet_on_pallas_kernel_with_spans(tmp_path):
    # A with-item span wrapping a pallas_call launch, plus Ref-mutation
    # idioms inside the kernel, must not trip the span-discipline rule.
    src = """
        import jax
        from jax.experimental import pallas as pl
        from qfedx_tpu.utils import obs


        def _kernel(x_ref, o_ref):
            i = pl.program_id(0)

            @pl.when(i == 0)
            def _init():
                o_ref[...] = x_ref[...]

            o_ref[...] = o_ref[...] * 2.0


        def launch(x):
            with obs.span("pallas.launch"):
                return pl.pallas_call(
                    _kernel,
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                    grid=(1,),
                )(x)
    """
    assert findings_for(tmp_path, "QFX003", {"kern.py": src}) == []
