"""Tier-1 lint: telemetry goes through obs/metrics, not print().

``benchmarks/check_no_print.py`` holds the single definition (AST scan,
allowlist); this test wires it into the suite so a stray print() in
library code fails CI, not a code review.
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from benchmarks.check_no_print import ALLOWED, find_prints  # noqa: E402


def test_no_bare_print_in_package():
    offenders = find_prints()
    assert offenders == [], (
        "bare print() in qfedx_tpu/ — route telemetry through obs "
        f"spans/counters or run/metrics JSONL: {offenders}"
    )


def test_allowlist_is_minimal():
    # The allowlist names the two terminal-output entry points and
    # nothing else; growing it should be a conscious diff here.
    assert ALLOWED == {"run/cli.py", "run/demo.py"}
