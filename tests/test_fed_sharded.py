"""2-D mesh federation: clients axis × sharded-statevector axis.

The combined parallelism program (SURVEY.md §7.3.1 + §7.3.5): federated
clients as one mesh axis, each client's quantum state sharded over the
other. Correctness anchor: the sharded VQC must produce the same logits and
the same federated round as the dense VQC with identical parameters.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from qfedx_tpu.fed.config import FedConfig
from qfedx_tpu.fed.round import make_fed_round, shard_client_data
from qfedx_tpu.models.vqc import make_vqc_classifier
from qfedx_tpu.models.vqc_sharded import (
    fed_mesh_2d,
    host_apply,
    make_sharded_vqc_classifier,
)
from qfedx_tpu.utils.compat import shard_map

N_QUBITS = 5  # 2 global (sv=4), 3 local


@pytest.fixture(scope="module")
def mesh2d():
    return fed_mesh_2d(num_client_devices=2, sv_size=4)


@pytest.fixture(scope="module")
def models():
    dense = make_vqc_classifier(N_QUBITS, n_layers=2, num_classes=2)
    sharded = make_sharded_vqc_classifier(
        N_QUBITS, sv_size=4, n_layers=2, num_classes=2
    )
    return dense, sharded


def test_sharded_apply_matches_dense(mesh2d, models):
    dense, sharded = models
    params = dense.init(jax.random.PRNGKey(0))
    assert jax.tree.structure(params) == jax.tree.structure(
        sharded.init(jax.random.PRNGKey(0))
    )
    x = jnp.asarray(
        np.random.default_rng(0).uniform(0, 1, (6, N_QUBITS)), dtype=jnp.float32
    )
    got = np.asarray(host_apply(sharded, mesh2d)(params, x))
    want = np.asarray(dense.apply(params, x))
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_fed_round_2d_matches_dense_1d(mesh2d, models):
    """One federated round on the (2, 4) mesh ≡ the same round computed with
    the dense model on a 1-D client mesh (same params, data, keys)."""
    dense, sharded = models
    clients, samples = 4, 8
    cfg = FedConfig(local_epochs=1, batch_size=4, learning_rate=0.1, momentum=0.0)
    rng = np.random.default_rng(1)
    cx = rng.uniform(0, 1, (clients, samples, N_QUBITS)).astype(np.float32)
    cy = rng.integers(0, 2, (clients, samples)).astype(np.int32)
    cm = np.ones((clients, samples), dtype=np.float32)
    params = dense.init(jax.random.PRNGKey(7))
    rkey = jax.random.PRNGKey(9)

    round_2d = make_fed_round(sharded, cfg, mesh2d, num_clients=clients)
    sx, sy, sm = shard_client_data(mesh2d, cx, cy, jnp.asarray(cm))
    p2d, stats2d = round_2d(params, sx, sy, sm, rkey)

    from qfedx_tpu.fed.round import client_mesh

    mesh1d = client_mesh(num_devices=4)
    round_1d = make_fed_round(dense, cfg, mesh1d, num_clients=clients)
    dx, dy, dm = shard_client_data(mesh1d, cx, cy, jnp.asarray(cm))
    p1d, stats1d = round_1d(params, dx, dy, dm, rkey)

    np.testing.assert_allclose(
        float(stats2d.mean_loss), float(stats1d.mean_loss), atol=1e-4
    )
    for a, b in zip(jax.tree.leaves(p2d), jax.tree.leaves(p1d)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


@pytest.mark.slow
def test_fed_round_2d_converges(mesh2d, models):
    """Multi-round training on the 2-D mesh drives the loss down."""
    _, sharded = models
    clients, samples = 4, 16
    cfg = FedConfig(
        local_epochs=2, batch_size=8, learning_rate=0.2, optimizer="adam"
    )
    rng = np.random.default_rng(2)
    cx = rng.uniform(0, 1, (clients, samples, N_QUBITS)).astype(np.float32)
    cy = (cx[..., 0] > 0.5).astype(np.int32)
    cm = np.ones((clients, samples), dtype=np.float32)
    round_fn = make_fed_round(sharded, cfg, mesh2d, num_clients=clients)
    sx, sy, sm = shard_client_data(mesh2d, cx, cy, jnp.asarray(cm))
    params = sharded.init(jax.random.PRNGKey(0))
    losses = []
    for r in range(8):
        params, stats = round_fn(params, sx, sy, sm, jax.random.PRNGKey(100 + r))
        losses.append(float(stats.mean_loss))
    assert losses[-1] < losses[0], losses


def test_sharded_amplitude_encoding_matches_dense(mesh2d):
    """Amplitude encoding on the sharded engine (2^n features → sharded
    state) ≡ dense, including the all-zero → uniform fallback."""
    dense = make_vqc_classifier(
        N_QUBITS, n_layers=2, num_classes=2, encoding="amplitude"
    )
    sharded = make_sharded_vqc_classifier(
        N_QUBITS, sv_size=4, n_layers=2, num_classes=2, encoding="amplitude"
    )
    params = dense.init(jax.random.PRNGKey(1))
    rng = np.random.default_rng(3)
    x = rng.normal(size=(5, 1 << N_QUBITS)).astype(np.float32)
    x[2] = 0.0  # uniform-superposition fallback row
    got = np.asarray(host_apply(sharded, mesh2d)(params, jnp.asarray(x)))
    want = np.asarray(dense.apply(params, jnp.asarray(x)))
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_sharded_readout_noise_matches_dense(mesh2d):
    """Analytic readout channels act on the replicated post-psum ⟨Z⟩, so
    sharded eval under noise ≡ dense eval under the same NoiseModel."""
    from qfedx_tpu.noise.channels import NoiseModel

    nm = NoiseModel(depolarizing_p=0.2, amp_damping_gamma=0.1, readout_e01=0.05,
                    readout_e10=0.05)
    dense = make_vqc_classifier(N_QUBITS, n_layers=2, num_classes=2, noise_model=nm)
    sharded = make_sharded_vqc_classifier(
        N_QUBITS, sv_size=4, n_layers=2, num_classes=2, noise_model=nm
    )
    params = dense.init(jax.random.PRNGKey(2))
    x = jnp.asarray(
        np.random.default_rng(4).uniform(0, 1, (4, N_QUBITS)), dtype=jnp.float32
    )
    got = np.asarray(host_apply(sharded, mesh2d)(params, x))
    want = np.asarray(dense.apply(params, x))
    np.testing.assert_allclose(got, want, atol=1e-4)


@pytest.mark.slow
def test_sharded_trajectory_noise_matches_dense_sample_for_sample(mesh2d):
    """Circuit-level Kraus trajectories: the sharded engine computes global
    branch norms (psum) and samples with the replicated key using the dense
    engine's exact fold layout — so the SAME key must select the SAME
    branches and produce identical logits, not just equal distributions."""
    from qfedx_tpu.noise.channels import NoiseModel

    nm = NoiseModel(depolarizing_p=0.15, amp_damping_gamma=0.1, circuit_level=True)
    dense = make_vqc_classifier(N_QUBITS, n_layers=2, num_classes=2, noise_model=nm)
    sharded = make_sharded_vqc_classifier(
        N_QUBITS, sv_size=4, n_layers=2, num_classes=2, noise_model=nm
    )
    assert dense.apply_train is not None and sharded.apply_train is not None
    params = dense.init(jax.random.PRNGKey(5))
    x = jnp.asarray(
        np.random.default_rng(6).uniform(0, 1, (4, N_QUBITS)), dtype=jnp.float32
    )
    key = jax.random.PRNGKey(77)
    from jax.sharding import PartitionSpec as P

    sh_fn = jax.jit(
        shard_map(
            sharded.apply_train,
            mesh=mesh2d,
            in_specs=(P(), P(), P()),
            out_specs=P(),
            check_vma=False,
        )
    )
    got = np.asarray(sh_fn(params, x, key))
    want = np.asarray(dense.apply_train(params, x, key))
    np.testing.assert_allclose(got, want, atol=1e-4)


@pytest.mark.slow
def test_sharded_shots_train_matches_dense(mesh2d):
    """Finite-shot training noise: replicated key ⇒ identical binomial
    draws on sharded and dense paths."""
    from jax.sharding import PartitionSpec as P

    from qfedx_tpu.noise.channels import NoiseModel

    nm = NoiseModel(shots=128)
    dense = make_vqc_classifier(N_QUBITS, n_layers=1, num_classes=2, noise_model=nm)
    sharded = make_sharded_vqc_classifier(
        N_QUBITS, sv_size=4, n_layers=1, num_classes=2, noise_model=nm
    )
    params = dense.init(jax.random.PRNGKey(8))
    x = jnp.asarray(
        np.random.default_rng(9).uniform(0, 1, (4, N_QUBITS)), dtype=jnp.float32
    )
    key = jax.random.PRNGKey(21)
    sh_fn = jax.jit(
        shard_map(
            sharded.apply_train,
            mesh=mesh2d,
            in_specs=(P(), P(), P()),
            out_specs=P(),
            check_vma=False,
        )
    )
    got = np.asarray(sh_fn(params, x, key))
    want = np.asarray(dense.apply_train(params, x, key))
    np.testing.assert_allclose(got, want, atol=1e-4)
    # eval stays exact/deterministic
    e1 = np.asarray(host_apply(sharded, mesh2d)(params, x))
    e2 = np.asarray(host_apply(sharded, mesh2d)(params, x))
    np.testing.assert_allclose(e1, e2)


@pytest.mark.slow
def test_cli_sv_size_trains_end_to_end(tmp_path):
    """VERDICT round-1 item 2 criterion: the CLI-built sharded path —
    ``train --model vqc --qubits 8 --sv-size 4`` — runs on the 8-device
    mesh (2 client groups × 4-way sv sharding) and produces run artifacts."""
    from qfedx_tpu.run.cli import build_parser, config_from_args, run_train

    cfg = config_from_args(
        build_parser().parse_args(
            [
                "train", "--model", "vqc", "--qubits", "8", "--sv-size", "4",
                "--layers", "1", "--classes", "0,1", "--clients", "4",
                "--rounds", "2", "--local-epochs", "1", "--batch-size", "8",
                "--lr", "0.1", "--optimizer", "adam",
                "--run-root", str(tmp_path), "--name", "sv",
            ]
        )
    )
    assert cfg.model.sv_size == 4
    summary = run_train(cfg)
    assert 0.0 <= summary["final_accuracy"] <= 1.0
    assert (tmp_path / "sv" / "summary.json").exists()


def test_mesh_validation():
    with pytest.raises(ValueError, match="power of two"):
        make_sharded_vqc_classifier(6, sv_size=3)
    with pytest.raises(ValueError, match="local qubits"):
        make_sharded_vqc_classifier(3, sv_size=4)
    with pytest.raises(ValueError, match="devices"):
        fed_mesh_2d(num_client_devices=4, sv_size=4)
