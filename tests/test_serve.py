"""Serving subsystem (r14): engine padding parity, warm-bucket compile
contract, micro-batcher flush/shed/drain discipline, fault sites, the
shared persistent-forward cache, and the CLI round trip.

Shapes are tiny (4 qubits, 1 layer) — tier-1 budget discipline: the
serving invariants are shape-independent, and the dense-width serving
numbers are bench.py's job (`_bench_serve`), not a unit test's.
"""

from __future__ import annotations

import json
import threading
import time

import jax
import numpy as np
import pytest

from qfedx_tpu import obs
from qfedx_tpu.models.vqc import make_vqc_classifier
from qfedx_tpu.serve import (
    MicroBatcher,
    Overloaded,
    RequestError,
    ServeConfig,
    ServeEngine,
    ShuttingDown,
    engine_from_run_dir,
    persistent_forward,
)
from qfedx_tpu.utils.faults import FaultPlan
from qfedx_tpu.utils.retry import RetryExhausted

N = 4
FEATS = (N,)


def _engine(buckets=(1, 2, 4), deadline_ms=150.0, max_queue=8, seed=0):
    model = make_vqc_classifier(n_qubits=N, n_layers=1, num_classes=2)
    params = model.init(jax.random.PRNGKey(seed))
    cfg = ServeConfig(
        buckets=buckets, deadline_ms=deadline_ms, max_queue=max_queue
    )
    return ServeEngine(model, params, FEATS, config=cfg), model, params


def _rows(m, seed=0):
    return np.random.default_rng(seed).uniform(0, 1, (m, N)).astype(
        np.float32
    )


# -- config / pin grammar ------------------------------------------------------


def test_serve_config_validation():
    with pytest.raises(ValueError, match="ascending"):
        ServeConfig(buckets=(4, 2))
    with pytest.raises(ValueError, match="ascending"):
        ServeConfig(buckets=(2, 2))
    with pytest.raises(ValueError, match="non-empty"):
        ServeConfig(buckets=())
    with pytest.raises(ValueError, match="deadline_ms"):
        ServeConfig(deadline_ms=0)
    with pytest.raises(ValueError, match="max_queue"):
        ServeConfig(max_queue=0)


def test_serve_pins_resolve_and_reject(monkeypatch):
    monkeypatch.setenv("QFEDX_SERVE_BUCKETS", "2,16")
    monkeypatch.setenv("QFEDX_SERVE_DEADLINE_MS", "7.5")
    monkeypatch.setenv("QFEDX_SERVE_QUEUE", "9")
    cfg = ServeConfig.resolve()
    assert cfg.buckets == (2, 16)
    assert cfg.deadline_ms == 7.5 and cfg.max_queue == 9
    # explicit args beat pins (CLI > pin > default)
    assert ServeConfig.resolve(buckets=(4,)).buckets == (4,)
    monkeypatch.setenv("QFEDX_SERVE_BUCKETS", "fast")
    with pytest.raises(ValueError, match="QFEDX_SERVE_BUCKETS"):
        ServeConfig.resolve()
    monkeypatch.setenv("QFEDX_SERVE_BUCKETS", "2,16")
    monkeypatch.setenv("QFEDX_SERVE_QUEUE", "-3")
    with pytest.raises(ValueError, match="QFEDX_SERVE_QUEUE"):
        ServeConfig.resolve()


# -- padding parity (ISSUE r14 satellite) --------------------------------------


@pytest.mark.parametrize("dtype", ["f32", "bf16"])
def test_padding_parity_bit_identical(monkeypatch, dtype):
    """A batch padded up to a bucket must answer the REAL rows
    bit-identically to the unpadded forward — every engine route is
    row-independent, so padding is purely shape plumbing; and the pad
    rows are sliced off before any readout post-processing."""
    if dtype == "bf16":
        monkeypatch.setenv("QFEDX_DTYPE", "bf16")
    engine, model, params = _engine(buckets=(8,))
    x = _rows(3)
    padded = engine.infer(x)
    exact = np.asarray(persistent_forward(model.apply)(params, x))
    assert padded.shape == (3, 2)
    assert np.array_equal(padded, exact), (
        f"{dtype}: padded bucket forward != unpadded forward on real rows"
    )
    # postprocess normalizes over the already-sliced rows only
    post = engine.postprocess(padded)
    assert post["probs"].shape == (3, 2)
    assert np.allclose(post["probs"].sum(axis=1), 1.0, atol=1e-6)


def test_pad_rows_never_reach_responses():
    engine, model, params = _engine(buckets=(4,))
    with MicroBatcher(engine) as b:
        futs = [b.submit(r) for r in _rows(2)]
        out = [f.result(timeout=30) for f in futs]
    assert len(out) == 2
    for rec in out:
        assert rec["logits"].shape == (2,)
        assert np.all(np.isfinite(rec["probs"]))


# -- warmup / zero-compile contract --------------------------------------------


def test_warmup_compiles_every_bucket_no_compile_in_loop(monkeypatch):
    """The serving-loop compile contract, asserted via the obs
    compile-attribution listener (r08): warmup's spans absorb all
    compile time; every serve.compute span after it carries
    compile_s == 0 and the compile.* counters do not move."""
    monkeypatch.setenv("QFEDX_TRACE", "1")
    obs.reset()
    engine, _, _ = _engine(buckets=(1, 2, 4), deadline_ms=30.0)
    warm = engine.warmup()
    assert set(warm["buckets"]) == {1, 2, 4}
    # The resolved route answers scanned-vs-per-layer even when every
    # raw pin is unset (r17): booleans + a concrete dtype name, never "".
    assert isinstance(warm["route_resolved"]["fuse"], bool)
    assert isinstance(warm["route_resolved"]["scan_layers"], bool)
    assert warm["route_resolved"]["dtype"] in ("float32", "bfloat16")

    def compile_total():
        return sum(
            v for k, v in obs.registry().counters.items()
            if k.startswith("compile.")
        )

    compiled_at_warmup = compile_total()
    assert compiled_at_warmup > 0, "warmup should have compiled the buckets"
    with MicroBatcher(engine) as b:
        futs = [b.submit(r) for r in _rows(1)]
        futs += [b.submit(r) for r in _rows(2, seed=1)]
        futs += [b.submit(r) for r in _rows(4, seed=2)]
        for f in futs:
            f.result(timeout=30)
    assert compile_total() == compiled_at_warmup, (
        "a compile fired inside the serving loop"
    )
    compute_spans = [
        s for s in obs.registry().spans if s.name == "serve.compute"
    ]
    assert compute_spans, "serving should have recorded serve.compute spans"
    assert all(s.compile_s == 0.0 for s in compute_spans)


def test_eval_and_serving_share_one_compiled_artifact(monkeypatch):
    """The r14 eval satellite: make_evaluator instances and the serve
    engine route through ONE persistent-forward wrapper per (model,
    route) — building a second evaluator (the trainer's capped + full
    pair) or warming a same-shaped bucket triggers NO new compile."""
    from qfedx_tpu.fed.evaluate import make_evaluator

    monkeypatch.setenv("QFEDX_TRACE", "1")
    obs.reset()
    model = make_vqc_classifier(n_qubits=N, n_layers=1, num_classes=2)
    params = model.init(jax.random.PRNGKey(0))
    x, y = _rows(6), np.array([0, 1] * 3)

    def compile_total():
        return sum(
            v for k, v in obs.registry().counters.items()
            if k.startswith("compile.")
        )

    ev_full = make_evaluator(model, batch_size=4)
    ev_full(params, x, y)
    first = compile_total()
    assert first > 0
    ev_capped = make_evaluator(model, batch_size=4, max_batches=1)
    ev_capped(params, x, y)
    assert compile_total() == first, (
        "second evaluator recompiled the same forward (the pre-r14 "
        "duplicate-compile leak)"
    )
    cfg = ServeConfig(buckets=(4,), deadline_ms=10.0, max_queue=8)
    engine = ServeEngine(model, params, FEATS, config=cfg)
    engine.warmup()
    assert compile_total() == first, (
        "serve warmup recompiled the evaluator's executable"
    )


def test_forward_cache_frees_dropped_models():
    """The cache must not pin dead models: wrappers are anchored on the
    forward callable itself, so dropping the model collects the whole
    cycle (a global registry holding wrappers would keep every sweep
    cell's executables alive forever)."""
    import gc
    import weakref

    model = make_vqc_classifier(n_qubits=N, n_layers=1, num_classes=2)
    ref = weakref.ref(model.apply)
    assert persistent_forward(model.apply) is persistent_forward(model.apply)
    del model
    gc.collect()
    assert ref() is None, (
        "dropped model's forward is still pinned by the persistent-"
        "forward cache"
    )


def test_forward_cache_is_route_keyed(monkeypatch):
    """The shared forward resolves the routing pins PER CALL: a forward
    bound before a pin flip (an evaluator built outside a with_env
    window, called inside it) dispatches to the flipped route, and the
    flip never contaminates the original route's executable."""
    from qfedx_tpu.serve.forward import cached_routes

    model = make_vqc_classifier(n_qubits=N, n_layers=1, num_classes=2)
    params = model.init(jax.random.PRNGKey(0))
    shared = persistent_forward(model.apply)
    assert persistent_forward(model.apply) is shared
    x = _rows(2)
    f32_out = np.asarray(shared(params, x))
    assert cached_routes(model.apply) == 1
    monkeypatch.setenv("QFEDX_DTYPE", "bf16")
    shared(params, x)  # same facade, dispatches to a NEW route wrapper
    assert cached_routes(model.apply) == 2, (
        "pin flip did not resolve to its own route wrapper"
    )
    monkeypatch.delenv("QFEDX_DTYPE")
    assert np.array_equal(np.asarray(shared(params, x)), f32_out), (
        "original route's executable was contaminated by the pin flip"
    )
    assert cached_routes(model.apply) == 2  # restored route re-used, not re-jitted


# -- micro-batcher flush / shed / drain ----------------------------------------


def test_bucket_full_flush_beats_deadline():
    engine, _, _ = _engine(buckets=(1, 2, 4), deadline_ms=5000.0)
    engine.warmup()
    with MicroBatcher(engine) as b:
        t0 = time.monotonic()
        futs = [b.submit(r) for r in _rows(4)]
        for f in futs:
            f.result(timeout=30)
        elapsed = time.monotonic() - t0
    assert elapsed < 4.0, "a full bucket waited for the deadline"
    assert b.stats["full_flushes"] >= 1
    assert b.stats["deadline_flushes"] == 0


def test_deadline_flush_fires_for_partial_bucket():
    engine, _, _ = _engine(buckets=(4,), deadline_ms=150.0)
    engine.warmup()
    with MicroBatcher(engine) as b:
        t0 = time.monotonic()
        fut = b.submit(_rows(1)[0])
        fut.result(timeout=30)
        elapsed = time.monotonic() - t0
    assert elapsed >= 0.05, (
        "a lone request flushed before its deadline window"
    )
    assert b.stats["deadline_flushes"] >= 1
    assert b.stats["full_flushes"] == 0


def test_bounded_queue_sheds_with_exact_count():
    engine, _, _ = _engine(buckets=(1,), deadline_ms=5.0, max_queue=2)
    engine.warmup()
    started, release = threading.Event(), threading.Event()
    orig = engine.infer

    def gated(x, seq=0):
        started.set()
        release.wait(timeout=30)
        return orig(x, seq)

    engine.infer = gated
    b = MicroBatcher(engine).start()
    try:
        first = b.submit(_rows(1)[0])
        assert started.wait(timeout=10)  # dispatcher now blocked in infer
        queued = [b.submit(r) for r in _rows(2, seed=1)]  # fills max_queue
        with pytest.raises(Overloaded):
            b.submit(_rows(1, seed=2)[0])
        assert b.stats["shed"] == 1
    finally:
        release.set()
        b.close(drain=True)
    for f in [first, *queued]:
        assert f.result(timeout=30)["logits"].shape == (2,)


def test_sigterm_drains_in_flight_requests():
    """The CLI's shutdown discipline (mirrors run_serve): SIGTERM lands
    as KeyboardInterrupt on the main thread, and the drain answers every
    admitted request before exit — none dropped, none errored."""
    import os
    import signal as signal_mod

    engine, _, _ = _engine(buckets=(2,), deadline_ms=50.0)
    engine.warmup()
    orig = engine.infer

    def slow(x, seq=0):
        time.sleep(0.05)
        return orig(x, seq)

    engine.infer = slow

    def _on_sigterm(signum, frame):
        raise KeyboardInterrupt("SIGTERM")

    prev = signal_mod.signal(signal_mod.SIGTERM, _on_sigterm)
    b = MicroBatcher(engine).start()
    try:
        futs = [b.submit(r) for r in _rows(5)]
        with pytest.raises(KeyboardInterrupt, match="SIGTERM"):
            os.kill(os.getpid(), signal_mod.SIGTERM)
            time.sleep(5)  # the signal interrupts this sleep
        b.close(drain=True)
        assert all(f.done() for f in futs)
        for f in futs:
            assert f.result(timeout=1)["logits"].shape == (2,)
    finally:
        signal_mod.signal(signal_mod.SIGTERM, prev)
        b.close(drain=True)
    assert b.stats["served"] == 5


def test_close_without_drain_fails_pending():
    engine, _, _ = _engine(buckets=(1,), deadline_ms=10000.0, max_queue=8)
    engine.warmup()
    started, release = threading.Event(), threading.Event()
    orig = engine.infer

    def gated(x, seq=0):
        started.set()
        release.wait(timeout=30)
        return orig(x, seq)

    engine.infer = gated
    b = MicroBatcher(engine).start()
    head = b.submit(_rows(1)[0])
    assert started.wait(timeout=10)
    pending = [b.submit(r) for r in _rows(2, seed=1)]
    release.set()
    b.close(drain=False)
    head.result(timeout=30)  # in-compute batch still completes
    for f in pending:
        with pytest.raises(ShuttingDown):
            f.result(timeout=5)
    with pytest.raises(ShuttingDown):
        b.submit(_rows(1)[0])


# -- fault sites (r14 robustness satellite) ------------------------------------


def test_serve_request_fault_rejects_without_poisoning(monkeypatch):
    """A serve.request NaN mutation fails ITS OWN submit (the 4xx); the
    co-batched honest requests answer normally — the batch is never
    poisoned (the serving sibling of the r11 quarantine)."""
    plan = {"seed": 3, "rules": [
        {"site": "serve.request", "kind": "nan", "rounds": [1]},
    ]}
    monkeypatch.setenv("QFEDX_FAULTS", json.dumps(plan))
    engine, _, _ = _engine(buckets=(2,), deadline_ms=50.0)
    engine.warmup()
    rows = _rows(3)
    with MicroBatcher(engine) as b:
        ok0 = b.submit(rows[0])  # seq 0
        with pytest.raises(RequestError, match="NaN"):
            b.submit(rows[1])  # seq 1 — mutated by the plan
        ok2 = b.submit(rows[2])  # seq 2
        r0, r2 = ok0.result(timeout=30), ok2.result(timeout=30)
    assert b.stats["rejected"] == 1 and b.stats["served"] == 2
    assert np.all(np.isfinite(r0["logits"]))
    assert np.all(np.isfinite(r2["logits"]))


def test_serve_request_malformed_kind(monkeypatch):
    plan = {"seed": 3, "rules": [
        {"site": "serve.request", "kind": "malformed", "rounds": [0]},
    ]}
    monkeypatch.setenv("QFEDX_FAULTS", json.dumps(plan))
    engine, _, _ = _engine(buckets=(1,))
    with MicroBatcher(engine) as b:
        with pytest.raises(RequestError, match="shape"):
            b.submit(_rows(1)[0])


def test_serve_request_rule_grammar():
    for bad in ({"clients": [1]}, {"waves": [0]}, {"times": 1}):
        with pytest.raises(ValueError, match="serve.request"):
            FaultPlan(rules=[{"site": "serve.request", "kind": "nan", **bad}])
    with pytest.raises(ValueError, match="serve.request kind"):
        FaultPlan(rules=[{"site": "serve.request", "kind": "drop"}])
    # serve.compute is a plain error site: error kind only, times applies
    FaultPlan(rules=[{"site": "serve.compute", "times": 1}])
    with pytest.raises(ValueError, match="serve.compute"):
        FaultPlan(rules=[{"site": "serve.compute", "kind": "nan"}])


def test_serve_compute_transient_retries_and_recovers(monkeypatch):
    """times:1 fails attempt 0 of batch seq 1; the shared retry policy
    (seeded jitter) recovers in place — the request still answers."""
    plan = {"seed": 5, "rules": [
        {"site": "serve.compute", "rounds": [1], "times": 1},
    ]}
    monkeypatch.setenv("QFEDX_FAULTS", json.dumps(plan))
    engine, model, params = _engine(buckets=(2,))
    engine.warmup()
    out = engine.infer(_rows(2), seq=1)
    assert np.array_equal(
        out, np.asarray(persistent_forward(model.apply)(params, _rows(2)))
    )


def test_serve_compute_persistent_failure_surfaces(monkeypatch):
    plan = {"seed": 5, "rules": [
        {"site": "serve.compute", "rounds": [1]},  # every attempt
    ]}
    monkeypatch.setenv("QFEDX_FAULTS", json.dumps(plan))
    engine, _, _ = _engine(buckets=(1, 2), deadline_ms=30.0)
    engine.warmup()
    with pytest.raises(RetryExhausted):
        engine.infer(_rows(1), seq=1)
    # through the batcher the error lands on the batch's futures, and
    # the NEXT batch (seq 2) serves normally — no poisoned loop state
    monkeypatch.setenv("QFEDX_FAULTS", json.dumps(plan))
    with MicroBatcher(engine) as b:
        f1 = b.submit(_rows(1)[0])
        with pytest.raises(RetryExhausted):
            f1.result(timeout=30)
        f2 = b.submit(_rows(1, seed=1)[0])
        assert np.all(np.isfinite(f2.result(timeout=30)["logits"]))


# -- live telemetry + request-scoped tracing (r15) -----------------------------


from conftest import free_port as _free_port  # noqa: E402 — shared helper


def test_metrics_scrape_reconciles_with_batcher_ledger(monkeypatch):
    """The r15 acceptance pin: a live /metrics scrape's
    serve.requests_served / rejected / shed counters reconcile EXACTLY
    with the batcher's final ledger — including with QFEDX_TRACE off
    (the live-metrics gate), while the batcher runs."""
    import urllib.request

    from qfedx_tpu.obs import server as obs_server

    monkeypatch.delenv("QFEDX_TRACE", raising=False)
    port = _free_port()
    monkeypatch.setenv("QFEDX_METRICS_PORT", str(port))
    obs.reset()
    engine, _, _ = _engine(buckets=(1,), deadline_ms=5.0, max_queue=2)
    engine.warmup()
    started, release = threading.Event(), threading.Event()
    orig = engine.infer

    def gated(x, seq=0):
        started.set()
        release.wait(timeout=30)
        return orig(x, seq)

    engine.infer = gated
    b = MicroBatcher(engine).start()
    try:
        assert obs_server.active_server() is not None, (
            "batcher.start did not bring up the pinned endpoint"
        )
        first = b.submit(_rows(1)[0])
        assert started.wait(timeout=10)
        queued = [b.submit(r) for r in _rows(2, seed=1)]
        with pytest.raises(Overloaded):
            b.submit(_rows(1, seed=2)[0])  # shed
        with pytest.raises(RequestError):
            b.submit(np.zeros((N + 1,), np.float32))  # rejected (shape)
        # /healthz mid-run: the serve source reports the live queue
        hz = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10
        ).read())
        assert hz["components"]["serve"]["queue_depth"] == 2
        assert hz["components"]["serve"]["shed"] == 1
        release.set()
        for f in [first, *queued]:
            f.result(timeout=30)
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ).read().decode()
        scraped = {
            line.split(" ")[0]: float(line.split(" ")[1])
            for line in body.splitlines()
            if line and not line.startswith("#") and "{" not in line
        }
        assert scraped["qfedx_serve_requests_served"] == b.stats["served"] == 3
        assert scraped["qfedx_serve_requests_shed"] == b.stats["shed"] == 1
        assert (
            scraped["qfedx_serve_requests_rejected"]
            == b.stats["rejected"]
            == 1
        )
        assert scraped["qfedx_serve_batches"] == b.stats["batches"]
        assert scraped["qfedx_serve_latency_ms_count"] == 3
        # r21 build-info pin: the exposition leads with ONE labeled
        # gauge (value 1) naming versions/backend and the resolved
        # fuse/scan/pallas/dtype route — the process states what it is.
        build_lines = [
            line for line in body.splitlines()
            if line.startswith("qfedx_build_info{")
        ]
        assert len(build_lines) == 1 and build_lines[0].endswith(" 1")
        import jax as _jax

        assert f'backend="{_jax.default_backend()}"' in build_lines[0]
        for label in ("version=", "jax=", "dtype=", "fuse=", "scan=",
                      "pallas="):
            assert label in build_lines[0]
    finally:
        release.set()
        b.close(drain=True)
        obs_server.stop_server()
    # the batcher's health source unregisters on close
    from qfedx_tpu.obs.server import health_payload

    assert "serve" not in health_payload()["components"]


def test_serve_latency_histogram_p95_within_one_bucket(monkeypatch):
    """The histogram acceptance pin on the REAL serving path: the
    serve.latency_ms registry histogram's p95 lands within one
    bucket-width of the exact percentile of the futures' measured
    latencies (and never above it)."""
    monkeypatch.setenv("QFEDX_TRACE", "1")
    obs.reset()
    engine, _, _ = _engine(buckets=(1, 2, 4), deadline_ms=10.0, max_queue=64)
    engine.warmup()
    futs = []
    with MicroBatcher(engine) as b:
        for i in range(24):
            futs.append(b.submit(_rows(1, seed=i)[0]))
        for f in futs:
            f.result(timeout=30)
    exact = sorted((f.done_t - f.submit_t) * 1e3 for f in futs)
    h = obs.registry().histos["serve.latency_ms"]
    assert h.count == len(futs) == b.stats["served"]
    for q in (0.50, 0.95):
        exact_q = obs.percentile(exact, q)
        lo, hi = obs.Histogram.bucket_bounds(exact_q)
        approx = h.percentile(q)
        assert approx == lo and lo <= exact_q < hi, (
            f"q={q}: histogram {approx} not within one bucket "
            f"[{lo}, {hi}) of exact {exact_q}"
        )


def test_request_ids_propagate_into_serve_spans(monkeypatch):
    """Request-scoped tracing (r15 tentpole): the batcher propagates
    each flush's request seqs so serve.queue AND the engine's
    pad/compute/fetch spans carry the ids they served — per-request
    latency is decomposable in trace.json instead of batch-only."""
    monkeypatch.setenv("QFEDX_TRACE", "1")
    obs.reset()
    engine, _, _ = _engine(buckets=(1, 2, 4), deadline_ms=5000.0)
    engine.warmup()
    with MicroBatcher(engine) as b:
        futs = [b.submit(r) for r in _rows(4)]  # bucket-full flush
        for f in futs:
            f.result(timeout=30)
    expect = ",".join(str(f.seq) for f in futs)
    spans = obs.registry().spans
    for name in ("serve.queue", "serve.pad", "serve.compute", "serve.fetch"):
        tagged = [s for s in spans if s.name == name and "reqs" in s.meta]
        assert tagged, f"{name} spans carry no request ids"
        assert tagged[-1].meta["reqs"] == expect, (
            f"{name}: {tagged[-1].meta['reqs']} != {expect}"
        )
    # warmup spans predate any request and stay untagged
    assert all(
        "reqs" not in s.meta for s in spans if s.name == "serve.warmup"
    )


# -- restore + CLI round trip --------------------------------------------------


def _write_run_dir(tmp_path, seed=7):
    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.run.checkpoint import Checkpointer
    from qfedx_tpu.run.config import (
        DataConfig,
        ExperimentConfig,
        ModelConfig,
        build_model,
    )
    from qfedx_tpu.run.metrics import _jsonable

    cfg = ExperimentConfig(
        data=DataConfig(dataset="iris", classes=(0, 1), num_clients=2),
        model=ModelConfig(model="vqc", n_qubits=N, n_layers=1),
        fed=FedConfig(batch_size=8),
        seed=seed,
    )
    run_dir = tmp_path / "run"
    run_dir.mkdir()
    (run_dir / "config.json").write_text(json.dumps(_jsonable(cfg)))
    model = build_model(cfg, 2)
    params = model.init(jax.random.PRNGKey(seed))
    Checkpointer(run_dir / "checkpoints", every=1).save(3, params)
    return run_dir, model, params, cfg


def test_experiment_config_round_trip(tmp_path):
    from qfedx_tpu.fed.config import DPConfig, FedConfig
    from qfedx_tpu.run.config import (
        DataConfig,
        ExperimentConfig,
        ModelConfig,
        experiment_config_from_dict,
    )
    from qfedx_tpu.run.metrics import _jsonable

    cfg = ExperimentConfig(
        data=DataConfig(dataset="mnist", classes=(0, 1, 2)),
        model=ModelConfig(model="vqc", n_qubits=6, encoding="reupload"),
        fed=FedConfig(
            batch_size=16, optimizer="adam", secure_agg=True,
            dp=DPConfig(clip_norm=0.5, noise_multiplier=2.0),
        ),
        num_rounds=7,
        name="rt",
    )
    back = experiment_config_from_dict(
        json.loads(json.dumps(_jsonable(cfg)))
    )
    assert back == cfg
    # forward compat: unknown keys warn and are dropped, not fatal
    blob = json.loads(json.dumps(_jsonable(cfg)))
    blob["model"]["hyperdrive"] = 11
    with pytest.warns(RuntimeWarning, match="hyperdrive"):
        back2 = experiment_config_from_dict(blob)
    assert back2 == cfg


def test_engine_from_run_dir_serves_checkpoint(tmp_path):
    run_dir, model, params, _cfg = _write_run_dir(tmp_path)
    engine, info = engine_from_run_dir(
        run_dir, config=ServeConfig(buckets=(2,), deadline_ms=10.0)
    )
    assert info["round"] == 3 and info["num_classes"] == 2
    x = _rows(2)
    assert np.array_equal(
        engine.infer(x),
        np.asarray(persistent_forward(model.apply)(params, x)),
    )
    with pytest.raises(FileNotFoundError, match="config.json"):
        engine_from_run_dir(tmp_path / "nope")


def test_cli_serve_end_to_end(tmp_path, capsys):
    """`qfedx serve` answers a JSONL stream from a restored checkpoint:
    valid requests in order, malformed ones as per-request 400s."""
    from qfedx_tpu.run.cli import build_parser, run_serve

    run_dir, _, _, _ = _write_run_dir(tmp_path)
    req_path = tmp_path / "req.jsonl"
    out_path = tmp_path / "resp.jsonl"
    req_path.write_text(
        json.dumps({"id": "a", "features": [0.1] * N}) + "\n"
        + json.dumps([0.5] * N) + "\n"
        + json.dumps({"id": "bad", "features": [1.0, 2.0]}) + "\n"
    )
    args = build_parser().parse_args([
        "serve", "--run-dir", str(run_dir), "--buckets", "2",
        "--deadline-ms", "5", "--input", str(req_path),
        "--output", str(out_path),
    ])
    summary = run_serve(args)
    recs = [json.loads(l) for l in out_path.read_text().splitlines()]
    assert [r["id"] for r in recs] == ["a", 1, "bad"]
    assert "pred" in recs[0] and "probs" in recs[1]
    assert recs[2]["code"] == 400 and "shape" in recs[2]["error"]
    # served = engine-answered requests; responses = emitted JSONL lines
    # (including the 400) — served + rejected reconciles, no double count
    assert summary["served"] == 2 and summary["rejected"] == 1
    assert summary["responses"] == 3 and summary["shed"] == 0
