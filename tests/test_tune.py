"""The r21 closed tuning loop (tune/controller.py, tune/offline.py).

Covers the acceptance surface of the tentpole: the QFEDX_TUNE pin
grammar, default-off r20-invariance (no controller object, no tune.*
instruments), the drifting-load decision path — a real MicroBatcher
under singles traffic shrinks the bucket cap, an injected latency drift
tightens the deadline, a firing watchdog alert forces the one legal
move (revert-to-baseline) — with ZERO compile events after warmup and
EXACT three-surface reconciliation (metrics.jsonl event rows ==
tune.* counters == controller totals, gauges back at baseline), the
relax/grow directions re-opening the lattice, and the offline
`qfedx tune` sweep → best_config.json → `--tuned` restore round trip.

Shapes are tiny (4 qubits, 1 layer): every invariant here is
shape-independent; tuned serving NUMBERS are bench.py's job.
"""

from __future__ import annotations

import json

import jax
import numpy as np
import pytest

from qfedx_tpu import obs, tune
from qfedx_tpu.models.vqc import make_vqc_classifier
from qfedx_tpu.obs import flight, watch
from qfedx_tpu.obs import server as obs_server
from qfedx_tpu.serve import MicroBatcher, ServeConfig, ServeEngine
from qfedx_tpu.utils import pins

N = 4
FEATS = (N,)


@pytest.fixture(autouse=True)
def _clean_tuning_state():
    obs_server.stop_server()
    obs.reset()
    watch.reset()
    flight.reset()
    tune.clear_event_sink()
    yield
    obs_server.stop_server()
    watch.reset()
    flight.reset()
    tune.clear_event_sink()
    obs.reset()


def _engine(buckets=(1, 2, 4), deadline_ms=20.0, max_queue=64,
            slo_ms=50.0, seed=0):
    model = make_vqc_classifier(n_qubits=N, n_layers=1, num_classes=2)
    params = model.init(jax.random.PRNGKey(seed))
    cfg = ServeConfig(
        buckets=buckets, deadline_ms=deadline_ms,
        max_queue=max_queue, slo_ms=slo_ms,
    )
    return ServeEngine(model, params, FEATS, config=cfg)


def _rows(m, seed=0):
    return np.random.default_rng(seed).uniform(0, 1, (m, N)).astype(
        np.float32
    )


def _compile_total():
    return sum(
        v for k, v in obs.registry().counters.items()
        if k.startswith("compile.")
    )


def _write_run_dir(tmp_path, seed=7):
    # The serve-restore fixture shape (tests/test_serve.py): a tracked
    # config.json + one checkpoint is everything `qfedx tune` needs.
    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.run.checkpoint import Checkpointer
    from qfedx_tpu.run.config import (
        DataConfig,
        ExperimentConfig,
        ModelConfig,
        build_model,
    )
    from qfedx_tpu.run.metrics import _jsonable

    cfg = ExperimentConfig(
        data=DataConfig(dataset="iris", classes=(0, 1), num_clients=2),
        model=ModelConfig(model="vqc", n_qubits=N, n_layers=1),
        fed=FedConfig(batch_size=8),
        seed=seed,
    )
    run_dir = tmp_path / "run"
    run_dir.mkdir()
    (run_dir / "config.json").write_text(json.dumps(_jsonable(cfg)))
    model = build_model(cfg, 2)
    params = model.init(jax.random.PRNGKey(seed))
    Checkpointer(run_dir / "checkpoints", every=1).save(3, params)
    return run_dir


# -- pin grammar ---------------------------------------------------------------


def test_tune_pin_speaks_the_interval_grammar(monkeypatch):
    monkeypatch.delenv("QFEDX_TUNE", raising=False)
    assert tune.interval_s() == 0.0 and not tune.enabled()
    for off in ("0", "off"):
        monkeypatch.setenv("QFEDX_TUNE", off)
        assert tune.interval_s() == 0.0 and not tune.enabled()
    for on in ("1", "on"):
        monkeypatch.setenv("QFEDX_TUNE", on)
        assert tune.interval_s() == 1.0 and tune.enabled()
    monkeypatch.setenv("QFEDX_TUNE", "2.5")
    assert tune.interval_s() == 2.5
    for bad in ("fast", "-3"):
        monkeypatch.setenv("QFEDX_TUNE", bad)
        with pytest.raises(ValueError, match="QFEDX_TUNE"):
            tune.interval_s()


# -- default-off invariance (the r20 contract) ---------------------------------


def test_default_off_is_bit_identical_to_static_serving(monkeypatch):
    """QFEDX_TUNE unset: warmup attaches NO controller, the batcher
    reads its static config, and not one tune.* instrument exists —
    the r20 serving path, untouched."""
    monkeypatch.delenv("QFEDX_TUNE", raising=False)
    engine = _engine()
    engine.warmup()
    assert engine.tuner is None
    assert tune.maybe_controller(engine) is None
    with MicroBatcher(engine) as b:
        futs = [b.submit(r) for r in _rows(4)]
        for f in futs:
            f.result(timeout=30)
    assert b.stats["served"] == 4
    reg = obs.registry()
    assert not any(k.startswith("tune.") for k in reg.counters)
    assert not any(k.startswith("tune.") for k in reg.gauges)
    # a hand-built controller is equally inert while the pin is off
    ctl = tune.TuneController(engine)
    assert ctl.decide_once() == []
    assert ctl.totals == {"decisions": 0, "reverts": 0}


# -- the tentpole acceptance path ----------------------------------------------


def test_drifting_load_decides_reverts_and_reconciles(
    monkeypatch, tmp_path
):
    """The closed loop end to end: singles traffic shrinks the bucket
    cap, a latency drift tightens the deadline, a firing alert reverts
    both to baseline — zero compiles after warmup, and the event rows,
    tune.* counters, controller totals and gauges reconcile EXACTLY."""
    monkeypatch.setenv("QFEDX_TRACE", "1")
    monkeypatch.setenv("QFEDX_TUNE", "60")  # enabled; ticker dormant here
    monkeypatch.delenv("QFEDX_WATCH", raising=False)
    # The watchdog's serve.p95_slo rule reads the LIFETIME p95 against
    # this pin; park it far above the injected drift so the one alert in
    # play is the injected trainer.loss. The controller is unaffected:
    # it reads the engine's EXPLICIT ServeConfig.slo_ms (CLI > pin).
    monkeypatch.setenv("QFEDX_SERVE_SLO_MS", "100000")
    obs.reset()

    from qfedx_tpu.run.metrics import ExperimentRun, validate_metrics_record

    engine = _engine(buckets=(1, 2, 4), deadline_ms=20.0, slo_ms=50.0)
    decisions = []
    with ExperimentRun(tmp_path, name="tunerun") as run:
        engine.warmup()
        ctl = engine.tuner
        assert isinstance(ctl, tune.TuneController)
        try:
            compiled_at_warmup = _compile_total()
            assert compiled_at_warmup > 0

            # tick 1 is a counter BASELINE, never a decision
            assert ctl.decide_once() == []

            # singles trickle: mean occupancy 1.0 <= 0.25*4 -> shrink 4->2
            with MicroBatcher(engine) as b:
                for r in _rows(6):
                    b.submit(r).result(timeout=30)
            got = ctl.decide_once()
            decisions += got
            assert [d["decision"] for d in got] == ["buckets.shrink"]
            assert ctl.max_bucket == 2 and got[0]["to"] == 2

            # latency drift: window p95 >= 0.8*SLO -> deadline 20->10
            for _ in range(tune.MIN_WINDOW_COUNT + 4):
                obs.histogram("serve.latency_ms", 100.0)
            got = ctl.decide_once()
            decisions += got
            assert [d["decision"] for d in got] == ["deadline.tighten"]
            assert ctl.deadline_ms == 10.0

            # the batcher consults the ACTIVE cap per flush: two queued
            # requests are now a FULL bucket, not a deadline wait
            with MicroBatcher(engine) as b:
                futs = [b.submit(r) for r in _rows(2)]
                for f in futs:
                    f.result(timeout=30)
            assert b.stats["served"] == 2

            # detection outranks adaptation: a firing alert makes
            # revert-to-baseline the ONLY legal move...
            monkeypatch.setenv("QFEDX_WATCH", "1")
            obs.gauge("fed.loss", float("nan"))
            assert [a["rule"] for a in watch.evaluate_once()] == [
                "trainer.loss"
            ]
            got = ctl.decide_once()
            decisions += got
            assert [d["decision"] for d in got] == ["revert.alert"]
            assert got[0]["revert"] is True
            assert ctl.deadline_ms == 20.0 and ctl.max_bucket == 4
            # ...and while it keeps firing, hold still at baseline
            assert ctl.decide_once() == []
            assert obs.registry().gauges["tune.alert_backoff"] == 1.0

            # recovery: alert clears, the loop resumes (calm window +
            # baseline config = no spurious decision), traffic serves
            obs.gauge("fed.loss", 0.4)
            watch.evaluate_once()
            assert watch.active_alerts() == []
            assert ctl.decide_once() == []
            with MicroBatcher(engine) as b:
                b.submit(_rows(1)[0]).result(timeout=30)

            # EXACT reconciliation across every surface
            reg = obs.registry()
            assert len(decisions) == 3
            assert ctl.totals == {"decisions": 3, "reverts": 1}
            assert reg.counters["tune.decisions"] == 3.0
            assert reg.counters["tune.reverts"] == 1.0
            assert reg.gauges["tune.active_deadline_ms"] == 20.0
            assert reg.gauges["tune.active_max_bucket"] == 4.0
            assert reg.gauges["tune.alert_backoff"] == 0.0
            spans = [s for s in reg.spans if s.name == "tune.decide"]
            assert [s.meta["decision"] for s in spans] == [
                "buckets.shrink", "deadline.tighten", "revert.alert",
            ]
            body = obs_server.render_prometheus()
            assert "qfedx_tune_decisions 3.0" in body
            assert "qfedx_tune_reverts 1.0" in body
            assert "qfedx_tune_active_deadline_ms 20.0" in body
            assert "qfedx_tune_active_max_bucket 4.0" in body

            # the zero-compile pin held across every decision and every
            # post-decision flush (the r08 attribution listener)
            assert _compile_total() == compiled_at_warmup
        finally:
            ctl.stop()

    # one decision = one schema-valid {"event": "tune"} row, in order
    rows = [
        json.loads(line)
        for line in (run.dir / "metrics.jsonl").read_text().splitlines()
        if line.strip()
    ]
    tune_rows = [r for r in rows if r.get("event") == "tune"]
    for r in tune_rows:
        validate_metrics_record(r)
    assert [(r["decision"], r["revert"]) for r in tune_rows] == [
        ("buckets.shrink", False),
        ("deadline.tighten", False),
        ("revert.alert", True),
    ]
    assert [r["decision"] for r in tune_rows] == [
        d["decision"] for d in decisions
    ]


def test_relax_and_grow_reopen_the_lattice(monkeypatch):
    """The recovery directions: a calm window doubles the deadline back
    toward baseline (never past it) and full batches grow the cap one
    warmed bucket at a time — then a calm baseline holds still."""
    monkeypatch.setenv("QFEDX_TUNE", "60")
    monkeypatch.delenv("QFEDX_WATCH", raising=False)
    obs.reset()
    engine = _engine(buckets=(1, 2, 4), deadline_ms=20.0, slo_ms=50.0)
    ctl = tune.TuneController(engine)
    assert ctl.decide_once() == []  # counter baseline tick

    # start from a tightened/shrunk active point inside the lattice
    ctl.deadline_ms = 5.0
    ctl.max_bucket = 2
    for _ in range(tune.MIN_WINDOW_COUNT):
        obs.histogram("serve.latency_ms", 1.0)  # p95 << 0.3*SLO
    obs.counter("serve.requests_served", 4.0)   # occupancy 2.0 >= 0.9*2
    obs.counter("serve.batches", 2.0)
    got = ctl.decide_once()
    assert [d["decision"] for d in got] == [
        "deadline.relax", "buckets.grow",
    ]
    assert ctl.deadline_ms == 10.0 and ctl.max_bucket == 4

    # a second calm window walks the deadline to baseline, cap is
    # already at the top bucket: exactly one decision
    for _ in range(tune.MIN_WINDOW_COUNT):
        obs.histogram("serve.latency_ms", 1.0)
    got = ctl.decide_once()
    assert [d["decision"] for d in got] == ["deadline.relax"]
    assert ctl.deadline_ms == 20.0

    # at baseline on a calm window: no motion, totals stand
    for _ in range(tune.MIN_WINDOW_COUNT):
        obs.histogram("serve.latency_ms", 1.0)
    assert ctl.decide_once() == []
    assert ctl.totals == {"decisions": 3, "reverts": 0}


# -- the offline half: qfedx tune -> best_config.json -> --tuned ---------------


def test_offline_sweep_writes_sidecar_and_apply_respects_operator(
    tmp_path, monkeypatch
):
    """tune_run_dir sweeps the lattice through the REAL serving stack
    and writes a schema-1 pin sidecar; apply_best_config replays it
    through utils/pins but never clobbers an operator-set pin."""
    from qfedx_tpu.tune import offline

    run_dir = _write_run_dir(tmp_path)
    record = offline.tune_run_dir(
        run_dir,
        slo_ms=250.0,
        bucket_sets=((1, 2), (1, 4)),
        deadlines_ms=(5.0,),
        requests=8,
        rate_fracs=(0.5,),
    )
    side = run_dir / "best_config.json"
    assert side.exists() and record["path"] == str(side)
    disk = json.loads(side.read_text())
    assert disk["schema"] == offline.BEST_CONFIG_SCHEMA
    assert disk["key"]["model"].startswith("vqc")
    assert disk["key"]["slo_ms"] == 250.0
    assert disk["key"]["backend"] == jax.default_backend()
    assert len(disk["cells"]) == 2  # 2 bucket sets x 1 deadline
    assert set(disk["pins"]) == {
        "QFEDX_SERVE_BUCKETS", "QFEDX_SERVE_DEADLINE_MS",
    }
    assert disk["pins"]["QFEDX_SERVE_DEADLINE_MS"] == "5"
    assert disk["score"]["metric"] == "throughput_at_slo"

    # restore: the unset pin is applied, the operator-set pin is kept
    monkeypatch.delenv("QFEDX_SERVE_BUCKETS", raising=False)
    monkeypatch.setenv("QFEDX_SERVE_DEADLINE_MS", "33")
    applied = offline.apply_best_config(run_dir)
    assert applied["applied"] == {
        "QFEDX_SERVE_BUCKETS": disk["pins"]["QFEDX_SERVE_BUCKETS"],
    }
    assert applied["skipped"] == {"QFEDX_SERVE_DEADLINE_MS": "33"}
    cfg = ServeConfig.resolve()
    assert cfg.buckets == tuple(
        int(b) for b in disk["pins"]["QFEDX_SERVE_BUCKETS"].split(",")
    )
    assert cfg.deadline_ms == 33.0  # the operator won
    pins.clear_pin("QFEDX_SERVE_BUCKETS")

    # a torn or foreign sidecar is loud, not silently wrong
    side.write_text(json.dumps({"schema": 99, "pins": {}}))
    with pytest.raises(ValueError, match="schema"):
        offline.load_best_config(side)
    side.write_text(json.dumps({"schema": 1}))
    with pytest.raises(ValueError, match="pins"):
        offline.load_best_config(side)


def test_cli_tune_then_serve_tuned_round_trip(tmp_path, monkeypatch):
    """`qfedx tune` writes the sidecar; bare `qfedx serve --tuned`
    restores it from the run dir and the resolved config reflects the
    tuned lattice while answering real requests."""
    from qfedx_tpu.run.cli import build_parser, run_serve, run_tune

    for pin in ("QFEDX_SERVE_BUCKETS", "QFEDX_SERVE_DEADLINE_MS"):
        monkeypatch.delenv(pin, raising=False)
    run_dir = _write_run_dir(tmp_path)
    args = build_parser().parse_args([
        "tune", "--run-dir", str(run_dir), "--buckets", "1,2",
        "--deadlines", "5", "--requests", "8", "--slo-ms", "250",
    ])
    record = run_tune(args)
    assert record["pins"] == {
        "QFEDX_SERVE_BUCKETS": "1,2",
        "QFEDX_SERVE_DEADLINE_MS": "5",
    }
    assert (run_dir / "best_config.json").exists()

    req = tmp_path / "req.jsonl"
    out = tmp_path / "resp.jsonl"
    req.write_text(json.dumps({"id": "a", "features": [0.1] * N}) + "\n")
    sargs = build_parser().parse_args([
        "serve", "--run-dir", str(run_dir), "--tuned",
        "--input", str(req), "--output", str(out),
    ])
    summary = run_serve(sargs)
    assert summary["served"] == 1
    recs = [json.loads(l) for l in out.read_text().splitlines()]
    assert recs[0]["id"] == "a" and "pred" in recs[0]
    # the tuned pins are live in this process (monkeypatch restores env)
    cfg = ServeConfig.resolve()
    assert cfg.buckets == (1, 2) and cfg.deadline_ms == 5.0


def test_inspect_surfaces_tune_decisions_and_sidecar(tmp_path):
    """`qfedx inspect` tallies the {"event": "tune"} ledger (per-id
    counts + reverts) and summarizes the best_config.json sidecar."""
    from qfedx_tpu.run.cli import run_inspect

    run_dir = tmp_path / "run"
    run_dir.mkdir()
    rows = [
        {"schema": 1, "round": 0, "ts": 1.0},
        {"schema": 1, "event": "tune", "ts": 2.0,
         "decision": "buckets.shrink", "revert": False},
        {"schema": 1, "event": "tune", "ts": 3.0,
         "decision": "deadline.tighten", "revert": False},
        {"schema": 1, "event": "tune", "ts": 4.0,
         "decision": "revert.alert", "revert": True},
    ]
    (run_dir / "metrics.jsonl").write_text(
        "".join(json.dumps(r) + "\n" for r in rows)
    )
    (run_dir / "best_config.json").write_text(json.dumps({
        "schema": 1,
        "key": {"model": "vqc"},
        "pins": {"QFEDX_SERVE_BUCKETS": "1,2"},
        "score": {"metric": "throughput_at_slo",
                  "throughput_at_slo": 12.0},
        "cells": [{}, {}],
        "provenance": {"source": "qfedx tune"},
    }) + "\n")
    out = run_inspect(run_dir)
    assert out["tune_decisions"] == {
        "buckets.shrink": 1, "deadline.tighten": 1, "revert.alert": 1,
    }
    assert out["tune_reverts"] == 1
    assert out["tune"]["pins"] == {"QFEDX_SERVE_BUCKETS": "1,2"}
    assert out["tune"]["cells"] == 2
