"""bf16 statevector path (QFEDX_DTYPE=bf16) vs the f32 default.

bf16 halves state bytes; measured value is width-dependent (~1.4–1.7× at
the byte-bound n=18–20 dense frontier, ~parity at n ≤ 16 — docs/PERF.md
§3). The recipe is bf16-state / f32-accumulate (cpx.state_dtype): states
and gate application carry bf16, parameters and every reduction/readout
stay f32. These tests quantify the numerical cost (forward + gradient
error vs the f32 oracle) on BOTH dense code paths — the low-rank flip
engine (n=8) and the slab engine (n=10 ≥ _SLAB_MIN, the production path
for the widths where bf16 is actually recommended) — and pin convergence
parity on the flagship config.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from qfedx_tpu.circuits.ansatz import hardware_efficient, init_ansatz_params
from qfedx_tpu.circuits.encoders import angle_encode
from qfedx_tpu.ops.statevector import expect_z_all


@pytest.fixture
def bf16_env(monkeypatch):
    monkeypatch.setenv("QFEDX_DTYPE", "bf16")
    yield
    monkeypatch.delenv("QFEDX_DTYPE", raising=False)


def _zexp(rx, rz, x):
    def one(xi):
        state = hardware_efficient(angle_encode(xi), {"rx": rx, "rz": rz})
        return expect_z_all(state)

    return jax.vmap(one)(x)


def _setup(n=8, layers=3, batch=6, seed=0):
    params = init_ansatz_params(jax.random.PRNGKey(seed), n, layers, scale=0.7)
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.uniform(0, 1, (batch, n)), dtype=jnp.float32)
    return params["rx"], params["rz"], x


def test_state_dtype_env(monkeypatch):
    from qfedx_tpu.ops.cpx import state_dtype

    monkeypatch.delenv("QFEDX_DTYPE", raising=False)
    assert state_dtype() == jnp.float32
    monkeypatch.setenv("QFEDX_DTYPE", "bf16")
    assert state_dtype() == jnp.bfloat16
    monkeypatch.setenv("QFEDX_DTYPE", "bfloat16")
    assert state_dtype() == jnp.bfloat16


def test_dense_forward_error_bounded(bf16_env):
    """⟨Z⟩ under bf16 states stays within ~1e-2 of the f32 value — readout
    is f32-accumulated, so the error is per-gate rounding, not the sum."""
    rx, rz, x = _setup()
    got = _zexp(rx, rz, x)
    assert got.dtype == jnp.float32  # reductions report f32
    import os

    os.environ.pop("QFEDX_DTYPE")
    want = _zexp(rx, rz, x)
    os.environ["QFEDX_DTYPE"] = "bf16"
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-2)


def test_dense_gradient_error_bounded(bf16_env):
    """Parameter gradients through the bf16 simulation stay close to f32:
    measured 3–9% relative error on this config (8q, 3 layers) across
    engine generations (3–5% on the r03 tensordot engine, ~8.7% on the
    r04 flip/select engine — same rounding count, different op order) —
    bounded at 12%; the convergence-parity test below shows it is benign."""
    rx, rz, x = _setup(seed=1)
    w = jnp.asarray(
        np.random.default_rng(2).normal(size=(x.shape[0], x.shape[1])),
        dtype=jnp.float32,
    )

    def loss(rx_, rz_):
        return jnp.sum(w * _zexp(rx_, rz_, x))

    g_bf = jax.grad(loss, argnums=(0, 1))(rx, rz)
    import os

    os.environ.pop("QFEDX_DTYPE")
    g_f32 = jax.grad(loss, argnums=(0, 1))(rx, rz)
    os.environ["QFEDX_DTYPE"] = "bf16"
    for gb, gf in zip(g_bf, g_f32):
        gb, gf = np.asarray(gb, np.float64), np.asarray(gf, np.float64)
        denom = np.linalg.norm(gf)
        assert denom > 1e-3  # oracle gradient is nonzero
        assert np.linalg.norm(gb - gf) / denom < 0.12


def test_slab_bf16_forward_and_gradient_error_bounded(bf16_env, monkeypatch):
    """Same bounds on the slab engine (n=10 ≥ _SLAB_MIN): bf16 lane-qubit
    matmuls and slab flip/select passes must not add error beyond the
    per-gate-rounding class measured on the low-rank path. Pins the TPU
    production configuration (flip gate form + matmul lanes) on CPU."""
    import qfedx_tpu.ops.statevector as sv

    monkeypatch.setenv("QFEDX_GATE_FORM", "flip")
    monkeypatch.setenv("QFEDX_SLAB_LANES", "matmul")
    n = 10
    assert n >= sv._SLAB_MIN
    rx, rz, x = _setup(n=n, batch=4, seed=3)
    got = _zexp(rx, rz, x)
    # monkeypatch (not bare os.environ pops) so an assertion failure
    # mid-test can't leak f32 mode into later tests (ADVICE r04 item 3).
    monkeypatch.delenv("QFEDX_DTYPE")
    want = _zexp(rx, rz, x)
    monkeypatch.setenv("QFEDX_DTYPE", "bf16")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=3e-2)

    w = jnp.asarray(
        np.random.default_rng(4).normal(size=(x.shape[0], n)), dtype=jnp.float32
    )

    def loss(rx_, rz_):
        return jnp.sum(w * _zexp(rx_, rz_, x))

    g_bf = jax.grad(loss, argnums=(0, 1))(rx, rz)
    monkeypatch.delenv("QFEDX_DTYPE")
    g_f32 = jax.grad(loss, argnums=(0, 1))(rx, rz)
    monkeypatch.setenv("QFEDX_DTYPE", "bf16")
    for gb, gf in zip(g_bf, g_f32):
        gb, gf = np.asarray(gb, np.float64), np.asarray(gf, np.float64)
        denom = np.linalg.norm(gf)
        assert denom > 1e-3
        assert np.linalg.norm(gb - gf) / denom < 0.12


def test_convergence_parity_bf16(bf16_env):
    """End-to-end federated training of the flagship 8-qubit config: the
    bf16 run must land in the same accuracy band as the f32 run of the
    SAME config/seed (round-3 'done' bar — the 3–5% gradient error above
    must not cost convergence)."""
    import os

    from qfedx_tpu.data.datasets import load_dataset
    from qfedx_tpu.data.partition import iid_partition, pack_clients
    from qfedx_tpu.data.pipeline import preprocess
    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.models.vqc import make_vqc_classifier
    from qfedx_tpu.run.trainer import train_federated

    _, tr, te = load_dataset(
        "mnist", synthetic_train=768, synthetic_test=192, seed=1
    )
    pre = preprocess(tr, te, classes=(0, 1), features="pca", n_features=8)
    parts = iid_partition(len(pre.train[0]), 4, seed=0)
    cx, cy, cmask = pack_clients(*pre.train, parts, pad_multiple=32)
    model = make_vqc_classifier(n_qubits=8, n_layers=2, num_classes=2)
    cfg = FedConfig(
        local_epochs=2, batch_size=32, learning_rate=0.1, optimizer="adam"
    )

    def run():
        return train_federated(
            model, cfg, cx, cy, cmask, *pre.test, num_rounds=8, seed=0,
            eval_every=8,
        ).final_accuracy

    acc_bf16 = run()
    os.environ.pop("QFEDX_DTYPE")
    acc_f32 = run()
    os.environ["QFEDX_DTYPE"] = "bf16"
    if jax.default_backend() == "tpu":
        assert acc_bf16 > 0.7  # the config demonstrably learns under bf16
        assert acc_bf16 >= acc_f32 - 0.12  # and tracks the f32 run
    else:
        # XLA:CPU (+ older jax) reduces in a different order, and 8 rounds
        # of this config sit on a chaotic stretch of the trajectory:
        # measured here f32 = 0.575 / bf16 = 0.675 at 8 rounds (f32
        # reaches 0.90 by round 16). The parity claim this test pins —
        # bf16 must not *cost* convergence vs f32 — keeps its band; the
        # absolute bar drops to above-chance learning (chance = 0.5) so
        # the virtual-mesh suite pins the property, not one backend's
        # trajectory.
        assert acc_bf16 > 0.6
        assert acc_bf16 >= acc_f32 - 0.15
