"""End-to-end federated training: the minimum slice (SURVEY.md §7.2).

BASELINE.md config 1 shape: 4-qubit angle-encoded VQC, binary
classification, clients on a device mesh, psum FedAvg → accuracy > 0.95 on
the synthetic learnable dataset. Plus the classical-CNN apples-to-apples
path on the same harness.
"""

import numpy as np
import pytest

from qfedx_tpu.data.datasets import load_dataset
from qfedx_tpu.data.partition import dirichlet_partition, iid_partition, pack_clients
from qfedx_tpu.data.pipeline import preprocess
from qfedx_tpu.fed.config import DPConfig, FedConfig
from qfedx_tpu.models.cnn import make_tiny_cnn
from qfedx_tpu.models.vqc import make_vqc_classifier
from qfedx_tpu.run.trainer import train_federated


def _vqc_data(num_clients=8, n_features=4, classes=(0, 1), train=1024, test=256):
    _, tr, te = load_dataset("mnist", synthetic_train=train, synthetic_test=test, seed=1)
    pre = preprocess(tr, te, classes=classes, features="pca", n_features=n_features)
    parts = iid_partition(len(pre.train[0]), num_clients, seed=0)
    cx, cy, cmask = pack_clients(*pre.train, parts, pad_multiple=32)
    return (cx, cy, cmask), pre.test, len(classes)


def test_vqc_fedavg_converges():
    (cx, cy, cmask), (tx, ty), k = _vqc_data()
    model = make_vqc_classifier(n_qubits=4, n_layers=3, num_classes=k)
    cfg = FedConfig(local_epochs=2, batch_size=32, learning_rate=0.1, optimizer="adam")
    res = train_federated(
        model, cfg, cx, cy, cmask, tx, ty, num_rounds=10, eval_every=5, seed=0
    )
    assert res.accuracies[0] < 0.8  # untrained
    assert res.final_accuracy > 0.95, f"accuracies: {res.accuracies}"


def test_vqc_non_iid_dp_trains():
    """BASELINE config-2 shape: non-IID Dirichlet clients + DP-SGD; model
    should still learn (above chance) and ε should be tracked."""
    _, tr, te = load_dataset("mnist", synthetic_train=1024, synthetic_test=256, seed=2)
    pre = preprocess(tr, te, classes=(0, 1, 2), features="pca", n_features=8)
    parts = dirichlet_partition(pre.train[1], 8, alpha=0.5, seed=0)
    cx, cy, cmask = pack_clients(*pre.train, parts, pad_multiple=32)
    model = make_vqc_classifier(n_qubits=8, n_layers=3, num_classes=3)
    cfg = FedConfig(
        local_epochs=1,
        batch_size=32,
        learning_rate=0.1,
        optimizer="adam",
        dp=DPConfig(clip_norm=1.0, noise_multiplier=0.1),
    )
    res = train_federated(
        model, cfg, cx, cy, cmask, *pre.test, num_rounds=10, eval_every=10, seed=0
    )
    assert res.final_accuracy > 0.5, f"accuracies: {res.accuracies}"
    assert len(res.epsilons) == 10 and res.epsilons[-1] > res.epsilons[0]


def test_cnn_same_harness_converges():
    """The reference's main path (TinyCNN FedAvg on 3-class data,
    src/CFed/Classical_FL.py:159-218) on our SPMD harness."""
    _, tr, te = load_dataset("mnist", synthetic_train=512, synthetic_test=128, seed=3)
    pre = preprocess(tr, te, classes=(0, 1, 2), features="image")
    parts = iid_partition(len(pre.train[0]), 4, seed=0)
    cx, cy, cmask = pack_clients(*pre.train, parts, pad_multiple=32)
    model = make_tiny_cnn(num_classes=3)
    cfg = FedConfig(local_epochs=2, batch_size=32, learning_rate=0.02, momentum=0.9)
    res = train_federated(
        model, cfg, cx, cy, cmask, *pre.test, num_rounds=8, eval_every=4, seed=0,
    )
    assert res.final_accuracy > 0.9, f"accuracies: {res.accuracies}"


def test_centralized_vqc_baseline_converges():
    """The centralized-VQC baseline (reference ROADMAP.md:109): one client
    holding all data on the same harness — the apples-to-apples anchor the
    federated accuracies are compared against."""
    (cx, cy, cmask), (tx, ty), k = _vqc_data(num_clients=1, train=512, test=128)
    assert cx.shape[0] == 1
    model = make_vqc_classifier(n_qubits=4, n_layers=3, num_classes=k)
    cfg = FedConfig(local_epochs=4, batch_size=32, learning_rate=0.1, optimizer="adam")
    res = train_federated(
        model, cfg, cx, cy, cmask, tx, ty, num_rounds=10, eval_every=5, seed=0
    )
    assert res.final_accuracy > 0.95, f"accuracies: {res.accuracies}"


def test_reupload_vqc_trains():
    (cx, cy, cmask), (tx, ty), k = _vqc_data(train=512, test=128)
    model = make_vqc_classifier(n_qubits=4, n_layers=2, num_classes=k, encoding="reupload")
    cfg = FedConfig(local_epochs=2, batch_size=32, learning_rate=0.1, optimizer="adam")
    res = train_federated(model, cfg, cx, cy, cmask, tx, ty, num_rounds=5, eval_every=5)
    assert res.final_accuracy > 0.9, f"accuracies: {res.accuracies}"
