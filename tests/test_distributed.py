"""2-process ``jax.distributed`` federated round ≡ single-process (r07).

VERDICT r05 missing #2: every multi-device test in the suite runs ONE
process with 8 virtual devices — the process boundary (coordinator
handshake, cross-process collectives, global-array assembly) was wrapped
(``parallel/mesh.py:distributed_init``) but never exercised. This test
spawns two real CPU processes over a localhost coordinator, runs one
federated round on the 2-process global mesh (one device per process, so
the aggregation psum crosses the process boundary via gloo), and pins
parity against the same round computed in-process on the virtual mesh.
Slow-marked: two cold JAX processes compile the round program from
scratch (~1–2 min).
"""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_WORKER = os.path.join(_REPO, "tests", "_distributed_worker.py")


from conftest import free_port as _free_port  # noqa: E402 — shared helper


def _run_workers(out_path: str, mode: str):
    """Spawn the 2-process gloo worker pair and return process 0's saved
    result arrays (or, for ``trace`` mode, the shard directory — each
    process writes its own ``trace.<i>.json``)."""
    port = _free_port()
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [
                sys.executable,
                _WORKER,
                f"localhost:{port}",
                "2",
                str(pid),
                out_path,
                mode,
            ],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(2)
    ]
    outputs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outputs.append(out)
    for p, out in zip(procs, outputs):
        assert p.returncode == 0, f"worker failed:\n{out[-4000:]}"
    assert os.path.exists(out_path)
    if mode == "trace":
        return out_path
    return np.load(out_path)


@pytest.mark.slow
def test_two_process_round_matches_single_process(tmp_path):
    got = _run_workers(str(tmp_path / "dist_result.npz"), "flat")

    # Single-process oracle: the identical round (same model/config/data/
    # keys, 2 clients on a 2-device mesh — one block per device, exactly
    # the worker's program shape) on the virtual 8-device platform.
    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.fed.round import (
        client_mesh,
        make_fed_round,
        shard_client_data,
    )
    from qfedx_tpu.models.vqc import make_vqc_classifier

    num_clients, samples, n_q = 2, 8, 3
    cfg = FedConfig(local_epochs=2, batch_size=4, learning_rate=0.1,
                    optimizer="adam")
    model = make_vqc_classifier(n_qubits=n_q, n_layers=2, num_classes=2)
    rng = np.random.default_rng(0)
    cx = rng.uniform(0, 1, (num_clients, samples, n_q)).astype(np.float32)
    cy = rng.integers(0, 2, (num_clients, samples)).astype(np.int32)
    cm = np.ones((num_clients, samples), dtype=np.float32)
    mesh = client_mesh(num_devices=2)
    scx, scy, scm = shard_client_data(mesh, cx, cy, jnp.asarray(cm))
    params = model.init(jax.random.PRNGKey(0))
    round_fn = make_fed_round(model, cfg, mesh, num_clients=num_clients)
    ref_params, ref_stats = round_fn(
        params, scx, scy, scm, jax.random.PRNGKey(42)
    )

    ref_leaves = jax.tree.leaves(ref_params)
    assert len(ref_leaves) == sum(1 for k in got.files if k.startswith("leaf"))
    for i, ref in enumerate(ref_leaves):
        np.testing.assert_allclose(
            got[f"leaf{i}"], np.asarray(ref), atol=1e-6, rtol=0
        )
    np.testing.assert_allclose(
        got["mean_loss"], np.asarray(ref_stats.mean_loss), atol=1e-5
    )
    assert float(got["total_weight"]) == float(ref_stats.total_weight)


@pytest.mark.slow
def test_two_process_hier_round_matches_flat_single_process(tmp_path):
    """r10 hierarchy over REAL cross-process collectives: the worker pair
    runs a 4-client cohort as TWO waves of ``make_fed_round_partial``
    (each wave's partial psum crosses the process boundary via gloo),
    accumulates and applies; the oracle is the FLAT one-program round on
    the virtual 2-device mesh. Ring secure-agg is on, so a wave's masks
    pair with clients in the OTHER wave — cancellation must survive both
    the wave split and the process boundary. sgd keeps the wave-split
    comparison float-tight (tests/test_hier.py's tolerance rationale)."""
    got = _run_workers(str(tmp_path / "dist_hier_result.npz"), "hier")

    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.fed.round import (
        client_mesh,
        make_fed_round,
        shard_client_data,
    )
    from qfedx_tpu.models.vqc import make_vqc_classifier

    num_clients, samples, n_q = 4, 8, 3
    cfg = FedConfig(local_epochs=2, batch_size=4, learning_rate=0.1,
                    optimizer="sgd", secure_agg=True,
                    secure_agg_mode="ring")
    model = make_vqc_classifier(n_qubits=n_q, n_layers=2, num_classes=2)
    rng = np.random.default_rng(0)
    cx = rng.uniform(0, 1, (num_clients, samples, n_q)).astype(np.float32)
    cy = rng.integers(0, 2, (num_clients, samples)).astype(np.int32)
    cm = np.ones((num_clients, samples), dtype=np.float32)
    mesh = client_mesh(num_devices=2)
    scx, scy, scm = shard_client_data(mesh, cx, cy, jnp.asarray(cm))
    params = model.init(jax.random.PRNGKey(0))
    round_fn = make_fed_round(model, cfg, mesh, num_clients=num_clients)
    ref_params, ref_stats = round_fn(
        params, scx, scy, scm, jax.random.PRNGKey(42)
    )

    ref_leaves = jax.tree.leaves(ref_params)
    assert len(ref_leaves) == sum(1 for k in got.files if k.startswith("leaf"))
    for i, ref in enumerate(ref_leaves):
        np.testing.assert_allclose(
            got[f"leaf{i}"], np.asarray(ref), atol=2e-5, rtol=0
        )
    np.testing.assert_allclose(
        got["mean_loss"], np.asarray(ref_stats.mean_loss), atol=1e-5
    )
    assert float(got["total_weight"]) == float(ref_stats.total_weight)


@pytest.mark.slow
def test_two_process_byzantine_defended_matches_single_process(tmp_path):
    """r12 parity over REAL cross-process collectives: the worker pair
    runs the 2-wave hier round with a scale:1000 attacker hosted by
    PROCESS 1 (client 1, wave 0) and the clip_mean defense on — every
    controller derives the same attack input from the seeded plan with
    zero communication, the attacked upload is clipped inside the
    cross-process program, and the defended aggregate must match the
    single-process flat guards-on round given the same attack
    (wave-split tolerance, tests/test_hier.py rationale)."""
    got = _run_workers(str(tmp_path / "dist_byz_result.npz"), "byzantine")

    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.fed.round import (
        client_mesh,
        make_fed_round,
        shard_client_data,
    )
    from qfedx_tpu.models.vqc import make_vqc_classifier
    from qfedx_tpu.utils.faults import FaultPlan

    num_clients, samples, n_q = 4, 8, 3
    cfg = FedConfig(local_epochs=2, batch_size=4, learning_rate=0.1,
                    optimizer="sgd", secure_agg=True,
                    secure_agg_mode="ring", aggregator="clip_mean",
                    clip_bound=0.5)
    model = make_vqc_classifier(n_qubits=n_q, n_layers=2, num_classes=2)
    rng = np.random.default_rng(0)
    cx = rng.uniform(0, 1, (num_clients, samples, n_q)).astype(np.float32)
    cy = rng.integers(0, 2, (num_clients, samples)).astype(np.int32)
    cm = np.ones((num_clients, samples), dtype=np.float32)
    mesh = client_mesh(num_devices=2)
    scx, scy, scm = shard_client_data(mesh, cx, cy, jnp.asarray(cm))
    params = model.init(jax.random.PRNGKey(0))
    plan = FaultPlan(seed=0, rules=[{
        "site": "client.byzantine", "kind": "scale:1000", "clients": [1],
    }])
    byz = plan.byzantine_attack(0, np.arange(num_clients))
    round_fn = make_fed_round(model, cfg, mesh, num_clients=num_clients)
    ref_params, ref_stats = round_fn(
        params, scx, scy, scm, jax.random.PRNGKey(42), byzantine=byz
    )

    assert int(got["clipped_clients"]) == 1
    assert int(ref_stats.clipped_clients) == 1
    ref_leaves = jax.tree.leaves(ref_params)
    assert len(ref_leaves) == sum(1 for k in got.files if k.startswith("leaf"))
    for i, ref in enumerate(ref_leaves):
        np.testing.assert_allclose(
            got[f"leaf{i}"], np.asarray(ref), atol=2e-5, rtol=0
        )
    np.testing.assert_allclose(
        got["mean_loss"], np.asarray(ref_stats.mean_loss), atol=1e-5
    )
    assert float(got["total_weight"]) == float(ref_stats.total_weight)


@pytest.mark.slow
def test_two_process_dropout_spans_process_boundary(tmp_path):
    """r11 dropout resilience over REAL cross-process collectives: the
    worker pair drops client 1 (hosted by process 1, wave 0) via a
    seeded FaultPlan, so the surviving ring over {0, 2, 3} pairs
    client 0 with partners in the OTHER wave on the OTHER process —
    ring-mask cancellation must survive a casualty whose pair partner
    lives across the boundary. Oracle: the flat single-process
    guards-on round with the same survivor mask (wave-split tolerance,
    tests/test_hier.py rationale)."""
    got = _run_workers(str(tmp_path / "dist_drop_result.npz"), "dropout")

    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.fed.round import (
        client_mesh,
        make_fed_round,
        shard_client_data,
    )
    from qfedx_tpu.models.vqc import make_vqc_classifier

    num_clients, samples, n_q = 4, 8, 3
    cfg = FedConfig(local_epochs=2, batch_size=4, learning_rate=0.1,
                    optimizer="sgd", secure_agg=True,
                    secure_agg_mode="ring")
    model = make_vqc_classifier(n_qubits=n_q, n_layers=2, num_classes=2)
    rng = np.random.default_rng(0)
    cx = rng.uniform(0, 1, (num_clients, samples, n_q)).astype(np.float32)
    cy = rng.integers(0, 2, (num_clients, samples)).astype(np.int32)
    cm = np.ones((num_clients, samples), dtype=np.float32)
    mesh = client_mesh(num_devices=2)
    scx, scy, scm = shard_client_data(mesh, cx, cy, jnp.asarray(cm))
    params = model.init(jax.random.PRNGKey(0))
    round_fn = make_fed_round(model, cfg, mesh, num_clients=num_clients)
    survivors = np.array([1.0, 0.0, 1.0, 1.0], dtype=np.float32)
    ref_params, ref_stats = round_fn(
        params, scx, scy, scm, jax.random.PRNGKey(42), survivors=survivors
    )

    assert int(got["num_participants"]) == 3
    assert int(ref_stats.num_participants) == 3
    ref_leaves = jax.tree.leaves(ref_params)
    assert len(ref_leaves) == sum(1 for k in got.files if k.startswith("leaf"))
    for i, ref in enumerate(ref_leaves):
        np.testing.assert_allclose(
            got[f"leaf{i}"], np.asarray(ref), atol=2e-5, rtol=0
        )
    np.testing.assert_allclose(
        got["mean_loss"], np.asarray(ref_stats.mean_loss), atol=1e-5
    )
    assert float(got["total_weight"]) == float(ref_stats.total_weight)


@pytest.mark.slow
def test_two_process_trace_shards_merge_into_two_lanes(tmp_path):
    """r15 multi-process trace merge over the REAL 2-process harness:
    each gloo worker runs a traced round and writes its registry as
    ``trace.<process_index>.json``; the merger must produce ONE
    Chrome/Perfetto file with a lane per process (distinct pids, named
    tracks) whose intervals stay monotonically nested per lane — the
    cross-process timeline the process-local registry could never show.
    The shard/merge unit logic is pinned fast in tests/test_obs.py;
    this test pins that REAL multi-controller processes produce
    mergeable shards."""
    shard_dir = str(tmp_path / "shards")
    os.makedirs(shard_dir, exist_ok=True)
    _run_workers(shard_dir, "trace")

    from qfedx_tpu import obs

    shards = obs.find_shards(shard_dir)
    assert [p.name for p in shards] == ["trace.0.json", "trace.1.json"]
    merged = obs.merge_trace_shards(
        shard_dir, out_path=os.path.join(shard_dir, "trace.json")
    )
    import json

    on_disk = json.loads(
        open(os.path.join(shard_dir, "trace.json")).read()
    )
    assert on_disk == merged
    xs = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
    pids = {e["pid"] for e in xs}
    assert pids == {0, 1}, f"expected one lane per process, got {pids}"
    lane_names = {
        e["pid"]: e["args"]["name"]
        for e in merged["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    assert lane_names == {0: "qfedx process 0", 1: "qfedx process 1"}
    for pid in (0, 1):
        lane = [e for e in xs if e["pid"] == pid]
        names = {e["name"] for e in lane}
        # Both processes recorded the host phase pair.
        assert {"round.dispatch", "round.fetch"} <= names
        for e in lane:
            assert e["ts"] >= 0 and e["dur"] >= 0
        # Monotonic nesting per lane: any two intervals on one thread
        # track either nest or are disjoint (no partial overlap).
        by_tid: dict = {}
        for e in lane:
            by_tid.setdefault(e["tid"], []).append(e)
        for evs in by_tid.values():
            evs = sorted(evs, key=lambda e: (e["ts"], -e["dur"]))
            for a, b in zip(evs, evs[1:]):
                a0, a1 = a["ts"], a["ts"] + a["dur"]
                b0, b1 = b["ts"], b["ts"] + b["dur"]
                assert b0 >= a0
                assert b1 <= a1 + 1e-3 or b0 >= a1 - 1e-3, (
                    f"partial overlap in lane {pid}: {a} vs {b}"
                )


@pytest.mark.slow
def test_two_process_stale_discounted_apply_matches_single_process(tmp_path):
    """r13 parity over REAL cross-process collectives: the worker pair
    builds QFEDX_STALE partials (per-wave secure-agg pair graphs — the
    self-cancelling construction a buffered straggler needs) for both
    waves and applies them through ``make_apply_partials`` with wave 1
    tagged ONE ROUND STALE (constant discount 0.5). The oracle is the
    identical mixed-age computation on the virtual single-process mesh
    — the discounted apply, the wave-restricted masks and their
    cancellation must all survive the process boundary (wave-split
    tolerance, tests/test_hier.py rationale)."""
    got = _run_workers(str(tmp_path / "dist_stale_result.npz"), "stale")

    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.fed.round import (
        client_mesh,
        make_apply_partials,
        make_fed_round_partial,
        shard_client_data,
        stack_partials,
    )
    from qfedx_tpu.models.vqc import make_vqc_classifier

    num_clients, samples, n_q = 4, 8, 3
    cfg = FedConfig(local_epochs=2, batch_size=4, learning_rate=0.1,
                    optimizer="sgd", secure_agg=True,
                    secure_agg_mode="ring")
    model = make_vqc_classifier(n_qubits=n_q, n_layers=2, num_classes=2)
    rng = np.random.default_rng(0)
    cx = rng.uniform(0, 1, (num_clients, samples, n_q)).astype(np.float32)
    cy = rng.integers(0, 2, (num_clients, samples)).astype(np.int32)
    cm = np.ones((num_clients, samples), dtype=np.float32)
    mesh = client_mesh(num_devices=2)
    params = model.init(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(42)

    import os as _os

    prev = _os.environ.get("QFEDX_STALE")
    _os.environ["QFEDX_STALE"] = "1"
    try:
        pf = make_fed_round_partial(
            model, cfg, mesh, wave_clients=2, cohort_clients=num_clients
        )
        parts = []
        for w in range(2):
            sl = slice(w * 2, (w + 1) * 2)
            wx, wy, wm = shard_client_data(
                mesh, cx[sl], cy[sl], jnp.asarray(cm[sl])
            )
            parts.append(pf(params, wx, wy, wm, np.int32(w * 2), key))
        ref_params, ref_stats = make_apply_partials(cfg, num_clients)(
            params, stack_partials(parts),
            ages=np.array([0.0, 1.0], np.float32),
        )
    finally:
        if prev is None:
            _os.environ.pop("QFEDX_STALE", None)
        else:
            _os.environ["QFEDX_STALE"] = prev

    ref_leaves = jax.tree.leaves(ref_params)
    assert len(ref_leaves) == sum(1 for k in got.files if k.startswith("leaf"))
    for i, ref in enumerate(ref_leaves):
        np.testing.assert_allclose(
            got[f"leaf{i}"], np.asarray(ref), atol=2e-5, rtol=0
        )
    np.testing.assert_allclose(
        got["mean_loss"], np.asarray(ref_stats.mean_loss), atol=1e-5
    )
    assert float(got["total_weight"]) == float(ref_stats.total_weight)
