"""Dropout-resilient aggregation parity pins (r11 tentpole).

The three contracts the fault-tolerant round stands on:

(a) **Guards on + zero casualties ≡ the unguarded (r10) program.** The
    quarantine/survivor machinery must be free when nothing fails.
    sgd/DP/adam-without-SA rows are BIT-identical; the secure-agg rows
    carry the measured XLA:CPU compile-structure tolerances — the same
    class tests/test_hier.py documents (adam+SA drift persists with
    ``secure_agg_scale=0``, i.e. it is adam's rsqrt path compiling
    differently in a structurally different program, not mask residue;
    re-measured for this file's matrix on CPU).
(b) **A round with dropouts ≡ the survivor-only round, bit for bit.**
    The survivor mask restricts the EFFECTIVE participation set that
    weights and pair graphs run over, so a casualty's exclusion is
    arithmetically the same program as never sampling it — pinned by
    monkeypatching ``participation_mask`` to return the
    survivor-restricted set directly.
(c) **lr=0 mask cancellation with dropouts.** With learning_rate=0
    every delta is 0, so the aggregate is pure ring masks — which must
    cancel to float dust even when clients drop, including casualties
    whose ring partners live in other waves; plus the explicit
    server-side ``unmatched_mask_sum`` oracle (masks drawn over the
    PRE-dropout graph, casualty masks regenerated and subtracted).

Shapes tiny (3 qubits, 1 layer, 16 clients) — tier-1 budget.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import qfedx_tpu.fed.round as fed_round
from qfedx_tpu.fed.config import DPConfig, FedConfig
from qfedx_tpu.fed.round import (
    client_mesh,
    guards_enabled,
    make_accumulate_partial,
    make_apply_partial,
    make_fed_round,
    make_fed_round_partial,
    shard_client_data,
)
from qfedx_tpu.fed.sampling import participation_mask
from qfedx_tpu.fed.secure_agg import ring_mask, unmatched_mask_sum
from qfedx_tpu.models.vqc import make_vqc_classifier
from qfedx_tpu.utils import trees

C, S, N_Q = 16, 4, 3


def _data(seed=0):
    rng = np.random.default_rng(seed)
    cx = rng.uniform(0, 1, (C, S, N_Q)).astype(np.float32)
    cy = (cx.mean(axis=2) > 0.5).astype(np.int32)
    cm = np.ones((C, S), dtype=np.float32)
    return cx, cy, cm


def _model():
    return make_vqc_classifier(n_qubits=N_Q, n_layers=1, num_classes=2)


def _cfg(**kw):
    base = dict(local_epochs=1, batch_size=4, learning_rate=0.1,
                optimizer="sgd", client_fraction=0.5)
    base.update(kw)
    return FedConfig(**base)


def test_guards_pin_parses(monkeypatch):
    monkeypatch.setenv("QFEDX_GUARDS", "off")
    assert guards_enabled() is False
    monkeypatch.delenv("QFEDX_GUARDS", raising=False)
    assert guards_enabled() is True
    monkeypatch.setenv("QFEDX_GUARDS", "sometimes")
    with pytest.raises(ValueError):
        guards_enabled()


def test_guards_off_wrapper_keeps_signature(monkeypatch):
    """Guards on or off, the builders return the SAME signature:
    survivors=None is accepted everywhere (no caller branching), while
    a real mask against the unguarded program raises loudly instead of
    being silently dropped."""
    monkeypatch.setenv("QFEDX_GUARDS", "off")
    cfg = _cfg()
    model = _model()
    mesh = client_mesh(num_devices=4)
    cx, cy, cm = _data()
    params = model.init(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(1)
    scx, scy, scm = shard_client_data(mesh, cx, cy, jnp.asarray(cm))
    fn = make_fed_round(model, cfg, mesh, num_clients=C)
    fn(params, scx, scy, scm, key, survivors=None)  # accepted
    with pytest.raises(ValueError, match="QFEDX_GUARDS"):
        fn(params, scx, scy, scm, key,
           survivors=np.ones(C, dtype=np.float32))
    pf = make_fed_round_partial(
        model, cfg, mesh, wave_clients=C, cohort_clients=C
    )
    pf(params, scx, scy, scm, np.int32(0), key, survivors=None)
    with pytest.raises(ValueError, match="QFEDX_GUARDS"):
        pf(params, scx, scy, scm, np.int32(0), key,
           survivors=np.ones(C, dtype=np.float32))


# (a) guards on + zero casualties vs the unguarded program. atol=None
# means bit-identical; the SA rows carry the measured compile-structure
# tolerances (module docstring).
PARITY = [
    # adam-without-SA is bit-identical too (measured); the row is
    # omitted to keep this file inside the tier-1 wall-clock budget —
    # sgd_dp pins the DP composition, adam_sa pins adam.
    ("sgd_plain", dict(), None),
    ("sgd_dp", dict(dp=DPConfig(clip_norm=1.0, noise_multiplier=0.5)), None),
    ("sgd_sa", dict(secure_agg=True, secure_agg_mode="ring"), 1e-7),
    ("adam_sa", dict(optimizer="adam", secure_agg=True,
                     secure_agg_mode="ring"), 5e-3),
]


@pytest.mark.parametrize("label,kw,atol", PARITY, ids=[p[0] for p in PARITY])
def test_guards_on_zero_casualties_matches_unguarded(
    monkeypatch, label, kw, atol
):
    cfg = _cfg(**kw)
    model = _model()
    mesh = client_mesh(num_devices=4)
    cx, cy, cm = _data()
    params = model.init(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(7)
    scx, scy, scm = shard_client_data(mesh, cx, cy, jnp.asarray(cm))
    monkeypatch.delenv("QFEDX_GUARDS", raising=False)
    p_on, s_on = make_fed_round(model, cfg, mesh, num_clients=C)(
        params, scx, scy, scm, key
    )
    monkeypatch.setenv("QFEDX_GUARDS", "off")
    p_off, s_off = make_fed_round(model, cfg, mesh, num_clients=C)(
        params, scx, scy, scm, key
    )
    for a, b in zip(jax.tree.leaves(p_on), jax.tree.leaves(p_off)):
        if atol is None:
            assert np.array_equal(np.asarray(a), np.asarray(b))
        else:
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=atol, rtol=0
            )
    assert int(s_on.num_participants) == int(s_off.num_participants)
    assert float(s_on.rejected_updates) == 0.0
    assert float(s_on.dropped_clients) == 0.0
    assert float(s_on.applied) == 1.0


def test_dropout_round_is_bitexact_survivor_only_round(monkeypatch):
    """(b): a round where clients DIE equals, bit for bit, the round
    where they were never sampled — the in-program mask-recovery
    contract. The reference injects the survivor-restricted set through
    ``participation_mask`` itself (a different code path producing the
    same effective set)."""
    cfg = _cfg(secure_agg=True, secure_agg_mode="ring")
    model = _model()
    mesh = client_mesh(num_devices=4)
    cx, cy, cm = _data(seed=3)
    params = model.init(jax.random.PRNGKey(1))
    key = jax.random.PRNGKey(9)
    scx, scy, scm = shard_client_data(mesh, cx, cy, jnp.asarray(cm))

    part = np.asarray(participation_mask(key, C, cfg.client_fraction))
    surv = np.ones(C, dtype=np.float32)
    surv[[2, 7, 11]] = 0.0  # casualties: some sampled, some not
    eff = (part * surv).astype(np.float32)

    p_drop, s_drop = make_fed_round(model, cfg, mesh, num_clients=C)(
        params, scx, scy, scm, key, survivors=surv
    )
    monkeypatch.setattr(
        fed_round, "participation_mask",
        lambda k, n, f: jnp.asarray(eff),
    )
    p_ref, s_ref = make_fed_round(model, cfg, mesh, num_clients=C)(
        params, scx, scy, scm, key
    )
    for a, b in zip(jax.tree.leaves(p_drop), jax.tree.leaves(p_ref)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    assert float(s_drop.mean_loss) == float(s_ref.mean_loss)
    assert int(s_drop.num_participants) == int(eff.sum())
    assert int(s_drop.dropped_clients) == int((part * (1 - surv)).sum())


def test_dropout_result_ignores_casualty_data():
    """The casualty's data must be fully excluded: replacing a dropped
    client's examples with garbage changes nothing, bitwise."""
    cfg = _cfg(client_fraction=1.0, secure_agg=True)
    model = _model()
    mesh = client_mesh(num_devices=4)
    cx, cy, cm = _data(seed=5)
    params = model.init(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(3)
    surv = np.ones(C, dtype=np.float32)
    surv[6] = 0.0
    fn = make_fed_round(model, cfg, mesh, num_clients=C)
    scx, scy, scm = shard_client_data(mesh, cx, cy, jnp.asarray(cm))
    p1, _ = fn(params, scx, scy, scm, key, survivors=surv)
    cx2 = cx.copy()
    cx2[6] = np.nan  # even garbage that would NaN the whole psum
    sgx, sgy, sgm = shard_client_data(mesh, cx2, cy, jnp.asarray(cm))
    p2, _ = fn(params, sgx, sgy, sgm, key, survivors=surv)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("waves", [4])
def test_lr0_masks_cancel_with_dropouts_across_waves(waves):
    """(c): lr=0 ⇒ the accumulated update_sum is pure ring masks over
    the surviving set — required ~0 for every wave split, with
    casualties whose ring partners live in OTHER waves."""
    cfg = _cfg(learning_rate=0.0, momentum=0.0, secure_agg=True,
               client_fraction=1.0)
    model = _model()
    mesh = client_mesh(num_devices=4)
    cx, cy, cm = _data(seed=1)
    params = model.init(jax.random.PRNGKey(2))
    key = jax.random.PRNGKey(4)
    surv = np.ones(C, dtype=np.float32)
    surv[[1, 9]] = 0.0  # wave 0 and wave 2 casualties at waves=4
    wc = C // waves
    pf = make_fed_round_partial(
        model, cfg, mesh, wave_clients=wc, cohort_clients=C
    )
    accum = make_accumulate_partial()
    acc = None
    for w in range(waves):
        sl = slice(w * wc, (w + 1) * wc)
        wx, wy, wm = shard_client_data(
            mesh, cx[sl], cy[sl], jnp.asarray(cm[sl])
        )
        part = pf(params, wx, wy, wm, np.int32(w * wc), key,
                  survivors=surv)
        acc = part if acc is None else accum(acc, part)
    residual = max(
        float(jnp.max(jnp.abs(leaf)))
        for leaf in jax.tree.leaves(acc.update_sum)
    )
    assert residual < 1e-5, (
        f"masks left {residual} with dropouts across {waves} waves"
    )
    assert int(acc.num_participants) == C - 2
    assert int(acc.dropped_clients) == 2


def test_unmatched_mask_sum_is_the_server_side_correction():
    """The explicit recovery oracle: masks drawn over the PRE-dropout
    pair graph, summed over survivors only, leave exactly the dropped
    clients' unmatched masks — which the server regenerates
    (deterministic keys) and subtracts to float dust."""
    key = jax.random.PRNGKey(11)
    template = {"a": jnp.zeros((5,)), "b": jnp.zeros((2, 3))}
    part = jnp.asarray(
        np.array([1, 1, 0, 1, 1, 1, 0, 1], dtype=np.float32)
    )
    surv = jnp.asarray(
        np.array([1, 0, 1, 1, 1, 0, 1, 1], dtype=np.float32)
    )
    n = 8
    survivor_sum = trees.tree_zeros_like(template)
    for i in range(n):
        m = ring_mask(key, i, n, template, part, scale=1.0, neighbors=2)
        survivor_sum = jax.tree.map(
            lambda a, x: a + surv[i] * x, survivor_sum, m
        )
    # Survivors alone do NOT cancel (the unmatched-mask corruption)...
    residue = max(
        float(jnp.max(jnp.abs(leaf)))
        for leaf in jax.tree.leaves(survivor_sum)
    )
    assert residue > 0.1
    # ...until the server adds the regenerated casualty masks back.
    correction = unmatched_mask_sum(
        key, n, template, part, surv, scale=1.0, neighbors=2
    )
    recovered = jax.tree.map(jnp.add, survivor_sum, correction)
    assert max(
        float(jnp.max(jnp.abs(leaf)))
        for leaf in jax.tree.leaves(recovered)
    ) < 1e-5


def test_nan_quarantine_never_reaches_theta():
    """A client whose data (hence Δθ) goes non-finite is zeroed and
    counted; θ stays finite, and the result is within mask dust of
    dropping the client outright (its regenerated masks stay in the
    sum, so only the pair-graph float dust differs)."""
    cfg = _cfg(client_fraction=1.0, secure_agg=True)
    model = _model()
    mesh = client_mesh(num_devices=4)
    cx, cy, cm = _data(seed=8)
    params = model.init(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(6)
    fn = make_fed_round(model, cfg, mesh, num_clients=C)
    bad = cx.copy()
    bad[4] = np.inf
    sbx, sby, sbm = shard_client_data(mesh, bad, cy, jnp.asarray(cm))
    p_q, s_q = fn(params, sbx, sby, sbm, key)
    for leaf in jax.tree.leaves(p_q):
        assert np.all(np.isfinite(np.asarray(leaf)))
    assert np.isfinite(float(s_q.mean_loss))
    assert int(s_q.rejected_updates) == 1
    assert int(s_q.num_participants) == C - 1
    # vs. an explicit drop of the same client: same surviving data terms
    surv = np.ones(C, dtype=np.float32)
    surv[4] = 0.0
    scx, scy, scm = shard_client_data(mesh, cx, cy, jnp.asarray(cm))
    p_d, s_d = fn(params, scx, scy, scm, key, survivors=surv)
    for a, b in zip(jax.tree.leaves(p_q), jax.tree.leaves(p_d)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=0
        )
    assert int(s_d.num_participants) == C - 1


def test_min_participation_skips_round_identity():
    """Graceful degradation: below the survivor floor the apply is the
    IDENTITY (θ bitwise unchanged, applied=0); above it the round
    proceeds."""
    cfg = _cfg(client_fraction=1.0, min_participation=0.75)
    model = _model()
    mesh = client_mesh(num_devices=4)
    cx, cy, cm = _data(seed=2)
    params = model.init(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(5)
    fn = make_fed_round(model, cfg, mesh, num_clients=C)
    scx, scy, scm = shard_client_data(mesh, cx, cy, jnp.asarray(cm))
    surv = np.ones(C, dtype=np.float32)
    surv[: C // 2] = 0.0  # 8/16 survive < 0.75 floor
    p_skip, s_skip = fn(params, scx, scy, scm, key, survivors=surv)
    assert float(s_skip.applied) == 0.0
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(p_skip)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    p_ok, s_ok = fn(params, scx, scy, scm, key)
    assert float(s_ok.applied) == 1.0
    assert any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(p_ok))
    )
    # the hierarchy root honors the same floor
    pf = make_fed_round_partial(
        model, cfg, mesh, wave_clients=C, cohort_clients=C
    )
    apply_fn = make_apply_partial(cfg, C)
    acc = pf(params, scx, scy, scm, np.int32(0), key, survivors=surv)
    p_h, s_h = apply_fn(params, acc)
    assert float(s_h.applied) == 0.0
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(p_h)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_wave_split_with_dropouts_matches_flat():
    """Dropout recovery composes with the r10 hierarchy: a 4-wave round
    with casualties equals the flat round with the same survivor mask
    within the documented wave-split tolerance (summation order only)."""
    cfg = _cfg(secure_agg=True)
    model = _model()
    mesh = client_mesh(num_devices=4)
    cx, cy, cm = _data(seed=4)
    params = model.init(jax.random.PRNGKey(1))
    key = jax.random.PRNGKey(8)
    surv = np.ones(C, dtype=np.float32)
    surv[[0, 13]] = 0.0
    scx, scy, scm = shard_client_data(mesh, cx, cy, jnp.asarray(cm))
    p_flat, s_flat = make_fed_round(model, cfg, mesh, num_clients=C)(
        params, scx, scy, scm, key, survivors=surv
    )
    pf = make_fed_round_partial(
        model, cfg, mesh, wave_clients=4, cohort_clients=C
    )
    accum = make_accumulate_partial()
    acc = None
    for w in range(4):
        sl = slice(w * 4, (w + 1) * 4)
        wx, wy, wm = shard_client_data(
            mesh, cx[sl], cy[sl], jnp.asarray(cm[sl])
        )
        part = pf(params, wx, wy, wm, np.int32(w * 4), key, survivors=surv)
        acc = part if acc is None else accum(acc, part)
    p_h, s_h = make_apply_partial()(params, acc)
    for a, b in zip(jax.tree.leaves(p_flat), jax.tree.leaves(p_h)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5, rtol=0
        )
    assert int(s_h.num_participants) == int(s_flat.num_participants)
    assert int(s_h.dropped_clients) == int(s_flat.dropped_clients)
