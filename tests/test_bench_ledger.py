"""`qfedx bench history` — the bench-trajectory regression ledger (r20).

Host-side only (no backend, no jit): the ledger parses committed
BENCH_r*.json files, tags methodology eras (pre-r04 rows are excluded
from trends) and result provenance (on-chip vs CPU-container numbers
never cross-compare), and exits 1 on a same-provenance regression so a
driver can gate on it. Plus the `qfedx inspect` surfacing satellite:
alert-event totals, the flight dump, and the adjacent bench trajectory
all ride the one inspect JSON line.
"""

import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _write_bench(d, n, parsed=None, tail="", rc=0):
    rec = {"n": n, "cmd": "python bench.py", "rc": rc, "tail": tail,
           "parsed": parsed}
    (d / f"BENCH_r{n:02d}.json").write_text(json.dumps(rec))


def _history(tmp_path, *extra):
    from qfedx_tpu.run.cli import build_parser, run_bench_history

    args = build_parser().parse_args(
        ["bench", "history", "--dir", str(tmp_path), "--json", *extra]
    )
    return run_bench_history(args)


def test_bench_history_gates_on_seeded_regression(tmp_path, capsys):
    """The acceptance fixture: a same-provenance regression exits 1
    while the pre-r04-methodology row and the on-chip-vs-CPU boundary
    are tagged, not compared."""
    _write_bench(tmp_path, 2, parsed={"metric": "m", "value": 9999.0})
    _write_bench(tmp_path, 4, parsed={"metric": "m", "value": 100.0})
    _write_bench(tmp_path, 5, parsed={"metric": "m", "value": 110.0})
    _write_bench(
        tmp_path, 6, parsed={"metric": "m", "value": 50.0, "backend": "cpu"}
    )
    _write_bench(
        tmp_path, 7, parsed={"metric": "m", "value": 40.0, "backend": "cpu"}
    )
    rc = _history(tmp_path)
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 1
    assert report["regressed"] == ["value"]
    by_round = {r["round"]: r for r in report["rows"]}
    assert by_round[2]["methodology"] == "pre-r04"
    assert by_round[4]["provenance"] == "tpu"  # watermark inference
    assert by_round[6]["provenance"] == "cpu"  # explicit backend field
    v = report["verdicts"]["value"]
    # r07 vs r06: both cpu — the chip numbers never enter the ratio
    assert (v["prev_round"], v["now_round"]) == (6, 7)
    assert v["verdict"] == "regressed" and v["ratio"] == 0.8
    assert report["latest_on_chip"] == 5
    # --no-gate keeps the same report advisory
    assert _history(tmp_path, "--no-gate") == 0


def test_bench_history_never_crosses_provenance(tmp_path, capsys):
    """A CPU container number FAR below the chip number is
    'no-prior-same-provenance', not a regression."""
    _write_bench(tmp_path, 4, parsed={"metric": "m", "value": 1000.0})
    _write_bench(
        tmp_path, 6, parsed={"metric": "m", "value": 10.0, "backend": "cpu"}
    )
    rc = _history(tmp_path)
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert report["verdicts"]["value"]["verdict"] == (
        "no-prior-same-provenance"
    )


def test_bench_history_recovers_parsed_from_tail(tmp_path, capsys):
    _write_bench(tmp_path, 4, parsed={"metric": "m", "value": 100.0})
    _write_bench(
        tmp_path, 5, parsed=None,
        tail='noise\n{"metric": "m", "value": 95.0}\ntrailing\n',
    )
    rc = _history(tmp_path)
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0  # 0.95 exactly is flat, not regressed
    by_round = {r["round"]: r for r in report["rows"]}
    assert by_round[5]["parseable"] and by_round[5]["recovered_from_tail"]
    assert report["verdicts"]["value"]["verdict"] == "flat"


def test_bench_history_empty_dir_exits_2(tmp_path):
    assert _history(tmp_path) == 2


def test_bench_history_numeric_sort_not_lexicographic(tmp_path, capsys):
    # r10 must sort AFTER r9, not between r1 and r2
    _write_bench(tmp_path, 9, parsed={"metric": "m", "value": 100.0})
    _write_bench(tmp_path, 10, parsed={"metric": "m", "value": 50.0,
                                       "backend": "cpu"})
    _write_bench(tmp_path, 11, parsed={"metric": "m", "value": 49.0,
                                       "backend": "cpu"})
    _history(tmp_path)
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert [r["round"] for r in report["rows"]] == [9, 10, 11]


def test_inspect_surfaces_alerts_flight_and_bench(tmp_path, capsys):
    """The satellite: `qfedx inspect` reports alert-event totals by
    rule, the flight dump, and the adjacent bench trajectory."""
    run_dir = tmp_path / "runs" / "r1"
    run_dir.mkdir(parents=True)
    rows = [
        {"schema": 1, "round": 1, "ts": 1.0, "loss": 0.5},
        {"schema": 1, "event": "alert", "state": "firing",
         "rule": "serve.shed_rate", "ts": 2.0},
        {"schema": 1, "event": "alert", "state": "cleared",
         "rule": "serve.shed_rate", "ts": 3.0},
        {"schema": 1, "event": "alert", "state": "firing",
         "rule": "serve.shed_rate", "ts": 4.0},
        {"schema": 1, "round": 2, "ts": 5.0, "loss": 0.4},
    ]
    (run_dir / "metrics.jsonl").write_text(
        "".join(json.dumps(r) + "\n" for r in rows)
    )
    (run_dir / "flight.json").write_text(json.dumps(
        {"schema": 1, "reason": "sigterm", "events": [{"t": 1.0}]}
    ))
    _write_bench(tmp_path, 4, parsed={"metric": "m", "value": 100.0})

    from qfedx_tpu.run.cli import run_inspect

    out = run_inspect(run_dir)
    capsys.readouterr()
    assert out["rounds_completed"] == 2  # event rows never count
    assert out["alerts_fired"] == {"serve.shed_rate": 2}
    assert out["event_rows"] == 3
    assert out["flight"]["reason"] == "sigterm"
    assert out["flight"]["events"] == 1
    assert out["bench_history"]["latest"] == 4  # found via parent walk
