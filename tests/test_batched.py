"""Batched slab engine (ops.batched) ≡ the vmapped dense engine.

The batched path is a pure performance routing (docs/PERF.md §8): batch
folded into slab rows instead of a vmap axis. Every op and the full model
must match the vmapped dense engine exactly — these tests pin the parity
on the CPU mesh (QFEDX_BATCHED=1 forces the TPU production routing here).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from qfedx_tpu.ops import gates
from qfedx_tpu.ops.batched import (
    apply_cnot_b,
    apply_gate_b,
    bstate_amplitude,
    bstate_product,
    expect_z_all_b,
)
from qfedx_tpu.ops.cpx import CArray
from qfedx_tpu.ops.statevector import (
    apply_cnot,
    apply_gate,
    expect_z_all,
    product_state,
)

N = 10  # smallest slab width (statevector._SLAB_MIN)
B = 3


def _rand_bstate(seed: int, complex_: bool = True) -> CArray:
    rng = np.random.default_rng(seed)
    re = jnp.asarray(rng.standard_normal((B, 1 << N)), dtype=jnp.float32)
    if not complex_:
        return CArray(re, None)
    im = jnp.asarray(rng.standard_normal((B, 1 << N)), dtype=jnp.float32)
    return CArray(re, im)


def _as_tensors(state: CArray) -> CArray:
    """(B, 2^n) → (B,) + (2,)*n for the vmapped reference engine."""
    shape = (B,) + (2,) * N
    return CArray(
        state.re.reshape(shape),
        None if state.im is None else state.im.reshape(shape),
    )


def _flat(state: CArray) -> np.ndarray:
    re = np.asarray(state.re).reshape(B, -1)
    im = (
        np.zeros_like(re)
        if state.im is None
        else np.asarray(state.im).reshape(B, -1)
    )
    return re + 1j * im


def assert_state_close(a: CArray, b: CArray, atol=1e-5):
    np.testing.assert_allclose(_flat(a), _flat(b), atol=atol, rtol=0)


def test_product_state_parity():
    rng = np.random.default_rng(0)
    angles = jnp.asarray(rng.uniform(0, np.pi, (B, N)), dtype=jnp.float32)
    from qfedx_tpu.circuits.encoders import angle_amplitudes

    batched = bstate_product(angle_amplitudes(angles, "ry"))
    ref = jax.vmap(lambda a: product_state(angle_amplitudes(a, "ry")))(angles)
    assert_state_close(batched, CArray(ref.re.reshape(B, -1), None))


def test_product_state_complex_parity():
    rng = np.random.default_rng(1)
    angles = jnp.asarray(rng.uniform(0, np.pi, (B, N)), dtype=jnp.float32)
    from qfedx_tpu.circuits.encoders import angle_amplitudes

    batched = bstate_product(angle_amplitudes(angles, "rx"))
    ref = jax.vmap(lambda a: product_state(angle_amplitudes(a, "rx")))(angles)
    assert_state_close(
        batched,
        CArray(ref.re.reshape(B, -1), ref.im.reshape(B, -1)),
    )


def test_amplitude_parity():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((B, 1 << N)), dtype=jnp.float32)
    x = x.at[1].set(0.0)  # exercise the uniform fallback row
    from qfedx_tpu.circuits.encoders import amplitude_encode

    batched = bstate_amplitude(x, jnp.float32)
    ref = jax.vmap(amplitude_encode)(x)
    assert_state_close(batched, CArray(ref.re.reshape(B, -1), None))


@pytest.mark.parametrize("qubit", [0, 2, N - 7, N - 1])  # row and lane
@pytest.mark.parametrize("complex_state", [False, True])
def test_gate_parity(qubit, complex_state):
    state = _rand_bstate(3, complex_state)
    g = gates.rot_zx(jnp.float32(0.7), jnp.float32(-0.3))
    batched = apply_gate_b(state, N, g, qubit)
    ref = jax.vmap(lambda s_re, s_im: apply_gate(
        CArray(s_re, s_im if complex_state else None), g, qubit
    ))(
        _as_tensors(state).re,
        _as_tensors(state).im if complex_state else _as_tensors(state).re,
    )
    assert_state_close(batched, ref)


@pytest.mark.parametrize("qubit", [1, N - 2])  # row and lane
def test_per_sample_gate_parity(qubit):
    state = _rand_bstate(4, complex_=True)
    thetas = jnp.asarray([0.3, -1.2, 2.5], dtype=jnp.float32)
    batched = apply_gate_b(state, N, gates.ry_batched(thetas), qubit)
    tens = _as_tensors(state)
    ref = jax.vmap(
        lambda s_re, s_im, t: apply_gate(CArray(s_re, s_im), gates.ry(t), qubit)
    )(tens.re, tens.im, thetas)
    assert_state_close(batched, ref)


@pytest.mark.parametrize(
    "ctrl,tgt",
    [
        (0, 1),  # row-row
        (1, 0),  # row-row reversed
        (N - 2, N - 1),  # lane-lane
        (1, N - 2),  # row control, lane target
        (N - 2, 1),  # lane control, row target
        (N - 1, 0),  # the entangler-ring wrap gate
    ],
)
def test_cnot_parity(ctrl, tgt):
    state = _rand_bstate(5, complex_=True)
    batched = apply_cnot_b(state, N, ctrl, tgt)
    tens = _as_tensors(state)
    ref = jax.vmap(
        lambda s_re, s_im: apply_cnot(CArray(s_re, s_im), ctrl, tgt)
    )(tens.re, tens.im)
    assert_state_close(batched, ref)


def test_expect_z_parity():
    state = _rand_bstate(6, complex_=True)
    batched = expect_z_all_b(state, N)
    tens = _as_tensors(state)
    ref = jax.vmap(lambda s_re, s_im: expect_z_all(CArray(s_re, s_im)))(
        tens.re, tens.im
    )
    np.testing.assert_allclose(
        np.asarray(batched), np.asarray(ref), atol=1e-4, rtol=0
    )


@pytest.mark.parametrize("encoding", ["angle", "amplitude", "reupload"])
def test_model_parity(encoding, monkeypatch):
    """Full model: batched routing ≡ vmap routing, logits and gradients."""
    import optax

    from qfedx_tpu.models.vqc import make_vqc_classifier

    feats = (1 << N) if encoding == "amplitude" else N
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.uniform(0, 1, (B, feats)), dtype=jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, (B,)), dtype=jnp.int32)

    def loss(model):
        def f(p):
            return optax.softmax_cross_entropy_with_integer_labels(
                model.apply(p, x), y
            ).mean()

        return f

    # The routing env is read lazily at FIRST APPLY (not at build), so
    # each model's entire on/off phase — build, apply, grad — runs with
    # its pin still set; interleaving builds then applies would run both
    # models down the same path and pin nothing.
    monkeypatch.setenv("QFEDX_BATCHED", "1")
    m_on = make_vqc_classifier(
        n_qubits=N, n_layers=2, num_classes=2, encoding=encoding
    )
    params = m_on.init(jax.random.PRNGKey(0))
    # Spy on the batched readout so a silent routing fallback (both
    # models running the vmap path) fails loudly instead of comparing
    # vmap against itself.
    hits = []
    real = expect_z_all_b

    def spy(state, n):
        hits.append(n)
        return real(state, n)

    monkeypatch.setattr(
        "qfedx_tpu.ops.batched.expect_z_all_b", spy
    )
    logits_on = np.asarray(m_on.apply(params, x))
    monkeypatch.setattr("qfedx_tpu.ops.batched.expect_z_all_b", real)
    assert hits, "batched routing was not exercised"
    g_on = jax.grad(loss(m_on))(params)

    monkeypatch.setenv("QFEDX_BATCHED", "0")
    m_off = make_vqc_classifier(
        n_qubits=N, n_layers=2, num_classes=2, encoding=encoding
    )
    logits_off = np.asarray(m_off.apply(params, x))
    g_off = jax.grad(loss(m_off))(params)

    np.testing.assert_allclose(logits_on, logits_off, atol=1e-5, rtol=0)
    for a, b in zip(jax.tree.leaves(g_on), jax.tree.leaves(g_off)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=0
        )
