"""Tier-1 lint: the QFEDX_* pin surface and its docs table cannot drift.

``benchmarks/check_pins.py`` holds the single definition (AST scan of
exact pin-name literals vs the docs/OBSERVABILITY.md table rows); this
test wires it into the suite so an undocumented pin — or a stale table
row — fails CI, not a code review. The synthetic cases prove the guard
actually fires in both directions.
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from benchmarks.check_pins import (  # noqa: E402
    check,
    documented_pins,
    source_pins,
)


def test_pin_table_matches_source():
    assert check() == []


def test_every_known_pin_family_member_is_seen():
    # The scanner must at least find the pins the framework is built on;
    # an empty scan would make the table check vacuously pass.
    pins = source_pins()
    for name in (
        "QFEDX_DTYPE", "QFEDX_FOLD_CLIENTS", "QFEDX_FUSE", "QFEDX_TRACE",
        "QFEDX_PIPELINE", "QFEDX_DONATE", "QFEDX_HIER", "QFEDX_STREAM",
    ):
        assert name in pins, f"scanner lost {name}"
    assert len(documented_pins()) >= len(pins) - 1


def test_guard_fires_both_directions(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        'import os\n'
        'val = os.environ.get("QFEDX_UNDOCUMENTED")\n'
        '# prose mention of QFEDX_NOT_A_READ inside a comment is ignored\n'
        'msg = "set QFEDX_EMBEDDED=1 to enable"  # embedded: ignored\n'
    )
    doc = tmp_path / "OBS.md"
    doc.write_text(
        "| pin | values |\n|---|---|\n"
        "| `QFEDX_UNDOCUMENTED` | `0`/`1` |\n"
    )
    assert check(pkg, doc) == []  # documented read + ignored prose: clean
    doc.write_text(
        "| pin | values |\n|---|---|\n| `QFEDX_STALE_ROW` | `0`/`1` |\n"
    )
    problems = check(pkg, doc)
    assert any("QFEDX_UNDOCUMENTED" in p for p in problems)
    assert any("QFEDX_STALE_ROW" in p for p in problems)
