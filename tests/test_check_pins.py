"""Tier-1 lint: the QFEDX_* pin surface and its docs table cannot drift.

``benchmarks/check_pins.py`` holds the single definition (AST scan of
exact pin-name literals vs the docs/OBSERVABILITY.md table rows); this
test wires it into the suite so an undocumented pin — or a stale table
row — fails CI, not a code review. The synthetic cases prove the guard
actually fires in both directions.
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from benchmarks.check_pins import (  # noqa: E402
    check,
    documented_pins,
    source_pins,
)


def test_pin_table_matches_source():
    assert check() == []


def test_every_known_pin_family_member_is_seen():
    # The scanner must at least find the pins the framework is built on;
    # an empty scan would make the table check vacuously pass.
    pins = source_pins()
    for name in (
        "QFEDX_DTYPE", "QFEDX_FOLD_CLIENTS", "QFEDX_FUSE", "QFEDX_TRACE",
        "QFEDX_PIPELINE", "QFEDX_DONATE", "QFEDX_HIER", "QFEDX_STREAM",
        "QFEDX_PROFILE",
    ):
        assert name in pins, f"scanner lost {name}"
    assert len(documented_pins()) >= len(pins) - 1


def test_guard_fires_both_directions(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        'import os\n'
        'val = os.environ.get("QFEDX_UNDOCUMENTED")\n'
        '# prose mention of QFEDX_NOT_A_READ inside a comment is ignored\n'
        'msg = "set QFEDX_EMBEDDED=1 to enable"  # embedded: ignored\n'
    )
    doc = tmp_path / "OBS.md"
    doc.write_text(
        "| pin | values |\n|---|---|\n"
        "| `QFEDX_UNDOCUMENTED` | `0`/`1` |\n"
    )
    assert check(pkg, doc) == []  # documented read + ignored prose: clean
    doc.write_text(
        "| pin | values |\n|---|---|\n| `QFEDX_STALE_ROW` | `0`/`1` |\n"
    )
    problems = check(pkg, doc)
    assert any("QFEDX_UNDOCUMENTED" in p for p in problems)
    assert any("QFEDX_STALE_ROW" in p for p in problems)


# --- the fault-site taxonomy guard (r12 satellite, same family) -------------

from benchmarks.check_faults import (  # noqa: E402
    check as check_faults,
    documented_taxonomy,
)


def test_fault_taxonomy_matches_source():
    assert check_faults() == []


def test_fault_taxonomy_covers_every_site_and_kind():
    # The parser must see the real table; an empty parse would make the
    # drift check vacuously pass.
    from qfedx_tpu.utils.faults import doc_taxonomy

    doc = documented_taxonomy()
    code = doc_taxonomy()
    assert set(doc) == set(code)
    assert "client.byzantine" in doc
    for kind in ("scale:k", "sign_flip", "noise", "label_flip"):
        assert kind in doc["client.byzantine"]


# --- the span-taxonomy guard (r15 satellite, same family) --------------------

from benchmarks.check_spans import (  # noqa: E402
    check as check_spans,
    documented_spans,
    source_spans,
)


def test_span_taxonomy_matches_source():
    assert check_spans() == []


def test_span_scanner_sees_the_known_spans():
    # An empty scan would make the taxonomy check vacuously pass; the
    # scanner must at least find the spans the subsystems are built on.
    spans = source_spans()
    for name in (
        "round.dispatch", "round.fetch", "serve.compute", "serve.queue",
        "ingest.h2d", "engine.trace", "checkpoint.async_write",
        "obs.http",
    ):
        assert name in spans, f"scanner lost {name}"
    assert documented_spans() >= set(spans)


def test_span_guard_fires_both_directions(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "from qfedx_tpu import obs\n"
        'def f():\n'
        '    with obs.span("made.up_span", round=1):\n'
        '        pass\n'
        '    with obs.span("documented.span"):\n'
        '        pass\n'
        '    name = "prose.span mentioned in a string"  # ignored\n'
    )
    doc = tmp_path / "OBS.md"
    doc.write_text(
        "## Span taxonomy\n\n"
        "| Span | Where | What |\n|---|---|---|\n"
        "| `documented.span` | mod.py | a test span |\n"
        "| `stale.span` | nowhere | gone |\n"
    )
    problems = check_spans(pkg, doc)
    assert any("made.up_span" in p for p in problems)
    assert any("stale.span" in p and "stale" in p for p in problems)
    assert not any("documented.span" in p for p in problems)
    assert not any("prose.span" in p for p in problems)


# --- the profile_summary schema guard (r16 satellite, same family) -----------

from benchmarks.check_profile import (  # noqa: E402
    check as check_profile,
    documented_fields,
    source_fields,
)


def test_profile_schema_matches_source():
    assert check_profile() == []


def test_profile_schema_scanner_sees_the_known_fields():
    # An empty parse would make the drift check vacuously pass; the
    # table must carry at least the fields the floor evidence is
    # built on (ISSUE r16 acceptance surface).
    fields = source_fields()
    for name in (
        "ops_executed", "gap_p50_us", "device_busy_fraction",
        "measured_vs_static", "spans",
    ):
        assert name in fields, f"SUMMARY_FIELDS lost {name}"
    assert documented_fields() == fields


def test_profile_schema_guard_fires_both_directions(tmp_path):
    doc = tmp_path / "OBS.md"
    doc.write_text(
        "## The `profile_summary.json` schema\n\n"
        "| field | meaning |\n|---|---|\n"
        "| `ops_executed` | executed op events |\n"
        "| `stale_field` | gone |\n"
    )
    problems = check_profile(doc)
    assert any("gap_p50_us" in p for p in problems)  # undocumented field
    assert any("stale_field" in p and "stale" in p for p in problems)
    assert not any("'ops_executed'" in p for p in problems)
    # rows outside the schema section are not schema rows
    doc.write_text(
        "## Some other table\n\n| field |\n|---|\n| `ops_executed` |\n"
    )
    assert "ops_executed" not in documented_fields(doc)


# --- the alert-rule taxonomy guard (r20 satellite, same family) --------------

from benchmarks.check_alerts import (  # noqa: E402
    check_alerts,
    documented_alert_rules,
)


def test_alert_taxonomy_matches_source():
    assert check_alerts() == []


def test_alert_taxonomy_covers_every_rule():
    # An empty parse would make the drift check vacuously pass; the
    # table must carry exactly the append-only RULE_IDS surface, pins
    # included.
    from qfedx_tpu.obs.watch import rule_taxonomy

    doc = documented_alert_rules()
    code = rule_taxonomy()
    assert set(doc) == set(code)
    for rid in (
        "serve.p95_slo", "serve.shed_rate", "serve.queue_sat",
        "trainer.stall", "trainer.loss", "trainer.eps_burn",
    ):
        assert rid in doc, f"taxonomy lost {rid}"
        assert doc[rid] == code[rid]["threshold_pin"]


def test_alert_guard_fires_both_directions(tmp_path):
    doc = tmp_path / "OBS.md"
    doc.write_text(
        "## Alert-rule taxonomy\n\n"
        "| Rule ID | Signal | Threshold pin | Fires on |\n"
        "|---|---|---|---|\n"
        "| `serve.p95_slo` | p95 | `QFEDX_SERVE_SLO_MS` | breach |\n"
        "| `serve.shed_rate` | sheds | `QFEDX_WRONG_PIN` | sheds |\n"
        "| `made.up_rule` | nothing | `QFEDX_WATCH_SHED` | never |\n"
    )
    problems = check_alerts(doc)
    # missing rules, a wrong-pin cell, and the stale row all fire
    assert any("trainer.stall" in p for p in problems)
    assert any(
        "serve.shed_rate" in p and "QFEDX_WRONG_PIN" in p for p in problems
    )
    assert any("made.up_rule" in p and "stale" in p for p in problems)
    assert not any("serve.p95_slo" in p for p in problems)
    # rows outside the section are not taxonomy rows
    doc.write_text(
        "## Some other table\n\n| id |\n|---|\n| `serve.p95_slo` |\n"
    )
    assert "serve.p95_slo" not in documented_alert_rules(doc)


# --- the tune-decision taxonomy guard (r21 satellite, same family) -----------

from benchmarks.check_tune import (  # noqa: E402
    check_tune,
    documented_tune_decisions,
)


def test_tune_taxonomy_matches_source():
    assert check_tune() == []


def test_tune_taxonomy_covers_every_decision():
    # An empty parse would make the drift check vacuously pass; the
    # table must carry exactly the append-only DECISION_IDS surface,
    # threshold pins included.
    from qfedx_tpu.tune import decision_taxonomy

    doc = documented_tune_decisions()
    code = decision_taxonomy()
    assert set(doc) == set(code)
    for did in (
        "deadline.tighten", "deadline.relax", "buckets.shrink",
        "buckets.grow", "revert.alert",
    ):
        assert did in doc, f"taxonomy lost {did}"
        assert doc[did] == code[did]["threshold_pin"]


def test_tune_guard_fires_both_directions(tmp_path):
    doc = tmp_path / "OBS.md"
    doc.write_text(
        "## Tune decision taxonomy\n\n"
        "| Decision ID | Signal | Threshold pin | Means |\n"
        "|---|---|---|---|\n"
        "| `deadline.tighten` | p95 | `QFEDX_TUNE_HI` | tighten |\n"
        "| `buckets.shrink` | occupancy | `QFEDX_WRONG_PIN` | shrink |\n"
        "| `made.up_decision` | nothing | `QFEDX_TUNE_LO` | never |\n"
    )
    problems = check_tune(doc)
    # missing decisions, a wrong-pin cell, and the stale row all fire
    assert any("deadline.relax" in p for p in problems)
    assert any(
        "buckets.shrink" in p and "QFEDX_WRONG_PIN" in p for p in problems
    )
    assert any("made.up_decision" in p and "stale" in p for p in problems)
    assert not any("deadline.tighten" in p for p in problems)
    # rows outside the section are not taxonomy rows
    doc.write_text(
        "## Some other table\n\n| id |\n|---|\n| `deadline.tighten` |\n"
    )
    assert "deadline.tighten" not in documented_tune_decisions(doc)


def test_fault_guard_fires_both_directions(tmp_path):
    doc = tmp_path / "ROB.md"
    doc.write_text(
        "## Fault-site taxonomy\n\n"
        "| Site | Kinds | Fires |\n|---|---|---|\n"
        "| `client.compute` | `drop`, `nan`, `inf` | per client |\n"
        "| `made.up_site` | `error` | never |\n"
    )
    problems = check_faults(doc)
    # missing sites (byzantine, registry.fetch, ...) AND the stale row
    assert any("client.byzantine" in p for p in problems)
    assert any("made.up_site" in p and "stale" in p for p in problems)
    # a row missing one KIND fires too
    doc.write_text(
        "## Fault-site taxonomy\n\n"
        "| Site | Kinds |\n|---|---|\n"
        "| `client.compute` | `drop`, `nan` |\n"
    )
    problems = check_faults(doc)
    assert any("client.compute" in p and "inf" in str(p) for p in problems)
