"""Mesh construction helpers (single-host paths on the virtual 8-CPU mesh;
the multi-slice arrangement policy with fake slice-tagged devices)."""

from types import SimpleNamespace

import jax
import numpy as np
import pytest

from qfedx_tpu.parallel.mesh import fed_mesh, hybrid_device_array, hybrid_fed_mesh


def fake_devices(num_slices, per_slice):
    """Fake TPU devices carrying the ``slice_index`` attribute, interleaved
    across slices the way jax.devices() can return them on multi-slice."""
    devs = [
        SimpleNamespace(id=s * per_slice + i, slice_index=s, platform="tpu")
        for s in range(num_slices)
        for i in range(per_slice)
    ]
    # shuffle deterministically: the policy must not rely on input order
    rng = np.random.default_rng(0)
    return [devs[i] for i in rng.permutation(len(devs))]


def test_fed_mesh_shapes():
    m = fed_mesh(sv_size=1)
    assert m.shape == {"clients": 8, "sv": 1}
    m = fed_mesh(sv_size=4)
    assert m.shape == {"clients": 2, "sv": 4}
    # sv groups are contiguous device runs (ICI-adjacency proxy)
    arr = np.array(m.devices).reshape(2, 4)
    ids = [[d.id for d in row] for row in arr]
    assert ids[0] == sorted(ids[0]) and ids[1] == sorted(ids[1])


def test_fed_mesh_divisibility():
    with pytest.raises(ValueError, match="divisible"):
        fed_mesh(sv_size=3)


def test_hybrid_falls_back_on_single_slice():
    m = hybrid_fed_mesh(sv_size=2)
    assert m.shape == {"clients": 4, "sv": 2}


def test_hybrid_array_keeps_sv_groups_within_a_slice():
    """The DCN branch (untested in round 1): every sv group must sit inside
    one slice — the sv axis exchanges half a statevector per gate and must
    ride ICI, never DCN (module header policy)."""
    arr = hybrid_device_array(fake_devices(num_slices=4, per_slice=8), sv_size=4)
    assert arr.shape == (8, 4)  # 32 devices → 8 client groups × sv 4
    for row in arr:
        assert len({d.slice_index for d in row}) == 1  # sv never crosses DCN
    # clients axis spans all slices (DCN-tolerant axis outermost)
    assert {row[0].slice_index for row in arr} == {0, 1, 2, 3}
    # slices appear in index order, and devices within a group are the
    # slice's contiguous id run (ICI adjacency proxy)
    assert [row[0].slice_index for row in arr] == [0, 0, 1, 1, 2, 2, 3, 3]
    for row in arr:
        ids = [d.id for d in row]
        assert ids == list(range(min(ids), min(ids) + 4))


def test_hybrid_array_validates_fit_and_balance():
    with pytest.raises(ValueError, match="fit within a slice"):
        hybrid_device_array(fake_devices(2, 4), sv_size=8)
    lopsided = fake_devices(2, 4)[:-1]  # one slice loses a device
    with pytest.raises(ValueError, match="unequal slice"):
        hybrid_device_array(lopsided, sv_size=2)


def test_hybrid_fed_mesh_multi_slice_sv1_shape():
    """sv_size=1 multi-slice: pure client parallelism, one column."""
    arr = hybrid_device_array(fake_devices(num_slices=2, per_slice=4), sv_size=1)
    assert arr.shape == (8, 1)
    assert [d.slice_index for d in arr[:, 0]] == [0, 0, 0, 0, 1, 1, 1, 1]
