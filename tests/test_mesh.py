"""Mesh construction helpers (single-host paths on the virtual 8-CPU mesh)."""

import jax
import numpy as np
import pytest

from qfedx_tpu.parallel.mesh import fed_mesh, hybrid_fed_mesh


def test_fed_mesh_shapes():
    m = fed_mesh(sv_size=1)
    assert m.shape == {"clients": 8, "sv": 1}
    m = fed_mesh(sv_size=4)
    assert m.shape == {"clients": 2, "sv": 4}
    # sv groups are contiguous device runs (ICI-adjacency proxy)
    arr = np.array(m.devices).reshape(2, 4)
    ids = [[d.id for d in row] for row in arr]
    assert ids[0] == sorted(ids[0]) and ids[1] == sorted(ids[1])


def test_fed_mesh_divisibility():
    with pytest.raises(ValueError, match="divisible"):
        fed_mesh(sv_size=3)


def test_hybrid_falls_back_on_single_slice():
    m = hybrid_fed_mesh(sv_size=2)
    assert m.shape == {"clients": 4, "sv": 2}
