"""Sweep harness (reference ROADMAP.md:102-120's evaluation protocol)."""

import json

import numpy as np
import pytest

from qfedx_tpu.run.sweep import preset_cells, run_sweep


def test_presets_well_formed():
    for preset in ("quick", "roadmap", "baseline"):
        cells = preset_cells(preset)
        assert cells and len({c["name"] for c in cells}) == len(cells)
    # roadmap carries the spec's grid axes: qubits, α, p, σ
    names = [c["name"] for c in preset_cells("roadmap")]
    assert {"q2-iid", "q8-iid", "q4-a0.1", "q4-p0.3", "q4-dp2.0"} <= set(names)
    with pytest.raises(ValueError, match="unknown preset"):
        preset_cells("nope")


@pytest.mark.slow
def test_sweep_quick_end_to_end(tmp_path):
    """2 cells × 2 seeds through the full path: results.json with per-seed
    runs and mean±std aggregates, the markdown table, and the DP plot."""
    result = run_sweep(preset="quick", seeds=2, root=tmp_path)
    out = tmp_path / "sweep-quick"
    data = json.loads((out / "results.json").read_text())
    assert data["seeds"] == 2
    aggs = data["aggregates"]
    assert set(aggs) == {"q4-iid", "q4-dp"}
    for name, a in aggs.items():
        # High-variance cells escalate to 5 seeds (ROADMAP.md:119's 3–5
        # band, triggered at accuracy std > 0.1); quiet cells stay at the
        # requested 2. Either way the escalation rule must hold.
        runs = data["runs"][name]
        accs = [r["accuracy"] for r in runs]
        assert a["n_seeds"] == len(runs)
        if a["n_seeds"] == 2:
            assert float(np.std(accs)) <= 0.1
        else:
            # Escalation runs ALL the way to 5 once triggered (no
            # data-dependent early stop — ADVICE r04 item 2): the
            # trigger is std > 0.1 over the base seeds.
            assert a["n_seeds"] == 5
            assert float(np.std(accs[:2])) > 0.1  # the base-seed trigger
        assert a["accuracy_min"] == pytest.approx(min(accs))
        assert 0.0 <= a["accuracy_mean"] <= 1.0 and a["accuracy_std"] >= 0.0
        assert a["comm_mb_per_round"] > 0
    assert aggs["q4-dp"]["epsilon_mean"] > 0  # DP cell tracked ε
    md = (out / "results.md").read_text()
    assert "q4-dp" in md and "±" in md
    assert (out / "accuracy_vs_epsilon.png").exists()  # DP cell present
    assert result["dir"] == str(out)
