"""Observability subsystem: spans, counters, exporters, trainer wiring.

Covers the ISSUE r08 acceptance surface that is testable on CPU: the
QFEDX_TRACE pin (default-off no-op path), span nesting/attribution,
jax.monitoring compile attribution, the Chrome/Perfetto trace.json
structure (schema + monotonic, nested intervals), and the trainer's
per-round ``phases`` metrics + summary ``phase_breakdown`` rollup.
"""

import json

import numpy as np
import pytest

from qfedx_tpu import obs


@pytest.fixture()
def traced(monkeypatch):
    """Fresh registry with tracing pinned on; leaves a clean registry."""
    monkeypatch.setenv("QFEDX_TRACE", "1")
    obs.reset()
    yield
    obs.reset()


# --- pin + disabled path -----------------------------------------------------


def test_disabled_by_default(monkeypatch):
    monkeypatch.delenv("QFEDX_TRACE", raising=False)
    obs.reset()
    assert not obs.enabled()
    with obs.span("phantom") as sp:
        obs.counter("phantom.count")
        obs.gauge("phantom.gauge", 3.0)
    # Null span: shared no-op object, nothing recorded anywhere.
    assert sp.duration == 0.0
    sp.set(extra=1)  # no-op, must not raise
    assert obs.registry().spans == []
    assert obs.registry().counters == {}
    assert obs.registry().gauges == {}


def test_disabled_span_is_shared_singleton(monkeypatch):
    monkeypatch.delenv("QFEDX_TRACE", raising=False)
    with obs.span("a") as s1:
        pass
    with obs.span("b") as s2:
        pass
    assert s1 is s2  # the disabled path allocates nothing


def test_pin_rejects_typos(monkeypatch):
    monkeypatch.setenv("QFEDX_TRACE", "yes")
    with pytest.raises(ValueError, match="QFEDX_TRACE"):
        obs.enabled()


def test_pin_off_values(monkeypatch):
    for v in ("0", "off"):
        monkeypatch.setenv("QFEDX_TRACE", v)
        assert not obs.enabled()
    for v in ("1", "on"):
        monkeypatch.setenv("QFEDX_TRACE", v)
        assert obs.enabled()


# --- spans, counters, rollups ------------------------------------------------


def test_span_nesting_and_meta(traced):
    with obs.span("outer", round=1) as outer:
        with obs.span("inner") as inner:
            inner.set(items=3)
    spans = obs.registry().spans
    assert [s.name for s in spans] == ["inner", "outer"]  # closed in order
    inner, outer = spans
    assert inner.depth == 1 and outer.depth == 0
    assert inner.parent is outer
    assert outer.meta == {"round": 1} and inner.meta == {"items": 3}
    # Monotonic + nested intervals.
    assert outer.t1 >= outer.t0 and inner.t1 >= inner.t0
    assert outer.t0 <= inner.t0 and inner.t1 <= outer.t1


def test_counters_and_gauges(traced):
    obs.counter("ops", 3)
    obs.counter("ops", 4)
    obs.gauge("mem", 10.0)
    obs.gauge("mem", 20.0)
    reg = obs.registry()
    assert reg.counters["ops"] == 7.0
    assert reg.gauges["mem"] == 20.0  # last value wins


def test_phase_rollup(traced):
    for _ in range(4):
        with obs.span("a"):
            pass
    with obs.span("b"):
        pass
    roll = obs.phase_rollup()
    assert set(roll) == {"a", "b"}
    assert roll["a"]["count"] == 4 and roll["b"]["count"] == 1
    for row in roll.values():
        assert row["total_s"] >= row["p50_s"] >= 0.0
        assert row["p95_s"] >= row["p50_s"]
    totals = obs.phase_totals()
    assert totals["a"] == roll["a"]["total_s"]


def test_compile_time_attributed_to_open_span(traced):
    import jax
    import jax.numpy as jnp

    offset = np.random.default_rng(0).uniform()  # defeat any jit cache

    @jax.jit
    def fresh(x):
        return jnp.sin(x) * offset + 1.0

    with obs.span("round.dispatch") as sp:
        fresh(jnp.arange(8.0)).block_until_ready()
    assert sp.compile_s > 0.0, "jax.monitoring compile events not attributed"
    counters = obs.registry().counters
    assert any(k.startswith("compile.") for k in counters)


# --- chrome trace ------------------------------------------------------------


def _validate_chrome_trace(path):
    """Structural Perfetto/chrome://tracing contract: traceEvents list,
    complete ("X") events with the required keys, non-negative monotonic
    intervals, children nested inside their parents."""
    obj = json.loads(path.read_text())
    assert isinstance(obj["traceEvents"], list)
    xs = [e for e in obj["traceEvents"] if e.get("ph") == "X"]
    assert xs, "no complete events"
    for e in xs:
        for key in ("name", "ph", "ts", "dur", "pid", "tid"):
            assert key in e, f"event missing {key}: {e}"
        assert e["ts"] >= 0 and e["dur"] >= 0
    return xs


def test_write_chrome_trace_schema_and_nesting(traced, tmp_path):
    with obs.span("round", round=1):
        with obs.span("dispatch"):
            pass
        with obs.span("eval"):
            pass
    obs.counter("c", 2)
    path = obs.write_chrome_trace(tmp_path / "trace.json")
    xs = _validate_chrome_trace(path)
    by_name = {e["name"]: e for e in xs}
    assert set(by_name) == {"round", "dispatch", "eval"}
    parent = by_name["round"]
    for child in ("dispatch", "eval"):
        c = by_name[child]
        assert parent["ts"] <= c["ts"]
        assert c["ts"] + c["dur"] <= parent["ts"] + parent["dur"] + 1e-3
    # Counters ride along as an instant event; metadata names the process.
    phs = {e.get("ph") for e in json.loads(path.read_text())["traceEvents"]}
    assert "M" in phs and "i" in phs


# --- trainer integration -----------------------------------------------------


def test_trainer_emits_phases_and_rollup(traced, tmp_path):
    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.models.vqc import make_vqc_classifier
    from qfedx_tpu.run.metrics import ExperimentRun
    from qfedx_tpu.run.trainer import train_federated

    model = make_vqc_classifier(n_qubits=2, n_layers=1, num_classes=2)
    rng = np.random.default_rng(0)
    cx = rng.uniform(0, 1, (4, 8, 2)).astype(np.float32)
    cy = rng.integers(0, 2, (4, 8)).astype(np.int32)
    cm = np.ones((4, 8), dtype=np.float32)
    tx = rng.uniform(0, 1, (16, 2)).astype(np.float32)
    ty = rng.integers(0, 2, 16).astype(np.int32)
    cfg = FedConfig(local_epochs=1, batch_size=4, learning_rate=0.1)

    rows = []
    with ExperimentRun(tmp_path, "obs", config=cfg) as run:
        res = train_federated(
            model, cfg, cx, cy, cm, tx, ty, num_rounds=2,
            on_round_end=lambda r, m: (rows.append(m), run.on_round_end(r, m)),
        )
        run.finish(final_accuracy=res.final_accuracy)

    # Every metrics row carries its phase walls; dispatch dominates and
    # the recorded phases stay within the row's measured wall.
    assert len(rows) == 2
    for row in rows:
        phases = row["phases"]
        assert phases["dispatch_s"] > 0
        assert phases["dispatch_s"] <= row["time_s"] + 1e-6
        assert phases["eval_s"] >= 0
    # Round 1 triggered the XLA compile; the listener must attribute it
    # to that round's dispatch, not let it hide in wall time (r05 bug).
    assert rows[0]["phases"].get("compile_s", 0) > 0
    assert "compile_s" not in rows[1]["phases"]

    # Registry: trace-time spans from the jitted seams landed too.
    names = {s.name for s in obs.registry().spans}
    assert {"round.dispatch", "round.eval", "fed.trace.local_update",
            "fed.trace.aggregate", "engine.trace"} <= names

    # summary.json rollup (ExperimentRun.finish merges it when tracing).
    summary = json.loads((run.dir / "summary.json").read_text())
    pb = summary["phase_breakdown"]
    assert pb["round.dispatch"]["count"] == 2
    assert pb["round.dispatch"]["total_s"] > 0
    # The JSONL rows parse and carry the same phases.
    lines = [
        json.loads(l)
        for l in (run.dir / "metrics.jsonl").read_text().splitlines()
    ]
    assert all("phases" in l for l in lines)

    # And the whole run exports a loadable chrome trace.
    path = obs.write_chrome_trace(tmp_path / "trace.json")
    _validate_chrome_trace(path)


def test_pipelined_trace_schema_dispatch_overlaps_drain(traced, tmp_path):
    """--trace on a pipelined run (r09): the exported trace.json carries
    the round.dispatch / round.fetch span pair with their round/chunk
    schema, and shows chunk k+1's dispatch event BEFORE chunk k's
    host-side drain (round.fetch) — the overlap the pipeline exists
    for, pinned on the artifact a human would actually load in
    Perfetto. (The registry-level ordering contract, both depths, is
    pinned in tests/test_pipeline.py.)"""
    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.models.vqc import make_vqc_classifier
    from qfedx_tpu.run.trainer import train_federated

    model = make_vqc_classifier(n_qubits=2, n_layers=1, num_classes=2)
    rng = np.random.default_rng(1)
    cx = rng.uniform(0, 1, (4, 8, 2)).astype(np.float32)
    cy = rng.integers(0, 2, (4, 8)).astype(np.int32)
    cm = np.ones((4, 8), dtype=np.float32)
    tx = rng.uniform(0, 1, (16, 2)).astype(np.float32)
    ty = rng.integers(0, 2, 16).astype(np.int32)
    cfg = FedConfig(local_epochs=1, batch_size=4, learning_rate=0.1)

    from qfedx_tpu.run.checkpoint import Checkpointer

    rows = []
    train_federated(
        model, cfg, cx, cy, cm, tx, ty, num_rounds=4, rounds_per_call=2,
        pipeline_depth=1, on_round_end=lambda r, m: rows.append(m),
        checkpointer=Checkpointer(tmp_path / "ck", every=2),
    )
    path = obs.write_chrome_trace(tmp_path / "trace.json")
    xs = _validate_chrome_trace(path)
    events = json.loads(path.read_text())["traceEvents"]
    # The async checkpoint write ran on the background writer thread —
    # its track is NAMED in the trace, and the span is present.
    assert any(s.name == "checkpoint.async_write"
               for s in obs.registry().spans)
    tnames = {
        e["args"]["name"] for e in events
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }
    assert "qfedx-ckpt-writer" in tnames
    disp = sorted(
        (e for e in xs if e["name"] == "round.dispatch"),
        key=lambda e: e["ts"],
    )
    fetch = sorted(
        (e for e in xs if e["name"] == "round.fetch"), key=lambda e: e["ts"]
    )
    # Schema: both span families carry the chunk's first round + length.
    assert [e["args"]["round"] for e in disp] == [1, 3]
    assert [e["args"]["chunk"] for e in disp] == [2, 2]
    assert [e["args"]["round"] for e in fetch] == [1, 3]
    # The pipeline overlap, visible in the artifact: chunk 2's dispatch
    # event starts before chunk 1's drain fetch does.
    assert disp[1]["ts"] < fetch[0]["ts"]
    # Every metrics row decomposes its wall into dispatch+fetch shares.
    assert rows and all(
        "dispatch_s" in r["phases"] and "fetch_s" in r["phases"]
        for r in rows
    )


def test_fuse_counters_via_engine(traced, monkeypatch):
    """The fusion pass reports trace-time op counts when it runs."""
    import jax
    import jax.numpy as jnp

    monkeypatch.setenv("QFEDX_GATE_FORM", "flip")
    monkeypatch.setenv("QFEDX_SLAB_LANES", "matmul")
    monkeypatch.setenv("QFEDX_BATCHED", "1")
    monkeypatch.setenv("QFEDX_FUSE", "1")
    from qfedx_tpu.models.vqc import make_vqc_classifier

    model = make_vqc_classifier(n_qubits=12, n_layers=1, num_classes=2)
    params = model.init(jax.random.PRNGKey(0))
    x = jnp.zeros((2, 12), jnp.float32)
    jax.jit(model.apply).lower(params, x)  # trace only — no CPU compile
    counters = obs.registry().counters
    assert counters.get("fuse.passes", 0) >= 1
    assert counters["fuse.ops_out"] < counters["fuse.ops_in"]
    assert any(s.name == "engine.trace" for s in obs.registry().spans)
