"""Observability subsystem: spans, counters, exporters, trainer wiring,
bounded histograms, live endpoints, trace shards (r08 + r15).

Covers the r08 acceptance surface testable on CPU (QFEDX_TRACE pin,
span nesting/attribution, compile attribution, trace.json structure,
trainer phases/rollup) plus the r15 live half: log-bucketed histogram
quantile error (within one bucket-width of exact), registry thread
safety under concurrent writers, the /metrics + /healthz endpoint and
its default-off invariance, request-scoped trace contexts, the
multi-process shard merge unit logic, and the crash-flushed partial
trace.
"""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from qfedx_tpu import obs
from qfedx_tpu.obs import server as obs_server


@pytest.fixture()
def traced(monkeypatch):
    """Fresh registry with tracing pinned on; leaves a clean registry."""
    monkeypatch.setenv("QFEDX_TRACE", "1")
    obs.reset()
    yield
    obs.reset()


# --- pin + disabled path -----------------------------------------------------


def test_disabled_by_default(monkeypatch):
    monkeypatch.delenv("QFEDX_TRACE", raising=False)
    obs.reset()
    assert not obs.enabled()
    with obs.span("phantom") as sp:
        obs.counter("phantom.count")
        obs.gauge("phantom.gauge", 3.0)
    # Null span: shared no-op object, nothing recorded anywhere.
    assert sp.duration == 0.0
    sp.set(extra=1)  # no-op, must not raise
    assert obs.registry().spans == []
    assert obs.registry().counters == {}
    assert obs.registry().gauges == {}


def test_disabled_span_is_shared_singleton(monkeypatch):
    monkeypatch.delenv("QFEDX_TRACE", raising=False)
    with obs.span("a") as s1:
        pass
    with obs.span("b") as s2:
        pass
    assert s1 is s2  # the disabled path allocates nothing


def test_pin_rejects_typos(monkeypatch):
    monkeypatch.setenv("QFEDX_TRACE", "yes")
    with pytest.raises(ValueError, match="QFEDX_TRACE"):
        obs.enabled()


def test_pin_off_values(monkeypatch):
    for v in ("0", "off"):
        monkeypatch.setenv("QFEDX_TRACE", v)
        assert not obs.enabled()
    for v in ("1", "on"):
        monkeypatch.setenv("QFEDX_TRACE", v)
        assert obs.enabled()


# --- spans, counters, rollups ------------------------------------------------


def test_span_nesting_and_meta(traced):
    with obs.span("outer", round=1) as outer:
        with obs.span("inner") as inner:
            inner.set(items=3)
    spans = obs.registry().spans
    assert [s.name for s in spans] == ["inner", "outer"]  # closed in order
    inner, outer = spans
    assert inner.depth == 1 and outer.depth == 0
    assert inner.parent is outer
    assert outer.meta == {"round": 1} and inner.meta == {"items": 3}
    # Monotonic + nested intervals.
    assert outer.t1 >= outer.t0 and inner.t1 >= inner.t0
    assert outer.t0 <= inner.t0 and inner.t1 <= outer.t1


def test_counters_and_gauges(traced):
    obs.counter("ops", 3)
    obs.counter("ops", 4)
    obs.gauge("mem", 10.0)
    obs.gauge("mem", 20.0)
    reg = obs.registry()
    assert reg.counters["ops"] == 7.0
    assert reg.gauges["mem"] == 20.0  # last value wins


def test_phase_rollup(traced):
    for _ in range(4):
        with obs.span("a"):
            pass
    with obs.span("b"):
        pass
    roll = obs.phase_rollup()
    assert set(roll) == {"a", "b"}
    assert roll["a"]["count"] == 4 and roll["b"]["count"] == 1
    for row in roll.values():
        assert row["total_s"] >= row["p50_s"] >= 0.0
        assert row["p95_s"] >= row["p50_s"]
    totals = obs.phase_totals()
    assert totals["a"] == roll["a"]["total_s"]


def test_compile_time_attributed_to_open_span(traced):
    import jax
    import jax.numpy as jnp

    offset = np.random.default_rng(0).uniform()  # defeat any jit cache

    @jax.jit
    def fresh(x):
        return jnp.sin(x) * offset + 1.0

    with obs.span("round.dispatch") as sp:
        fresh(jnp.arange(8.0)).block_until_ready()
    assert sp.compile_s > 0.0, "jax.monitoring compile events not attributed"
    counters = obs.registry().counters
    assert any(k.startswith("compile.") for k in counters)


# --- chrome trace ------------------------------------------------------------


def _validate_chrome_trace(path):
    """Structural Perfetto/chrome://tracing contract: traceEvents list,
    complete ("X") events with the required keys, non-negative monotonic
    intervals, children nested inside their parents."""
    obj = json.loads(path.read_text())
    assert isinstance(obj["traceEvents"], list)
    xs = [e for e in obj["traceEvents"] if e.get("ph") == "X"]
    assert xs, "no complete events"
    for e in xs:
        for key in ("name", "ph", "ts", "dur", "pid", "tid"):
            assert key in e, f"event missing {key}: {e}"
        assert e["ts"] >= 0 and e["dur"] >= 0
    return xs


def test_write_chrome_trace_schema_and_nesting(traced, tmp_path):
    with obs.span("round", round=1):
        with obs.span("dispatch"):
            pass
        with obs.span("eval"):
            pass
    obs.counter("c", 2)
    path = obs.write_chrome_trace(tmp_path / "trace.json")
    xs = _validate_chrome_trace(path)
    by_name = {e["name"]: e for e in xs}
    assert set(by_name) == {"round", "dispatch", "eval"}
    parent = by_name["round"]
    for child in ("dispatch", "eval"):
        c = by_name[child]
        assert parent["ts"] <= c["ts"]
        assert c["ts"] + c["dur"] <= parent["ts"] + parent["dur"] + 1e-3
    # Counters ride along as an instant event; metadata names the process.
    phs = {e.get("ph") for e in json.loads(path.read_text())["traceEvents"]}
    assert "M" in phs and "i" in phs


# --- trainer integration -----------------------------------------------------


def test_trainer_emits_phases_and_rollup(traced, tmp_path):
    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.models.vqc import make_vqc_classifier
    from qfedx_tpu.run.metrics import ExperimentRun
    from qfedx_tpu.run.trainer import train_federated

    model = make_vqc_classifier(n_qubits=2, n_layers=1, num_classes=2)
    rng = np.random.default_rng(0)
    cx = rng.uniform(0, 1, (4, 8, 2)).astype(np.float32)
    cy = rng.integers(0, 2, (4, 8)).astype(np.int32)
    cm = np.ones((4, 8), dtype=np.float32)
    tx = rng.uniform(0, 1, (16, 2)).astype(np.float32)
    ty = rng.integers(0, 2, 16).astype(np.int32)
    cfg = FedConfig(local_epochs=1, batch_size=4, learning_rate=0.1)

    rows = []
    with ExperimentRun(tmp_path, "obs", config=cfg) as run:
        res = train_federated(
            model, cfg, cx, cy, cm, tx, ty, num_rounds=2,
            on_round_end=lambda r, m: (rows.append(m), run.on_round_end(r, m)),
        )
        run.finish(final_accuracy=res.final_accuracy)

    # Every metrics row carries its phase walls; dispatch dominates and
    # the recorded phases stay within the row's measured wall.
    assert len(rows) == 2
    for row in rows:
        phases = row["phases"]
        assert phases["dispatch_s"] > 0
        assert phases["dispatch_s"] <= row["time_s"] + 1e-6
        assert phases["eval_s"] >= 0
    # Round 1 triggered the XLA compile; the listener must attribute it
    # to that round's dispatch, not let it hide in wall time (r05 bug).
    assert rows[0]["phases"].get("compile_s", 0) > 0
    assert "compile_s" not in rows[1]["phases"]

    # Registry: trace-time spans from the jitted seams landed too.
    names = {s.name for s in obs.registry().spans}
    assert {"round.dispatch", "round.eval", "fed.trace.local_update",
            "fed.trace.aggregate", "engine.trace"} <= names

    # summary.json rollup (ExperimentRun.finish merges it when tracing).
    summary = json.loads((run.dir / "summary.json").read_text())
    pb = summary["phase_breakdown"]
    assert pb["round.dispatch"]["count"] == 2
    assert pb["round.dispatch"]["total_s"] > 0
    # The JSONL rows parse and carry the same phases.
    lines = [
        json.loads(l)
        for l in (run.dir / "metrics.jsonl").read_text().splitlines()
    ]
    assert all("phases" in l for l in lines)

    # And the whole run exports a loadable chrome trace.
    path = obs.write_chrome_trace(tmp_path / "trace.json")
    _validate_chrome_trace(path)


def test_pipelined_trace_schema_dispatch_overlaps_drain(traced, tmp_path):
    """--trace on a pipelined run (r09): the exported trace.json carries
    the round.dispatch / round.fetch span pair with their round/chunk
    schema, and shows chunk k+1's dispatch event BEFORE chunk k's
    host-side drain (round.fetch) — the overlap the pipeline exists
    for, pinned on the artifact a human would actually load in
    Perfetto. (The registry-level ordering contract, both depths, is
    pinned in tests/test_pipeline.py.)"""
    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.models.vqc import make_vqc_classifier
    from qfedx_tpu.run.trainer import train_federated

    model = make_vqc_classifier(n_qubits=2, n_layers=1, num_classes=2)
    rng = np.random.default_rng(1)
    cx = rng.uniform(0, 1, (4, 8, 2)).astype(np.float32)
    cy = rng.integers(0, 2, (4, 8)).astype(np.int32)
    cm = np.ones((4, 8), dtype=np.float32)
    tx = rng.uniform(0, 1, (16, 2)).astype(np.float32)
    ty = rng.integers(0, 2, 16).astype(np.int32)
    cfg = FedConfig(local_epochs=1, batch_size=4, learning_rate=0.1)

    from qfedx_tpu.run.checkpoint import Checkpointer

    rows = []
    train_federated(
        model, cfg, cx, cy, cm, tx, ty, num_rounds=4, rounds_per_call=2,
        pipeline_depth=1, on_round_end=lambda r, m: rows.append(m),
        checkpointer=Checkpointer(tmp_path / "ck", every=2),
    )
    path = obs.write_chrome_trace(tmp_path / "trace.json")
    xs = _validate_chrome_trace(path)
    events = json.loads(path.read_text())["traceEvents"]
    # The async checkpoint write ran on the background writer thread —
    # its track is NAMED in the trace, and the span is present.
    assert any(s.name == "checkpoint.async_write"
               for s in obs.registry().spans)
    tnames = {
        e["args"]["name"] for e in events
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }
    assert "qfedx-ckpt-writer" in tnames
    disp = sorted(
        (e for e in xs if e["name"] == "round.dispatch"),
        key=lambda e: e["ts"],
    )
    fetch = sorted(
        (e for e in xs if e["name"] == "round.fetch"), key=lambda e: e["ts"]
    )
    # Schema: both span families carry the chunk's first round + length.
    assert [e["args"]["round"] for e in disp] == [1, 3]
    assert [e["args"]["chunk"] for e in disp] == [2, 2]
    assert [e["args"]["round"] for e in fetch] == [1, 3]
    # The pipeline overlap, visible in the artifact: chunk 2's dispatch
    # event starts before chunk 1's drain fetch does.
    assert disp[1]["ts"] < fetch[0]["ts"]
    # Every metrics row decomposes its wall into dispatch+fetch shares.
    assert rows and all(
        "dispatch_s" in r["phases"] and "fetch_s" in r["phases"]
        for r in rows
    )


# --- bounded histograms (r15 tentpole) ---------------------------------------


def test_histogram_quantiles_within_one_bucket_of_exact():
    """The accuracy pin: the histogram's p50/p95 apply obs.percentile's
    nearest-rank rule to bucket counts and report the LOWER edge of the
    bucket holding that rank — so |approx - exact| < that bucket's
    width, and approx <= exact always."""
    rng = np.random.default_rng(7)
    for scale, vals in (
        ("ms", rng.lognormal(1.0, 1.2, 4000)),
        ("s", rng.uniform(1e-4, 5e-2, 1000)),
    ):
        h = obs.Histogram()
        for v in vals:
            h.record(v)
        s = sorted(vals)
        for q in (0.5, 0.95, 0.99):
            exact = obs.percentile(s, q)
            approx = h.percentile(q)
            lo, hi = obs.Histogram.bucket_bounds(exact)
            assert lo <= exact < hi
            assert approx == lo, (
                f"{scale} q={q}: approx {approx} != lower edge {lo} "
                f"of exact {exact}'s bucket"
            )
            assert approx <= exact < approx + (hi - lo) + 1e-12


def test_histogram_count_sum_empty_and_clamps():
    h = obs.Histogram()
    assert h.percentile(0.5) == 0.0 and h.count == 0
    h.record(0.0)        # below LO -> underflow, lower edge 0
    h.record(1e30)       # beyond the grid -> overflow bucket
    assert h.count == 2
    assert h.percentile(0.0) == 0.0
    assert h.percentile(1.0) > 0.0  # overflow lower edge, not inf/crash
    assert h.sum == pytest.approx(1e30)


def test_histogram_merge_is_exact():
    rng = np.random.default_rng(1)
    a_vals = rng.lognormal(0, 1, 500)
    b_vals = rng.lognormal(2, 0.5, 700)
    a, b, both = obs.Histogram(), obs.Histogram(), obs.Histogram()
    for v in a_vals:
        a.record(v)
        both.record(v)
    for v in b_vals:
        b.record(v)
        both.record(v)
    a.merge(b)
    assert a.count == both.count
    assert a.sum == pytest.approx(both.sum)
    for q in (0.1, 0.5, 0.95):
        assert a.percentile(q) == both.percentile(q)


def test_phase_rollup_histogram_quantiles_match_exact_within_bucket(traced):
    """The rollup now reads bucket-resolution quantiles from the
    registry's per-span histograms; count/total stay exact and p95
    stays within one bucket-width of the sorted-span-list answer."""
    import time as _time

    for i in range(20):
        with obs.span("work"):
            _time.sleep(0.0002 * (1 + (i % 5)))
    durs = sorted(
        s.duration for s in obs.registry().spans if s.name == "work"
    )
    roll = obs.phase_rollup()["work"]
    assert roll["count"] == 20
    assert roll["total_s"] == pytest.approx(sum(durs), rel=1e-4)
    exact95 = obs.percentile(durs, 0.95)
    lo, hi = obs.Histogram.bucket_bounds(exact95)
    assert lo - 1e-6 <= roll["p95_s"] <= exact95
    # Explicit span lists roll up through the SAME definition.
    assert (
        obs.phase_rollup(obs.registry().spans)["work"]["p95_s"]
        == roll["p95_s"]
    )


# --- registry thread safety (r15 hardening satellite) ------------------------


def test_registry_hammer_concurrent_writers_lose_nothing(traced):
    """Uploader/serve/telemetry threads bump the same instruments
    concurrently; the registry must lose no increments, histogram
    observations, or spans."""
    threads_n, per_thread = 8, 2000

    def hammer(tid):
        for i in range(per_thread):
            obs.counter("hammer.count")
            obs.counter("hammer.weighted", 2.0)
            obs.histogram("hammer.histo", 1.0 + (i % 7))
            obs.gauge(f"hammer.gauge_{tid}", float(i))
        with obs.span("hammer.span"):
            pass

    threads = [
        threading.Thread(target=hammer, args=(t,)) for t in range(threads_n)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    reg = obs.registry()
    assert reg.counters["hammer.count"] == threads_n * per_thread
    assert reg.counters["hammer.weighted"] == 2.0 * threads_n * per_thread
    assert reg.histos["hammer.histo"].count == threads_n * per_thread
    assert sum(1 for s in reg.spans if s.name == "hammer.span") == threads_n
    for t in range(threads_n):
        assert reg.gauges[f"hammer.gauge_{t}"] == float(per_thread - 1)


# --- windowed snapshots: Histogram.snapshot_delta (r21) ----------------------


def test_snapshot_delta_exact_nearest_rank_on_drift():
    """The tune controller's window rule: snapshot_delta returns the
    since-last-call window — count/sum exact, p50/p95 the nearest-rank
    lower-edge quantile over ONLY the window — so a latency regime
    change shows up in one tick instead of being averaged into the
    lifetime distribution."""
    h = obs.Histogram()
    fast = [1.0 + 0.1 * i for i in range(50)]
    for v in fast:
        h.record(v)
    w1 = h.snapshot_delta()
    assert w1["count"] == 50
    assert w1["sum"] == pytest.approx(sum(fast), rel=1e-9)
    # Drift: the next window must see ONLY the slow regime.
    slow = [10.0] * 10 + [200.0] * 10
    for v in slow:
        h.record(v)
    w2 = h.snapshot_delta()
    assert w2["count"] == 20
    assert w2["sum"] == pytest.approx(sum(slow), rel=1e-9)
    for q, got in ((0.50, w2["p50"]), (0.95, w2["p95"])):
        exact = obs.percentile(sorted(slow), q)
        lo, _hi = obs.Histogram.bucket_bounds(exact)
        assert lo - 1e-9 <= got <= exact, (q, got, exact)
    # p95 reflects the drift, not the 50 fast samples still in the
    # lifetime counts.
    assert w2["p95"] > max(fast)
    assert h.count == 70  # lifetime view untouched by the rebasing
    # The window is consumed: an idle tick reads an empty window.
    w3 = h.snapshot_delta()
    assert w3 == {"count": 0, "sum": 0.0, "p50": 0.0, "p95": 0.0}


def test_snapshot_delta_thread_safe_under_hammer():
    """Writers hammer the histogram while one consumer (the tune
    ticker's role) drains windows: no observation may be lost or
    double-counted across the window boundaries."""
    h = obs.Histogram()
    threads_n, per_thread = 8, 2000
    windows: list[dict] = []
    stop = threading.Event()

    def writer():
        for i in range(per_thread):
            h.record(1.0 + (i % 7))

    def consumer():
        while not stop.is_set():
            windows.append(h.snapshot_delta())

    threads = [threading.Thread(target=writer) for _ in range(threads_n)]
    drain = threading.Thread(target=consumer)
    drain.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    drain.join()
    windows.append(h.snapshot_delta())  # the remainder
    total = threads_n * per_thread
    assert sum(w["count"] for w in windows) == total
    expect_sum = sum(1.0 + (i % 7) for i in range(per_thread)) * threads_n
    assert sum(w["sum"] for w in windows) == pytest.approx(
        expect_sum, rel=1e-6
    )
    assert h.count == total  # lifetime counts saw every record too


# --- request-scoped trace context (r15 tentpole) -----------------------------


def test_trace_context_stamps_nested_spans(traced):
    with obs.trace_context(reqs="3,4,5"):
        with obs.span("serve.pad", batch=3) as sp:
            pass
        with obs.trace_context(reqs="9"):  # innermost context wins
            with obs.span("serve.compute"):
                pass
    with obs.span("outside"):
        pass
    spans = {s.name: s for s in obs.registry().spans}
    assert spans["serve.pad"].meta == {"reqs": "3,4,5", "batch": 3}
    assert spans["serve.compute"].meta == {"reqs": "9"}
    assert "reqs" not in spans["outside"].meta
    # explicit span meta beats the context on collision
    with obs.trace_context(reqs="1"):
        with obs.span("explicit", reqs="override"):
            pass
    assert obs.registry().spans[-1].meta["reqs"] == "override"


def test_trace_context_noop_when_disabled(monkeypatch):
    monkeypatch.delenv("QFEDX_TRACE", raising=False)
    obs.reset()
    with obs.trace_context(reqs="1,2"):
        with obs.span("x"):
            pass
    assert obs.registry().spans == []


# --- live endpoints (r15 tentpole) -------------------------------------------


from conftest import free_port as _free_port  # noqa: E402 — shared helper


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return r.status, r.read().decode()


@pytest.fixture()
def telemetry():
    """An ephemeral-port telemetry server; always torn down."""
    srv = obs_server.start_server(0)
    yield srv
    obs_server.stop_server()


def test_metrics_endpoint_renders_registry(traced, telemetry):
    obs.counter("serve.requests_served", 5)
    obs.counter("serve.requests_served", 2)
    obs.gauge("serve.queue_depth", 3)
    for v in (1.0, 2.0, 4.0):
        obs.histogram("serve.latency_ms", v)
    with obs.span("round.dispatch", round=1):
        pass
    status, body = _get(telemetry.port, "/metrics")
    assert status == 200
    lines = body.splitlines()
    assert "qfedx_serve_requests_served 7.0" in lines
    assert "qfedx_serve_queue_depth 3.0" in lines
    assert "qfedx_serve_latency_ms_count 3" in lines
    assert 'qfedx_serve_latency_ms_bucket{le="+Inf"} 3' in lines
    # cumulative le rows are non-decreasing and end at count
    cums = [
        int(l.rsplit(" ", 1)[1]) for l in lines
        if l.startswith("qfedx_serve_latency_ms_bucket")
    ]
    assert cums == sorted(cums) and cums[-1] == 3
    # span-duration histograms render with the _seconds suffix
    assert any(l.startswith("qfedx_round_dispatch_seconds_count") for l in lines)
    # the scrape itself recorded an obs.http span
    assert any(
        s.name == "obs.http" and s.meta.get("path") == "/metrics"
        for s in obs.registry().spans
    )


def test_healthz_sources_and_degraded_status(telemetry):
    obs_server.set_health_source(
        "trainer", lambda: {"last_completed_round": 4, "rounds_total": 10}
    )
    try:
        status, body = _get(telemetry.port, "/healthz")
        assert status == 200
        hz = json.loads(body)
        assert hz["status"] == "ok"
        assert hz["components"]["trainer"]["last_completed_round"] == 4
        from qfedx_tpu.run.metrics import METRICS_SCHEMA_VERSION

        assert hz["metrics_schema"] == METRICS_SCHEMA_VERSION

        def sick():
            raise RuntimeError("wedged")

        obs_server.set_health_source("serve", sick)
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            _get(telemetry.port, "/healthz")
        assert exc_info.value.code == 503
        hz = json.loads(exc_info.value.read())
        assert hz["status"] == "degraded"
        assert "wedged" in hz["components"]["serve"]["error"]
        # a sick source must not take the healthy one down with it
        assert hz["components"]["trainer"]["last_completed_round"] == 4
    finally:
        obs_server.clear_health_source("trainer")
        obs_server.clear_health_source("serve")


def test_unknown_path_404s(telemetry):
    with pytest.raises(urllib.error.HTTPError) as exc_info:
        _get(telemetry.port, "/nope")
    assert exc_info.value.code == 404


def test_live_metrics_gate_without_trace_pin(monkeypatch, telemetry):
    """While an endpoint is up the BOUNDED instruments record with
    QFEDX_TRACE off; spans (unbounded) still require the pin."""
    monkeypatch.delenv("QFEDX_TRACE", raising=False)
    obs.reset()
    assert obs.metrics_enabled() and not obs.enabled()
    obs.counter("live.count")
    obs.histogram("live.histo", 1.0)
    with obs.span("live.span"):
        pass
    reg = obs.registry()
    assert reg.counters["live.count"] == 1.0
    assert reg.histos["live.histo"].count == 1
    assert reg.spans == []  # spans stay pin-gated


def test_metrics_port_default_off_invariance(monkeypatch):
    """With QFEDX_METRICS_PORT unset, maybe_start is a no-op: no server,
    no qfedx-metrics thread, instruments stay dark."""
    monkeypatch.delenv("QFEDX_METRICS_PORT", raising=False)
    monkeypatch.delenv("QFEDX_TRACE", raising=False)
    obs.reset()
    assert obs_server.maybe_start() is None
    assert obs_server.active_server() is None
    assert not any(
        t.name == "qfedx-metrics" for t in threading.enumerate()
    )
    obs.counter("dark")
    assert obs.registry().counters == {}


def test_metrics_port_pin_grammar(monkeypatch):
    from qfedx_tpu.utils.pins import port_pin

    monkeypatch.setenv("QFEDX_METRICS_PORT", "off")
    assert obs_server.metrics_port() == 0
    monkeypatch.setenv("QFEDX_METRICS_PORT", "9108")
    assert obs_server.metrics_port() == 9108
    for bad in ("fast", "-1", "70000"):
        monkeypatch.setenv("QFEDX_METRICS_PORT", bad)
        with pytest.raises(ValueError, match="QFEDX_METRICS_PORT"):
            port_pin("QFEDX_METRICS_PORT")


def test_metrics_name_collision_renders(traced, telemetry):
    """A value histogram sharing a name with a span must not break the
    scrape (sorted() once compared the Histogram objects themselves)."""
    obs.histogram("collide", 1.0)
    with obs.span("collide"):
        pass
    status, body = _get(telemetry.port, "/metrics")
    assert status == 200
    assert "qfedx_collide_count 1" in body
    assert "qfedx_collide_seconds_count 1" in body


def test_maybe_start_degrades_on_busy_port(monkeypatch):
    """Two processes sharing one exported QFEDX_METRICS_PORT (gloo pair,
    trainer + serve on a host): the loser warns and runs WITHOUT
    telemetry instead of dying at startup."""
    import socket as socket_mod

    with socket_mod.socket() as holder:
        holder.bind(("127.0.0.1", 0))
        holder.listen(1)
        port = holder.getsockname()[1]
        monkeypatch.setenv("QFEDX_METRICS_PORT", str(port))
        with pytest.warns(RuntimeWarning, match="QFEDX_METRICS_PORT"):
            assert obs_server.maybe_start() is None
        assert obs_server.active_server() is None


def test_maybe_start_honors_pin_and_is_idempotent(monkeypatch):
    port = _free_port()
    monkeypatch.setenv("QFEDX_METRICS_PORT", str(port))
    try:
        srv = obs_server.maybe_start()
        assert srv is not None and srv.port == port
        assert obs_server.maybe_start() is srv  # one server per process
        status, _ = _get(port, "/healthz")
        assert status == 200
    finally:
        obs_server.stop_server()


# --- trace shards + merge (r15 tentpole; unit half of the gloo pin) ----------


def _make_shard(tmp_path, idx, origin_unix, span_names, monkeypatch):
    monkeypatch.setenv("QFEDX_TRACE", "1")
    obs.reset()
    obs.registry().origin_unix = origin_unix
    outer, *inner = span_names
    with obs.span(outer, round=1):
        for name in inner:
            with obs.span(name):
                pass
    return obs.write_trace_shard(tmp_path, process_index=idx)


def test_trace_shard_write_and_merge_aligns_lanes(tmp_path, monkeypatch):
    p0 = _make_shard(
        tmp_path, 0, 1000.0, ["round.dispatch", "round.fetch"], monkeypatch
    )
    p1 = _make_shard(
        tmp_path, 1, 1001.5, ["round.dispatch", "round.eval"], monkeypatch
    )
    assert [p.name for p in (p0, p1)] == ["trace.0.json", "trace.1.json"]
    assert obs.find_shards(tmp_path) == [p0, p1]
    # each shard is itself a loadable chrome trace
    for p in (p0, p1):
        obj = json.loads(p.read_text())
        assert obj["traceEvents"] and "qfedx_shard" in obj
    merged = obs.merge_trace_shards(
        tmp_path, out_path=tmp_path / "merged.json"
    )
    assert json.loads((tmp_path / "merged.json").read_text()) == merged
    xs = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
    assert {e["pid"] for e in xs} == {0, 1}
    lanes = {
        e["pid"]: e["args"]["name"]
        for e in merged["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    assert lanes == {0: "qfedx process 0", 1: "qfedx process 1"}
    # alignment: shard 1's origin is 1.5 s later -> its events shift
    # +1.5e6 µs relative to shard 0's lane
    lane0 = [e for e in xs if e["pid"] == 0]
    lane1 = [e for e in xs if e["pid"] == 1]
    assert min(e["ts"] for e in lane1) >= 1.5e6
    assert min(e["ts"] for e in lane0) < 1.5e6
    # nesting survives the shift per lane
    for lane in (lane0, lane1):
        parent = max(lane, key=lambda e: e["dur"])
        for e in lane:
            assert parent["ts"] <= e["ts"]
            assert e["ts"] + e["dur"] <= parent["ts"] + parent["dur"] + 1e-3


def test_merge_without_shards_is_loud(tmp_path):
    with pytest.raises(FileNotFoundError, match="trace"):
        obs.merge_trace_shards(tmp_path)


# --- crash flush (r15 satellite) ---------------------------------------------


def test_killed_run_flushes_partial_trace_and_rollup(traced, tmp_path):
    """A run killed mid-loop (hook raising — the same unwind SIGTERM's
    KeyboardInterrupt takes through utils/host) must leave a valid,
    parseable trace.json of the COMPLETED spans plus a partial phase
    rollup, instead of losing the whole observability record."""
    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.models.vqc import make_vqc_classifier
    from qfedx_tpu.run.metrics import ExperimentRun
    from qfedx_tpu.run.trainer import train_federated

    model = make_vqc_classifier(n_qubits=2, n_layers=1, num_classes=2)
    rng = np.random.default_rng(0)
    cx = rng.uniform(0, 1, (4, 8, 2)).astype(np.float32)
    cy = rng.integers(0, 2, (4, 8)).astype(np.int32)
    cm = np.ones((4, 8), dtype=np.float32)
    tx = rng.uniform(0, 1, (16, 2)).astype(np.float32)
    ty = rng.integers(0, 2, 16).astype(np.int32)
    cfg = FedConfig(local_epochs=1, batch_size=4, learning_rate=0.1)

    def die(r, m):
        if r >= 1:
            raise KeyboardInterrupt("SIGTERM")

    with pytest.raises(KeyboardInterrupt):
        with ExperimentRun(tmp_path, "crash", config=cfg) as run:
            train_federated(
                model, cfg, cx, cy, cm, tx, ty, num_rounds=5,
                on_round_end=die,
            )
    xs = _validate_chrome_trace(run.dir / "trace.json")
    assert any(e["name"] == "round.dispatch" for e in xs)
    summary = json.loads((run.dir / "summary.json").read_text())
    assert summary["partial"] is True
    assert summary["crashed"] == "KeyboardInterrupt"
    assert summary["phase_breakdown"]["round.dispatch"]["count"] >= 1
    # a clean finish() would have written the real summary; the partial
    # one never overwrites it
    (run.dir / "summary.json").write_text(json.dumps({"final": 1}))
    run.flush_partial_observability("again")
    assert json.loads((run.dir / "summary.json").read_text()) == {"final": 1}


# --- device-timeline profiling (r16 tentpole) --------------------------------

from qfedx_tpu.obs import profile as obs_profile  # noqa: E402


def _profile_fixture_events():
    """A small checked-in Perfetto/trace-event capture with known math:
    one device lane (hlo_op-tagged ops, one nested child), one host
    annotation lane (the QFEDX_TRACE_XLA bridge's mirror of
    ``round.dispatch``), and python-profiler noise the parser must
    ignore. Intervals in µs:

      matmul.1  [100, 1100)            top-level
      fusion.2  [1103, 2100)  gap 3    top-level
      child.4   [1200, 1300)           NESTED inside fusion.2
      fusion.2  [2110, 3100)  gap 10   top-level
    """
    dev = {"pid": 7, "tid": 70}
    return [
        {"ph": "M", "pid": 7, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "X", "name": "matmul.1", "ts": 100.0, "dur": 1000.0,
         "args": {"hlo_module": "jit_f", "hlo_op": "matmul.1"}, **dev},
        {"ph": "X", "name": "fusion.2", "ts": 1103.0, "dur": 997.0,
         "args": {"hlo_module": "jit_f", "hlo_op": "fusion.2"}, **dev},
        {"ph": "X", "name": "child.4", "ts": 1200.0, "dur": 100.0,
         "args": {"hlo_module": "jit_f", "hlo_op": "child.4"}, **dev},
        {"ph": "X", "name": "fusion.2", "ts": 2110.0, "dur": 990.0,
         "args": {"hlo_module": "jit_f", "hlo_op": "fusion.2"}, **dev},
        # the annotation lane: a host thread, no hlo_op args
        {"ph": "X", "name": "round.dispatch", "ts": 50.0, "dur": 3100.0,
         "pid": 7, "tid": 11},
        # python-profiler noise: not an op, not an annotation
        {"ph": "X", "name": "$profiler.py:91 start_trace", "ts": 0.0,
         "dur": 3200.0, "pid": 7, "tid": 11},
    ]


def test_profile_parse_op_census_total_and_self_time():
    parsed = obs_profile.parse_events(_profile_fixture_events())
    # executed SLOTS: the nested child folds into its parent — the
    # count shares one slot definition with the gap/busy census
    assert parsed["ops_executed"] == 3
    assert parsed["ops_distinct"] == 2  # matmul.1, fusion.2
    census = parsed["census"]
    assert set(census) == {"matmul", "fusion", "child"}
    assert census["matmul"] == {
        "count": 1, "total_us": 1000.0, "self_us": 1000.0
    }
    # the two fusion.2 instances group under one base name; the nested
    # child's 100 µs is subtracted from its parent's SELF time only
    assert census["fusion"]["count"] == 2
    assert census["fusion"]["total_us"] == pytest.approx(1987.0)
    assert census["fusion"]["self_us"] == pytest.approx(1887.0)
    assert census["child"]["self_us"] == pytest.approx(100.0)


def test_profile_parse_gaps_busy_and_lanes():
    parsed = obs_profile.parse_events(_profile_fixture_events())
    # top-level intervals only: busy 1000+997+990 over window [100,3100)
    assert parsed["device_lanes"] == 1
    assert parsed["busy_us"] == pytest.approx(2987.0)
    assert parsed["window_us"] == pytest.approx(3000.0)
    # gaps 3 and 10 µs — the nested child opens NO gap
    h = parsed["gap_hist"]
    assert h.count == 2
    assert parsed["gap_sum_us"] == pytest.approx(13.0)
    summary = obs_profile.summarize(parsed, static_state_ops=3, steps=1)
    # bounded-histogram quantiles: lower bucket edge, never above exact
    lo3, hi3 = obs.Histogram.bucket_bounds(3.0)
    assert summary["gap_p50_us"] == pytest.approx(lo3, abs=1e-3)
    assert lo3 <= 3.0 < hi3
    assert summary["gap_p95_us"] == pytest.approx(10.0, rel=0.11)
    assert summary["gap_p95_us"] <= 10.0 + 1e-6
    assert summary["gap_mean_us"] == pytest.approx(6.5)
    assert summary["device_busy_fraction"] == pytest.approx(
        2987.0 / 3000.0, abs=1e-3
    )
    # 3 top-level slots / 1 step vs a static census of 3: exact
    # agreement — ops x gap prices the floor over ONE slot definition
    assert summary["ops_per_step"] == 3.0
    assert summary["measured_vs_static"] == pytest.approx(1.0, abs=1e-3)
    assert summary["schema"] == obs_profile.PROFILE_SUMMARY_SCHEMA_VERSION


def test_profile_summary_fields_match_contract():
    """summarize() emits EXACTLY the SUMMARY_FIELDS keys — the schema
    the docs table and check_profile.py guard."""
    parsed = obs_profile.parse_events(_profile_fixture_events())
    summary = obs_profile.summarize(parsed)
    assert set(summary) == set(obs_profile.SUMMARY_FIELDS)
    # and with every optional input supplied, still the same keys
    summary = obs_profile.summarize(parsed, static_state_ops=9, steps=2)
    assert set(summary) == set(obs_profile.SUMMARY_FIELDS)


def test_profile_span_correlation_and_rollup_columns(traced):
    """Span correlation: the annotation range's device overlap becomes
    per-span device_busy_s/utilization, and phase_rollup rows carry
    the columns with device_busy_s <= wall and utilization in (0,1]."""
    with obs.span("round.dispatch", round=1):
        pass
    parsed = obs_profile.parse_events(
        _profile_fixture_events(), span_names={"round.dispatch"}
    )
    ann = parsed["annotations"]["round.dispatch"]
    assert ann["count"] == 1
    assert ann["wall_us"] == pytest.approx(3100.0)
    assert ann["busy_us"] == pytest.approx(2987.0)  # top-level overlap
    summary = obs_profile.summarize(parsed)
    row = summary["spans"]["round.dispatch"]
    assert row["device_busy_s"] == pytest.approx(2987e-6)
    assert row["utilization"] == pytest.approx(2987.0 / 3100.0, abs=1e-3)
    obs_profile.attach_span_device(summary)
    roll = obs.phase_rollup()["round.dispatch"]
    # the real registry span is ~µs long; the clamp keeps the invariant
    assert 0 < roll["device_busy_s"] <= roll["total_s"]
    assert 0 < roll["utilization"] <= 1.0


def test_profile_device_pid_fallback_detector():
    """Backends that drop hlo_op args: every X event on a device-named
    pid is an op event (the TPU-lane fallback)."""
    events = [
        {"ph": "M", "pid": 3, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "python"}},
        {"ph": "X", "name": "fusion.9", "ts": 0.0, "dur": 5.0,
         "pid": 3, "tid": 1},
        {"ph": "X", "name": "fusion.9", "ts": 9.0, "dur": 5.0,
         "pid": 3, "tid": 1},
        {"ph": "X", "name": "host_thing", "ts": 0.0, "dur": 50.0,
         "pid": 1, "tid": 1},
    ]
    parsed = obs_profile.parse_events(events)
    assert parsed["ops_executed"] == 2
    assert parsed["gap_hist"].count == 1  # one 4 µs gap, host ignored


def test_profile_merged_trace_aligns_device_lane(traced, tmp_path):
    """The merged Perfetto file: host spans and the device lane share
    one time origin — the k-th annotation of a name anchors to the k-th
    registry span of that name."""
    with obs.span("round.dispatch", round=1):
        pass
    sp = obs.registry().spans[-1]
    t0_rel_us = (sp.t0 - obs.registry().origin) * 1e6
    parsed = obs_profile.parse_events(
        _profile_fixture_events(), span_names={"round.dispatch"}
    )
    offset = obs_profile.align_offset_us(parsed)
    assert offset == pytest.approx(t0_rel_us - 50.0, abs=1e-3)
    path = obs_profile.write_merged_trace(tmp_path / "merged.json", parsed)
    obj = json.loads(path.read_text())
    xs = [e for e in obj["traceEvents"] if e.get("ph") == "X"]
    host = [e for e in xs if e["name"] == "round.dispatch"]
    dev = [e for e in xs if e["pid"] == 1000]
    # the device lane carries the 3 TOP-LEVEL scheduling slots; the
    # nested child is an op's internal decomposition, not a slot
    assert host and len(dev) == 3
    lanes = {
        e["pid"]: e["args"]["name"]
        for e in obj["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    assert lanes[1000] == "qfedx device"
    # shared origin: the fixture's first op starts 50 µs after the
    # annotation, i.e. at the registry span's t0 + 50 on the merged axis
    first_dev = min(e["ts"] for e in dev)
    assert first_dev == pytest.approx(t0_rel_us + 50.0, abs=1.0)


def test_profile_meta_anchor_fallback_alignment(tmp_path):
    """Without annotations the capture_meta.json start anchor rebases
    the lane (~ms accuracy) instead of leaving it unaligned."""
    obs.reset()
    events = [e for e in _profile_fixture_events()
              if e["name"] != "round.dispatch"]
    parsed = obs_profile.parse_events(events)
    parsed["capture_meta"] = {"start_rel_origin_us": 5000.0}
    offset = obs_profile.align_offset_us(parsed)
    assert offset == pytest.approx(5000.0 - parsed["t_min_us"])
    parsed2 = obs_profile.parse_events(events)
    assert obs_profile.align_offset_us(parsed2) is None  # neither anchor


def test_profile_pin_grammar(monkeypatch):
    monkeypatch.delenv("QFEDX_PROFILE", raising=False)
    assert obs_profile.profile_dir("/d") is None  # unset = off
    for v in ("0", "off"):
        monkeypatch.setenv("QFEDX_PROFILE", v)
        assert obs_profile.profile_dir("/d") is None
    for v in ("1", "on"):
        monkeypatch.setenv("QFEDX_PROFILE", v)
        assert obs_profile.profile_dir("/d") == "/d"
    monkeypatch.setenv("QFEDX_PROFILE", "./captures")
    assert obs_profile.profile_dir("/d") == "./captures"
    monkeypatch.setenv("QFEDX_PROFILE", "yes")
    with pytest.raises(ValueError, match="QFEDX_PROFILE"):
        obs_profile.profile_dir("/d")


def test_profile_capture_crash_safe_and_parseable(tmp_path):
    """A capture killed by an exception mid-region (the unwind SIGTERM
    takes through the utils/host translation) still stops the profiler
    session and leaves a PARSEABLE capture of the executed ops — the
    torn-capture failure mode of the bare jax.profiler.trace context
    this replaced."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(x):
        return jnp.sin(x) @ jnp.cos(x).T

    x = jnp.ones((64, 64))
    f(x).block_until_ready()  # compile outside the capture
    with pytest.raises(KeyboardInterrupt):
        with obs_profile.capture(tmp_path / "prof"):
            f(x).block_until_ready()
            raise KeyboardInterrupt("SIGTERM")
    parsed = obs_profile.parse_capture(tmp_path / "prof")
    assert parsed["ops_executed"] > 0
    assert parsed["capture_meta"]["start_rel_origin_us"] > 0
    # the one-call API parses the same capture and writes the artifact
    summary = obs_profile.write_profile_summary(
        tmp_path, capture_dir=tmp_path / "prof"
    )
    assert set(summary) == set(obs_profile.SUMMARY_FIELDS)
    assert json.loads(
        (tmp_path / "profile_summary.json").read_text()
    ) == summary
    # and a second capture works (the session was really stopped)
    with obs_profile.capture(tmp_path / "prof2"):
        f(x).block_until_ready()
    assert obs_profile.find_capture(tmp_path / "prof2") is not None


def test_profile_parse_without_capture_is_loud(tmp_path):
    with pytest.raises(FileNotFoundError, match="capture"):
        obs_profile.parse_capture(tmp_path)


@pytest.mark.slow
def test_profile_real_capture_end_to_end(traced, tmp_path, monkeypatch):
    """A real CPU capture around a real (tiny) federated round: the
    summary's fields exist, span correlation attributes device time to
    round.dispatch with utilization in (0,1], device_busy_s <= wall in
    the rollup, and the merged Perfetto file carries host + device
    lanes on one origin."""
    monkeypatch.setenv("QFEDX_TRACE_XLA", "1")
    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.models.vqc import make_vqc_classifier
    from qfedx_tpu.run.trainer import train_federated

    model = make_vqc_classifier(n_qubits=2, n_layers=1, num_classes=2)
    rng = np.random.default_rng(0)
    cx = rng.uniform(0, 1, (4, 8, 2)).astype(np.float32)
    cy = rng.integers(0, 2, (4, 8)).astype(np.int32)
    cm = np.ones((4, 8), dtype=np.float32)
    tx = rng.uniform(0, 1, (16, 2)).astype(np.float32)
    ty = rng.integers(0, 2, 16).astype(np.int32)
    cfg = FedConfig(local_epochs=1, batch_size=4, learning_rate=0.1)

    with obs_profile.capture(tmp_path / "prof"):
        train_federated(
            model, cfg, cx, cy, cm, tx, ty, num_rounds=2, pipeline_depth=0,
        )
    parsed = obs_profile.parse_capture(tmp_path / "prof")
    summary = obs_profile.summarize(parsed)
    assert set(summary) == set(obs_profile.SUMMARY_FIELDS)
    assert summary["ops_executed"] > 0
    assert summary["device_lanes"] >= 1
    assert summary["gap_count"] > 0
    # SOME phase carries real device time (which one depends on where
    # the async dispatch's execution lands — dispatch vs fetch vs eval)
    assert summary["spans"], "no annotation ranges correlated"
    for row in summary["spans"].values():
        assert 0 < row["utilization"] <= 1.0
    obs_profile.attach_span_device(summary)
    roll = obs.phase_rollup()
    attributed = [r for r in roll.values() if "device_busy_s" in r]
    assert attributed
    for r in attributed:
        assert 0 < r["device_busy_s"] <= r["total_s"] + 1e-9
        assert 0 < r["utilization"] <= 1.0
    path = obs_profile.write_merged_trace(tmp_path / "merged.json", parsed)
    obj = json.loads(path.read_text())
    pids = {e.get("pid") for e in obj["traceEvents"] if e.get("ph") == "X"}
    assert 1 in pids and 1000 in pids  # host spans + the device lane


@pytest.mark.slow
def test_profile_dense18q_measured_census_loose_pin(tmp_path):
    """The ISSUE r16 acceptance pin, LOOSE form (exact numbers are
    recorded in docs/PERF.md §16): a profiled dense18q step on this
    container yields a measured census comparable to the static
    obs/hlo.py census and a µs-scale per-op gap. On XLA:CPU the
    executed-thunk count runs BELOW the lowered census at this width
    (the backend's own fusion merges state passes — the §16 correction
    to the §15 census-÷-wall inference), so the band is wide on the low
    side; the agreement tightens to <10% at n=12 (also §16)."""
    import os
    import sys

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    from benchmarks._util import build_step, device_sync
    from qfedx_tpu.obs.hlo import lowered_state_ops

    fn, params, steps = build_step(18, 3, 16, 1)
    static = lowered_state_ops(fn, params, 18)
    assert static > 2000  # the ~3k state-op program §15 priced
    params, ls = fn(params)
    device_sync(ls)
    with obs_profile.capture(tmp_path / "prof"):
        params, ls = fn(params)
        device_sync(params)
    parsed = obs_profile.parse_capture(tmp_path / "prof")
    summary = obs_profile.summarize(
        parsed, static_state_ops=static, steps=steps
    )
    # loose: measured within [0.5, 1.1] of static (measured 0.61 on
    # this container, within 10% on-chip per the §15 model; PERF §16)
    assert 0.5 <= summary["measured_vs_static"] <= 1.1, summary
    # µs-scale per-op gap: the §15 band is 3–5 µs on-chip; this
    # container's CPU thunk gaps measured ~12 µs at this width (§16)
    assert 0.3 <= summary["gap_p50_us"] <= 50.0, summary
    assert summary["device_busy_fraction"] > 0.5


def test_fuse_counters_via_engine(traced, monkeypatch):
    """The fusion pass reports trace-time op counts when it runs."""
    import jax
    import jax.numpy as jnp

    monkeypatch.setenv("QFEDX_GATE_FORM", "flip")
    monkeypatch.setenv("QFEDX_SLAB_LANES", "matmul")
    monkeypatch.setenv("QFEDX_BATCHED", "1")
    monkeypatch.setenv("QFEDX_FUSE", "1")
    from qfedx_tpu.models.vqc import make_vqc_classifier

    model = make_vqc_classifier(n_qubits=12, n_layers=1, num_classes=2)
    params = model.init(jax.random.PRNGKey(0))
    x = jnp.zeros((2, 12), jnp.float32)
    jax.jit(model.apply).lower(params, x)  # trace only — no CPU compile
    counters = obs.registry().counters
    assert counters.get("fuse.passes", 0) >= 1
    assert counters["fuse.ops_out"] < counters["fuse.ops_in"]
    assert any(s.name == "engine.trace" for s in obs.registry().spans)
