"""Pipelined trainer ≡ sequential trainer (r09 tentpole).

The round loop is a software pipeline: at depth D, chunk k+1 is
dispatched (its params input is chunk k's device output — no host
round-trip) before chunk k's stats are drained with one batched fetch,
so host work overlaps device compute. The contract pinned here:

- depth 0 vs depth ≥ 1 is BIT-IDENTICAL — losses, accuracies, ε series,
  metrics.jsonl rows (modulo wall-clock fields), final params;
- buffer donation (QFEDX_DONATE, fed.round) changes no results — pinned
  the same way test_fold_clients pins the client fold;
- mid-run crash + resume through the ASYNC checkpoint writer lands on
  the uninterrupted trajectory;
- the hot loop performs no blocking fetch between issuing chunk k+1 and
  draining chunk k (instrumented via the obs registry: the k+1
  round.dispatch span opens before chunk k's round.fetch span), and
  depth 0 reproduces the sequential order.
"""

import json

import jax
import numpy as np
import pytest

from qfedx_tpu import obs
from qfedx_tpu.fed.config import DPConfig, FedConfig
from qfedx_tpu.models.vqc import make_vqc_classifier
from qfedx_tpu.run.checkpoint import Checkpointer
from qfedx_tpu.run.trainer import resolve_pipeline_depth, train_federated

_TIME_KEYS = ("time_s", "phases", "mem_bytes_in_use")


def _setup(seed=0, clients=4, samples=8, n_q=2):
    model = make_vqc_classifier(n_qubits=n_q, n_layers=1, num_classes=2)
    rng = np.random.default_rng(seed)
    cx = rng.uniform(0, 1, (clients, samples, n_q)).astype(np.float32)
    cy = rng.integers(0, 2, (clients, samples)).astype(np.int32)
    cm = np.ones((clients, samples), dtype=np.float32)
    tx = rng.uniform(0, 1, (16, n_q)).astype(np.float32)
    ty = rng.integers(0, 2, 16).astype(np.int32)
    return model, cx, cy, cm, tx, ty


def _strip_time(row):
    return {k: v for k, v in row.items() if k not in _TIME_KEYS}


def test_resolve_pipeline_depth_pin(monkeypatch):
    monkeypatch.delenv("QFEDX_PIPELINE", raising=False)
    assert resolve_pipeline_depth() == 1  # default on, double-buffering
    assert resolve_pipeline_depth(0) == 0  # explicit arg wins
    monkeypatch.setenv("QFEDX_PIPELINE", "3")
    assert resolve_pipeline_depth(0) == 0
    for env, want in (
        ("0", 0), ("off", 0), ("OFF", 0), ("1", 1), ("on", 1), ("ON", 1),
        ("2", 2),
    ):
        monkeypatch.setenv("QFEDX_PIPELINE", env)
        assert resolve_pipeline_depth() == want
    monkeypatch.setenv("QFEDX_PIPELINE", "fast")
    with pytest.raises(ValueError, match="QFEDX_PIPELINE"):
        resolve_pipeline_depth()
    with pytest.raises(ValueError, match="pipeline_depth"):
        resolve_pipeline_depth(-1)


def test_donate_pin_grammar(monkeypatch):
    """QFEDX_DONATE accepts the same 0/off/1/on grammar as its r09
    sibling pins and raises loudly on typos."""
    from qfedx_tpu.fed.round import donate_enabled

    for env, want in (("0", False), ("off", False), ("OFF", False),
                      ("1", True), ("on", True), ("ON", True)):
        monkeypatch.setenv("QFEDX_DONATE", env)
        assert donate_enabled() is want
    monkeypatch.setenv("QFEDX_DONATE", "yes")
    with pytest.raises(ValueError, match="QFEDX_DONATE"):
        donate_enabled()
    monkeypatch.delenv("QFEDX_DONATE")
    assert donate_enabled() is (jax.default_backend() != "cpu")


def test_depth_parity_scanned(tmp_path):
    """Depth 0 ≡ 1 ≡ 2 on the scanned in-scan-eval path: losses,
    accuracies, and the metrics.jsonl rows the run writes (wall-clock
    fields excluded — they are the thing the pipeline changes)."""
    from qfedx_tpu.run.metrics import ExperimentRun

    model, cx, cy, cm, tx, ty = _setup()
    cfg = FedConfig(
        local_epochs=1, batch_size=4, learning_rate=0.1, optimizer="adam"
    )
    out = {}
    for depth in (0, 1, 2):
        with ExperimentRun(tmp_path, f"d{depth}", config=cfg) as run:
            res = train_federated(
                model, cx=cx, cy=cy, cmask=cm, test_x=tx, test_y=ty,
                cfg=cfg, num_rounds=6, rounds_per_call=3, seed=7,
                pipeline_depth=depth, on_round_end=run.on_round_end,
            )
        rows = [
            json.loads(l)
            for l in (run.dir / "metrics.jsonl").read_text().splitlines()
        ]
        for row in rows:
            row.pop("ts", None)
        out[depth] = (res, [_strip_time(r) for r in rows])
    res0, rows0 = out[0]
    for depth in (1, 2):
        res_d, rows_d = out[depth]
        assert res_d.losses == res0.losses
        assert res_d.accuracies == res0.accuracies
        assert rows_d == rows0
        for a, b in zip(
            jax.tree.leaves(res_d.params), jax.tree.leaves(res0.params)
        ):
            assert np.array_equal(np.asarray(a), np.asarray(b))


def test_depth_parity_dp_secure_agg():
    """The full privacy composition (client-mode DP + ring secure-agg +
    client sampling) drains through the pipeline unchanged: ε series and
    params bit-equal at depth 0 vs 1."""
    model, cx, cy, cm, tx, ty = _setup(seed=2)
    cfg = FedConfig(
        local_epochs=1,
        batch_size=4,
        learning_rate=0.1,
        client_fraction=0.6,
        dp=DPConfig(clip_norm=0.5, noise_multiplier=0.5),
        secure_agg=True,
    )
    res = {
        depth: train_federated(
            model, cx=cx, cy=cy, cmask=cm, test_x=tx, test_y=ty, cfg=cfg,
            num_rounds=4, rounds_per_call=2, seed=11, pipeline_depth=depth,
        )
        for depth in (0, 1)
    }
    assert res[1].losses == res[0].losses
    assert res[1].epsilons == res[0].epsilons
    assert res[1].accuracies == res[0].accuracies
    for a, b in zip(
        jax.tree.leaves(res[1].params), jax.tree.leaves(res[0].params)
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_pipeline_crash_resume_matches_uninterrupted(tmp_path):
    """Crash mid-run under the pipelined loop + async checkpoint writer,
    then resume: the trainer must drain the queued round-3 write before
    propagating (the checkpoint the resume needs is durable), and the
    resumed trajectory lands bit-exactly on the uninterrupted depth-0
    run (same fold-in key derivation at any depth)."""
    model, cx, cy, cm, tx, ty = _setup(seed=3)
    cfg = FedConfig(
        local_epochs=1, batch_size=4, learning_rate=0.1, optimizer="adam"
    )
    ref = train_federated(
        model, cx=cx, cy=cy, cmask=cm, test_x=tx, test_y=ty, cfg=cfg,
        num_rounds=5, seed=11, pipeline_depth=0,
        checkpointer=Checkpointer(tmp_path / "ref", every=1),
    )

    class Crash(RuntimeError):
        pass

    ck = Checkpointer(tmp_path / "crash", every=1)

    def die_at_3(rnd, metrics):
        if rnd + 1 == 3:
            raise Crash()

    with pytest.raises(Crash):
        train_federated(
            model, cx=cx, cy=cy, cmask=cm, test_x=tx, test_y=ty, cfg=cfg,
            num_rounds=5, seed=11, pipeline_depth=1, checkpointer=ck,
            on_round_end=die_at_3,
        )
    # The async write of round 3 was queued before the hook raised; the
    # trainer's unwind path waits for it — durable before we get here.
    assert ck.latest_round() == 3

    res = train_federated(
        model, cx=cx, cy=cy, cmask=cm, test_x=tx, test_y=ty, cfg=cfg,
        num_rounds=5, seed=11, pipeline_depth=1, checkpointer=ck,
    )
    assert len(res.round_times_s) == 2  # only rounds 4-5 ran
    for got, want in zip(
        jax.tree.leaves(res.params), jax.tree.leaves(ref.params)
    ):
        assert np.array_equal(np.asarray(got), np.asarray(want))


def test_donation_parity(tmp_path, monkeypatch):
    """QFEDX_DONATE pinned 1 ≡ 0 (the fold-pin precedent): donation is a
    buffer-aliasing decision, never a math decision — including through
    a pipelined run with a mid-run checkpoint boundary, where the
    trainer must snapshot θ before the donating next dispatch consumes
    it."""
    model, cx, cy, cm, tx, ty = _setup(seed=4)
    cfg = FedConfig(
        local_epochs=1, batch_size=4, learning_rate=0.1, optimizer="adam"
    )
    results = {}
    for pin in ("1", "0"):
        monkeypatch.setenv("QFEDX_DONATE", pin)
        ck = Checkpointer(tmp_path / f"donate{pin}", every=2)
        results[pin] = train_federated(
            model, cx=cx, cy=cy, cmask=cm, test_x=tx, test_y=ty, cfg=cfg,
            num_rounds=4, rounds_per_call=2, seed=5, pipeline_depth=1,
            checkpointer=ck,
        )
        assert ck.latest_round() == 4
    assert results["1"].losses == results["0"].losses
    assert results["1"].accuracies == results["0"].accuracies
    for a, b in zip(
        jax.tree.leaves(results["1"].params),
        jax.tree.leaves(results["0"].params),
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_no_blocking_fetch_between_dispatch_and_drain(monkeypatch):
    """The pipeline contract, instrumented via the obs registry: at
    depth 1 the round.dispatch span of chunk k+1 OPENS before the
    round.fetch span of chunk k (no blocking fetch between issuing k+1
    and draining k); at depth 0 chunk k is fully drained before chunk
    k+1 is issued — the sequential loop, reproduced exactly."""
    monkeypatch.setenv("QFEDX_TRACE", "1")
    model, cx, cy, cm, tx, ty = _setup(seed=5)
    cfg = FedConfig(
        local_epochs=1, batch_size=4, learning_rate=0.1, optimizer="adam"
    )

    def spans_for(depth):
        obs.reset()
        train_federated(
            model, cx=cx, cy=cy, cmask=cm, test_x=tx, test_y=ty, cfg=cfg,
            num_rounds=6, rounds_per_call=3, seed=6, pipeline_depth=depth,
        )
        spans = obs.registry().spans
        disp = sorted(
            (s for s in spans if s.name == "round.dispatch"),
            key=lambda s: s.t0,
        )
        fetch = sorted(
            (s for s in spans if s.name == "round.fetch"), key=lambda s: s.t0
        )
        obs.reset()
        return disp, fetch

    disp, fetch = spans_for(depth=1)
    # Two 3-round chunks; spans carry the schema (first round + length).
    assert [s.meta["round"] for s in disp] == [1, 4]
    assert [s.meta["chunk"] for s in disp] == [3, 3]
    assert [s.meta["round"] for s in fetch] == [1, 4]
    # Chunk 2 issued strictly before chunk 1's drain fetch begins.
    assert disp[1].t0 < fetch[0].t0
    # Fetches drain in chunk order.
    assert fetch[0].t1 <= fetch[1].t0

    disp, fetch = spans_for(depth=0)
    assert [s.meta["round"] for s in disp] == [1, 4]
    # Sequential: chunk 1 fully drained before chunk 2 is dispatched.
    assert fetch[0].t1 <= disp[1].t0
