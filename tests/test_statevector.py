"""Statevector engine correctness vs. dense linear algebra ground truth."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from qfedx_tpu.ops import gates
from qfedx_tpu.ops.statevector import (
    apply_gate,
    apply_gate_2q,
    expect_z,
    expect_z_all,
    fidelity,
    probabilities,
    product_state,
    zero_state,
)


def dense_1q(gate: np.ndarray, qubit: int, n: int) -> np.ndarray:
    """Full 2^n × 2^n matrix for a 1-qubit gate (ground truth via kron)."""
    ops = [np.eye(2)] * n
    ops[qubit] = np.asarray(gate)
    out = ops[0]
    for m in ops[1:]:
        out = np.kron(out, m)
    return out


def rand_state(n, seed=0):
    rng = np.random.default_rng(seed)
    v = rng.normal(size=2**n) + 1j * rng.normal(size=2**n)
    v /= np.linalg.norm(v)
    return v.astype(np.complex64)


def test_rotation_gates_match_closed_form():
    theta = 0.7321
    np.testing.assert_allclose(
        np.asarray(gates.rx(theta)),
        np.cos(theta / 2) * np.eye(2) - 1j * np.sin(theta / 2) * np.array([[0, 1], [1, 0]]),
        atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(gates.ry(theta)),
        [[np.cos(theta / 2), -np.sin(theta / 2)], [np.sin(theta / 2), np.cos(theta / 2)]],
        atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(gates.rz(theta)),
        np.diag([np.exp(-0.5j * theta), np.exp(0.5j * theta)]),
        atol=1e-6,
    )


@pytest.mark.parametrize("name", ["X", "Y", "Z", "H", "S", "T"])
def test_fixed_gates_unitary(name):
    g = np.asarray(getattr(gates, name))
    np.testing.assert_allclose(g @ g.conj().T, np.eye(2), atol=1e-6)


def test_apply_gate_matches_dense():
    n = 4
    psi = rand_state(n, seed=1)
    state = jnp.asarray(psi).reshape((2,) * n)
    for q in range(n):
        got = apply_gate(state, gates.H, q).reshape(-1)
        want = dense_1q(np.asarray(gates.H), q, n) @ psi
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-5)


def test_apply_gate_2q_matches_dense_cnot():
    # CNOT on (control=0, target=1) for 3 qubits, big-endian axis order.
    n = 3
    psi = rand_state(n, seed=2)
    state = jnp.asarray(psi).reshape((2,) * n)
    got = apply_gate_2q(state, gates.CNOT, 0, 1).reshape(-1)
    cnot01 = np.zeros((8, 8))
    for i in range(8):
        b = [(i >> 2) & 1, (i >> 1) & 1, i & 1]
        if b[0] == 1:
            b[1] ^= 1
        j = (b[0] << 2) | (b[1] << 1) | b[2]
        cnot01[j, i] = 1.0
    np.testing.assert_allclose(np.asarray(got), cnot01 @ psi, atol=1e-5)


def test_apply_gate_2q_nonadjacent_and_reversed():
    n = 3
    psi = rand_state(n, seed=3)
    state = jnp.asarray(psi).reshape((2,) * n)
    # control=2, target=0
    got = apply_gate_2q(state, gates.CNOT, 2, 0).reshape(-1)
    mat = np.zeros((8, 8))
    for i in range(8):
        b = [(i >> 2) & 1, (i >> 1) & 1, i & 1]
        if b[2] == 1:
            b[0] ^= 1
        j = (b[0] << 2) | (b[1] << 1) | b[2]
        mat[j, i] = 1.0
    np.testing.assert_allclose(np.asarray(got), mat @ psi, atol=1e-5)


def test_zero_state_and_probabilities():
    s = zero_state(3)
    p = probabilities(s)
    assert p.shape == (8,)
    np.testing.assert_allclose(np.asarray(p), [1, 0, 0, 0, 0, 0, 0, 0], atol=1e-7)


def test_product_state_matches_sequential_gates():
    angles = jnp.array([0.3, 1.1, 2.0])
    amps = jnp.stack([jnp.cos(angles / 2), jnp.sin(angles / 2)], axis=-1)
    direct = product_state(amps.astype(jnp.complex64))
    seq = zero_state(3)
    for q in range(3):
        seq = apply_gate(seq, gates.ry(angles[q]), q)
    np.testing.assert_allclose(np.asarray(direct), np.asarray(seq), atol=1e-6)


def test_expect_z_values():
    s = zero_state(2)
    assert np.asarray(expect_z(s, 0)) == pytest.approx(1.0)
    s = apply_gate(s, gates.X, 1)
    assert np.asarray(expect_z(s, 1)) == pytest.approx(-1.0)
    s = apply_gate(s, gates.H, 0)
    assert np.asarray(expect_z(s, 0)) == pytest.approx(0.0, abs=1e-6)
    np.testing.assert_allclose(np.asarray(expect_z_all(s)), [0.0, -1.0], atol=1e-6)


def test_state_norm_preserved_through_circuit():
    state = zero_state(4)
    key = jax.random.PRNGKey(0)
    for q in range(4):
        state = apply_gate(state, gates.ry(jax.random.uniform(jax.random.fold_in(key, q))), q)
    for q in range(3):
        state = apply_gate_2q(state, gates.CNOT, q, q + 1)
    assert float(jnp.sum(probabilities(state))) == pytest.approx(1.0, abs=1e-5)


def test_fidelity_self_and_orthogonal():
    a = zero_state(2)
    b = apply_gate(zero_state(2), gates.X, 0)
    assert float(fidelity(a, a)) == pytest.approx(1.0, abs=1e-6)
    assert float(fidelity(a, b)) == pytest.approx(0.0, abs=1e-6)


def test_engine_jits_and_vmaps():
    def circuit(theta):
        s = zero_state(3)
        for q in range(3):
            s = apply_gate(s, gates.ry(theta[q]), q)
        s = apply_gate_2q(s, gates.CNOT, 0, 1)
        return expect_z(s, 1)

    thetas = jnp.array([[0.1, 0.2, 0.3], [1.0, 1.1, 1.2]])
    out = jax.jit(jax.vmap(circuit))(thetas)
    assert out.shape == (2,)
    single = circuit(thetas[0])
    np.testing.assert_allclose(np.asarray(out[0]), np.asarray(single), atol=1e-6)
