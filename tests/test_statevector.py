"""Statevector engine correctness vs. dense linear algebra ground truth.

The engine stores states as real (re, im) pairs (TPU has no complex dtype);
ground truth here is ordinary numpy complex linear algebra via kron.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from qfedx_tpu.ops import gates
from qfedx_tpu.ops.cpx import CArray, from_complex, to_complex
from qfedx_tpu.ops.statevector import (
    apply_gate,
    apply_gate_2q,
    expect_z,
    expect_z_all,
    fidelity,
    probabilities,
    product_state,
    zero_state,
)


def gate_matrix(g: CArray) -> np.ndarray:
    """CArray gate → dense complex matrix (4×4 for two-qubit tensors)."""
    m = to_complex(g)
    if m.ndim == 4:
        return m.reshape(4, 4)
    return m


def dense_1q(gate: np.ndarray, qubit: int, n: int) -> np.ndarray:
    """Full 2^n × 2^n matrix for a 1-qubit gate (ground truth via kron)."""
    ops = [np.eye(2)] * n
    ops[qubit] = np.asarray(gate)
    out = ops[0]
    for m in ops[1:]:
        out = np.kron(out, m)
    return out


def rand_state(n, seed=0):
    rng = np.random.default_rng(seed)
    v = rng.normal(size=2**n) + 1j * rng.normal(size=2**n)
    v /= np.linalg.norm(v)
    return v.astype(np.complex64)


def as_cstate(psi: np.ndarray, n: int) -> CArray:
    return from_complex(psi.reshape((2,) * n))


def test_rotation_gates_match_closed_form():
    theta = 0.7321
    np.testing.assert_allclose(
        gate_matrix(gates.rx(theta)),
        np.cos(theta / 2) * np.eye(2)
        - 1j * np.sin(theta / 2) * np.array([[0, 1], [1, 0]]),
        atol=1e-6,
    )
    np.testing.assert_allclose(
        gate_matrix(gates.ry(theta)),
        [[np.cos(theta / 2), -np.sin(theta / 2)], [np.sin(theta / 2), np.cos(theta / 2)]],
        atol=1e-6,
    )
    np.testing.assert_allclose(
        gate_matrix(gates.rz(theta)),
        np.diag([np.exp(-0.5j * theta), np.exp(0.5j * theta)]),
        atol=1e-6,
    )
    # real-only fast paths: ry is real, rx/rz are not
    assert gates.ry(theta).im is None
    assert gates.rx(theta).im is not None and gates.rz(theta).im is not None


@pytest.mark.parametrize("name", ["X", "Y", "Z", "H", "S", "T", "CNOT", "CZ", "SWAP"])
def test_fixed_gates_unitary(name):
    g = gate_matrix(getattr(gates, name))
    np.testing.assert_allclose(g @ g.conj().T, np.eye(g.shape[0]), atol=1e-6)


def test_crz_matches_dense():
    theta = 1.234
    got = gate_matrix(gates.crz(theta))
    want = np.diag([1, 1, np.exp(-0.5j * theta), np.exp(0.5j * theta)])
    np.testing.assert_allclose(got, want, atol=1e-6)


@pytest.mark.parametrize("gname", ["H", "Y", "S"])
def test_apply_gate_matches_dense(gname):
    n = 4
    psi = rand_state(n, seed=1)
    state = as_cstate(psi, n)
    g = getattr(gates, gname)
    for q in range(n):
        got = to_complex(apply_gate(state, g, q)).reshape(-1)
        want = dense_1q(gate_matrix(g), q, n) @ psi
        np.testing.assert_allclose(got, want, atol=1e-5)


def test_apply_rotation_to_real_state_stays_consistent():
    """Real state + complex gate exercises the mixed contraction path."""
    n = 3
    psi = np.zeros(8, dtype=np.complex64)
    psi[3] = 1.0
    state = CArray(jnp.asarray(psi.real.reshape(2, 2, 2)), None)
    got = to_complex(apply_gate(state, gates.rx(0.9), 1)).reshape(-1)
    want = dense_1q(gate_matrix(gates.rx(0.9)), 1, n) @ psi
    np.testing.assert_allclose(got, want, atol=1e-6)


def _cnot_dense(control: int, target: int, n: int) -> np.ndarray:
    dim = 2**n
    mat = np.zeros((dim, dim))
    for i in range(dim):
        bits = [(i >> (n - 1 - k)) & 1 for k in range(n)]
        if bits[control] == 1:
            bits[target] ^= 1
        j = sum(b << (n - 1 - k) for k, b in enumerate(bits))
        mat[j, i] = 1.0
    return mat


def test_apply_gate_2q_matches_dense_cnot():
    n = 3
    psi = rand_state(n, seed=2)
    state = as_cstate(psi, n)
    got = to_complex(apply_gate_2q(state, gates.CNOT, 0, 1)).reshape(-1)
    np.testing.assert_allclose(got, _cnot_dense(0, 1, n) @ psi, atol=1e-5)


def test_apply_gate_2q_nonadjacent_and_reversed():
    n = 3
    psi = rand_state(n, seed=3)
    state = as_cstate(psi, n)
    got = to_complex(apply_gate_2q(state, gates.CNOT, 2, 0)).reshape(-1)
    np.testing.assert_allclose(got, _cnot_dense(2, 0, n) @ psi, atol=1e-5)


def test_crz_2q_application_matches_dense():
    n = 3
    psi = rand_state(n, seed=4)
    state = as_cstate(psi, n)
    theta = 0.77
    got = to_complex(apply_gate_2q(state, gates.crz(theta), 1, 2)).reshape(-1)
    ops = np.kron(np.eye(2), gate_matrix(gates.crz(theta)))
    np.testing.assert_allclose(got, ops @ psi, atol=1e-5)


def test_zero_state_and_probabilities():
    s = zero_state(3)
    assert s.im is None  # real fast path
    p = probabilities(s)
    np.testing.assert_allclose(np.asarray(p), [1, 0, 0, 0, 0, 0, 0, 0], atol=1e-7)


def test_product_state_matches_sequential_gates():
    angles = jnp.array([0.3, 1.1, 2.0])
    amps = CArray(jnp.stack([jnp.cos(angles / 2), jnp.sin(angles / 2)], axis=-1), None)
    direct = product_state(amps)
    assert direct.im is None  # real stays real
    seq = zero_state(3)
    for q in range(3):
        seq = apply_gate(seq, gates.ry(angles[q]), q)
    np.testing.assert_allclose(to_complex(direct), to_complex(seq), atol=1e-6)


def test_product_state_complex_amps():
    """rx-encoded qubits are complex; product must match gate application."""
    angles = jnp.array([0.5, 1.3])
    seq = zero_state(2)
    for q in range(2):
        seq = apply_gate(seq, gates.rx(angles[q]), q)
    from qfedx_tpu.circuits.encoders import angle_amplitudes

    direct = product_state(angle_amplitudes(angles / jnp.pi * jnp.pi, "rx"))
    np.testing.assert_allclose(to_complex(direct), to_complex(seq), atol=1e-6)


def test_expect_z_values():
    s = zero_state(2)
    assert float(expect_z(s, 0)) == pytest.approx(1.0)
    s = apply_gate(s, gates.X, 1)
    assert float(expect_z(s, 1)) == pytest.approx(-1.0)
    s = apply_gate(s, gates.H, 0)
    assert float(expect_z(s, 0)) == pytest.approx(0.0, abs=1e-6)
    np.testing.assert_allclose(np.asarray(expect_z_all(s)), [0.0, -1.0], atol=1e-6)


def test_state_norm_preserved_through_circuit():
    state = zero_state(4)
    key = jax.random.PRNGKey(0)
    for q in range(4):
        state = apply_gate(state, gates.rx(jax.random.uniform(jax.random.fold_in(key, q))), q)
    for q in range(3):
        state = apply_gate_2q(state, gates.CNOT, q, q + 1)
    assert float(jnp.sum(probabilities(state))) == pytest.approx(1.0, abs=1e-5)


def test_fidelity_self_and_orthogonal():
    a = zero_state(2)
    b = apply_gate(zero_state(2), gates.X, 0)
    assert float(fidelity(a, a)) == pytest.approx(1.0, abs=1e-6)
    assert float(fidelity(a, b)) == pytest.approx(0.0, abs=1e-6)
    # phase-insensitive: global phase from rz must not change fidelity
    c = apply_gate(a, gates.rz(1.1), 0)
    assert float(fidelity(a, c)) == pytest.approx(
        float(np.abs(np.vdot(to_complex(a).reshape(-1), to_complex(c).reshape(-1))) ** 2),
        abs=1e-6,
    )


def test_engine_jits_and_vmaps():
    def circuit(theta):
        s = zero_state(3)
        for q in range(3):
            s = apply_gate(s, gates.ry(theta[q]), q)
        s = apply_gate_2q(s, gates.CNOT, 0, 1)
        return expect_z(s, 1)

    thetas = jnp.array([[0.1, 0.2, 0.3], [1.0, 1.1, 1.2]])
    out = jax.jit(jax.vmap(circuit))(thetas)
    assert out.shape == (2,)
    np.testing.assert_allclose(np.asarray(out[0]), np.asarray(circuit(thetas[0])), atol=1e-6)


def test_flat_rank_1q_dot_path_matches_tensor_path(monkeypatch):
    """1-qubit gates via the rank-3 reshaped dot view — the production
    CPU path at n ≥ _FLAT_RANK in the "dot" gate form — must match the
    (2,)*n tensordot form, values AND gradients, forced at small n by
    lowering the threshold."""
    import qfedx_tpu.ops.statevector as sv
    from qfedx_tpu.circuits.ansatz import hardware_efficient, init_ansatz_params
    from qfedx_tpu.circuits.encoders import angle_encode

    monkeypatch.setenv("QFEDX_GATE_FORM", "dot")
    n = 5
    params = init_ansatz_params(jax.random.PRNGKey(0), n, 2, scale=0.7)
    x = jnp.asarray(np.random.default_rng(0).uniform(0, 1, (n,)), jnp.float32)

    def loss(p):
        state = hardware_efficient(angle_encode(x), p)
        return jnp.sum(sv.expect_z_all(state) * jnp.arange(1.0, n + 1))

    want, g_tensor = loss(params), jax.grad(loss)(params)
    monkeypatch.setattr(sv, "_FLAT_RANK", 1)
    got, g_flat = loss(params), jax.grad(loss)(params)
    monkeypatch.setattr(sv, "_FLAT_RANK", 15)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)
    for k in g_flat:
        np.testing.assert_allclose(
            np.asarray(g_flat[k]), np.asarray(g_tensor[k]), atol=1e-5
        )


def test_flat_rank_2q_path_matches_tensor_path(monkeypatch):
    """General apply_gate_2q via the rank-5 reshaped view (_FLAT_RANK,
    the high-rank XLA-compile-wall workaround for non-CNOT 2q gates) must
    match the (2,)*n tensor form — forced at small n by lowering the
    threshold. Covers both qubit orders and a complex gate (CRZ)."""
    import qfedx_tpu.ops.statevector as sv

    n = 6
    rng = np.random.default_rng(3)
    state = from_complex(
        (rng.normal(size=(2,) * n) + 1j * rng.normal(size=(2,) * n)).astype(
            np.complex64
        )
    )
    g = gates.crz(0.83)
    for q1, q2 in ((1, 4), (4, 1), (0, 5)):
        want = to_complex(apply_gate_2q(state, g, q1, q2))
        monkeypatch.setattr(sv, "_FLAT_RANK", 1)
        got = to_complex(sv.apply_gate_2q(state, g, q1, q2))
        monkeypatch.setattr(sv, "_FLAT_RANK", 15)
        np.testing.assert_allclose(got, want, atol=1e-6)


# --- slab engine (n ≥ _SLAB_MIN: row/lane layout) -------------------------
#
# The production path for 10–20-qubit states: row-qubit gates as
# flip/select on leading axes, lane-qubit gates as (R,128)×(128,128)
# structured matmuls, CNOT in four row/lane cases, two-pass ⟨Z⟩ readout.
# n=10 (3 row bits, 7 lane bits) exercises every case against (a) numpy
# complex ground truth and (b) the independently-tested low-rank flip
# path with gradients. QFEDX_SLAB_LANES=matmul pins the TPU lane
# strategy (CPU auto-selects the cheap "flip" form — _lane_strategy).


@pytest.fixture
def slab_matmul_lanes(monkeypatch):
    # Pin the full TPU production configuration on the CPU test backend:
    # flip/slab gate form + MXU-style lane matmuls (see _gate_form /
    # _lane_strategy — CPU auto-selects the cheap "dot"/"flip" forms).
    monkeypatch.setenv("QFEDX_GATE_FORM", "flip")
    monkeypatch.setenv("QFEDX_SLAB_LANES", "matmul")


def test_slab_1q_gates_match_dense_oracle(slab_matmul_lanes):
    import qfedx_tpu.ops.statevector as sv

    n = 10
    assert n >= sv._SLAB_MIN  # the slab path is the one under test
    v = rand_state(n)
    state = as_cstate(v, n)
    for gname, q in [
        ("ry", 0), ("ry", 2), ("ry", 3), ("ry", 9),  # row + lane, real
        ("rz", 1), ("rz", 5),                        # complex diag
        ("rx", 2), ("rx", 7),                        # complex off-diag
    ]:
        g = gates.ROTATIONS[gname](0.6 + 0.1 * q)
        got = to_complex(apply_gate(state, g, q)).reshape(-1)
        want = dense_1q(gate_matrix(g), q, n) @ v
        np.testing.assert_allclose(got, want, atol=1e-5)
    # imag-only gate (Y) on a row and a lane qubit
    for q in (1, 8):
        got = to_complex(apply_gate(state, gates.Y, q)).reshape(-1)
        want = dense_1q(gate_matrix(gates.Y), q, n) @ v
        np.testing.assert_allclose(got, want, atol=1e-5)


def test_slab_cnot_all_four_cases_match_dense_oracle(slab_matmul_lanes):
    import qfedx_tpu.ops.statevector as sv
    from qfedx_tpu.ops.statevector import apply_cnot

    n = 10  # row bits: qubits 0-2, lane bits: qubits 3-9
    assert n >= sv._SLAB_MIN
    v = rand_state(n, seed=1)
    state = as_cstate(v, n)
    cases = [
        (0, 1),  # row ctrl → row tgt
        (2, 1),  # row-row, reversed order
        (1, 6),  # row ctrl → lane tgt
        (5, 2),  # lane ctrl → row tgt
        (4, 8),  # lane-lane
        (9, 3),  # lane-lane, reversed
        (9, 0),  # the ring's wrap link: lane ctrl → row tgt
    ]
    for c, t in cases:
        got = to_complex(apply_cnot(state, c, t)).reshape(-1)
        want = _cnot_dense(c, t, n) @ v
        np.testing.assert_allclose(got, want, atol=1e-5, err_msg=f"cnot {c}->{t}")


def test_slab_expect_z_all_matches_dense_oracle(slab_matmul_lanes):
    import qfedx_tpu.ops.statevector as sv

    n = 10
    assert n >= sv._SLAB_MIN
    v = rand_state(n, seed=2)
    state = as_cstate(v, n)
    got = np.asarray(sv.expect_z_all(state))
    probs = np.abs(v) ** 2
    idx = np.arange(2**n)
    want = np.array(
        [probs[(idx >> (n - 1 - q)) & 1 == 0].sum()
         - probs[(idx >> (n - 1 - q)) & 1 == 1].sum() for q in range(n)]
    )
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_slab_flip_lanes_matches_dense_oracle(monkeypatch):
    """The flip-form slab engine with the "flip" LANE strategy (the
    default for QFEDX_GATE_FORM=flip on a CPU backend — low-rank reverse
    views instead of 128×128 matmuls) against the numpy oracle: 1q gates
    on row+lane qubits and all four CNOT row/lane cases."""
    import qfedx_tpu.ops.statevector as sv
    from qfedx_tpu.ops.statevector import apply_cnot

    monkeypatch.setenv("QFEDX_GATE_FORM", "flip")
    monkeypatch.setenv("QFEDX_SLAB_LANES", "flip")
    n = 10
    assert n >= sv._SLAB_MIN
    v = rand_state(n, seed=5)
    state = as_cstate(v, n)
    for gname, q in [("ry", 1), ("rz", 2), ("rx", 5), ("rz", 9)]:
        g = gates.ROTATIONS[gname](0.4 + 0.2 * q)
        got = to_complex(apply_gate(state, g, q)).reshape(-1)
        want = dense_1q(gate_matrix(g), q, n) @ v
        np.testing.assert_allclose(got, want, atol=1e-5)
    for c, t in [(0, 1), (1, 6), (5, 2), (4, 8), (9, 0)]:
        got = to_complex(apply_cnot(state, c, t)).reshape(-1)
        want = _cnot_dense(c, t, n) @ v
        np.testing.assert_allclose(
            got, want, atol=1e-5, err_msg=f"cnot {c}->{t}"
        )


def test_slab_circuit_and_grads_match_low_rank_path(slab_matmul_lanes, monkeypatch):
    """Full HEA circuit (all four CNOT cases + complex rotations on row
    and lane qubits) + readout + jax.grad: slab engine vs the low-rank
    flip path, forced by moving _SLAB_MIN."""
    import qfedx_tpu.ops.statevector as sv
    from qfedx_tpu.circuits.ansatz import hardware_efficient, init_ansatz_params
    from qfedx_tpu.circuits.encoders import angle_encode

    n = 10
    params = init_ansatz_params(jax.random.PRNGKey(0), n, 2, scale=0.7)
    x = jnp.asarray(np.random.default_rng(0).uniform(0, 1, (n,)), jnp.float32)

    def loss(p):
        state = hardware_efficient(angle_encode(x), p)
        return jnp.sum(sv.expect_z_all(state) * jnp.arange(1.0, n + 1))

    assert n >= sv._SLAB_MIN
    want = loss(params)
    g_slab = jax.grad(loss)(params)
    monkeypatch.setattr(sv, "_SLAB_MIN", 99)  # force the low-rank path
    got = loss(params)
    g_low = jax.grad(loss)(params)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4)
    for k in g_slab:
        np.testing.assert_allclose(
            np.asarray(g_slab[k]), np.asarray(g_low[k]), atol=1e-4
        )
