"""HLO census regression: the fusion pass must shrink state-sized ops.

The census (qfedx_tpu/obs/hlo.py, factored out of
benchmarks/profile_step.py) counts lowered StableHLO ops that touch a
≥2^n-element tensor — one HBM pass / scheduling slot each, the quantity
the r07 fusion compiler exists to reduce (docs/PERF.md §12: 3089→2322
at n=16 on the chip). This pins the invariant at n=12 on CPU: lowering
only (fn.lower — backend-independent, cheap; the pathological XLA:CPU
compile of flip programs is never entered), TPU production routing
pinned via the env knobs.
"""

import os
import sys

import pytest

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from qfedx_tpu.obs.hlo import count_state_ops, module_counts  # noqa: E402

_TPU_ROUTING = {
    "QFEDX_GATE_FORM": "flip",
    "QFEDX_SLAB_LANES": "matmul",
    "QFEDX_BATCHED": "1",
}


def _state_ops(monkeypatch, fuse_pin: str, n=12, layers=2, batch=4,
               scan_pin: str = "off") -> dict:
    from benchmarks._util import build_step

    for k, v in _TPU_ROUTING.items():
        monkeypatch.setenv(k, v)
    monkeypatch.setenv("QFEDX_FUSE", fuse_pin)
    monkeypatch.setenv("QFEDX_SCAN_LAYERS", scan_pin)
    fn, params, _ = build_step(n, layers, batch, steps=1)
    return module_counts(fn, params, n, compiled=False)


def test_fused_fewer_state_ops_than_unfused(monkeypatch):
    fused = _state_ops(monkeypatch, "1")
    unfused = _state_ops(monkeypatch, "off")
    assert 0 < fused["lowered_state_ops"] < unfused["lowered_state_ops"], (
        f"fusion no longer reduces state-sized ops: "
        f"fused={fused['lowered_state_ops']} "
        f"unfused={unfused['lowered_state_ops']}"
    )
    # Raw totals are NOT the metric (fusion adds tiny composition ops);
    # the census must keep reporting both so nobody regresses to totals.
    assert fused["lowered_ops"] > fused["lowered_state_ops"]


# The scanned step's census budget at (n=12, L=2, B=4): measured 336 on
# this container (r17) vs 1939 r07-fused — the budget leaves slack for
# lowering drift but fails LONG before anything re-unrolls the layers
# (one extra per-layer copy of the body would blow past it).
_SCANNED_BUDGET = 600


def test_scanned_census_below_fused_and_budget(monkeypatch):
    """The r17 op-count collapse can't silently regress: the scanned
    step lowers STRICTLY below the r07-fused census and under an
    absolute budget (ISSUE r17 satellite)."""
    fused = _state_ops(monkeypatch, "1")
    scanned = _state_ops(monkeypatch, "1", scan_pin="1")
    assert (
        0
        < scanned["lowered_state_ops"]
        < fused["lowered_state_ops"]
    ), (
        f"scan no longer reduces state-sized ops: "
        f"scanned={scanned['lowered_state_ops']} "
        f"fused={fused['lowered_state_ops']}"
    )
    assert scanned["lowered_state_ops"] < _SCANNED_BUDGET, (
        f"scanned census {scanned['lowered_state_ops']} exceeds the "
        f"absolute budget {_SCANNED_BUDGET} — did the body grow or the "
        "layer stack partially unroll?"
    )


def test_scanned_census_depth_invariant(monkeypatch):
    """THE signature of scan-over-fused-layers: the lowered program
    contains the super-gate body ONCE, so the static census does not
    grow with layer count (the r07-fused census grows linearly). jax
    lowers the backward scan slightly differently for length ≤ 3, so
    the exact-equality pin sits in the asymptotic regime and shallow
    stacks are only required not to exceed it."""
    two = _state_ops(monkeypatch, "1", layers=2, scan_pin="1")
    four = _state_ops(monkeypatch, "1", layers=4, scan_pin="1")
    six = _state_ops(monkeypatch, "1", layers=6, scan_pin="1")
    assert four["lowered_state_ops"] == six["lowered_state_ops"]
    assert two["lowered_state_ops"] <= four["lowered_state_ops"]


def test_count_state_ops_scans_operands_and_results():
    # A scalar-result reduce still READS a state-sized operand; a
    # broadcast from a scalar still WRITES a state-sized result. Both
    # must count — plus small ops must not.
    txt = "\n".join(
        [
            '  %0 = stablehlo.reduce(%a) : (tensor<4096xf32>) -> tensor<f32>',
            '  %1 = stablehlo.broadcast_in_dim %s : (tensor<f32>)'
            ' -> tensor<2x4096xf32>',
            '  %2 = stablehlo.add %x, %y : tensor<16x128xf32>',
        ]
    )
    out = count_state_ops(txt, 1 << 12)
    assert out == {"lowered_ops": 3, "lowered_state_ops": 2}


def test_profile_step_reexports():
    # Back-compat: existing callers import the census from the script.
    from benchmarks import profile_step

    assert profile_step.count_state_ops is count_state_ops
    assert profile_step.module_counts is module_counts

# ---------------------------------------------------------------------------
# r19 Pallas scan-body kernel: the census guard goes cross-platform
# ---------------------------------------------------------------------------
#
# The pallas route only lowers for the TPU backend (interpret mode runs the
# kernel as a traced emulation, which the census would mis-count — the
# interpreter INFLATES state ops). jax.export targets the TPU lowering from
# this CPU container, so the guard measures the program the chip would
# actually run: the whole super-layer body collapses into tpu_custom_call
# slots and the scan carry-copy / xs-slice machinery around it disappears.
# Measured on this container (n=12, L=2, B=4): pallas 279 state ops, 2
# custom calls, 1 while loop vs scanned 336 / 0 / 3.


def _tpu_lowered_text(monkeypatch, pallas_pin: str, n=12, layers=2,
                      batch=4) -> str:
    from jax import export as jexport
    import jax

    from benchmarks._util import build_step
    from qfedx_tpu.ops import pallas_body

    for k, v in _TPU_ROUTING.items():
        monkeypatch.setenv(k, v)
    monkeypatch.setenv("QFEDX_FUSE", "1")
    monkeypatch.setenv("QFEDX_SCAN_LAYERS", "1")
    monkeypatch.setenv("QFEDX_PALLAS", pallas_pin)
    # jax.export lowers for the *target* platform; interpret mode would
    # substitute the traced emulation, so force the Mosaic path.
    monkeypatch.setattr(pallas_body, "_interpret_default", lambda: False)
    fn, params, _ = build_step(n, layers, batch, steps=1)
    return jexport.export(jax.jit(fn), platforms=["tpu"])(params).mlir_module()


def test_pallas_route_below_scanned_census_tpu(monkeypatch):
    """The kernel must EARN its place: the pallas route's TPU-lowered
    census at n=12 sits strictly below the r17 scanned census, the body
    occupies exactly two kernel slots (forward lives in the step's fwd
    and bwd residual passes; the adjoint sweep is the second), and the
    scan machinery shrinks (3 while loops -> 1: only the optimizer-step
    scan survives — the carry-copy/xs-slice loops around the body are
    gone)."""
    pallas_txt = _tpu_lowered_text(monkeypatch, "1")
    scanned_txt = _tpu_lowered_text(monkeypatch, "0")
    pallas = count_state_ops(pallas_txt, 1 << 12)
    scanned = count_state_ops(scanned_txt, 1 << 12)
    assert (
        0 < pallas["lowered_state_ops"] < scanned["lowered_state_ops"]
    ), (
        f"pallas route no longer beats the scanned census: "
        f"pallas={pallas['lowered_state_ops']} "
        f"scanned={scanned['lowered_state_ops']}"
    )
    assert scanned_txt.count("tpu_custom_call") == 0
    assert pallas_txt.count("tpu_custom_call") == 2, (
        "the super-layer body must lower as exactly two kernel launches "
        "(forward + adjoint); more means the body leaked back into "
        "per-op lowering, fewer means a route fell off the kernel"
    )
    assert (
        pallas_txt.count("stablehlo.while")
        < scanned_txt.count("stablehlo.while")
    ), "pallas route kept the scan carry machinery it exists to erase"
