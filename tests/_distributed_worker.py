"""Subprocess worker for the 2-process ``jax.distributed`` parity test.

Run as: ``python tests/_distributed_worker.py <coordinator> <nproc> <pid>
<out_path> [mode]``. Each process owns ONE XLA:CPU device; cross-process
CPU collectives use the gloo backend
(``jax_cpu_collectives_implementation`` — must be set before
``jax.distributed.initialize``). Initialization goes through
``parallel.mesh.distributed_init`` — the wrapper the multi-host story
ships — then one federated round runs over the 2-process global mesh
and process 0 writes the resulting parameters + stats for the parent to
compare against the single-process oracle.

``mode`` (default ``flat``): ``flat`` runs the one-program
``make_fed_round``; ``hier`` runs the r10 hierarchical round — a
4-client cohort in TWO waves of ``make_fed_round_partial`` (each wave's
psum crosses the process boundary via gloo), accumulated and applied by
``make_apply_partial`` — so cross-wave secure-agg mask cancellation is
exercised over REAL cross-process collectives, not just the virtual
mesh. ``dropout`` (r11) is ``hier`` plus a mid-round casualty decided
by the ``distributed.peer`` fault site: each process consults
``FaultPlan.check("distributed.peer", round, wave=peer)`` per peer
(deterministic — all controllers agree with zero communication) and a
firing peer's wave-0 client joins the survivor mask as dead. The rule
targets peer 1, so client 1 (process 1, wave 0) dies and the surviving
ring over {0, 2, 3} pairs client 0 with partners in the OTHER wave on
the OTHER process — dropout-resilient mask cancellation across both
the wave split and the process boundary. ``byzantine`` (r12) is
``hier`` with a ``client.byzantine`` ``scale:1000`` rule targeting
client 1 — hosted by PROCESS 1 in wave 0 — and the ``clip_mean``
defense on: every controller derives the same attack input from the
plan with zero communication (``byzantine_multipliers``), the attacked
upload is clipped inside the cross-process program, and the defended
aggregate must match the single-process flat round bit-for-tolerance.
``trace`` (r15) is ``flat`` under QFEDX_TRACE=1 with EVERY process
writing its obs registry as a trace shard
(``obs.write_trace_shard`` → ``trace.<process_index>.json`` in the
out_path DIRECTORY); the parent merges the shards with
``obs.merge_trace_shards`` and pins two process lanes with monotonic
nesting — the multi-process observability the process-local registry
could never show alone.
"""

import os
import sys


def main() -> None:
    coordinator, nproc, pid, out_path = sys.argv[1:5]
    mode = sys.argv[5] if len(sys.argv) > 5 else "flat"
    os.environ["JAX_PLATFORMS"] = "cpu"
    if mode == "trace":
        # Pinned BEFORE any qfedx import: spans must record from the
        # first host phase on both processes.
        os.environ["QFEDX_TRACE"] = "1"
    # The parent test env forces 8 virtual devices; this worker must own
    # exactly one device so the mesh spans the PROCESS boundary.
    os.environ.pop("XLA_FLAGS", None)

    import jax

    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    # distributed_init must run BEFORE the first backend touch, but
    # importing the qfedx_tpu package initializes the backend as a side
    # effect (ops.gates builds concrete gate constants at import time).
    # Load parallel/mesh.py directly — same code object, no package
    # __init__ — call distributed_init, THEN import the framework.
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "_qfedx_mesh", os.path.join(repo, "qfedx_tpu", "parallel", "mesh.py")
    )
    mesh_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mesh_mod)
    mesh_mod.distributed_init(
        coordinator_address=coordinator,
        num_processes=int(nproc),
        process_id=int(pid),
    )
    assert len(jax.devices()) == int(nproc), jax.devices()
    assert len(jax.local_devices()) == 1

    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.fed.round import make_fed_round
    from qfedx_tpu.models.vqc import make_vqc_classifier

    if mode == "stale":
        # r13: the staleness-discounted apply over REAL cross-process
        # collectives. QFEDX_STALE pins per-wave secure-agg pair graphs
        # at BUILD time (each wave's partial self-cancels — the
        # property that lets one wave arrive a round late), wave 1 is
        # treated as the straggler (age 1), and make_apply_partials
        # folds the mixed-age stack with the constant discount. The
        # parent compares against the identical computation on the
        # virtual single-process mesh.
        os.environ["QFEDX_STALE"] = "1"
        num_clients, samples, n_q = 4, 8, 3
        cfg = FedConfig(local_epochs=2, batch_size=4, learning_rate=0.1,
                        optimizer="sgd", secure_agg=True,
                        secure_agg_mode="ring")
    elif mode == "byzantine":
        # r12: same 2-wave hier shape, attacker on process 1, clip_mean
        # defense (composes with the cohort-wide ring graph — the
        # robust rules' per-wave graphs are pinned single-process in
        # tests/test_byzantine.py; here the thing under test is the
        # defense inside REAL cross-process collectives).
        num_clients, samples, n_q = 4, 8, 3
        cfg = FedConfig(local_epochs=2, batch_size=4, learning_rate=0.1,
                        optimizer="sgd", secure_agg=True,
                        secure_agg_mode="ring", aggregator="clip_mean",
                        clip_bound=0.5)
    elif mode in ("hier", "dropout"):
        # 4-client cohort split into 2 waves of 2 (one client per
        # process per wave); sgd keeps the wave-split comparison
        # float-tight (tests/test_hier.py's tolerance rationale), ring
        # SA makes cross-wave mask cancellation the thing under test.
        num_clients, samples, n_q = 4, 8, 3
        cfg = FedConfig(local_epochs=2, batch_size=4, learning_rate=0.1,
                        optimizer="sgd", secure_agg=True,
                        secure_agg_mode="ring")
    else:
        num_clients, samples, n_q = 2, 8, 3
        cfg = FedConfig(local_epochs=2, batch_size=4, learning_rate=0.1,
                        optimizer="adam")
    model = make_vqc_classifier(n_qubits=n_q, n_layers=2, num_classes=2)

    # Deterministic data/keys: every process builds identical host values
    # (the multi-controller contract), then materializes GLOBAL arrays —
    # client-sharded inputs span both processes' devices, so they must be
    # assembled shard-by-shard, not device_put from one host.
    rng = np.random.default_rng(0)
    cx = rng.uniform(0, 1, (num_clients, samples, n_q)).astype(np.float32)
    cy = rng.integers(0, 2, (num_clients, samples)).astype(np.int32)
    cm = np.ones((num_clients, samples), dtype=np.float32)

    mesh = Mesh(np.array(jax.devices()), ("clients",))

    def globalize(x, spec):
        return jax.make_array_from_callback(
            x.shape, NamedSharding(mesh, spec), lambda idx: x[idx]
        )

    params = jax.tree.map(
        lambda p: globalize(np.asarray(p), P()),
        model.init(jax.random.PRNGKey(0)),
    )
    key = globalize(np.asarray(jax.random.PRNGKey(42)), P())

    if mode in ("hier", "dropout", "byzantine", "stale"):
        from qfedx_tpu.fed.round import (
            make_accumulate_partial,
            make_apply_partial,
            make_apply_partials,
            make_fed_round_partial,
            stack_partials,
        )

        survivors = None
        byz = None
        if mode == "byzantine":
            # Every controller derives the SAME attack input from the
            # seeded plan — zero communication, like the dropout mode's
            # survivor agreement below. The attacker (client 1) lives
            # on process 1 in wave 0; its ×1000 upload is clipped
            # inside the cross-process program.
            from qfedx_tpu.utils.faults import FaultPlan

            plan = FaultPlan(seed=0, rules=[{
                "site": "client.byzantine", "kind": "scale:1000",
                "clients": [1],
            }])
            byz_np = plan.byzantine_attack(0, np.arange(num_clients))
            assert byz_np is not None and byz_np[1, 0] == 1000.0
            byz = globalize(byz_np, P())
        if mode == "dropout":
            # The distributed.peer fault site decides the casualty:
            # every process consults check(round=0, wave=peer) for each
            # peer — deterministic, so all controllers agree without
            # communication — and a firing peer's wave-0 client joins
            # the survivor mask as dead. The rule targets peer 1, whose
            # wave-0 client (id 1) then has surviving ring partners
            # only in the other wave / on the other process.
            from qfedx_tpu.utils.faults import FaultInjected, FaultPlan

            plan = FaultPlan(seed=0, rules=[{
                "site": "distributed.peer", "rounds": [0], "waves": [1],
            }])
            surv_np = np.ones(num_clients, dtype=np.float32)
            for peer in range(int(nproc)):
                try:
                    plan.check("distributed.peer", 0, wave=peer)
                except FaultInjected:
                    surv_np[peer] = 0.0  # peer's wave-0 client dies
            assert surv_np.tolist() == [1.0, 0.0, 1.0, 1.0]
            survivors = globalize(surv_np, P())

        wave = int(nproc)  # one client per process per wave
        partial_fn = make_fed_round_partial(
            model, cfg, mesh, wave_clients=wave, cohort_clients=num_clients
        )
        accum = make_accumulate_partial()
        acc = None
        parts = []
        for w in range(num_clients // wave):
            sl = slice(w * wave, (w + 1) * wave)
            wx = globalize(cx[sl], P("clients"))
            wy = globalize(cy[sl], P("clients"))
            wm = globalize(cm[sl], P("clients"))
            wb = globalize(np.asarray(w * wave, dtype=np.int32), P())
            part = partial_fn(params, wx, wy, wm, wb, key,
                              survivors=survivors, byzantine=byz)
            parts.append(part)
            acc = part if acc is None else accum(acc, part)
        if mode == "stale":
            # Wave 1 lands ONE ROUND LATE: the mixed-age discounted
            # apply runs over cross-process partials (per-wave pair
            # graphs — QFEDX_STALE was pinned before the build above).
            new_params, stats = make_apply_partials(cfg, num_clients)(
                params, stack_partials(parts),
                ages=np.array([0.0, 1.0], np.float32),
            )
        else:
            new_params, stats = make_apply_partial()(params, acc)
    else:
        scx = globalize(cx, P("clients"))
        scy = globalize(cy, P("clients"))
        scm = globalize(cm, P("clients"))

        round_fn = make_fed_round(model, cfg, mesh, num_clients=num_clients)
        if mode == "trace":
            from qfedx_tpu import obs

            # The host-phase span pair every traced round records
            # (round.dispatch encloses the enqueue, round.fetch the
            # blocking drain) — nested fed.trace.* spans ride inside
            # the dispatch's trace. Every process records its OWN
            # registry; every process writes its OWN shard.
            with obs.span("round.dispatch", round=1):
                new_params, stats = round_fn(params, scx, scy, scm, key)
            with obs.span("round.fetch", round=1):
                jax.block_until_ready((new_params, stats))
            os.makedirs(out_path, exist_ok=True)
            obs.write_trace_shard(out_path)
            print(f"worker {pid} done", flush=True)
            return
        new_params, stats = round_fn(params, scx, scy, scm, key)

    if int(pid) == 0:
        leaves = {
            f"leaf{i}": np.asarray(l)
            for i, l in enumerate(jax.tree.leaves(new_params))
        }
        leaves["mean_loss"] = np.asarray(stats.mean_loss)
        leaves["total_weight"] = np.asarray(stats.total_weight)
        leaves["num_participants"] = np.asarray(stats.num_participants)
        leaves["clipped_clients"] = np.asarray(stats.clipped_clients)
        np.savez(out_path, **leaves)
    print(f"worker {pid} done", flush=True)


if __name__ == "__main__":
    main()
