"""Pallas scan-body kernel (ops/pallas_body.py, r19) parity + routing.

Tier-1 is CPU-only, so correctness rides ``pallas_call`` interpret mode
(the kernel spec pins ``interpret=True`` off-TPU) at the same altitudes
as tests/test_scan_layers.py:

- pin: QFEDX_PALLAS grammar (loud on bad values), the fuse→scan→pallas
  gating chain, and ``route_ok``'s per-program shape gates — a False
  anywhere is the r17 lax.scan program unchanged, pinned by lowered-
  text IDENTITY (``=0`` ≡ unset, bit-for-bit);
- kinds: every kernel emission (lane/rowmat/mask/glane/growmat/rowperm/
  rowpair + all four CNOT placements) ≡ the scanned route's
  ``_exec_stacked`` executors on a directly-constructed program,
  logits AND coefficient gradients, dense and batched/grouped;
- model: QFEDX_PALLAS=1 ≡ =0 logits AND gradients for the HEA model on
  the batched engine and the client-folded path (f32 ≤ 2e-5, bf16
  rounding-bounded), circuit-level Kraus noise stays a scan barrier,
  and the serving cache keys on the pin (a flip compiles a SECOND
  route, never serves the stale program);
- chip: a slow-marked smoke asserting the zero-compiles-in-the-loop
  serving contract under the kernel route (skipped off-TPU — the
  on-chip half of the r19 evidence, BENCH_r06+).

All tests pin the TPU production formulation (flip gate form + matmul
lanes) so the kernel sees the real slab programs on the CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from qfedx_tpu.circuits import ansatz
from qfedx_tpu.ops import fuse
from qfedx_tpu.ops import pallas_body as pb
from qfedx_tpu.ops.cpx import CArray, from_complex

N = 10  # smallest slab width
R = 1 << (N - 7)


@pytest.fixture
def tpu_form(monkeypatch):
    monkeypatch.setenv("QFEDX_GATE_FORM", "flip")
    monkeypatch.setenv("QFEDX_SLAB_LANES", "matmul")
    monkeypatch.setenv("QFEDX_FUSE", "1")
    monkeypatch.setenv("QFEDX_SCAN_LAYERS", "1")


def _rand_state(n: int, seed: int = 0) -> CArray:
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(2,) * n) + 1j * rng.normal(size=(2,) * n)
    return from_complex(x / np.linalg.norm(x))


def _rand_state_b(n: int, b: int, seed: int = 0) -> CArray:
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(b, 1 << n)) + 1j * rng.normal(size=(b, 1 << n))
    x = x / np.linalg.norm(x, axis=1, keepdims=True)
    return CArray(
        jnp.asarray(x.real, jnp.float32), jnp.asarray(x.imag, jnp.float32)
    )


def _stacks(n, n_layers, seed=0):
    rng = np.random.default_rng(seed)
    rx = jnp.asarray(rng.uniform(-2, 2, (n_layers, n)), dtype=jnp.float32)
    rz = jnp.asarray(rng.uniform(-2, 2, (n_layers, n)), dtype=jnp.float32)
    return rx, rz


def _model(monkeypatch, encoding, n_layers=2, noise_model=None):
    from qfedx_tpu.models.vqc import make_vqc_classifier

    monkeypatch.setenv("QFEDX_BATCHED", "1")
    return make_vqc_classifier(
        n_qubits=N,
        n_layers=n_layers,
        num_classes=2,
        encoding=encoding,
        noise_model=noise_model,
    )


# --- the pin and the gating chain -------------------------------------------


def test_pallas_pin_rejects_invalid(monkeypatch):
    monkeypatch.setenv("QFEDX_PALLAS", "banana")
    with pytest.raises(ValueError, match="QFEDX_PALLAS"):
        pb.pallas_enabled()


@pytest.mark.parametrize(
    "pin,expect", [("1", True), ("on", True), ("0", False), ("off", False)]
)
def test_pallas_pin_values(monkeypatch, pin, expect):
    monkeypatch.setenv("QFEDX_PALLAS", pin)
    assert pb.pallas_enabled() is expect


def test_resolved_route_chain(monkeypatch):
    """The fuse→scan→pallas chain: each stage conjoined with the one
    below it — pallas can never report engaged without the scan route,
    nor scan without fuse (the kernel is built ON the stacked
    programs)."""
    monkeypatch.setenv("QFEDX_FUSE", "1")
    monkeypatch.setenv("QFEDX_SCAN_LAYERS", "1")
    monkeypatch.setenv("QFEDX_PALLAS", "1")
    assert pb.resolved_route() == {
        "fuse": True, "scan_layers": True, "pallas": True,
    }
    monkeypatch.setenv("QFEDX_SCAN_LAYERS", "0")
    route = pb.resolved_route()
    assert route["scan_layers"] is False and route["pallas"] is False
    monkeypatch.setenv("QFEDX_SCAN_LAYERS", "1")
    monkeypatch.setenv("QFEDX_FUSE", "0")
    assert pb.resolved_route() == {
        "fuse": False, "scan_layers": False, "pallas": False,
    }


def _lane_body(n_layers=2, groups=None, seed=0):
    rng = np.random.default_rng(seed)
    shape = (n_layers,) + (() if groups is None else (groups,)) + (128, 128)
    c = CArray(
        jnp.asarray(rng.normal(size=shape), jnp.float32),
        jnp.asarray(rng.normal(size=shape), jnp.float32),
    )
    return fuse.ScanProgram(
        (), (fuse.StackedOp("lane", (), c, True),), n_layers
    )


def test_route_ok_gates(monkeypatch, tpu_form):
    monkeypatch.setenv("QFEDX_PALLAS", "1")
    state = _rand_state(N)
    prog = _lane_body()
    assert pb.route_ok(state, N, prog, batched=False) is True
    # pin off / below the slab: the r17 program unchanged
    monkeypatch.setenv("QFEDX_PALLAS", "0")
    assert pb.route_ok(state, N, prog, batched=False) is False
    monkeypatch.setenv("QFEDX_PALLAS", "1")
    assert pb.route_ok(_rand_state(8), 8, prog, batched=False) is False
    # body kinds the kernel does not emit degrade, never break
    g1 = fuse.ScanProgram(
        (),
        (fuse.StackedOp(
            "g1", (0,),
            CArray(jnp.zeros((2, 2, 2)), None), True,
        ),),
        2,
    )
    assert pb.route_ok(state, N, g1, batched=False) is False
    # grouped coefficients must divide the state-block grid (G | B)
    bstate = _rand_state_b(N, 4)
    assert pb.route_ok(bstate, N, _lane_body(groups=2), True) is True
    assert pb.route_ok(bstate, N, _lane_body(groups=3), True) is False


def test_pallas_off_never_enters_kernel(monkeypatch, tpu_form):
    """QFEDX_PALLAS=0 (and unset, off-TPU) reproduces the r17 route
    bit-for-bit: the kernel entry is never called."""

    def boom(*a, **k):  # pragma: no cover - failure mode
        raise AssertionError("apply_scan_pallas called with pallas off")

    monkeypatch.setattr(pb, "apply_scan_pallas", boom)
    rx, rz = _stacks(N, 3)
    state = _rand_state(N)
    monkeypatch.setenv("QFEDX_PALLAS", "0")
    ansatz.hardware_efficient(state, {"rx": rx, "rz": rz})
    monkeypatch.delenv("QFEDX_PALLAS")
    ansatz.hardware_efficient(state, {"rx": rx, "rz": rz})


def test_pallas_off_lowered_text_identity(monkeypatch, tpu_form):
    """The =0 contract is IDENTITY, not parity: the lowered text of the
    scanned step with QFEDX_PALLAS=0 equals the unset lowering
    byte-for-byte, and =1 produces a different program (the kernel
    call)."""
    rx, rz = _stacks(N, 3)
    state = _rand_state(N)

    def lowered():
        def fn(rx, rz):
            out = ansatz.hardware_efficient(state, {"rx": rx, "rz": rz})
            return out.re
        return jax.jit(fn).lower(rx, rz).as_text()

    monkeypatch.delenv("QFEDX_PALLAS", raising=False)
    unset = lowered()
    monkeypatch.setenv("QFEDX_PALLAS", "0")
    assert lowered() == unset
    monkeypatch.setenv("QFEDX_PALLAS", "1")
    assert lowered() != unset


# --- kernel kinds vs the scanned executors ----------------------------------


def _kind_program(n_layers, groups=None, seed=11):
    """A stacked program exercising EVERY kernel emission — including
    the backend-gated kinds (rowperm, growmat) the CPU fusion pass
    never produces — with conditioned coefficients so f32 parity holds
    at absolute tolerance."""
    rng = np.random.default_rng(seed)
    g = () if groups is None else (groups,)

    def unitary(shape):
        # Haar-ish unitaries per leading index: the production coeffs
        # are unitary, so the state norm stays 1 and absolute parity
        # tolerances mean what they say.
        d = shape[-1]
        lead = shape[:-2]
        z = rng.normal(size=lead + (d, d)) + 1j * rng.normal(
            size=lead + (d, d)
        )
        q, r = np.linalg.qr(z)
        q = q * (np.diagonal(r, axis1=-2, axis2=-1)
                 / np.abs(np.diagonal(r, axis1=-2, axis2=-1)))[..., None, :]
        return CArray(
            jnp.asarray(q.real, jnp.float32), jnp.asarray(q.imag, jnp.float32)
        )

    def phases(shape):
        th = rng.uniform(-np.pi, np.pi, size=shape)
        return CArray(
            jnp.asarray(np.cos(th), jnp.float32),
            jnp.asarray(np.sin(th), jnp.float32),
        )

    L = n_layers
    perm = rng.permutation(R)
    body = (
        fuse.StackedOp("lane", (), unitary((L,) + g + (128, 128)), True),
        fuse.StackedOp("mask", (), phases((L,) + g + (1 << N,)), True),
        fuse.StackedOp("growmat", (8,), unitary((L,) + g + (2, R, R)), True),
        fuse.StackedOp(
            "rowpair", (0, 2),
            jax.tree.map(
                lambda x: x.reshape(x.shape[:-2] + (2, 2, 2, 2)),
                unitary((L,) + g + (4, 4)),
            ),
            True,
        ),
        fuse.StackedOp("rowperm", (), perm, False),
        fuse.StackedOp("glane", (1,), unitary((L,) + g + (2, 128, 128)), True),
        fuse.StackedOp("rowmat", (), unitary((L,) + g + (R, R)), True),
        fuse.StackedOp("cnot", (0, 1), None, False),   # row-row
        fuse.StackedOp("cnot", (5, 8), None, False),   # lane-lane
        fuse.StackedOp("cnot", (2, 9), None, False),   # row ctrl, lane tgt
        fuse.StackedOp("cnot", (9, 2), None, False),   # lane ctrl, row tgt
    )
    return fuse.ScanProgram((), body, L)


def _coeff_tree(program):
    return tuple(op.coeffs for op in program.body if op.stacked)


def _with_coeffs(program, coeffs):
    it = iter(coeffs)
    body = tuple(
        op._replace(coeffs=next(it)) if op.stacked else op
        for op in program.body
    )
    return program._replace(body=body)


@pytest.mark.parametrize("batched,groups", [
    # The dense arm of this matrix is covered by
    # test_dense_engine_parity_and_grads (same _emit per kind — only
    # the packing differs, and the HEA test drives dense packing);
    # keeping the kinds torture to the batched arms holds the tier-1
    # single-core budget.
    (True, None), (True, 2),
])
def test_kernel_kinds_parity_and_grads(monkeypatch, tpu_form,
                                       batched, groups):
    """Every kernel emission ≡ ``_exec_stacked``: one program through
    ``fuse.apply_scan`` under both pin values, outputs and coefficient
    COTANGENTS compared — the custom_vjp's adjoint-kernel state pass
    and the vjp-of-the-layer-body coefficient contraction both pinned
    against lax.scan's autodiff."""
    L = 3
    program = _kind_program(L, groups=groups)
    state = _rand_state_b(N, 4, seed=5) if batched else _rand_state(N, 5)
    rng = np.random.default_rng(6)
    w = jnp.asarray(
        rng.normal(size=(1 << N,)), jnp.float32
    ).reshape((1 << N,) if batched else (2,) * N)
    coeffs = _coeff_tree(program)

    def fwd(coeffs):
        out = fuse.apply_scan(
            state, N, _with_coeffs(program, coeffs), batched=batched
        )
        return out.re, out.im

    def loss(coeffs):
        re, im = fwd(coeffs)
        return jnp.sum(w * (re**2 + im**2))

    monkeypatch.setenv("QFEDX_PALLAS", "0")
    f0 = jax.tree.leaves(jax.jit(fwd)(coeffs))
    g0 = jax.tree.leaves(jax.jit(jax.grad(loss))(coeffs))
    monkeypatch.setenv("QFEDX_PALLAS", "1")
    f1 = jax.tree.leaves(jax.jit(fwd)(coeffs))
    g1 = jax.tree.leaves(jax.jit(jax.grad(loss))(coeffs))
    for a, b in zip(f0, f1):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5, rtol=0
        )
    for a, b in zip(g0, g1):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5, rtol=0
        )


# --- model-level parity (the tier-1 acceptance matrix) ----------------------


def test_dense_engine_parity_and_grads(monkeypatch, tpu_form):
    """Dense engine: HEA logits and angle gradients, pallas vs scanned
    (the natural CPU fusion body — rowmat + glane + wrap CNOT)."""
    rx, rz = _stacks(N, 3, seed=7)
    state = _rand_state(N, 3)
    rng = np.random.default_rng(8)
    w = jnp.asarray(rng.normal(size=(2,) * N), jnp.float32)

    def loss(rx, rz):
        out = ansatz.hardware_efficient(state, {"rx": rx, "rz": rz})
        return jnp.sum(w * (out.re**2 + out.im**2))

    monkeypatch.setenv("QFEDX_PALLAS", "0")
    l0 = jax.jit(loss)(rx, rz)
    g0 = jax.jit(jax.grad(loss, argnums=(0, 1)))(rx, rz)
    monkeypatch.setenv("QFEDX_PALLAS", "1")
    l1 = jax.jit(loss)(rx, rz)
    g1 = jax.jit(jax.grad(loss, argnums=(0, 1)))(rx, rz)
    np.testing.assert_allclose(
        np.asarray(l0), np.asarray(l1), atol=2e-5, rtol=0
    )
    for a, b in zip(g0, g1):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5, rtol=0
        )


def test_model_pallas_parity(monkeypatch, tpu_form):
    """Batched engine + client-folded path: QFEDX_PALLAS=1 ≡ =0 logits
    AND gradients through the real classifier (the same acceptance
    matrix r17 pinned for the scan route)."""
    import optax

    m = _model(monkeypatch, "angle")
    rng = np.random.default_rng(9)
    x = jnp.asarray(rng.uniform(0, 1, (2, N)), dtype=jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, (2,)), dtype=jnp.int32)
    params = m.init(jax.random.PRNGKey(0))

    monkeypatch.setenv("QFEDX_PALLAS", "1")
    a = m.apply(params, x)
    monkeypatch.setenv("QFEDX_PALLAS", "0")
    b = m.apply(params, x)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5, rtol=0)

    def loss(p):
        return optax.softmax_cross_entropy_with_integer_labels(
            m.apply(p, x), y
        ).mean()

    monkeypatch.setenv("QFEDX_PALLAS", "1")
    g1 = jax.grad(loss)(params)
    monkeypatch.setenv("QFEDX_PALLAS", "0")
    g0 = jax.grad(loss)(params)
    for u, v in zip(jax.tree.leaves(g1), jax.tree.leaves(g0)):
        np.testing.assert_allclose(
            np.asarray(u), np.asarray(v), atol=2e-5, rtol=0
        )

    # client-folded path: per-client stacks become kernel coeff GROUPS
    cparams = jax.tree.map(
        lambda p: p[None]
        * (1.0 + 0.1 * jnp.arange(2).reshape((2,) + (1,) * p.ndim)),
        params,
    )
    cx = jnp.stack([x, x * 0.9])
    monkeypatch.setenv("QFEDX_PALLAS", "1")
    fa = m.apply_clients(cparams, cx)
    monkeypatch.setenv("QFEDX_PALLAS", "0")
    fb = m.apply_clients(cparams, cx)
    np.testing.assert_allclose(
        np.asarray(fa), np.asarray(fb), atol=2e-5, rtol=0
    )


def test_model_pallas_parity_bf16(monkeypatch, tpu_form):
    monkeypatch.setenv("QFEDX_DTYPE", "bf16")
    m = _model(monkeypatch, "angle")
    rng = np.random.default_rng(10)
    x = jnp.asarray(rng.uniform(0, 1, (2, N)), dtype=jnp.float32)
    params = m.init(jax.random.PRNGKey(1))
    monkeypatch.setenv("QFEDX_PALLAS", "1")
    a = np.asarray(m.apply(params, x))
    monkeypatch.setenv("QFEDX_PALLAS", "0")
    b = np.asarray(m.apply(params, x))
    assert np.all(np.isfinite(a))
    np.testing.assert_allclose(a, b, atol=3e-2, rtol=0)


def test_noise_channels_stay_barriers(monkeypatch, tpu_form):
    """Circuit-level Kraus noise keeps the per-layer loop — a channel
    between layers is a scan barrier, so the kernel route (like the
    scan route before it) never sees it and trajectories coincide
    sample-for-sample on the SAME PRNG stream."""
    from qfedx_tpu.noise import NoiseModel

    nm = NoiseModel(depolarizing_p=0.1, circuit_level=True)
    m = _model(monkeypatch, "angle", n_layers=2, noise_model=nm)
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.uniform(0, 1, (2, N)), dtype=jnp.float32)
    params = m.init(jax.random.PRNGKey(2))
    key = jax.random.PRNGKey(3)
    monkeypatch.setenv("QFEDX_PALLAS", "1")
    a = np.asarray(m.apply_train(params, x, key))
    monkeypatch.setenv("QFEDX_PALLAS", "0")
    b = np.asarray(m.apply_train(params, x, key))
    np.testing.assert_allclose(a, b, atol=1e-5, rtol=0)


def test_persistent_forward_routes_on_pallas_pin(monkeypatch, tpu_form):
    """The serving cache keys on QFEDX_PALLAS: flipping the pin around
    one facade compiles a SECOND route instead of serving the stale
    program (serve/forward.py _ROUTING_PINS)."""
    from qfedx_tpu.serve.forward import cached_routes, persistent_forward

    m = _model(monkeypatch, "angle")
    params = m.init(jax.random.PRNGKey(4))
    x = jnp.zeros((2, N), dtype=jnp.float32)
    fwd = persistent_forward(m.apply)
    monkeypatch.setenv("QFEDX_PALLAS", "1")
    fwd(params, x)
    assert cached_routes(m.apply) == 1
    monkeypatch.setenv("QFEDX_PALLAS", "0")
    fwd(params, x)
    assert cached_routes(m.apply) == 2


# --- on-chip smoke (the BENCH_r06+ half of the r19 evidence) ----------------


@pytest.mark.slow
def test_serve_zero_compiles_under_kernel_route_on_chip(monkeypatch):
    """On the chip the kernel is the DEFAULT serving route; the r14
    zero-compiles-in-the-loop contract must hold under it — warmup
    absorbs the Mosaic compile, the loop re-dispatches the cached
    kernel program."""
    if jax.default_backend() != "tpu":
        pytest.skip("on-chip smoke: requires a TPU backend")
    from qfedx_tpu import obs
    from qfedx_tpu.serve.engine import ServeEngine
    from qfedx_tpu.serve.forward import persistent_forward

    monkeypatch.setenv("QFEDX_TRACE", "1")
    monkeypatch.setenv("QFEDX_PALLAS", "1")
    obs.reset()
    m = _model(monkeypatch, "angle")
    params = m.init(jax.random.PRNGKey(5))
    engine = ServeEngine(
        persistent_forward(m.apply), params, n_features=N, buckets=(1, 4)
    )
    warm = engine.warmup()
    assert warm["route_resolved"]["pallas"] is True

    def compile_total():
        return sum(
            v for k, v in obs.registry().counters.items()
            if k.startswith("compile.")
        )

    at_warmup = compile_total()
    assert at_warmup > 0
    rng = np.random.default_rng(12)
    for _ in range(8):
        engine.infer(jnp.asarray(
            rng.uniform(0, 1, (3, N)), dtype=jnp.float32
        ))
    assert compile_total() == at_warmup
