"""DP primitives and the RDP accountant (reference ROADMAP.md Phase 3)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from qfedx_tpu.fed.accountant import RDPAccountant, rdp_subsampled_gaussian, DEFAULT_ORDERS
from qfedx_tpu.fed.config import DPConfig
from qfedx_tpu.fed.privacy import clip_by_global_norm, privatize
from qfedx_tpu.utils import trees


def test_clip_noop_below_threshold():
    tree = {"a": jnp.array([0.3, 0.4])}  # norm 0.5
    out = clip_by_global_norm(tree, 1.0)
    np.testing.assert_allclose(np.asarray(out["a"]), [0.3, 0.4], atol=1e-7)


def test_clip_scales_to_norm():
    tree = {"a": jnp.array([3.0, 4.0])}  # norm 5
    out = clip_by_global_norm(tree, 1.0)
    assert float(trees.global_norm(out)) == pytest.approx(1.0, abs=1e-6)
    # direction preserved
    np.testing.assert_allclose(np.asarray(out["a"]), [0.6, 0.8], atol=1e-6)


def test_privatize_noise_scale():
    """Empirical noise std ≈ σ·C over many coordinates."""
    dp = DPConfig(clip_norm=0.5, noise_multiplier=2.0)
    tree = {"a": jnp.zeros(20000)}
    out = privatize(tree, dp, jax.random.PRNGKey(0))
    std = float(jnp.std(out["a"]))
    assert std == pytest.approx(1.0, rel=0.05)  # σC = 2·0.5


def test_rdp_full_batch_closed_form():
    orders = np.array([2, 4, 8])
    rdp = rdp_subsampled_gaussian(1.0, 2.0, orders)
    np.testing.assert_allclose(rdp, orders / (2 * 4.0), atol=1e-12)


def test_rdp_subsampling_amplifies():
    orders = DEFAULT_ORDERS
    full = rdp_subsampled_gaussian(1.0, 1.0, orders)
    sub = rdp_subsampled_gaussian(0.1, 1.0, orders)
    assert np.all(sub <= full + 1e-12)
    assert sub[0] < full[0] * 0.5  # strong amplification at small q


def test_accountant_epsilon_plausible():
    """ROADMAP.md:62: accountant returns plausible ε for given σ, q, T, δ.

    Reference regime: σ=1, q=1, T=30 rounds, δ=1e-5. Known ballpark for the
    Gaussian mechanism under 30-fold composition: ε in the tens.
    """
    acct = RDPAccountant()
    for _ in range(30):
        acct.step(q=1.0, sigma=1.0)
    eps = acct.epsilon(1e-5)
    assert 5.0 < eps < 60.0

    # More noise → less ε; subsampling → much less ε.
    acct2 = RDPAccountant()
    for _ in range(30):
        acct2.step(q=1.0, sigma=2.0)
    assert acct2.epsilon(1e-5) < eps

    acct3 = RDPAccountant()
    for _ in range(30):
        acct3.step(q=0.1, sigma=1.0)
    assert acct3.epsilon(1e-5) < acct2.epsilon(1e-5)


def test_accountant_monotone_in_rounds():
    acct = RDPAccountant()
    eps_seq = []
    for _ in range(5):
        acct.step(q=0.3, sigma=1.5)
        eps_seq.append(acct.epsilon(1e-5))
    assert all(b >= a for a, b in zip(eps_seq, eps_seq[1:]))


def test_accountant_rejects_bad_delta():
    acct = RDPAccountant()
    acct.step(1.0, 1.0)
    with pytest.raises(ValueError):
        acct.epsilon(0.0)


def test_sigma_zero_is_infinite():
    rdp = rdp_subsampled_gaussian(0.5, 0.0, np.array([2, 3]))
    assert np.all(np.isinf(rdp))


# --- per-example DP-SGD (BASELINE config 2; ROADMAP.md:50-58) ---------------


def _linear_model(n_features=4, num_classes=2):
    """Tiny linear classifier with hand-computable per-example gradients."""
    from qfedx_tpu.models.api import Model

    def init(key):
        return {"w": jnp.zeros((n_features, num_classes))}

    def apply(params, x):
        return x @ params["w"]

    return Model(init=init, apply=apply, wrap_delta=lambda d: d, name="lin")


def test_per_example_clip_bound_exact():
    """With σ=0 the DP-SGD batch gradient must equal the mean of the
    per-example gradients each clipped to C — verified against a
    hand-rolled oracle on a linear model."""
    import optax

    from qfedx_tpu.fed.client import _make_dp_example_grad
    from qfedx_tpu.fed.config import FedConfig

    clip = 0.05
    model = _linear_model()
    cfg = FedConfig(
        dp=DPConfig(clip_norm=clip, noise_multiplier=0.0, mode="example")
    )
    grad_fn = _make_dp_example_grad(model, cfg)

    rng = np.random.default_rng(0)
    b, f = 8, 4
    x = jnp.asarray(rng.normal(size=(b, f)) * 5.0, dtype=jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, b), dtype=jnp.int32)
    mask = jnp.asarray([1, 1, 1, 1, 1, 1, 0, 0], dtype=jnp.float32)
    params = {"w": jnp.asarray(rng.normal(size=(f, 2)) * 0.1, jnp.float32)}

    _, got = grad_fn(params, params, x, y, mask, jax.random.PRNGKey(0))

    def one_grad(xi, yi):
        g = jax.grad(
            lambda p: optax.softmax_cross_entropy_with_integer_labels(
                (xi[None] @ p["w"])[0], yi
            )
        )(params)
        norm = float(trees.global_norm(g))
        return jax.tree.map(lambda t: t * min(1.0, clip / norm), g)

    want = trees.tree_zeros_like(params)
    for i in range(b):
        if float(mask[i]) > 0:
            want = trees.tree_add(want, one_grad(x[i], y[i]))
    want = trees.tree_scale(want, 1.0 / b)  # lot size stays B under padding
    np.testing.assert_allclose(
        np.asarray(got["w"]), np.asarray(want["w"]), atol=1e-6
    )
    # Every surviving contribution is ≤ C/B in norm, so the bound holds.
    assert float(trees.global_norm(got)) <= clip * b / b + 1e-6


def test_per_example_noise_scale():
    """σ>0: noise std on the batch gradient is σ·C/B (lot-size normalized)."""
    from qfedx_tpu.fed.client import _make_dp_example_grad
    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.models.api import Model

    n = 20000
    model = Model(
        init=lambda k: {"w": jnp.zeros(n)},
        apply=lambda p, x: jnp.zeros((x.shape[0], 2)) + p["w"][:2],
        wrap_delta=lambda d: d,
        name="null",
    )
    sigma, clip, b = 2.0, 0.5, 4
    cfg = FedConfig(dp=DPConfig(clip_norm=clip, noise_multiplier=sigma,
                                mode="example"))
    grad_fn = _make_dp_example_grad(model, cfg)
    x = jnp.zeros((b, 3))
    y = jnp.zeros((b,), dtype=jnp.int32)
    mask = jnp.zeros((b,))  # zero signal: output is pure noise / B
    params = {"w": jnp.zeros(n)}
    _, g = grad_fn(params, params, x, y, mask, jax.random.PRNGKey(1))
    std = float(jnp.std(g["w"]))
    assert std == pytest.approx(sigma * clip / b, rel=0.05)


def test_example_mode_accountant_composition():
    """Per-local-step composition: E epochs × n_batches steps per round at
    q = p·B/S must give the same ε as the manual per-step loop."""
    sigma, q, rounds, epochs, n_batches = 1.2, 0.25, 6, 2, 3
    acct = RDPAccountant()
    for _ in range(rounds):
        acct.step(q=q, sigma=sigma, num_steps=epochs * n_batches)
    manual = RDPAccountant()
    for _ in range(rounds * epochs * n_batches):
        manual.step(q=q, sigma=sigma)
    assert acct.epsilon(1e-5) == pytest.approx(manual.epsilon(1e-5), rel=1e-9)
    # and it is strictly more spend than one client-level step per round
    client = RDPAccountant()
    for _ in range(rounds):
        client.step(q=1.0, sigma=sigma)
    assert acct.epsilon(1e-5) != client.epsilon(1e-5)


def test_spsa_rejects_example_mode():
    from qfedx_tpu.fed.config import FedConfig

    with pytest.raises(ValueError, match="spsa"):
        FedConfig(optimizer="spsa",
                  dp=DPConfig(mode="example"))


def test_example_mode_trains_above_chance_single_digit_eps():
    """Config-2-shaped run (DP-SGD, non-IID) learns above chance while the
    accountant reports single-digit ε — the BASELINE config 2 contract."""
    from qfedx_tpu.data.datasets import load_dataset
    from qfedx_tpu.data.partition import dirichlet_partition, pack_clients
    from qfedx_tpu.data.pipeline import preprocess
    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.models.vqc import make_vqc_classifier
    from qfedx_tpu.run.trainer import train_federated

    _, tr, te = load_dataset("mnist", synthetic_train=2560, synthetic_test=256,
                             seed=3)
    pre = preprocess(tr, te, classes=(0, 1), features="pca", n_features=4)
    parts = dirichlet_partition(pre.train[1], 4, alpha=2.0, seed=1)
    cx, cy, cmask = pack_clients(*pre.train, parts, pad_multiple=32)
    model = make_vqc_classifier(n_qubits=4, n_layers=2, num_classes=2)
    cfg = FedConfig(
        local_epochs=1, batch_size=32, learning_rate=0.15, optimizer="adam",
        dp=DPConfig(clip_norm=0.5, noise_multiplier=3.0, mode="example"),
    )
    # 16 rounds: at 8 rounds XLA:CPU (+ older jax) reductions leave this
    # noisy trajectory collapsed onto one class (0.459 for every seed —
    # the test-set class fraction) while it escapes by round 16 (0.918,
    # ε = 2.6, measured); the contract — learns above chance at
    # single-digit ε — is round-count-robust, so test where both
    # backends' trajectories have converged.
    res = train_federated(model, cfg, cx, cy, cmask, *pre.test,
                          num_rounds=16, seed=0, eval_every=16)
    assert res.final_accuracy > 0.7
    assert 0 < res.epsilons[-1] < 10.0
