"""DP primitives and the RDP accountant (reference ROADMAP.md Phase 3)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from qfedx_tpu.fed.accountant import RDPAccountant, rdp_subsampled_gaussian, DEFAULT_ORDERS
from qfedx_tpu.fed.config import DPConfig
from qfedx_tpu.fed.privacy import clip_by_global_norm, privatize
from qfedx_tpu.utils import trees


def test_clip_noop_below_threshold():
    tree = {"a": jnp.array([0.3, 0.4])}  # norm 0.5
    out = clip_by_global_norm(tree, 1.0)
    np.testing.assert_allclose(np.asarray(out["a"]), [0.3, 0.4], atol=1e-7)


def test_clip_scales_to_norm():
    tree = {"a": jnp.array([3.0, 4.0])}  # norm 5
    out = clip_by_global_norm(tree, 1.0)
    assert float(trees.global_norm(out)) == pytest.approx(1.0, abs=1e-6)
    # direction preserved
    np.testing.assert_allclose(np.asarray(out["a"]), [0.6, 0.8], atol=1e-6)


def test_privatize_noise_scale():
    """Empirical noise std ≈ σ·C over many coordinates."""
    dp = DPConfig(clip_norm=0.5, noise_multiplier=2.0)
    tree = {"a": jnp.zeros(20000)}
    out = privatize(tree, dp, jax.random.PRNGKey(0))
    std = float(jnp.std(out["a"]))
    assert std == pytest.approx(1.0, rel=0.05)  # σC = 2·0.5


def test_rdp_full_batch_closed_form():
    orders = np.array([2, 4, 8])
    rdp = rdp_subsampled_gaussian(1.0, 2.0, orders)
    np.testing.assert_allclose(rdp, orders / (2 * 4.0), atol=1e-12)


def test_rdp_subsampling_amplifies():
    orders = DEFAULT_ORDERS
    full = rdp_subsampled_gaussian(1.0, 1.0, orders)
    sub = rdp_subsampled_gaussian(0.1, 1.0, orders)
    assert np.all(sub <= full + 1e-12)
    assert sub[0] < full[0] * 0.5  # strong amplification at small q


def test_accountant_epsilon_plausible():
    """ROADMAP.md:62: accountant returns plausible ε for given σ, q, T, δ.

    Reference regime: σ=1, q=1, T=30 rounds, δ=1e-5. Known ballpark for the
    Gaussian mechanism under 30-fold composition: ε in the tens.
    """
    acct = RDPAccountant()
    for _ in range(30):
        acct.step(q=1.0, sigma=1.0)
    eps = acct.epsilon(1e-5)
    assert 5.0 < eps < 60.0

    # More noise → less ε; subsampling → much less ε.
    acct2 = RDPAccountant()
    for _ in range(30):
        acct2.step(q=1.0, sigma=2.0)
    assert acct2.epsilon(1e-5) < eps

    acct3 = RDPAccountant()
    for _ in range(30):
        acct3.step(q=0.1, sigma=1.0)
    assert acct3.epsilon(1e-5) < acct2.epsilon(1e-5)


def test_accountant_monotone_in_rounds():
    acct = RDPAccountant()
    eps_seq = []
    for _ in range(5):
        acct.step(q=0.3, sigma=1.5)
        eps_seq.append(acct.epsilon(1e-5))
    assert all(b >= a for a, b in zip(eps_seq, eps_seq[1:]))


def test_accountant_rejects_bad_delta():
    acct = RDPAccountant()
    acct.step(1.0, 1.0)
    with pytest.raises(ValueError):
        acct.epsilon(0.0)


def test_sigma_zero_is_infinite():
    rdp = rdp_subsampled_gaussian(0.5, 0.0, np.array([2, 3]))
    assert np.all(np.isinf(rdp))
