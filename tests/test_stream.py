"""Streamed ingestion + cohort sampling (r10): determinism, overlap,
trainer parity, resume.

Covers the host half of the unbounded-cohort tentpole:

- ``fed.sampling.CohortSampler`` — seeded, RESUMABLE per-round draws: a
  run resumed at round r must replay rounds r, r+1, … with identical
  cohorts (the test_run_io-style matrix below), because the draw is a
  pure function of (seed, round), never of sampler call history.
- ``data.stream`` — registries are deterministic per client id
  (wherever/whenever fetched), the wave uploader preserves order and
  content at every depth, propagates worker errors, and at depth ≥ 1
  genuinely overlaps: an ``ingest.h2d`` span from the uploader thread
  lands strictly INSIDE the round's ``round.dispatch`` span (the
  acceptance criterion's trace shape, pinned structurally via queue
  semantics — wave 2's upload cannot start before wave 0 is consumed,
  which happens inside the dispatch).
- ``run.trainer.train_federated_streamed`` — one-wave streaming over an
  ArrayRegistry is bit-identical to the resident ``train_federated`` on
  the same bytes; results are depth-invariant; crash/resume through the
  Checkpointer replays identically (sampler + key derivation both
  stateless in the round index).
"""

import jax
import numpy as np
import pytest

from qfedx_tpu import obs
from qfedx_tpu.data.stream import (
    ArrayRegistry,
    DroppedWave,
    StreamError,
    SyntheticRegistry,
    WaveStream,
    resolve_stream_depth,
)
from qfedx_tpu.fed.config import DPConfig, FedConfig
from qfedx_tpu.fed.round import client_mesh
from qfedx_tpu.fed.sampling import CohortSampler
from qfedx_tpu.models.vqc import make_vqc_classifier
from qfedx_tpu.run.trainer import train_federated, train_federated_streamed

N_Q = 3


def _data(C=16, S=4, seed=0):
    rng = np.random.default_rng(seed)
    cx = rng.uniform(0, 1, (C, S, N_Q)).astype(np.float32)
    cy = (cx.mean(axis=2) > 0.5).astype(np.int32)
    cm = np.ones((C, S), dtype=np.float32)
    return cx, cy, cm


def _model():
    return make_vqc_classifier(n_qubits=N_Q, n_layers=1, num_classes=2)


def _test_set(n=32, seed=9):
    rng = np.random.default_rng(seed)
    tx = rng.uniform(0, 1, (n, N_Q)).astype(np.float32)
    ty = (tx.mean(axis=1) > 0.5).astype(np.int32)
    return tx, ty


# --- CohortSampler ----------------------------------------------------------


@pytest.mark.parametrize(
    "registry_size,cohort_size",
    [(64, 16), (1000, 64), (1 << 20, 256), (32, 32)],
)
def test_sampler_resume_determinism(registry_size, cohort_size):
    """The determinism-across-resume matrix: a fresh sampler (as a
    resumed run would build) reproduces any round's cohort exactly; ids
    are unique, sorted, in-range; different rounds/seed differ."""
    s1 = CohortSampler(registry_size, cohort_size, seed=7)
    draws = [s1.round_ids(r) for r in range(6)]
    s2 = CohortSampler(registry_size, cohort_size, seed=7)
    for r in (5, 3, 0):  # out of order — resume never replays history
        np.testing.assert_array_equal(draws[r], s2.round_ids(r))
    for ids in draws:
        assert len(ids) == cohort_size
        assert len(np.unique(ids)) == cohort_size
        assert ids.min() >= 0 and ids.max() < registry_size
        assert np.all(np.diff(ids) > 0)  # sorted = cohort position order
    if cohort_size < registry_size:
        assert not np.array_equal(draws[0], draws[1])
        s3 = CohortSampler(registry_size, cohort_size, seed=8)
        assert not np.array_equal(draws[0], s3.round_ids(0))
    else:
        np.testing.assert_array_equal(draws[0], np.arange(registry_size))


def test_sampler_rejects_bad_shapes():
    with pytest.raises(ValueError):
        CohortSampler(8, 16)
    with pytest.raises(ValueError):
        CohortSampler(8, 0)
    with pytest.raises(ValueError):
        CohortSampler(8, 4).round_ids(-1)


# --- registries -------------------------------------------------------------


def test_synthetic_registry_deterministic_per_client():
    """A client's data is identical whichever batch it is fetched in —
    the property that makes 10⁶ simulated clients free AND resumable."""
    reg = SyntheticRegistry(1 << 20, samples=4, n_features=N_Q, seed=3)
    a = reg.batch(np.array([5, 999_999, 12]))
    b = reg.batch(np.array([999_999]))
    np.testing.assert_array_equal(a[0][1], b[0][0])
    np.testing.assert_array_equal(a[1][1], b[1][0])
    # different clients / seeds actually differ; features in [0, 1)
    assert not np.array_equal(a[0][0], a[0][2])
    c = SyntheticRegistry(1 << 20, samples=4, n_features=N_Q, seed=4).batch(
        np.array([5])
    )
    assert not np.array_equal(a[0][0], c[0][0])
    assert a[0].min() >= 0.0 and a[0].max() < 1.0
    with pytest.raises(ValueError):
        reg.batch(np.array([1 << 20]))


def test_array_registry_slices():
    cx, cy, cm = _data()
    reg = ArrayRegistry(cx, cy, cm)
    assert reg.num_clients == 16
    bx, by, bm = reg.batch(np.array([3, 0]))
    np.testing.assert_array_equal(bx[0], cx[3])
    np.testing.assert_array_equal(by[1], cy[0])
    np.testing.assert_array_equal(bm[0], cm[3])


# --- WaveStream -------------------------------------------------------------


@pytest.mark.parametrize("depth", [0, 1, 3])
def test_wave_stream_order_and_content(depth):
    cx, cy, cm = _data(C=16)
    reg = ArrayRegistry(cx, cy, cm)
    mesh = client_mesh(num_devices=4)
    ids = np.arange(16)
    stream = WaveStream(reg, mesh, ids, wave_size=4, depth=depth)
    seen = []
    for wave_base, (wx, wy, wm) in stream:
        seen.append(wave_base)
        np.testing.assert_array_equal(
            np.asarray(wx), cx[wave_base:wave_base + 4]
        )
        np.testing.assert_array_equal(
            np.asarray(wy), cy[wave_base:wave_base + 4]
        )
    assert seen == [0, 4, 8, 12]
    stream.close()  # idempotent on a consumed stream


def test_close_midstream_neither_stalls_nor_leaks_thread():
    """Early consumer exit (the trainer's finally-close on a mid-round
    error): close() must not deadlock against the uploader's terminal
    sentinel put on a full queue — the thread exits promptly instead of
    leaking with staged device buffers."""
    import time

    reg = ArrayRegistry(*_data(C=16))
    mesh = client_mesh(num_devices=4)
    stream = WaveStream(reg, mesh, np.arange(16), wave_size=4, depth=1)
    next(stream)  # uploader is now racing ahead of the consumer
    t0 = time.perf_counter()
    stream.close()
    assert time.perf_counter() - t0 < 2.0
    assert stream._thread is not None and not stream._thread.is_alive()


def test_wave_stream_validates_divisibility():
    reg = ArrayRegistry(*_data(C=16))
    mesh = client_mesh(num_devices=4)
    with pytest.raises(ValueError):
        WaveStream(reg, mesh, np.arange(16), wave_size=5)
    with pytest.raises(ValueError):  # wave not divisible by mesh axis
        WaveStream(reg, mesh, np.arange(16), wave_size=2)


def test_wave_stream_propagates_worker_errors():
    class Exploding:
        num_clients = 16

        def batch(self, ids):
            if ids[0] >= 8:
                raise RuntimeError("registry fetch failed")
            cx, cy, cm = _data(C=16)
            return cx[ids], cy[ids], cm[ids]

    mesh = client_mesh(num_devices=4)
    stream = WaveStream(Exploding(), mesh, np.arange(16), wave_size=4,
                        depth=1)
    got = [next(stream), next(stream)]
    assert [g[0] for g in got] == [0, 4]
    # A persistent failure surfaces as the TYPED StreamError (r11) with
    # the failing wave index and the root cause attached — and, being a
    # RuntimeError whose message embeds the original, pre-r11 callers
    # matching on that still work.
    with pytest.raises(StreamError, match="registry fetch failed") as ei:
        for _ in stream:
            pass
    assert ei.value.wave == 2
    assert isinstance(ei.value.original, RuntimeError)
    # close() after a failed uploader must not hang (r11 satellite)
    import time

    t0 = time.perf_counter()
    stream.close()
    assert time.perf_counter() - t0 < 2.0


def test_wave_stream_retries_transient_faults_in_place():
    """A fault-plan registry failure bounded by ``times: 1`` is
    recovered by the uploader's retry: every wave arrives, in order,
    with the right bytes — the consumer never learns anything failed."""
    from qfedx_tpu.utils.faults import FaultPlan

    cx, cy, cm = _data(C=16)
    reg = ArrayRegistry(cx, cy, cm)
    mesh = client_mesh(num_devices=4)
    plan = FaultPlan(seed=0, rules=[
        {"site": "registry.fetch", "waves": [1], "times": 1},
        {"site": "ingest.h2d", "waves": [2], "times": 1},
    ])
    stream = WaveStream(reg, mesh, np.arange(16), wave_size=4, depth=1,
                        fault_plan=plan, round_idx=0)
    seen = []
    for wave_base, (wx, wy, wm) in stream:
        seen.append(wave_base)
        np.testing.assert_array_equal(
            np.asarray(wx), cx[wave_base:wave_base + 4]
        )
    assert seen == [0, 4, 8, 12]
    # An UNBOUNDED rule (no times) exhausts the retry → StreamError.
    plan2 = FaultPlan(seed=0, rules=[
        {"site": "registry.fetch", "waves": [1]},
    ])
    stream2 = WaveStream(reg, mesh, np.arange(16), wave_size=4, depth=1,
                         fault_plan=plan2, round_idx=0)
    assert next(stream2)[0] == 0
    with pytest.raises(StreamError, match="injected fault") as ei:
        next(stream2)
    assert ei.value.wave == 1
    stream2.close()


def test_retry_exhaustion_converts_wave_to_dropped_marker():
    """r12 satellite, failure shape 1 (fails fast, persistently): with
    ``on_wave_error="drop"`` a wave whose fetch exhausts the retry
    arrives as a DroppedWave marker IN its cohort slot — the other
    waves' bytes are untouched and the stream neither stalls nor dies."""
    from qfedx_tpu.utils.faults import FaultPlan

    cx, cy, cm = _data(C=16)
    reg = ArrayRegistry(cx, cy, cm)
    mesh = client_mesh(num_devices=4)
    plan = FaultPlan(seed=0, rules=[
        {"site": "registry.fetch", "waves": [1]},  # no times = persistent
    ])
    for depth in (0, 1):
        stream = WaveStream(reg, mesh, np.arange(16), wave_size=4,
                            depth=depth, fault_plan=plan, round_idx=0,
                            on_wave_error="drop")
        got = list(stream)
        stream.close()
        assert len(got) == 4
        assert isinstance(got[1], DroppedWave)
        assert got[1].wave == 1 and got[1].wave_base == 4
        assert isinstance(got[1].error, StreamError)
        for item in (got[0], got[2], got[3]):
            wave_base, (wx, _wy, _wm) = item
            np.testing.assert_array_equal(
                np.asarray(wx), cx[wave_base:wave_base + 4]
            )
    with pytest.raises(ValueError, match="on_wave_error"):
        WaveStream(reg, mesh, np.arange(16), wave_size=4,
                   on_wave_error="retry")


def test_wave_deadline_converts_hung_fetch_no_hang():
    """r12 satellite, failure shape 2 (hangs, never fails): a wave
    whose fetch SLEEPS past ``wave_deadline_s`` converts into a
    DroppedWave promptly; when the uploader later unsticks and delivers
    the stale wave it is DISCARDED (never both dropped and computed)
    and the remaining waves flow normally. In "raise" mode the deadline
    is a prompt typed error instead of a silent stall."""
    import time

    cx, cy, cm = _data(C=16)

    class Hanging:
        num_clients = 16

        def batch(self, ids):
            if ids[0] == 4:  # wave 1 hangs well past the deadline
                time.sleep(2.0)
            return cx[ids], cy[ids], cm[ids]

    mesh = client_mesh(num_devices=4)
    # deadline 1.2 < the 2.0 s hang (wave 1 converts) but the uploader
    # unsticks INSIDE wave 2's window, so the stale wave-1 delivery is
    # discarded and waves 2/3 still flow.
    stream = WaveStream(Hanging(), mesh, np.arange(16), wave_size=4,
                        depth=1, on_wave_error="drop",
                        wave_deadline_s=1.2)
    t0 = time.perf_counter()
    got = list(stream)
    stream.close()
    assert time.perf_counter() - t0 < 6.0  # no-hang, bounded by sleeps
    dropped = [g for g in got if isinstance(g, DroppedWave)]
    served = [g for g in got if not isinstance(g, DroppedWave)]
    assert [d.wave for d in dropped] == [1]
    assert "deadline" in str(dropped[0].error)
    # every OTHER wave arrived exactly once with the right bytes
    assert sorted(g[0] for g in served) == [0, 8, 12]
    for wave_base, (wx, _wy, _wm) in served:
        np.testing.assert_array_equal(
            np.asarray(wx), cx[wave_base:wave_base + 4]
        )
    # raise mode: the deadline surfaces as a prompt typed error
    stream2 = WaveStream(Hanging(), mesh, np.arange(16), wave_size=4,
                         depth=1, wave_deadline_s=0.4)
    assert next(stream2)[0] == 0
    t0 = time.perf_counter()
    with pytest.raises(StreamError, match="deadline"):
        next(stream2)
    assert time.perf_counter() - t0 < 1.5
    stream2.close()


def test_trainer_converts_dead_wave_to_dropouts_with_mask_recovery():
    """The trainer-level pin (r12 satellite): a persistently failing
    wave becomes survivor-mask dropouts — the round COMPLETES, the
    casualties are accounted exactly, and under ring secure-agg the
    regenerated-mask correction holds: at lr=0 θ matches the fault-free
    run to float dust even though a whole wave's pair partners died."""
    from qfedx_tpu.utils.faults import FaultPlan

    cx, cy, cm = _data(seed=9)
    tx, ty = _test_set()
    model = _model()
    cfg = FedConfig(
        local_epochs=1, batch_size=4, learning_rate=0.0, momentum=0.0,
        secure_agg=True, secure_agg_mode="ring",
    )
    mesh = client_mesh(num_devices=4)
    reg = ArrayRegistry(cx, cy, cm)
    kw = dict(cohort_size=16, wave_size=4, num_rounds=1, seed=3,
              eval_every=3, mesh=mesh)
    clean = train_federated_streamed(model, cfg, reg, tx, ty, **kw)
    plan = FaultPlan(seed=0, rules=[{"site": "registry.fetch", "waves": [1]}])
    rows = []
    dead = train_federated_streamed(
        model, cfg, reg, tx, ty, fault_plan=plan,
        on_round_end=lambda r, m: rows.append(m), **kw,
    )
    assert rows[0]["dropped_clients"] == 4  # the whole wave, exactly
    assert rows[0]["dropped_waves"] == 1
    assert rows[0]["participants"] == 12
    for a, b in zip(
        jax.tree.leaves(clean.params), jax.tree.leaves(dead.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=0
        )
    # A plan-dropped client INSIDE the dead wave is still one casualty,
    # counted once: the wave's SAMPLED clients all drop (its wave never
    # dispatched, so the in-program counter cannot see any of them).
    plan_both = FaultPlan(seed=0, rules=[
        {"site": "registry.fetch", "waves": [1]},
        {"site": "client.compute", "kind": "drop", "clients": [5]},  # wave 1
    ])
    rows_b = []
    train_federated_streamed(
        model, cfg, reg, tx, ty, fault_plan=plan_both,
        on_round_end=lambda r, m: rows_b.append(m), **kw,
    )
    assert rows_b[0]["dropped_clients"] == 4
    assert rows_b[0]["participants"] == 12
    # EVERY wave dead ⇒ the round degrades to a logged skip, θ intact
    plan_all = FaultPlan(seed=0, rules=[{"site": "registry.fetch"}])
    rows_all = []
    res_all = train_federated_streamed(
        model, cfg, reg, tx, ty, fault_plan=plan_all,
        on_round_end=lambda r, m: rows_all.append(m), **kw,
    )
    assert rows_all[0].get("skipped") is True
    assert rows_all[0]["dropped_clients"] == 16
    assert rows_all[0]["participants"] == 0
    assert all(np.isfinite(np.ravel(np.asarray(l)))
               .all() for l in jax.tree.leaves(res_all.params))


def test_uploader_death_without_sentinel_raises_promptly():
    """The stranding bug (r11 satellite): an uploader that dies without
    queuing anything — simulated by a no-op thread body — must surface
    a StreamError within the liveness-poll window, not block forever."""
    import time

    reg = ArrayRegistry(*_data(C=16))
    mesh = client_mesh(num_devices=4)
    real_uploader = WaveStream._uploader
    WaveStream._uploader = lambda self: None
    try:
        stream = WaveStream(reg, mesh, np.arange(16), wave_size=4, depth=1)
    finally:
        WaveStream._uploader = real_uploader
    t0 = time.perf_counter()
    with pytest.raises(StreamError, match="uploader thread died"):
        next(stream)
    assert time.perf_counter() - t0 < 3.0
    stream.close()


def test_stream_depth_pin(monkeypatch):
    monkeypatch.delenv("QFEDX_STREAM", raising=False)
    assert resolve_stream_depth() == 1
    monkeypatch.setenv("QFEDX_STREAM", "off")
    assert resolve_stream_depth() == 0
    monkeypatch.setenv("QFEDX_STREAM", "3")
    assert resolve_stream_depth() == 3
    assert resolve_stream_depth(0) == 0  # explicit arg wins
    monkeypatch.setenv("QFEDX_STREAM", "fast")
    with pytest.raises(ValueError):
        resolve_stream_depth()
    with pytest.raises(ValueError):
        resolve_stream_depth(-1)


# --- streamed trainer -------------------------------------------------------


def test_streamed_one_wave_matches_resident_trainer():
    """Full-cohort single-wave streaming ≡ the resident trainer on the
    same packed arrays, bit-for-bit (same programs, same keys, same
    cohort order) — the depth-0/flat reproduction contract."""
    cx, cy, cm = _data()
    tx, ty = _test_set()
    model = _model()
    cfg = FedConfig(
        local_epochs=1, batch_size=4, learning_rate=0.1, optimizer="adam",
        client_fraction=0.5, secure_agg=True, secure_agg_mode="ring",
    )
    res_flat = train_federated(
        model, cfg, cx, cy, cm, tx, ty, num_rounds=2, seed=5, eval_every=1,
    )
    res_s = train_federated_streamed(
        model, cfg, ArrayRegistry(cx, cy, cm), tx, ty,
        cohort_size=16, num_rounds=2, seed=5, eval_every=1,
    )
    for a, b in zip(
        jax.tree.leaves(res_flat.params), jax.tree.leaves(res_s.params)
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    assert res_flat.losses == res_s.losses
    assert res_flat.accuracies == res_s.accuracies


def test_streamed_depth_invariance_and_wave_split():
    """Results are identical at any prefetch depth (streaming changes
    WHEN H2D happens, never what is computed), and a 4-wave split stays
    within the documented wave-split tolerance of the 1-wave result."""
    cx, cy, cm = _data(seed=2)
    tx, ty = _test_set()
    model = _model()
    cfg = FedConfig(
        local_epochs=1, batch_size=4, learning_rate=0.1, optimizer="sgd",
        secure_agg=True, secure_agg_mode="ring",
    )
    reg = ArrayRegistry(cx, cy, cm)
    mesh = client_mesh(num_devices=4)

    def run(wave_size, depth):
        return train_federated_streamed(
            model, cfg, reg, tx, ty, cohort_size=16, wave_size=wave_size,
            num_rounds=2, seed=3, eval_every=3, mesh=mesh,
            stream_depth=depth,
        )

    r_d0 = run(4, 0)
    r_d2 = run(4, 2)
    for a, b in zip(
        jax.tree.leaves(r_d0.params), jax.tree.leaves(r_d2.params)
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    r_whole = run(16, 1)
    for a, b in zip(
        jax.tree.leaves(r_whole.params), jax.tree.leaves(r_d0.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, rtol=0
        )
    # Hierarchical comm accounting: (W+1)·|θ| — more waves, more partial
    # uplinks; never C× client deltas.
    assert r_d0.comm_mb_per_round > r_whole.comm_mb_per_round
    assert r_d0.comm_mb_per_round == pytest.approx(
        r_whole.comm_mb_per_round * 5 / 2
    )


def test_streamed_resume_replays_identically(tmp_path):
    """Crash/resume determinism end-to-end: rounds 0..3 straight equal
    rounds 0..1 + restore + rounds 2..3 — cohort draws and round keys
    are both stateless in the round index."""
    from qfedx_tpu.run.checkpoint import Checkpointer

    cx, cy, cm = _data(seed=4)
    tx, ty = _test_set()
    model = _model()
    cfg = FedConfig(
        local_epochs=1, batch_size=4, learning_rate=0.1, optimizer="sgd",
    )
    reg = ArrayRegistry(cx, cy, cm)
    mesh = client_mesh(num_devices=4)
    kw = dict(
        cohort_size=8, wave_size=4, seed=11, eval_every=5, mesh=mesh,
    )
    straight = train_federated_streamed(
        model, cfg, reg, tx, ty, num_rounds=4, **kw
    )
    ck = Checkpointer(tmp_path / "ck", every=2)
    train_federated_streamed(
        model, cfg, reg, tx, ty, num_rounds=2, checkpointer=ck, **kw
    )
    resumed = train_federated_streamed(
        model, cfg, reg, tx, ty, num_rounds=4,
        checkpointer=Checkpointer(tmp_path / "ck", every=2), **kw
    )
    for a, b in zip(
        jax.tree.leaves(straight.params), jax.tree.leaves(resumed.params)
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_streamed_dp_accountant_sees_global_cohort():
    """Client-mode DP under registry sampling: the accountant's q is
    client_fraction · cohort/registry (cohort subsampling is real
    amplification over the registry population) — ε must come out LOWER
    than a cohort-equals-registry run of the same length."""
    cx, cy, cm = _data(C=32, seed=6)
    tx, ty = _test_set()
    model = _model()
    cfg = FedConfig(
        local_epochs=1, batch_size=4, learning_rate=0.1,
        client_fraction=0.5,
        dp=DPConfig(clip_norm=1.0, noise_multiplier=1.0),
    )
    reg = ArrayRegistry(cx, cy, cm)
    mesh = client_mesh(num_devices=4)
    sub = train_federated_streamed(
        model, cfg, reg, tx, ty, cohort_size=8, wave_size=8,
        num_rounds=2, seed=1, eval_every=3, mesh=mesh,
    )
    full = train_federated_streamed(
        model, cfg, reg, tx, ty, cohort_size=32, wave_size=8,
        num_rounds=2, seed=1, eval_every=3, mesh=mesh,
    )
    assert len(sub.epsilons) == len(full.epsilons) == 2
    assert sub.epsilons[-1] < full.epsilons[-1]


def test_streamed_hier_off_requires_single_wave(monkeypatch):
    cx, cy, cm = _data()
    tx, ty = _test_set()
    monkeypatch.setenv("QFEDX_HIER", "off")
    with pytest.raises(ValueError, match="QFEDX_HIER"):
        train_federated_streamed(
            _model(), FedConfig(local_epochs=1, batch_size=4),
            ArrayRegistry(cx, cy, cm), tx, ty,
            cohort_size=16, wave_size=4, num_rounds=1,
        )


def test_h2d_overlaps_dispatch_in_trace(monkeypatch):
    """The acceptance-criterion trace shape: with prefetch on, an
    ingest.h2d span recorded by the uploader thread STARTS inside the
    round.dispatch span. Deterministic via queue semantics at depth 1:
    wave 2's upload cannot begin until wave 0 is consumed (inside the
    dispatch), and must finish before wave 2 dispatches (also inside)."""
    monkeypatch.setenv("QFEDX_TRACE", "1")
    obs.reset()
    cx, cy, cm = _data()
    tx, ty = _test_set()
    model = _model()
    cfg = FedConfig(local_epochs=1, batch_size=4, learning_rate=0.1)
    train_federated_streamed(
        model, cfg, ArrayRegistry(cx, cy, cm), tx, ty,
        cohort_size=16, wave_size=4, num_rounds=1, seed=0, eval_every=2,
        mesh=client_mesh(num_devices=4), stream_depth=1,
    )
    spans = obs.registry().spans
    dispatch = [s for s in spans if s.name == "round.dispatch"]
    h2d = [s for s in spans if s.name == "ingest.h2d"]
    assert len(dispatch) == 1 and len(h2d) == 4
    assert {s.meta["wave"] for s in h2d} == {0, 1, 2, 3}
    assert all(s.tname == "qfedx-ingest" for s in h2d)
    d = dispatch[0]
    inside = [s for s in h2d if d.t0 < s.t0 < d.t1]
    assert inside, "no ingest.h2d span started inside round.dispatch"
    # queue depth gauge was exercised
    assert "ingest.queue_depth" in obs.registry().gauges


# --- buffer mode: straggler salvage (r13) -----------------------------------


def test_buffer_mode_declared_straggler_salvaged():
    """Deterministic injection path: a plan-delayed wave (delay >
    deadline) yields a LateWave marker immediately — no head-of-line
    blocking of the other waves — and poll_late hands the finished
    upload over with the right bytes, exactly once."""
    from qfedx_tpu.data.stream import LateWave
    from qfedx_tpu.utils.faults import FaultPlan

    cx, cy, cm = _data(C=16)
    reg = ArrayRegistry(cx, cy, cm)
    mesh = client_mesh(num_devices=4)
    plan = FaultPlan(seed=0, rules=[
        {"site": "wave.delay", "kind": "delay:0.4", "waves": [1]},
    ])
    stream = WaveStream(reg, mesh, np.arange(16), wave_size=4, depth=1,
                        fault_plan=plan, round_idx=0,
                        on_wave_error="buffer", wave_deadline_s=0.1)
    got = list(stream)
    assert len(got) == 4
    late = [g for g in got if isinstance(g, LateWave)]
    served = [g for g in got if not isinstance(g, LateWave)]
    assert [lw.wave for lw in late] == [1] and late[0].wave_base == 4
    assert sorted(g[0] for g in served) == [0, 8, 12]  # others prompt
    assert stream.late_pending()
    items, failed = stream.poll_late(timeout_s=10.0)
    assert failed == [] and len(items) == 1
    wave_base, (wx, _wy, _wm) = items[0]
    assert wave_base == 4
    np.testing.assert_array_equal(np.asarray(wx), cx[4:8])
    assert not stream.late_pending()
    # exactly once: a second poll returns nothing
    assert stream.poll_late() == ([], [])
    stream.close()


def test_buffer_mode_genuine_hang_salvaged_via_deadline():
    """Unplanned-slowness path: a registry fetch that HANGS past the
    consumer deadline converts into a LateWave (instead of r12's
    DroppedWave) and the unstuck upload is banked for poll_late — the
    straggler's work survives without any fault plan."""
    import time

    from qfedx_tpu.data.stream import LateWave

    cx, cy, cm = _data(C=16)

    class Hanging:
        num_clients = 16

        def batch(self, ids):
            if ids[0] == 4:
                time.sleep(0.8)
            return cx[ids], cy[ids], cm[ids]

    mesh = client_mesh(num_devices=4)
    stream = WaveStream(Hanging(), mesh, np.arange(16), wave_size=4,
                        depth=1, on_wave_error="buffer",
                        wave_deadline_s=0.25)
    got = list(stream)
    late = [g for g in got if isinstance(g, LateWave)]
    assert [lw.wave for lw in late] == [1]
    items, failed = stream.poll_late(timeout_s=10.0)
    assert failed == [] and [it[0] for it in items] == [4]
    np.testing.assert_array_equal(np.asarray(items[0][1][0]), cx[4:8])
    stream.close()


def test_buffer_mode_failed_wave_still_drops():
    """A wave that FAILS (retry exhausted) is a casualty even in buffer
    mode — there is nothing to finish in the background; and a
    straggler whose deferred upload then fails surfaces through
    poll_late's failed list, not as a silent hang."""
    from qfedx_tpu.utils.faults import FaultPlan

    reg = ArrayRegistry(*_data(C=16))
    mesh = client_mesh(num_devices=4)
    plan = FaultPlan(seed=0, rules=[
        {"site": "registry.fetch", "waves": [2]},  # persistent failure
    ])
    stream = WaveStream(reg, mesh, np.arange(16), wave_size=4, depth=1,
                        fault_plan=plan, round_idx=0,
                        on_wave_error="buffer", wave_deadline_s=5.0)
    got = list(stream)
    stream.close()
    dropped = [g for g in got if isinstance(g, DroppedWave)]
    assert [d.wave for d in dropped] == [2]
    # straggler + persistent failure => failed via poll_late
    plan2 = FaultPlan(seed=0, rules=[
        {"site": "wave.delay", "kind": "delay:0.3", "waves": [1]},
        {"site": "registry.fetch", "waves": [1]},
    ])
    stream2 = WaveStream(reg, mesh, np.arange(16), wave_size=4, depth=1,
                         fault_plan=plan2, round_idx=0,
                         on_wave_error="buffer", wave_deadline_s=0.1)
    list(stream2)
    items, failed = stream2.poll_late(timeout_s=15.0)
    assert items == [] and failed == [1]
    assert not stream2.late_pending()
    stream2.close()


# --- graceful shutdown (r13 satellite) --------------------------------------


def _shutdown_run(tmp_path, interrupt_round, num_rounds=4, kill=None):
    from qfedx_tpu.run.checkpoint import Checkpointer

    cx, cy, cm = _data(seed=4)
    tx, ty = _test_set()
    cfg = FedConfig(local_epochs=1, batch_size=4, learning_rate=0.1)
    reg = ArrayRegistry(cx, cy, cm)
    mesh = client_mesh(num_devices=4)
    kw = dict(cohort_size=16, wave_size=4, seed=11, eval_every=9, mesh=mesh)

    def hook(r, m):
        if interrupt_round is not None and r == interrupt_round:
            if kill is not None:
                kill()
            else:
                raise KeyboardInterrupt
    ck = Checkpointer(tmp_path / "ck", every=100)  # cadence never fires
    return train_federated_streamed(
        _model(), cfg, reg, tx, ty, num_rounds=num_rounds,
        checkpointer=ck, on_round_end=hook, **kw,
    )


def test_kill_the_consumer_drains_and_checkpoints(tmp_path):
    """Graceful shutdown: a KeyboardInterrupt mid-run (the Ctrl-C /
    orchestrator-kill shape) drains the wave uploader and async
    checkpoint writer, writes ONE final synchronous checkpoint, leaves
    no ingest thread behind, and a resumed run replays to the exact
    bytes of an uninterrupted one."""
    import threading

    import jax as _jax

    straight = _shutdown_run(tmp_path / "a", interrupt_round=None)
    with pytest.raises(KeyboardInterrupt):
        _shutdown_run(tmp_path / "b", interrupt_round=1)
    # no leaked uploader thread (the no-daemon-hang pin)
    assert not any(
        t.name == "qfedx-ingest" and t.is_alive()
        for t in threading.enumerate()
    )
    # the final synchronous checkpoint exists at the last COMPLETED round
    from qfedx_tpu.run.checkpoint import Checkpointer

    ck = Checkpointer(tmp_path / "b" / "ck", every=100)
    assert ck.latest_round() == 1
    ck.verify(1)
    resumed = _shutdown_run(tmp_path / "b", interrupt_round=None)
    for a, b in zip(
        _jax.tree.leaves(straight.params), _jax.tree.leaves(resumed.params)
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_sigterm_translates_to_graceful_interrupt(tmp_path):
    """An orchestrator's SIGTERM lands as KeyboardInterrupt("SIGTERM")
    and takes the same drain + final-checkpoint path."""
    import os
    import signal as signal_mod

    def kill():
        os.kill(os.getpid(), signal_mod.SIGTERM)

    with pytest.raises(KeyboardInterrupt, match="SIGTERM"):
        _shutdown_run(tmp_path, interrupt_round=1, kill=kill)
    from qfedx_tpu.run.checkpoint import Checkpointer

    assert Checkpointer(tmp_path / "ck", every=100).latest_round() == 1


def test_stale_late_marker_never_shifts_cohort_slots():
    """Review regression (r13): a genuinely-slow wave ahead of a
    plan-DECLARED straggler means the consumer's own deadline covers
    the declared wave before the uploader's queued LateWave marker
    arrives — the stale marker must be discarded (never re-yielded into
    a later wave's cohort slot, which would double-count the straggler
    and silently lose the final wave) and both stragglers' uploads must
    still salvage. Second shape: a declared marker left UNCONSUMED on
    the queue when iteration ends must not crash poll_late."""
    import time

    from qfedx_tpu.data.stream import LateWave
    from qfedx_tpu.utils.faults import FaultPlan

    cx, cy, cm = _data(C=16)

    class SlowWave0:
        num_clients = 16

        def batch(self, ids):
            if ids[0] == 0:
                time.sleep(0.5)  # genuine slowness, NOT plan-declared
            return cx[ids], cy[ids], cm[ids]

    mesh = client_mesh(num_devices=4)
    plan = FaultPlan(seed=0, rules=[
        {"site": "wave.delay", "kind": "delay:1.0", "waves": [1]},
    ])
    stream = WaveStream(SlowWave0(), mesh, np.arange(16), wave_size=4,
                        depth=1, fault_plan=plan, round_idx=0,
                        on_wave_error="buffer", wave_deadline_s=0.1)
    got = list(stream)
    assert len(got) == 4
    late = [g for g in got if isinstance(g, LateWave)]
    served = [g for g in got if not isinstance(g, LateWave)]
    # waves 0 (deadline) and 1 (declared) late EXACTLY ONCE each; waves
    # 2 and 3 served exactly once — no slot shift, no lost final wave
    assert sorted(lw.wave for lw in late) == [0, 1]
    assert sorted(g[0] for g in served) == [8, 12]
    items, failed = stream.poll_late(timeout_s=15.0)
    assert failed == [] and sorted(it[0] for it in items) == [0, 4]
    for lo, (wx, _wy, _wm) in items:
        np.testing.assert_array_equal(np.asarray(wx), cx[lo:lo + 4])
    stream.close()

    # shape 2: LAST wave declared late behind a genuinely slow wave —
    # its marker may still sit on the queue when iteration ends;
    # poll_late must classify it, not crash, and still salvage both.
    class SlowWave2:
        num_clients = 16

        def batch(self, ids):
            if ids[0] == 8:
                time.sleep(0.5)
            return cx[ids], cy[ids], cm[ids]

    plan2 = FaultPlan(seed=0, rules=[
        {"site": "wave.delay", "kind": "delay:1.0", "waves": [3]},
    ])
    stream2 = WaveStream(SlowWave2(), mesh, np.arange(16), wave_size=4,
                         depth=1, fault_plan=plan2, round_idx=0,
                         on_wave_error="buffer", wave_deadline_s=0.1)
    got2 = list(stream2)
    assert len(got2) == 4
    items2, failed2 = stream2.poll_late(timeout_s=15.0)
    assert failed2 == []
    banked = sorted(it[0] for it in items2)
    fresh = sorted(g[0] for g in got2 if not isinstance(g, LateWave))
    # every wave exactly once across fresh + salvaged, none doubled
    assert sorted(banked + fresh) == [0, 4, 8, 12]
    stream2.close()
