"""Staleness-aware buffered aggregation (r13): parity, cancellation,
accounting, mixed-age robustness.

The r13 tentpole lets a straggler wave contribute to a LATER round at a
staleness discount instead of dying (``QFEDX_STALE``, fed/round +
data/stream + run/trainer). These tests pin the contracts it stands on:

1. **Stale-off bit-exactness** — QFEDX_STALE off (the default) builds
   the r12 program exactly; stale ON with zero stragglers matches it
   bit-for-bit without secure-agg and to wave-split tolerance with it
   (per-wave pair graphs draw DIFFERENT masks, which must still cancel
   — the test_hier tolerance rationale).
2. **Self-cancelling stale waves** — under QFEDX_STALE every wave's
   ring masks pair only within the wave, so at lr=0 a SINGLE wave's
   partial is pure mask dust on its own (< 1e-5); without the pin the
   same partial carries unmatched cross-wave edges (the contrast that
   proves the test can detect the difference).
3. **ε-invariance under lateness** — the DP accountant charged the
   ORIGIN round at sampling time; folding the already-noised partial in
   later is post-processing, so injected delays change no ε.
4. **Mixed-age robust combines** — trimmed_mean/median run across a
   stack holding fresh AND stale wave partials.

Shapes are tiny (3 qubits, 1 layer, 16 clients) and injected delays are
fractions of a second: this file must stay cheap inside the tier-1
wall-clock budget.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from qfedx_tpu.data.stream import ArrayRegistry
from qfedx_tpu.fed.config import DPConfig, FedConfig
from qfedx_tpu.fed.round import (
    client_mesh,
    make_apply_partials,
    make_fed_round_partial,
    shard_client_data,
    stack_partials,
    stale_enabled,
)
from qfedx_tpu.fed.robust import staleness_discount
from qfedx_tpu.models.vqc import make_vqc_classifier
from qfedx_tpu.run.trainer import train_federated_streamed
from qfedx_tpu.utils.faults import FaultPlan

C, S, N_Q = 16, 4, 3


def _data(seed=0):
    rng = np.random.default_rng(seed)
    cx = rng.uniform(0, 1, (C, S, N_Q)).astype(np.float32)
    cy = (cx.mean(axis=2) > 0.5).astype(np.int32)
    cm = np.ones((C, S), dtype=np.float32)
    return cx, cy, cm


def _model():
    return make_vqc_classifier(n_qubits=N_Q, n_layers=1, num_classes=2)


def _test_set(n=32, seed=9):
    rng = np.random.default_rng(seed)
    tx = rng.uniform(0, 1, (n, N_Q)).astype(np.float32)
    ty = (tx.mean(axis=1) > 0.5).astype(np.int32)
    return tx, ty


_STRAGGLER_PLAN = [
    # Declared up front (delay ≫ deadline) so the injection is
    # deterministic: exactly wave 1 goes late, exactly at round 1.
    {"site": "wave.delay", "kind": "delay:0.5", "rounds": [1],
     "waves": [1]},
]


def _run_streamed(cfg, stale_env, monkeypatch, plan=None, num_rounds=2,
                  rows=None, **kw):
    monkeypatch.setenv("QFEDX_STALE", "1" if stale_env else "0")
    cx, cy, cm = _data(seed=7)
    tx, ty = _test_set()
    args = dict(
        cohort_size=C, wave_size=4, num_rounds=num_rounds, seed=3,
        eval_every=num_rounds + 1, mesh=client_mesh(num_devices=4),
        fault_plan=plan,
    )
    args.update(kw)
    return train_federated_streamed(
        _model(), cfg, ArrayRegistry(cx, cy, cm), tx, ty,
        on_round_end=(
            None if rows is None else (lambda r, m: rows.append(m))
        ),
        **args,
    )


def test_stale_pin_parses(monkeypatch):
    monkeypatch.delenv("QFEDX_STALE", raising=False)
    assert stale_enabled() is False  # default OFF — the house invariant
    monkeypatch.setenv("QFEDX_STALE", "on")
    assert stale_enabled() is True
    monkeypatch.setenv("QFEDX_STALE", "sometimes")
    with pytest.raises(ValueError):
        stale_enabled()


def test_staleness_config_validation():
    FedConfig(staleness_mode="poly", staleness_alpha=2.0)
    with pytest.raises(ValueError, match="staleness_mode"):
        FedConfig(staleness_mode="linear")
    with pytest.raises(ValueError, match="staleness_alpha"):
        FedConfig(staleness_mode="constant", staleness_alpha=0.0)
    with pytest.raises(ValueError, match="staleness_alpha"):
        FedConfig(staleness_mode="constant", staleness_alpha=1.5)
    with pytest.raises(ValueError, match="staleness_max_age"):
        FedConfig(staleness_max_age=0)


def test_staleness_discount_shapes():
    ages = np.array([0.0, 1.0, 3.0], np.float32)
    c = np.asarray(staleness_discount("constant", 0.25, ages))
    np.testing.assert_allclose(c, [1.0, 0.25, 0.25])
    p = np.asarray(staleness_discount("poly", 1.0, ages))
    np.testing.assert_allclose(p, [1.0, 0.5, 0.25])
    # s(0) = 1 EXACTLY in both families — fresh waves cost nothing.
    assert c[0] == 1.0 and p[0] == 1.0
    with pytest.raises(ValueError):
        staleness_discount("linear", 1.0, ages)


# --- 1: the stale-off parity matrix -----------------------------------------

MATRIX = [
    # (label, secure_agg, dp, exact)
    ("plain", False, None, True),
    ("dp", False, "client", True),
    ("sa", True, None, False),
    ("sa_dp", True, "client", False),
]


@pytest.mark.parametrize(
    "label,sa,dp,exact", MATRIX, ids=[m[0] for m in MATRIX]
)
def test_stale_on_without_stragglers_matches_off(
    monkeypatch, label, sa, dp, exact
):
    """QFEDX_STALE with zero stragglers vs the default r12 program:
    bit-exact when no masks are involved (the discount path multiplies
    by exactly 1.0 and sums in the same order); with secure-agg the
    per-wave pair graphs draw DIFFERENT masks, which must still cancel
    to wave-split tolerance. QFEDX_STALE=0 itself trivially rebuilds
    r12 (same code path) — the interesting parity is stale ON changing
    nothing observable until a wave is actually late."""
    cfg = FedConfig(
        local_epochs=1, batch_size=4, learning_rate=0.1, optimizer="sgd",
        client_fraction=0.5, secure_agg=sa, secure_agg_mode="ring",
        dp=DPConfig(clip_norm=1.0, noise_multiplier=0.5, mode=dp)
        if dp else None,
    )
    off = _run_streamed(cfg, False, monkeypatch)
    on = _run_streamed(cfg, True, monkeypatch)
    for a, b in zip(jax.tree.leaves(off.params), jax.tree.leaves(on.params)):
        if exact:
            assert np.array_equal(np.asarray(a), np.asarray(b))
        else:
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-5, rtol=0
            )
    if dp:
        assert off.epsilons == on.epsilons


# --- 2: self-cancelling stale waves (lr=0 mask residual) --------------------


def _single_wave_residual(monkeypatch, stale: str) -> float:
    """Max |update_sum| of ONE wave's partial at lr=0 under ring SA —
    the direct measure of whether the wave's masks cancel on their own."""
    monkeypatch.setenv("QFEDX_STALE", stale)
    cfg = FedConfig(
        local_epochs=1, batch_size=4, learning_rate=0.0, momentum=0.0,
        optimizer="sgd", secure_agg=True, secure_agg_mode="ring",
    )
    model = _model()
    mesh = client_mesh(num_devices=4)
    cx, cy, cm = _data(seed=1)
    params = model.init(jax.random.PRNGKey(2))
    pf = make_fed_round_partial(
        model, cfg, mesh, wave_clients=4, cohort_clients=C
    )
    wx, wy, wm = shard_client_data(mesh, cx[4:8], cy[4:8], jnp.asarray(cm[4:8]))
    part = pf(params, wx, wy, wm, np.int32(4), jax.random.PRNGKey(5))
    return max(
        float(jnp.max(jnp.abs(leaf)))
        for leaf in jax.tree.leaves(part.update_sum)
    )


def test_stale_wave_partial_is_self_cancelling(monkeypatch):
    """The property buffered staleness stands on: with QFEDX_STALE the
    pair graph is wave-restricted, so a lone wave's lr=0 partial is
    pure mask dust (< 1e-5) — it can land in ANY later round without
    corruption. Without the pin the same partial carries unmatched
    cross-wave ring edges (residual orders of magnitude larger), which
    is also the proof this test can tell the difference."""
    assert _single_wave_residual(monkeypatch, "1") < 1e-5
    assert _single_wave_residual(monkeypatch, "0") > 1e-3


def test_lr0_straggler_leaves_theta_unchanged(monkeypatch):
    """End-to-end cancellation: lr=0 + ring SA + an injected one-round
    straggler — after the stale partial folds in, θ still equals the
    initial parameters to float dust (fresh waves cancel per wave, the
    stale wave cancels on its own, and the discount scales zeros)."""
    cfg = FedConfig(
        local_epochs=1, batch_size=4, learning_rate=0.0, momentum=0.0,
        optimizer="sgd", secure_agg=True, secure_agg_mode="ring",
    )
    rows = []
    res = _run_streamed(
        cfg, True, monkeypatch, plan=FaultPlan(seed=0, rules=_STRAGGLER_PLAN),
        num_rounds=3, rows=rows, wave_deadline_s=0.1, stale_poll_s=10.0,
    )
    assert rows[1]["late_waves"] == 1
    assert rows[2]["stale_partials_applied"] == 1
    # Compare against the model's own init for THIS run's seed: the
    # trainer derives init from seed=3 — rebuild it the same way.
    key = jax.random.PRNGKey(3)
    init_key, _ = jax.random.split(key)
    init = _model().init(init_key)
    for a, b in zip(jax.tree.leaves(init), jax.tree.leaves(res.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=0
        )


# --- 3: ε-invariance under lateness -----------------------------------------


def test_epsilon_invariant_under_injected_delays(monkeypatch):
    """The accountant charges the ORIGIN round at sampling time, so a
    wave arriving a round late (and folding in at a discount) changes
    no ε — pinned exactly, per round, against the clean run."""
    cfg = FedConfig(
        local_epochs=1, batch_size=4, learning_rate=0.1, optimizer="sgd",
        client_fraction=0.5,
        dp=DPConfig(clip_norm=1.0, noise_multiplier=1.0),
    )
    clean = _run_streamed(cfg, True, monkeypatch, num_rounds=3)
    rows = []
    slow = _run_streamed(
        cfg, True, monkeypatch, plan=FaultPlan(seed=0, rules=_STRAGGLER_PLAN),
        num_rounds=3, rows=rows, wave_deadline_s=0.1, stale_poll_s=10.0,
    )
    assert rows[1]["late_waves"] == 1  # the delay actually fired
    assert rows[2]["stale_partials_applied"] == 1
    assert clean.epsilons == slow.epsilons
    assert len(clean.epsilons) == 3


# --- 4: robust rules over mixed-age partials --------------------------------


@pytest.mark.parametrize("agg", ["trimmed_mean", "median"])
def test_robust_combine_spans_mixed_age_partials(monkeypatch, agg):
    """trimmed_mean/median with a straggler in the stack: the round
    completes, the stale partial joins the cross-wave combine (exact
    ledger counts), θ stays finite, and the trimmed_fraction stat is
    reported over the mixed-age contributors."""
    cfg = FedConfig(
        local_epochs=1, batch_size=4, learning_rate=0.1, optimizer="sgd",
        aggregator=agg, trim_fraction=0.25,
    )
    rows = []
    res = _run_streamed(
        cfg, True, monkeypatch, plan=FaultPlan(seed=0, rules=_STRAGGLER_PLAN),
        num_rounds=3, rows=rows, wave_deadline_s=0.1, stale_poll_s=10.0,
    )
    assert rows[1]["late_waves"] == 1
    assert rows[1]["participants"] == 12
    assert rows[2]["stale_partials_applied"] == 1
    assert rows[2]["participants"] == 20  # 16 fresh + 4 stale
    assert rows[2]["aggregator"] == agg
    assert rows[2]["trimmed_fraction"] > 0
    for leaf in jax.tree.leaves(res.params):
        assert np.all(np.isfinite(np.asarray(leaf)))


def test_stacked_apply_discounts_ages_directly():
    """Unit-level discount semantics: two identical partials, one
    tagged stale — the constant-discount apply must land exactly
    between apply(fresh only) and apply(both fresh): the stale twin
    contributes with weight α. (Σ s·wΔ / Σ s·w over identical deltas
    equals the common mean, so use DIFFERENT deltas per wave.)"""
    cfg = FedConfig(staleness_mode="constant", staleness_alpha=0.5)
    params = {"w": jnp.zeros((2,), jnp.float32)}

    def part(delta, weight):
        from qfedx_tpu.fed.round import RoundPartial

        return RoundPartial(
            update_sum={"w": jnp.asarray(delta, jnp.float32) * weight},
            weight_sum=jnp.float32(weight),
            loss_sum=jnp.float32(0.0),
            num_participants=jnp.float32(weight),
        )

    fresh = part([1.0, 0.0], 4.0)
    stale = part([0.0, 2.0], 4.0)
    apply_fn = make_apply_partials(cfg, cohort_clients=0)
    p_new, stats = apply_fn(
        params, stack_partials([fresh, stale]),
        ages=np.array([0.0, 1.0], np.float32),
    )
    # θ = (1·4·[1,0] + 0.5·4·[0,2]) / (4 + 2) = [2/3, 2/3]
    np.testing.assert_allclose(
        np.asarray(p_new["w"]), [2.0 / 3.0, 2.0 / 3.0], atol=1e-6
    )
    # counts stay undiscounted — stale clients genuinely participated
    assert float(stats.num_participants) == 8.0
    # ages=None is the r12 apply exactly: plain sum, no discount
    p_plain, _ = apply_fn(params, stack_partials([fresh, stale]))
    np.testing.assert_allclose(np.asarray(p_plain["w"]), [0.5, 1.0], atol=1e-6)
    # poly mode: s(1) = (1+1)^-1 = 0.5 — same result by construction
    cfg_p = FedConfig(staleness_mode="poly", staleness_alpha=1.0)
    p_poly, _ = make_apply_partials(cfg_p, 0)(
        params, stack_partials([fresh, stale]),
        ages=np.array([0.0, 1.0], np.float32),
    )
    np.testing.assert_allclose(
        np.asarray(p_poly["w"]), np.asarray(p_new["w"]), atol=1e-7
    )


# --- lifecycle: recovery, bounded buffer, guard rails -----------------------


def test_straggler_clients_are_recovered_not_dropped(monkeypatch):
    """The tentpole's point: with buffering ON a one-round straggler
    costs zero clients — every sampled client's work lands (one round
    of it discounted); with the r12 drop path the same injection loses
    the wave outright. Ledger counts pinned exactly."""
    cfg = FedConfig(local_epochs=1, batch_size=4, learning_rate=0.1)
    plan_rules = [
        {"site": "client.slow", "kind": "slow:0.5", "clients": [5]},
    ]
    rows_buf = []
    _run_streamed(
        cfg, True, monkeypatch, plan=FaultPlan(seed=0, rules=plan_rules),
        num_rounds=3, rows=rows_buf, wave_deadline_s=0.1, stale_poll_s=10.0,
    )
    # client 5 lives in wave 1 (ids 4..7): its wave goes late EVERY
    # round; each next round salvages it. No dropouts anywhere.
    for r, row in enumerate(rows_buf):
        assert row["late_waves"] == 1
        assert row["dropped_clients"] == 0
        assert row["stale_partials_applied"] == (1 if r > 0 else 0)
    # drop mode (stale off): a straggler is pure casualties. The LAST
    # wave is delayed (no trailing waves — drop mode has no up-front
    # declaration, so a mid-round straggler head-of-line-blocks the
    # in-order uploader and later waves would time out too).
    rows_drop = []
    _run_streamed(
        cfg, False, monkeypatch,
        plan=FaultPlan(seed=0, rules=[
            {"site": "wave.delay", "kind": "delay:0.6", "waves": [3]},
        ]),
        num_rounds=2, rows=rows_drop, wave_deadline_s=0.1,
    )
    assert rows_drop[0]["dropped_clients"] == 4
    assert rows_drop[0]["dropped_waves"] == 1


def test_dead_straggler_degrades_to_dropouts(monkeypatch):
    """A wave that goes late AND then fails its deferred upload for
    good (persistent registry fault) degrades to casualties at the
    round that discovers it — counted once, exactly, with the SAME
    convention as the fresh dead-wave path: every SAMPLED client of
    the never-dispatched wave counts, including one the plan had
    already marked dropped (no in-program counter ever saw it, and
    'drop' vs 'buffer' must reconcile to identical totals)."""
    cfg = FedConfig(local_epochs=1, batch_size=4, learning_rate=0.1)
    plan = FaultPlan(seed=0, rules=[
        {"site": "wave.delay", "kind": "delay:0.3", "rounds": [0],
         "waves": [1]},
        {"site": "registry.fetch", "rounds": [0], "waves": [1]},
        # a plan-dropped client INSIDE the dead straggler wave — still
        # exactly one of the wave's 4 casualties, never uncounted
        {"site": "client.compute", "kind": "drop", "clients": [5],
         "rounds": [0]},
    ])
    rows = []
    _run_streamed(
        cfg, True, monkeypatch, plan=plan, num_rounds=2, rows=rows,
        wave_deadline_s=0.1, stale_poll_s=10.0,
    )
    assert rows[0]["late_waves"] == 1
    assert rows[0]["dropped_clients"] == 0  # not yet known dead
    assert rows[1]["stale_partials_applied"] == 0
    assert rows[1]["dropped_clients"] == 4  # the whole sampled wave
    assert rows[1]["stale_discarded_waves"] == 1


def test_overage_straggler_is_abandoned(monkeypatch):
    """The BOUNDED buffer: a straggler still unresolved after
    staleness_max_age rounds is abandoned — its clients counted as
    dropouts — instead of pinning host state forever."""
    cfg = FedConfig(
        local_epochs=1, batch_size=4, learning_rate=0.1,
        staleness_max_age=1,
    )
    plan = FaultPlan(seed=0, rules=[
        {"site": "wave.delay", "kind": "delay:2.0", "rounds": [0],
         "waves": [1]},
    ])
    rows = []
    _run_streamed(
        cfg, True, monkeypatch, plan=plan, num_rounds=2, rows=rows,
        wave_deadline_s=0.1, stale_poll_s=0.2,
    )
    assert rows[0]["late_waves"] == 1
    assert rows[1]["stale_partials_applied"] == 0
    assert rows[1]["stale_discarded_waves"] == 1
    assert rows[1]["dropped_clients"] == 4


def test_stale_requires_hier_and_guards(monkeypatch):
    cfg = FedConfig(local_epochs=1, batch_size=4)
    monkeypatch.setenv("QFEDX_HIER", "off")
    # wave == cohort so the hier-off multi-wave guard stays silent and
    # the STALENESS requirement is what fires
    with pytest.raises(ValueError, match="QFEDX_STALE"):
        _run_streamed(cfg, True, monkeypatch, wave_size=C)
    monkeypatch.delenv("QFEDX_HIER")
    monkeypatch.setenv("QFEDX_GUARDS", "off")
    with pytest.raises(ValueError, match="QFEDX_GUARDS"):
        _run_streamed(cfg, True, monkeypatch)
