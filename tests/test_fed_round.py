"""Federated runtime tests on the 8-device virtual CPU mesh.

The central equivalence check: the one-program SPMD round must reproduce the
reference's sequential semantics (per-client local training then
sample-weighted averaging — reference src/CFed/Classical_FL.py:104-157)
exactly, because it is the same math reorganized, not an approximation.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from qfedx_tpu.fed.client import make_local_update
from qfedx_tpu.fed.config import DPConfig, FedConfig
from qfedx_tpu.fed.round import client_mesh, make_fed_round, shard_client_data
from qfedx_tpu.models.api import Model
from qfedx_tpu.models.vqc import make_vqc_classifier
from qfedx_tpu.utils import trees


def linear_model(dim=4, classes=2):
    """Tiny deterministic linear model — fast, convex, exact-math friendly."""

    def init(key):
        return {
            "w": jnp.zeros((dim, classes), dtype=jnp.float32),
            "b": jnp.zeros((classes,), dtype=jnp.float32),
        }

    def apply(params, x):
        return x @ params["w"] + params["b"]

    return Model(init=init, apply=apply, name="linear")


def make_client_data(num_clients=8, samples=16, dim=4, seed=0):
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(dim,))
    cx = rng.normal(size=(num_clients, samples, dim)).astype(np.float32)
    cy = (cx @ w_true > 0).astype(np.int32)
    cmask = np.ones((num_clients, samples), dtype=np.float32)
    return jnp.asarray(cx), jnp.asarray(cy), cmask, w_true


@pytest.fixture(scope="module")
def mesh():
    return client_mesh()


def _sequential_round(model, cfg, params, cx, cy, cmask, round_key, num_clients):
    """Host-side re-implementation of one round with the same PRNG layout
    as fed.round.make_fed_round — the reference-semantics oracle."""
    local_update = make_local_update(model, cfg)
    train_key = jax.random.fold_in(round_key, 0x7A41)
    deltas, weights = [], []
    for cid in range(num_clients):
        delta, n, _ = local_update(
            params, cx[cid], cy[cid], cmask[cid], jax.random.fold_in(train_key, cid)
        )
        deltas.append(delta)
        weights.append(float(n))
    total = sum(weights)
    agg = trees.tree_zeros_like(params)
    for d, w in zip(deltas, weights):
        agg = trees.tree_add(agg, trees.tree_scale(d, w / total))
    return trees.tree_add(params, agg)


def test_spmd_round_matches_sequential_semantics(mesh):
    model = linear_model()
    cfg = FedConfig(local_epochs=2, batch_size=8, learning_rate=0.1, momentum=0.0)
    cx, cy, cmask, _ = make_client_data()
    params = model.init(jax.random.PRNGKey(0))
    round_key = jax.random.PRNGKey(42)

    round_fn = make_fed_round(model, cfg, mesh, num_clients=8)
    scx, scy, scm = shard_client_data(mesh, cx, cy, jnp.asarray(cmask))
    new_params, stats = round_fn(params, scx, scy, scm, round_key)

    expected = _sequential_round(model, cfg, params, cx, cy, cmask, round_key, 8)
    for k in expected:
        np.testing.assert_allclose(
            np.asarray(new_params[k]), np.asarray(expected[k]), atol=1e-5
        )
    assert float(stats.total_weight) == pytest.approx(8 * 16)
    assert float(stats.num_participants) == 8


def test_round_with_client_blocks(mesh):
    """16 clients on 8 devices → blocks of 2 per device (SURVEY §7.3.5)."""
    model = linear_model()
    cfg = FedConfig(local_epochs=1, batch_size=8, learning_rate=0.1, momentum=0.0)
    cx, cy, cmask, _ = make_client_data(num_clients=16)
    params = model.init(jax.random.PRNGKey(0))
    round_key = jax.random.PRNGKey(7)

    round_fn = make_fed_round(model, cfg, mesh, num_clients=16)
    new_params, stats = round_fn(params, cx, cy, jnp.asarray(cmask), round_key)
    expected = _sequential_round(model, cfg, params, cx, cy, cmask, round_key, 16)
    for k in expected:
        np.testing.assert_allclose(
            np.asarray(new_params[k]), np.asarray(expected[k]), atol=1e-5
        )


def test_empty_client_contributes_zero(mesh):
    model = linear_model()
    cfg = FedConfig(local_epochs=1, batch_size=8, learning_rate=0.1, momentum=0.0)
    cx, cy, cmask, _ = make_client_data()
    cmask = cmask.copy()
    cmask[3] = 0.0  # client 3 has no data (Dirichlet small-α case, SURVEY §7.4)
    params = model.init(jax.random.PRNGKey(0))
    round_fn = make_fed_round(model, cfg, mesh, num_clients=8)
    new_params, stats = round_fn(params, cx, cy, jnp.asarray(cmask), jax.random.PRNGKey(1))
    assert float(stats.total_weight) == pytest.approx(7 * 16)
    assert np.all(np.isfinite(np.asarray(new_params["w"])))


def test_client_sampling_reduces_participants(mesh):
    model = linear_model()
    cfg = FedConfig(
        local_epochs=1, batch_size=8, learning_rate=0.1, momentum=0.0, client_fraction=0.5
    )
    cx, cy, cmask, _ = make_client_data()
    params = model.init(jax.random.PRNGKey(0))
    round_fn = make_fed_round(model, cfg, mesh, num_clients=8)
    _, stats = round_fn(params, cx, cy, jnp.asarray(cmask), jax.random.PRNGKey(3))
    n_part = float(stats.num_participants)
    assert 0 <= n_part < 8  # strictly fewer than all with high probability


def test_zero_participants_is_noop(mesh):
    model = linear_model()
    cfg = FedConfig(
        local_epochs=1, batch_size=8, learning_rate=0.5, momentum=0.0, client_fraction=1e-6
    )
    cx, cy, cmask, _ = make_client_data()
    params = {"w": jnp.ones((4, 2)), "b": jnp.zeros((2,))}
    round_fn = make_fed_round(model, cfg, mesh, num_clients=8)
    new_params, stats = round_fn(params, cx, cy, jnp.asarray(cmask), jax.random.PRNGKey(0))
    assert float(stats.num_participants) == 0
    np.testing.assert_allclose(np.asarray(new_params["w"]), 1.0, atol=1e-6)


def test_secure_agg_masks_cancel(mesh):
    """ROADMAP.md:55,61 unit test: masked aggregation ≡ raw aggregation."""
    model = linear_model()
    base = dict(local_epochs=1, batch_size=8, learning_rate=0.1, momentum=0.0)
    cx, cy, cmask, _ = make_client_data()
    params = model.init(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(11)

    plain = make_fed_round(model, FedConfig(**base), mesh, num_clients=8)
    masked = make_fed_round(
        model, FedConfig(**base, secure_agg=True, secure_agg_scale=5.0), mesh, num_clients=8
    )
    p_plain, _ = plain(params, cx, cy, jnp.asarray(cmask), key)
    p_masked, _ = masked(params, cx, cy, jnp.asarray(cmask), key)
    for k in p_plain:
        np.testing.assert_allclose(
            np.asarray(p_plain[k]), np.asarray(p_masked[k]), atol=1e-4
        )


def test_secure_agg_cancels_under_sampling(mesh):
    model = linear_model()
    base = dict(
        local_epochs=1, batch_size=8, learning_rate=0.1, momentum=0.0, client_fraction=0.6
    )
    cx, cy, cmask, _ = make_client_data()
    params = model.init(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(13)
    plain = make_fed_round(model, FedConfig(**base), mesh, num_clients=8)
    # complete pair graph here; the default ring graph is covered above and
    # at 256 clients below — both graphs must cancel under sampling.
    masked = make_fed_round(
        model,
        FedConfig(**base, secure_agg=True, secure_agg_scale=3.0,
                  secure_agg_mode="pairwise"),
        mesh,
        num_clients=8,
    )
    p_plain, s_plain = plain(params, cx, cy, jnp.asarray(cmask), key)
    p_masked, s_masked = masked(params, cx, cy, jnp.asarray(cmask), key)
    assert float(s_plain.num_participants) == float(s_masked.num_participants)
    for k in p_plain:
        np.testing.assert_allclose(
            np.asarray(p_plain[k]), np.asarray(p_masked[k]), atol=1e-4
        )


@pytest.mark.slow
def test_round_equality_at_64_clients(mesh):
    """BASELINE config-4 client count: 64 clients = blocks of 8 per device;
    the SPMD round must still match the sequential oracle exactly.
    Slow (~37 s: the 64-client sequential oracle) — the same property is
    pinned in-gate at 8 and 16 clients; this runs under -m slow."""
    model = linear_model()
    cfg = FedConfig(local_epochs=1, batch_size=8, learning_rate=0.1, momentum=0.0)
    cx, cy, cmask, _ = make_client_data(num_clients=64)
    params = model.init(jax.random.PRNGKey(0))
    round_key = jax.random.PRNGKey(21)
    round_fn = make_fed_round(model, cfg, mesh, num_clients=64)
    new_params, stats = round_fn(params, cx, cy, jnp.asarray(cmask), round_key)
    expected = _sequential_round(model, cfg, params, cx, cy, cmask, round_key, 64)
    for k in expected:
        np.testing.assert_allclose(
            np.asarray(new_params[k]), np.asarray(expected[k]), atol=1e-5
        )
    assert float(stats.num_participants) == 64


def test_secure_agg_ring_at_256_clients(mesh):
    """BASELINE config-5 client count with ring secure-agg + sampling:
    masked round ≡ plain round, and the round stays fast (the O(C²)
    complete graph would sample 65,536 PRG trees here; the ring samples
    512)."""
    import time

    model = linear_model()
    base = dict(
        local_epochs=1, batch_size=8, learning_rate=0.1, momentum=0.0,
        client_fraction=0.5,
    )
    cx, cy, cmask, _ = make_client_data(num_clients=256, samples=8)
    params = model.init(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(17)
    plain = make_fed_round(model, FedConfig(**base), mesh, num_clients=256)
    masked = make_fed_round(
        model,
        FedConfig(**base, secure_agg=True, secure_agg_scale=3.0,
                  secure_agg_mode="ring", secure_agg_neighbors=2),
        mesh,
        num_clients=256,
    )
    p_plain, s_plain = plain(params, cx, cy, jnp.asarray(cmask), key)
    p_masked, s_masked = masked(params, cx, cy, jnp.asarray(cmask), key)
    jax.block_until_ready(p_masked)
    t0 = time.perf_counter()
    p_masked2, _ = masked(params, cx, cy, jnp.asarray(cmask), key)
    jax.block_until_ready(p_masked2)
    steady = time.perf_counter() - t0
    assert float(s_plain.num_participants) == float(s_masked.num_participants)
    for k in p_plain:
        np.testing.assert_allclose(
            np.asarray(p_plain[k]), np.asarray(p_masked[k]), atol=2e-4
        )
    assert steady < 10.0, f"steady-state 256-client masked round took {steady:.1f}s"


def test_dp_secure_agg_sampling_compose(mesh):
    """Round-2 VERDICT item 8: DP + secure-agg + client sampling all ON in
    one round at 64 clients. The revealed aggregate must (a) equal the
    same DP round without masks — cancellation holds under DP weighting —
    and (b) carry exactly the DP-calibrated noise (σC/√k for k uniform-
    weight participants), i.e. the masks add no variance of their own."""
    dim = 2000
    model = linear_model(dim=dim)
    base = dict(
        local_epochs=1, batch_size=8, learning_rate=0.1, momentum=0.0,
        client_fraction=0.5,
    )
    sigma, clip = 2.0, 0.05
    rng = np.random.default_rng(3)
    cx = jnp.asarray(rng.normal(size=(64, 8, dim)).astype(np.float32))
    cy = jnp.asarray(rng.integers(0, 2, (64, 8)).astype(np.int32))
    cmask = jnp.ones((64, 8), dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(23)

    dp = DPConfig(clip_norm=clip, noise_multiplier=sigma)
    dp0 = DPConfig(clip_norm=clip, noise_multiplier=0.0)
    mk = lambda **kw: make_fed_round(
        model, FedConfig(**base, **kw), mesh, num_clients=64
    )
    p_dp, s_dp = mk(dp=dp)(params, cx, cy, cmask, key)
    p_all, s_all = mk(
        dp=dp, secure_agg=True, secure_agg_scale=5.0,
        secure_agg_mode="ring", secure_agg_neighbors=2,
    )(params, cx, cy, cmask, key)
    p_clip, _ = mk(dp=dp0)(params, cx, cy, cmask, key)

    # (a) masks cancel exactly under DP weighting + sampling.
    assert float(s_dp.num_participants) == float(s_all.num_participants)
    for k in p_dp:
        np.testing.assert_allclose(
            np.asarray(p_dp[k]), np.asarray(p_all[k]), atol=2e-4
        )
    # (b) the noise in the revealed aggregate is the DP calibration:
    # subtracting the σ=0 (clip-only) round isolates Σ N(0,σ²C²)/k over
    # k participants → coordinate std σC/√k, unchanged by the masks.
    k_part = float(s_all.num_participants)
    resid = np.asarray(p_all["w"]) - np.asarray(p_clip["w"])
    want_std = sigma * clip / np.sqrt(k_part)
    assert np.std(resid) == pytest.approx(want_std, rel=0.1)


def test_dp_clip_bounds_update_and_noise_present(mesh):
    model = linear_model()
    cx, cy, cmask, _ = make_client_data()
    params = model.init(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(5)

    # σ=0: pure clipping. The aggregated update is a convex combination of
    # per-client clipped deltas, so its norm is ≤ C.
    clip_cfg = FedConfig(
        local_epochs=3,
        batch_size=8,
        learning_rate=1.0,
        momentum=0.0,
        dp=DPConfig(clip_norm=0.05, noise_multiplier=0.0),
    )
    round_fn = make_fed_round(model, clip_cfg, mesh, num_clients=8)
    new_params, _ = round_fn(params, cx, cy, jnp.asarray(cmask), key)
    update_norm = float(trees.global_norm(trees.tree_sub(new_params, params)))
    assert update_norm <= 0.05 + 1e-5

    # σ>0: same round differs from σ=0 (noise actually lands).
    noisy_cfg = FedConfig(
        local_epochs=3,
        batch_size=8,
        learning_rate=1.0,
        momentum=0.0,
        dp=DPConfig(clip_norm=0.05, noise_multiplier=1.0),
    )
    noisy_fn = make_fed_round(model, noisy_cfg, mesh, num_clients=8)
    noisy_params, _ = noisy_fn(params, cx, cy, jnp.asarray(cmask), key)
    assert not np.allclose(
        np.asarray(noisy_params["w"]), np.asarray(new_params["w"]), atol=1e-6
    )


def test_fedprox_stays_closer_to_global(mesh):
    model = linear_model()
    cx, cy, cmask, _ = make_client_data()
    params = model.init(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(9)
    base = dict(local_epochs=5, batch_size=8, learning_rate=0.1, momentum=0.0)
    avg = make_fed_round(model, FedConfig(**base), mesh, num_clients=8)
    prox = make_fed_round(
        model, FedConfig(**base, algorithm="fedprox", prox_mu=1.0), mesh, num_clients=8
    )
    p_avg, _ = avg(params, cx, cy, jnp.asarray(cmask), key)
    p_prox, _ = prox(params, cx, cy, jnp.asarray(cmask), key)
    d_avg = float(trees.global_norm(trees.tree_sub(p_avg, params)))
    d_prox = float(trees.global_norm(trees.tree_sub(p_prox, params)))
    assert d_prox < d_avg


def test_adam_optimizer_round_runs(mesh):
    model = linear_model()
    cfg = FedConfig(local_epochs=1, batch_size=8, learning_rate=0.01, optimizer="adam")
    cx, cy, cmask, _ = make_client_data()
    params = model.init(jax.random.PRNGKey(0))
    round_fn = make_fed_round(model, cfg, mesh, num_clients=8)
    new_params, _ = round_fn(params, cx, cy, jnp.asarray(cmask), jax.random.PRNGKey(2))
    assert np.all(np.isfinite(np.asarray(new_params["w"])))
    assert not np.allclose(np.asarray(new_params["w"]), 0.0)


def test_scanned_rounds_match_sequential():
    """make_fed_rounds(K) ≡ K sequential make_fed_round calls, bit-for-bit
    key derivation included (the trainer's fold_in(base, rnd) scheme) —
    with DP + secure-agg + sampling on so every PRNG path is exercised."""
    from qfedx_tpu.fed.round import make_fed_rounds

    num_clients, samples, n_q = 8, 8, 3
    model = make_vqc_classifier(n_qubits=n_q, n_layers=1, num_classes=2)
    cfg = FedConfig(
        local_epochs=1, batch_size=4, learning_rate=0.1, optimizer="adam",
        client_fraction=0.75,
        dp=DPConfig(clip_norm=1.0, noise_multiplier=0.5),
        secure_agg=True,
    )
    mesh = client_mesh(num_devices=4)
    rng = np.random.default_rng(0)
    cx = rng.uniform(0, 1, (num_clients, samples, n_q)).astype(np.float32)
    cy = rng.integers(0, 2, (num_clients, samples)).astype(np.int32)
    cm = np.ones((num_clients, samples), dtype=np.float32)
    scx, scy, scm = shard_client_data(mesh, cx, cy, jnp.asarray(cm))

    base = jax.random.PRNGKey(7)
    params0 = model.init(jax.random.PRNGKey(0))

    one = make_fed_round(model, cfg, mesh, num_clients=num_clients)
    p_seq = params0
    seq_losses = []
    for rnd in range(2, 5):  # start_round=2: offset must round-trip too
        p_seq, st = one(p_seq, scx, scy, scm, jax.random.fold_in(base, rnd))
        seq_losses.append(float(st.mean_loss))

    chunk = make_fed_rounds(
        model, cfg, mesh, num_clients=num_clients, rounds_per_call=3
    )
    p_scan, stats = chunk(params0, scx, scy, scm, base, 2)

    for a, b in zip(jax.tree.leaves(p_seq), jax.tree.leaves(p_scan)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(stats.mean_loss), np.asarray(seq_losses), atol=1e-5
    )


def test_trainer_rounds_per_call_equivalence():
    """train_federated(rounds_per_call=2) reproduces the K=1 run exactly
    (same seeds → same params). The scanned run evaluates ON DEVICE every
    round (in-scan eval — no eval_every trade-off), so its accuracy series
    is denser: at rounds the K=1 run also evaluated, both must agree."""
    from qfedx_tpu.run.trainer import train_federated

    num_clients, samples, n_q = 4, 8, 3
    model = make_vqc_classifier(n_qubits=n_q, n_layers=1, num_classes=2)
    cfg = FedConfig(local_epochs=1, batch_size=4, learning_rate=0.1,
                    optimizer="adam")
    rng = np.random.default_rng(1)
    cx = rng.uniform(0, 1, (num_clients, samples, n_q)).astype(np.float32)
    cy = rng.integers(0, 2, (num_clients, samples)).astype(np.int32)
    cm = np.ones((num_clients, samples), dtype=np.float32)
    tx = rng.uniform(0, 1, (16, n_q)).astype(np.float32)
    ty = rng.integers(0, 2, (16,)).astype(np.int32)

    kw = dict(num_rounds=4, seed=3, eval_every=2)
    r1 = train_federated(model, cfg, cx, cy, cm, tx, ty, **kw)
    r2 = train_federated(model, cfg, cx, cy, cm, tx, ty,
                         rounds_per_call=2, **kw)
    for a, b in zip(jax.tree.leaves(r1.params), jax.tree.leaves(r2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    # r1: [round0, r2, r4] (host eval on cadence); r2: [round0, r1..r4]
    # (in-scan eval, every round). Shared rounds must agree.
    assert len(r1.accuracies) == 3 and len(r2.accuracies) == 5
    np.testing.assert_allclose(
        [r1.accuracies[0], r1.accuracies[1], r1.accuracies[2]],
        [r2.accuracies[0], r2.accuracies[2], r2.accuracies[4]],
        atol=1e-6,
    )
    np.testing.assert_allclose(r1.losses, r2.losses, atol=1e-5)
