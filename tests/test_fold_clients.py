"""The folded client axis ≡ the vmap client path (r06 tentpole).

Three layers of parity, each pinning the fold at a different altitude:

- ops: grouped (G,2,2) gate coefficients on a (G·S, 2^n) slab ≡ a
  per-client vmap of the dense engine (row and lane qubits);
- model: ``apply_clients`` with the batched slab engine pinned ≡ a vmap
  of ``apply`` over diverged per-client params — logits AND gradients,
  f32 and bf16 tolerances;
- round: ``make_fed_round`` / ``make_fed_rounds`` with the fold pinned on
  ≡ pinned off (QFEDX_FOLD_CLIENTS), on the 8-device virtual mesh.

Also documents the r05 time_to_target finding: the batched auto-route is
gated on _SLAB_MIN and can NOT engage at the flagship 8-qubit shape, so
the suspected routing change is exonerated by construction (bench.py /
docs/PERF.md §11 for the real mechanism).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from qfedx_tpu.ops import gates
from qfedx_tpu.ops.batched import apply_gate_b, batched_enabled
from qfedx_tpu.ops.cpx import CArray
from qfedx_tpu.ops.statevector import apply_gate

N = 10  # smallest slab width (statevector._SLAB_MIN)
G, S = 3, 2  # client groups × samples per client
B = G * S


def _rand_state(seed: int) -> CArray:
    rng = np.random.default_rng(seed)
    re = jnp.asarray(rng.standard_normal((B, 1 << N)), dtype=jnp.float32)
    im = jnp.asarray(rng.standard_normal((B, 1 << N)), dtype=jnp.float32)
    return CArray(re, im)


@pytest.mark.parametrize("qubit", [0, 2, N - 7, N - 2, N - 1])  # row + lane
def test_grouped_gate_parity(qubit):
    """(G,2,2) grouped coefficients ≡ per-group vmap of the dense engine."""
    state = _rand_state(0)
    th = jnp.asarray([0.3, -1.2, 2.5], dtype=jnp.float32)
    ph = jnp.asarray([0.9, 0.1, -0.7], dtype=jnp.float32)
    out = apply_gate_b(state, N, gates.rot_zx_batched(th, ph), qubit)

    tens_re = state.re.reshape((G, S) + (2,) * N)
    tens_im = state.im.reshape((G, S) + (2,) * N)

    def one(s_re, s_im, t, p):
        o = apply_gate(CArray(s_re, s_im), gates.rot_zx(t, p), qubit)
        return o.re, o.im

    ref_re, ref_im = jax.vmap(
        jax.vmap(one, in_axes=(0, 0, None, None)), in_axes=(0, 0, 0, 0)
    )(tens_re, tens_im, th, ph)
    np.testing.assert_allclose(
        np.asarray(out.re), np.asarray(ref_re).reshape(B, -1), atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(out.im), np.asarray(ref_im).reshape(B, -1), atol=1e-5
    )


def test_grouped_gate_rejects_nondivisor_groups():
    state = _rand_state(1)
    bad = gates.rot_zx_batched(jnp.zeros(4), jnp.zeros(4))  # 4 ∤ 6
    with pytest.raises(ValueError, match="G must divide B"):
        apply_gate_b(state, N, bad, 0)


def test_bstate_amplitude_rejects_non_pow2():
    """The batched route fails with the same clear ValueError as
    circuits.encoders.amplitude_encode (ADVICE r05), not a reshape error."""
    from qfedx_tpu.ops.batched import bstate_amplitude

    with pytest.raises(ValueError, match="2\\^n features"):
        bstate_amplitude(jnp.zeros((2, 1000)), jnp.float32)


def test_batched_route_cannot_engage_below_slab(monkeypatch):
    """The r05 time_to_target suspect (models/vqc.py batched auto-route at
    the flagship 8-qubit shape) is impossible by construction: the route
    gates on _SLAB_MIN before reading any pin."""
    monkeypatch.setenv("QFEDX_BATCHED", "1")
    assert batched_enabled(8) is False


def _diverged_cparams(model, c):
    p0 = model.init(jax.random.PRNGKey(0))
    return jax.tree.map(
        lambda p: p[None]
        * (1.0 + 0.1 * jnp.arange(c).reshape((c,) + (1,) * p.ndim)),
        p0,
    )


@pytest.mark.parametrize("encoding", ["angle", "reupload"])
def test_apply_clients_engine_parity(encoding, monkeypatch):
    """Folded slab engine (per-client grouped gates) ≡ vmap of the
    per-client apply: logits and gradients, diverged params."""
    import optax

    from qfedx_tpu.models.vqc import make_vqc_classifier

    monkeypatch.setenv("QFEDX_BATCHED", "1")
    c, bsz = 2, 2
    model = make_vqc_classifier(
        n_qubits=N, n_layers=1, num_classes=2, encoding=encoding
    )
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.uniform(0, 1, (c, bsz, N)), dtype=jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, (c, bsz)), dtype=jnp.int32)
    cparams = _diverged_cparams(model, c)

    folded = model.apply_clients(cparams, x)
    ref = jax.vmap(model.apply)(cparams, x)
    np.testing.assert_allclose(
        np.asarray(folded), np.asarray(ref), atol=1e-5, rtol=0
    )

    def loss(f):
        def g(cp):
            return optax.softmax_cross_entropy_with_integer_labels(
                f(cp, x), y
            ).mean()

        return g

    g_fold = jax.grad(loss(model.apply_clients))(cparams)
    g_ref = jax.grad(loss(lambda cp, xx: jax.vmap(model.apply)(cp, xx)))(
        cparams
    )
    for a, b in zip(jax.tree.leaves(g_fold), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=0
        )


def test_apply_clients_engine_parity_bf16(monkeypatch):
    """Same parity under QFEDX_DTYPE=bf16 — the folded and vmap routes run
    the same bf16-state/f32-accumulate recipe, so they agree to bf16
    rounding, and gradients stay finite and close."""
    import optax

    from qfedx_tpu.models.vqc import make_vqc_classifier

    monkeypatch.setenv("QFEDX_BATCHED", "1")
    monkeypatch.setenv("QFEDX_DTYPE", "bf16")
    c, bsz = 2, 2
    model = make_vqc_classifier(n_qubits=N, n_layers=1, num_classes=2)
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.uniform(0, 1, (c, bsz, N)), dtype=jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, (c, bsz)), dtype=jnp.int32)
    cparams = _diverged_cparams(model, c)

    folded = model.apply_clients(cparams, x)
    ref = jax.vmap(model.apply)(cparams, x)
    np.testing.assert_allclose(
        np.asarray(folded), np.asarray(ref), atol=3e-2, rtol=0
    )

    def loss(f):
        def g(cp):
            return optax.softmax_cross_entropy_with_integer_labels(
                f(cp, x), y
            ).mean()

        return g

    g_fold = jax.grad(loss(model.apply_clients))(cparams)
    g_ref = jax.grad(loss(lambda cp, xx: jax.vmap(model.apply)(cp, xx)))(
        cparams
    )
    for a, b in zip(jax.tree.leaves(g_fold), jax.tree.leaves(g_ref)):
        a, b = np.asarray(a), np.asarray(b)
        assert np.all(np.isfinite(a))
        np.testing.assert_allclose(a, b, atol=3e-2, rtol=0)


def _fed_data(num_clients=8, samples=8, n_q=3, seed=0):
    rng = np.random.default_rng(seed)
    cx = rng.uniform(0, 1, (num_clients, samples, n_q)).astype(np.float32)
    cy = rng.integers(0, 2, (num_clients, samples)).astype(np.int32)
    cm = np.ones((num_clients, samples), dtype=np.float32)
    return cx, cy, cm


@pytest.mark.parametrize(
    "cfg_kwargs",
    [
        dict(optimizer="adam"),
        dict(momentum=0.9),
        dict(algorithm="fedprox", prox_mu=0.5),
    ],
    ids=["adam", "sgd-momentum", "fedprox"],
)
def test_fed_round_folded_matches_vmap(cfg_kwargs, monkeypatch):
    """make_fed_round with the client fold pinned ON ≡ pinned OFF on the
    8-device mesh (same keys, same math, different program structure)."""
    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.fed.round import (
        client_mesh,
        fold_clients_enabled,
        make_fed_round,
        shard_client_data,
    )
    from qfedx_tpu.models.vqc import make_vqc_classifier

    num_clients, samples, n_q = 8, 8, 3
    cfg = FedConfig(
        local_epochs=2, batch_size=4, learning_rate=0.1, **cfg_kwargs
    )
    mesh = client_mesh()
    cx, cy, cm = _fed_data(num_clients, samples, n_q)
    key = jax.random.PRNGKey(42)

    results = {}
    for pin in ("1", "0"):
        monkeypatch.setenv("QFEDX_FOLD_CLIENTS", pin)
        model = make_vqc_classifier(n_qubits=n_q, n_layers=2, num_classes=2)
        assert fold_clients_enabled(model, cfg) is (pin == "1")
        params = model.init(jax.random.PRNGKey(0))
        rf = make_fed_round(model, cfg, mesh, num_clients=num_clients)
        scx, scy, scm = shard_client_data(mesh, cx, cy, jnp.asarray(cm))
        results[pin] = rf(params, scx, scy, scm, key)
    p1, s1 = results["1"]
    p0, s0 = results["0"]
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p0)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-6, rtol=0
        )
    np.testing.assert_allclose(
        float(s1.mean_loss), float(s0.mean_loss), atol=1e-5
    )
    assert float(s1.total_weight) == float(s0.total_weight)


def test_fed_round_folded_composes_privacy(monkeypatch):
    """DP (client mode) + secure agg + sampling post-processing is shared
    between the paths: folded ≡ vmap with everything on."""
    from qfedx_tpu.fed.config import DPConfig, FedConfig
    from qfedx_tpu.fed.round import (
        client_mesh,
        make_fed_round,
        shard_client_data,
    )
    from qfedx_tpu.models.vqc import make_vqc_classifier

    num_clients, samples, n_q = 8, 8, 3
    cfg = FedConfig(
        local_epochs=1,
        batch_size=4,
        learning_rate=0.1,
        client_fraction=0.6,
        dp=DPConfig(clip_norm=0.5, noise_multiplier=0.5),
        secure_agg=True,
    )
    mesh = client_mesh()
    cx, cy, cm = _fed_data(num_clients, samples, n_q, seed=2)
    key = jax.random.PRNGKey(11)
    results = {}
    for pin in ("1", "0"):
        monkeypatch.setenv("QFEDX_FOLD_CLIENTS", pin)
        model = make_vqc_classifier(n_qubits=n_q, n_layers=1, num_classes=2)
        params = model.init(jax.random.PRNGKey(0))
        rf = make_fed_round(model, cfg, mesh, num_clients=num_clients)
        scx, scy, scm = shard_client_data(mesh, cx, cy, jnp.asarray(cm))
        results[pin] = rf(params, scx, scy, scm, key)
    p1, s1 = results["1"]
    p0, s0 = results["0"]
    assert float(s1.num_participants) == float(s0.num_participants)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p0)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-6, rtol=0
        )


def test_fed_rounds_scanned_folded_on_mesh(monkeypatch):
    """The folded path through make_fed_rounds (the trainer's scanned
    dispatch) on the 8-device virtual mesh ≡ the same scan with the fold
    pinned off, and ≡ sequential folded rounds (key-derivation parity)."""
    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.fed.round import (
        client_mesh,
        make_fed_round,
        make_fed_rounds,
        shard_client_data,
    )
    from qfedx_tpu.models.vqc import make_vqc_classifier

    num_clients, samples, n_q = 8, 8, 3
    cfg = FedConfig(
        local_epochs=1, batch_size=4, learning_rate=0.1, optimizer="adam"
    )
    mesh = client_mesh()
    cx, cy, cm = _fed_data(num_clients, samples, n_q, seed=4)
    base = jax.random.PRNGKey(7)

    out = {}
    for pin in ("1", "0"):
        monkeypatch.setenv("QFEDX_FOLD_CLIENTS", pin)
        model = make_vqc_classifier(n_qubits=n_q, n_layers=1, num_classes=2)
        params0 = model.init(jax.random.PRNGKey(0))
        scx, scy, scm = shard_client_data(mesh, cx, cy, jnp.asarray(cm))
        chunk = make_fed_rounds(
            model, cfg, mesh, num_clients=num_clients, rounds_per_call=3
        )
        out[pin] = chunk(params0, scx, scy, scm, base, 2)
        if pin == "1":
            # Sequential folded rounds with the trainer's fold_in(base, r)
            # derivation must match the scan exactly.
            one = make_fed_round(model, cfg, mesh, num_clients=num_clients)
            p_seq = params0
            for rnd in range(2, 5):
                p_seq, _ = one(
                    p_seq, scx, scy, scm, jax.random.fold_in(base, rnd)
                )
            for a, b in zip(
                jax.tree.leaves(p_seq), jax.tree.leaves(out["1"][0])
            ):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), atol=1e-6, rtol=0
                )
    for a, b in zip(jax.tree.leaves(out["1"][0]), jax.tree.leaves(out["0"][0])):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-6, rtol=0
        )
    np.testing.assert_allclose(
        np.asarray(out["1"][1].mean_loss),
        np.asarray(out["0"][1].mean_loss),
        atol=1e-5,
    )


def test_fed_round_folded_slab_engine(monkeypatch):
    """End-to-end at a SLAB width: the folded round with the batched
    engine pinned (the TPU production composition: per-client grouped
    gates inside shard_map) ≡ the vmap round, n=10 on the 8-device mesh
    (~27 s on XLA:CPU — two n=10 local-update compiles)."""
    from qfedx_tpu.fed.config import FedConfig
    from qfedx_tpu.fed.round import (
        client_mesh,
        make_fed_round,
        shard_client_data,
    )
    from qfedx_tpu.models.vqc import make_vqc_classifier

    monkeypatch.setenv("QFEDX_BATCHED", "1")
    num_clients, samples, n_q = 8, 4, N
    cfg = FedConfig(local_epochs=1, batch_size=4, learning_rate=0.1,
                    momentum=0.0)
    mesh = client_mesh()
    cx, cy, cm = _fed_data(num_clients, samples, n_q, seed=6)
    key = jax.random.PRNGKey(9)
    results = {}
    for pin in ("1", "0"):
        monkeypatch.setenv("QFEDX_FOLD_CLIENTS", pin)
        model = make_vqc_classifier(n_qubits=n_q, n_layers=1, num_classes=2)
        params = model.init(jax.random.PRNGKey(0))
        rf = make_fed_round(model, cfg, mesh, num_clients=num_clients)
        scx, scy, scm = shard_client_data(mesh, cx, cy, jnp.asarray(cm))
        results[pin] = rf(params, scx, scy, scm, key)
    for a, b in zip(
        jax.tree.leaves(results["1"][0]), jax.tree.leaves(results["0"][0])
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=0
        )
