"""Fused rotation gate, circuit-level trajectory noise, 20-qubit capability."""

import jax
import jax.numpy as jnp
import numpy as np

from qfedx_tpu.models.vqc import make_vqc_classifier
from qfedx_tpu.noise import NoiseModel
from qfedx_tpu.ops import gates, statevector as sv
from qfedx_tpu.ops.cpx import from_complex, to_complex


def test_rot_zx_equals_sequential():
    """gates.rot_zx(θ, φ) ≡ RZ(φ)·RX(θ) applied one after the other."""
    rng = np.random.default_rng(0)
    x = rng.normal(size=(2,) * 4) + 1j * rng.normal(size=(2,) * 4)
    state = from_complex(x / np.linalg.norm(x))
    for th, ph, q in [(0.7, 1.3, 0), (2.1, -0.4, 2), (0.0, 0.9, 3), (1.1, 0.0, 1)]:
        seq = sv.apply_gate(sv.apply_gate(state, gates.rx(th), q), gates.rz(ph), q)
        fused = sv.apply_gate(state, gates.rot_zx(th, ph), q)
        np.testing.assert_allclose(
            to_complex(fused), to_complex(seq), atol=1e-6
        )


def test_circuit_level_noise_trains_and_matches_analytic_mean():
    """Trajectory-noise training path: runs, is stochastic, and its mean
    logit is within sampling error of the analytic (readout-map) forward
    for a depolarizing channel."""
    p = 0.2
    nm = NoiseModel(depolarizing_p=p, circuit_level=True)
    model = make_vqc_classifier(3, n_layers=1, num_classes=2, noise_model=nm)
    params = model.init(jax.random.PRNGKey(0))
    x = jnp.asarray([[0.2, 0.6, 0.8]], dtype=jnp.float32)

    assert model.apply_train is not None
    draws = np.stack(
        [
            np.asarray(model.apply_train(params, x, jax.random.PRNGKey(i)))
            for i in range(300)
        ]
    )
    assert draws.std(axis=0).max() > 1e-4  # genuinely stochastic

    # Analytic comparison: 1 layer of per-qubit depolarizing before Z
    # measurement shrinks ⟨Z⟩ by (1−p) — exactly what eval's apply computes.
    analytic = np.asarray(model.apply(params, x))
    np.testing.assert_allclose(draws.mean(axis=0), analytic, atol=0.05)


def test_circuit_noise_rejects_reupload():
    nm = NoiseModel(depolarizing_p=0.1, circuit_level=True)
    try:
        make_vqc_classifier(3, encoding="reupload", noise_model=nm)
        assert False, "expected ValueError"
    except ValueError as e:
        assert "circuit-level" in str(e)


def test_twenty_qubit_forward():
    """BASELINE config-5 scale: a 20-qubit VQC forward on one (virtual)
    device — 2×4 MB state, real-pair engine. One sample, one layer."""
    model = make_vqc_classifier(20, n_layers=1, num_classes=2)
    params = model.init(jax.random.PRNGKey(0))
    x = jnp.linspace(0.05, 0.95, 20).reshape(1, 20)
    logits = model.apply(params, x)
    assert logits.shape == (1, 2)
    assert np.isfinite(np.asarray(logits)).all()
