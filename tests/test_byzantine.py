"""Byzantine-robust aggregation pins (r12 tentpole).

The contracts the robust-aggregation layer stands on, in the shape of
tests/test_robust_round.py's matrix:

(a) **Defense off ≡ r11, bit for bit.** ``aggregator="mean"`` IS the
    r11 program, and ``clip_mean`` at ``clip_bound=inf`` compiles no
    clip ops (the ``min_participation=0`` idiom), so the two builds are
    the SAME program — pinned bit-identical across the secure-agg × DP
    matrix and across the wave/survivor composition.
(b) **clip_mean bounds an attacker.** A ``scale:k`` adversary moves θ
    under plain mean; under a finite bound its influence collapses to
    ≈ one honest update, ``clipped_clients`` counts it exactly, and the
    bound composes with ring masks (the mask joins AFTER the clip).
(c) **trimmed_mean/median reject outliers per client** (masks off) —
    the attacked robust round lands within noise of the attack-free
    robust round while plain mean is dragged away.
(d) **The hierarchy bounds a captured WAVE.** Robust rules combine
    ACROSS per-wave partials (``make_apply_partials``), so a fully
    byzantine wave is trimmed even when secure-agg masking hides its
    per-client structure — with the pair graph restricted per wave
    (each wave's lr=0 partial is pure mask dust on its own).

Shapes tiny (3 qubits, 1 layer, 16 clients) — tier-1 budget.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from qfedx_tpu.fed.config import DPConfig, FedConfig
from qfedx_tpu.fed.robust import (
    resolve_aggregator,
    robust_combine,
    trimmed_fraction_stat,
)
from qfedx_tpu.fed.round import (
    client_mesh,
    make_apply_partials,
    make_fed_round,
    make_fed_round_partial,
    shard_client_data,
    stack_partials,
)
from qfedx_tpu.models.vqc import make_vqc_classifier

C, S, N_Q = 16, 4, 3


def _data(seed=0):
    rng = np.random.default_rng(seed)
    cx = rng.uniform(0, 1, (C, S, N_Q)).astype(np.float32)
    cy = (cx.mean(axis=2) > 0.5).astype(np.int32)
    cm = np.ones((C, S), dtype=np.float32)
    return cx, cy, cm


def _model():
    return make_vqc_classifier(n_qubits=N_Q, n_layers=1, num_classes=2)


def _cfg(**kw):
    base = dict(local_epochs=1, batch_size=4, learning_rate=0.1,
                optimizer="sgd", client_fraction=0.5)
    base.update(kw)
    return FedConfig(**base)


def _attack(scale_clients=(), scale=100.0, noise_clients=(), sigma=1.0):
    byz = np.zeros((C, 2), dtype=np.float32)
    byz[:, 0] = 1.0
    for c in scale_clients:
        byz[c, 0] = scale
    for c in noise_clients:
        byz[c, 1] = sigma
    return byz


def _maxdiff(a_tree, b_tree):
    return max(
        float(jnp.max(jnp.abs(jnp.asarray(a) - jnp.asarray(b))))
        for a, b in zip(jax.tree.leaves(a_tree), jax.tree.leaves(b_tree))
    )


def test_aggregator_pin_and_config_validation(monkeypatch):
    monkeypatch.delenv("QFEDX_AGG", raising=False)
    assert resolve_aggregator(_cfg()) == "mean"
    assert resolve_aggregator(_cfg(aggregator="median")) == "median"
    monkeypatch.setenv("QFEDX_AGG", "trimmed_mean")
    assert resolve_aggregator(_cfg()) == "trimmed_mean"  # pin overrides
    monkeypatch.setenv("QFEDX_AGG", "huber")
    with pytest.raises(ValueError, match="QFEDX_AGG"):
        resolve_aggregator(_cfg())
    monkeypatch.delenv("QFEDX_AGG", raising=False)
    with pytest.raises(ValueError, match="aggregator"):
        FedConfig(aggregator="krum")
    with pytest.raises(ValueError, match="clip_bound"):
        FedConfig(clip_bound=0.0)
    with pytest.raises(ValueError, match="trim_fraction"):
        FedConfig(trim_fraction=0.5)
    # flat round + robust rule + secure-agg = silent mean — rejected
    with pytest.raises(ValueError, match="per-client visibility"):
        make_fed_round(
            _model(), _cfg(aggregator="median", secure_agg=True),
            client_mesh(num_devices=4), num_clients=C,
        )
    # same hole at the hierarchy seam: ONE wave spanning the cohort has
    # no cross-wave level to defend at — rejected, not degenerated
    with pytest.raises(ValueError, match="WAVE level"):
        make_fed_round_partial(
            _model(), _cfg(aggregator="median", secure_agg=True),
            client_mesh(num_devices=4), wave_clients=C,
        )


def test_robust_combine_matches_numpy_oracle():
    """The sorting-network primitive against a numpy oracle, including
    absent contributors (the traced-m machinery must trim among the
    LIVE entries only)."""
    rng = np.random.default_rng(3)
    v = rng.normal(size=(8, 5)).astype(np.float32)
    present = np.array([1, 1, 0, 1, 1, 1, 0, 1], np.float32)
    live = v[present > 0]  # 6 contributors
    med, m, tf = robust_combine({"x": jnp.asarray(v)}, present, "median", 0.0)
    np.testing.assert_allclose(
        np.asarray(med["x"]), np.median(live, axis=0), atol=1e-6
    )
    assert float(m) == 6.0
    assert float(tf) == pytest.approx((6 - 2) / 6)
    tm, m2, tf2 = robust_combine(
        {"x": jnp.asarray(v)}, present, "trimmed_mean", 0.2
    )
    k = int(0.2 * 6)  # 1 per end
    oracle = np.mean(np.sort(live, axis=0)[k:6 - k], axis=0)
    np.testing.assert_allclose(np.asarray(tm["x"]), oracle, atol=1e-6)
    assert float(tf2) == pytest.approx(2 * k / 6)
    # m = 0 degenerates to zeros, not NaN
    z, m0, _ = robust_combine(
        {"x": jnp.asarray(v)}, np.zeros(8, np.float32), "median", 0.0
    )
    assert float(m0) == 0.0
    assert np.all(np.asarray(z["x"]) == 0.0)
    assert float(trimmed_fraction_stat("mean", 0.2, 6)) == 0.0


# (a) mean ≡ clip_mean(∞): the clip ops are elided at build time, so
# the two builds are the same program — bit-identical everywhere, SA
# and adam rows included (no compile-structure caveat applies when the
# programs are literally identical).
PARITY = [
    ("sgd_dp", dict(dp=DPConfig(clip_norm=1.0, noise_multiplier=0.5))),
    ("sgd_sa", dict(secure_agg=True, secure_agg_mode="ring")),
    ("adam_sa", dict(optimizer="adam", secure_agg=True,
                     secure_agg_mode="ring")),
]


@pytest.mark.parametrize("label,kw", PARITY, ids=[p[0] for p in PARITY])
def test_clip_inf_is_bitexact_mean(label, kw):
    model = _model()
    mesh = client_mesh(num_devices=4)
    cx, cy, cm = _data()
    params = model.init(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(7)
    scx, scy, scm = shard_client_data(mesh, cx, cy, jnp.asarray(cm))
    p_mean, s_mean = make_fed_round(
        model, _cfg(**kw), mesh, num_clients=C
    )(params, scx, scy, scm, key)
    p_clip, s_clip = make_fed_round(
        model, _cfg(**kw, aggregator="clip_mean"), mesh, num_clients=C
    )(params, scx, scy, scm, key)
    for a, b in zip(jax.tree.leaves(p_mean), jax.tree.leaves(p_clip)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    assert float(s_clip.clipped_clients) == 0.0
    assert float(s_clip.trimmed_fraction) == 0.0
    assert int(s_mean.num_participants) == int(s_clip.num_participants)


def test_clip_inf_bitexact_composes_with_waves_and_survivors():
    """(a) across the r10/r11 composition: 2-wave hierarchical round
    with secure-agg AND mid-round dropouts — clip_mean(∞) partials and
    apply reproduce the mean hierarchy bit for bit."""
    model = _model()
    mesh = client_mesh(num_devices=4)
    cx, cy, cm = _data(seed=3)
    params = model.init(jax.random.PRNGKey(1))
    key = jax.random.PRNGKey(9)
    surv = np.ones(C, dtype=np.float32)
    surv[[2, 11]] = 0.0

    def run(agg):
        cfg = _cfg(secure_agg=True, aggregator=agg)
        pf = make_fed_round_partial(
            model, cfg, mesh, wave_clients=C // 2, cohort_clients=C
        )
        parts = []
        for w in range(2):
            sl = slice(w * (C // 2), (w + 1) * (C // 2))
            wx, wy, wm = shard_client_data(
                mesh, cx[sl], cy[sl], jnp.asarray(cm[sl])
            )
            parts.append(pf(params, wx, wy, wm, np.int32(w * (C // 2)),
                            key, survivors=surv))
        return make_apply_partials(cfg, C)(params, stack_partials(parts))

    p_mean, s_mean = run("mean")
    p_clip, s_clip = run("clip_mean")
    for a, b in zip(jax.tree.leaves(p_mean), jax.tree.leaves(p_clip)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    assert int(s_mean.dropped_clients) == int(s_clip.dropped_clients)
    assert float(s_clip.clipped_clients) == 0.0


def test_clip_mean_bounds_attacker_with_exact_count():
    """(b): a scale:1000 attacker drags plain mean far from the clean
    round; a finite bound collapses its influence to ≈ one honest
    update and counts exactly one clipped client — with ring masks ON
    (the clip happens before the mask joins)."""
    model = _model()
    mesh = client_mesh(num_devices=4)
    cx, cy, cm = _data(seed=5)
    params = model.init(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(3)
    scx, scy, scm = shard_client_data(mesh, cx, cy, jnp.asarray(cm))
    byz = _attack(scale_clients=[6], scale=1000.0)
    cfg_mean = _cfg(client_fraction=1.0, secure_agg=True)
    fn_mean = make_fed_round(model, cfg_mean, mesh, num_clients=C)
    p_clean, _ = fn_mean(params, scx, scy, scm, key)
    p_att, _ = fn_mean(params, scx, scy, scm, key, byzantine=byz)
    d_undefended = _maxdiff(p_att, p_clean)
    fn_clip = make_fed_round(
        model,
        _cfg(client_fraction=1.0, secure_agg=True,
             aggregator="clip_mean", clip_bound=0.5),
        mesh, num_clients=C,
    )
    p_def, s_def = fn_clip(params, scx, scy, scm, key, byzantine=byz)
    d_defended = _maxdiff(p_def, p_clean)
    assert int(s_def.clipped_clients) == 1
    assert d_undefended > 0.5, d_undefended
    assert d_defended < 0.1, d_defended
    assert d_defended < d_undefended / 10
    # the attack input shape is validated loudly
    with pytest.raises(ValueError, match="byzantine"):
        fn_clip(params, scx, scy, scm, key,
                byzantine=np.ones((C,), np.float32))


@pytest.mark.parametrize("agg", ["trimmed_mean", "median"])
def test_robust_rules_reject_scale_attack_per_client(agg):
    """(c): masks off, the coordinate-wise rule excludes the attacker —
    the attacked robust round stays within noise of the attack-free
    robust round, while its distance under plain mean is large."""
    model = _model()
    mesh = client_mesh(num_devices=4)
    cx, cy, cm = _data(seed=8)
    params = model.init(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(6)
    scx, scy, scm = shard_client_data(mesh, cx, cy, jnp.asarray(cm))
    byz = _attack(scale_clients=[4], scale=1000.0)
    cfg = _cfg(client_fraction=1.0, aggregator=agg, trim_fraction=0.2)
    fn = make_fed_round(model, cfg, mesh, num_clients=C)
    p_clean, s_clean = fn(params, scx, scy, scm, key)
    p_att, s_att = fn(params, scx, scy, scm, key, byzantine=byz)
    assert _maxdiff(p_att, p_clean) < 0.05
    assert float(s_att.trimmed_fraction) > 0.0
    assert int(s_att.num_participants) == C
    # same attack through plain mean, for scale: it must hurt
    fn_mean = make_fed_round(
        model, _cfg(client_fraction=1.0), mesh, num_clients=C
    )
    p_mean_clean, _ = fn_mean(params, scx, scy, scm, key)
    p_mean_att, _ = fn_mean(params, scx, scy, scm, key, byzantine=byz)
    assert _maxdiff(p_mean_att, p_mean_clean) > 0.5


def test_hier_robust_bounds_fully_captured_wave():
    """(d): 4 waves under ring secure-agg, wave 1 entirely byzantine
    (scale:1000). Per-wave pair graphs keep each wave's partial clean;
    the cross-wave trimmed mean (trim_fraction 0.25 ⇒ 1 wave per end)
    discards the hostile wave — θ lands within noise of the clean run.
    The additive mean hierarchy under the same attack is dragged away."""
    model = _model()
    mesh = client_mesh(num_devices=4)
    cx, cy, cm = _data(seed=4)
    params = model.init(jax.random.PRNGKey(1))
    key = jax.random.PRNGKey(8)
    wc = C // 4
    byz = _attack(scale_clients=range(wc, 2 * wc), scale=1000.0)

    def run(agg, attack, secure=True):
        cfg = _cfg(client_fraction=1.0, secure_agg=secure, aggregator=agg,
                   trim_fraction=0.25)
        pf = make_fed_round_partial(
            model, cfg, mesh, wave_clients=wc, cohort_clients=C
        )
        parts = []
        for w in range(4):
            sl = slice(w * wc, (w + 1) * wc)
            wx, wy, wm = shard_client_data(
                mesh, cx[sl], cy[sl], jnp.asarray(cm[sl])
            )
            parts.append(pf(params, wx, wy, wm, np.int32(w * wc), key,
                            byzantine=attack))
        return make_apply_partials(cfg, C)(params, stack_partials(parts))

    p_clean, _ = run("trimmed_mean", None)
    p_def, s_def = run("trimmed_mean", byz)
    assert _maxdiff(p_def, p_clean) < 0.05
    assert float(s_def.trimmed_fraction) == pytest.approx(0.5)  # 2/4 waves
    p_mean_clean, _ = run("mean", None)
    p_mean_att, _ = run("mean", byz)
    assert _maxdiff(p_mean_att, p_mean_clean) > 0.5


def test_robust_sa_per_wave_masks_cancel():
    """The wave-restricted pair graph: at lr=0 EVERY wave's partial is
    pure mask dust on its own (cohort-graph masks would only cancel in
    the cross-wave sum — useless to a non-additive combine), and the
    stacked robust apply leaves θ within float dust."""
    model = _model()
    mesh = client_mesh(num_devices=4)
    cx, cy, cm = _data(seed=1)
    params = model.init(jax.random.PRNGKey(2))
    key = jax.random.PRNGKey(4)
    cfg = FedConfig(local_epochs=1, batch_size=4, learning_rate=0.0,
                    momentum=0.0, client_fraction=1.0, secure_agg=True,
                    aggregator="trimmed_mean", trim_fraction=0.25)
    wc = C // 4
    pf = make_fed_round_partial(
        model, cfg, mesh, wave_clients=wc, cohort_clients=C
    )
    parts = []
    for w in range(4):
        sl = slice(w * wc, (w + 1) * wc)
        wx, wy, wm = shard_client_data(
            mesh, cx[sl], cy[sl], jnp.asarray(cm[sl])
        )
        part = pf(params, wx, wy, wm, np.int32(w * wc), key)
        residual = max(
            float(jnp.max(jnp.abs(leaf)))
            for leaf in jax.tree.leaves(part.update_sum)
        )
        assert residual < 1e-5, f"wave {w} masks left {residual}"
        parts.append(part)
    p_new, stats = make_apply_partials(cfg, C)(
        params, stack_partials(parts)
    )
    assert _maxdiff(p_new, params) < 1e-5
    assert int(stats.num_participants) == C


def test_streamed_robust_defends_against_plan(tmp_path):
    """End-to-end through the streamed trainer: a client.byzantine plan
    (scale + label_flip attackers) under trimmed_mean + ring SA over 2
    waves completes, reports the aggregator ledger in metrics.jsonl
    rows, and keeps θ finite."""
    from qfedx_tpu.data.stream import ArrayRegistry
    from qfedx_tpu.run.trainer import train_federated_streamed
    from qfedx_tpu.utils.faults import FaultPlan

    cx, cy, cm = _data(seed=6)
    tx, ty = cx[:, 0, :], cy[:, 0]
    model = _model()
    cfg = FedConfig(local_epochs=1, batch_size=4, learning_rate=0.1,
                    secure_agg=True, aggregator="trimmed_mean",
                    trim_fraction=0.3)
    plan = FaultPlan(seed=2, rules=[
        {"site": "client.byzantine", "kind": "scale:1000", "clients": [3]},
        {"site": "client.byzantine", "kind": "label_flip", "clients": [9]},
    ])
    rows = []
    res = train_federated_streamed(
        model, cfg, ArrayRegistry(cx, cy, cm), tx, ty,
        cohort_size=C, wave_size=C // 4, num_rounds=2, seed=1,
        eval_every=3, mesh=client_mesh(num_devices=4), fault_plan=plan,
        on_round_end=lambda r, m: rows.append(m),
    )
    for leaf in jax.tree.leaves(res.params):
        assert np.all(np.isfinite(np.asarray(leaf)))
    assert len(rows) == 2
    for row in rows:
        assert row["aggregator"] == "trimmed_mean"
        # final combine = across 4 waves at trim 0.3 ⇒ 1 per end ⇒ 2/4
        assert row["trimmed_fraction"] == pytest.approx(0.5)
        assert "clipped_clients" not in row
    # robust + SA + a single wave is rejected loudly, not weakened
    with pytest.raises(ValueError, match="2 waves"):
        train_federated_streamed(
            model, cfg, ArrayRegistry(cx, cy, cm), tx, ty,
            cohort_size=C, wave_size=C, num_rounds=1, seed=1,
            mesh=client_mesh(num_devices=4),
        )
