"""Encoder demo entry point and preprocessed-data caching."""

import numpy as np

from qfedx_tpu.data.pipeline import Preprocessed
from qfedx_tpu.run.demo import run_demo


def test_run_demo(tmp_path):
    out = run_demo(out_dir=str(tmp_path), dataset="mnist")
    assert abs(out["amp_norm"] - 1.0) < 1e-5  # encoded state is normalized
    assert len(out["z"]) == 4 and all(-1 <= z <= 1 for z in out["z"])
    assert (tmp_path / "encoding_demo.png").stat().st_size > 0


def test_preprocessed_save_load_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    prep = Preprocessed(
        train=(rng.normal(size=(10, 4)).astype(np.float32), np.arange(10, dtype=np.int32) % 2),
        val=(rng.normal(size=(3, 4)).astype(np.float32), np.zeros(3, dtype=np.int32)),
        test=(rng.normal(size=(5, 4)).astype(np.float32), np.ones(5, dtype=np.int32)),
        num_classes=2,
    )
    path = tmp_path / "data.npz"
    prep.save(path)
    loaded = Preprocessed.load(path)
    assert loaded.num_classes == 2
    np.testing.assert_array_equal(loaded.train[0], prep.train[0])
    np.testing.assert_array_equal(loaded.test[1], prep.test[1])
