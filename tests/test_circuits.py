"""Encoders, ansatze, readout, and the parameter-shift ≡ jax.grad check
(the reference roadmap's own Phase-1 verification, ROADMAP.md:27)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from qfedx_tpu.circuits.ansatz import (
    data_reuploading,
    hardware_efficient,
    init_ansatz_params,
    init_reuploading_params,
)
from qfedx_tpu.circuits.encoders import amplitude_encode, angle_encode
from qfedx_tpu.circuits.gradients import param_shift_grad, param_shift_grad_pytree
from qfedx_tpu.circuits.readout import init_readout_params, z_logits
from qfedx_tpu.ops import gates
from qfedx_tpu.ops.cpx import to_complex
from qfedx_tpu.ops.statevector import apply_gate, expect_z, probabilities, zero_state


def test_angle_encode_matches_gate_application():
    feats = jnp.array([0.0, 0.25, 0.5, 1.0])
    state = angle_encode(feats)
    seq = zero_state(4)
    for q in range(4):
        seq = apply_gate(seq, gates.ry(feats[q] * jnp.pi), q)
    np.testing.assert_allclose(to_complex(state), to_complex(seq), atol=1e-6)
    # f=0 → |0⟩ (⟨Z⟩=1), f=1 → |1⟩ (⟨Z⟩=-1), f=0.5 → equator (⟨Z⟩=0)
    assert float(expect_z(state, 0)) == pytest.approx(1.0, abs=1e-6)
    assert float(expect_z(state, 3)) == pytest.approx(-1.0, abs=1e-6)
    assert float(expect_z(state, 2)) == pytest.approx(0.0, abs=1e-6)


def test_angle_encode_bases():
    feats = jnp.array([0.3, 0.7])
    for basis in ("rx", "ry", "rz"):
        state = angle_encode(feats, basis=basis)
        assert float(jnp.sum(probabilities(state))) == pytest.approx(1.0, abs=1e-6)


def test_amplitude_encode_normalizes():
    x = jnp.array([3.0, 0.0, 0.0, 4.0])
    state = amplitude_encode(x)
    np.testing.assert_allclose(
        to_complex(state).reshape(-1), [0.6, 0, 0, 0.8], atol=1e-6
    )


def test_amplitude_encode_zero_fallback_uniform():
    state = amplitude_encode(jnp.zeros(8))
    np.testing.assert_allclose(
        np.asarray(probabilities(state)), np.full(8, 1 / 8), atol=1e-6
    )


def test_amplitude_encode_rejects_non_power_of_two():
    with pytest.raises(ValueError):
        amplitude_encode(jnp.ones(6))


def test_amplitude_encode_vmaps():
    xs = jnp.eye(4)
    states = jax.vmap(amplitude_encode)(xs)
    assert states.shape == (4, 2, 2)


def test_hardware_efficient_unit_norm_and_entangles():
    key = jax.random.PRNGKey(0)
    params = init_ansatz_params(key, 4, 2, scale=1.0)
    state = hardware_efficient(angle_encode(jnp.array([0.1, 0.5, 0.9, 0.4])), params)
    assert float(jnp.sum(probabilities(state))) == pytest.approx(1.0, abs=1e-5)
    # Entangled in general: state should not factor as a product — check via
    # purity of the 1-qubit reduced density matrix < 1.
    full = to_complex(state).reshape(2, 8)
    rho = full @ full.conj().T
    purity = float(np.real(np.trace(rho @ rho)))
    assert purity < 0.999


def test_data_reuploading_runs_and_depends_on_input():
    key = jax.random.PRNGKey(1)
    params = init_reuploading_params(key, 3, 2)
    s1 = data_reuploading(jnp.array([0.1, 0.2, 0.3]), params)
    s2 = data_reuploading(jnp.array([0.9, 0.8, 0.7]), params)
    assert float(jnp.sum(probabilities(s1))) == pytest.approx(1.0, abs=1e-5)
    assert not np.allclose(to_complex(s1), to_complex(s2), atol=1e-3)


def test_readout_shapes_and_bounds():
    key = jax.random.PRNGKey(2)
    params = init_readout_params(key, 3)
    state = angle_encode(jnp.array([0.2, 0.5, 0.8, 0.1]))
    logits = z_logits(state, params)
    assert logits.shape == (3,)
    # with unit scale / zero bias, logits are ⟨Z⟩ ∈ [-1, 1]
    assert np.all(np.abs(np.asarray(logits)) <= 1.0 + 1e-6)


def test_readout_rejects_too_many_classes():
    params = init_readout_params(jax.random.PRNGKey(0), 5)
    with pytest.raises(ValueError):
        z_logits(angle_encode(jnp.array([0.1, 0.2])), params)


def _expectation_fn(n_qubits=3, n_layers=2):
    """⟨Z_0⟩ of an encoded + variational circuit as fn of flat params."""
    feats = jnp.array([0.15, 0.62, 0.87])

    def fn(params):
        state = hardware_efficient(angle_encode(feats), params)
        return expect_z(state, 0)

    params = init_ansatz_params(jax.random.PRNGKey(3), n_qubits, n_layers, scale=0.7)
    return fn, params


def test_parameter_shift_matches_jax_grad():
    """The Phase-1 check (ROADMAP.md:27): parameter-shift ≡ adjoint (here:
    reverse-mode AD through the simulator) within tolerance."""
    fn, params = _expectation_fn()
    ad_grad = jax.grad(fn)(params)
    ps_grad = param_shift_grad_pytree(fn, params)
    for k in ad_grad:
        np.testing.assert_allclose(
            np.asarray(ad_grad[k]), np.asarray(ps_grad[k]), atol=2e-4
        )


def test_parameter_shift_flat_vector():
    def fn(theta):
        state = zero_state(1)
        state = apply_gate(state, gates.ry(theta[0]), 0)
        return expect_z(state, 0)

    theta = jnp.array([0.4])
    # d/dθ cos(θ) = -sin(θ)
    got = param_shift_grad(fn, theta)
    np.testing.assert_allclose(np.asarray(got), [-np.sin(0.4)], atol=1e-5)


def test_grad_through_reuploading_circuit():
    feats = jnp.array([0.2, 0.6, 0.4])
    params = init_reuploading_params(jax.random.PRNGKey(4), 3, 2)

    def loss(p):
        return expect_z(data_reuploading(feats, p), 0)

    g = jax.grad(loss)(params)
    total = sum(float(jnp.sum(jnp.abs(v))) for v in jax.tree.leaves(g))
    assert np.isfinite(total) and total > 0


def test_remat_ansatz_matches_plain():
    """jax.checkpoint-per-layer (remat) must not change values or grads."""
    from qfedx_tpu.ops.statevector import expect_z_all

    n, layers = 5, 3
    params = init_ansatz_params(jax.random.PRNGKey(0), n, layers, scale=0.6)
    x = jnp.linspace(0.1, 0.9, n)

    def loss(p, remat):
        state = hardware_efficient(angle_encode(x), p, remat=remat)
        return jnp.sum(expect_z_all(state) * jnp.arange(1.0, n + 1))

    np.testing.assert_allclose(
        float(loss(params, False)), float(loss(params, True)), atol=1e-6
    )
    g0 = jax.grad(lambda p: loss(p, False))(params)
    g1 = jax.grad(lambda p: loss(p, True))(params)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_remat_reupload_matches_plain():
    """remat through the data-reuploading circuit: identical values/grads."""
    from qfedx_tpu.circuits.ansatz import data_reuploading, init_reuploading_params
    from qfedx_tpu.ops.statevector import expect_z_all

    n, layers = 4, 3
    params = init_reuploading_params(jax.random.PRNGKey(1), n, layers, scale=0.5)
    x = jnp.linspace(0.2, 0.8, n)

    def loss(p, remat):
        state = data_reuploading(x, p, remat=remat)
        return jnp.sum(expect_z_all(state) * jnp.arange(1.0, n + 1))

    np.testing.assert_allclose(
        float(loss(params, False)), float(loss(params, True)), atol=1e-6
    )
    g0 = jax.grad(lambda p: loss(p, False))(params)
    g1 = jax.grad(lambda p: loss(p, True))(params)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
