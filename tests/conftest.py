"""Test configuration: force an 8-device virtual CPU platform.

The reference simulates N federated clients in a single sequential process
(reference src/CFed/Classical_FL.py:132-140); our framework maps clients onto
a jax.sharding.Mesh axis. To test multi-chip semantics without TPU hardware,
we force 8 host (CPU) devices — the same SPMD code then runs hostside
(SURVEY.md §4: the TPU-native analog of the roadmap's "simulate N clients on
one machine").

This module must run before jax is imported anywhere in the test process.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_enable_x64", False)
