"""Test configuration: force an 8-device virtual CPU platform.

The reference simulates N federated clients in a single sequential process
(reference src/CFed/Classical_FL.py:132-140); our framework maps clients onto
a jax.sharding.Mesh axis. To test multi-chip semantics without TPU hardware,
we force 8 host (CPU) devices — the same SPMD code then runs hostside
(SURVEY.md §4: the TPU-native analog of the roadmap's "simulate N clients on
one machine").

Note: the environment may import jax at interpreter startup (sitecustomize)
with JAX_PLATFORMS pointing at a tunneled TPU, so setting env vars here can
be too late for the env-var path. The backend itself initializes lazily, so
``jax.config.update`` before first device use still selects the platform,
and XLA_FLAGS is read at backend init for the host device count.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

assert len(jax.devices()) == 8, (
    "tests require the 8-device virtual CPU platform; got "
    f"{jax.devices()} — was a backend already initialized before conftest?"
)


def free_port() -> int:
    """One shared ephemeral-port helper (gloo coordinators, telemetry
    servers — test_distributed, test_obs, test_serve)."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]
