"""Quantum-kernel head: Gram properties, training, federated harness ride."""

import jax
import jax.numpy as jnp
import numpy as np

from qfedx_tpu.fed.config import FedConfig
from qfedx_tpu.models.kernel import (
    init_landmarks_from_data,
    kernel_matrix,
    make_quantum_kernel_classifier,
)
from qfedx_tpu.run.trainer import train_federated


def test_kernel_matrix_properties():
    rng = np.random.default_rng(0)
    xs = jnp.asarray(rng.uniform(0, 1, (5, 3)), dtype=jnp.float32)
    k = kernel_matrix(xs, xs)
    k = np.asarray(k)
    np.testing.assert_allclose(k, k.T, atol=1e-5)  # symmetric
    np.testing.assert_allclose(np.diag(k), 1.0, atol=1e-5)  # k(x,x)=1
    assert (k >= -1e-6).all() and (k <= 1 + 1e-6).all()  # fidelity ∈ [0,1]


def test_kernel_distinguishes_points():
    a = jnp.asarray([[0.0, 0.0]], dtype=jnp.float32)
    b = jnp.asarray([[1.0, 1.0]], dtype=jnp.float32)
    cross = float(kernel_matrix(a, b)[0, 0])
    assert cross < 0.1  # RY(0)|0⟩ vs RY(π)|0⟩ are orthogonal per qubit


def test_model_shapes_and_landmark_seeding():
    model = make_quantum_kernel_classifier(4, n_landmarks=8, num_classes=3)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.uniform(0, 1, (10, 4)), dtype=jnp.float32)
    params = init_landmarks_from_data(params, x)
    np.testing.assert_allclose(np.asarray(params["landmarks"]), np.asarray(x[:8]))
    logits = model.apply(params, x)
    assert logits.shape == (10, 3)
    assert np.isfinite(np.asarray(logits)).all()


def test_kernel_model_trains_federated():
    """The kernel head rides the same SPMD FedAvg harness as the VQC."""
    n_qubits, clients, samples = 3, 4, 16
    rng = np.random.default_rng(2)
    # Separable synthetic task: class = x[0] > 0.5.
    cx = rng.uniform(0, 1, (clients, samples, n_qubits)).astype(np.float32)
    cy = (cx[..., 0] > 0.5).astype(np.int32)
    cm = np.ones((clients, samples), dtype=np.float32)
    tx = rng.uniform(0, 1, (64, n_qubits)).astype(np.float32)
    ty = (tx[:, 0] > 0.5).astype(np.int32)

    model = make_quantum_kernel_classifier(n_qubits, n_landmarks=8, num_classes=2)
    cfg = FedConfig(local_epochs=2, batch_size=8, learning_rate=0.2, optimizer="adam")
    res = train_federated(model, cfg, cx, cy, cm, tx, ty, num_rounds=10)
    assert res.final_accuracy > 0.8, res.accuracies


def test_closed_form_kernel_matches_dense_oracle():
    """Product-state fidelity factorization ≡ explicit-statevector Gram
    matrix, both bases, including x == y diagonal (K=1)."""
    from qfedx_tpu.models.kernel import kernel_matrix, kernel_matrix_dense

    rng = np.random.default_rng(0)
    xs = jnp.asarray(rng.uniform(0, 1, (5, 6)), dtype=jnp.float32)
    ys = jnp.asarray(rng.uniform(0, 1, (3, 6)), dtype=jnp.float32)
    for basis in ("ry", "rx"):
        got = kernel_matrix(xs, ys, basis)
        want = kernel_matrix_dense(xs, ys, basis)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)
    self_k = kernel_matrix(xs, xs)
    np.testing.assert_allclose(np.diag(np.asarray(self_k)), 1.0, atol=1e-6)


def test_kernel_head_at_20_qubits():
    """Config-5 width (20 qubits) is O(n) through the closed form — no
    statevector, instant on any backend."""
    from qfedx_tpu.models.kernel import make_quantum_kernel_classifier

    model = make_quantum_kernel_classifier(20, n_landmarks=8, num_classes=2)
    params = model.init(jax.random.PRNGKey(0))
    x = jnp.asarray(
        np.random.default_rng(1).uniform(0, 1, (16, 20)), dtype=jnp.float32
    )
    logits = model.apply(params, x)
    assert logits.shape == (16, 2)
    assert np.all(np.isfinite(np.asarray(logits)))
    g = jax.grad(lambda p: jnp.sum(model.apply(p, x) ** 2))(params)
    assert all(np.all(np.isfinite(np.asarray(l))) for l in jax.tree.leaves(g))
