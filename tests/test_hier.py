"""Hierarchical (partial/apply) vs flat aggregation parity (r10).

The r10 tentpole splits the round program into per-wave
``RoundPartial``s combined across waves (fed/round.py). These tests pin
the two contracts the hierarchy stands on:

1. **Same structure ⇒ same bits.** A 1-wave partial + apply IS the flat
   round computed in two dispatches; results match the one-program
   round bit-for-bit across the SA × DP × dtype matrix.
2. **Split waves ⇒ documented tolerance.** A W-wave round sums the same
   per-client contributions in a different order, so parity is
   float-accumulation-tight (≤ ~1e-5) — EXCEPT that XLA:CPU compiles
   the adam local-update numerics slightly differently when the
   secure-agg subcomputation is present in a structurally different
   program (measured ~2e-4/round drift even with masks scaled to ZERO,
   i.e. it is compile-structure sensitivity of adam's rsqrt path, not
   mask residue; see the calibration test). Adam+SA rows therefore pin
   at 5e-3.

Mask cancellation across the hierarchy is pinned directly: with
learning_rate=0 every client's delta is exactly 0, so the accumulated
``update_sum`` IS the sum of all ring masks — required ~0 for every
wave split, including waves whose ring neighbors live in other waves.

Shapes are deliberately tiny (3 qubits, 1 layer, 16 clients): tier-1
runs under a hard wall-clock budget and this file sits mid-alphabet.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from qfedx_tpu.fed.config import DPConfig, FedConfig
from qfedx_tpu.fed.round import (
    client_mesh,
    hier_enabled,
    make_accumulate_partial,
    make_apply_partial,
    make_fed_round,
    make_fed_round_partial,
    shard_client_data,
)
from qfedx_tpu.models.vqc import make_vqc_classifier

C, S, N_Q = 16, 4, 3


def _data(seed=0):
    rng = np.random.default_rng(seed)
    cx = rng.uniform(0, 1, (C, S, N_Q)).astype(np.float32)
    cy = (cx.mean(axis=2) > 0.5).astype(np.int32)
    cm = np.ones((C, S), dtype=np.float32)
    return cx, cy, cm


def _model():
    return make_vqc_classifier(n_qubits=N_Q, n_layers=1, num_classes=2)


def _run_flat(model, cfg, mesh, cx, cy, cm, params, key):
    fn = make_fed_round(model, cfg, mesh, num_clients=C)
    scx, scy, scm = shard_client_data(mesh, cx, cy, jnp.asarray(cm))
    return fn(params, scx, scy, scm, key)


def _run_waves(model, cfg, mesh, cx, cy, cm, params, key, num_waves):
    wc = C // num_waves
    pf = make_fed_round_partial(
        model, cfg, mesh, wave_clients=wc, cohort_clients=C
    )
    accum = make_accumulate_partial()
    acc = None
    for w in range(num_waves):
        sl = slice(w * wc, (w + 1) * wc)
        wx, wy, wm = shard_client_data(
            mesh, cx[sl], cy[sl], jnp.asarray(cm[sl])
        )
        part = pf(params, wx, wy, wm, np.int32(w * wc), key)
        acc = part if acc is None else accum(acc, part)
    return make_apply_partial()(params, acc), acc


# The parity matrix: every privacy composition the round supports, both
# dtypes the engine runs. sgd rows are float-accumulation-tight; the
# adam+SA row documents the XLA:CPU compile-structure tolerance (module
# docstring — the drift persists with secure_agg_scale=0, so it is not
# mask residue).
MATRIX = [
    # (label, secure_agg, dp, optimizer, dtype, waves, atol)
    ("plain_f32", False, None, "sgd", None, 4, 2e-5),
    ("sa_f32", True, None, "sgd", None, 4, 2e-5),
    ("dp_f32", False, "client", "sgd", None, 2, 2e-5),
    ("sa_dp_f32", True, "client", "sgd", None, 4, 2e-5),
    ("plain_bf16", False, None, "sgd", "bf16", 2, 5e-4),
    ("sa_bf16", True, None, "sgd", "bf16", 2, 5e-4),
    ("sa_adam_f32", True, None, "adam", None, 4, 5e-3),
]


@pytest.mark.parametrize(
    "label,sa,dp,opt,dtype,waves,atol",
    MATRIX,
    ids=[m[0] for m in MATRIX],
)
def test_wave_split_matches_flat(
    monkeypatch, label, sa, dp, opt, dtype, waves, atol
):
    if dtype is not None:
        monkeypatch.setenv("QFEDX_DTYPE", dtype)
    cfg = FedConfig(
        local_epochs=1,
        batch_size=4,
        learning_rate=0.1,
        optimizer=opt,
        client_fraction=0.5,
        secure_agg=sa,
        secure_agg_mode="ring",
        dp=DPConfig(clip_norm=1.0, noise_multiplier=0.5, mode=dp)
        if dp
        else None,
    )
    model = _model()
    mesh = client_mesh(num_devices=4)
    cx, cy, cm = _data()
    params = model.init(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(7)

    p_flat, s_flat = _run_flat(model, cfg, mesh, cx, cy, cm, params, key)
    (p_h, s_h), _ = _run_waves(
        model, cfg, mesh, cx, cy, cm, params, key, num_waves=waves
    )
    for a, b in zip(jax.tree.leaves(p_flat), jax.tree.leaves(p_h)):
        np.testing.assert_allclose(
            np.asarray(a, dtype=np.float32),
            np.asarray(b, dtype=np.float32),
            atol=atol,
            rtol=0,
        )
    # The hierarchy must not change WHO participated or the total weight:
    # these are integer-/count-valued and exact under any wave split.
    assert int(s_h.num_participants) == int(s_flat.num_participants)
    np.testing.assert_allclose(
        float(s_h.total_weight), float(s_flat.total_weight), rtol=1e-6
    )


def test_one_wave_is_bitexact_flat():
    """Same program structure ⇒ same bits: partial(whole cohort) + apply
    reproduces the one-program flat round exactly, including SA + DP."""
    cfg = FedConfig(
        local_epochs=1,
        batch_size=4,
        learning_rate=0.1,
        optimizer="adam",
        client_fraction=0.6,
        secure_agg=True,
        secure_agg_mode="ring",
        dp=DPConfig(clip_norm=1.0, noise_multiplier=0.5),
    )
    model = _model()
    mesh = client_mesh(num_devices=4)
    cx, cy, cm = _data(seed=3)
    params = model.init(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(9)
    p_flat, s_flat = _run_flat(model, cfg, mesh, cx, cy, cm, params, key)
    (p_h, s_h), _ = _run_waves(
        model, cfg, mesh, cx, cy, cm, params, key, num_waves=1
    )
    for a, b in zip(jax.tree.leaves(p_flat), jax.tree.leaves(p_h)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    assert float(s_h.mean_loss) == float(s_flat.mean_loss)


@pytest.mark.parametrize("waves", [1, 2, 4])
def test_ring_masks_cancel_across_waves(waves):
    """With lr=0 every delta is exactly 0, so the accumulated update_sum
    is the sum of all secure-agg ring masks over the cohort — which must
    cancel to float dust even when a client's ring neighbors live in
    OTHER waves (the hierarchy-wide cancellation the tentpole needs)."""
    cfg = FedConfig(
        local_epochs=1,
        batch_size=4,
        learning_rate=0.0,
        optimizer="sgd",
        momentum=0.0,
        client_fraction=0.5,
        secure_agg=True,
        secure_agg_mode="ring",
    )
    model = _model()
    mesh = client_mesh(num_devices=4)
    cx, cy, cm = _data(seed=1)
    params = model.init(jax.random.PRNGKey(2))
    key = jax.random.PRNGKey(4)
    _, acc = _run_waves(
        model, cfg, mesh, cx, cy, cm, params, key, num_waves=waves
    )
    residual = max(
        float(jnp.max(jnp.abs(leaf)))
        for leaf in jax.tree.leaves(acc.update_sum)
    )
    assert residual < 1e-5, f"ring masks left {residual} across {waves} waves"


def test_partials_are_additive():
    """partial(cohort positions A ∪ B) ≈ partial(A) + partial(B): the
    accumulation the streamed trainer performs is exactly wave-sum
    associativity (sgd keeps the comparison float-tight)."""
    cfg = FedConfig(
        local_epochs=1, batch_size=4, learning_rate=0.1, optimizer="sgd",
        secure_agg=True, secure_agg_mode="ring",
    )
    model = _model()
    mesh = client_mesh(num_devices=4)
    cx, cy, cm = _data(seed=5)
    params = model.init(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(1)
    pf8 = make_fed_round_partial(
        model, cfg, mesh, wave_clients=8, cohort_clients=C
    )
    pf4 = make_fed_round_partial(
        model, cfg, mesh, wave_clients=4, cohort_clients=C
    )
    accum = make_accumulate_partial()
    wx, wy, wm = shard_client_data(mesh, cx[:8], cy[:8], jnp.asarray(cm[:8]))
    whole = pf8(params, wx, wy, wm, np.int32(0), key)
    halves = []
    for w in range(2):
        sl = slice(w * 4, (w + 1) * 4)
        hx, hy, hm = shard_client_data(
            mesh, cx[sl], cy[sl], jnp.asarray(cm[sl])
        )
        halves.append(pf4(params, hx, hy, hm, np.int32(w * 4), key))
    summed = accum(halves[0], halves[1])
    for a, b in zip(
        jax.tree.leaves(whole.update_sum), jax.tree.leaves(summed.update_sum)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5, rtol=0
        )
    assert float(whole.weight_sum) == float(summed.weight_sum)


def test_hier_pin_parses(monkeypatch):
    monkeypatch.setenv("QFEDX_HIER", "off")
    assert hier_enabled() is False
    monkeypatch.setenv("QFEDX_HIER", "1")
    assert hier_enabled() is True
    monkeypatch.delenv("QFEDX_HIER", raising=False)
    assert hier_enabled() is True
    monkeypatch.setenv("QFEDX_HIER", "maybe")
    with pytest.raises(ValueError):
        hier_enabled()
