"""Checkpointing, metrics logging, and experiment-run artifacts.

The subsystems the reference specifies but never builds: checkpoint θ every
K rounds with resume (reference ROADMAP.md:90-91) and experiment tracking
(reference ROADMAP.md:92-93) — exercised here including trainer-level
resume.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from qfedx_tpu.fed.config import FedConfig
from qfedx_tpu.models.vqc import make_vqc_classifier
from qfedx_tpu.run.checkpoint import Checkpointer
from qfedx_tpu.run.metrics import ExperimentRun, MetricsLogger
from qfedx_tpu.run.trainer import train_federated


def small_params(seed=0):
    k = jax.random.PRNGKey(seed)
    return {
        "a": jax.random.normal(k, (3, 2)),
        "nested": {"b": jnp.arange(4, dtype=jnp.float32)},
    }


def test_save_restore_roundtrip(tmp_path):
    ck = Checkpointer(tmp_path, every=1)
    params = small_params()
    ck.save(7, params)
    template = jax.tree.map(jnp.zeros_like, params)
    restored, rnd = ck.restore_latest(template)
    assert rnd == 7
    for got, want in zip(jax.tree.leaves(restored), jax.tree.leaves(params)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))


def test_maybe_save_cadence_and_gc(tmp_path):
    ck = Checkpointer(tmp_path, every=2, keep=2)
    params = small_params()
    saved = [r for r in range(1, 9) if ck.maybe_save(r, params) is not None]
    assert saved == [2, 4, 6, 8]
    assert sorted(ck._rounds()) == [6, 8]  # older ones garbage-collected


def test_restore_latest_empty(tmp_path):
    assert Checkpointer(tmp_path).restore_latest(small_params()) is None


def test_restore_shape_mismatch_fails(tmp_path):
    ck = Checkpointer(tmp_path)
    ck.save(1, small_params())
    bad_template = {"a": jnp.zeros((5, 5)), "nested": {"b": jnp.zeros(4)}}
    with pytest.raises(ValueError, match="shape"):
        ck.restore(1, bad_template)


def test_metrics_logger_jsonl(tmp_path):
    path = tmp_path / "m.jsonl"
    with MetricsLogger(path) as log:
        log.log({"round": 1, "acc": jnp.asarray(0.5)})
        log.log({"round": 2, "acc": np.float32(0.75)})
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert [l["round"] for l in lines] == [1, 2]
    assert lines[1]["acc"] == pytest.approx(0.75)
    assert all("ts" in l for l in lines)


def test_metrics_schema_round_trip_validates(tmp_path):
    """The r15 schema contract: every logged row carries
    ``"schema": METRICS_SCHEMA_VERSION``; reading the file back through
    ``validate_metrics_record`` round-trips cleanly, and a field-name
    drift (missing round, wrong version) fails LOUDLY naming the field
    — so the live /healthz endpoint (which reports the same version)
    and the JSONL file can never silently disagree."""
    from qfedx_tpu.run.metrics import (
        METRICS_SCHEMA_VERSION,
        validate_metrics_record,
    )

    path = tmp_path / "m.jsonl"
    logged = [
        {"round": 1, "loss": 0.5, "accuracy": 0.9},
        {"round": 2, "loss": 0.4, "epsilon": 1.25, "dropped_clients": 2},
    ]
    with MetricsLogger(path) as log:
        for rec in logged:
            log.log(rec)
    rows = [
        validate_metrics_record(json.loads(l))
        for l in path.read_text().splitlines()
    ]
    for rec, row in zip(logged, rows):
        assert row["schema"] == METRICS_SCHEMA_VERSION
        for k, v in rec.items():  # every logged field survives verbatim
            assert row[k] == pytest.approx(v)
    # drift fails loudly, naming the offender
    with pytest.raises(ValueError, match="round"):
        validate_metrics_record({"schema": METRICS_SCHEMA_VERSION, "ts": 1.0})
    with pytest.raises(ValueError, match="schema"):
        validate_metrics_record({"schema": 99, "round": 1, "ts": 1.0})
    with pytest.raises(ValueError, match="round"):
        validate_metrics_record(
            {"schema": METRICS_SCHEMA_VERSION, "round": "one", "ts": 1.0}
        )
    # an explicit schema in the record wins (forward-written files)
    with MetricsLogger(tmp_path / "m2.jsonl") as log:
        log.log({"round": 1, "schema": METRICS_SCHEMA_VERSION})
    row = json.loads((tmp_path / "m2.jsonl").read_text())
    assert row["schema"] == METRICS_SCHEMA_VERSION


def test_killed_writer_leaves_whole_json_lines(tmp_path):
    """The crash-safety claim, enforced: a writer dying WITHOUT close()
    or interpreter shutdown (os._exit skips flush/atexit — the OOM-kill/
    SIGKILL shape) must leave every logged record as a complete JSON
    line. Buffered writes silently break this (records sat in the
    process buffer); MetricsLogger flushes + fsyncs per append."""
    import subprocess
    import sys

    path = tmp_path / "m.jsonl"
    code = (
        "import os\n"
        "from qfedx_tpu.run.metrics import MetricsLogger\n"
        f"log = MetricsLogger({str(path)!r})\n"
        "for i in range(3):\n"
        "    log.log({'round': i + 1, 'loss': 0.5})\n"
        "os._exit(1)\n"
    )
    proc = subprocess.run([sys.executable, "-c", code], timeout=120)
    assert proc.returncode == 1
    lines = path.read_text().splitlines()
    assert [json.loads(l)["round"] for l in lines] == [1, 2, 3]


def test_async_checkpointer_roundtrip_and_cadence(tmp_path):
    """save_async + wait ≡ save: same files, same restore; the async
    cadence helper fires on the same every-K schedule as maybe_save."""
    ck = Checkpointer(tmp_path, every=2, keep=3)
    params = small_params()
    queued = [r for r in range(1, 7) if ck.maybe_save_async(r, params)]
    ck.wait()
    assert queued == [2, 4, 6]
    assert sorted(ck._rounds()) == [2, 4, 6]
    restored, rnd = ck.restore_latest(jax.tree.map(jnp.zeros_like, params))
    assert rnd == 6
    for got, want in zip(jax.tree.leaves(restored), jax.tree.leaves(params)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))


def test_async_writer_error_surfaces_on_wait(tmp_path, monkeypatch):
    """A writer-thread failure must not vanish: wait() re-raises it —
    since r11 as the typed CheckpointWriteError (retries exhausted),
    with the root cause in the message, on ``.original`` and chained."""
    from qfedx_tpu.run.checkpoint import CheckpointWriteError

    ck = Checkpointer(tmp_path, every=1)

    def boom(*a, **k):
        raise OSError("disk full")

    monkeypatch.setattr(np, "savez", boom)
    ck.save_async(1, small_params())
    with pytest.raises(CheckpointWriteError, match="disk full") as ei:
        ck.wait()
    assert isinstance(ei.value.original, OSError)
    assert ei.value.round_idx == 1
    # The error is consumed — the writer is reusable afterwards.
    monkeypatch.undo()
    ck.save_async(2, small_params())
    ck.wait()
    assert ck.latest_round() == 2


def test_async_writer_retries_transient_failures(tmp_path, monkeypatch):
    """One flaky write (fails twice, then the filesystem recovers) must
    land on disk via the shared retry policy — no error surfaces."""
    calls = {"n": 0}
    real_savez = np.savez

    def flaky(*a, **k):
        calls["n"] += 1
        if calls["n"] <= 2:
            raise OSError("transient stall")
        return real_savez(*a, **k)

    monkeypatch.setattr(np, "savez", flaky)
    ck = Checkpointer(tmp_path, every=1)
    ck.save_async(1, small_params())
    ck.wait()  # no raise
    assert calls["n"] == 3
    assert ck.latest_round() == 1


def test_async_writer_injected_fault_recovers(tmp_path, monkeypatch):
    """The checkpoint.write fault site (QFEDX_FAULTS): a ``times: 1``
    rule fails the first attempt of round 1's write; the retry recovers
    and the checkpoint still lands."""
    import json

    monkeypatch.setenv("QFEDX_FAULTS", json.dumps({"seed": 0, "rules": [
        {"site": "checkpoint.write", "rounds": [1], "times": 1},
    ]}))
    ck = Checkpointer(tmp_path, every=1)
    ck.save_async(1, small_params())
    ck.wait()
    assert ck.latest_round() == 1


def test_async_writer_error_suppressed_on_unwind_is_returned(
    tmp_path, monkeypatch
):
    """wait(raise_errors=False) — the trainer's crash-unwind path — must
    not silently erase a writer failure: the suppressed error is
    returned (the trainer attaches it to the propagating exception)."""
    ck = Checkpointer(tmp_path, every=1)

    def boom(*a, **k):
        raise OSError("disk full")

    monkeypatch.setattr(np, "savez", boom)
    ck.save_async(1, small_params())
    err = ck.wait(raise_errors=False)
    from qfedx_tpu.run.checkpoint import CheckpointWriteError

    assert isinstance(err, CheckpointWriteError)
    assert isinstance(err.original, OSError)
    assert ck.wait(raise_errors=False) is None  # consumed exactly once


def test_crash_unwind_surfaces_pending_writer_error(tmp_path, monkeypatch):
    """A failed async write followed by an unrelated crash: the writer
    error must ride along on the propagating exception (add_note on
    3.11+, __context__ chaining on 3.10) instead of vanishing — the
    operator must learn the on-disk checkpoint predates the crash."""
    model, cx, cy, cm, tx, ty = _toy_training_setup()
    cfg = FedConfig(local_epochs=1, batch_size=4, learning_rate=0.1,
                    optimizer="adam")
    ck = Checkpointer(tmp_path, every=2)

    def boom(*a, **k):
        raise OSError("disk full")

    monkeypatch.setattr(np, "savez", boom)

    class Crash(RuntimeError):
        pass

    def die_at_3(rnd, metrics):
        if rnd + 1 == 3:  # after round 2's async write has failed
            # Crash from inside an except block: the crash arrives with
            # its __context__ already occupied — the writer error must
            # still surface (appended to the END of the chain on 3.10).
            try:
                raise KeyError("inner")
            except KeyError:
                raise Crash()

    with pytest.warns(RuntimeWarning, match="checkpoint"):
        with pytest.raises(Crash) as ei:
            train_federated(
                model, cfg, cx, cy, cm, tx, ty, num_rounds=5,
                pipeline_depth=1, checkpointer=ck, on_round_end=die_at_3,
            )
    exc = ei.value
    notes = getattr(exc, "__notes__", [])
    chain, seen = [], set()
    while exc is not None and id(exc) not in seen:
        chain.append(exc)
        seen.add(id(exc))
        exc = exc.__context__
    assert any("checkpoint" in n for n in notes) or any(
        isinstance(e, OSError) for e in chain
    )


def test_async_checkpoint_killed_mid_write_never_corrupts_latest(tmp_path):
    """The async sibling of the killed-metrics-writer test: a checkpoint
    write killed MID-FILE (partial tmp bytes, then os._exit — the
    OOM-kill/SIGKILL shape, no atexit, no flush) must never leave a
    corrupt latest checkpoint. Atomic tmp+rename guarantees the
    interrupted round simply does not exist; the prior round restores."""
    import subprocess
    import sys

    code = (
        "import os\n"
        "import numpy as np\n"
        "from qfedx_tpu.run.checkpoint import Checkpointer\n"
        "params = {'a': np.arange(6.0, dtype=np.float32).reshape(3, 2)}\n"
        f"ck = Checkpointer({str(tmp_path)!r}, every=1)\n"
        "ck.save(1, params)\n"
        "def partial_then_die(f, *arrs):\n"
        "    f.write(b'corrupt partial npz bytes')\n"
        "    f.flush()\n"
        "    os._exit(1)\n"
        "np.savez = partial_then_die\n"
        "ck.save_async(2, params)\n"
        "ck.wait()\n"
        "os._exit(0)\n"  # unreachable: the writer thread kills the process
    )
    proc = subprocess.run([sys.executable, "-c", code], timeout=240)
    assert proc.returncode == 1
    ck = Checkpointer(tmp_path, every=1)
    assert ck.latest_round() == 1  # round 2 never became visible
    assert not (tmp_path / "ckpt_000002.npz").exists()
    template = {"a": jnp.zeros((3, 2))}
    restored, rnd = ck.restore_latest(template)
    assert rnd == 1
    np.testing.assert_allclose(
        np.asarray(restored["a"]),
        np.arange(6.0, dtype=np.float32).reshape(3, 2),
    )


def test_compile_cache_pin_matrix(monkeypatch, tmp_path):
    """QFEDX_COMPILE_CACHE resolution: off/on/path, loud on typos (the
    QFEDX_* pin convention — a typoed off value must not silently
    measure the cached path)."""
    from qfedx_tpu.utils.cache import compile_cache_dir

    monkeypatch.delenv("QFEDX_COMPILE_CACHE", raising=False)
    default = str(tmp_path / "default")
    assert compile_cache_dir(default) == default
    for off in ("0", "off", "OFF"):
        monkeypatch.setenv("QFEDX_COMPILE_CACHE", off)
        assert compile_cache_dir(default) is None
    for on in ("1", "on", "ON"):
        monkeypatch.setenv("QFEDX_COMPILE_CACHE", on)
        assert compile_cache_dir(default) == default
    monkeypatch.setenv("QFEDX_COMPILE_CACHE", str(tmp_path / "redirect"))
    assert compile_cache_dir(default) == str(tmp_path / "redirect")
    monkeypatch.setenv("QFEDX_COMPILE_CACHE", "~/xla")
    assert compile_cache_dir(default).endswith("/xla")
    for typo in ("0ff", "false", "no", "xla_cache"):
        monkeypatch.setenv("QFEDX_COMPILE_CACHE", typo)
        with pytest.raises(ValueError, match="QFEDX_COMPILE_CACHE"):
            compile_cache_dir(default)


def test_trainer_async_final_round_durable(tmp_path):
    """Pipelined trainer + async writer: the FINAL round's save is
    synchronous by contract — after train_federated returns, the last
    round is on disk (even off the every-K cadence) and restores to the
    exact returned params."""
    model, cx, cy, cm, tx, ty = _toy_training_setup()
    cfg = FedConfig(local_epochs=1, batch_size=4, learning_rate=0.1,
                    optimizer="adam")
    ck = Checkpointer(tmp_path, every=2)
    res = train_federated(
        model, cfg, cx, cy, cm, tx, ty, num_rounds=3, pipeline_depth=1,
        checkpointer=ck,
    )
    assert ck.latest_round() == 3  # 3 is off the every-2 cadence
    restored, _ = ck.restore_latest(jax.tree.map(jnp.zeros_like, res.params))
    for got, want in zip(
        jax.tree.leaves(restored), jax.tree.leaves(res.params)
    ):
        assert np.array_equal(np.asarray(got), np.asarray(want))


def test_agreed_run_dir_name_matrix(tmp_path):
    """Single-process resume/collide matrix of the run-dir naming rule
    (the multi-host broadcast path shares the collide semantics; its
    agreement protocol is exercised by the distributed test)."""
    import re

    from qfedx_tpu.run.metrics import _agreed_run_dir_name

    # Fresh name: used as-is whether or not this is a resume.
    assert _agreed_run_dir_name(tmp_path, "exp", False) == "exp"
    assert _agreed_run_dir_name(tmp_path, "exp", True) == "exp"
    (tmp_path / "exp").mkdir()
    # Collision + resume: reuse the existing dir (checkpoints live there).
    assert _agreed_run_dir_name(tmp_path, "exp", True) == "exp"
    # Collision + fresh run: timestamp-suffixed sibling, never the original.
    stamped = _agreed_run_dir_name(tmp_path, "exp", False)
    assert re.fullmatch(r"exp-\d{8}-\d{6}", stamped)


def test_experiment_run_collision_and_resume_dirs(tmp_path):
    cfg = FedConfig(local_epochs=1, batch_size=4)
    with ExperimentRun(tmp_path, "dup", config=cfg) as r1:
        r1.finish()
    with ExperimentRun(tmp_path, "dup", config=cfg) as r2:
        r2.finish()
    assert r2.dir != r1.dir  # fresh run never clobbers the old artifacts
    assert r1.dir.exists() and r2.dir.exists()
    assert (r1.dir / "summary.json").exists()
    with ExperimentRun(tmp_path, "dup", config=cfg, resume=True) as r3:
        pass
    assert r3.dir == r1.dir  # resume goes back to the ORIGINAL name


def test_experiment_run_artifacts(tmp_path):
    cfg = FedConfig(local_epochs=1, batch_size=4)
    with ExperimentRun(tmp_path, "exp", config=cfg) as run:
        run.on_round_end(0, {"loss": 1.0})
        run.finish(final_accuracy=0.9)
    assert json.loads((run.dir / "config.json").read_text())["batch_size"] == 4
    assert json.loads((run.dir / "summary.json").read_text())["final_accuracy"] == 0.9
    assert len((run.dir / "metrics.jsonl").read_text().splitlines()) == 1


def _toy_training_setup(n_qubits=2, clients=4, samples=8, seed=0):
    model = make_vqc_classifier(n_qubits=n_qubits, n_layers=1, num_classes=2)
    rng = np.random.default_rng(seed)
    cx = rng.uniform(0, 1, (clients, samples, n_qubits)).astype(np.float32)
    cy = rng.integers(0, 2, (clients, samples)).astype(np.int32)
    cm = np.ones((clients, samples), dtype=np.float32)
    tx = rng.uniform(0, 1, (16, n_qubits)).astype(np.float32)
    ty = rng.integers(0, 2, 16).astype(np.int32)
    return model, cx, cy, cm, tx, ty


def test_trainer_checkpoint_resume(tmp_path):
    """Round-K checkpointing + resume through the real trainer loop."""
    model, cx, cy, cm, tx, ty = _toy_training_setup()
    cfg = FedConfig(local_epochs=1, batch_size=4, learning_rate=0.1, optimizer="adam")

    ck = Checkpointer(tmp_path, every=1)
    res1 = train_federated(
        model, cfg, cx, cy, cm, tx, ty, num_rounds=2, checkpointer=ck
    )
    assert ck.latest_round() == 2

    # Resume: a fresh call with the same checkpointer starts at round 2 and
    # runs only the remaining round.
    res2 = train_federated(
        model, cfg, cx, cy, cm, tx, ty, num_rounds=3, checkpointer=ck
    )
    assert ck.latest_round() == 3
    assert len(res2.round_times_s) == 1  # only round 3 executed


class _SimulatedCrash(RuntimeError):
    pass


def test_crash_mid_run_resumes_bit_exactly(tmp_path):
    """Fault injection (reference ROADMAP.md:90-91): the process dies
    mid-loop; a fresh process resuming from the checkpoint must land on
    BIT-IDENTICAL final params and the same ε as an uninterrupted run —
    round keys are derived by fold-in from the seed, so the trajectory is
    reproducible, and restore must not perturb a single bit."""
    from qfedx_tpu.fed.config import DPConfig

    model, cx, cy, cm, tx, ty = _toy_training_setup()
    cfg = FedConfig(
        local_epochs=1, batch_size=4, learning_rate=0.1, optimizer="adam",
        dp=DPConfig(clip_norm=1.0, noise_multiplier=0.5),
    )

    # Uninterrupted reference run: 5 rounds.
    ref = train_federated(
        model, cfg, cx, cy, cm, tx, ty, num_rounds=5, seed=11,
        checkpointer=Checkpointer(tmp_path / "ref", every=1),
    )

    # Crashing run: killed by an injected exception after round 3's
    # checkpoint hits disk (on_round_end fires after maybe_save).
    ck = Checkpointer(tmp_path / "crash", every=1)

    def die_at_3(rnd, metrics):
        if rnd + 1 == 3:
            raise _SimulatedCrash()

    with pytest.raises(_SimulatedCrash):
        train_federated(
            model, cfg, cx, cy, cm, tx, ty, num_rounds=5, seed=11,
            checkpointer=ck, on_round_end=die_at_3,
        )
    assert ck.latest_round() == 3

    # Fresh "process": same config+seed, resumes at round 3, finishes 4-5.
    res = train_federated(
        model, cfg, cx, cy, cm, tx, ty, num_rounds=5, seed=11, checkpointer=ck
    )
    assert len(res.round_times_s) == 2  # only rounds 4 and 5 ran
    for got, want in zip(jax.tree.leaves(res.params), jax.tree.leaves(ref.params)):
        assert np.array_equal(np.asarray(got), np.asarray(want)), "params diverged"
    # ε accounting replays the checkpointed rounds: final ε identical.
    assert res.epsilons[-1] == pytest.approx(ref.epsilons[-1], rel=1e-12)


def test_client_dropout_mid_run_continues(tmp_path):
    """Fault injection (reference ROADMAP.md:90-91 "continue despite client
    dropouts"): a client's data mask zeroes mid-run — later rounds must
    keep training on the survivors, with the weight totals reflecting the
    loss and params staying finite."""
    from qfedx_tpu.fed.round import client_mesh, make_fed_round

    model, cx, cy, cm, tx, ty = _toy_training_setup()
    cfg = FedConfig(local_epochs=1, batch_size=4, learning_rate=0.1, momentum=0.0)
    mesh = client_mesh(num_devices=4)
    round_fn = make_fed_round(model, cfg, mesh, num_clients=4)
    params = model.init(jax.random.PRNGKey(0))

    for rnd in range(3):
        params, stats = round_fn(
            params, cx, cy, jnp.asarray(cm), jax.random.PRNGKey(rnd)
        )
    assert float(stats.total_weight) == pytest.approx(4 * 8)

    cm_dropped = cm.copy()
    cm_dropped[1] = 0.0  # client 1 dies between rounds
    for rnd in range(3, 6):
        params, stats = round_fn(
            params, cx, cy, jnp.asarray(cm_dropped), jax.random.PRNGKey(rnd)
        )
    assert float(stats.total_weight) == pytest.approx(3 * 8)
    assert float(stats.num_participants) == 4  # sampled, but one is empty
    assert all(np.isfinite(np.asarray(x)).all() for x in jax.tree.leaves(params))


# --- checkpoint integrity: sha256 sidecar + last-good fallback (r13) --------


def test_checkpoint_sha_sidecar_written_and_verified(tmp_path):
    from qfedx_tpu.run.checkpoint import CheckpointIntegrityError

    ck = Checkpointer(tmp_path, every=1, keep=3)
    ck.save(2, small_params())
    sha_path = tmp_path / "ckpt_000002.sha256"
    assert sha_path.exists()
    ck.verify(2)  # clean checkpoint passes
    # flip bytes INSIDE the npz (not a truncation — the parse might
    # even survive it; the sha must not)
    npz = tmp_path / "ckpt_000002.npz"
    raw = bytearray(npz.read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    npz.write_bytes(bytes(raw))
    with pytest.raises(CheckpointIntegrityError, match="sha256 mismatch"):
        ck.verify(2)
    # explicit restore of a named round is LOUD, never silent fallback
    with pytest.raises(CheckpointIntegrityError):
        ck.restore(2, jax.tree.map(jnp.zeros_like, small_params()))


def test_restore_latest_falls_back_to_last_good(tmp_path):
    """The r13 satellite headline: a torn/corrupt newest checkpoint
    costs one checkpoint interval, not the run — restore_latest warns,
    skips it, and restores the previous last-good file."""
    params = small_params()
    ck = Checkpointer(tmp_path, every=1, keep=3)
    ck.save(2, params)
    newer = jax.tree.map(lambda x: x + 1.0, params)
    ck.save(4, newer)
    # corrupt the NEWEST checkpoint (torn write / bit rot shape)
    npz = tmp_path / "ckpt_000004.npz"
    npz.write_bytes(npz.read_bytes()[: npz.stat().st_size // 2])
    template = jax.tree.map(jnp.zeros_like, params)
    with pytest.warns(RuntimeWarning, match="falling back"):
        restored, rnd = Checkpointer(tmp_path, every=1).restore_latest(
            template
        )
    assert rnd == 2
    for got, want in zip(jax.tree.leaves(restored), jax.tree.leaves(params)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))
    # every checkpoint corrupt -> clean None (fresh start), not a crash
    npz2 = tmp_path / "ckpt_000002.npz"
    npz2.write_bytes(b"not an npz at all")
    with pytest.warns(RuntimeWarning):
        assert Checkpointer(tmp_path, every=1).restore_latest(template) is None


def test_checkpoint_without_sidecar_is_legacy_ok(tmp_path):
    """Pre-r13 checkpoints carry no sha sidecar: they restore (no sha
    to check) and a TORN legacy file still triggers the fallback via
    the parse-failure path."""
    params = small_params()
    ck = Checkpointer(tmp_path, every=1)
    ck.save(3, params)
    (tmp_path / "ckpt_000003.sha256").unlink()
    restored, rnd = ck.restore_latest(jax.tree.map(jnp.zeros_like, params))
    assert rnd == 3
    # torn legacy file (no sidecar): unreadable npz -> skipped with a warning
    ck.save(5, params)
    (tmp_path / "ckpt_000005.sha256").unlink()
    npz = tmp_path / "ckpt_000005.npz"
    npz.write_bytes(npz.read_bytes()[:40])
    with pytest.warns(RuntimeWarning, match="falling back"):
        _, rnd = ck.restore_latest(jax.tree.map(jnp.zeros_like, params))
    assert rnd == 3


def test_write_fault_keeps_previous_last_good(tmp_path, monkeypatch):
    """Exercised via the existing checkpoint.write fault site: a
    persistently failing round-4 write surfaces as the suppressed
    async-writer error, and resume verifies + restores the round-2
    last-good checkpoint untouched."""
    import warnings

    params = small_params()
    ck = Checkpointer(tmp_path, every=2)
    ck.save(2, params)
    monkeypatch.setenv(
        "QFEDX_FAULTS",
        json.dumps({"seed": 1, "rules": [
            {"site": "checkpoint.write", "rounds": [4]},
        ]}),
    )
    ck.save_async(4, jax.tree.map(lambda x: x + 1.0, params))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        err = ck.wait(raise_errors=False)
    assert err is not None
    monkeypatch.delenv("QFEDX_FAULTS")
    restored, rnd = ck.restore_latest(jax.tree.map(jnp.zeros_like, params))
    assert rnd == 2
    ck.verify(2)
    for got, want in zip(jax.tree.leaves(restored), jax.tree.leaves(params)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))


def test_resave_crash_window_never_pairs_new_bytes_with_old_sidecar(
    tmp_path, monkeypatch
):
    """Review regression (r13): re-saving an already-checkpointed round
    (the graceful-shutdown path does) and dying between the npz rename
    and the sidecar write must leave new-bytes + NO sidecar (legacy-
    tolerated) — never new bytes beside the previous save's stale hash,
    which would reject a perfectly good checkpoint on resume."""
    import qfedx_tpu.run.checkpoint as cp

    params_v1 = small_params(0)
    params_v2 = jax.tree.map(lambda x: x + 1.0, params_v1)
    ck = Checkpointer(tmp_path, every=1)
    ck.save(2, params_v1)

    real_replace = cp.os.replace

    def die_on_sidecar(src, dst, **kw):
        if str(dst).endswith(".sha256"):
            raise RuntimeError("killed between renames")
        return real_replace(src, dst, **kw)

    monkeypatch.setattr(cp.os, "replace", die_on_sidecar)
    with pytest.raises(RuntimeError, match="killed"):
        ck.save(2, params_v2)
    monkeypatch.undo()
    # the stale v1 sidecar is GONE; the v2 npz verifies (legacy path)
    assert not (tmp_path / "ckpt_000002.sha256").exists()
    ck.verify(2)
    restored, rnd = Checkpointer(tmp_path, every=1).restore_latest(
        jax.tree.map(jnp.zeros_like, params_v1)
    )
    assert rnd == 2
    for got, want in zip(
        jax.tree.leaves(restored), jax.tree.leaves(params_v2)
    ):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))


def test_busy_reports_inflight_async_writes(tmp_path):
    """The interrupt path's race guard: busy() is True while a queued
    async write has not hit disk, False after wait() drains it."""
    import threading

    ck = Checkpointer(tmp_path, every=1)
    assert ck.busy() is False
    gate = threading.Event()
    real_save = ck.save

    def slow_save(r, p):
        gate.wait(timeout=10.0)
        return real_save(r, p)

    ck.save = slow_save
    ck.save_async(3, small_params())
    assert ck.busy() is True  # writer blocked behind the gate
    gate.set()
    ck.wait()
    assert ck.busy() is False
    ck.save = real_save
    assert (tmp_path / "ckpt_000003.npz").exists()
