"""Checkpointing, metrics logging, and experiment-run artifacts.

The subsystems the reference specifies but never builds: checkpoint θ every
K rounds with resume (reference ROADMAP.md:90-91) and experiment tracking
(reference ROADMAP.md:92-93) — exercised here including trainer-level
resume.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from qfedx_tpu.fed.config import FedConfig
from qfedx_tpu.models.vqc import make_vqc_classifier
from qfedx_tpu.run.checkpoint import Checkpointer
from qfedx_tpu.run.metrics import ExperimentRun, MetricsLogger
from qfedx_tpu.run.trainer import train_federated


def small_params(seed=0):
    k = jax.random.PRNGKey(seed)
    return {
        "a": jax.random.normal(k, (3, 2)),
        "nested": {"b": jnp.arange(4, dtype=jnp.float32)},
    }


def test_save_restore_roundtrip(tmp_path):
    ck = Checkpointer(tmp_path, every=1)
    params = small_params()
    ck.save(7, params)
    template = jax.tree.map(jnp.zeros_like, params)
    restored, rnd = ck.restore_latest(template)
    assert rnd == 7
    for got, want in zip(jax.tree.leaves(restored), jax.tree.leaves(params)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))


def test_maybe_save_cadence_and_gc(tmp_path):
    ck = Checkpointer(tmp_path, every=2, keep=2)
    params = small_params()
    saved = [r for r in range(1, 9) if ck.maybe_save(r, params) is not None]
    assert saved == [2, 4, 6, 8]
    assert sorted(ck._rounds()) == [6, 8]  # older ones garbage-collected


def test_restore_latest_empty(tmp_path):
    assert Checkpointer(tmp_path).restore_latest(small_params()) is None


def test_restore_shape_mismatch_fails(tmp_path):
    ck = Checkpointer(tmp_path)
    ck.save(1, small_params())
    bad_template = {"a": jnp.zeros((5, 5)), "nested": {"b": jnp.zeros(4)}}
    with pytest.raises(ValueError, match="shape"):
        ck.restore(1, bad_template)


def test_metrics_logger_jsonl(tmp_path):
    path = tmp_path / "m.jsonl"
    with MetricsLogger(path) as log:
        log.log({"round": 1, "acc": jnp.asarray(0.5)})
        log.log({"round": 2, "acc": np.float32(0.75)})
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert [l["round"] for l in lines] == [1, 2]
    assert lines[1]["acc"] == pytest.approx(0.75)
    assert all("ts" in l for l in lines)


def test_experiment_run_artifacts(tmp_path):
    cfg = FedConfig(local_epochs=1, batch_size=4)
    with ExperimentRun(tmp_path, "exp", config=cfg) as run:
        run.on_round_end(0, {"loss": 1.0})
        run.finish(final_accuracy=0.9)
    assert json.loads((run.dir / "config.json").read_text())["batch_size"] == 4
    assert json.loads((run.dir / "summary.json").read_text())["final_accuracy"] == 0.9
    assert len((run.dir / "metrics.jsonl").read_text().splitlines()) == 1


def test_trainer_checkpoint_resume(tmp_path):
    """Round-K checkpointing + resume through the real trainer loop."""
    n_qubits, clients, samples = 2, 4, 8
    model = make_vqc_classifier(n_qubits=n_qubits, n_layers=1, num_classes=2)
    cfg = FedConfig(local_epochs=1, batch_size=4, learning_rate=0.1, optimizer="adam")
    rng = np.random.default_rng(0)
    cx = rng.uniform(0, 1, (clients, samples, n_qubits)).astype(np.float32)
    cy = rng.integers(0, 2, (clients, samples)).astype(np.int32)
    cm = np.ones((clients, samples), dtype=np.float32)
    tx = rng.uniform(0, 1, (16, n_qubits)).astype(np.float32)
    ty = rng.integers(0, 2, 16).astype(np.int32)

    ck = Checkpointer(tmp_path, every=1)
    res1 = train_federated(
        model, cfg, cx, cy, cm, tx, ty, num_rounds=2, checkpointer=ck
    )
    assert ck.latest_round() == 2

    # Resume: a fresh call with the same checkpointer starts at round 2 and
    # runs only the remaining round.
    res2 = train_federated(
        model, cfg, cx, cy, cm, tx, ty, num_rounds=3, checkpointer=ck
    )
    assert ck.latest_round() == 3
    assert len(res2.round_times_s) == 1  # only round 3 executed
