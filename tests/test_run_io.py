"""Checkpointing, metrics logging, and experiment-run artifacts.

The subsystems the reference specifies but never builds: checkpoint θ every
K rounds with resume (reference ROADMAP.md:90-91) and experiment tracking
(reference ROADMAP.md:92-93) — exercised here including trainer-level
resume.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from qfedx_tpu.fed.config import FedConfig
from qfedx_tpu.models.vqc import make_vqc_classifier
from qfedx_tpu.run.checkpoint import Checkpointer
from qfedx_tpu.run.metrics import ExperimentRun, MetricsLogger
from qfedx_tpu.run.trainer import train_federated


def small_params(seed=0):
    k = jax.random.PRNGKey(seed)
    return {
        "a": jax.random.normal(k, (3, 2)),
        "nested": {"b": jnp.arange(4, dtype=jnp.float32)},
    }


def test_save_restore_roundtrip(tmp_path):
    ck = Checkpointer(tmp_path, every=1)
    params = small_params()
    ck.save(7, params)
    template = jax.tree.map(jnp.zeros_like, params)
    restored, rnd = ck.restore_latest(template)
    assert rnd == 7
    for got, want in zip(jax.tree.leaves(restored), jax.tree.leaves(params)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))


def test_maybe_save_cadence_and_gc(tmp_path):
    ck = Checkpointer(tmp_path, every=2, keep=2)
    params = small_params()
    saved = [r for r in range(1, 9) if ck.maybe_save(r, params) is not None]
    assert saved == [2, 4, 6, 8]
    assert sorted(ck._rounds()) == [6, 8]  # older ones garbage-collected


def test_restore_latest_empty(tmp_path):
    assert Checkpointer(tmp_path).restore_latest(small_params()) is None


def test_restore_shape_mismatch_fails(tmp_path):
    ck = Checkpointer(tmp_path)
    ck.save(1, small_params())
    bad_template = {"a": jnp.zeros((5, 5)), "nested": {"b": jnp.zeros(4)}}
    with pytest.raises(ValueError, match="shape"):
        ck.restore(1, bad_template)


def test_metrics_logger_jsonl(tmp_path):
    path = tmp_path / "m.jsonl"
    with MetricsLogger(path) as log:
        log.log({"round": 1, "acc": jnp.asarray(0.5)})
        log.log({"round": 2, "acc": np.float32(0.75)})
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert [l["round"] for l in lines] == [1, 2]
    assert lines[1]["acc"] == pytest.approx(0.75)
    assert all("ts" in l for l in lines)


def test_killed_writer_leaves_whole_json_lines(tmp_path):
    """The crash-safety claim, enforced: a writer dying WITHOUT close()
    or interpreter shutdown (os._exit skips flush/atexit — the OOM-kill/
    SIGKILL shape) must leave every logged record as a complete JSON
    line. Buffered writes silently break this (records sat in the
    process buffer); MetricsLogger flushes + fsyncs per append."""
    import subprocess
    import sys

    path = tmp_path / "m.jsonl"
    code = (
        "import os\n"
        "from qfedx_tpu.run.metrics import MetricsLogger\n"
        f"log = MetricsLogger({str(path)!r})\n"
        "for i in range(3):\n"
        "    log.log({'round': i + 1, 'loss': 0.5})\n"
        "os._exit(1)\n"
    )
    proc = subprocess.run([sys.executable, "-c", code], timeout=120)
    assert proc.returncode == 1
    lines = path.read_text().splitlines()
    assert [json.loads(l)["round"] for l in lines] == [1, 2, 3]


def test_agreed_run_dir_name_matrix(tmp_path):
    """Single-process resume/collide matrix of the run-dir naming rule
    (the multi-host broadcast path shares the collide semantics; its
    agreement protocol is exercised by the distributed test)."""
    import re

    from qfedx_tpu.run.metrics import _agreed_run_dir_name

    # Fresh name: used as-is whether or not this is a resume.
    assert _agreed_run_dir_name(tmp_path, "exp", False) == "exp"
    assert _agreed_run_dir_name(tmp_path, "exp", True) == "exp"
    (tmp_path / "exp").mkdir()
    # Collision + resume: reuse the existing dir (checkpoints live there).
    assert _agreed_run_dir_name(tmp_path, "exp", True) == "exp"
    # Collision + fresh run: timestamp-suffixed sibling, never the original.
    stamped = _agreed_run_dir_name(tmp_path, "exp", False)
    assert re.fullmatch(r"exp-\d{8}-\d{6}", stamped)


def test_experiment_run_collision_and_resume_dirs(tmp_path):
    cfg = FedConfig(local_epochs=1, batch_size=4)
    with ExperimentRun(tmp_path, "dup", config=cfg) as r1:
        r1.finish()
    with ExperimentRun(tmp_path, "dup", config=cfg) as r2:
        r2.finish()
    assert r2.dir != r1.dir  # fresh run never clobbers the old artifacts
    assert r1.dir.exists() and r2.dir.exists()
    assert (r1.dir / "summary.json").exists()
    with ExperimentRun(tmp_path, "dup", config=cfg, resume=True) as r3:
        pass
    assert r3.dir == r1.dir  # resume goes back to the ORIGINAL name


def test_experiment_run_artifacts(tmp_path):
    cfg = FedConfig(local_epochs=1, batch_size=4)
    with ExperimentRun(tmp_path, "exp", config=cfg) as run:
        run.on_round_end(0, {"loss": 1.0})
        run.finish(final_accuracy=0.9)
    assert json.loads((run.dir / "config.json").read_text())["batch_size"] == 4
    assert json.loads((run.dir / "summary.json").read_text())["final_accuracy"] == 0.9
    assert len((run.dir / "metrics.jsonl").read_text().splitlines()) == 1


def _toy_training_setup(n_qubits=2, clients=4, samples=8, seed=0):
    model = make_vqc_classifier(n_qubits=n_qubits, n_layers=1, num_classes=2)
    rng = np.random.default_rng(seed)
    cx = rng.uniform(0, 1, (clients, samples, n_qubits)).astype(np.float32)
    cy = rng.integers(0, 2, (clients, samples)).astype(np.int32)
    cm = np.ones((clients, samples), dtype=np.float32)
    tx = rng.uniform(0, 1, (16, n_qubits)).astype(np.float32)
    ty = rng.integers(0, 2, 16).astype(np.int32)
    return model, cx, cy, cm, tx, ty


def test_trainer_checkpoint_resume(tmp_path):
    """Round-K checkpointing + resume through the real trainer loop."""
    model, cx, cy, cm, tx, ty = _toy_training_setup()
    cfg = FedConfig(local_epochs=1, batch_size=4, learning_rate=0.1, optimizer="adam")

    ck = Checkpointer(tmp_path, every=1)
    res1 = train_federated(
        model, cfg, cx, cy, cm, tx, ty, num_rounds=2, checkpointer=ck
    )
    assert ck.latest_round() == 2

    # Resume: a fresh call with the same checkpointer starts at round 2 and
    # runs only the remaining round.
    res2 = train_federated(
        model, cfg, cx, cy, cm, tx, ty, num_rounds=3, checkpointer=ck
    )
    assert ck.latest_round() == 3
    assert len(res2.round_times_s) == 1  # only round 3 executed


class _SimulatedCrash(RuntimeError):
    pass


def test_crash_mid_run_resumes_bit_exactly(tmp_path):
    """Fault injection (reference ROADMAP.md:90-91): the process dies
    mid-loop; a fresh process resuming from the checkpoint must land on
    BIT-IDENTICAL final params and the same ε as an uninterrupted run —
    round keys are derived by fold-in from the seed, so the trajectory is
    reproducible, and restore must not perturb a single bit."""
    from qfedx_tpu.fed.config import DPConfig

    model, cx, cy, cm, tx, ty = _toy_training_setup()
    cfg = FedConfig(
        local_epochs=1, batch_size=4, learning_rate=0.1, optimizer="adam",
        dp=DPConfig(clip_norm=1.0, noise_multiplier=0.5),
    )

    # Uninterrupted reference run: 5 rounds.
    ref = train_federated(
        model, cfg, cx, cy, cm, tx, ty, num_rounds=5, seed=11,
        checkpointer=Checkpointer(tmp_path / "ref", every=1),
    )

    # Crashing run: killed by an injected exception after round 3's
    # checkpoint hits disk (on_round_end fires after maybe_save).
    ck = Checkpointer(tmp_path / "crash", every=1)

    def die_at_3(rnd, metrics):
        if rnd + 1 == 3:
            raise _SimulatedCrash()

    with pytest.raises(_SimulatedCrash):
        train_federated(
            model, cfg, cx, cy, cm, tx, ty, num_rounds=5, seed=11,
            checkpointer=ck, on_round_end=die_at_3,
        )
    assert ck.latest_round() == 3

    # Fresh "process": same config+seed, resumes at round 3, finishes 4-5.
    res = train_federated(
        model, cfg, cx, cy, cm, tx, ty, num_rounds=5, seed=11, checkpointer=ck
    )
    assert len(res.round_times_s) == 2  # only rounds 4 and 5 ran
    for got, want in zip(jax.tree.leaves(res.params), jax.tree.leaves(ref.params)):
        assert np.array_equal(np.asarray(got), np.asarray(want)), "params diverged"
    # ε accounting replays the checkpointed rounds: final ε identical.
    assert res.epsilons[-1] == pytest.approx(ref.epsilons[-1], rel=1e-12)


def test_client_dropout_mid_run_continues(tmp_path):
    """Fault injection (reference ROADMAP.md:90-91 "continue despite client
    dropouts"): a client's data mask zeroes mid-run — later rounds must
    keep training on the survivors, with the weight totals reflecting the
    loss and params staying finite."""
    from qfedx_tpu.fed.round import client_mesh, make_fed_round

    model, cx, cy, cm, tx, ty = _toy_training_setup()
    cfg = FedConfig(local_epochs=1, batch_size=4, learning_rate=0.1, momentum=0.0)
    mesh = client_mesh(num_devices=4)
    round_fn = make_fed_round(model, cfg, mesh, num_clients=4)
    params = model.init(jax.random.PRNGKey(0))

    for rnd in range(3):
        params, stats = round_fn(
            params, cx, cy, jnp.asarray(cm), jax.random.PRNGKey(rnd)
        )
    assert float(stats.total_weight) == pytest.approx(4 * 8)

    cm_dropped = cm.copy()
    cm_dropped[1] = 0.0  # client 1 dies between rounds
    for rnd in range(3, 6):
        params, stats = round_fn(
            params, cx, cy, jnp.asarray(cm_dropped), jax.random.PRNGKey(rnd)
        )
    assert float(stats.total_weight) == pytest.approx(3 * 8)
    assert float(stats.num_participants) == 4  # sampled, but one is empty
    assert all(np.isfinite(np.asarray(x)).all() for x in jax.tree.leaves(params))
