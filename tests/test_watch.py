"""The r20 detection layer: SLO watchdog, flight recorder, bench ledger.

Three contracts pinned here:

1. **Active detection end-to-end** — a live-scraped `/healthz` flips
   200 → 503 while an injected serve fault drives a watchdog rule over
   threshold, NAMES the firing rule in the payload, and recovers to 200
   when the fault clears; the alert counts reconcile EXACTLY against
   the FaultPlan's deterministic replay (the same oracle discipline the
   serve ledger tests use).
2. **The black box** — a SIGTERM'd run (the in-process utils/host
   translation, the test_stream idiom) leaves a parseable,
   size-bounded `flight.json` behind with default pins otherwise.
3. **Default-off invariance** — with the pins unset there is no ticker
   thread, no ring, no file, and `evaluate_once` is a `[]` no-op.

(The `qfedx bench history` regression-ledger tests live in
tests/test_bench_ledger.py — pure host-side, no backend.)
"""

import json
import os
import signal as signal_mod
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from qfedx_tpu import obs
from qfedx_tpu.obs import flight, watch
from qfedx_tpu.obs import server as obs_server
from qfedx_tpu.serve.batcher import MicroBatcher, RequestError
from qfedx_tpu.serve.engine import ServeConfig, ServeEngine
from qfedx_tpu.utils.faults import FaultPlan


@pytest.fixture(autouse=True)
def _clean_detection_state():
    obs_server.stop_server()  # a failed test must not leak its server
    obs.reset()
    watch.reset()
    flight.reset()
    yield
    obs_server.stop_server()
    watch.reset()
    flight.reset()
    obs.reset()


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return r.status, r.read().decode()


def _engine(buckets=(2,), max_queue=8):
    import jax

    from qfedx_tpu.models.vqc import make_vqc_classifier

    model = make_vqc_classifier(n_qubits=4, n_layers=1, num_classes=2)
    params = model.init(jax.random.PRNGKey(0))
    cfg = ServeConfig(
        buckets=buckets, deadline_ms=50.0, max_queue=max_queue
    )
    return ServeEngine(model, params, (4,), config=cfg)


def _rows(m, seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((m, 4)).astype(np.float32)


# --- the tentpole: live 200 -> 503 -> 200 with an exact fault oracle ----------


def test_healthz_flips_on_injected_fault_and_recovers(
    monkeypatch, tmp_path
):
    """The acceptance path: watchdog on, serve fault injected, the live
    probe degrades naming `serve.shed_rate`, recovery restores 200, and
    every count reconciles against the FaultPlan replay."""
    plan_spec = {"seed": 3, "rules": [
        {"site": "serve.request", "kind": "nan", "rounds": [1, 3]},
    ]}
    monkeypatch.setenv("QFEDX_FAULTS", json.dumps(plan_spec))
    monkeypatch.setenv("QFEDX_WATCH", "1")

    from qfedx_tpu.run.metrics import ExperimentRun, validate_metrics_record

    srv = obs_server.start_server(0)
    engine = _engine(buckets=(2,))
    engine.warmup()
    try:
        with ExperimentRun(tmp_path, name="watchrun") as run:
            with MicroBatcher(engine) as b:
                assert watch.evaluate_once() == []  # baseline tick
                status, body = _get(srv.port, "/healthz")
                assert status == 200
                assert json.loads(body)["alerts"]["active"] == []

                rows = _rows(5)
                rejected = 0
                for i in range(5):
                    try:
                        b.submit(rows[i]).result(timeout=30)
                    except RequestError:
                        rejected += 1

                active = watch.evaluate_once()  # the detection tick
                assert [a["rule"] for a in active] == ["serve.shed_rate"]
                with pytest.raises(urllib.error.HTTPError) as exc_info:
                    _get(srv.port, "/healthz")
                assert exc_info.value.code == 503
                hz = json.loads(exc_info.value.read())
                assert hz["status"] == "degraded"
                assert [a["rule"] for a in hz["alerts"]["active"]] == [
                    "serve.shed_rate"
                ]

                active = watch.evaluate_once()  # quiet tick: delta 0
                assert active == []
                status, body = _get(srv.port, "/healthz")
                assert status == 200
                hz = json.loads(body)
                assert hz["status"] == "ok"
                assert hz["alerts"]["fired_total"] == {
                    "serve.shed_rate": 1
                }

        # The exact oracle: replay the SAME plan spec on a fresh
        # instance — the deterministic mutation schedule IS the
        # expected rejection ledger, not a >= smell test.
        replay = FaultPlan(**plan_spec)
        expected = sum(
            1 for seq in range(5)
            if replay.request_mutation(seq) is not None
        )
        assert expected == 2  # the fixture itself stays honest
        assert rejected == expected == b.stats["rejected"]
        reg = obs.registry()
        assert reg.counters["serve.requests_rejected"] == expected
        assert reg.counters["alert.fired.serve.shed_rate"] == 1
        assert reg.gauges["alert.serve.shed_rate"] == 0.0  # cleared

        # ...and the structured event rows landed in metrics.jsonl,
        # schema-valid, firing value == the replayed count.
        rows_logged = [
            validate_metrics_record(json.loads(line))
            for line in (run.dir / "metrics.jsonl").read_text().splitlines()
            if line.strip()
        ]
        alerts = [r for r in rows_logged if r.get("event") == "alert"]
        assert [(a["state"], a["rule"]) for a in alerts] == [
            ("firing", "serve.shed_rate"),
            ("cleared", "serve.shed_rate"),
        ]
        assert alerts[0]["value"] == float(expected)
    finally:
        obs_server.stop_server()


def test_trainer_stall_rule_fires_on_flush_age(monkeypatch):
    """The wedged-wave detector: a trainer health source reporting a
    stale last_flush_age_s trips `trainer.stall`; a fresh flush clears
    it."""
    monkeypatch.setenv("QFEDX_WATCH", "on")
    monkeypatch.setenv("QFEDX_WATCH_STALL_S", "60")
    age = {"v": 5.0}
    obs_server.set_health_source(
        "trainer", lambda: {"last_flush_age_s": age["v"]}
    )
    try:
        assert watch.evaluate_once() == []
        age["v"] = 120.0
        active = watch.evaluate_once()
        assert [a["rule"] for a in active] == ["trainer.stall"]
        assert active[0]["threshold"] == 60.0
        age["v"] = 1.0
        assert watch.evaluate_once() == []
        assert watch.fired_totals() == {"trainer.stall": 1}
    finally:
        obs_server.clear_health_source("trainer")


def test_loss_rule_nonfinite_always_fires(monkeypatch):
    monkeypatch.setenv("QFEDX_WATCH", "1")
    obs.gauge("fed.loss", 0.42)
    assert watch.evaluate_once() == []
    obs.gauge("fed.loss", float("nan"))
    active = watch.evaluate_once()
    assert [a["rule"] for a in active] == ["trainer.loss"]
    obs.gauge("fed.loss", 0.40)
    assert watch.evaluate_once() == []


def test_eps_burn_rule_gates_on_budget(monkeypatch):
    monkeypatch.setenv("QFEDX_WATCH", "1")
    obs.gauge("fed.epsilon", 7.5)
    assert watch.evaluate_once() == []  # inf budget by default
    monkeypatch.setenv("QFEDX_WATCH_EPS", "5.0")
    active = watch.evaluate_once()
    assert [a["rule"] for a in active] == ["trainer.eps_burn"]
    assert active[0]["value"] == 7.5 and active[0]["threshold"] == 5.0


def test_sick_rule_counts_check_error_not_ticker_death(monkeypatch):
    monkeypatch.setenv("QFEDX_WATCH", "1")
    monkeypatch.setenv("QFEDX_WATCH_STALL_S", "not-a-float")
    obs_server.set_health_source(
        "trainer", lambda: {"last_flush_age_s": 999.0}
    )
    try:
        assert watch.evaluate_once() == []  # sick rule quiet, not fatal
        assert (
            obs.registry().counters["alert.check_error.trainer.stall"] == 1
        )
    finally:
        obs_server.clear_health_source("trainer")


# --- pin grammar + default-off invariance -------------------------------------


def test_watch_pin_grammar(monkeypatch):
    for raw, want in (
        ("1", 1.0), ("on", 1.0), ("ON", 1.0), ("2.5", 2.5), ("0.25", 0.25),
        ("0", 0.0), ("off", 0.0),
    ):
        monkeypatch.setenv("QFEDX_WATCH", raw)
        assert watch.interval_s() == want
    monkeypatch.delenv("QFEDX_WATCH")
    assert watch.interval_s() == 0.0
    for bad in ("yes", "1s", "-2", "0x1"):
        monkeypatch.setenv("QFEDX_WATCH", bad)
        with pytest.raises(ValueError, match="QFEDX_WATCH"):
            watch.interval_s()


def test_watch_default_off_no_thread_no_eval(monkeypatch):
    import threading

    monkeypatch.delenv("QFEDX_WATCH", raising=False)
    assert not watch.enabled()
    assert watch.maybe_start() is False
    assert watch.evaluate_once() == []
    assert not any(
        t.name == "qfedx-watchdog" for t in threading.enumerate()
    )
    # and with the metrics port also unset, instruments stay no-ops
    monkeypatch.delenv("QFEDX_METRICS_PORT", raising=False)
    monkeypatch.delenv("QFEDX_TRACE", raising=False)
    obs.counter("serve.requests_shed", 3)
    assert obs.registry().counters == {}


def test_watch_ticker_runs_and_stops(monkeypatch):
    import time

    monkeypatch.setenv("QFEDX_WATCH", "0.01")
    assert watch.maybe_start() is True
    assert watch.maybe_start() is True  # idempotent
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if obs.registry().gauges.get("alert.serve.shed_rate") is not None:
            break
        time.sleep(0.01)
    else:
        pytest.fail("ticker never evaluated")
    watch.stop()
    import threading

    assert not any(
        t.name == "qfedx-watchdog" for t in threading.enumerate()
    )


def test_watch_implies_bounded_instruments(monkeypatch):
    monkeypatch.delenv("QFEDX_TRACE", raising=False)
    monkeypatch.setenv("QFEDX_WATCH", "1")
    assert obs.metrics_enabled()
    obs.counter("serve.requests_shed", 2)
    assert obs.registry().counters["serve.requests_shed"] == 2.0
    # spans stay gated on QFEDX_TRACE — unbounded state needs the pin
    with obs.span("round.dispatch"):
        pass
    assert obs.registry().spans == []


# --- the flight recorder ------------------------------------------------------


def test_flight_default_off_records_nothing(monkeypatch):
    monkeypatch.delenv("QFEDX_FLIGHT", raising=False)
    assert not flight.enabled()
    flight.record("lifecycle", "x", a=1)
    assert flight.events() == []
    assert flight.dump() is None  # nothing to dump, no file


def test_flight_ring_is_bounded(monkeypatch, tmp_path):
    monkeypatch.setenv("QFEDX_FLIGHT", "8")
    for i in range(20):
        flight.record("counter", f"c{i}", v=i)
    evs = flight.events()
    assert len(evs) == 8
    assert flight.dropped() == 12
    assert evs[-1]["name"] == "c19"  # newest kept, oldest shed
    path = flight.dump(tmp_path / "flight.json", reason="test")
    doc = json.loads(path.read_text())
    assert doc["reason"] == "test" and doc["dropped"] == 12
    assert len(doc["events"]) == 8
    assert path.stat().st_size <= flight.byte_bound()


def test_flight_on_value_and_grammar(monkeypatch):
    monkeypatch.setenv("QFEDX_FLIGHT", "on")
    assert flight.capacity() == flight.DEFAULT_CAPACITY == 256
    monkeypatch.setenv("QFEDX_FLIGHT", "bogus")
    with pytest.raises(ValueError, match="QFEDX_FLIGHT"):
        flight.capacity()


def test_flight_truncates_unbounded_fields(monkeypatch, tmp_path):
    monkeypatch.setenv("QFEDX_FLIGHT", "4")
    flight.record("span", "x" * 10_000, detail="y" * 10_000)
    ev = flight.events()[0]
    assert len(ev["name"]) <= 160 and len(ev["detail"]) <= 160
    path = flight.dump(tmp_path / "f.json")
    assert path.stat().st_size <= flight.byte_bound()


def test_sigterm_run_leaves_parseable_bounded_flight_json(
    monkeypatch, tmp_path
):
    """The black-box acceptance: a SIGTERM'd run (in-process kill, the
    utils/host translation — the test_stream idiom) leaves a valid
    flight.json in the run dir, within the configured byte bound,
    stamped with the unwind reason. Default pins otherwise — no
    QFEDX_TRACE required."""
    monkeypatch.setenv("QFEDX_FLIGHT", "32")
    from qfedx_tpu.run.metrics import ExperimentRun
    from qfedx_tpu.utils.host import (
        install_sigterm_interrupt,
        restore_sigterm,
    )

    token = install_sigterm_interrupt()
    try:
        with pytest.raises(KeyboardInterrupt, match="SIGTERM"):
            with ExperimentRun(tmp_path, name="doomed") as run:
                for i in range(50):
                    flight.record("counter", "fed.round", round=i)
                os.kill(os.getpid(), signal_mod.SIGTERM)
    finally:
        restore_sigterm(token)

    dump_path = run.dir / "flight.json"
    assert dump_path.exists()
    doc = json.loads(dump_path.read_text())  # parses or the test fails
    assert doc["reason"] == "KeyboardInterrupt"
    assert doc["capacity"] == 32
    assert 0 < len(doc["events"]) <= 32
    assert doc["events"][-1]["name"] == "fed.round"
    assert dump_path.stat().st_size <= flight.byte_bound()
    ld = flight.last_dump()
    assert ld["path"] == str(dump_path) and ld["reason"] == "KeyboardInterrupt"


def test_alert_firing_snapshots_the_flight_ring(monkeypatch, tmp_path):
    monkeypatch.setenv("QFEDX_FLIGHT", "16")
    monkeypatch.setenv("QFEDX_WATCH", "1")
    monkeypatch.setenv("QFEDX_WATCH_EPS", "1.0")
    flight.set_dump_path(tmp_path / "flight.json")
    obs.gauge("fed.epsilon", 3.0)
    watch.evaluate_once()
    doc = json.loads((tmp_path / "flight.json").read_text())
    assert doc["reason"] == "alert.trainer.eps_burn"
    assert any(
        e["kind"] == "alert" and e["name"] == "trainer.eps_burn"
        for e in doc["events"]
    )
