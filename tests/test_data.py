"""Data layer tests: IDX parsing, partitioners, preprocessing, packing."""

import struct

import numpy as np
import pytest

from qfedx_tpu.data.datasets import load_dataset
from qfedx_tpu.data.idx import read_idx, read_idx_images, read_idx_labels
from qfedx_tpu.data.partition import (
    dirichlet_partition,
    iid_partition,
    pack_clients,
    partition_stats,
)
from qfedx_tpu.data.pipeline import (
    PCATransform,
    block_downsample,
    filter_classes,
    minmax_apply,
    minmax_fit,
    pool_features,
    preprocess,
    stratified_split,
)


def _write_idx(path, arr: np.ndarray):
    arr = np.ascontiguousarray(arr, dtype=np.uint8)
    header = struct.pack(">BBBB", 0, 0, 0x08, arr.ndim)
    header += struct.pack(f">{arr.ndim}I", *arr.shape)
    path.write_bytes(header + arr.tobytes())


def test_idx_roundtrip(tmp_path):
    imgs = np.random.default_rng(0).integers(0, 256, (5, 28, 28), dtype=np.uint8)
    labels = np.arange(5, dtype=np.uint8)
    _write_idx(tmp_path / "imgs", imgs)
    _write_idx(tmp_path / "labels", labels)
    np.testing.assert_array_equal(read_idx_images(tmp_path / "imgs"), imgs)
    np.testing.assert_array_equal(read_idx_labels(tmp_path / "labels"), labels)


def test_idx_rejects_garbage(tmp_path):
    p = tmp_path / "bad"
    p.write_bytes(b"\x01\x02\x03\x04\x05")
    with pytest.raises(ValueError):
        read_idx(p)


def test_iid_partition_covers_all_disjoint():
    parts = iid_partition(103, 4, seed=1)
    allidx = np.concatenate(parts)
    assert len(allidx) == 103
    assert len(np.unique(allidx)) == 103
    sizes = [len(p) for p in parts]
    assert max(sizes) - min(sizes) <= 1


def test_dirichlet_partition_covers_all_and_skews():
    y = np.repeat(np.arange(5), 200)
    parts = dirichlet_partition(y, 8, alpha=0.1, seed=3)
    allidx = np.concatenate([p for p in parts if len(p)])
    assert len(allidx) == 1000
    assert len(np.unique(allidx)) == 1000
    stats = partition_stats(y, parts, 5)
    assert stats.sum() == 1000
    # Low alpha should produce visible skew: some client/class cell near-empty
    # while another holds a large share of that class.
    per_class_max = stats.max(axis=0)
    assert (per_class_max > 200 * 0.5).any()


def test_dirichlet_high_alpha_balanced():
    y = np.repeat(np.arange(4), 250)
    parts = dirichlet_partition(y, 4, alpha=100.0, seed=0)
    sizes = np.array([len(p) for p in parts])
    assert sizes.min() > 150  # roughly balanced at high alpha


def test_pack_clients_shapes_and_mask():
    x = np.arange(20, dtype=np.float32).reshape(10, 2)
    y = np.arange(10)
    parts = [np.array([0, 1, 2]), np.array([3]), np.array([], dtype=np.int64)]
    cx, cy, mask = pack_clients(x, y, parts, pad_multiple=4)
    assert cx.shape == (3, 4, 2) and cy.shape == (3, 4) and mask.shape == (3, 4)
    np.testing.assert_array_equal(mask.sum(axis=1), [3, 1, 0])
    np.testing.assert_array_equal(cx[0, :3], x[:3])
    assert (cx[2] == 0).all()


def test_filter_classes_remaps():
    x = np.zeros((6, 2))
    y = np.array([0, 5, 7, 5, 0, 7])
    fx, fy = filter_classes(x, y, (5, 7))
    assert len(fx) == 4
    np.testing.assert_array_equal(fy, [0, 1, 0, 1])


def test_stratified_split_fractions():
    y = np.repeat(np.arange(3), 100)
    x = np.arange(300)[:, None]
    (rx, ry), (hx, hy) = stratified_split(x, y, 0.2, seed=0)
    assert len(hx) == 60 and len(rx) == 240
    for cls in range(3):
        assert (hy == cls).sum() == 20


def test_block_downsample_matches_manual():
    img = np.arange(16, dtype=np.float32).reshape(1, 4, 4)
    out = block_downsample(img, 2, 2)
    expected = np.array([[[2.5, 4.5], [10.5, 12.5]]], dtype=np.float32)
    np.testing.assert_allclose(out, expected)


def test_block_downsample_non_integer_stride():
    img = np.ones((2, 28, 28), dtype=np.float32)
    out = block_downsample(img, 4, 4)
    assert out.shape == (2, 4, 4)
    np.testing.assert_allclose(out, 1.0, rtol=1e-6)


def test_pool_features_chunks_and_pad():
    v = np.arange(10, dtype=np.float32)
    out = pool_features(v, 3)
    # chunk=3: [0,1,2] [3,4,5] [6..9]
    np.testing.assert_allclose(out, [1.0, 4.0, 7.5])
    padded = pool_features(np.ones(2, dtype=np.float32), 4)
    np.testing.assert_allclose(padded, [1, 1, 0, 0])


def test_pca_transform_shapes_and_determinism():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(50, 30)).astype(np.float32)
    pca = PCATransform.fit(x, 8)
    z = pca(x)
    assert z.shape == (50, 8)
    z2 = PCATransform.fit(x, 8)(x)
    np.testing.assert_allclose(z, z2, atol=1e-5)


def test_minmax_fit_apply():
    x = np.array([[0.0, 10.0], [1.0, 20.0]])
    lo, hi = minmax_fit(x)
    z = minmax_apply(x, lo, hi)
    np.testing.assert_allclose(z, [[0, 0], [1, 1]])


def test_load_dataset_synthetic_learnable_shapes():
    spec, (tx, ty), (ex, ey) = load_dataset("mnist", synthetic_train=64, synthetic_test=32)
    assert tx.shape == (64, 28, 28) and tx.dtype == np.uint8
    assert ty.shape == (64,) and ey.shape == (32,)
    spec_c, (cx, _), _ = load_dataset("cifar10", synthetic_train=16, synthetic_test=8)
    assert cx.shape == (16, 32, 32, 3)
    # Determinism
    _, (tx2, ty2), _ = load_dataset("mnist", synthetic_train=64, synthetic_test=32)
    np.testing.assert_array_equal(tx, tx2)
    np.testing.assert_array_equal(ty, ty2)


def test_load_dataset_reads_real_idx(tmp_path):
    imgs = np.random.default_rng(0).integers(0, 256, (6, 28, 28), dtype=np.uint8)
    labels = np.random.default_rng(1).integers(0, 10, 6).astype(np.uint8)
    _write_idx(tmp_path / "train-images.idx3-ubyte", imgs)
    _write_idx(tmp_path / "train-labels.idx1-ubyte", labels)
    _write_idx(tmp_path / "t10k-images.idx3-ubyte", imgs[:2])
    _write_idx(tmp_path / "t10k-labels.idx1-ubyte", labels[:2])
    spec, (tx, ty), (ex, ey) = load_dataset("mnist", raw_folder=tmp_path)
    np.testing.assert_array_equal(tx, imgs)
    np.testing.assert_array_equal(ey, labels[:2])


def test_preprocess_end_to_end_pca():
    _, train, test = load_dataset("mnist", synthetic_train=256, synthetic_test=64)
    pre = preprocess(train, test, classes=(0, 1), features="pca", n_features=4)
    assert pre.num_classes == 2
    assert pre.train[0].shape[1] == 4
    assert pre.train[0].min() >= 0.0 and pre.train[0].max() <= 1.0
    assert len(pre.val[0]) > 0 and len(pre.test[0]) > 0


def test_preprocess_downsample_mode():
    _, train, test = load_dataset("mnist", synthetic_train=128, synthetic_test=32)
    pre = preprocess(train, test, features="downsample", n_features=16)
    assert pre.train[0].shape[1] == 16


def test_iris_dataset_loads_and_trains():
    """Iris (reference ROADMAP.md:102-105's small-qubit dataset): local
    sklearn copy through the standard pipeline contract, end to end."""
    from qfedx_tpu.data.datasets import load_dataset
    from qfedx_tpu.run.cli import run_train
    from qfedx_tpu.run.config import (
        DataConfig, ExperimentConfig, FedConfig, ModelConfig,
    )

    spec, (tr_x, tr_y), (te_x, te_y) = load_dataset("iris", seed=0)
    assert spec.num_classes == 3 and tr_x.shape[1:] == (1, 4)
    assert len(tr_x) == 120 and len(te_x) == 30
    assert tr_x.dtype == np.uint8
    assert set(np.unique(tr_y)) == {0, 1, 2}

    cfg = ExperimentConfig(
        data=DataConfig(dataset="iris", classes=None, num_clients=4,
                        features="pca", seed=0),
        model=ModelConfig(model="vqc", n_qubits=4, n_layers=2),
        fed=FedConfig(local_epochs=2, batch_size=8, learning_rate=0.1,
                      optimizer="adam"),
        num_rounds=6,
        eval_every=3,
        run_root="/tmp/iris-test-runs",
        name="iris-e2e",
    )
    summary = run_train(cfg)
    # 3-class Iris is nearly linearly separable: a 4-qubit VQC should be
    # clearly above the 0.33 chance level within a few rounds.
    assert summary["final_accuracy"] >= 0.55
