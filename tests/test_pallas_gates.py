"""Pallas gate kernel vs the reference tensordot path (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import qfedx_tpu.ops.pallas_gates as pg
from qfedx_tpu.ops import gates, statevector as sv
from qfedx_tpu.ops.cpx import from_complex, to_complex


@pytest.fixture(autouse=True)
def interpret_mode():
    old = pg._INTERPRET
    pg._INTERPRET = True  # no TPU in the test environment
    yield
    pg._INTERPRET = old


def random_state(n, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(2,) * n) + 1j * rng.normal(size=(2,) * n)
    return from_complex(x / np.linalg.norm(x))


@pytest.mark.parametrize("qubit", [0, 3, 6])
def test_matches_tensordot(qubit):
    n = 7
    state = random_state(n, seed=qubit)
    gate = gates.rx(0.8)
    got = to_complex(pg.apply_gate_pallas(state, gate, qubit))
    want = to_complex(sv.apply_gate(state, gate, qubit))
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_real_state_complex_gate():
    n = 5
    state = sv.zero_state(n)
    got = to_complex(pg.apply_gate_pallas(state, gates.rz(0.5), 2))
    want = to_complex(sv.apply_gate(state, gates.rz(0.5), 2))
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_gradients_match_tensordot_path():
    """custom_vjp (adjoint gate + einsum) ≡ autodiff of the tensordot path."""
    n, qubit = 5, 2
    state = random_state(n, seed=9)

    def loss_pallas(theta):
        out = pg.apply_gate_pallas(state, gates.rx(theta), qubit)
        return sv.expect_z(out, qubit)

    def loss_dense(theta):
        out = sv.apply_gate(state, gates.rx(theta), qubit)
        return sv.expect_z(out, qubit)

    theta = jnp.asarray(0.7)
    np.testing.assert_allclose(
        float(loss_pallas(theta)), float(loss_dense(theta)), atol=1e-5
    )
    np.testing.assert_allclose(
        float(jax.grad(loss_pallas)(theta)),
        float(jax.grad(loss_dense)(theta)),
        atol=1e-4,
    )


def test_routing_eligibility():
    """apply_gate routes to the kernel only where blocks stay lane-aligned:
    R = 2^(n-q-1) ≥ 128 (measured on v5e: smaller R padded every block
    128/R× under (8,128) tiling and blew the scoped-vmem limit)."""
    assert pg.pallas_eligible(16, 0)
    assert pg.pallas_eligible(16, 8)  # R = 128, the boundary
    assert not pg.pallas_eligible(16, 9)  # R = 64 → would pad 2x
    assert not pg.pallas_eligible(15, 14)  # last qubit: R = 1


def test_state_gradient():
    """VJP w.r.t. the state itself (adjoint application)."""
    n, qubit = 4, 1
    state = random_state(n, seed=3)
    gate = gates.rz(0.9)

    def f_pallas(re):
        from qfedx_tpu.ops.cpx import CArray

        out = pg.apply_gate_pallas(CArray(re, state.im), gate, qubit)
        return jnp.sum(out.re**2) + jnp.sum(out.im**2)

    def f_dense(re):
        from qfedx_tpu.ops.cpx import CArray

        out = sv.apply_gate(CArray(re, state.im), gate, qubit)
        return jnp.sum(out.re**2) + jnp.sum(out.im**2)

    g1 = jax.grad(f_pallas)(state.re)
    g2 = jax.grad(f_dense)(state.re)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-4)
