"""Lint: the ``profile_summary.json`` schema and its docs table agree.

Rehosted (r18): the single definition now lives on the unified
analysis engine — ``qfedx_tpu.analysis.rules_doc`` (rule **QFX104**
under ``qfedx lint``; docs/ANALYSIS.md has the taxonomy). This wrapper
keeps the historical surface alive verbatim for
tests/test_check_pins.py and standalone runs. The contract is
unchanged: ``obs/profile.py``'s ``SUMMARY_FIELDS`` vs the
docs/OBSERVABILITY.md schema table, both directions.
"""

from __future__ import annotations

import sys
from pathlib import Path

_REPO = Path(__file__).resolve().parent.parent
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

from qfedx_tpu.analysis.rules_doc import (  # noqa: E402,F401
    check_profile as check,
    documented_fields,
    source_fields,
)


def main() -> int:
    problems = check()
    if problems:
        print("profile_summary.json schema drift (docs/OBSERVABILITY.md):")
        for p in problems:
            print(f"  {p}")
        return 1
    print(
        f"ok: {len(documented_fields())} profile_summary.json fields, "
        "source and docs/OBSERVABILITY.md schema table agree"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
