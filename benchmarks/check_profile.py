"""Lint: the ``profile_summary.json`` schema and its docs table agree.

``qfedx_tpu/obs/profile.py`` writes ``profile_summary.json`` with
exactly the ``SUMMARY_FIELDS`` keys; the schema table in
``docs/OBSERVABILITY.md`` ("## The ``profile_summary.json`` schema") is
the operator-facing contract for those fields. A field emitted without
a doc row is invisible to readers exactly the way an undocumented
QFEDX_* pin is, and a stale row misdocuments the artifact — so this
guard follows ``check_pins.py`` / ``check_spans.py``'s shape: single
definition, both directions, wired as a tier-1 test
(tests/test_check_pins.py) and runnable standalone (``python
benchmarks/check_profile.py`` exits non-zero with offenders).
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

_TABLE_ROW = re.compile(r"^\|\s*`([a-z0-9_]+)`")
_HEADING = "## The `profile_summary.json` schema"

_REPO = Path(__file__).resolve().parent.parent


def source_fields() -> set[str]:
    """The field names ``obs.profile.summarize`` emits — the
    SUMMARY_FIELDS contract (summarize() builds exactly these keys;
    tests/test_obs.py pins that equality on a real summary)."""
    sys.path.insert(0, str(_REPO))
    from qfedx_tpu.obs.profile import SUMMARY_FIELDS

    return set(SUMMARY_FIELDS)


def documented_fields(doc_path: str | Path | None = None) -> set[str]:
    """Field names with a row in the OBSERVABILITY.md schema table
    (rows under the schema heading, to the next heading)."""
    path = Path(doc_path) if doc_path else _REPO / "docs" / "OBSERVABILITY.md"
    names = set()
    in_section = False
    for line in path.read_text().splitlines():
        stripped = line.strip()
        if stripped.startswith("#"):
            in_section = stripped.startswith(_HEADING)
            continue
        if not in_section:
            continue
        m = _TABLE_ROW.match(stripped)
        if m and m.group(1) != "field":  # skip a literal header row
            names.add(m.group(1))
    return names


def check(
    doc_path: str | Path | None = None, fields: set[str] | None = None
) -> list[str]:
    """Problem strings (empty = clean): undocumented summary fields and
    stale schema-table rows."""
    fields = source_fields() if fields is None else set(fields)
    documented = documented_fields(doc_path)
    problems = [
        f"profile_summary.json field {name!r} (obs/profile.py "
        "SUMMARY_FIELDS) has no row in the docs/OBSERVABILITY.md "
        "schema table"
        for name in sorted(fields - documented)
    ]
    problems += [
        f"schema-table row {name!r} matches no SUMMARY_FIELDS entry in "
        "obs/profile.py (stale doc row?)"
        for name in sorted(documented - fields)
    ]
    return problems


def main() -> int:
    problems = check()
    if problems:
        print("profile_summary.json schema drift (docs/OBSERVABILITY.md):")
        for p in problems:
            print(f"  {p}")
        return 1
    print(
        f"ok: {len(documented_fields())} profile_summary.json fields, "
        "source and docs/OBSERVABILITY.md schema table agree"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
