"""Measure the Pallas gate kernel vs the default XLA path on real TPU.

Decides the routing threshold in ops/statevector.py:apply_gate from data
(round-1 VERDICT: the ≥2^14 cutoff was asserted, never measured). For each
qubit count n, times a batch of single-qubit gate applications on a fully
complex state through both paths and reports the ratio; run on the real
chip, results are committed to benchmarks/pallas_sweep.json and the
threshold constant updated to match.

Usage (from the repo root, on the TPU):
    python benchmarks/pallas_sweep.py [--min 10] [--max 22] [--reps 9]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root


def time_gate_chain(jax, n_qubits: int, use_pallas: bool, reps: int) -> float:
    """Median seconds PER CHAIN of 2n complex 1q gates (every qubit touched
    twice), with ``repeat`` chains run inside ONE jitted fori_loop so device
    work dominates the measurement — a single small dispatch through the
    tunneled TPU costs ~100ms of latency, which would otherwise swamp the
    sub-ms device time of one chain and flatten every comparison (measured:
    un-amortized chains timed ~0.11s at every n from 14 to 18)."""
    import jax.numpy as jnp

    from qfedx_tpu.ops import gates
    from qfedx_tpu.ops.cpx import CArray
    from qfedx_tpu.ops.statevector import apply_gate

    os.environ["QFEDX_PALLAS"] = "1" if use_pallas else "0"

    rng = np.random.default_rng(0)
    shape = (2,) * n_qubits
    re = rng.normal(size=shape).astype(np.float32)
    im = rng.normal(size=shape).astype(np.float32)
    nrm = np.sqrt((re**2 + im**2).sum())
    state = CArray(jnp.asarray(re / nrm), jnp.asarray(im / nrm))
    gate = gates.rot_zx(jnp.float32(0.3), jnp.float32(0.7))  # complex 2x2

    # ~2 GB of gate traffic per dispatch (16·2^n bytes per gate).
    repeat = max(4, (1 << 31) // (2 * n_qubits * 16 * (1 << n_qubits)))

    def chain(s: CArray) -> CArray:
        for q in range(n_qubits):
            s = apply_gate(s, gate, q)
        for q in reversed(range(n_qubits)):
            s = apply_gate(s, gate, q)
        return s

    @jax.jit
    def many(s: CArray) -> CArray:
        def body(_, st):
            return chain(st)

        return jax.lax.fori_loop(0, repeat, body, s)

    out = many(state)  # compile (env read at trace time)
    jax.block_until_ready(out.re)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = many(state)
        jax.block_until_ready(out.re)
        times.append(time.perf_counter() - t0)
    return sorted(times)[len(times) // 2] / repeat


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--min", type=int, default=10)
    ap.add_argument("--max", type=int, default=22)
    ap.add_argument("--reps", type=int, default=9)
    args = ap.parse_args()

    import jax

    platform = jax.devices()[0].platform
    rows = []
    for n in range(args.min, args.max + 1):
        xla_s = time_gate_chain(jax, n, use_pallas=False, reps=args.reps)
        try:
            pl_s = time_gate_chain(jax, n, use_pallas=True, reps=args.reps)
            err = None
        except Exception as e:  # noqa: BLE001
            pl_s, err = None, f"{type(e).__name__}: {e}"
        row = {
            "n_qubits": n,
            "gates": 2 * n,
            "xla_s": round(xla_s, 6),
            "pallas_s": round(pl_s, 6) if pl_s else None,
            "pallas_speedup": round(xla_s / pl_s, 3) if pl_s else None,
            "error": err,
        }
        rows.append(row)
        print(json.dumps(row))

    wins = [r["n_qubits"] for r in rows if (r["pallas_speedup"] or 0) > 1.05]
    out = {
        "platform": platform,
        "reps": args.reps,
        "rows": rows,
        "pallas_wins_at": wins,
        "recommended_threshold": min(wins) if wins else None,
    }
    path = Path(__file__).parent / "pallas_sweep.json"
    path.write_text(json.dumps(out, indent=2))
    print(f"wrote {path}: pallas wins at n ∈ {wins or 'nowhere'}")


if __name__ == "__main__":
    main()
