"""Lint: the fault-site taxonomy in ``docs/ROBUSTNESS.md`` matches code.

``utils/faults.py`` is the chaos harness's source of truth — its
``SITES`` / ``CLIENT_KINDS`` / ``BYZANTINE_KINDS`` literals define what
a ``FaultPlan`` can inject. An operator writing a plan reads the
taxonomy table in ``docs/ROBUSTNESS.md`` ("## Fault-site taxonomy"), so
a site or kind that exists in code but not in the table is invisible
exactly the way an undocumented ``QFEDX_*`` pin is — this guard follows
``check_pins.py``'s shape: single definition, wired as a tier-1 test
(tests/test_check_pins.py) and runnable standalone (``python
benchmarks/check_faults.py`` exits non-zero with offenders).

Contract: the doc table has one row per site, first cell the backticked
site name, second cell the backticked kind spellings — compared both
directions against ``faults.doc_taxonomy()`` (missing row/kind fails,
stale row/kind fails). ``doc_taxonomy`` is derived from the code
tuples, so a new injection mode cannot ship without its documentation
row.
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

_REPO = Path(__file__).resolve().parent.parent
_HEADING = "## Fault-site taxonomy"
_ROW = re.compile(r"^\|\s*`([a-z0-9_.]+)`\s*\|([^|]*)\|")
_TICKED = re.compile(r"`([^`]+)`")


def documented_taxonomy(doc_path: str | Path | None = None) -> dict:
    """``{site: (kinds...)}`` parsed from the taxonomy table rows under
    the "## Fault-site taxonomy" heading (to the next heading)."""
    path = Path(doc_path) if doc_path else _REPO / "docs" / "ROBUSTNESS.md"
    out: dict[str, tuple[str, ...]] = {}
    in_section = False
    for line in path.read_text().splitlines():
        stripped = line.strip()
        if stripped.startswith("#"):
            in_section = stripped.startswith(_HEADING)
            continue
        if not in_section:
            continue
        m = _ROW.match(stripped)
        if m and m.group(1) != "site":  # skip a literal header row
            out[m.group(1)] = tuple(_TICKED.findall(m.group(2)))
    return out


def check(doc_path: str | Path | None = None) -> list[str]:
    """Problem strings (empty = clean): taxonomy drift in either
    direction between utils/faults.py and docs/ROBUSTNESS.md."""
    from qfedx_tpu.utils.faults import doc_taxonomy

    code = doc_taxonomy()
    doc = documented_taxonomy(doc_path)
    problems = []
    for site, kinds in sorted(code.items()):
        if site not in doc:
            problems.append(
                f"fault site {site} (utils/faults.py) has no row in the "
                "docs/ROBUSTNESS.md fault-site taxonomy table"
            )
            continue
        missing = [k for k in kinds if k not in doc[site]]
        if missing:
            problems.append(
                f"fault site {site}: kinds {missing} missing from its "
                "docs/ROBUSTNESS.md taxonomy row"
            )
        stale = [k for k in doc[site] if k not in kinds]
        if stale:
            problems.append(
                f"fault site {site}: taxonomy row lists {stale}, not in "
                "utils/faults.py (stale doc kinds?)"
            )
    for site in sorted(set(doc) - set(code)):
        problems.append(
            f"taxonomy row {site} matches no site in utils/faults.py "
            "(stale doc row?)"
        )
    return problems


def main() -> int:
    problems = check()
    if problems:
        print("fault-site taxonomy drift (docs/ROBUSTNESS.md):")
        for p in problems:
            print(f"  {p}")
        return 1
    n = len(documented_taxonomy())
    print(f"ok: {n} fault sites, utils/faults.py and docs/ROBUSTNESS.md "
          "taxonomy agree")
    return 0


if __name__ == "__main__":
    if str(_REPO) not in sys.path:
        sys.path.insert(0, str(_REPO))
    sys.exit(main())
