"""Lint: the fault-site taxonomy in ``docs/ROBUSTNESS.md`` matches code.

Rehosted (r18): the single definition now lives on the unified
analysis engine — ``qfedx_tpu.analysis.rules_doc`` (rule **QFX102**
under ``qfedx lint``; docs/ANALYSIS.md has the taxonomy). This wrapper
keeps the historical surface alive verbatim for
tests/test_check_pins.py and standalone runs. The contract is
unchanged: ``utils/faults.doc_taxonomy()`` (derived from the
``SITES``/``*_KINDS`` code tuples) vs the docs table, per site and per
kind, both directions — a new injection mode cannot ship without its
documentation row.
"""

from __future__ import annotations

import sys
from pathlib import Path

_REPO = Path(__file__).resolve().parent.parent
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

from qfedx_tpu.analysis.rules_doc import (  # noqa: E402,F401
    check_faults as check,
    documented_taxonomy,
)


def main() -> int:
    problems = check()
    if problems:
        print("fault-site taxonomy drift (docs/ROBUSTNESS.md):")
        for p in problems:
            print(f"  {p}")
        return 1
    n = len(documented_taxonomy())
    print(f"ok: {n} fault sites, utils/faults.py and docs/ROBUSTNESS.md "
          "taxonomy agree")
    return 0


if __name__ == "__main__":
    sys.exit(main())
