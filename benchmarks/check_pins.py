"""Lint: every ``QFEDX_*`` pin read in ``qfedx_tpu/`` is documented.

The pin table in ``docs/OBSERVABILITY.md`` ("The ``QFEDX_*`` pin family
(one table)") is the contract surface for every env knob the framework
reads — values, defaults, read time, effect. A pin that exists in source
but not in the table is invisible to operators exactly the way a bare
print() is invisible to exporters, so this guard follows
``check_no_print.py``'s shape: AST-based single definition, wired as a
tier-1 test (tests/test_check_pins.py) and runnable standalone
(``python benchmarks/check_pins.py`` exits non-zero with offenders).

Detection: an exact string literal ``"QFEDX_..."`` anywhere in package
code IS a pin reference (``pins.bool_pin("QFEDX_HIER", ...)``,
``os.environ.get("QFEDX_TRACE")``, ``{"QFEDX_DTYPE": "bf16"}`` — every
read/write spelling funnels through such a literal; prose only ever
embeds pin names inside longer strings, which full-match filtering
ignores). The check runs both directions: source pins missing from the
table fail, and table rows whose pin no longer appears in source fail
too — a stale row misdocuments the system as surely as a missing one.
"""

from __future__ import annotations

import ast
import re
import sys
from pathlib import Path

_PIN_LITERAL = re.compile(r"QFEDX_[A-Z0-9_]+\Z")
_TABLE_ROW = re.compile(r"^\|\s*`(QFEDX_[A-Z0-9_]+)`")

_REPO = Path(__file__).resolve().parent.parent


def source_pins(package_root: str | Path | None = None) -> dict[str, list[str]]:
    """``{pin_name: ["rel/path.py:lineno", ...]}`` for every exact
    ``QFEDX_*`` string literal in package code."""
    root = Path(package_root) if package_root else _REPO / "qfedx_tpu"
    pins: dict[str, list[str]] = {}
    for py in sorted(root.rglob("*.py")):
        rel = py.relative_to(root).as_posix()
        if "__pycache__" in rel:
            continue
        tree = ast.parse(py.read_text(), filename=str(py))
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and _PIN_LITERAL.fullmatch(node.value)
            ):
                pins.setdefault(node.value, []).append(f"{rel}:{node.lineno}")
    return pins


def documented_pins(doc_path: str | Path | None = None) -> set[str]:
    """Pin names with a row in the OBSERVABILITY.md pin table."""
    path = Path(doc_path) if doc_path else _REPO / "docs" / "OBSERVABILITY.md"
    names = set()
    for line in path.read_text().splitlines():
        m = _TABLE_ROW.match(line.strip())
        if m:
            names.add(m.group(1))
    return names


def check(
    package_root: str | Path | None = None,
    doc_path: str | Path | None = None,
) -> list[str]:
    """Problem strings (empty = clean): undocumented source pins and
    stale table rows."""
    pins = source_pins(package_root)
    documented = documented_pins(doc_path)
    problems = [
        f"pin {name} read at {', '.join(sites)} has no row in the "
        "docs/OBSERVABILITY.md pin table"
        for name, sites in sorted(pins.items())
        if name not in documented
    ]
    problems += [
        f"pin table row {name} matches no QFEDX_* literal in qfedx_tpu/ "
        "(stale doc row?)"
        for name in sorted(documented - set(pins))
    ]
    return problems


def main() -> int:
    problems = check()
    if problems:
        print("QFEDX_* pin table drift (docs/OBSERVABILITY.md):")
        for p in problems:
            print(f"  {p}")
        return 1
    print(
        f"ok: {len(documented_pins())} pins, source and "
        "docs/OBSERVABILITY.md table agree"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
