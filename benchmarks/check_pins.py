"""Lint: every ``QFEDX_*`` pin read in ``qfedx_tpu/`` is documented.

Rehosted (r18): the single definition now lives on the unified
analysis engine — ``qfedx_tpu.analysis.rules_pins`` (rule **QFX101**
under ``qfedx lint``; docs/ANALYSIS.md has the taxonomy). This wrapper
keeps the historical surface alive verbatim: the tier-1 test
(tests/test_check_pins.py) imports ``check``/``source_pins``/
``documented_pins`` from here, and ``python benchmarks/check_pins.py``
still exits non-zero with offenders. The contract itself is unchanged:
an exact ``"QFEDX_..."`` string literal in package code IS a pin
reference, and the docs/OBSERVABILITY.md pin table must match it in
both directions (a stale row misdocuments the system as surely as a
missing one).
"""

from __future__ import annotations

import sys
from pathlib import Path

_REPO = Path(__file__).resolve().parent.parent
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

from qfedx_tpu.analysis.rules_pins import (  # noqa: E402,F401
    check,
    documented_pins,
    source_pins,
)


def main() -> int:
    problems = check()
    if problems:
        print("QFEDX_* pin table drift (docs/OBSERVABILITY.md):")
        for p in problems:
            print(f"  {p}")
        return 1
    print(
        f"ok: {len(documented_pins())} pins, source and "
        "docs/OBSERVABILITY.md table agree"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
