"""Lint: tune/controller decisions match the documented taxonomy.

Thin wrapper (the check_pins/check_spans pattern): the single
definition lives on the unified analysis engine —
``qfedx_tpu.analysis.rules_doc`` (rule **QFX107** under ``qfedx
lint``; docs/ANALYSIS.md has the taxonomy). The contract: every
decision ID in ``tune/controller.DECISIONS`` has a row in
docs/OBSERVABILITY.md's "## Tune decision taxonomy" table, every row
names a live decision, and each row's threshold-pin cell names the pin
the controller actually compares against — the operator reading a
``{"event": "tune"}`` row looks the ID up in exactly one place, which
must not lie about the knob that changes the behaviour.
"""

from __future__ import annotations

import sys
from pathlib import Path

_REPO = Path(__file__).resolve().parent.parent
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

from qfedx_tpu.analysis.rules_doc import (  # noqa: E402,F401
    check_tune,
    documented_tune_decisions,
)


def main() -> int:
    problems = check_tune()
    if problems:
        print("tune-decision taxonomy drift (docs/OBSERVABILITY.md):")
        for p in problems:
            print(f"  {p}")
        return 1
    print(
        f"ok: {len(documented_tune_decisions())} tune decisions, "
        "tune/controller.py and docs/OBSERVABILITY.md table agree"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
