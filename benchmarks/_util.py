"""Shared helpers for the bench/profiling scripts."""

from __future__ import annotations

import os
import time


def build_step(n_qubits, n_layers=3, batch=64, steps=8, encoding="angle"):
    """The standard bench program: ``steps`` SGD fwd+grad steps on a VQC,
    scanned into ONE jitted dispatch (the ~100 ms tunnel dispatch latency
    would otherwise flatten every timing to the latency floor). Shared by
    fused_sweep.py and profile_step.py so both always measure the same
    program. Returns (jitted_fn, params, steps)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from qfedx_tpu.models.vqc import make_vqc_classifier

    enable_cache(jax)
    model = make_vqc_classifier(
        n_qubits=n_qubits, n_layers=n_layers, num_classes=2, encoding=encoding
    )
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.uniform(0, 1, (batch, n_qubits)), dtype=jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, (batch,)), dtype=jnp.int32)

    def loss(p):
        logits = model.apply(p, x)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    @jax.jit
    def many_steps(params):
        def body(p, _):
            l, g = jax.value_and_grad(loss)(p)
            p2 = jax.tree.map(lambda a, b: a - 1e-6 * b, p, g)
            return p2, l

        return jax.lax.scan(body, params, None, length=steps)

    return many_steps, params, steps


def retry_timing(measure, floor=1e-3, attempts=5, label=""):
    """Run ``measure()`` (returns seconds) with a bounded retry of the
    tunnel's ~0s timing artifact: a blocked-on value that was already
    resident occasionally times as ~0 s, and the artifact can persist
    across one re-measure (observed r04 at n=15), so retry with pauses
    and refuse to return a bogus number. SINGLE definition of the
    policy — bench.py and every benchmarks/ script share it, so a
    threshold/retry change cannot silently diverge between them."""
    for _ in range(attempts):
        t = measure()
        if t >= floor:
            return t
        time.sleep(2)
    raise RuntimeError(
        f"persistent ~0s timing artifact{f' at {label}' if label else ''}; "
        "tunnel unhealthy"
    )


def timed_median(jax, fn, params, steps, reps=5, label=""):
    """Median seconds PER STEP over ``reps`` dispatches of a scanned
    ``steps``-step program, artifact-guarded by ``retry_timing``.
    Chains fn's first output back in as the next input: repeated
    dispatches with IDENTICAL inputs are elided by the tunnel and time
    as ~0 s (measured r04 — see bench.py _time_spmd)."""
    state = {"params": params}
    state["params"], ls = fn(state["params"])  # warm (compile)
    jax.block_until_ready(ls)

    def measure():
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            state["params"], ls = fn(state["params"])
            jax.block_until_ready(ls)
            times.append(time.perf_counter() - t0)
        return sorted(times)[len(times) // 2] / steps

    return retry_timing(measure, floor=1e-3 / steps, label=label)


def enable_cache(jax) -> None:
    """Point JAX's persistent compilation cache at the repo-local
    .jax_cache dir (single definition — bench.py, fused_sweep.py and
    profile_step.py all use this; the multi-minute Mosaic/XLA compiles
    make every re-run hot)."""
    try:
        cache = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".jax_cache",
        )
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
    except Exception:  # noqa: BLE001 — cache is an optimization only
        pass
