"""Shared helpers for the bench/profiling scripts."""

from __future__ import annotations

import os
import time


def build_step(n_qubits, n_layers=3, batch=64, steps=8, encoding="angle",
               remat=False):
    """The standard bench program: ``steps`` SGD fwd+grad steps on a VQC,
    scanned into ONE jitted dispatch (the ~100 ms tunnel dispatch latency
    would otherwise flatten every timing to the latency floor). Shared by
    fused_sweep.py and profile_step.py so both always measure the same
    program. Returns (jitted_fn, params, steps)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from qfedx_tpu.models.vqc import make_vqc_classifier

    enable_cache(jax)
    model = make_vqc_classifier(
        n_qubits=n_qubits, n_layers=n_layers, num_classes=2, encoding=encoding,
        remat=remat,
    )
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.uniform(0, 1, (batch, n_qubits)), dtype=jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, (batch,)), dtype=jnp.int32)

    def loss(p):
        logits = model.apply(p, x)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    @jax.jit
    def many_steps(params):
        def body(p, _):
            l, g = jax.value_and_grad(loss)(p)
            p2 = jax.tree.map(lambda a, b: a - 1e-6 * b, p, g)
            return p2, l

        return jax.lax.scan(body, params, None, length=steps)

    return many_steps, params, steps


def device_sync(x):
    """Force TRUE completion of ``x``'s computation by fetching its
    smallest array leaf to host. ``jax.block_until_ready`` through the
    tunnel has been observed (r04) returning in ~0.1 ms for a 330 ms
    program — readiness is acked for queued-but-unexecuted work unless a
    host fetch anchors it. All outputs of one XLA execution complete
    together, so fetching one (small) leaf proves the execution ran."""
    import jax
    import numpy as np

    leaves = [l for l in jax.tree.leaves(x) if hasattr(l, "size")]
    np.asarray(min(leaves, key=lambda l: l.size))
    return x


def retry_timing_vals(measure, floor=1e-3, attempts=8, blocks=3, label=""):
    """(median, sorted block results) of ``blocks`` valid ``measure()``
    results (seconds), with a bounded retry of the tunnel's ~0s timing
    artifact. Two-sided robustness: results below ``floor`` are the
    elision/early-ack artifact (discarded and retried — it can persist
    across a re-measure, observed r04 at n=15); taking the MEDIAN across
    independent chained blocks rejects slow outliers (a transient
    tunnel stall or mid-block recompile would otherwise inflate a
    single-block mean unchecked). The sorted per-block values let the
    bench SHIP its own spread instead of a point estimate (VERDICT r04
    weak 3). SINGLE definition of the policy — bench.py and every
    benchmarks/ script share it, so a threshold/retry change cannot
    silently diverge between them."""
    vals = []
    for _ in range(attempts):
        t = measure()
        if t >= floor:
            vals.append(t)
            if len(vals) >= blocks:
                break
        else:
            time.sleep(2)
    if not vals:
        raise RuntimeError(
            f"persistent ~0s timing artifact{f' at {label}' if label else ''}"
            "; tunnel unhealthy"
        )
    vals = sorted(vals)
    return vals[len(vals) // 2], vals


def retry_timing(measure, floor=1e-3, attempts=8, blocks=3, label=""):
    """Median-only view of ``retry_timing_vals`` (shared policy)."""
    return retry_timing_vals(measure, floor, attempts, blocks, label)[0]


def timed_median(fn, params, steps, reps=5, label=""):
    """Median seconds PER STEP across chained measurement blocks of a
    scanned ``steps``-step program. Each block: ``reps`` CHAINED
    dispatches (each rep's output params feed the next — the tunnel
    elides identical-input dispatches) timed as one wall block anchored
    by a real host fetch (``device_sync`` — block_until_ready alone can
    lie, see there); one tunnel round-trip amortizes over reps×steps.
    ``retry_timing`` takes the median over blocks and guards the ~0s
    artifact."""
    state = {"params": params}
    state["params"], ls = fn(state["params"])  # warm (compile)
    device_sync(ls)

    def measure():
        t0 = time.perf_counter()
        for _ in range(reps):
            state["params"], ls = fn(state["params"])
        device_sync(state["params"])
        return (time.perf_counter() - t0) / (reps * steps)

    return retry_timing(measure, floor=1e-3 / steps, label=label)


def with_env(env: dict, fn, *a, **k):
    """Run fn with env vars set, restoring previous values after —
    single definition shared by bench.py's lever rows and the
    profile-script A/B pins (the QFEDX_* knobs are read at trace time,
    so each pinned build must trace inside the pinned window)."""
    prev = {var: os.environ.get(var) for var in env}
    os.environ.update(env)
    try:
        return fn(*a, **k)
    finally:
        for var, old in prev.items():
            if old is None:
                os.environ.pop(var, None)
            else:
                os.environ[var] = old


def enable_cache(jax) -> None:
    """Point JAX's persistent compilation cache at the repo-local
    .jax_cache dir (bench.py, fused_sweep.py and profile_step.py all use
    this; the multi-minute Mosaic/XLA compiles make every re-run hot).
    The policy definition lives in qfedx_tpu.utils.cache (r09: the CLI
    shares it behind QFEDX_COMPILE_CACHE) — this wrapper only supplies
    the bench scripts' repo-local default directory, so the pin's
    off/redirect values apply to bench runs too."""
    from qfedx_tpu.utils.cache import enable_compile_cache

    enable_compile_cache(
        jax,
        default_dir=os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".jax_cache",
        ),
    )
