"""One-off harness for retuning sweep cells with a failing seed.

VERDICT r03 item 3: c2-8q-dpsgd, c3-cnn-fedprox, iris-4q, q4-c32 each
had a seed at or below chance hidden by the mean. This script runs a
single named cell (with optional knob overrides) across seeds and prints
per-seed accuracies, so retuning decisions are measured rather than
guessed. The tuned values land back in run/sweep.py preset_cells with a
comment citing the measurement.

Usage:
  python benchmarks/tune_cells.py <preset> <cell-name> [k=v ...] [--seeds N]
e.g.
  python benchmarks/tune_cells.py baseline iris-4q rounds=25 local_epochs=3
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def parse_val(v: str):
    try:
        return json.loads(v)
    except Exception:  # noqa: BLE001 — bare strings
        return v


def main():
    args = [a for a in sys.argv[1:]]
    seeds = 3
    if "--seeds" in args:
        i = args.index("--seeds")
        seeds = int(args[i + 1])
        args = args[:i] + args[i + 2 :]
    preset, name = args[0], args[1]
    overrides = {}
    for kv in args[2:]:
        k, v = kv.split("=", 1)
        overrides[k] = parse_val(v)

    from qfedx_tpu.run.sweep import _run_cell, preset_cells

    cell = next(c for c in preset_cells(preset) if c["name"] == name)
    cell.update(overrides)
    print(f"cell: {cell}", flush=True)
    accs = []
    for s in range(seeds):
        t0 = time.perf_counter()
        r = _run_cell(cell, seed=42 + s)
        accs.append(r["accuracy"])
        print(
            f"seed {s}: acc={r['accuracy']:.3f} eps={r['epsilon']} "
            f"({time.perf_counter() - t0:.1f}s)",
            flush=True,
        )
    import numpy as np

    print(
        f"mean={np.mean(accs):.3f} std={np.std(accs):.3f} "
        f"min={np.min(accs):.3f}",
        flush=True,
    )


if __name__ == "__main__":
    main()
