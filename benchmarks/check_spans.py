"""Lint: every span-name literal in ``qfedx_tpu/`` is in the taxonomy.

The span taxonomy table in ``docs/OBSERVABILITY.md`` ("## Span
taxonomy") is the contract surface for every phase name the framework
records — an operator reading a trace.json or a /metrics scrape looks
names up there. A span that exists in source but not in the table is
invisible exactly the way an undocumented QFEDX_* pin is, so this guard
follows ``check_pins.py``'s shape: AST-based single definition, wired
as a tier-1 test (tests/test_check_pins.py) and runnable standalone
(``python benchmarks/check_spans.py`` exits non-zero with offenders).

Detection: a string literal appearing as the FIRST argument of a
``span(...)`` / ``obs.span(...)`` call in package code IS a span name
(every recording site spells it that way; dynamic names would defeat
the taxonomy and none exist). The check runs both directions: source
spans missing from the table fail, and table rows whose span no longer
appears in source fail too — a stale row misdocuments the system as
surely as a missing one. It caught the r15 ``obs.http`` span before
its row existed, which is the point.
"""

from __future__ import annotations

import ast
import re
import sys
from pathlib import Path

_TABLE_ROW = re.compile(r"^\|\s*`([a-z0-9_.]+)`")
_HEADING = "## Span taxonomy"

_REPO = Path(__file__).resolve().parent.parent


def source_spans(package_root: str | Path | None = None) -> dict[str, list[str]]:
    """``{span_name: ["rel/path.py:lineno", ...]}`` for every
    ``span("name", ...)`` call site in package code."""
    root = Path(package_root) if package_root else _REPO / "qfedx_tpu"
    spans: dict[str, list[str]] = {}
    for py in sorted(root.rglob("*.py")):
        rel = py.relative_to(root).as_posix()
        if "__pycache__" in rel:
            continue
        tree = ast.parse(py.read_text(), filename=str(py))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = node.func
            name = (
                fn.attr if isinstance(fn, ast.Attribute)
                else fn.id if isinstance(fn, ast.Name)
                else None
            )
            if name != "span":
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(first.value, str):
                spans.setdefault(first.value, []).append(
                    f"{rel}:{node.lineno}"
                )
    return spans


def documented_spans(doc_path: str | Path | None = None) -> set[str]:
    """Span names with a row in the OBSERVABILITY.md span-taxonomy
    table (rows under the "## Span taxonomy" heading, to the next
    heading)."""
    path = Path(doc_path) if doc_path else _REPO / "docs" / "OBSERVABILITY.md"
    names = set()
    in_section = False
    for line in path.read_text().splitlines():
        stripped = line.strip()
        if stripped.startswith("#"):
            in_section = stripped.startswith(_HEADING)
            continue
        if not in_section:
            continue
        m = _TABLE_ROW.match(stripped)
        if m and m.group(1) != "span":  # skip a literal header row
            names.add(m.group(1))
    return names


def check(
    package_root: str | Path | None = None,
    doc_path: str | Path | None = None,
) -> list[str]:
    """Problem strings (empty = clean): undocumented source spans and
    stale taxonomy rows."""
    spans = source_spans(package_root)
    documented = documented_spans(doc_path)
    problems = [
        f"span {name!r} recorded at {', '.join(sites)} has no row in "
        "the docs/OBSERVABILITY.md span-taxonomy table"
        for name, sites in sorted(spans.items())
        if name not in documented
    ]
    problems += [
        f"span-taxonomy row {name!r} matches no span literal in "
        "qfedx_tpu/ (stale doc row?)"
        for name in sorted(documented - set(spans))
    ]
    return problems


def main() -> int:
    problems = check()
    if problems:
        print("span-taxonomy drift (docs/OBSERVABILITY.md):")
        for p in problems:
            print(f"  {p}")
        return 1
    print(
        f"ok: {len(documented_spans())} spans, source and "
        "docs/OBSERVABILITY.md taxonomy agree"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
