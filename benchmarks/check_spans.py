"""Lint: every span-name literal in ``qfedx_tpu/`` is in the taxonomy.

Rehosted (r18): the single definition now lives on the unified
analysis engine — ``qfedx_tpu.analysis.rules_spans`` (rule **QFX103**
under ``qfedx lint``, which also adds the QFX003 span-LEAK analysis;
docs/ANALYSIS.md has the taxonomy). This wrapper keeps the historical
surface alive verbatim for tests/test_check_pins.py and standalone
runs. The contract is unchanged: a string literal as the FIRST
argument of a ``span(...)`` call IS a span name, and the
docs/OBSERVABILITY.md "## Span taxonomy" table must match source in
both directions. It caught the r15 ``obs.http`` span before its row
existed, which is the point.
"""

from __future__ import annotations

import sys
from pathlib import Path

_REPO = Path(__file__).resolve().parent.parent
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

from qfedx_tpu.analysis.rules_spans import (  # noqa: E402,F401
    check,
    documented_spans,
    source_spans,
)


def main() -> int:
    problems = check()
    if problems:
        print("span-taxonomy drift (docs/OBSERVABILITY.md):")
        for p in problems:
            print(f"  {p}")
        return 1
    print(
        f"ok: {len(documented_spans())} spans, source and "
        "docs/OBSERVABILITY.md taxonomy agree"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
