"""Measure the fused whole-circuit kernel vs the per-gate XLA path on TPU.

Usage: python benchmarks/fused_sweep.py [n_qubits ...]
Prints one JSON line per config: fwd+grad seconds per step for the
default XLA path and QFEDX_FUSED=1 (whole-circuit kernel), with the
speedup. This is the data behind the fused routing default
(ops.fused_hea.AUTO_MIN_QUBITS).
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root


def build_step(n_qubits, n_layers, batch, steps=8):
    import jax
    import jax.numpy as jnp
    import optax

    from qfedx_tpu.models.vqc import make_vqc_classifier

    model = make_vqc_classifier(n_qubits=n_qubits, n_layers=n_layers, num_classes=2)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.uniform(0, 1, (batch, n_qubits)), dtype=jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, (batch,)), dtype=jnp.int32)

    def loss(p):
        logits = model.apply(p, x)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    @jax.jit
    def many_steps(params):
        def body(p, _):
            l, g = jax.value_and_grad(loss)(p)
            p2 = jax.tree.map(lambda a, b: a - 1e-6 * b, p, g)
            return p2, l

        return jax.lax.scan(body, params, None, length=steps)

    return many_steps, params, steps


def timeit(n_qubits, n_layers=3, batch=64, reps=5):
    import jax

    fn, params, steps = build_step(n_qubits, n_layers, batch)
    _, ls = fn(params)
    jax.block_until_ready(ls)

    def measure():
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            _, ls = fn(params)
            jax.block_until_ready(ls)
            times.append(time.perf_counter() - t0)
        return sorted(times)[len(times) // 2] / steps

    t = measure()
    # Transient tunnel glitches have produced ~0s timings (see the same
    # guard in bench.py); this workload cannot run in <1ms per step.
    if t < 1e-3:
        t = measure()
    return t


def with_env(var, val, fn, *a):
    prev = os.environ.get(var)
    os.environ[var] = val
    try:
        return fn(*a)
    finally:
        if prev is None:
            os.environ.pop(var, None)
        else:
            os.environ[var] = prev


def main():
    qubits = [int(a) for a in sys.argv[1:]] or [12, 14, 16, 18]
    for n in qubits:
        row = {"n_qubits": n, "n_layers": 3, "batch": 64}
        try:
            row["xla_s"] = round(with_env("QFEDX_FUSED", "0", timeit, n), 5)
            row["fused_s"] = round(with_env("QFEDX_FUSED", "1", timeit, n), 5)
            row["fused_speedup_vs_xla"] = round(row["xla_s"] / row["fused_s"], 3)
            row["fused_bf16_s"] = round(
                with_env("QFEDX_DTYPE", "bf16",
                         lambda m: with_env("QFEDX_FUSED", "1", timeit, m), n),
                5,
            )
            row["xla_bf16_s"] = round(
                with_env("QFEDX_DTYPE", "bf16",
                         lambda m: with_env("QFEDX_FUSED", "0", timeit, m), n),
                5,
            )
            row["fused_bf16_speedup_vs_xla_f32"] = round(
                row["xla_s"] / row["fused_bf16_s"], 3
            )
            if os.environ.get("QFEDX_FUSED_BB"):
                row["bb"] = int(os.environ["QFEDX_FUSED_BB"])
        except Exception as e:  # noqa: BLE001 — report per-config
            row["error"] = f"{type(e).__name__}: {e}"
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
