"""Measure the fused whole-circuit kernel vs the XLA slab engine on TPU.

Usage: python benchmarks/fused_sweep.py [n_qubits ...]
       python benchmarks/fused_sweep.py --encoding reupload [n_qubits ...]
Prints one JSON line per config: fwd+grad seconds per step for the
default XLA path (the r04 slab engine, QFEDX_FUSED unset/0) and
QFEDX_FUSED=1 (whole-circuit Pallas kernel), with the speedup. This is
the data behind the r04 routing decision (ops.fused_hea.fused_enabled:
auto routing to the kernel DISABLED — the slab engine measured faster
at every width, both encodings; docs/PERF.md §4). The reupload rows
answer VERDICT r03 item 2: config 4's circuit (~2× the gates/layer of
plain HEA) measured on its own kernel rather than assumed.
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root


def _enable_cache(jax):
    try:
        cache = str(Path(__file__).resolve().parent.parent / ".jax_cache")
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
    except Exception:  # noqa: BLE001 — cache is an optimization only
        pass


def build_step(n_qubits, n_layers, batch, steps=8, encoding="angle"):
    import jax
    import jax.numpy as jnp
    import optax

    from qfedx_tpu.models.vqc import make_vqc_classifier

    _enable_cache(jax)
    model = make_vqc_classifier(
        n_qubits=n_qubits, n_layers=n_layers, num_classes=2, encoding=encoding
    )
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.uniform(0, 1, (batch, n_qubits)), dtype=jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, (batch,)), dtype=jnp.int32)

    def loss(p):
        logits = model.apply(p, x)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    @jax.jit
    def many_steps(params):
        def body(p, _):
            l, g = jax.value_and_grad(loss)(p)
            p2 = jax.tree.map(lambda a, b: a - 1e-6 * b, p, g)
            return p2, l

        return jax.lax.scan(body, params, None, length=steps)

    return many_steps, params, steps


def timeit(n_qubits, n_layers=3, batch=64, reps=5, encoding="angle"):
    import jax

    fn, params, steps = build_step(n_qubits, n_layers, batch, encoding=encoding)
    _, ls = fn(params)
    jax.block_until_ready(ls)

    def measure():
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            _, ls = fn(params)
            jax.block_until_ready(ls)
            times.append(time.perf_counter() - t0)
        return sorted(times)[len(times) // 2] / steps

    t = measure()
    # Transient tunnel glitches have produced ~0s timings (see the same
    # guard in bench.py); this workload cannot run in <1ms per step.
    if t < 1e-3:
        t = measure()
    return t


def with_env(var, val, fn, *a):
    prev = os.environ.get(var)
    os.environ[var] = val
    try:
        return fn(*a)
    finally:
        if prev is None:
            os.environ.pop(var, None)
        else:
            os.environ[var] = prev


def main():
    args = sys.argv[1:]
    encoding = "angle"
    if "--encoding" in args:
        i = args.index("--encoding")
        if i + 1 >= len(args) or args[i + 1].startswith("-"):
            sys.exit("usage: fused_sweep.py [--encoding angle|reupload] "
                     "[--bf16] [n_qubits ...]")
        encoding = args[i + 1]
        args = args[:i] + args[i + 2 :]
    with_bf16 = "--bf16" in args
    if with_bf16:
        args.remove("--bf16")
    qubits = [int(a) for a in args] or [10, 12, 13, 14, 16]
    from qfedx_tpu.ops.fused_hea import fused_eligible

    for n in qubits:
        row = {
            "n_qubits": n, "n_layers": 3, "batch": 64, "encoding": encoding
        }
        t = lambda m: timeit(m, encoding=encoding)  # noqa: E731
        try:
            row["xla_s"] = round(with_env("QFEDX_FUSED", "0", t, n), 5)
            if not fused_eligible(n):
                # QFEDX_FUSED=1 is a no-op outside 8 ≤ n ≤ 16: timing the
                # "fused" config would just re-measure the XLA path and
                # record a fabricated ~1.0× parity row.
                row["fused_s"] = None
                row["note"] = "n outside fused-eligible range; XLA only"
                print(json.dumps(row), flush=True)
                continue
            row["fused_s"] = round(with_env("QFEDX_FUSED", "1", t, n), 5)
            row["fused_speedup_vs_xla"] = round(row["xla_s"] / row["fused_s"], 3)
            if with_bf16:
                row["fused_bf16_s"] = round(
                    with_env("QFEDX_DTYPE", "bf16",
                             lambda m: with_env("QFEDX_FUSED", "1", t, m), n),
                    5,
                )
                row["xla_bf16_s"] = round(
                    with_env("QFEDX_DTYPE", "bf16",
                             lambda m: with_env("QFEDX_FUSED", "0", t, m), n),
                    5,
                )
                row["fused_bf16_speedup_vs_xla_f32"] = round(
                    row["xla_s"] / row["fused_bf16_s"], 3
                )
            if os.environ.get("QFEDX_FUSED_BB"):
                row["bb"] = int(os.environ["QFEDX_FUSED_BB"])
        except Exception as e:  # noqa: BLE001 — report per-config
            row["error"] = f"{type(e).__name__}: {e}"
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
