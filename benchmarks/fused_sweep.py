"""Measure the fused whole-circuit kernel vs the XLA slab engine on TPU.

Usage: python benchmarks/fused_sweep.py [n_qubits ...]
       python benchmarks/fused_sweep.py --encoding reupload [n_qubits ...]
Prints one JSON line per config: fwd+grad seconds per step for the
default XLA path (the r04 slab engine, QFEDX_FUSED unset/0) and
QFEDX_FUSED=1 (whole-circuit Pallas kernel), with the speedup. This is
the data behind the r04 routing decision (ops.fused_hea.fused_enabled:
auto routing to the kernel DISABLED — the slab engine measured faster
at every width, both encodings; docs/PERF.md §4). The reupload rows
answer VERDICT r03 item 2: config 4's circuit (~2× the gates/layer of
plain HEA) measured on its own kernel rather than assumed.
"""

from __future__ import annotations

import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root


def timeit(n_qubits, n_layers=3, batch=64, reps=5, encoding="angle"):
    import jax

    from benchmarks._util import build_step, timed_median

    fn, params, steps = build_step(
        n_qubits, n_layers, batch, encoding=encoding
    )
    return timed_median(fn, params, steps, reps, label=f"n={n_qubits}")


def with_env(var, val, fn, *a):
    prev = os.environ.get(var)
    os.environ[var] = val
    try:
        return fn(*a)
    finally:
        if prev is None:
            os.environ.pop(var, None)
        else:
            os.environ[var] = prev


def main():
    args = sys.argv[1:]
    encoding = "angle"
    if "--encoding" in args:
        i = args.index("--encoding")
        if i + 1 >= len(args) or args[i + 1].startswith("-"):
            sys.exit("usage: fused_sweep.py [--encoding angle|reupload] "
                     "[--bf16] [n_qubits ...]")
        encoding = args[i + 1]
        args = args[:i] + args[i + 2 :]
    with_bf16 = "--bf16" in args
    if with_bf16:
        args.remove("--bf16")
    xla_only = "--xla-only" in args
    if xla_only:
        # r04: the reupload Pallas kernel's Mosaic compile is SIGKILLed
        # (OOM) by the tunnel's chipless AOT compile helper at every
        # width tried — XLA-only rows are the honest obtainable data.
        args.remove("--xla-only")
    qubits = [int(a) for a in args] or [10, 12, 13, 14, 16]
    from qfedx_tpu.ops.fused_hea import fused_eligible

    for n in qubits:
        row = {
            "n_qubits": n, "n_layers": 3, "batch": 64, "encoding": encoding
        }
        t = lambda m: timeit(m, encoding=encoding)  # noqa: E731
        try:
            row["xla_s"] = round(with_env("QFEDX_FUSED", "0", t, n), 5)
            if xla_only:
                row["note"] = "xla-only run (--xla-only)"
                print(json.dumps(row), flush=True)
                continue
            if not fused_eligible(n):
                # QFEDX_FUSED=1 is a no-op outside 8 ≤ n ≤ 16: timing the
                # "fused" config would just re-measure the XLA path and
                # record a fabricated ~1.0× parity row.
                row["fused_s"] = None
                row["note"] = "n outside fused-eligible range; XLA only"
                print(json.dumps(row), flush=True)
                continue
            # timed_median raises on the ~0s artifact, so fused_s > 0 here.
            row["fused_s"] = round(with_env("QFEDX_FUSED", "1", t, n), 5)
            row["fused_speedup_vs_xla"] = round(
                row["xla_s"] / row["fused_s"], 3
            )
            if with_bf16:
                row["fused_bf16_s"] = round(
                    with_env("QFEDX_DTYPE", "bf16",
                             lambda m: with_env("QFEDX_FUSED", "1", t, m), n),
                    5,
                )
                row["xla_bf16_s"] = round(
                    with_env("QFEDX_DTYPE", "bf16",
                             lambda m: with_env("QFEDX_FUSED", "0", t, m), n),
                    5,
                )
                row["fused_bf16_speedup_vs_xla_f32"] = round(
                    row["xla_s"] / row["fused_bf16_s"], 3
                )
            if os.environ.get("QFEDX_FUSED_BB"):
                row["bb"] = int(os.environ["QFEDX_FUSED_BB"])
        except Exception as e:  # noqa: BLE001 — report per-config
            row["error"] = f"{type(e).__name__}: {e}"
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
