"""Profile the n=16 fwd+grad training step on the real chip.

VERDICT r03 item 1: the bf16 null result (1.00x on dense despite halved
HBM bytes) falsified the "HBM-bound" model and est_flop_util sits at
0.69% — so the time is going somewhere no analytic byte count predicts.
This script measures instead of estimating:

  1. reproduces the bench timing (dense slab path);
  2. captures a ``jax.profiler.trace`` of each;
  3. parses the trace protobuf/json and prints a per-op time breakdown.

Run:  python benchmarks/profile_step.py [--trace-dir /tmp/qfedx-prof]
Findings land in docs/PERF.md (written by hand from this output).
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


from benchmarks._util import (
    build_step,
    device_sync,
    enable_cache,
    timed_median,
)

# The HLO state-sized-op census lives in the package proper now
# (qfedx_tpu/obs/hlo.py — importable observability primitive, shared
# with bench.py's fusion_hlo section and the tier-1 regression test);
# re-exported here so existing callers keep working.
from qfedx_tpu.obs.hlo import count_state_ops, module_counts  # noqa: E402,F401


def parse_trace(trace_dir):
    """Aggregate device-op durations from the newest trace.json.gz."""
    paths = sorted(
        glob.glob(
            os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True
        ),
        key=os.path.getmtime,
    )
    if not paths:
        return None, None
    with gzip.open(paths[-1], "rt") as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])
    # Identify device-side process/thread ids (TPU op track).
    proc_names = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            proc_names[e["pid"]] = e["args"].get("name", "")
    device_pids = {
        pid
        for pid, name in proc_names.items()
        if "TPU" in name or "/device" in name.lower() or "Chip" in name
    }
    by_op = defaultdict(float)
    total = 0.0
    n_events = 0
    for e in events:
        if e.get("ph") != "X":
            continue
        if device_pids and e.get("pid") not in device_pids:
            continue
        dur = e.get("dur", 0) / 1e6  # us -> s
        by_op[e.get("name", "?")] += dur
        total += dur
        n_events += 1
    return by_op, {"total_s": total, "n_events": n_events, "file": paths[-1],
                   "proc_names": proc_names}


def group_ops(by_op):
    """Bucket XLA op names into readable categories."""
    buckets = defaultdict(float)
    for name, t in by_op.items():
        low = name.lower()
        if "fusion" in low:
            key = "fusion"
        elif "dot" in low or "convolution" in low:
            key = "dot/conv"
        elif "transpose" in low or "copy" in low:
            key = "transpose/copy"
        elif "reduce" in low:
            key = "reduce"
        elif "dynamic" in low:
            key = "dynamic-slice/update"
        elif "custom" in low or "mosaic" in low or "tpu_custom_call" in low:
            key = "pallas-kernel"
        else:
            key = "other"
        buckets[key] += t
    return buckets


def run_hlo_counts(args):
    """Before/after-fusion op counts for the ONE-step program (the
    floor-reduction claim measured, not asserted — ISSUE r07 satellite).
    Env pins are read at trace time, so each route builds fresh."""
    import jax

    from benchmarks._util import with_env

    compiled = jax.default_backend() == "tpu"  # see module_counts
    results = {}
    for pin, label in (("1", "fused"), ("off", "unfused")):

        def one():
            fn, params, _ = build_step(
                args.n, args.layers, args.batch, 1, remat=args.remat
            )
            return module_counts(fn, params, args.n, compiled=compiled)

        results[label] = with_env({"QFEDX_FUSE": pin}, one)
    for label, row in results.items():
        print(f"[hlo:{label}] " + " ".join(f"{k}={v}" for k, v in row.items()))
    f, u = results.get("fused", {}), results.get("unfused", {})
    if "lowered_state_ops" in f and "lowered_state_ops" in u:
        print(
            f"[hlo] state-sized op reduction: {u['lowered_state_ops']} -> "
            f"{f['lowered_state_ops']} "
            f"({u['lowered_state_ops'] / max(f['lowered_state_ops'], 1):.2f}x)"
        )
    return results


def run_one(tag, trace_dir, args):
    """Time + trace one configuration (QFEDX_* env set by the caller
    BEFORE the model is built — routing is read at build/trace time)."""
    import jax

    fn, params, steps = build_step(
        args.n, args.layers, args.batch, args.steps, remat=args.remat
    )
    t = timed_median(fn, params, steps, label=f"n={args.n}")
    print(f"[{tag}] fwd+grad per step: {t*1e3:.2f} ms")
    tdir = os.path.join(trace_dir, tag)
    os.makedirs(tdir, exist_ok=True)
    # Chain + fetch-anchor inside the trace too: identical-input
    # re-dispatches are elided and bare block_until_ready can ack
    # unexecuted work (docs/PERF.md §6) — either would leave the trace
    # empty or partial.
    with jax.profiler.trace(tdir):
        for _ in range(2):
            params, ls = fn(params)
        device_sync(params)
    by_op, meta = parse_trace(tdir)
    if by_op is None:
        print(f"[{tag}] no trace file produced under {tdir}")
        return t, None
    print(f"[{tag}] trace: {meta['n_events']} device events, "
          f"{meta['total_s']*1e3:.1f} ms total device time "
          f"({meta['file']})")
    buckets = group_ops(by_op)
    for k, v in sorted(buckets.items(), key=lambda kv: -kv[1]):
        print(f"  {k:24s} {v*1e3:9.2f} ms  ({100*v/meta['total_s']:5.1f}%)")
    print(f"[{tag}] top 15 ops:")
    for name, v in sorted(by_op.items(), key=lambda kv: -kv[1])[:15]:
        print(f"  {v*1e3:9.2f} ms  {name[:110]}")
    return t, by_op


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace-dir", default="/tmp/qfedx-prof")
    ap.add_argument("--n", type=int, default=16)
    ap.add_argument("--layers", type=int, default=3)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--remat", action="store_true",
                    help="per-layer jax.checkpoint (the retired r04 n=20 "
                    "config — reproduces the cliff of docs/PERF.md §7; "
                    "the shipped bench runs n=20 without remat)")
    ap.add_argument("--hlo-only", action="store_true",
                    help="skip timing/tracing; report lowered + compiled "
                    "op counts with the fusion pass on vs off (the r07 "
                    "floor-reduction evidence — PERF.md §12). Runnable "
                    "off-chip with the TPU routing pinned (QFEDX_GATE_"
                    "FORM=flip QFEDX_SLAB_LANES=matmul QFEDX_BATCHED=1).")
    args = ap.parse_args()

    import jax

    enable_cache(jax)
    print(f"devices: {jax.devices()}")

    if args.hlo_only:
        run_hlo_counts(args)
        return

    run_one("xla", args.trace_dir, args)
    # Op-count evidence rides along with every profile: the same step
    # program's emitted + compiled op counts, fusion pass on vs off.
    run_hlo_counts(args)


if __name__ == "__main__":
    main()
