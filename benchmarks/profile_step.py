"""Profile the n=16 fwd+grad training step on the real chip.

VERDICT r03 item 1: the bf16 null result (1.00x on dense despite halved
HBM bytes) falsified the "HBM-bound" model and est_flop_util sits at
0.69% — so the time is going somewhere no analytic byte count predicts.
This script measures instead of estimating:

  1. reproduces the bench timing (dense slab path);
  2. captures a ``jax.profiler.trace`` of each;
  3. parses the trace protobuf/json and prints a per-op time breakdown.

Run:  python benchmarks/profile_step.py [--trace-dir /tmp/qfedx-prof]
Findings land in docs/PERF.md (written by hand from this output).
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import sys
import time
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


from benchmarks._util import (
    build_step,
    device_sync,
    enable_cache,
    timed_median,
)

# The HLO state-sized-op census lives in the package proper now
# (qfedx_tpu/obs/hlo.py — importable observability primitive, shared
# with bench.py's fusion_hlo section and the tier-1 regression test);
# re-exported here so existing callers keep working.
from qfedx_tpu.obs.hlo import count_state_ops, module_counts  # noqa: E402,F401


def parse_trace(trace_dir):
    """Aggregate device-op durations from the newest trace.json.gz."""
    paths = sorted(
        glob.glob(
            os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True
        ),
        key=os.path.getmtime,
    )
    if not paths:
        return None, None
    with gzip.open(paths[-1], "rt") as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])
    # Identify device-side process/thread ids (TPU op track).
    proc_names = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            proc_names[e["pid"]] = e["args"].get("name", "")
    device_pids = {
        pid
        for pid, name in proc_names.items()
        if "TPU" in name or "/device" in name.lower() or "Chip" in name
    }
    by_op = defaultdict(float)
    total = 0.0
    n_events = 0
    for e in events:
        if e.get("ph") != "X":
            continue
        if device_pids and e.get("pid") not in device_pids:
            continue
        dur = e.get("dur", 0) / 1e6  # us -> s
        by_op[e.get("name", "?")] += dur
        total += dur
        n_events += 1
    return by_op, {"total_s": total, "n_events": n_events, "file": paths[-1],
                   "proc_names": proc_names}


def group_ops(by_op):
    """Bucket XLA op names into readable categories."""
    buckets = defaultdict(float)
    for name, t in by_op.items():
        low = name.lower()
        if "fusion" in low:
            key = "fusion"
        elif "dot" in low or "convolution" in low:
            key = "dot/conv"
        elif "transpose" in low or "copy" in low:
            key = "transpose/copy"
        elif "reduce" in low:
            key = "reduce"
        elif "dynamic" in low:
            key = "dynamic-slice/update"
        elif "custom" in low or "mosaic" in low or "tpu_custom_call" in low:
            key = "pallas-kernel"
        else:
            key = "other"
        buckets[key] += t
    return buckets


def run_hlo_counts(args):
    """Before/after-fusion op counts for the ONE-step program (the
    floor-reduction claim measured, not asserted — ISSUE r07 satellite).
    Env pins are read at trace time, so each route builds fresh."""
    import jax

    from benchmarks._util import with_env

    compiled = jax.default_backend() == "tpu"  # see module_counts
    results = {}
    for pin, label in (("1", "fused"), ("off", "unfused")):

        def one():
            fn, params, _ = build_step(
                args.n, args.layers, args.batch, 1, remat=args.remat
            )
            return module_counts(fn, params, args.n, compiled=compiled)

        results[label] = with_env({"QFEDX_FUSE": pin}, one)
    for label, row in results.items():
        print(f"[hlo:{label}] " + " ".join(f"{k}={v}" for k, v in row.items()))
    f, u = results.get("fused", {}), results.get("unfused", {})
    if "lowered_state_ops" in f and "lowered_state_ops" in u:
        print(
            f"[hlo] state-sized op reduction: {u['lowered_state_ops']} -> "
            f"{f['lowered_state_ops']} "
            f"({u['lowered_state_ops'] / max(f['lowered_state_ops'], 1):.2f}x)"
        )
    return results


def run_one(tag, trace_dir, args):
    """Time + trace one configuration (QFEDX_* env set by the caller
    BEFORE the model is built — routing is read at build/trace time)."""
    import jax

    fn, params, steps = build_step(
        args.n, args.layers, args.batch, args.steps, remat=args.remat
    )
    t = timed_median(fn, params, steps, label=f"n={args.n}")
    print(f"[{tag}] fwd+grad per step: {t*1e3:.2f} ms")
    tdir = os.path.join(trace_dir, tag)
    os.makedirs(tdir, exist_ok=True)
    # Chain + fetch-anchor inside the trace too: identical-input
    # re-dispatches are elided and bare block_until_ready can ack
    # unexecuted work (docs/PERF.md §6) — either would leave the trace
    # empty or partial.
    with jax.profiler.trace(tdir):
        for _ in range(2):
            params, ls = fn(params)
        device_sync(params)
    by_op, meta = parse_trace(tdir)
    if by_op is None:
        print(f"[{tag}] no trace file produced under {tdir}")
        return t, None
    print(f"[{tag}] trace: {meta['n_events']} device events, "
          f"{meta['total_s']*1e3:.1f} ms total device time "
          f"({meta['file']})")
    buckets = group_ops(by_op)
    for k, v in sorted(buckets.items(), key=lambda kv: -kv[1]):
        print(f"  {k:24s} {v*1e3:9.2f} ms  ({100*v/meta['total_s']:5.1f}%)")
    print(f"[{tag}] top 15 ops:")
    for name, v in sorted(by_op.items(), key=lambda kv: -kv[1])[:15]:
        print(f"  {v*1e3:9.2f} ms  {name[:110]}")
    return t, by_op


def run_serve_profile(args):
    """Profile the SERVED forward (r14): per-bucket warmup compile wall,
    steady-state per-batch/per-request latency of the persistent
    compiled forward, and its lowered op census — the serving half of
    the PERF.md §15 floor methodology. The forward goes through the
    SAME persistent-forward cache production serving uses
    (serve/forward.py), so what is measured is what serves."""
    import jax
    import numpy as np

    from benchmarks._util import retry_timing
    from qfedx_tpu.models.vqc import make_vqc_classifier
    from qfedx_tpu.obs.hlo import module_counts
    from qfedx_tpu.serve.forward import persistent_forward

    model = make_vqc_classifier(
        n_qubits=args.n, n_layers=args.layers, num_classes=2,
        remat=args.remat,
    )
    params = model.init(jax.random.PRNGKey(0))
    fwd = persistent_forward(model.apply)
    rng = np.random.default_rng(0)
    buckets = tuple(int(b) for b in args.buckets.split(","))
    compiled = jax.default_backend() == "tpu"
    reps = 16
    for b in buckets:
        x = rng.uniform(0, 1, (b, args.n)).astype(np.float32)
        t0 = time.perf_counter()
        np.asarray(fwd(params, x))  # warmup: compile this bucket
        warm_s = time.perf_counter() - t0

        def measure():
            t0 = time.perf_counter()
            out = None
            for _ in range(reps):
                out = fwd(params, x)
            np.asarray(out)  # ONE fetch anchors true completion (§6)
            return (time.perf_counter() - t0) / reps

        t = retry_timing(measure, floor=1e-6, label=f"serve b={b}")
        print(f"[serve] bucket {b:4d}: warmup {warm_s*1e3:8.1f} ms, "
              f"batch {t*1e3:8.3f} ms, per-request {t/b*1e6:8.1f} us")
    xm = rng.uniform(0, 1, (buckets[-1], args.n)).astype(np.float32)
    counts = module_counts(
        jax.jit(lambda p: model.apply(p, xm)), params, args.n,
        compiled=compiled,
    )
    print("[serve:hlo] " + " ".join(f"{k}={v}" for k, v in counts.items()))


def run_device_profile(args):
    """``--device-profile`` (r16): the MEASURED device timeline of the
    step program — a crash-safe capture parsed into the runtime op
    census (obs/profile.py, no TF protos), printed as top-K ops, the
    inter-op gap quantiles, device-busy fraction, and the
    measured-vs-static floor attribution that PERF.md §16 records.
    The static side comes through the same ``obs.hlo.lowered_state_ops``
    helper bench.py's fusion_hlo / floor_attribution sections use."""
    from qfedx_tpu.obs import profile as obs_profile
    from qfedx_tpu.obs.hlo import lowered_state_ops

    fn, params, steps = build_step(
        args.n, args.layers, args.batch, args.steps, remat=args.remat
    )
    static = lowered_state_ops(fn, params, args.n)
    params, ls = fn(params)  # warm: compile outside the capture window
    device_sync(ls)
    tdir = os.path.join(args.trace_dir, "device")
    with obs_profile.capture(tdir):
        params, ls = fn(params)
        device_sync(params)
    parsed = obs_profile.parse_capture(tdir)
    summary = obs_profile.summarize(
        parsed, static_state_ops=static, steps=steps
    )
    print(f"[device] capture: {summary['capture']} "
          f"({summary['device_lanes']} lanes)")
    print(f"[device] ops executed: {summary['ops_executed']} "
          f"({summary['ops_per_step']}/step) vs static state census "
          f"{static} -> measured_vs_static {summary['measured_vs_static']}")
    print(f"[device] busy {summary['device_busy_s']*1e3:.1f} ms of "
          f"{summary['device_window_s']*1e3:.1f} ms window "
          f"(fraction {summary['device_busy_fraction']})")
    print(f"[device] inter-op gap: p50 {summary['gap_p50_us']} us, "
          f"p95 {summary['gap_p95_us']} us, mean {summary['gap_mean_us']} us "
          f"over {summary['gap_count']} gaps")
    print(f"[device] top {len(summary['top_ops'])} ops by device time:")
    for row in summary["top_ops"]:
        print(f"  {row['total_ms']:9.2f} ms total {row['self_ms']:9.2f} ms "
              f"self  x{row['count']:<5d} {row['op'][:80]}")
    print("[device:floor] " + json.dumps(
        obs_profile.floor_attribution(static, summary)
    ))
    return summary


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace-dir", default="/tmp/qfedx-prof")
    ap.add_argument("--n", type=int, default=16)
    ap.add_argument("--layers", type=int, default=3)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--remat", action="store_true",
                    help="per-layer jax.checkpoint (the retired r04 n=20 "
                    "config — reproduces the cliff of docs/PERF.md §7; "
                    "the shipped bench runs n=20 without remat)")
    ap.add_argument("--serve", action="store_true",
                    help="profile the SERVED forward instead of the "
                    "training step: per-bucket warmup compile wall + "
                    "steady-state batch latency + lowered op census "
                    "through the production persistent-forward cache "
                    "(PERF.md §15; docs/SERVING.md)")
    ap.add_argument("--buckets", default="1,8,32",
                    help="--serve: comma-separated bucket batch shapes")
    ap.add_argument("--device-profile", action="store_true",
                    help="capture + parse the DEVICE timeline of the "
                    "step program (obs/profile.py): measured op census "
                    "vs the static HLO census, inter-op gap histogram "
                    "quantiles, device-busy fraction, top-K ops — the "
                    "measured form of the PERF.md §15 floor model "
                    "(docs/PERF.md §16)")
    ap.add_argument("--hlo-only", action="store_true",
                    help="skip timing/tracing; report lowered + compiled "
                    "op counts with the fusion pass on vs off (the r07 "
                    "floor-reduction evidence — PERF.md §12). Runnable "
                    "off-chip with the TPU routing pinned (QFEDX_GATE_"
                    "FORM=flip QFEDX_SLAB_LANES=matmul QFEDX_BATCHED=1).")
    args = ap.parse_args()

    import jax

    enable_cache(jax)
    print(f"devices: {jax.devices()}")

    if args.serve:
        run_serve_profile(args)
        return
    if args.device_profile:
        run_device_profile(args)
        return
    if args.hlo_only:
        run_hlo_counts(args)
        return

    run_one("xla", args.trace_dir, args)
    # Op-count evidence rides along with every profile: the same step
    # program's emitted + compiled op counts, fusion pass on vs off.
    run_hlo_counts(args)


if __name__ == "__main__":
    main()
