"""Lint: no bare ``print()`` inside ``qfedx_tpu/`` outside the CLI/demo.

Rehosted (r18): the single definition now lives on the unified
analysis engine — ``qfedx_tpu.analysis.rules_prints`` (rule **QFX105**
under ``qfedx lint``; docs/ANALYSIS.md has the taxonomy). This wrapper
keeps the historical surface alive verbatim for tests/test_no_print.py
and standalone runs. The contract is unchanged: telemetry goes through
``obs`` and ``run/metrics``, progress text through the primary-gated
``say`` — a stray library ``print`` interleaves across multi-host pods
and reaches no exporter.
"""

from __future__ import annotations

import sys
from pathlib import Path

_REPO = Path(__file__).resolve().parent.parent
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

from qfedx_tpu.analysis.rules_prints import (  # noqa: E402,F401
    ALLOWED,
    find_prints,
)


def main() -> int:
    offenders = find_prints()
    if offenders:
        print("bare print() in qfedx_tpu/ (route through obs/metrics/say):")
        for off in offenders:
            print(f"  qfedx_tpu/{off}")
        return 1
    print("ok: no bare print() outside run/cli.py, run/demo.py")
    return 0


if __name__ == "__main__":
    sys.exit(main())
