"""Lint: no bare ``print()`` inside ``qfedx_tpu/`` outside the CLI/demo.

Telemetry goes through ``obs`` (spans/counters) and ``run/metrics``
(JSONL artifacts); progress text goes through the primary-gated ``say``
in ``run/cli.py``. A stray ``print`` in library code interleaves across
multi-host pods (utils/host.py docstring) and is invisible to every
exporter — the reference's whole observability story was prints, which
is exactly what this repo replaces (run/metrics.py docstring).

AST-based (string literals and docstrings mentioning print are fine);
wired as a tier-1 test in tests/test_no_print.py and runnable
standalone: ``python benchmarks/check_no_print.py`` exits non-zero with
offender ``path:line`` lines.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

# Files whose job is terminal output: the argparse CLI (primary-gated
# ``say``) and the walkthrough demo script.
ALLOWED = {"run/cli.py", "run/demo.py"}


def find_prints(package_root: str | Path | None = None) -> list[str]:
    """``["rel/path.py:lineno", ...]`` of bare print() calls under
    ``package_root`` (default: the qfedx_tpu package next to this
    repo's benchmarks/), excluding ALLOWED."""
    if package_root is None:
        package_root = Path(__file__).resolve().parent.parent / "qfedx_tpu"
    root = Path(package_root)
    offenders: list[str] = []
    for py in sorted(root.rglob("*.py")):
        rel = py.relative_to(root).as_posix()
        if rel in ALLOWED or "__pycache__" in rel:
            continue
        tree = ast.parse(py.read_text(), filename=str(py))
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"
            ):
                offenders.append(f"{rel}:{node.lineno}")
    return offenders


def main() -> int:
    offenders = find_prints()
    if offenders:
        print("bare print() in qfedx_tpu/ (route through obs/metrics/say):")
        for off in offenders:
            print(f"  qfedx_tpu/{off}")
        return 1
    print("ok: no bare print() outside run/cli.py, run/demo.py")
    return 0


if __name__ == "__main__":
    sys.exit(main())
