"""Lint: obs/watch alert rules match the documented taxonomy.

Thin wrapper (the check_pins/check_spans pattern): the single
definition lives on the unified analysis engine —
``qfedx_tpu.analysis.rules_doc`` (rule **QFX106** under ``qfedx
lint``; docs/ANALYSIS.md has the taxonomy). The contract: every rule
ID in ``obs/watch.RULES`` has a row in docs/OBSERVABILITY.md's
"## Alert-rule taxonomy" table, every row names a live rule, and each
row's threshold-pin cell names the pin the rule actually reads — the
operator paged by a ``qfedx_alert_*`` gauge looks the ID up in exactly
one place, which must not lie about the retuning knob.
"""

from __future__ import annotations

import sys
from pathlib import Path

_REPO = Path(__file__).resolve().parent.parent
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

from qfedx_tpu.analysis.rules_doc import (  # noqa: E402,F401
    check_alerts,
    documented_alert_rules,
)


def main() -> int:
    problems = check_alerts()
    if problems:
        print("alert-rule taxonomy drift (docs/OBSERVABILITY.md):")
        for p in problems:
            print(f"  {p}")
        return 1
    print(
        f"ok: {len(documented_alert_rules())} alert rules, obs/watch.py "
        "and docs/OBSERVABILITY.md table agree"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
