"""``python -m qfedx_tpu`` entry.

The platform request must be honored BEFORE any qfedx_tpu import: the
gate library materializes jnp constants at import time, which initializes
the jax backend — after that, a sitecustomize-preselected TPU platform
can no longer be switched away from (e.g. ``JAX_PLATFORMS=cpu`` for the
8-device virtual host mesh that tests and CPU sweeps use).
"""

import os

_want = os.environ.get("JAX_PLATFORMS")
if _want:
    import jax

    jax.config.update("jax_platforms", _want)

from qfedx_tpu.run.cli import main  # noqa: E402

main()
