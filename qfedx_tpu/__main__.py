from qfedx_tpu.run.cli import main

main()
