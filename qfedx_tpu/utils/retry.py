"""Exponential backoff with a deadline — the ONE retry policy for host I/O.

The r10 ingestion/checkpoint threads treat every error as fatal: a
single transient registry hiccup kills the uploader, a momentary
filesystem stall fails the async checkpoint write, and in both cases the
error only surfaces after the fact (ISSUE r11 satellites). At
million-client scale transient host-side failures are the NORMAL case —
the retry policy must be shared, deterministic, and bounded, not
hand-rolled per call site (the same consolidation argument as
``utils/pins``: by the time the third copy exists, two have drifted).

``retry_with_deadline(fn)`` calls ``fn(attempt)`` up to ``attempts``
times, sleeping ``base_delay · 2^k`` (capped at ``max_delay``) between
tries, never past ``deadline_s`` total. The attempt INDEX is passed to
``fn`` so callers can key deterministic fault injection
(``utils/faults``) and logging off it.

Jitter is SEEDED, never random (r12 satellite): pass ``jitter_site``
(a stable string naming the call site — e.g. ``"ingest/<round>/<wave>"``)
and each sleep is scaled by a factor in [0.5, 1.0) hashed from
(site, attempt). Concurrent uploader threads and processes therefore
de-correlate their backoff schedules — no lockstep retry stampede
against a recovering registry — while every schedule stays a pure
function of its coordinates: reruns, resumes and the fault harness see
identical timing, and a test can predict the exact delays
(tests/test_faults.py). ``jitter_site=None`` (the default) keeps the
bare exponential schedule.

On exhaustion a typed ``RetryExhausted`` raises, chaining the last
error (``__cause__``) and carrying ``attempts``/``elapsed_s`` — callers
that need the root cause for their own typed error (``StreamError``,
``CheckpointWriteError``) unwrap ``.last``.
"""

from __future__ import annotations

import hashlib
import time
from typing import Any, Callable, Iterable


def jitter_factor(site: str, attempt: int) -> float:
    """Deterministic backoff jitter in [0.5, 1.0): a pure hash of
    (site, attempt) — no ``random``, no process state. blake2b (not
    Python's ``hash``) because PYTHONHASHSEED randomization would make
    schedules differ across reruns, which is exactly what the fault
    harness must never see."""
    digest = hashlib.blake2b(
        f"{site}#{attempt}".encode(), digest_size=8
    ).digest()
    return 0.5 + 0.5 * (int.from_bytes(digest, "little") / 2.0**64)


class RetryExhausted(RuntimeError):
    """All attempts failed (or the deadline expired); ``.last`` is the
    final error, also chained as ``__cause__``."""

    def __init__(self, describe: str, attempts: int, elapsed_s: float,
                 last: BaseException):
        super().__init__(
            f"{describe} failed after {attempts} attempt(s) in "
            f"{elapsed_s:.2f}s: {type(last).__name__}: {last}"
        )
        self.attempts = attempts
        self.elapsed_s = elapsed_s
        self.last = last


def retry_with_deadline(
    fn: Callable[[int], Any],
    *,
    attempts: int = 3,
    base_delay_s: float = 0.05,
    max_delay_s: float = 1.0,
    deadline_s: float = 30.0,
    retry_on: Iterable[type[BaseException]] = (Exception,),
    describe: str = "operation",
    sleep: Callable[[float], None] = time.sleep,
    jitter_site: str | None = None,
) -> Any:
    """Run ``fn(attempt)``, retrying failed attempts with exponential
    backoff until success, ``attempts`` tries, or ``deadline_s`` wall —
    whichever first. Non-``retry_on`` exceptions propagate immediately
    (a KeyboardInterrupt must never be eaten by a backoff loop).
    ``sleep`` is injectable so tests pin the schedule without waiting.
    ``jitter_site`` turns on seeded schedule jitter (module docstring):
    delay k becomes ``min(base·2^k, max) · jitter_factor(site, k)``.
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    retry_on = tuple(retry_on)
    t0 = time.monotonic()
    last: BaseException | None = None
    for k in range(attempts):
        try:
            return fn(k)
        except retry_on as exc:  # noqa: PERF203 — the loop IS the policy
            last = exc
            elapsed = time.monotonic() - t0
            out_of_time = elapsed >= deadline_s
            if k == attempts - 1 or out_of_time:
                raise RetryExhausted(
                    describe, k + 1, elapsed, last
                ) from last
            delay = min(base_delay_s * (2.0 ** k), max_delay_s)
            if jitter_site is not None:
                delay *= jitter_factor(jitter_site, k)
            # Never sleep past the deadline: the next attempt must start
            # while there is still budget to fail it properly.
            delay = min(delay, max(0.0, deadline_s - elapsed))
            if delay > 0:
                sleep(delay)
    raise AssertionError("unreachable")  # pragma: no cover
