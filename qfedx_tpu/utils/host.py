"""Multi-host process-role helpers.

``parallel.mesh`` targets multi-host TPU pods, where every host runs the
same program (SPMD). Host-side artifacts — checkpoints, metrics JSONL,
summary files, progress prints — must be written by exactly one process or
concurrent writes to shared storage corrupt/duplicate them (the reference
is single-process and never faces this; src/CFed/Classical_FL.py prints
freely). Everything in ``run/`` that touches disk or stdout gates on
``is_primary()``.
"""

from __future__ import annotations

import jax


def is_primary() -> bool:
    """True on the process that owns host-side IO (process 0)."""
    return jax.process_index() == 0
