"""Multi-host process-role helpers.

``parallel.mesh`` targets multi-host TPU pods, where every host runs the
same program (SPMD). Host-side artifacts — checkpoints, metrics JSONL,
summary files, progress prints — must be written by exactly one process or
concurrent writes to shared storage corrupt/duplicate them (the reference
is single-process and never faces this; src/CFed/Classical_FL.py prints
freely). Everything in ``run/`` that touches disk or stdout gates on
``is_primary()``.
"""

from __future__ import annotations

import jax


def is_primary() -> bool:
    """True on the process that owns host-side IO (process 0)."""
    return jax.process_index() == 0


def install_sigterm_interrupt():
    """Translate SIGTERM into ``KeyboardInterrupt("SIGTERM")`` so an
    orchestrator's TERM drains exactly like a Ctrl-C (the r13 graceful-
    shutdown discipline, shared by the streamed trainer and ``qfedx
    serve`` — one hardened copy, because the first duplicate had
    already drifted on the restore path).

    Returns an opaque token for ``restore_sigterm``; None when no
    handler was installed (non-main thread, or an exotic embedding
    where ``signal.signal`` is rejected) — the caller simply runs
    unguarded then.
    """
    import signal
    import threading

    if threading.current_thread() is not threading.main_thread():
        return None

    def _on_sigterm(signum, frame):
        raise KeyboardInterrupt("SIGTERM")

    try:
        prev = signal.signal(signal.SIGTERM, _on_sigterm)
    except (ValueError, OSError):  # signals unavailable; run unguarded
        return None
    return (prev,)


def restore_sigterm(token) -> None:
    """Undo ``install_sigterm_interrupt``. A previous handler installed
    outside Python reads back as None — restore SIG_DFL then, never
    leave our raise-KeyboardInterrupt handler behind."""
    if token is None:
        return
    import signal

    (prev,) = token
    try:
        signal.signal(
            signal.SIGTERM, prev if prev is not None else signal.SIG_DFL
        )
    except (ValueError, TypeError, OSError):
        pass
