from qfedx_tpu.utils import trees  # noqa: F401
