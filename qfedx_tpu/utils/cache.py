"""Persistent XLA compilation cache — the QFEDX_COMPILE_CACHE pin.

The big slab/fed programs take minutes to compile cold (~50 s for the
n=18 engine step on the bench chip). bench.py has always pointed JAX's
persistent compilation cache at a repo-local directory so every run
after the first starts hot — but CLI users paid the full cold compile
every process. This module is the ONE definition both entry points use:
``benchmarks/_util.enable_cache`` delegates here with its repo-local
default directory, and ``run/cli.py`` calls ``enable_compile_cache``
before the first compile of a training run.

``QFEDX_COMPILE_CACHE`` (read when the cache is enabled, i.e. before
the first compile — it configures process-global jax state, not traced
program structure):

- ``0`` / ``off`` — disabled (every compile is cold);
- unset / ``1`` / ``on`` — enabled at the caller's default directory
  (the CLI uses ``~/.cache/qfedx_tpu/xla``; bench keeps the repo-local
  ``.jax_cache`` its committed artifacts were produced with);
- a path (contains a separator, or starts with ``~``/``.``) — enable
  AND redirect there, e.g. to pod-shared storage;
- anything else raises — the loud-typo convention every QFEDX_* pin
  follows (a typoed off value must not silently measure the cached
  path).
"""

from __future__ import annotations

import os

from qfedx_tpu.utils import pins

_DEFAULT_DIR = os.path.join("~", ".cache", "qfedx_tpu", "xla")


def compile_cache_dir(default: str | None = None) -> str | None:
    """Resolve the cache directory from QFEDX_COMPILE_CACHE (see module
    docstring); ``None`` means the cache is pinned off."""
    env = pins.str_pin("QFEDX_COMPILE_CACHE")
    if env is None:
        return os.path.expanduser(default or _DEFAULT_DIR)
    as_bool = pins.parse_onoff(env)
    if as_bool is False:
        return None
    if as_bool is True:
        return os.path.expanduser(default or _DEFAULT_DIR)
    if os.sep in env or env.startswith(("~", ".")):
        return os.path.expanduser(env)
    raise ValueError(
        f"QFEDX_COMPILE_CACHE={env!r}: expected '0'/'off', '1'/'on' or a "
        "directory path (with a path separator or ~/. prefix)"
    )


def enable_compile_cache(jax=None, default_dir: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at the resolved
    directory. Returns the directory in effect, or None when pinned off
    (or when this jax predates the cache config — the cache is an
    optimization, never a hard dependency)."""
    path = compile_cache_dir(default_dir)
    if path is None:
        return None
    if jax is None:
        import jax
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
    except Exception:  # noqa: BLE001 — cache is an optimization only
        return None
    return path
