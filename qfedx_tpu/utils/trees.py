"""Pytree utilities shared across the framework.

The reference passes parameters around as torch ``state_dict`` objects
(reference src/CFed/Classical_FL.py:64,66-81). Here all parameters are JAX
pytrees, and the federated runtime needs a handful of whole-tree operations:
flattening to a single vector (for ℓ2 clipping / secure-agg masks), global
norms, and elementwise arithmetic.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.flatten_util  # noqa: F401  (registers jax.flatten_util.ravel_pytree)
import jax.numpy as jnp

Pytree = Any


def tree_add(a: Pytree, b: Pytree) -> Pytree:
    return jax.tree.map(jnp.add, a, b)


def tree_sub(a: Pytree, b: Pytree) -> Pytree:
    return jax.tree.map(jnp.subtract, a, b)


def tree_scale(a: Pytree, s) -> Pytree:
    return jax.tree.map(lambda x: x * s, a)


def tree_zeros_like(a: Pytree) -> Pytree:
    return jax.tree.map(jnp.zeros_like, a)


def tree_map_with_path(fn: Callable, tree: Pytree) -> Pytree:
    return jax.tree_util.tree_map_with_path(fn, tree)


def global_norm_sq(tree: Pytree) -> jax.Array:
    """Squared ℓ2 norm across the whole pytree. Use this (not
    ``global_norm(t)**2``) inside differentiated code: sqrt at 0 has an
    infinite gradient, which NaNs e.g. the FedProx term on the first step."""
    return sum(jnp.sum(jnp.square(x)) for x in jax.tree.leaves(tree))


def global_norm(tree: Pytree) -> jax.Array:
    """ℓ2 norm across the whole pytree (DP clipping operates on this,
    per reference ROADMAP.md:50-51: "Clip Δθ to ℓ2 norm C")."""
    return jnp.sqrt(global_norm_sq(tree))


def tree_size(tree: Pytree) -> int:
    """Total number of scalar parameters in the tree (static)."""
    return sum(int(x.size) for x in jax.tree.leaves(tree))


def tree_bytes(tree: Pytree) -> int:
    """Total parameter bytes — the exact per-direction wire volume of a
    replicated-θ federated round, honest to each leaf's ACTUAL dtype (a
    bf16 or int leaf counts its real width, not an assumed 4 bytes)."""
    return sum(int(x.nbytes) for x in jax.tree.leaves(tree))


def ravel(tree: Pytree):
    """Flatten a pytree to a single 1-D vector plus an unravel function."""
    return jax.flatten_util.ravel_pytree(tree)


def tree_random_normal(key: jax.Array, tree: Pytree, dtype=None) -> Pytree:
    """A pytree of iid N(0,1) samples with the same structure/shapes as
    ``tree``. Each leaf gets an independent fold of ``key`` so the result is
    deterministic in tree structure (used for DP noise and secure-agg masks)."""
    leaves, treedef = jax.tree.flatten(tree)
    keys = jax.random.split(key, len(leaves))
    out = [
        jax.random.normal(k, x.shape, dtype or x.dtype)
        for k, x in zip(keys, leaves)
    ]
    return jax.tree.unflatten(treedef, out)
