"""The QFEDX_* boolean pin grammar — ONE parser for every on/off pin.

Every boolean pin (QFEDX_FUSE, QFEDX_TRACE, QFEDX_DONATE, …) accepts
``0``/``off``/``1``/``on`` case-insensitively and rejects anything else
with a loud ValueError — a typo must never silently measure the other
route (the wrong-path-measured error class, ADVICE r04 item 1). This
module exists so the grammar is defined and tested once instead of
hand-rolled per pin (by r09 five copies had grown, and they had already
drifted on case handling).

Import-light on purpose (os only): obs/trace.py calls this per span.
"""

from __future__ import annotations

import os
from typing import Callable


def parse_onoff(value: str) -> bool | None:
    """The grammar core: ``0``/``off`` → False, ``1``/``on`` → True
    (case-insensitive), anything else → None. Extended pins
    (QFEDX_PIPELINE's integer depths, QFEDX_COMPILE_CACHE's directory
    values) parse their bool prefix through THIS so the on/off spelling
    cannot drift per pin, then handle the None themselves."""
    low = value.lower()
    if low in ("0", "off"):
        return False
    if low in ("1", "on"):
        return True
    return None


def bool_pin(name: str, default: bool | Callable[[], bool]) -> bool:
    """Resolve the env pin ``name`` to a bool.

    ``default`` applies when the variable is unset; pass a callable for
    defaults that must stay lazy (e.g. ones that touch
    ``jax.default_backend()`` — the backend is only consulted when the
    pin does not decide).
    """
    env = os.environ.get(name)
    if env is None:
        return default() if callable(default) else default
    val = parse_onoff(env)
    if val is None:
        raise ValueError(f"{name}={env!r}: expected '1'/'on' or '0'/'off'")
    return val


def tpu_backend_default() -> bool:
    """The shared lazy default of the engine-routing pins whose route
    "follows the backend" (QFEDX_FUSE, QFEDX_SCAN_LAYERS): True exactly
    when the default JAX backend is TPU. Lazy on purpose — pass it as
    ``bool_pin``'s default so the backend is only initialized when the
    pin does not decide (probing it eagerly would pin the platform
    before callers could select one; see models/vqc's routing note)."""
    try:
        import jax

        return jax.default_backend() == "tpu"
    except Exception:  # noqa: BLE001 — no backend yet: conservative
        return False


def float_pin(name: str, default: float) -> float:
    """Resolve a float-valued pin (QFEDX_SERVE_DEADLINE_MS /
    QFEDX_SERVE_SLO_MS) with the family's loud grammar: unset → default,
    a parseable number → that value, anything else raises (the
    wrong-path-measured guard — see module docstring)."""
    env = os.environ.get(name)
    if env is None:
        return default
    try:
        return float(env)
    except ValueError:
        raise ValueError(f"{name}={env!r}: expected a number") from None


def int_pin(name: str, default: int) -> int:
    """Resolve a non-negative-integer pin (QFEDX_SERVE_QUEUE) loudly:
    unset → default, digits → that value, anything else raises. Range
    constraints beyond non-negativity belong to the consuming config's
    validation, where the explicit-argument path hits them too."""
    env = os.environ.get(name)
    if env is None:
        return default
    if not env.isdigit():
        raise ValueError(f"{name}={env!r}: expected a non-negative integer")
    return int(env)


def int_list_pin(name: str, default: tuple[int, ...]) -> tuple[int, ...]:
    """Resolve a comma-separated integer-list pin (QFEDX_SERVE_BUCKETS)
    loudly: unset → default, ``"1,8,32"`` → (1, 8, 32), anything else
    (including an empty value) raises."""
    env = os.environ.get(name)
    if env is None:
        return default
    try:
        out = tuple(int(tok) for tok in env.split(",") if tok.strip())
    except ValueError:
        out = ()
    if not out:
        raise ValueError(
            f"{name}={env!r}: expected comma-separated integers, "
            "e.g. '1,8,32'"
        )
    return out


def port_pin(name: str, default: int = 0) -> int:
    """Resolve a TCP-port pin (QFEDX_METRICS_PORT) loudly: unset →
    ``default`` (0 = feature off), ``off``/``0`` → 0, digits in
    [0, 65535] → that port, anything else raises. A port of 0 passed to
    the server binds an ephemeral port (tests); via the PIN, 0 simply
    means "no server" — the default-off invariance the telemetry
    endpoint pins (docs/OBSERVABILITY.md)."""
    env = os.environ.get(name)
    if env is None:
        return default
    if env.lower() == "off":
        return 0
    if not env.isdigit() or int(env) > 65535:
        raise ValueError(
            f"{name}={env!r}: expected 'off' or a port in [0, 65535]"
        )
    return int(env)


def str_pin(name: str, default: str | None = None) -> str | None:
    """The raw string value of pin ``name`` (``default`` when unset).

    The pass-through helper for pins whose grammar lives at the caller
    (QFEDX_FAULTS' JSON/path values, QFEDX_PROFILE / QFEDX_COMPILE_CACHE's
    on/off/path hybrid, the serve route snapshot that records every
    routing pin verbatim). No validation by design — it exists so raw
    ``os.environ`` reads still funnel through ONE module (the QFX002
    lint contract, docs/ANALYSIS.md) and the read site stays greppable."""
    return os.environ.get(name, default)


def choice_pin(
    name: str,
    choices: tuple[str, ...],
    default: str | None | Callable[[], str | None],
) -> str | None:
    """Resolve an enumerated-string pin (QFEDX_GATE_FORM's flip/dot,
    QFEDX_SLAB_LANES' matmul/flip, QFEDX_AGG's aggregator rules) with
    the family's loud grammar: unset or empty → ``default`` (callable
    for lazy backend-dependent defaults, same convention as bool_pin),
    a case-insensitive match of one of ``choices`` → that choice,
    anything else raises — a typo must never silently route the other
    engine (the wrong-path-measured error class, module docstring)."""
    env = os.environ.get(name)
    if not env:  # unset OR empty: the historical "if env:" gate
        return default() if callable(default) else default
    low = env.lower()
    if low not in choices:
        raise ValueError(
            f"{name}={env!r}: expected one of {choices}"
        )
    return low


def set_pin(name: str, value: str) -> None:
    """Write a pin for this process (CLI flag sugar: ``--trace`` sets
    QFEDX_TRACE=1). Writes funnel through here for the same reason
    reads do — one greppable seam instead of scattered ``os.environ``
    mutations (QFX002)."""
    os.environ[name] = value


def clear_pin(name: str) -> None:
    """Unset a pin (no-op when absent) — ``set_pin``'s inverse."""
    os.environ.pop(name, None)


def pin_is_set(name: str) -> bool:
    """Is the pin present in the environment at all? (Distinct from its
    parsed value: callers that only overlay a default must not clobber
    an operator's explicit setting.)"""
    return name in os.environ


def interval_pin(name: str, on_value: float, default: float = 0.0) -> float:
    """Resolve a period-in-seconds pin with the on/off grammar as a
    prefix: unset → ``default`` (0.0 = feature off), ``0``/``off`` → 0.0,
    ``1``/``on`` → ``on_value`` (the feature's default tick), a bare
    number → that period, anything else raises. QFEDX_TUNE (the adaptive
    controller's decision period) speaks this — the same shape
    QFEDX_WATCH established for the watchdog ticker, factored here so a
    third ticker pin cannot drift on spelling (module docstring)."""
    env = os.environ.get(name)
    if env is None:
        return default
    as_bool = parse_onoff(env)
    if as_bool is not None:
        return on_value if as_bool else 0.0
    try:
        period = float(env)
    except ValueError:
        raise ValueError(
            f"{name}={env!r}: expected '0'/'off', '1'/'on' or a period "
            "in seconds"
        ) from None
    if period < 0:
        raise ValueError(f"{name}={env!r}: period must be >= 0")
    return period


def depth_pin(name: str, default: int, on_value: int = 1) -> int:
    """Resolve an integer-depth pin with the on/off grammar as a prefix:
    ``0``/``off`` → 0, ``1``/``on`` → ``on_value``, a bare integer → that
    depth, anything else raises. QFEDX_PIPELINE (trainer loop depth) and
    QFEDX_STREAM (ingest prefetch depth) share this shape — the two
    host-loop depth knobs must not drift on spelling the way the bool
    pins once did (module docstring)."""
    env = os.environ.get(name)
    if env is None:
        return default
    as_bool = parse_onoff(env)
    if as_bool is not None:
        return on_value if as_bool else 0
    if env.isdigit():
        return int(env)
    raise ValueError(
        f"{name}={env!r}: expected '0'/'off', '1'/'on' or an integer depth"
    )
