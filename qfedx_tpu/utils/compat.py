"""JAX version compatibility shims.

The framework targets the current TPU toolchain, where ``jax.shard_map``
is a public top-level API with a ``check_vma`` flag. Older jax releases
(< 0.5) ship the same transform as ``jax.experimental.shard_map.shard_map``
with the flag spelled ``check_rep``. Every shard_map in the codebase goes
through this one wrapper so the whole SPMD layer (fed round, sharded
statevector, sharded VQC) runs on both toolchains — in particular on CPU
test environments pinned to an older jax, where the top-level name simply
not existing used to fail the entire federated test surface at import
time.
"""

from __future__ import annotations

import jax


def _check_kwarg(fn) -> str:
    """Which replication-check kwarg ``fn`` takes: the top-level promotion
    of shard_map and the check_rep → check_vma rename landed in different
    jax releases, so the spelling must be read off the signature, not
    inferred from where the function lives."""
    import inspect

    try:
        params = inspect.signature(fn).parameters
    except (TypeError, ValueError):  # C-accelerated / wrapped: assume new
        return "check_vma"
    return "check_vma" if "check_vma" in params else "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` on new jax, ``jax.experimental.shard_map`` on old.

    Same semantics either way; ``check_vma`` maps onto the old API's
    ``check_rep`` (both gate the replication/varying-manual-axes check).
    """
    if hasattr(jax, "shard_map"):
        sm = jax.shard_map
    else:
        from jax.experimental.shard_map import shard_map as sm
    return sm(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        **{_check_kwarg(sm): check_vma},
    )
