"""Deterministic fault injection — the chaos harness behind QFEDX_FAULTS.

Cross-device federation at QFed scale is DEFINED by partial
participation: clients die mid-round, local updates go non-finite,
registries and filesystems hiccup. The r11 round machinery survives all
of these (fed/round survivor masks + quarantine, data/stream +
run/checkpoint retries) — this module makes those paths TESTABLE by
injecting the failures deterministically at the real seams instead of
hoping production reproduces them.

A ``FaultPlan`` is a seeded list of rules. Every decision is a pure
function of ``(seed, site, round, wave, client/attempt)`` via a
SplitMix64 hash — no RNG state, so a plan fires identically across
reruns, processes and resumes (the same counter-based-determinism
design as ``data.stream.SyntheticRegistry``).

Registered sites (the real seams; each consulted by production code,
except ``distributed.peer`` which is consulted by the multi-process
test harness):

- ``client.compute`` — per-(round, client) casualties, ``kind``:
  ``drop`` (client dies: it joins the round's survivor mask as 0, its
  weighted contribution and secure-agg masks vanish — fed/round),
  ``nan`` / ``inf`` (its local data is poisoned so its Δθ goes
  non-finite and the quarantine path must catch it organically).
- ``client.byzantine`` — per-(round, client) ADVERSARIES (r12): the
  client completes local training, then tampers. ``kind``:
  ``scale:k`` multiplies its Δθ upload by k (the model-poisoning
  amplification attack), ``sign_flip`` negates it (= ``scale:-1`` but
  named for the taxonomy), ``noise`` (or ``noise:σ``, default σ=1)
  replaces it with σ·N(0, I), and ``label_flip`` flips its LABELS
  before training (binary 0/1 registries — y → 1−y) so the attack
  flows through real local gradients, not a synthetic delta. The first
  three reach the round program as a [cohort, 2] (multiplier, σ) input
  (``byzantine_multipliers``/``byzantine_noise`` → fed/round's attack
  variant); ``label_flip`` is applied by the WaveStream to the fetched
  batch (``label_flips``). The DEFENSE is ``FedConfig.aggregator``
  (clip_mean / trimmed_mean / median — docs/ROBUSTNESS.md).
- ``client.slow`` — per-(round, client) STRAGGLERS (r13): ``kind``
  ``slow:s`` (seconds; bare ``slow`` = 1 s) marks a client slow — the
  WaveStream uploader sleeps the wave's max slow-client seconds before
  fetching it, so a slow client holds up exactly its wave. Past the
  consumer's ``wave_deadline_s`` the wave goes late: a casualty under
  ``on_wave_error="drop"``, a buffered stale contribution under
  ``"buffer"`` (QFEDX_STALE, docs/ROBUSTNESS.md).
- ``wave.delay`` — the same straggle injected per (round, wave):
  ``kind`` ``delay:s`` sleeps the whole wave's upload ``s`` seconds.
  The wave-granular dial the straggler bench/chaos tests drive
  (``rate`` draws the per-(round, wave) coin, like the error sites).
- ``registry.fetch`` — transient error raised inside the WaveStream
  uploader's fetch, before the registry is read (data/stream retries).
- ``ingest.h2d`` — same, between host batch and ``device_put``.
- ``checkpoint.write`` — transient error in the async checkpoint
  writer's save attempt (run/checkpoint retries).
- ``distributed.peer`` — a peer process's in-flight client is declared
  dead: the 2-process gloo worker calls ``check("distributed.peer",
  round, wave=peer)`` per peer and folds firing peers into the round's
  survivor mask, so the casualty's ring partner lives on the OTHER
  process (tests/_distributed_worker.py dropout mode).
- ``serve.request`` — per-request corruption at the serving front door
  (r14): ``kind`` ``nan`` (features go non-finite) / ``malformed``
  (wrong feature shape). The micro-batcher mutates request #seq (the
  ``rounds`` coordinate is the request sequence) BEFORE validation, so
  the per-request 4xx rejection is exercised organically and a bad
  request can never poison its co-batched rows (serve/batcher.py).
- ``serve.compute`` — transient device error inside the serving
  engine's dispatch (the round coordinate is the batch sequence);
  retried under the shared seeded-jitter policy (serve/engine.py).

Rule spec (JSON or dict) — ``docs/ROBUSTNESS.md`` is the reference:

    {"seed": 7, "rules": [
      {"site": "client.compute", "kind": "drop", "clients": [3],
       "rounds": [1]},                       # exact casualty
      {"site": "client.compute", "kind": "nan", "rate": 0.05},
      {"site": "registry.fetch", "rate": 1.0, "rounds": [0],
       "times": 1}                           # fails attempt 0 only
    ]}

``rounds`` / ``waves`` restrict where a rule applies (absent = every-
where); ``clients`` lists exact registry ids, ``rate`` draws per-client
(client.compute) or per-(round, wave) (error sites) from the hash;
``times`` bounds how many retry ATTEMPTS an error site fails — the
transient/persistent dial (``times: 1`` + a 2-attempt retry = recovered,
``times`` absent = fails every attempt = persistent).

``QFEDX_FAULTS`` pins a plan process-wide: ``0``/``off`` (default) =
none, a ``{...}`` literal = inline JSON, anything else = path to a JSON
file. Read PER resolve (like QFEDX_TRACE) so tests flip it per run.
With no plan active every hook below is a no-op and the guarded round
program still runs — the faults-off bit-parity lever lives in
fed/round's QFEDX_GUARDS, not here.
"""

from __future__ import annotations

import json
import os
from functools import lru_cache
from pathlib import Path

import numpy as np

from qfedx_tpu.utils import pins

SITES = (
    "client.compute",
    "registry.fetch",
    "ingest.h2d",
    "checkpoint.write",
    "distributed.peer",
    # Appended (not inserted): _site_code indexes this tuple, so the
    # hash coordinates of every pre-r12 site — and therefore every
    # pinned plan draw — must not move.
    "client.byzantine",
    # r13 straggler sites (appended for the same reason).
    "client.slow",
    "wave.delay",
    # r14 serving sites (appended for the same reason).
    "serve.request",
    "serve.compute",
)
CLIENT_KINDS = ("drop", "nan", "inf")
# Byzantine base kinds; scale REQUIRES a parameter ("scale:100"), noise
# takes an optional σ ("noise" = σ 1.0, "noise:5" = σ 5).
BYZANTINE_KINDS = ("scale", "sign_flip", "noise", "label_flip")
# Straggler kinds (r13): slow takes optional seconds ("slow" = 1 s,
# "slow:0.5"); delay REQUIRES them ("delay:0.5").
SLOW_KINDS = ("slow",)
# Serving request corruptions (r14): the batcher MUTATES request #seq
# (nan = non-finite features, malformed = wrong feature shape) so the
# per-request rejection path is exercised through real validation — a
# mutation site like wave.delay, not an error site.
SERVE_REQUEST_KINDS = ("nan", "malformed")
_PER_CLIENT_SITES = ("client.compute", "client.byzantine", "client.slow")
# wave.delay returns a DURATION and serve.request returns a MUTATION
# (instead of raising), so check() rejects both — they are consulted
# through their own accessors, not the error-site path.
_ERROR_SITES = tuple(
    s for s in SITES
    if s not in _PER_CLIENT_SITES and s not in ("wave.delay", "serve.request")
)


def doc_taxonomy() -> dict[str, tuple[str, ...]]:
    """``{site: (kind spellings...)}`` — the canonical taxonomy that
    ``docs/ROBUSTNESS.md``'s fault-site table must mirror row for row
    (``benchmarks/check_faults.py`` enforces both directions). Derived
    from the literal tuples above so a new site or kind cannot ship
    without a documentation row."""
    kinds = {
        "client.compute": CLIENT_KINDS,
        "client.byzantine": ("scale:k", "sign_flip", "noise", "label_flip"),
        "client.slow": ("slow:s",),
        "wave.delay": ("delay:s",),
        "serve.request": SERVE_REQUEST_KINDS,
    }
    return {s: kinds.get(s, ("error",)) for s in SITES}


class FaultInjected(RuntimeError):
    """A planned transient/persistent failure, raised at an error site.

    Typed so retry policies and tests can distinguish injected chaos
    from real failures; carries the site and the (round, wave, attempt)
    coordinate that fired.
    """

    def __init__(self, site: str, round_idx: int, wave: int, attempt: int):
        super().__init__(
            f"injected fault at {site} (round={round_idx}, wave={wave}, "
            f"attempt={attempt})"
        )
        self.site = site
        self.round_idx = round_idx
        self.wave = wave
        self.attempt = attempt


def _splitmix64(x: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):  # mod-2^64 wraparound IS the mixer
        x = (x + np.uint64(0x9E3779B97F4A7C15)).astype(np.uint64)
        x ^= x >> np.uint64(30)
        x *= np.uint64(0xBF58476D1CE4E5B9)
        x ^= x >> np.uint64(27)
        x *= np.uint64(0x94D049BB133111EB)
        x ^= x >> np.uint64(31)
    return x


def _site_code(site: str) -> np.uint64:
    return np.uint64(SITES.index(site) + 1)


def _uniform(seed: int, site: str, round_idx: int, wave, ids) -> np.ndarray:
    """[len(ids)] float64 in [0, 1), pure in every coordinate."""
    ids = np.asarray(ids, dtype=np.uint64)
    x = np.uint64(seed)
    for part in (_site_code(site), np.uint64(round_idx + 1),
                 np.uint64(int(wave) + 1)):
        x = _splitmix64(x ^ part)
    bits = _splitmix64(x ^ ids)
    return (bits >> np.uint64(11)) / float(1 << 53)


class _Rule:
    def __init__(self, spec: dict):
        unknown = set(spec) - {
            "site", "kind", "rate", "clients", "rounds", "waves", "times"
        }
        if unknown:
            raise ValueError(f"unknown fault-rule keys {sorted(unknown)}")
        self.site = spec.get("site")
        if self.site not in SITES:
            raise ValueError(
                f"fault rule site {self.site!r} not in {SITES}"
            )
        self.kind = spec.get("kind", "error")
        self.kind_param: float | None = None
        if self.site == "client.compute":
            if self.kind not in CLIENT_KINDS:
                raise ValueError(
                    f"client.compute kind {self.kind!r} not in {CLIENT_KINDS}"
                )
        elif self.site == "client.byzantine":
            # Parameterized kinds: "scale:100" / "noise:5"; the base
            # name keys the hash so two scale rules at different k
            # still fall independent coins per rule position.
            base, _, param = str(self.kind).partition(":")
            if base not in BYZANTINE_KINDS:
                raise ValueError(
                    f"client.byzantine kind {self.kind!r}: base must be "
                    f"one of {BYZANTINE_KINDS}"
                )
            if param:
                if base not in ("scale", "noise"):
                    raise ValueError(
                        f"kind {base!r} takes no parameter, got "
                        f"{self.kind!r}"
                    )
                self.kind_param = float(param)
            elif base == "scale":
                raise ValueError(
                    "kind 'scale' needs a multiplier, e.g. 'scale:100'"
                )
            elif base == "noise":
                self.kind_param = 1.0
            if base == "scale" and self.kind_param == 0:
                raise ValueError("scale:0 is a drop, not an attack — "
                                 "use client.compute kind='drop'")
            if base == "noise" and not self.kind_param > 0:
                raise ValueError(f"noise sigma must be > 0, got {self.kind!r}")
            self.kind = base
        elif self.site == "client.slow":
            base, _, param = str(self.kind).partition(":")
            if base != "slow":
                raise ValueError(
                    f"client.slow kind {self.kind!r}: expected 'slow' "
                    "or 'slow:seconds' (e.g. 'slow:0.5')"
                )
            self.kind_param = float(param) if param else 1.0
            if not self.kind_param > 0:
                raise ValueError(
                    f"slow seconds must be > 0, got {self.kind!r}"
                )
            self.kind = base
        elif self.site == "wave.delay":
            base, _, param = str(self.kind).partition(":")
            if base != "delay" or not param:
                raise ValueError(
                    f"wave.delay kind {self.kind!r}: needs "
                    "'delay:seconds' (e.g. 'delay:0.5')"
                )
            self.kind_param = float(param)
            if not self.kind_param > 0:
                raise ValueError(
                    f"delay seconds must be > 0, got {self.kind!r}"
                )
            self.kind = base
        elif self.site == "serve.request":
            if self.kind not in SERVE_REQUEST_KINDS:
                raise ValueError(
                    f"serve.request kind {self.kind!r} not in "
                    f"{SERVE_REQUEST_KINDS}"
                )
        elif self.kind != "error":
            raise ValueError(
                f"{self.site} supports only kind='error', got {self.kind!r}"
            )
        self.rate = spec.get("rate")
        self.clients = (
            None if spec.get("clients") is None
            else np.asarray(spec["clients"], dtype=np.int64)
        )
        if self.site == "wave.delay" and self.clients is not None:
            # Accepting-but-ignoring a clients list would be the
            # wrong-thing-measured error class the loud grammar exists
            # to prevent.
            raise ValueError(
                "wave.delay is per-(round, wave): restrict with "
                "'rounds'/'waves'/'rate', not 'clients' — "
                "client-granular straggle is the client.slow site"
            )
        if self.site == "client.slow" and spec.get("waves") is not None:
            # Per-client draws pin wave=0 (a client exists independent
            # of wave layout), so a 'waves' restriction would silently
            # never fire — same accept-but-ignore class as above.
            raise ValueError(
                "client.slow draws per (round, client): restrict with "
                "'rounds'/'clients'/'rate', not 'waves' — "
                "wave-granular straggle is the wave.delay site"
            )
        if (
            self.site in ("client.slow", "wave.delay")
            and spec.get("times") is not None
        ):
            raise ValueError(
                f"{self.site} injects a DURATION, not a retryable "
                "error — 'times' (the retry-attempt bound) does not "
                "apply"
            )
        if self.site == "serve.request":
            # Per-REQUEST mutation: the round coordinate is the request
            # sequence number; clients/waves/times have no meaning and
            # accepting-but-ignoring them would be the silent-no-fire
            # class the loud grammar exists to prevent.
            for bad in ("clients", "waves", "times"):
                if spec.get(bad) is not None:
                    raise ValueError(
                        f"serve.request draws per request sequence: "
                        f"restrict with 'rounds' (= request seqs) or "
                        f"'rate', not {bad!r}"
                    )
        if self.site in _PER_CLIENT_SITES:
            if (self.rate is None) == (self.clients is None):
                raise ValueError(
                    f"{self.site} rule needs exactly one of "
                    "'rate' or 'clients'"
                )
        elif self.rate is None:
            self.rate = 1.0
        if self.rate is not None and not (0.0 <= float(self.rate) <= 1.0):
            raise ValueError(f"rate must be in [0, 1], got {self.rate}")
        self.rounds = (
            None if spec.get("rounds") is None
            else {int(r) for r in spec["rounds"]}
        )
        self.waves = (
            None if spec.get("waves") is None
            else {int(w) for w in spec["waves"]}
        )
        self.times = (
            None if spec.get("times") is None else int(spec["times"])
        )
        if self.times is not None and self.times < 1:
            raise ValueError(f"times must be >= 1, got {self.times}")

    def applies(self, round_idx: int, wave) -> bool:
        if self.rounds is not None and int(round_idx) not in self.rounds:
            return False
        if self.waves is not None and int(wave) not in self.waves:
            return False
        return True


class FaultPlan:
    """A seeded, deterministic fault schedule (module docstring spec)."""

    def __init__(self, seed: int = 0, rules: list[dict] | None = None):
        self.seed = int(seed)
        self.rules = [_Rule(dict(r)) for r in (rules or [])]

    @classmethod
    def from_spec(cls, spec: dict) -> "FaultPlan":
        unknown = set(spec) - {"seed", "rules"}
        if unknown:
            raise ValueError(f"unknown fault-plan keys {sorted(unknown)}")
        return cls(seed=spec.get("seed", 0), rules=spec.get("rules"))

    @classmethod
    def from_json(cls, text_or_path: str | os.PathLike) -> "FaultPlan":
        text = str(text_or_path)
        if not text.lstrip().startswith("{"):
            text = Path(text).read_text()
        return cls.from_spec(json.loads(text))

    # -- per-client sites (client.compute / client.byzantine) ----------------

    def _rule_hits(self, site: str, kinds: tuple, kind: str,
                   round_idx: int, ids):
        """Yield ``(rule, hit_mask)`` per matching rule — the ONE
        definition of the per-client draw (parameterized byzantine
        kinds need the rule; plain sites OR the masks)."""
        ids = np.asarray(ids, dtype=np.int64)
        for idx, rule in enumerate(self.rules):
            if rule.site != site or rule.kind != kind:
                continue
            if not rule.applies(round_idx, 0):
                continue
            if rule.clients is not None:
                hit = np.isin(ids, rule.clients)
            else:
                # Hash salted by the RULE's position (like ``check``)
                # AND the kind index, so a drop rule and a nan rule at
                # the same rate — or two overlapping drop rules — fall
                # independent coin flips per client.
                u = _uniform(
                    self.seed + kinds.index(kind) + 7919 * (idx + 1),
                    site, round_idx, 0, ids,
                )
                hit = u < float(rule.rate)
            yield rule, hit

    def _site_hits(
        self, site: str, kinds: tuple, kind: str, round_idx: int, ids
    ) -> np.ndarray:
        hit = np.zeros(len(np.asarray(ids)), dtype=bool)
        for _rule, h in self._rule_hits(site, kinds, kind, round_idx, ids):
            hit |= h
        return hit

    def _client_hits(self, kind: str, round_idx: int, ids) -> np.ndarray:
        return self._site_hits(
            "client.compute", CLIENT_KINDS, kind, round_idx, ids
        )

    def _byz_hits(self, kind: str, round_idx: int, ids) -> np.ndarray:
        return self._site_hits(
            "client.byzantine", BYZANTINE_KINDS, kind, round_idx, ids
        )

    def survivors(self, round_idx: int, cohort_ids) -> np.ndarray:
        """[len(cohort_ids)] float32 0/1: 0 = this client DROPS this
        round (dies mid-round; fed/round zeroes its contribution and its
        secure-agg masks never reach the aggregate)."""
        return (~self._client_hits("drop", round_idx, cohort_ids)).astype(
            np.float32
        )

    def poison(self, round_idx: int, cohort_ids) -> np.ndarray:
        """[len(cohort_ids)] float32 multiplier injecting non-finite
        client data: 1 = clean, nan/inf where a ``nan``/``inf`` rule
        fires — multiplied into the client's features so its local
        update goes non-finite and the quarantine must catch it."""
        out = np.ones(len(np.asarray(cohort_ids)), dtype=np.float32)
        out[self._client_hits("nan", round_idx, cohort_ids)] = np.nan
        out[self._client_hits("inf", round_idx, cohort_ids)] = np.inf
        return out

    def casualty_counts(self, round_idx: int, cohort_ids) -> dict:
        """{"drop": n, "nan": n, "inf": n} — the EXACT per-round casualty
        ledger the chaos tests reconcile against metrics.jsonl."""
        return {
            k: int(self._client_hits(k, round_idx, cohort_ids).sum())
            for k in CLIENT_KINDS
        }

    # -- client.byzantine adversaries (r12) ----------------------------------

    def _byz_rule_hits(self, kind: str, round_idx: int, ids):
        """``(rule, hit_mask)`` per matching byzantine rule —
        parameterized kinds (scale:k, noise:σ) need the RULE, not just
        the union; the draw itself is ``_rule_hits``, the one shared
        definition."""
        return self._rule_hits(
            "client.byzantine", BYZANTINE_KINDS, kind, round_idx, ids
        )

    def byzantine_multipliers(self, round_idx: int, cohort_ids) -> np.ndarray:
        """[len(cohort_ids)] float32 per-client Δθ multiplier: 1 =
        honest, k where a ``scale:k`` rule fires, negated where
        ``sign_flip`` fires (overlapping rules compose by product —
        a scaled sign-flipper uploads −k·Δθ)."""
        out = np.ones(len(np.asarray(cohort_ids)), dtype=np.float32)
        for rule, hit in self._byz_rule_hits("scale", round_idx, cohort_ids):
            out[hit] *= np.float32(rule.kind_param)
        for _rule, hit in self._byz_rule_hits(
            "sign_flip", round_idx, cohort_ids
        ):
            out[hit] *= np.float32(-1.0)
        return out

    def byzantine_noise(self, round_idx: int, cohort_ids) -> np.ndarray:
        """[len(cohort_ids)] float32 noise σ: 0 = honest; where a
        ``noise``/``noise:σ`` rule fires the client's upload is replaced
        by σ·N(0, I) (largest σ wins when rules overlap)."""
        out = np.zeros(len(np.asarray(cohort_ids)), dtype=np.float32)
        for rule, hit in self._byz_rule_hits("noise", round_idx, cohort_ids):
            out[hit] = np.maximum(out[hit], np.float32(rule.kind_param))
        return out

    def label_flips(self, round_idx: int, cohort_ids) -> np.ndarray:
        """[len(cohort_ids)] bool: clients whose LABELS flip before
        local training (data-level attack — flows through real
        gradients; binary-label registries, y → 1−y in data/stream)."""
        return self._byz_hits("label_flip", round_idx, cohort_ids)

    def byzantine_counts(self, round_idx: int, cohort_ids) -> dict:
        """{kind: n} per byzantine base kind — the exact per-round
        adversary ledger (the chaos tests reconcile ``clipped_clients``
        in metrics.jsonl against the update-level entries)."""
        return {
            k: int(self._byz_hits(k, round_idx, cohort_ids).sum())
            for k in BYZANTINE_KINDS
        }

    def byzantine_attack(self, round_idx: int, cohort_ids):
        """The round program's attack input: [cohort, 2] float32 of
        (multiplier, noise σ) — or None when every client is honest
        this round (the fast path: no attack program variant traces)."""
        mult = self.byzantine_multipliers(round_idx, cohort_ids)
        sigma = self.byzantine_noise(round_idx, cohort_ids)
        if np.all(mult == 1.0) and np.all(sigma == 0.0):
            return None
        return np.stack([mult, sigma], axis=1).astype(np.float32)

    # -- straggler sites (client.slow / wave.delay, r13) ---------------------

    def slow_seconds(self, round_idx: int, cohort_ids) -> np.ndarray:
        """[len(cohort_ids)] float32 seconds: 0 = prompt client; where a
        ``slow``/``slow:s`` rule fires, the client is a STRAGGLER — the
        WaveStream delays its wave by the wave's max slow seconds
        (largest s wins when rules overlap)."""
        out = np.zeros(len(np.asarray(cohort_ids)), dtype=np.float32)
        for rule, hit in self._rule_hits(
            "client.slow", SLOW_KINDS, "slow", round_idx, cohort_ids
        ):
            out[hit] = np.maximum(out[hit], np.float32(rule.kind_param))
        return out

    def wave_delay_s(self, round_idx: int, wave: int) -> float:
        """Injected upload delay (seconds) for one (round, wave) from
        ``wave.delay`` rules — per-coordinate coin like ``check``'s,
        salted per rule position; largest firing delay wins."""
        delay = 0.0
        for idx, rule in enumerate(self.rules):
            if rule.site != "wave.delay" or not rule.applies(
                round_idx, wave
            ):
                continue
            u = _uniform(
                self.seed + 7919 * (idx + 1), "wave.delay", round_idx,
                wave, [0],
            )[0]
            if u < float(rule.rate):
                delay = max(delay, float(rule.kind_param))
        return delay

    def wave_delays(
        self, round_idx: int, cohort_ids, wave_size: int
    ) -> np.ndarray:
        """[num_waves] float32 seconds of injected straggle per wave:
        the max of the wave's ``wave.delay`` draw and its slowest
        ``client.slow`` member — the ONE number the WaveStream sleeps
        before fetching each wave, and the oracle the straggler chaos
        tests reconcile late-wave counts against."""
        ids = np.asarray(cohort_ids)
        wave_size = int(wave_size)
        num_waves = len(ids) // wave_size
        slow = self.slow_seconds(round_idx, ids)
        out = np.zeros(num_waves, dtype=np.float32)
        for w in range(num_waves):
            blk = slow[w * wave_size:(w + 1) * wave_size]
            out[w] = max(
                float(blk.max()) if len(blk) else 0.0,
                self.wave_delay_s(round_idx, w),
            )
        return out

    # -- serving sites (r14) -------------------------------------------------

    def request_mutation(self, seq: int) -> str | None:
        """Mutation kind for serving request #``seq`` at the
        ``serve.request`` site — ``"nan"`` / ``"malformed"`` / None.
        The batcher applies the mutation BEFORE validation, so the
        per-request rejection (the 4xx path) is exercised through the
        same code real bad traffic hits. Per-coordinate coin like
        ``wave_delay_s``'s, salted per rule position; the first firing
        rule wins (rule order is the plan author's precedence)."""
        for idx, rule in enumerate(self.rules):
            if rule.site != "serve.request" or not rule.applies(seq, 0):
                continue
            u = _uniform(
                self.seed + 7919 * (idx + 1), "serve.request", seq, 0, [0]
            )[0]
            if u < float(rule.rate):
                from qfedx_tpu import obs

                obs.counter("faults.injected.serve.request")
                return rule.kind
        return None

    # -- error sites ---------------------------------------------------------

    def check(
        self, site: str, round_idx: int, wave: int = 0, attempt: int = 0
    ) -> None:
        """Raise ``FaultInjected`` if a rule fires at this coordinate.

        Production seams call this with their retry ATTEMPT index: a
        rule with ``times: t`` fails attempts 0..t-1 and then lets the
        operation through — the transient-failure shape retries must
        recover from. No matching rule (or attempt ≥ times) = no-op.
        """
        if site not in _ERROR_SITES:
            raise ValueError(f"unknown error site {site!r}")
        for idx, rule in enumerate(self.rules):
            if rule.site != site or not rule.applies(round_idx, wave):
                continue
            if rule.times is not None and attempt >= rule.times:
                continue
            # Salt the hash with the RULE's position so two rate rules
            # on the same site fall independent coins (the same
            # independence _client_hits keys by kind).
            u = _uniform(
                self.seed + 7919 * (idx + 1), site, round_idx, wave, [0]
            )[0]
            if u < float(rule.rate):
                from qfedx_tpu import obs

                obs.counter(f"faults.injected.{site}")
                raise FaultInjected(site, round_idx, wave, attempt)


@lru_cache(maxsize=8)
def _inline_plan(value: str) -> FaultPlan:
    return FaultPlan.from_json(value)


def active_plan() -> FaultPlan | None:
    """The process-wide plan pinned by ``QFEDX_FAULTS`` (module
    docstring grammar), or None. Read per call, like QFEDX_TRACE.
    Inline ``{...}`` values are cached by their literal text; a FILE
    path is re-read on every resolve — an operator editing the plan
    behind an unchanged path must not be served a stale parse (the
    per-call contract), and the files are tiny."""
    value = pins.str_pin("QFEDX_FAULTS", "")
    if value.lower() in ("", "0", "off"):
        return None
    if value.lstrip().startswith("{"):
        return _inline_plan(value)
    return FaultPlan.from_json(value)


def resolve_plan(fault_plan: FaultPlan | None = None) -> FaultPlan | None:
    """An explicit plan argument wins; otherwise the QFEDX_FAULTS pin."""
    return fault_plan if fault_plan is not None else active_plan()
