"""Serving: persistent compiled forward + request micro-batching.

The inference half of the north star (r14; docs/SERVING.md):

- ``serve.forward.persistent_forward`` — the process-wide compiled-
  forward cache shared by evaluation and serving.
- ``serve.engine.ServeEngine`` — bucketed, warmed, retried dispatch of
  the production engine route from a restored checkpoint.
- ``serve.batcher.MicroBatcher`` — latency-budgeted batching, bounded-
  queue shedding, graceful drain.

CLI: ``python -m qfedx_tpu serve --run-dir runs/<name>``.
"""

from qfedx_tpu.serve.batcher import (
    Future,
    MicroBatcher,
    Overloaded,
    RequestError,
    ShuttingDown,
)
from qfedx_tpu.serve.engine import (
    ServeConfig,
    ServeEngine,
    engine_from_run_dir,
    feature_shape_for,
    infer_num_classes,
)
from qfedx_tpu.serve.forward import persistent_forward

__all__ = [
    "Future",
    "MicroBatcher",
    "Overloaded",
    "RequestError",
    "ServeConfig",
    "ServeEngine",
    "ShuttingDown",
    "engine_from_run_dir",
    "feature_shape_for",
    "infer_num_classes",
    "persistent_forward",
]
