"""The persistent-forward cache: ONE jitted forward per model per route.

Before r14 every inference call-site built its own ``@jax.jit`` wrapper
around ``model.apply`` — the trainer alone builds two evaluators per run
(capped + full, run/trainer.py), a sweep builds two per cell, and each
wrapper carries its own empty executable cache. The serving engine makes
per-callsite wrappers untenable: a request must never pay a compile, so
the warmed executables have to be THE executables every other caller
hits (docs/PERF.md §15d records the honest boundary of the wall-clock
claim — jax's internal caches already dedup same-callable re-jits; what
this cache guarantees is artifact identity and route correctness).

``persistent_forward(fwd)`` returns a process-wide shared ``jax.jit``
wrapper for ``fwd``, keyed on:

- the ``fwd`` callable itself — the per-route wrappers are ANCHORED on
  the function object (a cache dict in its ``__dict__``), so their
  lifetime is exactly the model's: drop the model and the closure, the
  wrapper cycle is garbage-collected, and the compiled executables are
  freed. No global registry that could pin a sweep's dead models (a
  global WeakKeyDictionary cannot work here: its values would hold the
  key alive through ``jax.jit``'s own reference and nothing would ever
  evict);
- the engine-routing pins (QFEDX_DTYPE / QFEDX_FUSE / QFEDX_SCAN_LAYERS
  / QFEDX_BATCHED / QFEDX_GATE_FORM / QFEDX_SLAB_LANES /
  QFEDX_FOLD_CLIENTS), resolved
  PER CALL: the pins are read at trace time, so one jit wrapper used
  across a pin flip would cache the flipped route's executable under
  the old identity (the bench's with_env A/B levers flip pins around
  fixed models and long-lived evaluators — a shape-keyed jit cache
  would silently hand them the stale program, the wrong-path-measured
  error class of ADVICE r04). The returned facade dispatches each call
  to the current route's wrapper.

jax.jit itself caches one executable per input shape/dtype under the
wrapper, which is exactly the serving contract: warmup compiles every
bucket shape once, and every later call — from the batcher, from an
evaluator, from bench — is a cache hit on the same executable.
"""

from __future__ import annotations

import threading
from typing import Callable

import jax

from qfedx_tpu.utils import pins

# Pins consulted while TRACING an engine program (build-time routing).
# Per-call pins (QFEDX_TRACE, QFEDX_FAULTS) do not shape the program and
# deliberately do not key the cache.
_ROUTING_PINS = (
    "QFEDX_DTYPE",
    "QFEDX_FUSE",
    "QFEDX_SCAN_LAYERS",
    "QFEDX_PALLAS",
    "QFEDX_BATCHED",
    "QFEDX_GATE_FORM",
    "QFEDX_SLAB_LANES",
    "QFEDX_FOLD_CLIENTS",
)

# Attribute on the forward callable holding its {routing_key: wrapper}
# dict. Anchoring on the callable (instead of a module-global map) makes
# wrapper lifetime follow model lifetime (module docstring).
_ATTR = "_qfedx_persistent_forward"
_LOCK = threading.Lock()


def _routing_key() -> tuple:
    return tuple(pins.str_pin(p, "") for p in _ROUTING_PINS)


def persistent_forward(fwd: Callable) -> Callable:
    """THE shared forward for ``fwd``: one facade per callable, which
    resolves the routing key PER CALL and dispatches to the per-route
    ``jax.jit`` wrapper. Per-call resolution matters: an evaluator
    binds its forward once at build time and may be called inside a
    with_env pin window later — a wrapper frozen to its build-time
    route would then cache the flipped route's executable under the
    old key and serve it to post-restore callers. The six env reads
    cost ~µs per call, the same order as the obs span guard (PERF §13).

    Falls back to a fresh ``jax.jit`` for callables without a writable
    ``__dict__`` (exotic callables — the cache is an optimization,
    never a requirement)."""
    with _LOCK:
        shared = getattr(fwd, _ATTR, None)
        if shared is not None:
            return shared
        routes: dict = {}

        def shared(*args, **kwargs):
            key = _routing_key()
            with _LOCK:
                wrapper = routes.get(key)
                if wrapper is None:
                    wrapper = routes[key] = jax.jit(fwd)
            return wrapper(*args, **kwargs)

        shared._routes = routes
        try:
            setattr(fwd, _ATTR, shared)
        except (AttributeError, TypeError):
            return jax.jit(fwd)
        return shared


def cached_routes(fwd: Callable) -> int:
    """Routes compiled for ``fwd``'s shared forward — tests only."""
    shared = getattr(fwd, _ATTR, None)
    return len(shared._routes) if shared is not None else 0
