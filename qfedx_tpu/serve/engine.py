"""The serving engine: persistent compiled forward over bucketed batches.

The inference half of the north star ("serves heavy traffic from
millions of users", ROADMAP.md). The training loop got pipelined (r09),
hierarchical (r10) and fault-tolerant (r11–r13); this is the first
component that ANSWERS with the trained model. Design constraints, in
order:

1. **No request ever pays a compile.** Batch shapes are restricted to a
   small ordered set of BUCKETS; ``warmup()`` traces and compiles every
   bucket through the persistent-forward cache (``serve/forward.py`` —
   the same wrapper evaluation uses, so a process that trained/evaluated
   already owns some of the executables) before the first request is
   accepted, and the serving loop then only ever replays warm
   executables. ``tests/test_serve.py`` pins zero compile events inside
   the loop via the obs compile-attribution listener.
2. **Padding must be invisible.** A batch of m requests padded to bucket
   b runs m real rows + (b−m) zero rows; every per-sample engine route
   is row-independent, so the real rows' logits are BIT-IDENTICAL to the
   unpadded forward (pinned f32 + bf16), and padded rows are sliced off
   BEFORE any softmax/readout post-processing — a pad row can never leak
   into a response.
3. **Transient device errors retry, poisoned batches don't ship.** The
   compute dispatch runs under the shared seeded-jitter retry policy
   (``utils/retry``), with the ``serve.compute`` fault site
   (``utils/faults``, QFEDX_FAULTS) injected inside the attempt so the
   recovery path is deterministically testable. Malformed/non-finite
   REQUESTS are the batcher's problem (``serve.request`` site): they are
   rejected per-request before a batch is formed.

Spans: ``serve.warmup`` (per-bucket compile), ``serve.pad`` (bucket
selection + zero-fill), ``serve.compute`` (dispatch), ``serve.fetch``
(the one blocking device→host read). docs/SERVING.md is the operator
guide; docs/OBSERVABILITY.md has the pin table rows.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Any

import numpy as np

from qfedx_tpu import obs
from qfedx_tpu.serve.forward import _ROUTING_PINS, persistent_forward
from qfedx_tpu.utils import faults, pins
from qfedx_tpu.utils.retry import retry_with_deadline


@dataclass(frozen=True)
class ServeConfig:
    """Serving knobs. ``resolve()`` fills unset fields from the
    QFEDX_SERVE_* pins so CLI flags > pins > defaults, the same
    precedence as pipeline_depth (run/config.py)."""

    # Ascending batch shapes compiled at warmup; a request batch pads up
    # to the smallest bucket that fits. Few buckets = few executables =
    # cheap warmup; the largest bucket is the dispatch batch cap.
    buckets: tuple[int, ...] = (1, 8, 32)
    # Latency budget of the micro-batcher: a queued request waits at
    # most this long for its bucket to fill before being dispatched
    # anyway (the deadline flush).
    deadline_ms: float = 5.0
    # Bounded admission queue: submissions past this depth are SHED
    # (Overloaded) instead of growing an unbounded latency tail.
    max_queue: int = 256
    # Stated SLO for bench/ops rows (docs/SERVING.md): throughput_at_slo
    # is the highest offered load whose p95 latency stays under this.
    slo_ms: float = 50.0

    def __post_init__(self):
        if not self.buckets:
            raise ValueError("buckets must be non-empty")
        if any(b < 1 for b in self.buckets):
            raise ValueError(f"bucket sizes must be >= 1, got {self.buckets}")
        if tuple(sorted(set(self.buckets))) != tuple(self.buckets):
            raise ValueError(
                f"buckets must be strictly ascending, got {self.buckets}"
            )
        if not self.deadline_ms > 0:
            raise ValueError(f"deadline_ms={self.deadline_ms} must be > 0")
        if self.max_queue < 1:
            raise ValueError(f"max_queue={self.max_queue} must be >= 1")
        if not self.slo_ms > 0:
            raise ValueError(f"slo_ms={self.slo_ms} must be > 0")

    @classmethod
    def resolve(
        cls,
        buckets: tuple[int, ...] | None = None,
        deadline_ms: float | None = None,
        max_queue: int | None = None,
        slo_ms: float | None = None,
    ) -> "ServeConfig":
        return cls(
            buckets=(
                tuple(buckets) if buckets is not None
                else pins.int_list_pin("QFEDX_SERVE_BUCKETS", cls.buckets)
            ),
            deadline_ms=(
                deadline_ms if deadline_ms is not None
                else pins.float_pin("QFEDX_SERVE_DEADLINE_MS", cls.deadline_ms)
            ),
            max_queue=(
                max_queue if max_queue is not None
                else pins.int_pin("QFEDX_SERVE_QUEUE", cls.max_queue)
            ),
            slo_ms=(
                slo_ms if slo_ms is not None
                else pins.float_pin("QFEDX_SERVE_SLO_MS", cls.slo_ms)
            ),
        )


class ServeEngine:
    """Persistent compiled forward + bucketed padding + retried dispatch.

    ``model``: a host-callable ``models.api.Model`` (sv-sharded models
    need a mesh-wrapped apply and are rejected — serving them is a
    front-end away once ``host_apply`` is passed as the forward).
    ``params``: the restored parameter pytree (``engine_from_run_dir``).
    ``feature_shape``: per-request feature shape, e.g. ``(n_qubits,)``
    for angle-encoded VQCs, ``(28, 28)`` for image models.
    """

    def __init__(
        self,
        model,
        params,
        feature_shape: tuple[int, ...],
        config: ServeConfig | None = None,
        apply_fn=None,
    ):
        if apply_fn is None and getattr(model, "sv_size", 1) > 1:
            raise ValueError(
                f"model {model.name} is sv-sharded; its bare apply has "
                "collectives that cannot run outside a shard_map — pass "
                "apply_fn=host_apply(model, mesh)"
            )
        self.model = model
        self.params = params
        self.feature_shape = tuple(int(s) for s in feature_shape)
        self.config = config or ServeConfig.resolve()
        # THE shared wrapper (serve/forward.py): evaluation and serving
        # hit one executable cache per (model, route).
        self._fwd = persistent_forward(
            apply_fn if apply_fn is not None else model.apply
        )
        self._warm = False
        # The adaptive controller seam (tune/controller.py): attached by
        # warmup() iff QFEDX_TUNE is on, consulted by the batcher per
        # flush. None (the default) = the batcher reads this engine's
        # static config exactly as in r20.
        self.tuner = None

    # -- buckets -------------------------------------------------------------

    @property
    def max_bucket(self) -> int:
        return self.config.buckets[-1]

    def bucket_for(self, m: int) -> int:
        """Smallest compiled bucket that fits ``m`` rows."""
        for b in self.config.buckets:
            if m <= b:
                return b
        raise ValueError(
            f"batch of {m} exceeds the largest bucket "
            f"{self.max_bucket}; the batcher must split it"
        )

    # -- warmup --------------------------------------------------------------

    def warmup(self) -> dict[str, Any]:
        """Compile every bucket shape ahead of traffic (through the
        QFEDX_COMPILE_CACHE path when the CLI enabled it — a restarted
        server re-warms from the persistent cache instead of re-tracing
        XLA). Returns per-bucket wall + attributed compile seconds.

        Also the serving stack's telemetry hook: brings up the live
        /metrics + /healthz endpoint when QFEDX_METRICS_PORT is set
        (obs/server.py; default off — no thread, no behavior change),
        so even a batcher-less embedder gets a scrape surface the
        moment the engine warms."""
        from qfedx_tpu.obs import flight, watch
        from qfedx_tpu.obs import server as obs_server

        obs_server.maybe_start()
        # r20 detection: watchdog ticker + flight lifecycle edge at the
        # same startup seam as the live endpoint (both default off).
        watch.maybe_start()
        flight.record(
            "lifecycle", "engine.warmup", buckets=str(self.config.buckets)
        )
        # r21 adaptation: the tune controller attaches HERE because the
        # bucket set it may pick from is exactly the set this warmup is
        # about to compile — attach-after-warm could race a first flush
        # against an uncompiled shape. Default off: maybe_controller
        # returns None and nothing below changes.
        from qfedx_tpu import tune

        if self.tuner is None:
            self.tuner = tune.maybe_controller(self)
        if self.tuner is not None:
            self.tuner.maybe_start()
        per_bucket = {}
        for b in self.config.buckets:
            x = np.zeros((b,) + self.feature_shape, dtype=np.float32)
            with obs.span("serve.warmup", bucket=b) as sp:
                t0 = time.perf_counter()
                out = np.asarray(self._fwd(self.params, x))
                wall = time.perf_counter() - t0
            if not np.all(np.isfinite(out)):
                raise RuntimeError(
                    f"warmup forward at bucket {b} produced non-finite "
                    "logits — refusing to serve a broken checkpoint"
                )
            per_bucket[b] = {
                "wall_s": round(wall, 4),
                "compile_s": round(getattr(sp, "compile_s", 0.0), 4),
            }
        self._warm = True
        obs.counter("serve.warmup_buckets", len(per_bucket))
        # The engine-routing pins (serve/forward.py) of the programs
        # just compiled: ``route`` is the raw env snapshot (exact repro
        # of this process's routing key), ``route_resolved`` the
        # backend-defaulted answers for the fuse/scan/pallas chain
        # (each conjoined with the one below it — pallas_body
        # .resolved_route) — on a default deploy every raw pin is ""
        # and only the resolved values say whether the bucket floor is
        # the kernel, the r17 scan, or the per-layer program. Width/
        # depth gates (fuse.scan_active, route_ok) live below the
        # engine — models are opaque callables here.
        from qfedx_tpu.ops import pallas_body
        from qfedx_tpu.ops.cpx import state_dtype

        return {
            "buckets": per_bucket,
            "num_classes": int(out.shape[-1]),
            "route": {p: pins.str_pin(p, "") for p in _ROUTING_PINS},
            "route_resolved": {
                "dtype": np.dtype(state_dtype()).name,
                **pallas_body.resolved_route(),
            },
        }

    # -- inference -----------------------------------------------------------

    def infer(self, x: np.ndarray, seq: int = 0) -> np.ndarray:
        """Logits for ``x`` [m, *feature_shape], m ≤ max bucket.

        Pads up to the bucket, dispatches the warm executable (retrying
        transient errors — the ``serve.compute`` fault site fires inside
        the attempt), fetches ONCE, and slices the pad rows off before
        returning — they never reach readout post-processing.
        """
        x = np.asarray(x, dtype=np.float32)
        m = x.shape[0]
        bucket = self.bucket_for(m)
        with obs.span("serve.pad", batch=m, bucket=bucket):
            if m < bucket:
                xb = np.zeros((bucket,) + x.shape[1:], dtype=x.dtype)
                xb[:m] = x
            else:
                xb = x

        def attempt(k: int):
            if k > 0:
                obs.counter("serve.compute_retries")
            plan = faults.active_plan()
            if plan is not None:
                plan.check("serve.compute", seq, attempt=k)
            out = self._fwd(self.params, xb)
            # The fetch lives INSIDE the retried attempt: under async
            # dispatch a device execution error only surfaces at the
            # blocking device→host read, and a transient one must be
            # retryable — retrying just the (non-blocking) dispatch
            # would retry nothing real. serve.fetch nests under
            # serve.compute in the trace.
            with obs.span("serve.fetch", batch=m):
                return np.asarray(out)

        with obs.span("serve.compute", batch=m, bucket=bucket, seq=seq):
            logits = retry_with_deadline(
                attempt,
                attempts=3,
                base_delay_s=0.002,
                max_delay_s=0.05,
                deadline_s=5.0,
                describe=f"serve compute (batch {seq})",
                jitter_site=f"serve/{seq}",
            )
        obs.counter("serve.batches")
        obs.counter("serve.requests_served", m)
        return logits[:m]

    def postprocess(self, logits: np.ndarray) -> dict[str, np.ndarray]:
        """Softmax probabilities + predicted class for REAL rows only —
        callers pass the already-sliced logits, so a pad row can never
        enter the normalization."""
        z = logits - logits.max(axis=-1, keepdims=True)
        ez = np.exp(z)
        probs = ez / ez.sum(axis=-1, keepdims=True)
        return {"probs": probs, "pred": logits.argmax(axis=-1)}


# -- checkpoint restore ------------------------------------------------------


def infer_num_classes(cfg) -> int:
    """num_classes implied by an ExperimentConfig without touching data:
    an explicit class subset wins, else the dataset's full class count."""
    from qfedx_tpu.data.datasets import SPECS

    if cfg.data.classes is not None:
        return len(cfg.data.classes)
    return SPECS[cfg.data.dataset].num_classes


def feature_shape_for(cfg) -> tuple[int, ...]:
    """Per-request feature shape implied by an ExperimentConfig —
    mirrors build_data's shaping (run/config.py)."""
    from qfedx_tpu.data.datasets import SPECS

    m = cfg.model
    if m.model == "cnn":
        spec = SPECS[cfg.data.dataset]
        if spec.channels == 1:
            return (spec.height, spec.width)
        return (spec.height, spec.width, spec.channels)
    if m.model == "vqc" and m.encoding == "amplitude":
        return (1 << m.n_qubits,)
    return (m.n_qubits,)


def engine_from_run_dir(
    run_dir: str | os.PathLike,
    round_idx: int | None = None,
    config: ServeConfig | None = None,
) -> tuple[ServeEngine, dict[str, Any]]:
    """Restore a trained run into a ServeEngine.

    Rebuilds the model from the run dir's ``config.json`` (the
    reproducibility contract of run/metrics.ExperimentRun) and loads
    ``round_idx`` (or the newest last-good checkpoint — r13 integrity
    fallback applies) via the ``Model`` contract. Returns the engine and
    an info dict (restored round, model/run metadata).
    """
    import jax

    from qfedx_tpu.run.checkpoint import Checkpointer
    from qfedx_tpu.run.config import build_model, experiment_config_from_dict

    run_dir = Path(run_dir)
    cfg_path = run_dir / "config.json"
    if not cfg_path.exists():
        raise FileNotFoundError(
            f"{cfg_path} not found — serve needs a tracked run directory "
            "(one written by ExperimentRun / `qfedx_tpu train`)"
        )
    exp = experiment_config_from_dict(json.loads(cfg_path.read_text()))
    num_classes = infer_num_classes(exp)
    model = build_model(exp, num_classes)
    if model.sv_size > 1:
        raise NotImplementedError(
            "serving sv-sharded models needs a mesh-wrapped forward; "
            "restore on a pod and pass apply_fn=host_apply(model, mesh)"
        )
    template = model.init(jax.random.PRNGKey(exp.seed))
    ckpt = Checkpointer(run_dir / "checkpoints", every=1)
    if round_idx is not None:
        params = ckpt.restore(round_idx, template)
        restored = round_idx
    else:
        got = ckpt.restore_latest(template)
        if got is None:
            raise FileNotFoundError(
                f"no checkpoints under {run_dir / 'checkpoints'} — train "
                "with --checkpoint-every, or pass --round to pick one"
            )
        params, restored = got
    engine = ServeEngine(
        model, params, feature_shape_for(exp), config=config
    )
    info = {
        "round": restored,
        "model": model.name,
        "num_classes": num_classes,
        "run_dir": str(run_dir),
    }
    return engine, info
