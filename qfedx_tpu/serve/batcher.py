"""The request micro-batcher: latency-budgeted batching + load shedding.

Requests arrive one at a time; the device wants bucket-shaped batches
(``serve/engine.py``). The batcher is the host-side loop between them,
with the same discipline the r09–r13 trainer loop earned the hard way:

- **Two flush triggers.** A batch dispatches when the queue can fill the
  LARGEST bucket (bucket-full flush — never leave a full batch waiting)
  or when the OLDEST queued request has waited ``deadline_ms`` (deadline
  flush — the latency budget is per-request, so the clock starts at
  submit, not at batch formation). Bucket-full wins when both hold;
  tests pin the ordering.
- **Bounded admission.** Past ``max_queue`` pending requests, ``submit``
  raises ``Overloaded`` immediately (shed, counted) instead of growing
  an unbounded tail — the same backpressure-over-buffering call as the
  checkpoint writer's bounded queue (r09). Under overload the p95 of
  ADMITTED requests stays near the SLO; the excess is refused loudly.
- **Per-request rejection, never a poisoned batch.** A malformed or
  non-finite request fails ITS OWN submit with ``RequestError`` (the
  4xx) before a batch is formed — one bad request cannot corrupt the
  co-batched rows (the serving sibling of the r11 non-finite
  quarantine). The ``serve.request`` fault site (utils/faults) mutates
  incoming requests deterministically so this path is chaos-testable.
- **Graceful drain.** ``close(drain=True)`` — and the CLI's SIGTERM
  translation — stops admission, then flushes every queued request
  before returning (the r13 trainer's drain discipline): an in-flight
  request is ANSWERED, not dropped. ``close(drain=False)`` fails the
  pending futures with ``ShuttingDown``.

Spans: ``serve.queue`` times the dispatcher's wait-for-trigger phase;
pad/compute/fetch happen inside ``engine.infer`` — all of them carry
the REQUEST ids they served (``reqs`` meta via ``obs.trace_context``,
r15), so a request's latency decomposes across queue/pad/compute/fetch
in trace.json instead of only batch-aggregated. The
``serve.queue_depth`` gauge samples pending depth at every admission;
the ``serve.latency_ms`` bounded histogram records every answered
request's submit→answer latency (the /metrics quantile source —
obs/histo.py). ``start()`` brings up the live /metrics + /healthz
endpoint when ``QFEDX_METRICS_PORT`` is set (obs/server.py) and
registers this batcher's ledger as the ``serve`` health source;
``close()`` unregisters it.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any

import numpy as np

from qfedx_tpu import obs
from qfedx_tpu.obs import flight, watch
from qfedx_tpu.obs import server as obs_server
from qfedx_tpu.utils import faults


class RequestError(ValueError):
    """Client error — malformed shape or non-finite features. The
    request is rejected individually (4xx); the batch never sees it."""


class Overloaded(RuntimeError):
    """The bounded admission queue is full; this request was shed (503).
    Back off and retry — admitted requests keep their latency budget."""


class ShuttingDown(RuntimeError):
    """The batcher is closed (or closing without drain)."""


class Future:
    """Single-assignment result slot for one request. ``submit_t`` /
    ``done_t`` bracket the request's full queue+batch+compute+fetch
    latency — what the bench's p50/p95 rows report. Both come from the
    batcher's ONE injectable clock, so a test driving a fake clock gets
    coherent latencies."""

    __slots__ = (
        "_event", "_value", "_error", "_clock", "submit_t", "done_t", "seq",
    )

    def __init__(self, seq: int, clock):
        self._event = threading.Event()
        self._value: Any = None
        self._error: BaseException | None = None
        self._clock = clock
        self.submit_t = clock()
        self.done_t: float | None = None
        self.seq = seq

    def _set(self, value: Any = None, error: BaseException | None = None):
        self._value, self._error = value, error
        self.done_t = self._clock()
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None) -> Any:
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request {self.seq} unresolved after {timeout}s"
            )
        if self._error is not None:
            raise self._error
        return self._value


class MicroBatcher:
    """Admission queue + dispatcher thread in front of a ServeEngine."""

    def __init__(self, engine, clock=time.monotonic):
        self.engine = engine
        self.config = engine.config
        self._clock = clock
        self._cond = threading.Condition()
        self._pending: deque[tuple[float, np.ndarray, Future]] = deque()
        self._closed = False
        self._drain = True
        self._thread: threading.Thread | None = None
        self._seq = 0        # request sequence (the serve.request coord)
        self._batch_seq = 0  # batch sequence (the serve.compute coord)
        self.stats = {
            "served": 0, "rejected": 0, "shed": 0, "batches": 0,
            "deadline_flushes": 0, "full_flushes": 0,
        }
        self._health_fn = None  # registered by start(); identity-matched on close

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "MicroBatcher":
        if self._thread is not None:
            raise RuntimeError("batcher already started")
        # Live telemetry (r15): default off — maybe_start returns None
        # unless QFEDX_METRICS_PORT is set. The health source exposes
        # the ledger a /healthz probe needs to call the loop live.
        obs_server.maybe_start()
        # r20 detection: the watchdog ticker (QFEDX_WATCH, default off)
        # starts at the same seams the endpoint does, and the flight
        # ring gets the lifecycle edge.
        watch.maybe_start()
        flight.record("lifecycle", "batcher.start",
                      max_queue=self.config.max_queue)
        # One stable callable per batcher: bound-method attribute access
        # creates a fresh object each time, and close()'s only_if match
        # is by identity.
        self._health_fn = self._health
        obs_server.set_health_source("serve", self._health_fn)
        self._thread = threading.Thread(
            target=self._loop, name="qfedx-serve-batcher", daemon=True
        )
        self._thread.start()
        return self

    def _health(self) -> dict:
        with self._cond:
            return {
                "queue_depth": len(self._pending),
                # The admission ceiling, so queue_depth is readable as a
                # saturation fraction (the watchdog's serve.queue_sat
                # rule divides these two).
                "max_queue": self.config.max_queue,
                "closed": self._closed,
                "engine_warm": bool(getattr(self.engine, "_warm", False)),
                "buckets": list(self.config.buckets),
                **dict(self.stats),
            }

    def close(self, drain: bool = True, timeout: float | None = None):
        """Stop admission; drain (answer) or fail the queued requests;
        join the dispatcher."""
        with self._cond:
            self._closed = True
            self._drain = drain
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                raise TimeoutError("dispatcher did not drain in time")
            self._thread = None
        # Unregister AFTER the drain (probes see the closing ledger to
        # the end) and only if the registration is still OURS — closing
        # a never-started or superseded batcher must not evict another
        # batcher's live source.
        if getattr(self, "_health_fn", None) is not None:
            obs_server.clear_health_source("serve", only_if=self._health_fn)
        flight.record("lifecycle", "batcher.close", drain=drain)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close(drain=True)

    # -- admission -----------------------------------------------------------

    def _validate(self, features) -> np.ndarray:
        want = self.engine.feature_shape
        try:
            x = np.asarray(features, dtype=np.float32)
        except (TypeError, ValueError) as exc:
            raise RequestError(f"features not numeric: {exc}") from None
        if x.shape != want:
            raise RequestError(
                f"features shape {x.shape} != model feature shape {want}"
            )
        if not np.all(np.isfinite(x)):
            raise RequestError("features contain NaN/Inf")
        return x

    def submit(self, features) -> Future:
        """Admit one request; returns its Future. Raises RequestError
        (bad request), Overloaded (shed) or ShuttingDown."""
        with self._cond:
            seq = self._seq
            self._seq += 1
        plan = faults.active_plan()
        if plan is not None:
            # Deterministic request corruption (the serve.request site):
            # the mutated request must flow through the SAME validation
            # as real traffic — rejection is exercised organically, the
            # way client.compute NaNs exercise the quarantine.
            kind = plan.request_mutation(seq)
            if kind == "nan":
                features = np.full(
                    self.engine.feature_shape, np.nan, dtype=np.float32
                )
            elif kind == "malformed":
                features = np.zeros(
                    tuple(s + 1 for s in self.engine.feature_shape),
                    dtype=np.float32,
                )
        try:
            x = self._validate(features)
        except RequestError:
            with self._cond:  # stats bump under the ONE lock — submit
                self.stats["rejected"] += 1  # runs on many client threads
            obs.counter("serve.requests_rejected")
            raise
        with self._cond:
            if self._closed:
                raise ShuttingDown("batcher is closed")
            if len(self._pending) >= self.config.max_queue:
                self.stats["shed"] += 1
                obs.counter("serve.requests_shed")
                raise Overloaded(
                    f"queue depth {len(self._pending)} at max_queue="
                    f"{self.config.max_queue}"
                )
            fut = Future(seq, self._clock)
            self._pending.append((fut.submit_t, x, fut))
            obs.gauge("serve.queue_depth", len(self._pending))
            self._cond.notify_all()
        return fut

    # -- dispatcher ----------------------------------------------------------

    def _take_locked(self) -> tuple[list, str] | None:
        """Under the lock: wait for a flush trigger; pop up to one
        max-bucket of requests. None = closed and empty."""
        # The adaptation seam (r21): when the tune controller is
        # attached (QFEDX_TUNE — engine.warmup), the ACTIVE deadline and
        # bucket cap come from it, re-read once per flush so a decision
        # takes effect on the next batch with zero recompiles (the cap
        # only ever names a warmup-compiled bucket). tuner=None (the
        # default) reads the static config exactly as before.
        tuner = getattr(self.engine, "tuner", None)
        if tuner is not None:
            deadline_s = tuner.deadline_ms / 1e3
            cap = tuner.max_bucket
        else:
            deadline_s = self.config.deadline_ms / 1e3
            cap = self.engine.max_bucket
        while True:
            if self._pending and (self._closed or len(self._pending) >= cap):
                # Bucket-full flush (or the drain's final sweeps): take
                # immediately, never wait a deadline with a full batch.
                kind = "full" if len(self._pending) >= cap else "drain"
                return (
                    [self._pending.popleft()
                     for _ in range(min(cap, len(self._pending)))],
                    kind,
                )
            if self._pending:
                oldest = self._pending[0][0]
                wait = oldest + deadline_s - self._clock()
                if wait <= 0:
                    return (
                        [self._pending.popleft()
                         for _ in range(min(cap, len(self._pending)))],
                        "deadline",
                    )
                self._cond.wait(timeout=min(wait, 0.05))
            elif self._closed:
                return None
            else:
                self._cond.wait(timeout=0.05)

    def _loop(self):
        while True:
            with self._cond:
                # Idle wait OUTSIDE any span: an idle traced server must
                # not accumulate a span per poll tick.
                while not self._pending and not self._closed:
                    self._cond.wait(timeout=0.05)
                if not self._pending and self._closed:
                    return
            trace_ids = None
            with obs.span("serve.queue") as sp:
                with self._cond:
                    taken = self._take_locked()
                if taken is not None:
                    meta = {"size": len(taken[0]), "flush": taken[1]}
                    if obs.enabled():
                        # Request-scoped tracing (r15): the ids this
                        # flush serves, comma-joined — the SAME string
                        # the pad/compute/fetch spans carry below (via
                        # trace_context), so one request's path is
                        # grep-able across the trace. Built once per
                        # flush, and only when spans record: the
                        # disabled path stays join-free.
                        trace_ids = ",".join(
                            str(f.seq) for _t, _x, f in taken[0]
                        )
                        meta["reqs"] = trace_ids
                    sp.set(**meta)
            if taken is None:
                return
            reqs, kind = taken
            with self._cond:
                # Stats bumps live under the ONE lock everywhere (the
                # QFX004 lock-discipline contract): _health() hands out
                # dict(self.stats) under it, and dict iteration racing
                # a store is a RuntimeError, not just a lost count.
                if kind == "deadline":
                    self.stats["deadline_flushes"] += 1
                elif kind == "full":
                    self.stats["full_flushes"] += 1
                self._batch_seq += 1
                batch_seq = self._batch_seq
                drain_mode = self._closed and not self._drain
            if drain_mode:
                err = ShuttingDown("batcher closed without drain")
                for _, _, fut in reqs:
                    fut._set(error=err)
                continue
            x = np.stack([r[1] for r in reqs])
            try:
                # The trace context stamps every span the engine opens
                # for this batch (serve.pad/compute/fetch) with the
                # request ids it serves — batcher→engine propagation
                # without widening infer's signature (r15). trace_ids
                # was built (once) above only when tracing is on.
                if trace_ids is not None:
                    with obs.trace_context(reqs=trace_ids):
                        logits = self.engine.infer(x, seq=batch_seq)
                else:
                    logits = self.engine.infer(x, seq=batch_seq)
            except BaseException as exc:  # noqa: BLE001 — per-request surfacing
                for _, _, fut in reqs:
                    fut._set(error=exc)
                continue
            post = self.engine.postprocess(logits)
            for i, (_, _, fut) in enumerate(reqs):
                fut._set(value={
                    "logits": logits[i],
                    "probs": post["probs"][i],
                    "pred": int(post["pred"][i]),
                })
                # Bounded latency distribution (r15): submit→answer ms
                # per request into the log-bucketed histogram — what the
                # live /metrics quantiles and the CLI summary read,
                # instead of an unbounded sorted list.
                obs.histogram(
                    "serve.latency_ms", (fut.done_t - fut.submit_t) * 1e3
                )
            with self._cond:  # see the stats-under-lock note above
                self.stats["served"] += len(reqs)
                self.stats["batches"] += 1
