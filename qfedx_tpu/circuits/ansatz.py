"""Variational ansatz library.

Implements the reference's specified-but-unbuilt VQC module (reference
ROADMAP.md:20-23,126-128): hardware-efficient ansatz = per-qubit RX(θ)/RZ(φ)
rotations followed by a CNOT entangler ring, stacked L layers deep; plus the
data-reuploading variant (BASELINE.md config 4) that re-applies a trainable
affine re-encoding of the input between variational layers — the standard
remedy for expressivity/barren-plateau issues at higher qubit counts
(SURVEY.md §7.3.6).

All functions are pure: (state or features, params) → state. Circuit
structure (qubit count, depth) is static Python; parameters are traced, so
`jax.grad` differentiates through the whole simulation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from qfedx_tpu.ops import fuse, gates
from qfedx_tpu.ops.statevector import apply_cnot, apply_gate, product_state
from qfedx_tpu.circuits.encoders import angle_amplitudes


# --- trace-level IR emission (ops/fuse.py) ---------------------------------
#
# Every ansatz layer is ALSO expressible as a flat gate trace: a list of
# fuse.Op records with static qubit indices and traced coefficients. The
# fusion pass rewrites that trace into per-layer super-gates (lane
# matrices, row-pair 4×4s, phase masks) before it hits the engine —
# fewer, fatter XLA ops per step, the r07 lever on the ~9–14 ms/step
# non-streaming floor (docs/PERF.md §11–12). QFEDX_FUSE pins the route;
# off-route the layer functions below run their original per-gate loops
# unchanged. Noise stays correct by construction: traces never span a
# Kraus channel boundary (channels are applied between layer traces).


def _ring_ops(n_qubits: int) -> list:
    """IR trace of the CNOT entangler ring (matches _entangle_ring)."""
    if n_qubits < 2:
        return []
    ops = [fuse.Op("cnot", (q, q + 1)) for q in range(n_qubits - 1)]
    if n_qubits > 2:
        ops.append(fuse.Op("cnot", (n_qubits - 1, 0)))
    return ops


def hea_layer_ops(n_qubits: int, rx_angles, rz_angles) -> list:
    """IR trace of one hardware-efficient layer (shared coefficients):
    fused RZ·RX rotation per qubit, then the CNOT ring. Consumed by the
    dense fused route below and by parallel/circuit.py (the sharded
    engine runs the same trace through its own segment-and-fuse pass)."""
    return [
        fuse.Op("g1", (q,), gates.rot_zx(rx_angles[q], rz_angles[q]))
        for q in range(n_qubits)
    ] + _ring_ops(n_qubits)


def _hea_layer_ops_b(n_qubits: int, rx_angles, rz_angles) -> list:
    """Batched-slab layer trace, shared coefficients (the _b twins)."""
    return hea_layer_ops(n_qubits, rx_angles, rz_angles)


def hea_scan_ops(n_qubits: int, rx_stack, rz_stack) -> list:
    """Layer-STACKED IR trace of the HEA for the scan route (ops/fuse.py
    r17): ``rx_stack``/``rz_stack`` carry a leading layer axis — (L, n)
    shared, (L, C, n) client-folded — so each qubit's rotation coefficient
    is a (L[,C],2,2) stack and the whole L-layer ansatz is ONE trace
    consumed by ``fuse.fuse_ops_stacked`` instead of L per-layer traces."""
    return [
        fuse.Op(
            "g1",
            (q,),
            gates.rot_zx_batched(rx_stack[..., q], rz_stack[..., q]),
        )
        for q in range(n_qubits)
    ] + _ring_ops(n_qubits)


def _hea_layer_ops_cb(n_qubits: int, rx_angles, rz_angles) -> list:
    """Client-folded layer trace: per-client (C,2,2) grouped rotation
    stacks (gates.rot_zx_batched) — the fusion pass composes them into
    grouped (C,128,128) lane matrices and (C,4,4) row-pair stacks, so
    the folded federated path (docs/PERF.md §10) fuses too."""
    return [
        fuse.Op(
            "g1", (q,), gates.rot_zx_batched(rx_angles[:, q], rz_angles[:, q])
        )
        for q in range(n_qubits)
    ] + _ring_ops(n_qubits)


def init_ansatz_params(
    key: jax.Array, n_qubits: int, n_layers: int, scale: float = 0.1
) -> dict:
    """Small-angle init — near-identity start helps trainability at depth
    (barren-plateau mitigation; SURVEY.md §7.3.6)."""
    k1, k2 = jax.random.split(key)
    shape = (n_layers, n_qubits)
    return {
        "rx": scale * jax.random.normal(k1, shape, dtype=jnp.float32),
        "rz": scale * jax.random.normal(k2, shape, dtype=jnp.float32),
    }


def _entangle_ring(state: jnp.ndarray, n_qubits: int) -> jnp.ndarray:
    """CNOT ring: (0→1), (1→2), …, (n-1→0). Single qubit: no-op."""
    if n_qubits < 2:
        return state
    for q in range(n_qubits - 1):
        state = apply_cnot(state, q, q + 1)
    if n_qubits > 2:
        state = apply_cnot(state, n_qubits - 1, 0)
    return state


def ansatz_layer(state: jnp.ndarray, rx_angles, rz_angles) -> jnp.ndarray:
    """One hardware-efficient layer: RX(θ_q), RZ(φ_q) ∀q, then CNOT ring.

    The RX/RZ pair per qubit is applied as one fused 2×2 gate
    (gates.rot_zx) — half the state-sized contractions, same unitary.
    At slab widths with QFEDX_FUSE on, the whole layer additionally runs
    through the fusion pass (ops/fuse.py): lane rotations compose into
    one 128×128 MXU matrix, row rotations merge pairwise into 4×4
    super-gates, lane-lane ring CNOTs into one permutation matmul.
    """
    n = state.ndim
    if fuse.fuse_active(n):
        ops = hea_layer_ops(n, rx_angles, rz_angles)
        return fuse.apply_fused(state, fuse.fuse_ops(ops, n))
    for q in range(n):
        state = apply_gate(state, gates.rot_zx(rx_angles[q], rz_angles[q]), q)
    return _entangle_ring(state, n)


def hardware_efficient(
    state: jnp.ndarray, params: dict, remat: bool = False
) -> jnp.ndarray:
    """L-layer hardware-efficient ansatz applied to an encoded state.

    params: {"rx": (L, n), "rz": (L, n)} from `init_ansatz_params`.

    ``remat=True`` wraps each layer in ``jax.checkpoint``: reverse-mode
    autodiff then stores one 2^n state per LAYER instead of one per GATE
    (~2n fewer residuals) and recomputes the layer forward during the
    backward pass — the standard FLOPs-for-HBM trade that keeps deep
    circuits at 14+ qubits inside device memory.
    """
    n_layers = params["rx"].shape[0]
    n = state.ndim
    if not remat and fuse.scan_active(n, n_layers):
        # Scan-over-fused-layers (ops/fuse.py r17): the L layers share
        # one fused super-gate body; stacked (L,…) coefficients ride
        # the scan. remat keeps the per-layer loop (jax.checkpoint has
        # its own per-layer structure the scan would subsume).
        ops = hea_scan_ops(n, params["rx"], params["rz"])
        return fuse.apply_scan(
            state, n, fuse.fuse_ops_stacked(ops, n, n_layers)
        )
    layer_fn = ansatz_layer
    if remat:
        layer_fn = jax.checkpoint(ansatz_layer)
    for layer in range(n_layers):
        state = layer_fn(state, params["rx"][layer], params["rz"][layer])
    return state


def _entangle_ring_b(state, n_qubits: int):
    """CNOT ring on the batched slab (the batch-folded ``_entangle_ring``);
    CNOTs are coefficient-free so one form serves shared, per-sample and
    per-client layers alike."""
    from qfedx_tpu.ops.batched import apply_cnot_b

    if n_qubits < 2:
        return state
    for q in range(n_qubits - 1):
        state = apply_cnot_b(state, n_qubits, q, q + 1)
    if n_qubits > 2:
        state = apply_cnot_b(state, n_qubits, n_qubits - 1, 0)
    return state


def ansatz_layer_b(state, n_qubits: int, rx_angles, rz_angles, pre_ops=()):
    """Batched-slab twin of ``ansatz_layer``: same circuit, state shape
    (B, 2^n) with batch folded into slab rows (ops.batched — the layout
    fix for scanned-batch training; docs/PERF.md §8). ``pre_ops``: extra
    IR ops prepended to the layer trace (the data-reuploading encoder
    banks) so cross-boundary gates fuse into the same super-gates."""
    from qfedx_tpu.ops.batched import apply_gate_b

    if fuse.fuse_active(n_qubits):
        ops = list(pre_ops) + _hea_layer_ops_b(n_qubits, rx_angles, rz_angles)
        return fuse.apply_fused_b(state, n_qubits, fuse.fuse_ops(ops, n_qubits))
    for op in pre_ops:
        state = apply_gate_b(state, n_qubits, op.coeffs, op.qubits[0])
    for q in range(n_qubits):
        state = apply_gate_b(
            state, n_qubits, gates.rot_zx(rx_angles[q], rz_angles[q]), q
        )
    return _entangle_ring_b(state, n_qubits)


def hardware_efficient_b(state, n_qubits: int, params: dict):
    """Batched-slab twin of ``hardware_efficient`` (no remat variant: the
    batched path serves widths where remat measured 5× slower than the
    fitting tape — docs/PERF.md §7)."""
    n_layers = params["rx"].shape[0]
    if fuse.scan_active(n_qubits, n_layers):
        ops = hea_scan_ops(n_qubits, params["rx"], params["rz"])
        return fuse.apply_scan(
            state,
            n_qubits,
            fuse.fuse_ops_stacked(ops, n_qubits, n_layers),
            batched=True,
        )
    for layer in range(n_layers):
        state = ansatz_layer_b(
            state, n_qubits, params["rx"][layer], params["rz"][layer]
        )
    return state


def ansatz_layer_cb(state, n_qubits: int, rx_angles, rz_angles, pre_ops=()):
    """Client-folded ansatz layer: state (C·B, 2^n) with the CLIENT axis a
    leading group of the slab rows, angles (C, n) — one grouped gate
    (ops.batched per-group coefficients) per qubit instead of a client
    vmap over C engine traces (docs/PERF.md §10). With QFEDX_FUSE on the
    grouped stacks fuse like shared ones: (C,128,128) lane matrices and
    (C,2,2,2,2) row-pair super-gates (ops/fuse.py)."""
    from qfedx_tpu.ops.batched import apply_gate_b

    if fuse.fuse_active(n_qubits):
        ops = list(pre_ops) + _hea_layer_ops_cb(n_qubits, rx_angles, rz_angles)
        return fuse.apply_fused_b(state, n_qubits, fuse.fuse_ops(ops, n_qubits))
    for op in pre_ops:
        state = apply_gate_b(state, n_qubits, op.coeffs, op.qubits[0])
    for q in range(n_qubits):
        state = apply_gate_b(
            state,
            n_qubits,
            gates.rot_zx_batched(rx_angles[:, q], rz_angles[:, q]),
            q,
        )
    return _entangle_ring_b(state, n_qubits)


def hardware_efficient_cb(state, n_qubits: int, params: dict):
    """Client-folded ``hardware_efficient``: params leaves carry a leading
    client axis — {"rx": (C, L, n), "rz": (C, L, n)} — and the state is the
    (C·B, 2^n) client-major slab."""
    n_layers = params["rx"].shape[1]
    if fuse.scan_active(n_qubits, n_layers):
        # (C, L, n) → (L, C, n): the layer axis leads the scan stack and
        # the client axis stays a coefficient group (ops/fuse.py r17).
        ops = hea_scan_ops(
            n_qubits,
            jnp.moveaxis(params["rx"], 0, 1),
            jnp.moveaxis(params["rz"], 0, 1),
        )
        return fuse.apply_scan(
            state,
            n_qubits,
            fuse.fuse_ops_stacked(ops, n_qubits, n_layers),
            batched=True,
        )
    for layer in range(n_layers):
        state = ansatz_layer_cb(
            state, n_qubits, params["rx"][:, layer], params["rz"][:, layer]
        )
    return state


def data_reuploading_cb(features, params: dict):
    """Client-folded ``data_reuploading``: features (C, B, n) in [0,1],
    params leaves (C, L, n). Re-encoding angles depend on (client, sample,
    qubit), so the encoder banks are per-sample gates over the C·B folded
    rows; the variational layers are per-client grouped gates."""
    from qfedx_tpu.ops.batched import bstate_product

    c, b, n_qubits = features.shape
    n_layers = params["rx"].shape[1]
    if fuse.scan_active(n_qubits, n_layers - 1):
        # Layer 0 encodes |0…0⟩ directly (no bank) and runs alone; the
        # remaining L−1 [bank + variational layer] blocks share one
        # scanned trace: per-sample (L−1, C·B, 2, 2) bank stacks join
        # per-client (L−1, C, 2, 2) variational stacks (ops/fuse.py r17).
        ew = jnp.moveaxis(params["enc_w"], 0, 1)  # (L, C, n)
        eb = jnp.moveaxis(params["enc_b"], 0, 1)
        angles = (
            ew[:, :, None, :] * (features * jnp.pi)[None]
            + eb[:, :, None, :]
        ).reshape(n_layers, c * b, n_qubits)
        from qfedx_tpu.ops.batched import bstate_product_tree

        flat0 = angles[0]
        state = bstate_product_tree(angle_amplitudes(flat0, "ry"))
        state = ansatz_layer_cb(
            state, n_qubits, params["rx"][:, 0], params["rz"][:, 0]
        )
        ops = [
            fuse.Op("g1", (q,), gates.ry_batched(angles[1:, :, q]))
            for q in range(n_qubits)
        ] + hea_scan_ops(
            n_qubits,
            jnp.moveaxis(params["rx"], 0, 1)[1:],
            jnp.moveaxis(params["rz"], 0, 1)[1:],
        )
        return fuse.apply_scan(
            state,
            n_qubits,
            fuse.fuse_ops_stacked(ops, n_qubits, n_layers - 1),
            batched=True,
        )
    for layer in range(n_layers):
        angles = (
            params["enc_w"][:, layer][:, None] * (features * jnp.pi)
            + params["enc_b"][:, layer][:, None]
        )  # (C, B, n)
        flat = angles.reshape(c * b, n_qubits)
        pre_ops = ()
        if layer == 0:
            state = bstate_product(angle_amplitudes(flat, "ry"))
        else:
            # Re-encoding banks join the layer's gate trace as per-sample
            # (C·B,2,2) IR ops: under QFEDX_FUSE their lane qubits fuse
            # into one per-sample lane matrix and their row qubits pair
            # up, instead of n separate engine passes (ops/fuse.py).
            pre_ops = tuple(
                fuse.Op("g1", (q,), gates.ry_batched(flat[:, q]))
                for q in range(n_qubits)
            )
        state = ansatz_layer_cb(
            state,
            n_qubits,
            params["rx"][:, layer],
            params["rz"][:, layer],
            pre_ops=pre_ops,
        )
    return state


def data_reuploading_b(features, params: dict):
    """Batched-slab twin of ``data_reuploading``: features (B, n) in [0,1];
    re-encoding banks are per-sample RY gates (gates.ry_batched), joined
    to the layer's gate trace so they fuse with it under QFEDX_FUSE."""
    from qfedx_tpu.circuits.encoders import angle_amplitudes
    from qfedx_tpu.ops.batched import bstate_product

    n_layers, n_qubits = params["rx"].shape
    if fuse.scan_active(n_qubits, n_layers - 1):
        # Scan route: layer 0 alone, then ONE [bank + layer] body over
        # the remaining L−1 layers (per-sample (L−1,B,2,2) bank stacks).
        angles_all = (
            params["enc_w"][:, None, :] * (features * jnp.pi)[None]
            + params["enc_b"][:, None, :]
        )  # (L, B, n)
        from qfedx_tpu.ops.batched import bstate_product_tree

        state = bstate_product_tree(angle_amplitudes(angles_all[0], "ry"))
        state = ansatz_layer_b(
            state, n_qubits, params["rx"][0], params["rz"][0]
        )
        ops = [
            fuse.Op("g1", (q,), gates.ry_batched(angles_all[1:, :, q]))
            for q in range(n_qubits)
        ] + hea_scan_ops(n_qubits, params["rx"][1:], params["rz"][1:])
        return fuse.apply_scan(
            state,
            n_qubits,
            fuse.fuse_ops_stacked(ops, n_qubits, n_layers - 1),
            batched=True,
        )
    for layer in range(n_layers):
        angles = (
            params["enc_w"][layer][None] * (features * jnp.pi)
            + params["enc_b"][layer][None]
        )
        pre_ops = ()
        if layer == 0:
            state = bstate_product(angle_amplitudes(angles, "ry"))
        else:
            pre_ops = tuple(
                fuse.Op("g1", (q,), gates.ry_batched(angles[:, q]))
                for q in range(n_qubits)
            )
        state = ansatz_layer_b(
            state,
            n_qubits,
            params["rx"][layer],
            params["rz"][layer],
            pre_ops=pre_ops,
        )
    return state


def init_reuploading_params(
    key: jax.Array, n_qubits: int, n_layers: int, scale: float = 0.1
) -> dict:
    """Adds per-layer trainable affine re-encoding (w·x + b) of the input."""
    k1, k2, k3 = jax.random.split(key, 3)
    base = init_ansatz_params(k1, n_qubits, n_layers, scale)
    base["enc_w"] = jnp.ones((n_layers, n_qubits), dtype=jnp.float32) + (
        scale * jax.random.normal(k2, (n_layers, n_qubits), dtype=jnp.float32)
    )
    base["enc_b"] = scale * jax.random.normal(k3, (n_layers, n_qubits), dtype=jnp.float32)
    return base


def data_reuploading(
    features: jnp.ndarray, params: dict, remat: bool = False
) -> jnp.ndarray:
    """Data-reuploading circuit: [encode(w_l·x+b_l) → variational layer] × L.

    ``features`` in [0,1], shape (n,); the first encoding starts from |0…0⟩
    as a direct product state, later re-encodings are RY rotation banks.
    ``remat=True`` checkpoints each re-encode+layer block (same trade as
    `hardware_efficient`).
    """
    n_layers, n_qubits = params["rx"].shape

    def block(state, angles, rx_l, rz_l):
        if fuse.fuse_active(n_qubits):
            # Re-encoding bank + variational layer as ONE trace: the RY
            # bank's lane qubits fuse into the layer's lane matrix.
            ops = [
                fuse.Op("g1", (q,), gates.ry(angles[q]))
                for q in range(n_qubits)
            ] + hea_layer_ops(n_qubits, rx_l, rz_l)
            return fuse.apply_fused(state, fuse.fuse_ops(ops, n_qubits))
        for q in range(n_qubits):
            state = apply_gate(state, gates.ry(angles[q]), q)
        return ansatz_layer(state, rx_l, rz_l)

    if not remat and fuse.scan_active(n_qubits, n_layers - 1):
        angles_all = (
            params["enc_w"] * (features * jnp.pi)[None] + params["enc_b"]
        )  # (L, n)
        state = product_state(angle_amplitudes(angles_all[0], "ry"))
        state = ansatz_layer(state, params["rx"][0], params["rz"][0])
        ops = [
            fuse.Op("g1", (q,), gates.ry_batched(angles_all[1:, q]))
            for q in range(n_qubits)
        ] + hea_scan_ops(n_qubits, params["rx"][1:], params["rz"][1:])
        return fuse.apply_scan(
            state, n_qubits, fuse.fuse_ops_stacked(ops, n_qubits, n_layers - 1)
        )

    first_fn, block_fn = ansatz_layer, block
    if remat:
        first_fn = jax.checkpoint(ansatz_layer)
        block_fn = jax.checkpoint(block)

    for layer in range(n_layers):
        angles = params["enc_w"][layer] * (features * jnp.pi) + params["enc_b"][layer]
        if layer == 0:
            state = product_state(angle_amplitudes(angles, "ry"))
            state = first_fn(state, params["rx"][layer], params["rz"][layer])
        else:
            state = block_fn(
                state, angles, params["rx"][layer], params["rz"][layer]
            )
    return state
