from qfedx_tpu.circuits.encoders import amplitude_encode, angle_encode  # noqa: F401
from qfedx_tpu.circuits.ansatz import (  # noqa: F401
    hardware_efficient,
    init_ansatz_params,
)
from qfedx_tpu.circuits.readout import z_logits  # noqa: F401
