"""Measurement readout → logits.

Reference spec (ROADMAP.md:128): measure ⟨Z⟩ on readout qubit(s) and map to
a logit ``a·⟨Z⟩ + b``. Multi-class: class c reads qubit c (requires
num_classes ≤ n_qubits), each with its own trainable scale/bias.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from qfedx_tpu.ops.statevector import expect_z_all


def init_readout_params(key: jax.Array, num_classes: int) -> dict:
    del key  # deterministic init; key kept for API uniformity
    return {
        "scale": jnp.ones((num_classes,), dtype=jnp.float32),
        "bias": jnp.zeros((num_classes,), dtype=jnp.float32),
    }


def z_logits(state: jnp.ndarray, params: dict) -> jnp.ndarray:
    """logit_c = scale_c · ⟨Z_c⟩ + bias_c for c < num_classes."""
    num_classes = params["scale"].shape[0]
    if num_classes > state.ndim:
        raise ValueError(
            f"{num_classes} classes need ≥{num_classes} qubits, have {state.ndim}"
        )
    z = expect_z_all(state)[:num_classes]
    return params["scale"] * z + params["bias"]
