"""Data encoders: classical feature vector → quantum state.

Capability parity with the reference's two encoders, redesigned as pure
state constructions (no circuit objects):

- Angle encoding (reference src/QFed/qAngle.py:27-51): one rotation per
  qubit on |0…0⟩. A bank of single-qubit rotations on |0…0⟩ *is* a product
  state, so we materialize it directly via tensor products — no gate
  applications, O(2^n) writes total. With the default RY basis the state is
  purely real, which halves all downstream contraction work (ops.cpx).
  Feature→angle normalization is fitted on the training set upstream
  (`data.pipeline.minmax_fit`), fixing the reference's per-sample min-max
  quirk (SURVEY.md §7.4).
- Amplitude encoding (reference src/QFed/qAmplitude.py:11-41): ℓ2-normalize
  and reshape — on TPU there is no need for Qiskit's `initialize` circuit
  decomposition; the state is just the data. The all-zero → uniform
  superposition fallback is preserved, branch-free via `jnp.where`.
"""

from __future__ import annotations

import jax.numpy as jnp

from qfedx_tpu.ops.cpx import CArray, state_dtype
from qfedx_tpu.ops.statevector import product_state


def angle_amplitudes(angles: jnp.ndarray, basis: str = "ry") -> CArray:
    """Per-qubit 2-vectors for R_basis(angle)|0⟩; angles shape (n,) → (n, 2).

    cos/sin run in f32; the 2-vectors are cast to the state dtype so the
    product state (and everything downstream) carries QFEDX_DTYPE."""
    half = angles / 2.0
    c = jnp.cos(half).astype(state_dtype())
    s = jnp.sin(half).astype(state_dtype())
    if basis == "ry":
        # RY(θ)|0⟩ = [cos θ/2, sin θ/2] — real.
        return CArray(jnp.stack([c, s], axis=-1), None)
    if basis == "rx":
        # RX(θ)|0⟩ = [cos θ/2, −i sin θ/2].
        zero = jnp.zeros_like(c)
        return CArray(
            jnp.stack([c, zero], axis=-1), jnp.stack([zero, -s], axis=-1)
        )
    if basis == "rz":
        # RZ(θ)|0⟩ = e^{−iθ/2}|0⟩ — a pure phase, kept for API parity with
        # the reference's basis option (qAngle.py:45-50).
        zero = jnp.zeros_like(c)
        return CArray(
            jnp.stack([c, zero], axis=-1), jnp.stack([-s, zero], axis=-1)
        )
    raise ValueError(f"unknown basis {basis!r}")


def angle_encode(features: jnp.ndarray, basis: str = "ry") -> CArray:
    """Features in [0,1], shape (n,) → state (2,)*n via R(π·f_k) per qubit."""
    angles = features * jnp.pi
    return product_state(angle_amplitudes(angles, basis))


def amplitude_encode(x: jnp.ndarray) -> CArray:
    """x of length 2^n → normalized real state of shape (2,)*n.

    All-zero input falls back to the uniform superposition (reference
    qAmplitude.py:17-21), expressed branch-free so it vmaps/jits.
    """
    x = jnp.asarray(x, dtype=jnp.float32)
    size = x.shape[-1]
    n = size.bit_length() - 1
    if 1 << n != size:
        raise ValueError(f"amplitude encoding needs 2^n features, got {size}")
    norm = jnp.linalg.norm(x)  # normalize in f32, then cast the state
    uniform = jnp.full((size,), 1.0 / jnp.sqrt(size), dtype=jnp.float32)
    safe = jnp.where(norm > 0, x / jnp.where(norm > 0, norm, 1.0), uniform)
    return CArray(safe.reshape((2,) * n).astype(state_dtype()), None)
