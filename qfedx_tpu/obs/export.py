"""Exporters for the obs registry: phase rollups + Chrome/Perfetto trace.

Three sinks, per the observability contract:

1. ``phase_rollup()`` — per-phase {count, total_s, p50_s, p95_s,
   compile_s}: merged into ``metrics.jsonl`` rows by the trainer and
   into ``summary.json`` by ``run.metrics.ExperimentRun.finish``.
2. ``write_chrome_trace(path)`` — Chrome trace-event JSON ("X" complete
   events, µs timestamps) loadable in Perfetto / chrome://tracing; the
   ``--trace`` CLI flag writes one per run.
3. ``snapshot()`` — raw spans/counters/gauges for programmatic
   consumers (bench.py's ``phase_breakdown`` section uses
   ``phase_rollup``; ``snapshot``/``phase_totals`` are the raw/compact
   views for ad-hoc tooling).
"""

from __future__ import annotations

import json
from pathlib import Path

from qfedx_tpu.obs.histo import Histogram
from qfedx_tpu.obs.trace import Span, registry


def percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile of an already-sorted list — the ONE
    quantile DEFINITION. Since r15 the production reporters (phase
    rollup, serve CLI summary, bench serving rows) read quantiles from
    bounded ``obs.Histogram``s, whose ``percentile`` applies THIS rank
    rule to bucket counts — so histogram quantiles land within one
    bucket-width of this function's exact answer (pinned in
    tests/test_obs.py), and exact/approx can never drift on index
    math."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def phase_rollup(spans: list[Span] | None = None) -> dict[str, dict]:
    """Aggregate spans by name → {count, total_s, p50_s, p95_s,
    compile_s}, ordered by total_s descending (the expensive phase reads
    first in summary.json).

    With no argument this reads the registry's per-span-name duration
    HISTOGRAMS (bounded memory, maintained as spans close — r15), not
    the span list: quantiles are bucket-resolution (within one
    bucket-width of exact, always <= exact — lower-edge nearest-rank,
    obs/histo.py) while count/total/compile stay exact sums. An
    explicit span list takes the same path through ephemeral
    histograms, so the two calls cannot disagree on definitions.

    When a parsed profiler capture has attached per-span device
    attribution (obs/profile.attach_span_device, r16), registry rows
    additionally carry ``device_busy_s`` (clamped to the span wall) and
    ``utilization`` in (0, 1]."""
    device_by_name: dict = {}
    if spans is None:
        histos, compile_by_name = registry().span_rollup_source()
        device_by_name = registry().span_device_view()
    else:
        histos = {}
        compile_by_name = {}
        for sp in spans:
            h = histos.get(sp.name)
            if h is None:
                h = histos[sp.name] = Histogram()
            h.record(sp.duration)
            if sp.compile_s > 0:
                compile_by_name[sp.name] = (
                    compile_by_name.get(sp.name, 0.0) + sp.compile_s
                )
    rows = {}
    for name, h in histos.items():
        rows[name] = {
            "count": h.count,
            "total_s": round(h.sum, 6),
            "p50_s": round(h.percentile(0.50), 6),
            "p95_s": round(h.percentile(0.95), 6),
        }
        if compile_by_name.get(name, 0.0) > 0:
            rows[name]["compile_s"] = round(compile_by_name[name], 6)
        if name in device_by_name:
            busy_s, _util = device_by_name[name]
            total_s = rows[name]["total_s"]
            busy_s = round(min(busy_s, total_s), 6)
            # A clamp that zeroes the column (a µs-wall span whose
            # annotation window caught unrelated async device work) is
            # noise, not attribution — leave the row without columns.
            # utilization is recomputed over THIS row's wall so the two
            # columns can never contradict each other (the summary's
            # spans table keeps the annotation-wall ratio).
            if busy_s > 0 and total_s > 0:
                rows[name]["device_busy_s"] = busy_s
                rows[name]["utilization"] = round(
                    min(1.0, busy_s / total_s), 4
                )
    return dict(sorted(rows.items(), key=lambda kv: -kv[1]["total_s"]))


def phase_totals(spans: list[Span] | None = None) -> dict[str, float]:
    """Compact {phase: total_s} view — small enough for bench.py's
    printed one-line JSON (the driver's captured artifact, which the
    next round's vs_prev diff reads)."""
    return {
        name: row["total_s"] for name, row in phase_rollup(spans).items()
    }


def snapshot() -> dict:
    """Raw registry contents as plain JSON-able data."""
    reg = registry()
    return {
        "spans": [
            {
                "name": sp.name,
                "t0": sp.t0 - reg.origin,
                "dur_s": sp.duration,
                "depth": sp.depth,
                "compile_s": sp.compile_s,
                "meta": sp.meta,
            }
            for sp in reg.spans
        ],
        "counters": dict(reg.counters),
        "gauges": dict(reg.gauges),
        "histograms": {
            name: h.snapshot() for name, h in reg.histos.items()
        },
    }


def chrome_trace_events(spans: list[Span] | None = None) -> list[dict]:
    """Spans → Chrome trace-event list ("X" complete events). Timestamps
    are µs since the registry origin (monotonic clock), one pid, tid per
    originating thread — Perfetto renders the nesting from ts/dur."""
    reg = registry()
    spans = reg.spans if spans is None else spans
    tids: dict[int, int] = {}
    events: list[dict] = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": 1,
            "tid": 0,
            "args": {"name": "qfedx_tpu"},
        }
    ]
    for sp in spans:
        if sp.tid not in tids:
            tids[sp.tid] = len(tids)
            # Name the track after the originating thread — the r09
            # async checkpoint writer puts spans on a second thread, and
            # an anonymous numeric track defeats the point of the trace.
            events.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": 1,
                    "tid": tids[sp.tid],
                    "args": {"name": sp.tname or "thread"},
                }
            )
        tid = tids[sp.tid]
        args = {k: _jsonable_meta(v) for k, v in sp.meta.items()}
        if sp.compile_s > 0:
            args["compile_ms"] = round(sp.compile_s * 1e3, 3)
        events.append(
            {
                "name": sp.name,
                "ph": "X",
                "ts": round((sp.t0 - reg.origin) * 1e6, 3),
                "dur": round(sp.duration * 1e6, 3),
                "pid": 1,
                "tid": tid,
                "args": args,
            }
        )
    # Counters as one instant summary event at the end of the window.
    if reg.counters or reg.gauges:
        last = max(
            (e["ts"] + e["dur"] for e in events if e["ph"] == "X"), default=0.0
        )
        events.append(
            {
                "name": "counters",
                "ph": "i",
                "s": "g",
                "ts": last,
                "pid": 1,
                "tid": 0,
                "args": {**reg.counters, **reg.gauges},
            }
        )
    return events


def _jsonable_meta(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


def write_chrome_trace(path: str | Path, spans: list[Span] | None = None) -> Path:
    """Write the registry (or ``spans``) as a Chrome/Perfetto
    ``trace.json``. Plain ``{"traceEvents": [...]}`` array-of-events
    format — both viewers accept it."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(
        json.dumps(
            {
                "traceEvents": chrome_trace_events(spans),
                "displayTimeUnit": "ms",
            }
        )
    )
    return path
