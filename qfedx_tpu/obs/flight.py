"""The flight recorder: a bounded ring of recent events, dumped on death.

Why this exists: a crashed process with ``QFEDX_TRACE`` off (the
default) leaves NO record of its final seconds — the span registry is
empty, metrics.jsonl stops at the last completed round, and the live
``/metrics`` endpoint died with the process. The r15/r16 layers answer
"what is happening" while you watch; nothing answers "what *was*
happening" after the fact. This module is the black box: a fixed-size
ring of recent events (span closures, counter/gauge deltas, health
transitions, watchdog alert firings — see obs/watch.py) that records at
strictly bounded memory even with tracing off, and is dumped as a
single ``flight.json`` artifact when the process dies badly:

- on SIGTERM, riding the existing ``utils/host`` translation (SIGTERM →
  ``KeyboardInterrupt("SIGTERM")`` → ``ExperimentRun.__exit__``);
- on ANY exception unwinding ``ExperimentRun.__exit__`` (run/metrics.py);
- on a watchdog alert firing (obs/watch.py) — the moment something is
  already known to be wrong is the moment the recent past is most
  valuable, and the process may not live to SIGTERM.

Cost model: gated on the ``QFEDX_FLIGHT`` pin (default OFF — the
disabled path is one env read + one branch per tap, the same contract
as ``QFEDX_TRACE``). The pin carries the ring capacity through the
shared depth grammar: ``0``/``off`` → disabled, ``1``/``on`` → the
default 256 events, a bare integer → that many events. Memory is
``capacity`` small dicts (string fields truncated at record time); the
dump is re-truncated (oldest first) until it fits ``byte_bound()`` —
the "size-bounded, parseable" artifact contract pinned in tests.

The taps live in obs/trace.py's public ``counter``/``gauge``/
``histogram``/``span.__exit__`` (NOT in ``_Registry`` — the registry
stays a pure store), in obs/server.py's health-status transitions, and
in the serving/training components (``ServeEngine``, ``MicroBatcher``,
the streamed trainer) for lifecycle edges. Multi-host: only process 0
writes the dump (``utils.host.is_primary``), same as every other run
artifact.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from pathlib import Path

from qfedx_tpu.utils import pins
from qfedx_tpu.utils.host import is_primary

DEFAULT_CAPACITY = 256
FLIGHT_SCHEMA_VERSION = 1

# Per-event string budget: every str field is cut here at RECORD time,
# so a single event can never blow the dump envelope.
_MAX_STR = 160
# Dump envelope allowance + per-event budget behind byte_bound(): a
# truncated event serializes well under this (fields are capped above).
_ENVELOPE_BYTES = 4096
_PER_EVENT_BYTES = 512

_lock = threading.Lock()
_ring: deque | None = None
_dropped = 0
_dump_path: Path | None = None
_last_dump: dict | None = None


def capacity() -> int:
    """The QFEDX_FLIGHT pin through the shared depth grammar
    (pins.depth_pin): 0/'off'/unset → 0 (recorder off, the default),
    '1'/'on' → DEFAULT_CAPACITY events, a bare integer → that capacity.
    Read per call — the recorder can be toggled mid-process, same as
    QFEDX_TRACE."""
    return pins.depth_pin("QFEDX_FLIGHT", 0, on_value=DEFAULT_CAPACITY)


def enabled() -> bool:
    return capacity() > 0


def byte_bound() -> int:
    """The configured dump-size bound ``dump`` enforces: envelope
    allowance + a fixed per-event budget × the pinned capacity. A
    function of the pin, so operators size the black box with ONE knob."""
    return _ENVELOPE_BYTES + _PER_EVENT_BYTES * capacity()


def _ring_for(cap: int) -> deque:
    """The module ring, (re)built when the pinned capacity changes.
    Callers hold ``_lock``."""
    global _ring
    if _ring is None or _ring.maxlen != cap:
        old = list(_ring) if _ring is not None else []
        _ring = deque(old[-cap:], maxlen=cap)
    return _ring


def _clip(v):
    if v is None or isinstance(v, bool):
        return v
    if isinstance(v, int):
        return v
    if isinstance(v, float):
        return round(v, 6)
    return str(v)[:_MAX_STR]


def record(kind: str, name: str, **fields) -> None:
    """Append one event to the ring (no-op when QFEDX_FLIGHT is off).
    ``kind`` is the event class (``span``/``counter``/``gauge``/
    ``health``/``alert``/``lifecycle``/...), ``name`` the instrument or
    phase, ``fields`` small scalars — every string is truncated at
    record time so ring memory is a hard function of capacity."""
    cap = capacity()
    if cap <= 0:
        return
    # Side-effect-only telemetry stamp: the value never flows back into
    # the caller, so a counter bump during tracing records the TRACE
    # instant without baking host state into the traced program.
    ts = round(time.time(), 3)  # qfedx: ignore[QFX001] telemetry timestamp, write-only — never returned into a trace
    ev = {"t": ts, "kind": str(kind)[:40], "name": str(name)[:_MAX_STR]}
    for k, v in fields.items():
        ev[str(k)[:40]] = _clip(v)
    global _dropped
    with _lock:
        ring = _ring_for(cap)
        if len(ring) == cap:
            _dropped += 1
        ring.append(ev)


# -- taps (called from obs/trace.py and obs/server.py) -------------------------


def on_span(name: str, duration_s: float) -> None:
    record("span", name, ms=duration_s * 1e3)


def on_counter(name: str, inc: float) -> None:
    record("counter", name, inc=inc)


def on_gauge(name: str, value: float) -> None:
    record("gauge", name, value=value)


def on_histogram(name: str, value: float) -> None:
    record("histo", name, value=value)


def on_health(status: str, prev: str) -> None:
    record("health", "status", to=status, was=prev)


# -- the dump ------------------------------------------------------------------


def set_dump_path(path: str | Path | None) -> None:
    """Configure where ``maybe_dump`` writes. ExperimentRun points this
    at ``<run_dir>/flight.json``; the serve CLI at the served run dir.
    Latest caller wins — one process, one black box."""
    global _dump_path
    with _lock:
        _dump_path = Path(path) if path is not None else None


def dump_path() -> Path | None:
    with _lock:
        return _dump_path


def events() -> list[dict]:
    """Snapshot of the ring, oldest first (tests and ad-hoc dumps)."""
    with _lock:
        return list(_ring) if _ring is not None else []


def dropped() -> int:
    with _lock:
        return _dropped


def dump(path: str | Path | None = None, reason: str = "") -> Path | None:
    """Write the black box as ``flight.json``: valid JSON, at most
    ``byte_bound()`` bytes (oldest events are shed until it fits — the
    newest moments are the ones a post-mortem needs). Returns the path,
    or None when the recorder is off, no path is configured, or this is
    not the primary process. Raises on I/O errors — use ``maybe_dump``
    from crash paths."""
    if not enabled():
        return None
    target = Path(path) if path is not None else dump_path()
    if target is None or not is_primary():
        return None
    with _lock:
        evs = list(_ring) if _ring is not None else []
        dropped_n = _dropped
    bound = byte_bound()
    shed = 0
    while True:
        doc = {
            "schema": FLIGHT_SCHEMA_VERSION,
            "reason": str(reason)[:_MAX_STR],
            "ts": round(time.time(), 3),
            "pid": os.getpid(),
            "capacity": capacity(),
            "dropped": dropped_n,
            "shed_for_bound": shed,
            "events": evs,
        }
        blob = json.dumps(doc)
        if len(blob) + 1 <= bound or not evs:
            break
        cut = max(1, len(evs) // 8)
        evs = evs[cut:]
        shed += cut
    target.parent.mkdir(parents=True, exist_ok=True)
    target.write_text(blob + "\n")
    global _last_dump
    info = {
        "path": str(target),
        "bytes": len(blob) + 1,
        "reason": doc["reason"],
        "events": len(evs),
        "ts": doc["ts"],
    }
    with _lock:
        _last_dump = info
    return target


def maybe_dump(reason: str = "", path: str | Path | None = None) -> Path | None:
    """``dump`` that never raises — the crash-path wrapper (a failing
    black-box write must not mask the actual crash, the same contract
    as ExperimentRun.flush_partial_observability)."""
    try:
        return dump(path, reason)
    except Exception:  # noqa: BLE001 — dumping must not mask the crash
        return None


def last_dump() -> dict | None:
    """{path, bytes, reason, events, ts} of the most recent dump this
    process wrote (None before the first) — what `qfedx inspect` and
    tests read."""
    with _lock:
        return dict(_last_dump) if _last_dump else None


def reset() -> None:
    """Drop the ring, the configured path and the last-dump record
    (tests isolate themselves with this, like obs.reset)."""
    global _ring, _dropped, _dump_path, _last_dump
    with _lock:
        _ring = None
        _dropped = 0
        _dump_path = None
        _last_dump = None
