"""The SLO watchdog: declarative alert rules evaluated on a ticker.

Why this exists: the r15 endpoint *answers* scrapes and the r16
profiler runs when asked — nothing in-process ever DECIDES that a p95
SLO is breached, a trainer has stalled, or a loss has diverged. Every
control loop the ROADMAP names (hot-swap rollback, replica shedding,
auto-tuning) needs that decision made where the signals live. This
module is the detection half: a fixed set of stable-ID'd rules
(``RULE_IDS``) evaluated against the live obs registry and the
registered ``/healthz`` component sources, either on a daemon ticker
(``maybe_start``) or explicitly (``evaluate_once`` — what tests drive).

A rule transitioning to FIRING:

- sets the ``alert.<rule_id>`` gauge to 1 (rendered as
  ``qfedx_alert_<rule_id>`` on ``/metrics``) and bumps the
  ``alert.fired.<rule_id>`` counter;
- joins the ``alerts`` section of ``/healthz`` (obs/server.py), which
  drives the existing degraded→503 path — an orchestrator probe sees
  the FIRING RULE BY NAME, not just a sad status code;
- emits a structured ``{"event": "alert", ...}`` row into
  ``metrics.jsonl`` when an ExperimentRun has registered the event sink
  (``set_event_sink`` — same identity-matched registration contract as
  the health sources);
- records into the flight ring and triggers a black-box dump
  (obs/flight.py) — the moment something is known wrong is the moment
  the recent past is most valuable.

Clearing reverses the gauge and emits a ``cleared`` event; ``/healthz``
returns to 200 (the 200→503→200 round trip is pinned in tests against
an injected FaultPlan).

Cost model: everything gates on the ``QFEDX_WATCH`` pin (default OFF —
no thread, no state, and ``evaluate_once`` is a no-op returning []).
The pin carries the tick period: ``0``/``off`` → disabled, ``1``/``on``
→ a 1 s tick, a bare number → that many seconds. While the watchdog is
enabled the BOUNDED instruments record even without a live endpoint or
QFEDX_TRACE (``trace.metrics_enabled`` — a watchdog with an empty
registry would be blind); spans stay gated on QFEDX_TRACE alone.
Default-pin parity: with QFEDX_WATCH unset, nothing here runs — the
invariance tests pin it.

Thresholds are pins (one per rule — see the "Alert-rule taxonomy" table
in docs/OBSERVABILITY.md, enforced both directions by QFX106):
evaluation is host-side only and never touches compiled programs.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Callable

from qfedx_tpu.obs import flight, trace
from qfedx_tpu.utils import pins

# Stable rule identifiers — APPEND-ONLY, like faults.SITES: alert
# consumers (dashboards, the metrics.jsonl ledger, the taxonomy table)
# key on these strings.
RULE_IDS = (
    "serve.p95_slo",
    "serve.shed_rate",
    "serve.queue_sat",
    "trainer.stall",
    "trainer.loss",
    "trainer.eps_burn",
)

# serve.p95_slo holds fire until the latency histogram has a minimally
# meaningful population — a 2-sample p95 is noise, not an SLO breach.
P95_MIN_COUNT = 20


def interval_s() -> float:
    """The QFEDX_WATCH pin: '0'/'off'/unset → 0.0 (watchdog off, the
    default), '1'/'on' → 1.0 s tick, a bare number → that tick period in
    seconds. Loud on anything else (the family grammar). Read per call —
    host-side guard, toggleable mid-process like QFEDX_TRACE."""
    env = pins.str_pin("QFEDX_WATCH")
    if env is None:
        return 0.0
    as_bool = pins.parse_onoff(env)
    if as_bool is not None:
        return 1.0 if as_bool else 0.0
    try:
        period = float(env)
    except ValueError:
        raise ValueError(
            f"QFEDX_WATCH={env!r}: expected '0'/'off', '1'/'on' or a tick "
            "period in seconds"
        ) from None
    if not period > 0:
        raise ValueError(f"QFEDX_WATCH={env!r}: tick period must be > 0")
    return period


def enabled() -> bool:
    return interval_s() > 0


class Snapshot:
    """One tick's consistent view of the world: registry instruments +
    /healthz component sources + the elapsed time since the previous
    tick (what the delta rules normalize against)."""

    __slots__ = ("counters", "gauges", "histos", "components", "elapsed_s")

    def __init__(self, counters, gauges, histos, components, elapsed_s):
        self.counters = counters
        self.gauges = gauges
        self.histos = histos
        self.components = components
        self.elapsed_s = elapsed_s


class WatchRule:
    """One declarative rule: a stable id, the signal it reads, the pin
    holding its threshold, and a pure check over a Snapshot returning
    ``None`` (quiet) or ``(value, threshold, detail)`` (firing).
    ``state`` is the rule's private scratch dict across ticks (previous
    counter values for the delta rules)."""

    __slots__ = ("rule_id", "signal", "threshold_pin", "_check")

    def __init__(self, rule_id: str, signal: str, threshold_pin: str, check):
        if rule_id not in RULE_IDS:
            raise ValueError(f"unknown watch rule id {rule_id!r}")
        self.rule_id = rule_id
        self.signal = signal
        self.threshold_pin = threshold_pin
        self._check = check

    def check(self, snap: Snapshot, state: dict):
        return self._check(snap, state)


# -- the rules -----------------------------------------------------------------


def _check_p95_slo(snap: Snapshot, state: dict):
    h = snap.histos.get("serve.latency_ms")
    if h is None or h.count < P95_MIN_COUNT:
        return None
    slo = pins.float_pin("QFEDX_SERVE_SLO_MS", 50.0)
    p95 = h.percentile(0.95)
    if p95 > slo:
        return (p95, slo, f"serve p95 {p95:.3f}ms > SLO {slo:.3f}ms")
    return None


def _check_shed_rate(snap: Snapshot, state: dict):
    now = snap.counters.get("serve.requests_shed", 0.0) + snap.counters.get(
        "serve.requests_rejected", 0.0
    )
    prev = state.get("prev")
    state["prev"] = now
    if prev is None:  # first tick: a baseline, not a window
        return None
    delta = now - prev
    threshold = pins.float_pin("QFEDX_WATCH_SHED", 1.0)
    if delta >= threshold:
        return (
            delta,
            threshold,
            f"{delta:g} requests shed/rejected since last tick",
        )
    return None


def _check_queue_sat(snap: Snapshot, state: dict):
    comp = snap.components.get("serve")
    if not isinstance(comp, dict) or "queue_depth" not in comp:
        return None
    max_queue = comp.get("max_queue", 0)
    if not max_queue:
        return None
    frac = float(comp["queue_depth"]) / float(max_queue)
    threshold = pins.float_pin("QFEDX_WATCH_QUEUE", 0.9)
    if frac >= threshold:
        return (
            frac,
            threshold,
            f"queue {comp['queue_depth']}/{max_queue} "
            f"({frac:.0%} of max_queue)",
        )
    return None


def _check_trainer_stall(snap: Snapshot, state: dict):
    comp = snap.components.get("trainer")
    if not isinstance(comp, dict) or "last_flush_age_s" not in comp:
        return None
    age = float(comp["last_flush_age_s"])
    threshold = pins.float_pin("QFEDX_WATCH_STALL_S", 120.0)
    if age > threshold:
        return (age, threshold, f"no metrics flush for {age:.1f}s")
    return None


def _check_loss(snap: Snapshot, state: dict):
    loss = snap.gauges.get("fed.loss")
    if loss is None:
        return None
    limit = pins.float_pin("QFEDX_WATCH_LOSS_MAX", math.inf)
    if not math.isfinite(loss):
        return (loss, limit, f"loss is non-finite ({loss})")
    if loss > limit:
        return (loss, limit, f"loss {loss:.6g} > QFEDX_WATCH_LOSS_MAX {limit:g}")
    return None


def _check_eps_burn(snap: Snapshot, state: dict):
    eps = snap.gauges.get("fed.epsilon")
    if eps is None:
        return None
    budget = pins.float_pin("QFEDX_WATCH_EPS", math.inf)
    if eps > budget:
        return (eps, budget, f"DP epsilon {eps:.4f} > budget {budget:g}")
    return None


RULES = (
    WatchRule(
        "serve.p95_slo",
        "serve.latency_ms histogram p95",
        "QFEDX_SERVE_SLO_MS",
        _check_p95_slo,
    ),
    WatchRule(
        "serve.shed_rate",
        "serve.requests_shed + serve.requests_rejected counter delta",
        "QFEDX_WATCH_SHED",
        _check_shed_rate,
    ),
    WatchRule(
        "serve.queue_sat",
        "serve health source queue_depth / max_queue",
        "QFEDX_WATCH_QUEUE",
        _check_queue_sat,
    ),
    WatchRule(
        "trainer.stall",
        "trainer health source last_flush_age_s",
        "QFEDX_WATCH_STALL_S",
        _check_trainer_stall,
    ),
    WatchRule(
        "trainer.loss",
        "fed.loss gauge (non-finite always fires)",
        "QFEDX_WATCH_LOSS_MAX",
        _check_loss,
    ),
    WatchRule(
        "trainer.eps_burn",
        "fed.epsilon gauge",
        "QFEDX_WATCH_EPS",
        _check_eps_burn,
    ),
)


def rule_taxonomy() -> dict[str, dict]:
    """{rule_id: {signal, threshold_pin}} — what the QFX106 doc-taxonomy
    check (analysis/rules_doc.py, benchmarks/check_alerts.py) compares
    against the docs/OBSERVABILITY.md table."""
    return {
        r.rule_id: {"signal": r.signal, "threshold_pin": r.threshold_pin}
        for r in RULES
    }


# -- evaluation state ----------------------------------------------------------

_lock = threading.Lock()
_rule_state: dict[str, dict] = {}      # per-rule scratch across ticks
_active: dict[str, dict] = {}          # rule_id -> firing alert record
_fired_total: dict[str, int] = {}      # rule_id -> lifetime firing count
_last_tick: float | None = None
_sink: Callable[[dict], None] | None = None
_ticker: "threading.Thread | None" = None
_ticker_stop: "threading.Event | None" = None


def set_event_sink(fn: Callable[[dict], None]) -> None:
    """Register the structured-event consumer (ExperimentRun points this
    at its metrics.jsonl logger). Latest wins; unregister with
    ``clear_event_sink(only_if=fn)`` — identity-matched like the
    /healthz sources, so a closing run never evicts a newer one."""
    global _sink
    with _lock:
        _sink = fn


def clear_event_sink(only_if: Callable | None = None) -> None:
    global _sink
    with _lock:
        if only_if is None or _sink is only_if:
            _sink = None


def _emit(event: dict) -> None:
    with _lock:
        sink = _sink
    if sink is None:
        return
    try:
        sink(event)
    except Exception:  # noqa: BLE001 — a dying sink must not kill the ticker
        pass


def evaluate_once() -> list[dict]:
    """Run every rule against a fresh snapshot; fire/clear transitions;
    return the currently active alerts (what the ticker calls per tick
    and tests call directly — same code path, no thread required).
    No-op returning [] when QFEDX_WATCH is off."""
    if not enabled():
        return []
    from qfedx_tpu.obs import server

    counters, gauges, histos, _span_histos = trace.registry().instruments()
    components = server.health_components()
    now = time.monotonic()
    global _last_tick
    with _lock:
        elapsed = (now - _last_tick) if _last_tick is not None else 0.0
        _last_tick = now
    snap = Snapshot(counters, gauges, histos, components, elapsed)
    fired: list[tuple[str, dict]] = []
    cleared: list[str] = []
    for rule in RULES:
        with _lock:
            state = _rule_state.setdefault(rule.rule_id, {})
        try:
            hit = rule.check(snap, state)
        except Exception:  # noqa: BLE001 — one sick rule must not blind the rest
            hit = None
            trace.counter(f"alert.check_error.{rule.rule_id}")
        with _lock:
            was_active = rule.rule_id in _active
            if hit is not None:
                value, threshold, detail = hit
                rec = {
                    "rule": rule.rule_id,
                    "value": value,
                    "threshold": threshold,
                    "detail": detail,
                    "since": _active[rule.rule_id]["since"]
                    if was_active
                    else round(time.time(), 3),
                }
                _active[rule.rule_id] = rec
                if not was_active:
                    _fired_total[rule.rule_id] = (
                        _fired_total.get(rule.rule_id, 0) + 1
                    )
                    fired.append((rule.rule_id, rec))
            elif was_active:
                _active.pop(rule.rule_id, None)
                cleared.append(rule.rule_id)
        trace.gauge(f"alert.{rule.rule_id}", 1.0 if hit is not None else 0.0)
    for rid, rec in fired:
        trace.counter(f"alert.fired.{rid}")
        flight.record(
            "alert", rid, state="firing",
            value=rec["value"], threshold=rec["threshold"],
            detail=rec["detail"],
        )
        _emit({
            "event": "alert",
            "state": "firing",
            "rule": rid,
            "value": rec["value"],
            "threshold": rec["threshold"],
            "detail": rec["detail"],
        })
        # The black box dumps the moment detection trips — the process
        # may not live to a clean unwind.
        flight.maybe_dump(reason=f"alert.{rid}")
    for rid in cleared:
        flight.record("alert", rid, state="cleared")
        _emit({"event": "alert", "state": "cleared", "rule": rid})
    return active_alerts()


def active_alerts() -> list[dict]:
    """The currently firing alerts, sorted by rule id — what /healthz
    renders under ``alerts.active``."""
    with _lock:
        return [dict(_active[rid]) for rid in sorted(_active)]


def fired_totals() -> dict[str, int]:
    """Lifetime {rule_id: firing count} (transitions, not ticks) — the
    bench rows' ``alerts_fired`` source and /healthz ``fired_total``."""
    with _lock:
        return dict(_fired_total)


# -- the ticker ----------------------------------------------------------------


def maybe_start() -> bool:
    """Start the daemon ticker iff QFEDX_WATCH says so (default off —
    returns False, starts no thread). Idempotent; called from the same
    startup seams as obs_server.maybe_start (batcher.start,
    engine.warmup, the streamed trainer)."""
    period = interval_s()
    if period <= 0:
        return False
    global _ticker, _ticker_stop
    with _lock:
        if _ticker is not None and _ticker.is_alive():
            return True
        stop = threading.Event()

        def _loop():
            while not stop.wait(interval_s() or period):
                if stop.is_set():
                    return
                evaluate_once()

        t = threading.Thread(target=_loop, name="qfedx-watchdog", daemon=True)
        _ticker, _ticker_stop = t, stop
    t.start()
    return True


def stop() -> None:
    """Stop the ticker thread (tests / embedders); rule state survives —
    use ``reset`` for full isolation."""
    global _ticker, _ticker_stop
    with _lock:
        t, s = _ticker, _ticker_stop
        _ticker, _ticker_stop = None, None
    if s is not None:
        s.set()
    if t is not None:
        t.join(timeout=5.0)


def reset() -> None:
    """Stop the ticker and drop all alert/rule state (test isolation,
    like obs.reset / flight.reset)."""
    stop()
    global _last_tick, _sink
    with _lock:
        _rule_state.clear()
        _active.clear()
        _fired_total.clear()
        _last_tick = None
        _sink = None
